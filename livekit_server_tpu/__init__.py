"""livekit_server_tpu — a TPU-native real-time media framework.

A brand-new framework with the capabilities of the reference Go SFU
(suryatmodulus/livekit-server): rooms, participants, selective forwarding
(simulcast/SVC), active-speaker detection, congestion control, JWT auth,
multi-node routing, and observability — re-architected TPU-first.

Architecture (see SURVEY.md §7):
  - Control plane (signaling, rooms, subscriptions, auth, routing) is
    host-side Python — thin and latency-insensitive, mirroring the seams of
    the reference's pkg/service + pkg/rtc + pkg/routing layers.
  - The media data plane — the reference's pkg/sfu goroutine-per-packet hot
    path (receiver.go:635 forwardRTP, downtrack.go:680 WriteRTP) — is a
    tick-driven, batched JAX program over `[rooms × tracks × pkts × subs]`
    tensors: layer selection, SN/TS/codec munging, audio-level mixing, and
    bandwidth estimation run as vmapped/fused XLA (+Pallas) kernels.
  - The room axis shards over a `jax.sharding.Mesh` (ICI) for multi-chip
    scale-out; cross-host signal relay stays on the host control plane.
"""

from livekit_server_tpu.version import __version__

__all__ = ["__version__"]
