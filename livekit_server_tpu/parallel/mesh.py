"""Device mesh + shardings for the room axis.

Reference parity: the multi-node scale-out layer (pkg/routing/redisrouter.go
node registry + room pinning; SURVEY.md §2.3, §5.8). Where the reference
distributes rooms across *processes* connected by Redis pub/sub, this build
distributes rooms across *chips* connected by ICI: every media-plane tensor
carries a leading `[R]` room axis, sharded with
`NamedSharding(mesh, P("rooms", ...))`. One compiled program steps all
shards; per-room work never crosses chips, so no collectives are required on
the hot path — cross-room reductions (node telemetry) are the only psum.
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from livekit_server_tpu.analysis.registry import device_entry
from livekit_server_tpu.models import plane

# jax.shard_map (with check_vma) landed after 0.4.x; older versions ship
# it under jax.experimental with the check_rep spelling of the same knob.
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
    _SHARD_MAP_KW = {"check_vma": False}
else:  # pragma: no cover — exercised on older jax only
    from jax.experimental.shard_map import shard_map as _shard_map

    _SHARD_MAP_KW = {"check_rep": False}

ROOM_AXIS = "rooms"


def make_mesh(devices: Sequence[jax.Device] | None = None, n_devices: int | None = None) -> Mesh:
    """1-D mesh over the room axis.

    Rooms are embarrassingly parallel in the data plane (the reference's
    insight too: a room lives entirely on one node — roomallocator.go), so a
    1-D mesh is the right shape; within a shard, the tracks/packets/
    subscriber axes batch onto the MXU/VPU of that chip.
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (ROOM_AXIS,))


def room_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for any tensor with a leading [R] room axis."""
    return NamedSharding(mesh, P(ROOM_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_tree(tree: Any, mesh: Mesh) -> Any:
    """device_put every leaf with its leading axis split over the mesh.

    Scalar leaves (e.g. tick_ms) are replicated.
    """
    rs = room_sharding(mesh)
    rep = replicated(mesh)

    def put(x):
        x = jnp.asarray(x)
        return jax.device_put(x, rep if x.ndim == 0 else rs)

    return jax.tree.map(put, tree)


def page_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for the PAGED plane's pooled buffers: the leading axis is
    the page-pool axis [P] instead of [R], split over the same 1-D mesh.
    Unlike rooms, pages are NOT embarrassingly parallel — the paged tick
    gathers a room's sub column across its track pages (tmembers), so the
    paged mesh path uses plain GSPMD jit (the partitioner inserts the
    cross-shard gathers) rather than the dense tick's shard_map. The
    pager's allocator keeps a room's grid contiguous (one pow2 run), so
    most tmembers gathers stay shard-local anyway."""
    return NamedSharding(mesh, P(ROOM_AXIS))


def shard_pool(tree: Any, mesh: Mesh) -> Any:
    """device_put the pooled plane state / page table with every leaf's
    leading (page or room) axis split over the mesh; scalars replicate."""
    ps = page_sharding(mesh)
    rep = replicated(mesh)

    def put(x):
        x = jnp.asarray(x)
        return jax.device_put(x, rep if x.ndim == 0 else ps)

    return jax.tree.map(put, tree)


@device_entry("mesh.sharded_tick", builder=True)
def make_sharded_tick(
    mesh: Mesh,
    audio_params: Any | None = None,
    bwe_params: Any | None = None,
    donate: bool = True,
    red_enabled: bool = True,
):
    """jit of the full media-plane tick with room-axis in/out shardings.

    Returns a function (state, inputs) -> (state, outputs); `state` is
    donated so the per-tick state update is in-place in HBM.
    """
    from livekit_server_tpu.ops import audio as audio_ops, bwe as bwe_ops

    ap = audio_params or audio_ops.AudioLevelParams()
    bp = bwe_params or bwe_ops.BWEParams()

    def tick(state, inp):
        return plane.media_plane_tick(state, inp, ap, bp, red_enabled=red_enabled)

    def pspecs(tree):
        return jax.tree.map(
            lambda x: P() if jnp.asarray(x).ndim == 0 else P(ROOM_AXIS), tree
        )

    # shard_map, not bare GSPMD jit: the tick's hot kernels are Pallas
    # custom calls with a grid over the room axis, which the GSPMD
    # partitioner cannot split. shard_map traces the tick PER SHARD
    # (local room count), so the Pallas grids are shard-local by
    # construction and no collectives exist on the hot path (rooms are
    # embarrassingly parallel — roomallocator.go's one-node-per-room
    # insight, mapped to chips).
    cache: dict[str, Any] = {}

    @functools.wraps(tick)
    def compiled(state, inp):
        if "fn" not in cache:
            in_specs = (pspecs(state), pspecs(inp))
            out_shapes = jax.eval_shape(tick, state, inp)
            out_specs = jax.tree.map(
                lambda x: P() if x.ndim == 0 else P(ROOM_AXIS), out_shapes
            )
            smapped = _shard_map(
                tick, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                **_SHARD_MAP_KW,
            )
            cache["fn"] = jax.jit(
                smapped, donate_argnums=(0,) if donate else ()
            )
        return cache["fn"](state, inp)

    return compiled
