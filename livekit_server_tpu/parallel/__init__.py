"""Multi-chip scale-out for the media plane.

The reference scales out by pinning rooms to nodes and relaying signal
messages across nodes over Redis/psrpc (SURVEY.md §5.8,
pkg/routing/redisrouter.go). The TPU-native equivalent keeps that seam but
moves the *data plane* onto the device mesh: the `[R]` room axis of
`PlaneState` / `TickInputs` is sharded across chips over ICI
(`jax.sharding.Mesh` + NamedSharding), so one jitted `media_plane_tick`
advances every room on every chip, with XLA inserting any collectives.

Host-side room→shard placement (the analog of RedisRouter room pinning)
lives in livekit_server_tpu.routing; this package owns the device side.
"""

from livekit_server_tpu.parallel.mesh import (
    ROOM_AXIS,
    make_mesh,
    make_sharded_tick,
    room_sharding,
    shard_tree,
)

__all__ = [
    "ROOM_AXIS",
    "make_mesh",
    "make_sharded_tick",
    "room_sharding",
    "shard_tree",
]
