"""Epoch-fenced room ownership: the partition-tolerance primitives.

The lease-based failover of routing/router.py answers "who is dead?"
but not "who may write?". Under a bus partition both answers go wrong
at once: a survivor restores a room from its KV checkpoint while the
original owner — alive on the dark side — keeps forwarding media and
writing checkpoints, so the heal delivers duplicate wire packets and a
stale checkpoint clobbering the winner's. This module makes ownership
explicit and *fenced*, in the style of fencing tokens on a lease
service:

  RoomFence   every room pin carries a monotonically increasing
              ownership epoch in KV (``room_epoch:{room}`` holding
              ``{"e": epoch, "n": node_id}``). Taking a room over is an
              epoch CAS — exactly one claimant can move e→e+1 from a
              given record. Every checkpoint/pin write first CAS-asserts
              the writer's own record; a stale owner's expect string
              names a dead epoch, so its write loses instead of
              clobbering (FencedWriteRejected), and the loss doubles as
              the "you no longer own this room" signal (on_lost).
  LeaseGuard  a node that cannot refresh its liveness lease for longer
              than ``fence_grace`` must assume a survivor is (about to
              be) taking its rooms and go silent FIRST: the guard turns
              refresh outcomes into fence/recover transitions the
              FleetPlane maps onto egress muting, checkpoint freeze and
              supervisor quiesce (service/fleetplane.py).

No-overlap timeline (all clocks start at the dark node's last
successful refresh, t=0): the lease key expires at t=lease_ttl, so no
survivor can even observe the death before then, and its dead-pin scan
lands at most ``failover_interval`` later — the earliest takeover
completes after t=lease_ttl. The dark node self-fences at
t≈fence_grace. ``fence_grace < lease_ttl + failover_interval`` (config
validation) keeps the mute strictly ahead of any takeover; the
``fence_grace ≤ 2×lease_ttl`` ceiling bounds how long a blip can mute a
healthy node.

The CAS-assert-then-write pair is not transactional: a claim landing in
the gap can still race one write. That window is bounded by one bus
round-trip and only matters to checkpoint freshness (the winner
restores once, then every later stale write is rejected); pins and
epoch records themselves only ever move by CAS.
"""

from __future__ import annotations

import json
import time
from typing import Callable

ROOM_EPOCH_PREFIX = "room_epoch:"


class FencedWriteRejected(Exception):
    """A guarded write lost its epoch CAS: a higher epoch exists, so this
    node no longer owns the room and must go quiet for it."""

    def __init__(self, room: str):
        super().__init__(f"write fenced: room {room!r} owned at a higher epoch")
        self.room = room


def _record(epoch: int, node_id: str) -> str:
    # Compact separators: CAS compares exact raw strings, so every writer
    # must produce byte-identical encodings for identical records.
    return json.dumps({"e": epoch, "n": node_id}, separators=(",", ":"))


def _parse(raw: str | None) -> tuple[int, str]:
    if not raw:
        return 0, ""
    try:
        d = json.loads(raw)
        return int(d.get("e", 0)), str(d.get("n", ""))
    except (ValueError, TypeError):
        return 0, ""


class RoomFence:
    """Per-node view of room ownership epochs, backed by bus.cas.

    ``_owned`` caches the raw record string this node last wrote per
    room — the exact CAS expect for every guarded operation. Losing any
    CAS pops the cache and fires ``on_lost`` so the owner of the local
    replica (RoomManager) can tear it down without touching KV.
    """

    def __init__(self, bus, node_id: str, log=None):
        self.bus = bus
        self.node_id = node_id
        self.log = log
        self._owned: dict[str, str] = {}     # room → raw owned record
        self.on_lost: list[Callable[[str], None]] = []
        self.stats = {
            "claims": 0, "claim_losses": 0, "assumes": 0, "transfers": 0,
            "writes_fenced": 0, "releases": 0,
        }

    @staticmethod
    def _key(room: str) -> str:
        return ROOM_EPOCH_PREFIX + room

    # -- introspection ----------------------------------------------------
    def owns(self, room: str) -> bool:
        return room in self._owned

    def epoch_of(self, room: str) -> int:
        """Locally-owned epoch (0 = not owned here)."""
        return _parse(self._owned.get(room))[0]

    def owned_rooms(self) -> list[str]:
        return sorted(self._owned)

    async def read(self, room: str) -> tuple[int, str]:
        """Current (epoch, holder) straight from KV; (0, "") = unclaimed."""
        return _parse(await self.bus.get(self._key(room)))

    # -- ownership moves (all CAS) ----------------------------------------
    async def claim(self, room: str) -> bool:
        """Move the room's epoch to cur+1 naming this node. Exactly one
        claimant wins from any given record; winning invalidates every
        prior owner's guarded writes by construction."""
        key = self._key(room)
        cur = await self.bus.get(key)
        if cur is not None and cur == self._owned.get(room):
            return True   # already own it at the current epoch
        epoch, _holder = _parse(cur)
        nxt = _record(epoch + 1, self.node_id)
        if await self.bus.cas(key, cur, nxt):
            self._owned[room] = nxt
            self.stats["claims"] += 1
            return True
        self.stats["claim_losses"] += 1
        return False

    async def assume(self, room: str) -> bool:
        """Adopt ownership KV already assigns to this node (the target
        side of a transfer), or claim an unclaimed room. Never steals
        from another holder — a fenced node recovering must not re-claim
        rooms a survivor took while it was dark."""
        raw = await self.bus.get(self._key(room))
        if raw is None:
            return await self.claim(room)
        epoch, holder = _parse(raw)
        if holder == self.node_id:
            self._owned[room] = raw
            self.stats["assumes"] += 1
            return True
        return False

    async def transfer(self, room: str, target_node_id: str) -> bool:
        """Hand the room to ``target`` at epoch+1 (migration's COMMIT
        repin). From the source's owned record when we hold one, else
        from the current KV record. On success our own guarded writes
        for the room are dead, exactly as they must be."""
        key = self._key(room)
        cur = self._owned.get(room)
        if cur is None:
            cur = await self.bus.get(key)
        epoch, _holder = _parse(cur)
        nxt = _record(epoch + 1, target_node_id)
        if await self.bus.cas(key, cur, nxt):
            self._owned.pop(room, None)
            self.stats["transfers"] += 1
            return True
        self._lost(room)
        return False

    async def release(self, room: str) -> None:
        """Drop ownership and clear the KV record (room deletion). The
        record is only deleted while it still names our epoch — a racing
        claimant's record survives."""
        owned = self._owned.pop(room, None)
        if owned is not None:
            self.stats["releases"] += 1
            if await self.bus.cas(self._key(room), owned, owned):
                await self.bus.delete(self._key(room))

    def forget(self, room: str) -> None:
        """Drop the local ownership cache only (no KV traffic): the
        fenced-node path, where the bus is unreachable or the record
        already belongs to a survivor."""
        self._owned.pop(room, None)

    # -- fenced writes ----------------------------------------------------
    async def _assert_owner(self, room: str) -> None:
        owned = self._owned.get(room)
        if owned is None:
            if await self.assume(room):
                return
            self.stats["writes_fenced"] += 1
            raise FencedWriteRejected(room)
        if not await self.bus.cas(self._key(room), owned, owned):
            self.stats["writes_fenced"] += 1
            self._lost(room)
            raise FencedWriteRejected(room)

    async def guarded_set(
        self, room: str, key: str, value: str, ttl: float | None = None
    ) -> None:
        """The fenced writer API (graftcheck GC09): every checkpoint/
        snapshot/pin write for a room goes through here. CAS-asserts our
        epoch record, then writes; a dead epoch raises instead of
        writing."""
        await self._assert_owner(room)
        await self.bus.set(key, value, ttl)

    async def guarded_delete(self, room: str, key: str) -> None:
        await self._assert_owner(room)
        await self.bus.delete(key)

    def _lost(self, room: str) -> None:
        self._owned.pop(room, None)
        if self.log is not None:
            self.log.warn("room ownership lost (higher epoch)", room=room)
        for cb in list(self.on_lost):
            cb(room)


class LeaseGuard:
    """Lease-refresh outcomes → fence/recover transitions.

    Fed by KVRouter's stats worker after every refresh attempt. The
    guard itself only decides; the FleetPlane maps "fence" onto egress
    mute + checkpoint freeze + supervisor quiesce, and "recover" onto
    reconcile-then-unfence (the caller clears the flag via unfence()
    only AFTER reconciling, so a recovered node discovers which rooms it
    lost while still silent).
    """

    def __init__(self, fence_grace_s: float, clock=time.monotonic):
        self.fence_grace_s = float(fence_grace_s)
        self._clock = clock
        self.last_ok = clock()
        self.fenced = False
        self.fences = 0          # lifetime fence transitions (telemetry)

    def age(self) -> float:
        """Seconds since the last successful lease refresh."""
        return self._clock() - self.last_ok

    def observe(self, ok: bool) -> str:
        """→ "" | "fence" | "recover"."""
        if ok:
            self.last_ok = self._clock()
            return "recover" if self.fenced else ""
        if not self.fenced and self.age() > self.fence_grace_s:
            self.fenced = True
            self.fences += 1
            return "fence"
        return ""

    def unfence(self) -> None:
        self.fenced = False
