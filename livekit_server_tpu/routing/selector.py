"""Node selection policies for room placement.

Reference parity: pkg/routing/selector — AnySelector (any.go:23),
CPULoadSelector (cpuload.go:24), SystemLoadSelector (sysload.go:24),
RegionAwareSelector (haversine distance over configured regions,
regionaware.go:26-120), sort-by policies (utils.go), availability checks
(interfaces.go:33-64). TPU addition: every policy first filters nodes whose
device-mesh room capacity is exhausted (plane occupancy), because a TPU
node saturates its room tensor long before its CPUs.
"""

from __future__ import annotations

import math
import random
from typing import Protocol

from livekit_server_tpu.config.config import NodeSelectorConfig
from livekit_server_tpu.routing.node import LocalNode, NodeState


class NoNodesAvailable(Exception):
    pass


class NodeSelector(Protocol):
    def select_node(self, nodes: list[LocalNode]) -> LocalNode: ...


def _filter_available(nodes: list[LocalNode]) -> list[LocalNode]:
    # Draining/stopping nodes are excluded EXPLICITLY, not just via
    # is_available()'s SERVING check: a node mid-drain (migration plane,
    # service/migration.py) must receive no new rooms regardless of how
    # the availability predicate evolves.
    out = [
        n for n in nodes
        if n.state != NodeState.SHUTTING_DOWN and n.is_available()
    ]
    # Plane capacity gate (TPU-specific; no reference equivalent).
    out = [
        n
        for n in out
        if n.stats.plane_rooms_capacity == 0
        or n.stats.plane_rooms_used < n.stats.plane_rooms_capacity
    ]
    if not out:
        raise NoNodesAvailable
    return out


def _sort_by(nodes: list[LocalNode], key: str) -> list[LocalNode]:
    """selector/utils.go SelectSortedNode."""
    if key == "random" or not key:
        return random.sample(nodes, len(nodes))
    if key == "sysload":
        return sorted(nodes, key=lambda n: n.stats.load_avg_last1min)
    if key == "cpuload":
        return sorted(nodes, key=lambda n: n.stats.cpu_load)
    if key == "rooms":
        return sorted(nodes, key=lambda n: n.stats.num_rooms)
    raise ValueError(f"unknown sort_by: {key}")


class AnySelector:
    """any.go — any available node, sorted by policy."""

    def __init__(self, sort_by: str = "random"):
        self.sort_by = sort_by

    def select_node(self, nodes: list[LocalNode]) -> LocalNode:
        return _sort_by(_filter_available(nodes), self.sort_by)[0]


class CPULoadSelector:
    """cpuload.go — exclude nodes above the CPU load limit."""

    def __init__(self, cpu_load_limit: float = 0.9, sort_by: str = "random"):
        self.limit = cpu_load_limit
        self.sort_by = sort_by

    def select_node(self, nodes: list[LocalNode]) -> LocalNode:
        avail = _filter_available(nodes)
        ok = [n for n in avail if n.stats.cpu_load < self.limit]
        # Reference falls back to all nodes when none clear the bar.
        return _sort_by(ok or avail, self.sort_by)[0]


class SystemLoadSelector:
    """sysload.go — loadavg/NumCpus threshold variant."""

    def __init__(self, sysload_limit: float = 0.9, sort_by: str = "random"):
        self.limit = sysload_limit
        self.sort_by = sort_by

    def select_node(self, nodes: list[LocalNode]) -> LocalNode:
        avail = _filter_available(nodes)
        ok = [
            n
            for n in avail
            if n.stats.load_avg_last1min / max(n.stats.num_cpus, 1) < self.limit
        ]
        return _sort_by(ok or avail, self.sort_by)[0]


def haversine_km(lat1: float, lon1: float, lat2: float, lon2: float) -> float:
    """regionaware.go distanceBetween."""
    rl1, rl2 = math.radians(lat1), math.radians(lat2)
    dlat = rl2 - rl1
    dlon = math.radians(lon2 - lon1)
    a = math.sin(dlat / 2) ** 2 + math.cos(rl1) * math.cos(rl2) * math.sin(dlon / 2) ** 2
    return 6371.0 * 2 * math.asin(math.sqrt(a))


class RegionAwareSelector:
    """regionaware.go:26-120 — prefer nodes in the region closest to the
    current node's region; fall back to the inner selector over all."""

    def __init__(
        self,
        current_region: str,
        regions: list,
        inner: NodeSelector | None = None,
        sort_by: str = "random",
    ):
        self.current_region = current_region
        self.regions = {r.name: (r.lat, r.lon) for r in regions}
        self.inner = inner or AnySelector(sort_by)

    def _region_distance(self, region: str) -> float:
        if region == self.current_region:
            return 0.0
        if region not in self.regions or self.current_region not in self.regions:
            return math.inf
        here = self.regions[self.current_region]
        there = self.regions[region]
        return haversine_km(here[0], here[1], there[0], there[1])

    def select_node(self, nodes: list[LocalNode]) -> LocalNode:
        avail = _filter_available(nodes)
        by_dist = sorted(avail, key=lambda n: self._region_distance(n.region))
        best = self._region_distance(by_dist[0].region)
        if math.isinf(best):
            return self.inner.select_node(avail)
        closest = [n for n in by_dist if self._region_distance(n.region) == best]
        return self.inner.select_node(closest)


def create_selector(cfg: NodeSelectorConfig, current_region: str = "") -> NodeSelector:
    """routing.CreateRouter's selector construction (interfaces.go:116)."""
    if cfg.kind == "any":
        return AnySelector(cfg.sort_by)
    if cfg.kind == "cpuload":
        return CPULoadSelector(cfg.cpu_load_limit, cfg.sort_by)
    if cfg.kind == "sysload":
        return SystemLoadSelector(cfg.sysload_limit, cfg.sort_by)
    if cfg.kind == "regionaware":
        return RegionAwareSelector(current_region, cfg.regions, sort_by=cfg.sort_by)
    raise ValueError(f"unknown node selector kind: {cfg.kind}")
