"""Shared KV + pub/sub message bus.

Reference parity: the Redis seat — node registry hashes and room pinning
(pkg/routing/redisrouter.go:71-154), per-node pub/sub channels (:249-279),
and the psrpc message bus (wire_gen.go:218-223: Redis bus multi-node,
LocalMessageBus single-node). One interface, two implementations:

  - MemoryBus — in-process; N logical nodes in one process share one
    MemoryBus, exactly how the reference's single-node mode uses
    psrpc.NewLocalMessageBus and how its multi-node *tests* run N servers
    against one Redis (test/multinode_test.go). This is the fake-backend
    path for multi-node tests without a cluster.
  - An external bus (real Redis/etcd) can implement the same interface;
    gated off by default since this image ships no KV server.
"""

from __future__ import annotations

import asyncio
import fnmatch
import time
from typing import Any, AsyncIterator, Callable, Protocol


class MessageBus(Protocol):
    async def hset(self, key: str, field: str, value: str) -> None: ...
    async def hget(self, key: str, field: str) -> str | None: ...
    async def hgetall(self, key: str) -> dict[str, str]: ...
    async def hdel(self, key: str, field: str) -> None: ...
    async def set(self, key: str, value: str, ttl: float | None = None) -> None: ...
    async def get(self, key: str) -> str | None: ...
    async def delete(self, key: str) -> None: ...
    async def setnx(self, key: str, value: str, ttl: float | None = None) -> bool: ...
    async def cas(
        self, key: str, expect: str | None, value: str, ttl: float | None = None
    ) -> bool: ...
    async def publish(self, channel: str, msg: Any) -> int: ...
    def subscribe(self, channel: str, size: int = 200) -> "Subscription": ...


class Subscription:
    """One subscriber's bounded queue on a channel (drop-on-overflow, the
    reference's bounded-channel semantics — signal.go:295-348)."""

    # Process-wide overflow count across every subscription — exported
    # as livekit_bus_sub_dropped_total (a saturated bus must be visible,
    # not a per-instance count that dies with the subscription).
    total_dropped = 0

    def __init__(self, bus: "MemoryBus", channel: str, size: int):
        self._bus = bus
        self._channel = channel
        self._q: asyncio.Queue = asyncio.Queue(maxsize=size)
        self.dropped = 0
        self.closed = False

    def _offer(self, msg: Any) -> None:
        try:
            self._q.put_nowait(msg)
        except asyncio.QueueFull:
            self.dropped += 1
            Subscription.total_dropped += 1

    async def __aiter__(self) -> AsyncIterator[Any]:
        while not self.closed:
            msg = await self._q.get()
            if msg is _CLOSE:
                break
            yield msg

    async def read(self, timeout: float | None = None) -> Any:
        if timeout is None:
            msg = await self._q.get()
        else:
            msg = await asyncio.wait_for(self._q.get(), timeout)
        if msg is _CLOSE:
            raise asyncio.CancelledError("subscription closed")
        return msg

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        self._bus._unsubscribe(self._channel, self)
        try:
            self._q.put_nowait(_CLOSE)
        except asyncio.QueueFull:
            pass


_CLOSE = object()


class MemoryBus:
    """In-process MessageBus (hash/KV with TTL + fan-out pub/sub)."""

    def __init__(self):
        self._hashes: dict[str, dict[str, str]] = {}
        self._kv: dict[str, tuple[str, float | None]] = {}  # value, expiry
        self._subs: dict[str, list[Subscription]] = {}

    # -- hashes (node registry, room pinning) ---------------------------
    async def hset(self, key: str, field: str, value: str) -> None:
        self._hashes.setdefault(key, {})[field] = value

    async def hget(self, key: str, field: str) -> str | None:
        return self._hashes.get(key, {}).get(field)

    async def hgetall(self, key: str) -> dict[str, str]:
        return dict(self._hashes.get(key, {}))

    async def hdel(self, key: str, field: str) -> None:
        self._hashes.get(key, {}).pop(field, None)

    # -- plain KV with TTL (locks, object store) ------------------------
    def _live(self, key: str) -> str | None:
        ent = self._kv.get(key)
        if ent is None:
            return None
        value, exp = ent
        if exp is not None and time.monotonic() > exp:
            del self._kv[key]
            return None
        return value

    async def set(self, key: str, value: str, ttl: float | None = None) -> None:
        self._kv[key] = (value, time.monotonic() + ttl if ttl else None)

    async def get(self, key: str) -> str | None:
        return self._live(key)

    async def delete(self, key: str) -> None:
        self._kv.pop(key, None)

    async def setnx(self, key: str, value: str, ttl: float | None = None) -> bool:
        """Distributed-lock primitive (redisstore.go:242-280 room lock)."""
        if self._live(key) is not None:
            return False
        await self.set(key, value, ttl)
        return True

    async def cas(
        self, key: str, expect: str | None, value: str, ttl: float | None = None
    ) -> bool:
        """Compare-and-swap: write only if the key's current value is
        EXACTLY `expect` (None = key absent). The epoch-fencing primitive
        (routing/fleet.py): a stale owner's expect string names a dead
        epoch, so its write loses here instead of clobbering the winner's."""
        if self._live(key) != expect:
            return False
        await self.set(key, value, ttl)
        return True

    # -- pub/sub --------------------------------------------------------
    async def publish(self, channel: str, msg: Any) -> int:
        subs = list(self._subs.get(channel, []))
        # Pattern subscriptions (psrpc-style topic wildcards).
        for pat, lst in self._subs.items():
            if pat != channel and ("*" in pat or "?" in pat) and fnmatch.fnmatch(channel, pat):
                subs.extend(lst)
        for s in subs:
            s._offer(msg)
        return len(subs)

    def subscribe(self, channel: str, size: int = 200) -> Subscription:
        sub = Subscription(self, channel, size)
        self._subs.setdefault(channel, []).append(sub)
        return sub

    def _unsubscribe(self, channel: str, sub: Subscription) -> None:
        lst = self._subs.get(channel)
        if lst and sub in lst:
            lst.remove(sub)
            if not lst:
                del self._subs[channel]
