"""Routing / distribution layer.

Reference parity: pkg/routing (SURVEY.md §2.3) — the "distributed
communication backend". Node registry, room→node pinning, participant
signal relay, and placement selectors. Single-node mode uses in-memory
channels (LocalRouter, pkg/routing/localrouter.go); multi-node mode runs
over a shared KV + pub/sub bus (KVRouter — the seat Redis occupies in
pkg/routing/redisrouter.go). In this build, multi-node also carries the
TPU twist: a "node" is a host driving a device mesh, and the room axis is
first sharded across chips (livekit_server_tpu.parallel) before it ever
needs a second host.
"""

from livekit_server_tpu.routing.kv import MemoryBus, MessageBus
from livekit_server_tpu.routing.messagechannel import ChannelClosed, ChannelFull, MessageChannel
from livekit_server_tpu.routing.node import LocalNode, NodeState, NodeStats
from livekit_server_tpu.routing.router import (
    KVRouter,
    LocalRouter,
    ParticipantInit,
    Router,
    RouterError,
    create_router,
)
from livekit_server_tpu.routing.selector import (
    AnySelector,
    CPULoadSelector,
    NodeSelector,
    RegionAwareSelector,
    SystemLoadSelector,
    create_selector,
)

__all__ = [
    "AnySelector",
    "CPULoadSelector",
    "ChannelClosed",
    "ChannelFull",
    "KVRouter",
    "LocalNode",
    "LocalRouter",
    "MemoryBus",
    "MessageBus",
    "MessageChannel",
    "NodeSelector",
    "NodeState",
    "NodeStats",
    "ParticipantInit",
    "RegionAwareSelector",
    "Router",
    "RouterError",
    "create_router",
    "SystemLoadSelector",
    "create_selector",
]
