"""Bounded in-memory message channel (sink + source).

Reference parity: pkg/routing/messagechannel.go:26-80 — the
MessageSink/MessageSource pair behind every signal connection. Semantics
preserved: bounded buffer, non-blocking writes that raise ChannelFull on
overflow (the reference returns ErrChannelFull and *drops*, so a slow
consumer can't stall the signal path), idempotent close.
"""

from __future__ import annotations

import asyncio
from typing import Any

DEFAULT_SIZE = 200  # messagechannel.go DefaultMessageChannelSize


_SENTINEL = object()


class ChannelFull(Exception):
    pass


class ChannelClosed(Exception):
    pass


class MessageChannel:
    """Async bounded channel; WriteMessage never blocks (drop-on-full)."""

    # Process-wide overflow count across every channel instance —
    # exported as livekit_signal_channel_dropped_total (a saturated
    # signal path must be visible, not a silent local counter).
    total_dropped = 0

    def __init__(self, size: int = DEFAULT_SIZE, connection_id: str = ""):
        self._q: asyncio.Queue[Any] = asyncio.Queue(maxsize=size)
        self._closed = False
        self.connection_id = connection_id
        self.dropped = 0  # this channel's overflow count

    @property
    def is_closed(self) -> bool:
        return self._closed

    def write_message(self, msg: Any) -> None:
        if self._closed:
            raise ChannelClosed
        try:
            self._q.put_nowait(msg)
        except asyncio.QueueFull:
            self.dropped += 1
            MessageChannel.total_dropped += 1
            raise ChannelFull from None

    async def read_message(self) -> Any:
        """Blocking pop; raises ChannelClosed once drained after close."""
        if self._closed and self._q.empty():
            raise ChannelClosed
        msg = await self._q.get()
        if msg is _SENTINEL:
            self._q.put_nowait(_SENTINEL)  # wake any other reader
            raise ChannelClosed
        return msg

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._q.put_nowait(_SENTINEL)
        except asyncio.QueueFull:
            # Queue has items: a reader can't be parked in get(); the closed
            # flag is observed once the backlog drains.
            pass
