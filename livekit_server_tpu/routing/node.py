"""Node identity + stats.

Reference parity: pkg/routing/node.go:29-47 (LocalNode: guid, IP, NumCpus,
region, state, NodeStats) and prometheus.GetUpdatedNodeStats
(pkg/telemetry/prometheus/node.go:115-245), which feeds both the health
check and node selection. Stats here come from /proc + os (Linux), with
media-plane counters pushed in by the runtime each tick.
"""

from __future__ import annotations

import enum
import os
import time
from dataclasses import dataclass, field
from typing import ClassVar

from livekit_server_tpu.utils import ids

# Wall-clock tolerance for peers that predate the monotonic heartbeat
# stamp (mono_at == 0): their updated_at may be skewed by NTP steps, so
# freshness checks widen by this much instead of trusting it exactly.
SKEW_ALLOWANCE_S = 2.0


class NodeState(enum.IntEnum):
    STARTING_UP = 0
    SERVING = 1
    SHUTTING_DOWN = 2


@dataclass
class NodeStats:
    """livekit.NodeStats equivalent (node registry + selector input)."""

    updated_at: float = 0.0
    # Sender-side monotonic stamp (time.monotonic() on the PUBLISHING
    # node), refreshed with every heartbeat. Meaningless to compare
    # across machines directly — receivers only watch whether it
    # ADVANCES (LocalNode.is_available), which no clock step can fake.
    mono_at: float = 0.0
    started_at: float = field(default_factory=time.time)
    num_rooms: int = 0
    num_clients: int = 0
    num_tracks_in: int = 0
    num_tracks_out: int = 0
    bytes_in_per_sec: float = 0.0
    bytes_out_per_sec: float = 0.0
    packets_in_per_sec: float = 0.0
    packets_out_per_sec: float = 0.0
    nack_per_sec: float = 0.0
    num_cpus: int = field(default_factory=lambda: os.cpu_count() or 1)
    cpu_load: float = 0.0        # 1-min loadavg / num_cpus
    load_avg_last1min: float = 0.0
    memory_used: float = 0.0
    memory_total: float = 0.0
    # TPU additions: plane occupancy drives placement before CPU ever does.
    plane_rooms_used: int = 0
    plane_rooms_capacity: int = 0
    # Paged plane: HBM page-pool headroom (0/0 on a dense plane). The
    # selector's room-count signal saturates long before a paged pool
    # does, so placement reads pages when they're reported.
    plane_pages_used: int = 0
    plane_pages_capacity: int = 0


def sample_system_stats(stats: NodeStats) -> NodeStats:
    """Refresh host-derived fields (node_linux.go equivalent)."""
    stats.updated_at = time.time()
    try:
        load1, _, _ = os.getloadavg()
        stats.load_avg_last1min = load1
        stats.cpu_load = load1 / max(stats.num_cpus, 1)
    except OSError:
        pass
    try:
        with open("/proc/meminfo") as f:
            mem = dict(
                (line.split(":")[0], float(line.split()[1]))
                for line in f
                if ":" in line and len(line.split()) >= 2
            )
        stats.memory_total = mem.get("MemTotal", 0.0) * 1024
        stats.memory_used = (mem.get("MemTotal", 0.0) - mem.get("MemAvailable", 0.0)) * 1024
    except (OSError, ValueError):
        pass
    return stats


@dataclass
class LocalNode:
    """This process's identity in the cluster (node.go:29)."""

    node_id: str = field(default_factory=ids.new_node_id)
    ip: str = "127.0.0.1"
    region: str = ""
    state: NodeState = NodeState.SERVING
    stats: NodeStats = field(default_factory=NodeStats)

    # Receiver-side freshness observations, process-wide: node_id →
    # (newest sender mono_at seen, OUR monotonic clock when it first
    # appeared). Freshness is judged entirely on the RECEIVER's clock —
    # a peer whose wall clock stepped hours is neither falsely killed
    # (its advancing mono_at keeps refreshing the entry) nor falsely
    # alive (a dead node's stamp stops advancing and the entry ages on
    # our clock). Bounded by cluster size: one entry per node ever seen.
    _freshness: ClassVar[dict[str, tuple[float, float]]] = {}

    def to_dict(self) -> dict:
        d = {
            "node_id": self.node_id,
            "ip": self.ip,
            "region": self.region,
            "state": int(self.state),
        }
        d["stats"] = {k: v for k, v in vars(self.stats).items()}
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "LocalNode":
        stats = NodeStats(**d.get("stats", {}))
        return cls(
            node_id=d["node_id"],
            ip=d.get("ip", ""),
            region=d.get("region", ""),
            state=NodeState(d.get("state", 1)),
            stats=stats,
        )

    def is_available(self, max_age: float = 30.0) -> bool:
        """selector/interfaces.go IsAvailable — serving + fresh stats.

        Skew-tolerant: peers publishing a monotonic heartbeat stamp are
        judged by whether that stamp still ADVANCES, timed on the
        receiver's own clock; the wall-clock comparison survives only as
        a widened fallback for stamp-less peers."""
        if self.state != NodeState.SERVING:
            return False
        mono = self.stats.mono_at
        if mono:
            seen = LocalNode._freshness.get(self.node_id)
            now = time.monotonic()
            if seen is None or mono > seen[0]:
                LocalNode._freshness[self.node_id] = (mono, now)
                return True
            return now - seen[1] < max_age
        delta = time.time() - self.stats.updated_at
        return delta < max_age + SKEW_ALLOWANCE_S
