"""Routers: room→node mapping + participant signal start.

Reference parity: pkg/routing interfaces (interfaces.go:83-114 Router /
MessageRouter), LocalRouter (localrouter.go:32-147) for single-node, and
the Redis-backed router (redisrouter.go:48-311) for multi-node — node
registry, room pinning, signal relay, keep-alive stats. The KVRouter here
runs the same protocol over a MessageBus; with MemoryBus it reproduces the
reference's multi-node tests (N nodes, one process) and with a real KV it
scales to hosts.

Signal relay: StartParticipantSignal returns (connection_id, request_sink,
response_source). On the RTC-node side the registered session handler is
invoked with mirrored channels (signal.go RelaySignal stream semantics:
sequence-numbered envelopes, drop-on-overflow).
"""

from __future__ import annotations

import asyncio
import json
import time
from dataclasses import dataclass
from typing import Awaitable, Callable, Protocol

from livekit_server_tpu.routing.kv import MemoryBus, MessageBus
from livekit_server_tpu.routing.messagechannel import MessageChannel
from livekit_server_tpu.routing.node import LocalNode, NodeState
from livekit_server_tpu.routing.selector import NodeSelector
from livekit_server_tpu.utils import ids

NODES_KEY = "nodes"            # redisrouter.go NodesKey hash
NODE_ROOM_KEY = "room_node_map"  # NodeRoomKey hash
STATS_MAX_AGE = 30.0
# Liveness lease: a TTL key refreshed with every stats heartbeat. Expiry
# marks a node dead within lease_ttl (~3 heartbeats) instead of the 30 s
# registry staleness window — the signal room failover keys off.
NODE_LEASE_PREFIX = "node_lease:"

# handler(room_name, participant_init, request_source, response_sink)
SessionHandler = Callable[[str, dict, MessageChannel, MessageChannel], Awaitable[None]]


class RouterError(Exception):
    pass


@dataclass
class ParticipantInit:
    """routing.ParticipantInit (interfaces.go) — session start params."""

    identity: str
    name: str = ""
    reconnect: bool = False
    reconnect_reason: int = 0
    auto_subscribe: bool = True
    client_info: dict | None = None
    grants: dict | None = None
    region: str = ""
    connection_id: str = ""

    def to_dict(self) -> dict:
        return vars(self)

    @classmethod
    def from_dict(cls, d: dict) -> "ParticipantInit":
        return cls(**d)


class Router(Protocol):
    local_node: LocalNode

    async def register_node(self) -> None: ...
    async def unregister_node(self) -> None: ...
    async def list_nodes(self) -> list[LocalNode]: ...
    async def get_node_for_room(self, room_name: str) -> str: ...
    async def set_node_for_room(self, room_name: str, node_id: str) -> None: ...
    async def clear_room_state(self, room_name: str) -> None: ...
    async def try_takeover(self, room_name: str, dead_node_id: str = "") -> str: ...
    async def is_node_alive(self, node_id: str) -> bool: ...
    async def dead_room_pins(self) -> list[tuple[str, str]]: ...
    def on_new_session(self, handler: SessionHandler) -> None: ...
    async def start_participant_signal(
        self, room_name: str, init: ParticipantInit
    ) -> tuple[str, MessageChannel, MessageChannel]: ...
    async def drain(self) -> None: ...


class LocalRouter:
    """Single-node router (localrouter.go:32): identity mapping, in-memory
    channels, no external bus."""

    def __init__(self, local_node: LocalNode):
        self.local_node = local_node
        self._handler: SessionHandler | None = None
        self._room_nodes: dict[str, str] = {}
        # Strong refs: the event loop only weakly references tasks, so
        # untracked fire-and-forget sessions could be GC'd mid-flight.
        self._tasks: set[asyncio.Task] = set()

    def _track(self, task: asyncio.Task) -> asyncio.Task:
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return task

    async def register_node(self) -> None:
        self.local_node.stats.updated_at = time.time()

    async def unregister_node(self) -> None:
        pass

    async def list_nodes(self) -> list[LocalNode]:
        return [self.local_node]

    async def get_node_for_room(self, room_name: str) -> str:
        return self._room_nodes.get(room_name, "")

    async def set_node_for_room(self, room_name: str, node_id: str) -> None:
        self._room_nodes[room_name] = node_id

    async def clear_room_state(self, room_name: str) -> None:
        self._room_nodes.pop(room_name, None)

    async def try_takeover(self, room_name: str, dead_node_id: str = "") -> str:
        """Re-home a room whose pinned node died; returns the node that
        actually owns it afterwards. Single-node: always us."""
        self._room_nodes[room_name] = self.local_node.node_id
        return self.local_node.node_id

    async def is_node_alive(self, node_id: str) -> bool:
        return node_id == self.local_node.node_id

    async def dead_room_pins(self) -> list[tuple[str, str]]:
        """(room, node_id) pairs pinned to nodes that are no longer alive.
        Single-node: every pin is ours, so never any."""
        return []

    def on_new_session(self, handler: SessionHandler) -> None:
        self._handler = handler

    async def start_participant_signal(
        self, room_name: str, init: ParticipantInit
    ) -> tuple[str, MessageChannel, MessageChannel]:
        if self._handler is None:
            raise RouterError("no session handler registered")
        connection_id = ids.new_connection_id()
        init.connection_id = connection_id
        req = MessageChannel(connection_id=connection_id)
        resp = MessageChannel(connection_id=connection_id)
        self._track(asyncio.ensure_future(self._handler(room_name, init.to_dict(), req, resp)))
        return connection_id, req, resp

    async def drain(self) -> None:
        self.local_node.state = NodeState.SHUTTING_DOWN


class KVRouter(LocalRouter):
    """Multi-node router over a MessageBus (redisrouter.go:48).

    Nodes register in the NODES_KEY hash, heartbeat stats every
    `stats_interval`, pin rooms in NODE_ROOM_KEY, and relay signal messages
    over per-connection pub/sub channels with sequence numbers
    (signal.go:220-239 seq reconciliation: gaps are surfaced as relay
    errors rather than silently reordered).
    """

    def __init__(
        self,
        local_node: LocalNode,
        bus: MessageBus,
        stats_interval: float = 2.0,
        lease_ttl: float = 6.0,
    ):
        super().__init__(local_node)
        self.bus = bus
        self.stats_interval = stats_interval
        self.lease_ttl = lease_ttl
        # Ownership fence (routing/fleet.py RoomFence), attached by the
        # fleet plane. When present, every pin move rides an epoch CAS.
        self.fence = None
        # Monotonic stamp of the last lease refresh that reached the bus,
        # plus an async observer fed after EVERY attempt (ok or not) —
        # the self-fencing signal (service/fleetplane.py LeaseGuard).
        self.last_lease_ok = time.monotonic()
        self.on_lease: Callable[[bool], Awaitable[None]] | None = None
        self._stats_task: asyncio.Task | None = None
        self._session_task: asyncio.Task | None = None
        self._session_sub = None

    def _lease_key(self, node_id: str) -> str:
        return NODE_LEASE_PREFIX + node_id

    # -- node registry --------------------------------------------------
    async def register_node(self) -> None:
        self.local_node.stats.updated_at = time.time()
        await self.bus.hset(NODES_KEY, self.local_node.node_id, json.dumps(self.local_node.to_dict()))
        await self.bus.set(self._lease_key(self.local_node.node_id), "1", self.lease_ttl)
        self._session_sub = self.bus.subscribe(f"node_session:{self.local_node.node_id}")
        self._stats_task = self._track(asyncio.ensure_future(self._stats_worker()))
        self._session_task = self._track(asyncio.ensure_future(self._session_worker()))

    async def unregister_node(self) -> None:
        if self._stats_task:
            self._stats_task.cancel()
        if self._session_task:
            self._session_task.cancel()
        if self._session_sub is not None:
            self._session_sub.close()
        await self.bus.delete(self._lease_key(self.local_node.node_id))
        await self.bus.hdel(NODES_KEY, self.local_node.node_id)

    async def remove_dead_nodes(self) -> None:
        """redisrouter.go RemoveDeadNodes — reap stale registry entries."""
        for node in await self.list_nodes():
            if not node.is_available(STATS_MAX_AGE) and node.node_id != self.local_node.node_id:
                await self.bus.hdel(NODES_KEY, node.node_id)

    async def _stats_worker(self) -> None:
        while True:
            await asyncio.sleep(self.stats_interval)
            self.local_node.stats.updated_at = time.time()
            self.local_node.stats.mono_at = time.monotonic()
            ok = True
            try:
                await self.bus.hset(
                    NODES_KEY, self.local_node.node_id, json.dumps(self.local_node.to_dict())
                )
                await self.bus.set(self._lease_key(self.local_node.node_id), "1", self.lease_ttl)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — a severed/partitioned bus
                # must not kill the heartbeat task: the failed attempt IS
                # the signal the lease observer fences on, and the worker
                # must keep probing so recovery is observed too.
                ok = False
            if ok:
                self.last_lease_ok = time.monotonic()
            if self.on_lease is not None:
                try:
                    await self.on_lease(ok)
                except Exception:  # noqa: BLE001 — observer bugs must not
                    pass           # stop the lease heartbeat itself

    async def list_nodes(self) -> list[LocalNode]:
        raw = await self.bus.hgetall(NODES_KEY)
        return [LocalNode.from_dict(json.loads(v)) for v in raw.values()]

    # -- room pinning ---------------------------------------------------
    async def get_node_for_room(self, room_name: str) -> str:
        return await self.bus.hget(NODE_ROOM_KEY, room_name) or ""

    async def set_node_for_room(self, room_name: str, node_id: str) -> None:
        """Move a room pin. With a fence attached the pin only moves
        behind an epoch CAS: pinning to ourselves claims the next epoch,
        pinning elsewhere (migration COMMIT) transfers it — so the pin
        and the ownership epoch advance together and a concurrent
        claimant makes this raise instead of silently split-braining."""
        if self.fence is not None:
            from livekit_server_tpu.routing.fleet import FencedWriteRejected

            if node_id == self.local_node.node_id:
                moved = await self.fence.claim(room_name)
            else:
                moved = await self.fence.transfer(room_name, node_id)
            if not moved:
                raise FencedWriteRejected(room_name)
        await self.bus.hset(NODE_ROOM_KEY, room_name, node_id)

    async def clear_room_state(self, room_name: str) -> None:
        if self.fence is not None and self.fence.owns(room_name):
            await self.fence.release(room_name)
        await self.bus.hdel(NODE_ROOM_KEY, room_name)

    async def try_takeover(self, room_name: str, dead_node_id: str = "") -> str:
        """Serialized dead-node re-home: concurrent joins on different
        live nodes race to a setnx lock; exactly one rewrites the pin and
        releases the lock, the others route to the winner (prevents a
        split-brain room existing on two nodes at once). If the winner
        itself dies mid-takeover the lock TTL expires and the losers
        re-race, so a crash can delay — but never wedge — the re-home."""
        lock_key = f"takeover:{room_name}"
        for _ in range(10):
            if await self.bus.setnx(lock_key, self.local_node.node_id, 5.0):
                from livekit_server_tpu.routing.fleet import FencedWriteRejected

                try:
                    await self.set_node_for_room(room_name, self.local_node.node_id)
                except FencedWriteRejected:
                    # The epoch CAS lost to a restorer on the other
                    # election path (the orchestrator's create-lock):
                    # back off cleanly to whoever holds the epoch now.
                    await self.bus.delete(lock_key)
                    if self.fence is not None:
                        _epoch, holder = await self.fence.read(room_name)
                        if holder:
                            return holder
                    return await self.get_node_for_room(room_name) or self.local_node.node_id
                await self.bus.delete(lock_key)
                from livekit_server_tpu.utils.logger import log

                log.info("room takeover", room=room_name,
                         dead_node=dead_node_id[:12],
                         new_node=self.local_node.node_id[:12])
                return self.local_node.node_id
            # Lost the race: wait for the winner to release (or for its
            # TTL to lapse if it crashed), then read the new pin.
            for _ in range(300):
                if await self.bus.get(lock_key) is None:
                    break
                await asyncio.sleep(0.02)
            winner = await self.get_node_for_room(room_name)
            if winner and winner != dead_node_id:
                return winner
            # Pin still points at the dead node ⇒ the lock holder crashed
            # before repinning; race again.
        return await self.get_node_for_room(room_name) or self.local_node.node_id

    async def is_node_alive(self, node_id: str) -> bool:
        """One-field liveness probe for the join hot path (vs. fetching
        and parsing the whole registry).

        A node is alive when its registry entry exists AND either its
        lease key is live or its heartbeat is fresh within lease_ttl.
        The lease is the fast-death signal (expires ~3 missed heartbeats
        after a crash); the heartbeat fallback keeps one lost lease write
        from marking a healthy node dead, since both are rewritten on the
        same cadence."""
        if node_id == self.local_node.node_id:
            return True
        raw = await self.bus.hget(NODES_KEY, node_id)
        if not raw:
            return False
        if await self.bus.get(self._lease_key(node_id)) is not None:
            return True
        return LocalNode.from_dict(json.loads(raw)).is_available(self.lease_ttl)

    async def dead_room_pins(self) -> list[tuple[str, str]]:
        """(room, node_id) pairs whose pinned node's lease has lapsed —
        the failover worker's scan (see service/roommanager.py). Local
        pins are excluded: we cannot adjudicate our own death."""
        pins = await self.bus.hgetall(NODE_ROOM_KEY)
        alive_cache: dict[str, bool] = {}
        dead: list[tuple[str, str]] = []
        for room, node_id in pins.items():
            if not node_id or node_id == self.local_node.node_id:
                continue
            if node_id not in alive_cache:
                alive_cache[node_id] = await self.is_node_alive(node_id)
            if not alive_cache[node_id]:
                dead.append((room, node_id))
        return dead

    # -- signal relay ---------------------------------------------------
    async def start_participant_signal(
        self, room_name: str, init: ParticipantInit
    ) -> tuple[str, MessageChannel, MessageChannel]:
        node_id = await self.get_node_for_room(room_name)
        if not node_id:
            raise RouterError(f"no node for room {room_name}")
        if node_id == self.local_node.node_id and self._handler is not None:
            return await super().start_participant_signal(room_name, init)

        connection_id = ids.new_connection_id()
        init.connection_id = connection_id
        req = MessageChannel(connection_id=connection_id)
        resp = MessageChannel(connection_id=connection_id)
        resp_sub = self.bus.subscribe(f"signal_resp:{connection_id}")

        await self.bus.publish(
            f"node_session:{node_id}",
            json.dumps({"room": room_name, "init": init.to_dict()}),
        )
        # The RTC node publishes {"ready"} once it has subscribed to the
        # request channel; holding requests until then closes the race where
        # a fast first message (seq=1) is published before anyone listens
        # and the seq check tears the session down.
        ready = asyncio.Event()

        async def pump_requests():
            seq = 0
            try:
                try:
                    await asyncio.wait_for(ready.wait(), timeout=5.0)
                except asyncio.TimeoutError:
                    pass  # proceed; the RTC node may be older/acks-less
                while True:
                    msg = await req.read_message()
                    seq += 1
                    await self.bus.publish(
                        f"signal_req:{connection_id}", json.dumps({"seq": seq, "msg": msg})
                    )
            except Exception:
                await self.bus.publish(f"signal_req:{connection_id}", json.dumps({"close": True}))

        async def pump_responses():
            expect = 0
            try:
                async for raw in resp_sub:
                    env = json.loads(raw)
                    if env.get("ready"):
                        ready.set()
                        continue
                    if env.get("close"):
                        break
                    expect += 1
                    if env["seq"] != expect:
                        break  # relay gap ⇒ force client reconnect (signal.go:232)
                    resp.write_message(env["msg"])
            finally:
                resp.close()
                resp_sub.close()

        self._track(asyncio.ensure_future(pump_requests()))
        self._track(asyncio.ensure_future(pump_responses()))
        return connection_id, req, resp

    async def _session_worker(self) -> None:
        """RTC-node side: receive session starts, bridge bus↔handler."""
        assert self._session_sub is not None
        async for raw in self._session_sub:
            msg = json.loads(raw)
            if self._handler is None:
                continue
            init = ParticipantInit.from_dict(msg["init"])
            connection_id = init.connection_id
            req = MessageChannel(connection_id=connection_id)
            resp = MessageChannel(connection_id=connection_id)
            req_sub = self.bus.subscribe(f"signal_req:{connection_id}")
            # Ack: request channel is live — the signal node may now pump.
            await self.bus.publish(f"signal_resp:{connection_id}", json.dumps({"ready": True}))

            async def pump_in(req_sub=req_sub, req=req):
                expect = 0
                try:
                    async for raw_req in req_sub:
                        env = json.loads(raw_req)
                        if env.get("close"):
                            break
                        expect += 1
                        if env["seq"] != expect:
                            break  # dropped request envelope ⇒ kill session,
                            # client reconnects (signal.go:232 semantics)
                        req.write_message(env["msg"])
                finally:
                    req.close()
                    req_sub.close()

            async def pump_out(resp=resp, connection_id=connection_id):
                seq = 0
                try:
                    while True:
                        msg_out = await resp.read_message()
                        seq += 1
                        await self.bus.publish(
                            f"signal_resp:{connection_id}",
                            json.dumps({"seq": seq, "msg": msg_out}),
                        )
                except Exception:
                    await self.bus.publish(
                        f"signal_resp:{connection_id}", json.dumps({"close": True})
                    )

            self._track(asyncio.ensure_future(pump_in()))
            self._track(asyncio.ensure_future(pump_out()))
            self._track(
                asyncio.ensure_future(self._handler(msg["room"], msg["init"], req, resp))
            )

    async def drain(self) -> None:
        self.local_node.state = NodeState.SHUTTING_DOWN
        await self.bus.hset(NODES_KEY, self.local_node.node_id, json.dumps(self.local_node.to_dict()))


def create_router(
    local_node: LocalNode,
    bus: MessageBus | None,
    lease_ttl: float = 6.0,
    stats_interval: float = 2.0,
) -> Router:
    """interfaces.go:116 CreateRouter — bus present ⇒ distributed."""
    if bus is None:
        return LocalRouter(local_node)
    return KVRouter(local_node, bus, stats_interval=stats_interval, lease_ttl=lease_ttl)
