"""TCP message bus: the MessageBus protocol over real sockets.

Reference parity: the Redis deployment seat — multi-node livekit runs N
servers against one Redis for node registry, room pinning, and pub/sub
signal relay (pkg/routing/redisrouter.go:48-311; test/multinode_test.go
runs exactly this shape). This module ships both halves in-repo so a
cluster needs no external dependency:

  - BusServer — a standalone asyncio server holding the hash/KV/pub-sub
    state (`livekit-server-tpu bus` runs it; tests embed it)
  - TCPBusClient — a MessageBus implementation over one TCP connection;
    drop-in for MemoryBus in KVRouter/KVStore (config: kv.kind = "tcp",
    kv.address = "host:port")

Wire protocol: 4-byte big-endian length + UTF-8 JSON.
  request   {"i": id, "op": op, "a": [args]}
  response  {"i": id, "r": result}  |  {"i": id, "e": "error"}
  push      {"p": subscribed-pattern, "c": channel, "m": msg}

Ordering matters for the router's subscribe-then-publish handshakes, so
`subscribe()` writes its SUB frame synchronously on the shared writer —
frames from one client are processed strictly in order by the server.
"""

from __future__ import annotations

import asyncio
import fnmatch
import hmac
import json
import random
from collections import deque
from typing import Any

from livekit_server_tpu.routing.kv import MemoryBus, Subscription
from livekit_server_tpu.utils.backoff import (
    BackoffPolicy,
    CircuitBreaker,
    RetryAborted,
    retry_async,
)

MAX_FRAME = 8 * 1024 * 1024  # room snapshots ride the bus; give them room
MAX_BUFFERED = 4 * 1024 * 1024  # per-subscriber write backlog before drops


def _frame(obj: Any) -> bytes:
    raw = json.dumps(obj, separators=(",", ":")).encode()
    return len(raw).to_bytes(4, "big") + raw


async def _read_frame(reader: asyncio.StreamReader) -> Any:
    hdr = await reader.readexactly(4)
    n = int.from_bytes(hdr, "big")
    if n == 0 or n > MAX_FRAME:
        raise ConnectionError(f"bad frame length {n}")
    return json.loads(await reader.readexactly(n))


class BusServer:
    """Standalone KV/pub-sub node (the 'run one Redis' deployment seat).

    `token` is the Redis-AUTH seat: when set, a client's first frame must
    be {"op": "auth", "a": [token]} or the connection is refused — the bus
    carries room pins, node registry, signal relay, and room snapshots, so
    an unauthenticated listener is cluster-control-plane takeover."""

    def __init__(self, token: str = "") -> None:
        self.state = MemoryBus()  # hashes + KV with TTL (pub/sub is ours)
        self.token = token
        self.server: asyncio.AbstractServer | None = None
        # writer → {pattern, ...}
        self._subs: dict[asyncio.StreamWriter, set[str]] = {}
        # writer → node ident (the "ident" op; fault-injection partitions
        # sever by node, and a client survives reconnects by re-identing).
        self._idents: dict[asyncio.StreamWriter, str] = {}
        # Partition injection (FaultSpec.bus_partition_groups): idents NOT
        # in group 0 lose the bus — every op errors (their clients see a
        # non-retried RuntimeError, so leases lapse fast) and no pushes
        # flow to or from them. Asym pairs (src, dst) additionally hold
        # src→dst pushes in a bounded buffer flushed on heal — the
        # deterministic "COMMIT arrives after the heal" drill primitive.
        self._severed: set[str] = set()
        self._asym: set[tuple[str, str]] = set()
        self._held: deque = deque(maxlen=256)  # (writer, pattern, channel, msg)
        self.stats = {"conns": 0, "ops": 0, "published": 0}

    # -- partition injection (deterministic, driven by the fault harness) --
    def set_partition(self, groups, asym_pairs=()) -> None:
        """Sever node subsets: `groups` is an iterable of ident groups;
        group 0 keeps the bus (the bus process lives on the majority
        side), every other group loses it entirely. Idents that appear in
        no group (test-harness utility clients) stay connected."""
        self._severed = set()
        for i, g in enumerate(groups):
            if i > 0:
                self._severed |= {str(n) for n in g}
        self._asym = {(str(a), str(b)) for a, b in asym_pairs}
        self.stats["partitions"] = self.stats.get("partitions", 0) + 1

    def heal_partition(self) -> None:
        """Reconnect everyone and flush pushes held on asym pairs, in
        capture order — held messages arrive AFTER everything published
        during the partition, exactly like a delayed link coming back."""
        self._severed = set()
        self._asym = set()
        held, self._held = list(self._held), deque(maxlen=256)
        for w, pat, channel, msg in held:
            if not w.is_closing():
                w.write(_frame({"p": pat, "c": channel, "m": msg}))
        self.stats["heals"] = self.stats.get("heals", 0) + 1

    async def start(self, host: str = "127.0.0.1", port: int = 7850) -> None:
        self.server = await asyncio.start_server(self._handle, host, port)

    @property
    def port(self) -> int:
        return self.server.sockets[0].getsockname()[1]

    def close(self) -> None:
        if self.server is not None:
            self.server.close()

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        self.stats["conns"] += 1
        self._subs[writer] = set()
        authed = not self.token
        try:
            while True:
                req = await _read_frame(reader)
                self.stats["ops"] += 1
                if not authed:
                    ok = req.get("op") == "auth" and hmac.compare_digest(
                        str(req.get("a", [""])[0] or ""), self.token
                    )
                    writer.write(
                        _frame({"i": req.get("i", 0), "r": True} if ok
                               else {"i": req.get("i", 0), "e": "auth required"})
                    )
                    await writer.drain()
                    if not ok:
                        break
                    authed = True
                    continue
                try:
                    result = await self._dispatch(writer, req["op"], req.get("a", []))
                    writer.write(_frame({"i": req["i"], "r": result}))
                except Exception as e:  # noqa: BLE001 — survive bad ops
                    writer.write(_frame({"i": req["i"], "e": str(e)}))
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError, ConnectionResetError):
            pass
        finally:
            self._subs.pop(writer, None)
            self._idents.pop(writer, None)
            writer.close()

    async def _dispatch(self, writer, op: str, a: list):
        s = self.state
        if op == "ident":
            self._idents[writer] = str(a[0])
            return None
        if self._severed and self._idents.get(writer, "") in self._severed:
            # The severed side sees every op fail, not time out: the error
            # frame surfaces as a non-retried RuntimeError client-side, so
            # a partitioned node's lease refresh fails within one beat.
            raise RuntimeError("bus partitioned")
        if op == "hset":
            await s.hset(a[0], a[1], a[2])
        elif op == "hget":
            return await s.hget(a[0], a[1])
        elif op == "hgetall":
            return await s.hgetall(a[0])
        elif op == "hdel":
            await s.hdel(a[0], a[1])
        elif op == "set":
            await s.set(a[0], a[1], a[2])
        elif op == "get":
            return await s.get(a[0])
        elif op == "del":
            await s.delete(a[0])
        elif op == "setnx":
            return await s.setnx(a[0], a[1], a[2])
        elif op == "cas":
            return await s.cas(a[0], a[1], a[2], a[3])
        elif op == "pub":
            return self._publish(a[0], a[1], sender=self._idents.get(writer, ""))
        elif op == "sub":
            self._subs[writer].add(a[0])
        elif op == "unsub":
            self._subs[writer].discard(a[0])
        elif op == "auth":
            return True  # already authed (token-less bus, or re-auth)
        else:
            raise ValueError(f"unknown op {op}")
        return None

    def _publish(self, channel: str, msg: Any, sender: str = "") -> int:
        n = 0
        for w, patterns in list(self._subs.items()):
            dst = self._idents.get(w, "")
            if self._severed and dst in self._severed:
                continue  # receiver is on the dark side of the partition
            for pat in patterns:
                if pat == channel or (
                    ("*" in pat or "?" in pat) and fnmatch.fnmatch(channel, pat)
                ):
                    if w.is_closing():
                        continue
                    if sender and (sender, dst) in self._asym:
                        # One-way link failure: hold (not drop) until heal.
                        self._held.append((w, pat, channel, msg))
                        self.stats["held"] = self.stats.get("held", 0) + 1
                        continue
                    # Bounded like Subscription's drop-on-overflow queue: a
                    # stalled subscriber drops pushes instead of growing
                    # the bus process's write buffer without limit.
                    if w.transport.get_write_buffer_size() > MAX_BUFFERED:
                        self.stats["dropped"] = self.stats.get("dropped", 0) + 1
                        continue
                    w.write(_frame({"p": pat, "c": channel, "m": msg}))
                    n += 1
        self.stats["published"] += n
        return n


class TCPBusClient:
    """MessageBus over one TCP connection (the Redis-client seat).

    Reconnects automatically when the connection drops (the go-redis
    behavior the node registry depends on — a blip must not permanently
    sever a node from the cluster), under the uniform BackoffPolicy
    (exponential, full jitter) with a circuit breaker capping the dial
    rate when the bus is hard-down. Calls ride out short blips with a
    bounded retry of their own (counted in `retries`) before surfacing
    ConnectionError; every live subscription is re-issued on the fresh
    connection. Pushes published during the outage are lost — exactly
    Redis pub/sub semantics, which every consumer (heartbeats, signal
    relay seq-resume) already tolerates."""

    RECONNECT_MAX_S = 5.0
    CALL_TIMEOUT_S = 10.0  # per-attempt; a bus that accepts but never answers

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
                 host: str = "", port: int = 0, token: str = "",
                 jitter_seed: int | None = None):
        self._reader = reader
        self._writer = writer
        self._host, self._port, self._token = host, port, token
        self._next_id = 0
        self._ident = ""  # node identity announced via set_ident
        self._pending: dict[int, asyncio.Future] = {}
        self._subs: dict[str, list[Subscription]] = {}
        self._task = asyncio.ensure_future(self._read_loop())
        self.closed = False
        self._connected = True
        self.reconnects = 0
        self.retries = 0  # call-level retry count (telemetry gauge feed)
        self._dial_backoff = BackoffPolicy(base=0.05, max_delay=self.RECONNECT_MAX_S)
        # Full-jitter reconnect is default-on, with a PER-CLIENT rng: a
        # fleet re-dialing after a regional cut must de-correlate, and a
        # shared module-level rng would give chaos drills no seam to
        # seed. `jitter_seed` pins the stream for reproducible storms.
        self._dial_rng = random.Random(jitter_seed)
        # Hard-down bus: after 8 straight failed dials, stop hammering and
        # probe once per cooldown instead.
        self._dial_breaker = CircuitBreaker(threshold=8, cooldown_s=self.RECONNECT_MAX_S)
        # Call retries stay short and bounded: they exist to ride out the
        # reconnect window, not to mask a real outage from callers.
        self._call_policy = BackoffPolicy(base=0.05, max_delay=0.5, max_attempts=4)

    @classmethod
    async def connect(cls, host: str, port: int, token: str = "",
                      jitter_seed: int | None = None) -> "TCPBusClient":
        # Initial dial fails fast by design — the caller decides whether a
        # reachable bus is a boot requirement; only the established client
        # owns the reconnect policy.
        reader, writer = await asyncio.open_connection(host, port)  # graftcheck: disable=GC04
        client = cls(reader, writer, host=host, port=port, token=token,
                     jitter_seed=jitter_seed)
        if token:
            await client._call("auth", token)
        return client

    @classmethod
    async def connect_address(cls, address: str, token: str = "") -> "TCPBusClient":
        host, _, port = address.rpartition(":")
        return await cls.connect(host or "127.0.0.1", int(port), token=token)

    async def _read_loop(self) -> None:
        while True:
            try:
                while True:
                    msg = await _read_frame(self._reader)
                    if "p" in msg:  # push
                        for sub in list(self._subs.get(msg["p"], [])):
                            sub._offer(msg["m"])
                        continue
                    fut = self._pending.pop(msg["i"], None)
                    if fut is not None and not fut.done():
                        if "e" in msg:
                            fut.set_exception(RuntimeError(msg["e"]))
                        else:
                            fut.set_result(msg.get("r"))
            except (asyncio.IncompleteReadError, ConnectionError,
                    ConnectionResetError, OSError):
                pass
            except (ValueError, KeyError, TypeError):
                # Malformed frame (bad JSON, missing 'i'/'p', wrong types): the
                # stream is desynced, so this connection is unusable. Treat it
                # exactly like a connection loss — fall through to fail
                # pendings and reconnect — instead of letting the exception
                # kill the reader task while _connected stays True (which
                # would hang every pending and future call forever).
                pass
            # Connection dropped: fail in-flight calls now; callers retry.
            self._connected = False
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("bus connection lost"))
            self._pending.clear()
            if self.closed or not self._host:
                self.closed = True
                return
            if not await self._reconnect():
                self.closed = True
                return

    async def _reconnect(self) -> bool:
        """Dial until the bus answers (retry_async: jittered backoff,
        breaker-capped dial rate — one probe per cooldown against a
        hard-down bus), then re-auth and re-subscribe every live channel.
        Returns False only on close()."""

        async def dial() -> None:
            reader, writer = await asyncio.open_connection(self._host, self._port)
            try:
                self._writer.close()   # old transport: no fd leak
            except Exception:  # noqa: BLE001 — already torn down
                pass
            self._reader, self._writer = reader, writer
            # Mark live BEFORE re-issuing auth/subs: they go through
            # _send, which fails fast while disconnected.
            self._connected = True
            if self._token:
                # _send writes on the NEW connection; the response is
                # read by the outer loop after we return.
                self._send("auth", self._token).add_done_callback(
                    lambda f: f.exception()
                )
            if self._ident:
                self._send("ident", self._ident).add_done_callback(
                    lambda f: f.exception()
                )
            for channel in self._subs:
                self._send("sub", channel).add_done_callback(
                    lambda f: f.exception()
                )
            self.reconnects += 1

        try:
            await retry_async(
                dial, self._dial_backoff,
                retry_on=(OSError,),
                breaker=self._dial_breaker,
                wait_when_open=True,
                should_abort=lambda: self.closed,
                rng=self._dial_rng,
            )
        except RetryAborted:
            return False
        return True

    def _send(self, op: str, *args) -> asyncio.Future:
        if self.closed or not self._connected:
            # Fail fast mid-outage: a write to the dead transport would be
            # silently dropped and the call would hang forever.
            raise ConnectionError("bus connection lost")
        self._next_id += 1
        fut = asyncio.get_event_loop().create_future()
        self._pending[self._next_id] = fut
        self._writer.write(_frame({"i": self._next_id, "op": op, "a": list(args)}))
        return fut

    async def _call(self, op: str, *args):
        """One bus op under the uniform retry policy: a call that lands in
        the reconnect window retries (briefly, with jittered backoff)
        instead of failing on the first dead-transport write. Server-side
        errors (RuntimeError) never retry — only transport loss does."""

        def _on_retry(_attempt: int, _exc: BaseException) -> None:
            self.retries += 1

        return await retry_async(
            lambda: self._send(op, *args),
            self._call_policy,
            retry_on=(ConnectionError,),
            timeout=self.CALL_TIMEOUT_S,
            on_retry=_on_retry,
        )

    # -- MessageBus -----------------------------------------------------
    async def hset(self, key, field, value):
        await self._call("hset", key, field, value)

    async def hget(self, key, field):
        return await self._call("hget", key, field)

    async def hgetall(self, key):
        return await self._call("hgetall", key)

    async def hdel(self, key, field):
        await self._call("hdel", key, field)

    async def set(self, key, value, ttl=None):
        await self._call("set", key, value, ttl)

    async def get(self, key):
        return await self._call("get", key)

    async def delete(self, key):
        await self._call("del", key)

    async def setnx(self, key, value, ttl=None):
        return await self._call("setnx", key, value, ttl)

    async def cas(self, key, expect, value, ttl=None):
        return await self._call("cas", key, expect, value, ttl)

    async def publish(self, channel, msg):
        return await self._call("pub", channel, msg)

    def set_ident(self, node_id: str) -> None:
        """Name this connection to the bus (fire-and-forget, like
        subscribe): partitions sever by node ident, and _reconnect
        re-idents so the identity survives transport churn."""
        self._ident = node_id
        try:
            self._send("ident", node_id).add_done_callback(
                lambda f: f.exception()
            )
        except ConnectionError:
            pass  # re-sent by _reconnect once the transport is back

    def subscribe(self, channel: str, size: int = 200) -> Subscription:
        """Synchronous like MemoryBus.subscribe: the SUB frame goes on the
        wire immediately (writer.write is sync), so a publish awaited
        AFTER this call is ordered behind the subscription server-side."""
        sub = Subscription(self, channel, size)
        self._subs.setdefault(channel, []).append(sub)
        # Fire-and-forget op (response discarded via the pending future).
        # Mid-outage the send fails — the registration stands and
        # _reconnect re-issues it, so subscribe works across blips.
        try:
            self._send("sub", channel).add_done_callback(lambda f: f.exception())
        except ConnectionError:
            pass
        return sub

    def _unsubscribe(self, channel: str, sub: Subscription) -> None:
        lst = self._subs.get(channel)
        if lst and sub in lst:
            lst.remove(sub)
            if not lst:
                del self._subs[channel]
                if not self.closed:
                    self._send("unsub", channel).add_done_callback(
                        lambda f: f.exception()
                    )

    async def close(self) -> None:
        self.closed = True
        self._task.cancel()
        self._writer.close()
