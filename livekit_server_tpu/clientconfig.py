"""Per-client quirk configuration (device/SDK workarounds).

Reference parity: pkg/clientconfiguration/ — a rule list matched against
the client's ClientInfo at join (conf.go GetConfiguration); matching rules
yield a ClientConfiguration (disabled codecs, resume on/off) that rides
the JoinResponse and gates server behavior (match.go's script matcher,
staticconfiguration.go's shipped rules).

The reference evaluates tengo script expressions; here a rule is declara-
tive data — a list of OR-groups of field→value(s) AND-matches — which
covers every shipped rule without an embedded interpreter (no arbitrary
code evaluation on a hot join path).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class ClientConfiguration:
    """livekit.ClientConfiguration subset the server acts on."""

    resume_connection: str = ""              # "" | "enabled" | "disabled"
    disabled_codecs: list[str] = field(default_factory=list)          # both ways
    disabled_publish_codecs: list[str] = field(default_factory=list)  # publish only

    def to_dict(self) -> dict:
        return {
            "resume_connection": self.resume_connection,
            "disabled_codecs": {
                "codecs": [{"mime": m} for m in self.disabled_codecs],
                "publish": [{"mime": m} for m in self.disabled_publish_codecs],
            },
        }


@dataclass
class ConfigurationItem:
    """One rule: `match` is a list of AND-dicts (field → value or list of
    values, lowercase); the rule fires if ANY dict fully matches."""

    match: list[dict]
    configuration: ClientConfiguration
    merge: bool = False


# staticconfiguration.go StaticConfigurations (the active rule set):
# H.264 publish is broken on this Xiaomi model and on Firefox
# (desktop Linux + Android).
STATIC_CONFIGURATIONS = [
    ConfigurationItem(
        match=[
            {"device_model": "xiaomi 2201117ti", "os": "android"},
            {"browser": ["firefox", "firefox mobile"], "os": ["linux", "android"]},
        ],
        configuration=ClientConfiguration(
            disabled_publish_codecs=["video/h264"]
        ),
    ),
]


def _norm(v) -> str:
    return str(v).strip().lower()


def _and_match(rule: dict, info: dict) -> bool:
    for key, want in rule.items():
        got = _norm(info.get(key, ""))
        if isinstance(want, (list, tuple, set)):
            if got not in {_norm(w) for w in want}:
                return False
        elif got != _norm(want):
            return False
    return True


class ClientConfigurationManager:
    """conf.go StaticClientConfigurationManager."""

    def __init__(self, items: list[ConfigurationItem] | None = None):
        self.items = STATIC_CONFIGURATIONS if items is None else items

    def get_configuration(self, client_info: dict | None) -> ClientConfiguration | None:
        if not client_info:
            return None
        merged: ClientConfiguration | None = None
        for item in self.items:
            if not any(_and_match(rule, client_info) for rule in item.match):
                continue
            if not item.merge:
                return item.configuration
            if merged is None:
                merged = ClientConfiguration()
            if item.configuration.resume_connection:
                merged.resume_connection = item.configuration.resume_connection
            merged.disabled_codecs += item.configuration.disabled_codecs
            merged.disabled_publish_codecs += item.configuration.disabled_publish_codecs
        return merged
