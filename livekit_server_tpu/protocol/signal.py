"""Signal protocol: the /rtc WebSocket message surface.

Reference parity: livekit.SignalRequest / livekit.SignalResponse oneofs as
dispatched by pkg/rtc/signalhandler.go:24-97 (14 request variants) and
emitted throughout pkg/rtc (JoinResponse room.go:935, ParticipantUpdate,
SpeakersChanged, StreamStateUpdate, …). Framing is the JSON oneof shape of
the reference's JSON signal mode (pkg/service/wsprotocol.go): one
single-key object `{"<variant>": {...}}`.

Messages are tagged unions: `SignalRequest(kind, data)` where `kind` names
the oneof arm and `data` is the payload dict (typed payload dataclasses in
protocol.models are used for the structured ones). This keeps the wire
surface complete without a protobuf toolchain; a protobuf codec can slot in
behind encode/decode later without touching callers.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any

# Request variants a client may send (signalhandler.go:24-97).
REQUEST_KINDS = frozenset(
    {
        "offer",            # publisher SDP offer
        "answer",           # subscriber SDP answer
        "trickle",          # ICE candidate
        "add_track",        # AddTrackRequest
        "mute",             # MuteTrackRequest
        "subscription",     # UpdateSubscription
        "track_setting",    # UpdateTrackSettings (quality/dims/fps)
        "leave",            # LeaveRequest
        "update_layers",    # UpdateVideoLayers (deprecated upstream, kept)
        "subscription_permission",  # per-publisher subscription grants
        "sync_state",       # resume: replay subscriptions/tracks
        "simulate",         # fault injection scenarios
        "ping",             # rtt ping (responds pong)
        "update_metadata",  # participant metadata/name/attributes
        "request_relay",    # mint a media-relay allocation (TURN cred seat)
    }
)

# Response variants the server may send.
RESPONSE_KINDS = frozenset(
    {
        "join",
        "answer",
        "offer",
        "trickle",
        "update",                    # ParticipantUpdate
        "track_published",
        "track_unpublished",
        "leave",
        "mute",
        "speakers_changed",
        "room_update",
        "connection_quality",
        "stream_state_update",
        "subscribed_quality_update",
        "subscription_permission_update",
        "refresh_token",
        "pong",
        "reconnect",
        "subscription_response",
        "request_response",
        "track_subscribed",
        # Data packets ride the signal socket in this build (the reference
        # uses SCTP data channels; the seam is the same fan-out —
        # room.go:1455).
        "data_packet",
    }
)


@dataclass
class SignalRequest:
    kind: str
    data: dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        if self.kind not in REQUEST_KINDS:
            raise ValueError(f"unknown signal request kind: {self.kind!r}")


@dataclass
class SignalResponse:
    kind: str
    data: dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        if self.kind not in RESPONSE_KINDS:
            raise ValueError(f"unknown signal response kind: {self.kind!r}")


# -- binary framing ---------------------------------------------------------
# The reference negotiates JSON vs protobuf per WS connection
# (pkg/service/wsprotocol.go — SDKs speak the compact binary form). This
# build's binary mode is msgpack with numeric kind tags: a deliberate
# redesign (no protobuf toolchain), same capability — a compact,
# schema-tagged binary signal wire negotiated per connection.
#
# Frame: 0x00 | msgpack([kind_id, data]). The leading 0x00 can never
# collide with the media frames that share the BINARY channel: those are
# msgpack maps, whose first byte is 0x80-0x8f or 0xde/0xdf.
#
# Kind ids are STABLE WIRE CONSTANTS — append only, never renumber.
BINARY_MAGIC = 0x00

_REQUEST_ID_LIST = [
    "offer", "answer", "trickle", "add_track", "mute", "subscription",
    "track_setting", "leave", "update_layers", "subscription_permission",
    "sync_state", "simulate", "ping", "update_metadata", "request_relay",
]
_RESPONSE_ID_LIST = [
    "join", "answer", "offer", "trickle", "update", "track_published",
    "track_unpublished", "leave", "mute", "speakers_changed", "room_update",
    "connection_quality", "stream_state_update", "subscribed_quality_update",
    "subscription_permission_update", "refresh_token", "pong", "reconnect",
    "subscription_response", "request_response", "track_subscribed",
    "data_packet",
]
REQUEST_KIND_TO_ID = {k: i for i, k in enumerate(_REQUEST_ID_LIST)}
RESPONSE_KIND_TO_ID = {k: i for i, k in enumerate(_RESPONSE_ID_LIST)}

# Always-on invariant (asserts vanish under python -O): a drifted id list
# would silently renumber wire constants for deployed binary clients.
if set(_REQUEST_ID_LIST) != REQUEST_KINDS or set(_RESPONSE_ID_LIST) != RESPONSE_KINDS:
    raise RuntimeError("binary signal kind-id tables out of sync with KINDS")


def _encode_bin(kind_id: int, data: dict) -> bytes:
    import msgpack

    return bytes([BINARY_MAGIC]) + msgpack.packb([kind_id, data], use_bin_type=True)


def _decode_bin(raw: bytes, id_list: list[str], what: str) -> tuple[str, dict]:
    import msgpack

    if not raw or raw[0] != BINARY_MAGIC:
        raise ValueError(f"{what}: not a binary signal frame")
    try:
        msg = msgpack.unpackb(raw[1:], raw=False)
    except Exception as e:  # noqa: BLE001 — malformed wire bytes
        raise ValueError(f"{what}: malformed msgpack: {e}") from None
    if not isinstance(msg, (list, tuple)) or len(msg) != 2:
        raise ValueError(f"{what}: expected [kind_id, data] pair")
    kind_id, data = msg
    if not isinstance(kind_id, int) or not 0 <= kind_id < len(id_list):
        raise ValueError(f"{what}: unknown kind id {kind_id!r}")
    if data is None:
        data = {}
    if not isinstance(data, dict):
        raise ValueError(f"{what}: payload must be a map")
    return id_list[kind_id], data


def is_binary_signal_frame(data: bytes) -> bool:
    """Demux for the shared BINARY channel: signal frame vs media frame."""
    return bool(data) and data[0] == BINARY_MAGIC


def encode_signal_request_bin(req: SignalRequest) -> bytes:
    return _encode_bin(REQUEST_KIND_TO_ID[req.kind], req.data)


def decode_signal_request_bin(raw: bytes) -> SignalRequest:
    return SignalRequest(*_decode_bin(raw, _REQUEST_ID_LIST, "SignalRequest"))


def encode_signal_response_bin(resp: SignalResponse) -> bytes:
    return _encode_bin(RESPONSE_KIND_TO_ID[resp.kind], resp.data)


def decode_signal_response_bin(raw: bytes) -> SignalResponse:
    return SignalResponse(*_decode_bin(raw, _RESPONSE_ID_LIST, "SignalResponse"))


def _encode(kind: str, data: dict) -> str:
    return json.dumps({kind: data}, separators=(",", ":"))


def _decode(raw: str | bytes, kinds: frozenset[str], what: str) -> tuple[str, dict]:
    msg = json.loads(raw)
    if not isinstance(msg, dict) or len(msg) != 1:
        raise ValueError(f"{what}: expected single-key oneof object")
    kind, data = next(iter(msg.items()))
    if kind not in kinds:
        raise ValueError(f"{what}: unknown variant {kind!r}")
    if data is None:
        data = {}
    if not isinstance(data, dict):
        raise ValueError(f"{what}: payload for {kind!r} must be an object")
    return kind, data


def encode_signal_request(req: SignalRequest) -> str:
    return _encode(req.kind, req.data)


def decode_signal_request(raw: str | bytes) -> SignalRequest:
    return SignalRequest(*_decode(raw, REQUEST_KINDS, "SignalRequest"))


def encode_signal_response(resp: SignalResponse) -> str:
    return _encode(resp.kind, resp.data)


def decode_signal_response(raw: str | bytes) -> SignalResponse:
    return SignalResponse(*_decode(raw, RESPONSE_KINDS, "SignalResponse"))
