"""Wire/API protocol types.

The reference consumes these from the external `livekit/protocol` repo
(protobuf-generated Go types: Room, ParticipantInfo, TrackInfo,
SignalRequest/SignalResponse, …). This build defines the same surface as
plain Python dataclasses with JSON framing — the seam every layer above the
media plane speaks (service HTTP APIs, /rtc WebSocket signaling, routing
relay, webhooks).
"""

from livekit_server_tpu.protocol.models import (
    CodecInfo,
    ConnectionQuality,
    DataPacketKind,
    DisconnectReason,
    ParticipantInfo,
    ParticipantPermission,
    ParticipantState,
    RoomInfo,
    SimulcastLayer,
    TrackInfo,
    TrackSource,
    TrackType,
    VideoQuality,
)
from livekit_server_tpu.protocol.signal import (
    SignalRequest,
    SignalResponse,
    decode_signal_request,
    decode_signal_response,
    encode_signal_request,
    encode_signal_response,
)

__all__ = [
    "CodecInfo",
    "ConnectionQuality",
    "DataPacketKind",
    "DisconnectReason",
    "ParticipantInfo",
    "ParticipantPermission",
    "ParticipantState",
    "RoomInfo",
    "SimulcastLayer",
    "TrackInfo",
    "TrackSource",
    "TrackType",
    "VideoQuality",
    "SignalRequest",
    "SignalResponse",
    "decode_signal_request",
    "decode_signal_response",
    "encode_signal_request",
    "encode_signal_response",
]
