"""Core API model types.

Reference parity: livekit/protocol protobufs as used throughout the
reference (livekit.Room, livekit.ParticipantInfo, livekit.TrackInfo,
livekit.ParticipantPermission, enums VideoQuality/TrackType/TrackSource/
ConnectionQuality/DisconnectReason), consumed by pkg/service (Twirp APIs),
pkg/rtc (room state), and webhooks. Dataclasses + to_dict/from_dict JSON
framing replace protobuf; field names follow the proto JSON names so
payloads look like the reference's JSON signal mode
(pkg/service/wsprotocol.go).
"""

from __future__ import annotations

import dataclasses
import enum
import time
from dataclasses import dataclass, field
from typing import Any


class TrackType(enum.IntEnum):
    AUDIO = 0
    VIDEO = 1
    DATA = 2


class TrackSource(enum.IntEnum):
    UNKNOWN = 0
    CAMERA = 1
    MICROPHONE = 2
    SCREEN_SHARE = 3
    SCREEN_SHARE_AUDIO = 4


class VideoQuality(enum.IntEnum):
    LOW = 0
    MEDIUM = 1
    HIGH = 2
    OFF = 3


class ConnectionQuality(enum.IntEnum):
    POOR = 0
    GOOD = 1
    EXCELLENT = 2
    LOST = 3


class ParticipantState(enum.IntEnum):
    JOINING = 0
    JOINED = 1      # signal connected, no media yet
    ACTIVE = 2      # media flowing
    DISCONNECTED = 3


class DisconnectReason(enum.IntEnum):
    UNKNOWN_REASON = 0
    CLIENT_INITIATED = 1
    DUPLICATE_IDENTITY = 2
    SERVER_SHUTDOWN = 3
    PARTICIPANT_REMOVED = 4
    ROOM_DELETED = 5
    STATE_MISMATCH = 6
    JOIN_FAILURE = 7
    MIGRATION = 8
    SIGNAL_CLOSE = 9


class DataPacketKind(enum.IntEnum):
    RELIABLE = 0
    LOSSY = 1


def _to_dict(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {k: _to_dict(v) for k, v in dataclasses.asdict(obj).items()}
    if isinstance(obj, enum.Enum):
        return int(obj)
    if isinstance(obj, (list, tuple)):
        return [_to_dict(v) for v in obj]
    if isinstance(obj, dict):
        return {k: _to_dict(v) for k, v in obj.items()}
    return obj


class _Model:
    """Mixin: dict round-trip tolerant of unknown/missing keys."""

    def to_dict(self) -> dict:
        return _to_dict(self)

    @classmethod
    def from_dict(cls, d: dict):
        kw = {}
        for f in dataclasses.fields(cls):
            if f.name not in d:
                continue
            v = d[f.name]
            t = f.type if isinstance(f.type, type) else None
            sub = _NESTED.get((cls.__name__, f.name))
            if sub is not None and v is not None:
                if isinstance(v, list):
                    v = [sub.from_dict(x) if isinstance(x, dict) else x for x in v]
                elif isinstance(v, dict):
                    v = sub.from_dict(v)
            kw[f.name] = v
        return cls(**kw)


@dataclass
class SimulcastLayer(_Model):
    """One spatial encoding of a published video track (livekit.VideoLayer)."""

    quality: VideoQuality = VideoQuality.HIGH
    width: int = 0
    height: int = 0
    bitrate: int = 0
    ssrc: int = 0


@dataclass
class CodecInfo(_Model):
    """livekit.SimulcastCodecInfo / codec mime registration."""

    mime_type: str = ""
    mid: str = ""
    cid: str = ""
    layers: list[SimulcastLayer] = field(default_factory=list)


@dataclass
class TrackInfo(_Model):
    """livekit.TrackInfo (protocol) — the published-track descriptor."""

    sid: str = ""
    type: TrackType = TrackType.AUDIO
    name: str = ""
    muted: bool = False
    width: int = 0
    height: int = 0
    simulcast: bool = False
    disable_dtx: bool = False
    source: TrackSource = TrackSource.UNKNOWN
    layers: list[SimulcastLayer] = field(default_factory=list)
    mime_type: str = ""
    mid: str = ""
    codecs: list[CodecInfo] = field(default_factory=list)
    stereo: bool = False
    disable_red: bool = False
    stream: str = ""
    encryption: int = 0  # 0 none, 1 gcm, 2 custom — E2EE passthrough


def is_svc_mime(mime: str | None, is_video: bool) -> bool:
    """SVC codecs (VP9/AV1) carry all spatial layers in ONE stream and take
    the dependency-descriptor selection path (receiver.go IsSvcCodec)."""
    m = (mime or "").lower()
    return is_video and ("vp9" in m or "av1" in m)


@dataclass
class ParticipantPermission(_Model):
    """livekit.ParticipantPermission (auth grants → runtime enforcement,
    reference pkg/rtc/participant.go SetPermission)."""

    can_subscribe: bool = True
    can_publish: bool = True
    can_publish_data: bool = True
    can_publish_sources: list[TrackSource] = field(default_factory=list)
    hidden: bool = False
    recorder: bool = False
    can_update_metadata: bool = False
    agent: bool = False


@dataclass
class ParticipantInfo(_Model):
    """livekit.ParticipantInfo."""

    sid: str = ""
    identity: str = ""
    state: ParticipantState = ParticipantState.JOINING
    tracks: list[TrackInfo] = field(default_factory=list)
    metadata: str = ""
    joined_at: int = 0
    name: str = ""
    version: int = 0
    permission: ParticipantPermission = field(default_factory=ParticipantPermission)
    region: str = ""
    is_publisher: bool = False
    kind: int = 0  # 0 standard, 1 ingress, 2 egress, 3 sip, 4 agent
    attributes: dict[str, str] = field(default_factory=dict)


@dataclass
class RoomInfo(_Model):
    """livekit.Room."""

    sid: str = ""
    name: str = ""
    empty_timeout: int = 300
    departure_timeout: int = 20
    max_participants: int = 0
    creation_time: int = field(default_factory=lambda: int(time.time()))
    turn_password: str = ""
    enabled_codecs: list[CodecInfo] = field(default_factory=list)
    metadata: str = ""
    num_participants: int = 0
    num_publishers: int = 0
    active_recording: bool = False


# Nested-field deserialization table for _Model.from_dict.
_NESTED: dict[tuple[str, str], Any] = {
    ("CodecInfo", "layers"): SimulcastLayer,
    ("TrackInfo", "layers"): SimulcastLayer,
    ("TrackInfo", "codecs"): CodecInfo,
    ("ParticipantInfo", "tracks"): TrackInfo,
    ("ParticipantInfo", "permission"): ParticipantPermission,
    ("RoomInfo", "enabled_codecs"): CodecInfo,
}
