"""Native (C++) packet-path components, loaded via ctypes.

Reference parity: the per-packet byte work the reference does in Go on the
hot path — RTP header + extension parsing and VP8 descriptor decode
(pkg/sfu/buffer/buffer.go:417, buffer/vp8.go) and egress header rewrite
(pkg/sfu/downtrack.go WriteRTP) — compiled as a C++ batch library
(native/rtp_parser.cpp). One native call per receive/send batch replaces
per-packet managed-language work.

The library is built on demand with g++ (no pybind11 in this image; plain
C ABI + ctypes + numpy structured arrays). If no toolchain is available,
`rtp` falls back to a pure-Python parser with identical semantics so the
framework stays functional.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path

import numpy as np

_SRC = Path(__file__).resolve().parents[2] / "native" / "rtp_parser.cpp"
_EGRESS_SRC = Path(__file__).resolve().parents[2] / "native" / "egress.cpp"
_CACHE = Path(__file__).resolve().parent / "_build"

# Expected ABI of the compiled libraries; each .so exports an
# *_abi_version() checked at load time. A mismatch (stale cached build
# against newer Python bindings, or vice versa) forces one rebuild, then
# degrades to the pure-Python path rather than calling through a wrong
# signature. tools/check.py compares these strictly and fails the build.
EGRESS_ABI = 4
MUNGE_ABI = 2

# Keep in sync with struct ParsedPacket in rtp_parser.cpp.
PARSED_DTYPE = np.dtype(
    [
        ("ssrc", np.uint32), ("sn", np.uint16), ("pt", np.uint8),
        ("marker", np.uint8), ("ts", np.uint32),
        ("payload_off", np.int32), ("payload_len", np.int32),
        ("audio_level", np.uint8), ("voice", np.uint8),
        ("is_vp8", np.uint8), ("keyframe", np.uint8), ("begin_pic", np.uint8),
        ("tid", np.uint8), ("layer_sync", np.uint8),
        ("picture_id", np.int32), ("tl0picidx", np.int32), ("keyidx", np.int32),
        ("dd_off", np.int32), ("dd_len", np.int32),
        ("end_frame", np.uint8), ("sid", np.int8),
    ],
    align=True,
)


def _compile(src: Path, so_name: str, extra_flags: tuple[str, ...] = ()) -> Path | None:
    """Build (or reuse) one cached shared library; None on any failure —
    including a missing source next to a stale cache — so callers fall
    back to their pure-Python paths instead of dying at import."""
    _CACHE.mkdir(exist_ok=True)
    so = _CACHE / so_name
    try:
        if so.exists() and so.stat().st_mtime >= src.stat().st_mtime:
            return so
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", str(so), str(src),
             *extra_flags],
            check=True, capture_output=True, timeout=120,
        )
        return so
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        return None


def _build() -> Path | None:
    return _compile(_SRC, "librtp_parser.so")


class _NativeRTP:
    def __init__(self, so: Path):
        self.lib = ctypes.CDLL(str(so))
        self.lib.parse_rtp_batch.restype = ctypes.c_int
        self.lib.parse_rtp_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
            ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_void_p,
        ]
        self.lib.rewrite_rtp_batch.restype = None
        self.lib.rewrite_rtp_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ]
        self.lib.rewrite_rtp_vp8_batch.restype = None
        self.lib.rewrite_rtp_vp8_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ]
        self.lib.gather_ranges.restype = ctypes.c_int64
        self.lib.gather_ranges.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
            ctypes.c_void_p,
        ]
        self.native = True

    def gather_ranges(self, blob: np.ndarray, starts, lens) -> bytes:
        """bytes(blob[s0:s0+l0] + blob[s1:s1+l1] + ...) in one C call."""
        starts_c = np.ascontiguousarray(starts, np.int64)
        lens_c = np.ascontiguousarray(lens, np.int64)
        out = np.empty(int(lens_c.sum()), np.uint8)
        n = self.lib.gather_ranges(
            blob.ctypes.data, starts_c.ctypes.data, lens_c.ctypes.data,
            len(starts_c), out.ctypes.data,
        )
        return out[: int(n)].tobytes()

    def parse_batch(
        self,
        buf: bytes | bytearray,
        offsets: np.ndarray,
        lengths: np.ndarray,
        audio_level_ext: int = 1,
        vp8_pts: set[int] | None = None,
        dd_ext_id: int = 0,
        vp9_pts: set[int] | None = None,
        h264_pts: set[int] | None = None,
    ) -> np.ndarray:
        n = len(offsets)
        out = np.zeros(n, PARSED_DTYPE)
        out["dd_off"] = -1
        out["sid"] = -1

        def pt_mask(pts):
            m = np.zeros(16, np.uint8)
            for pt in pts or ():
                m[pt >> 3] |= 1 << (pt & 7)
            return m

        mask = pt_mask(vp8_pts)
        mask9 = pt_mask(vp9_pts)
        mask264 = pt_mask(h264_pts)
        # A contiguous uint8 ndarray passes zero-copy; anything else pays
        # one copy (the hot rx path always hands the former).
        if (
            isinstance(buf, np.ndarray)
            and buf.dtype == np.uint8
            and buf.flags.c_contiguous
        ):
            b = buf
        else:
            b = np.frombuffer(bytes(buf), np.uint8)
        offs = np.ascontiguousarray(offsets, np.int32)
        lens = np.ascontiguousarray(lengths, np.int32)
        self.lib.parse_rtp_batch(
            b.ctypes.data, offs.ctypes.data, lens.ctypes.data, n,
            audio_level_ext, mask.ctypes.data, out.ctypes.data, dd_ext_id,
            mask9.ctypes.data, mask264.ctypes.data,
        )
        return out

    def rewrite_batch(self, buf: bytearray, offsets, sns, tss, ssrcs) -> None:
        b = np.frombuffer(buf, np.uint8)
        offs = np.ascontiguousarray(offsets, np.int32)
        self.lib.rewrite_rtp_batch(
            b.ctypes.data, offs.ctypes.data, len(offs),
            np.ascontiguousarray(sns, np.uint16).ctypes.data,
            np.ascontiguousarray(tss, np.uint32).ctypes.data,
            np.ascontiguousarray(ssrcs, np.uint32).ctypes.data,
        )

    def rewrite_vp8_batch(
        self, buf: bytearray, offsets, lengths, sns, tss, ssrcs,
        pids, tl0s, keyidxs, vp8_flags,
    ) -> None:
        """Header + VP8 payload-descriptor rewrite (codecmunger/vp8.go:161):
        picture-id (width-preserving 7/15-bit), TL0PICIDX, KEYIDX patched
        in place from the device munger's per-(packet, subscriber) outputs."""
        b = np.frombuffer(buf, np.uint8)
        offs = np.ascontiguousarray(offsets, np.int32)
        self.lib.rewrite_rtp_vp8_batch(
            b.ctypes.data, offs.ctypes.data,
            np.ascontiguousarray(lengths, np.int32).ctypes.data, len(offs),
            np.ascontiguousarray(sns, np.uint16).ctypes.data,
            np.ascontiguousarray(tss, np.uint32).ctypes.data,
            np.ascontiguousarray(ssrcs, np.uint32).ctypes.data,
            np.ascontiguousarray(pids, np.int32).ctypes.data,
            np.ascontiguousarray(tl0s, np.int32).ctypes.data,
            np.ascontiguousarray(keyidxs, np.int32).ctypes.data,
            np.ascontiguousarray(vp8_flags, np.uint8).ctypes.data,
        )


class _PythonRTP:
    """Pure-Python fallback with identical output (toolchain-free envs)."""

    native = False

    def parse_batch(self, buf, offsets, lengths, audio_level_ext=1, vp8_pts=None,
                    dd_ext_id=0, vp9_pts=None, h264_pts=None):
        buf = bytes(buf)
        vp8_pts = vp8_pts or set()
        vp9_pts = vp9_pts or set()
        h264_pts = h264_pts or set()
        out = np.zeros(len(offsets), PARSED_DTYPE)
        for i, (off, ln) in enumerate(zip(offsets, lengths)):
            o = out[i]
            o["audio_level"] = 127
            o["picture_id"] = o["tl0picidx"] = o["keyidx"] = -1
            o["payload_len"] = -1
            o["dd_off"] = -1
            o["sid"] = -1
            p = buf[off : off + ln]
            if len(p) < 12 or p[0] >> 6 != 2:
                continue
            cc = p[0] & 0x0F
            has_ext = (p[0] >> 4) & 1
            has_pad = (p[0] >> 5) & 1
            o["marker"] = p[1] >> 7
            o["pt"] = p[1] & 0x7F
            o["sn"] = int.from_bytes(p[2:4], "big")
            o["ts"] = int.from_bytes(p[4:8], "big")
            o["ssrc"] = int.from_bytes(p[8:12], "big")
            q = 12 + cc * 4
            if q > len(p):
                continue
            if has_ext:
                if q + 4 > len(p):
                    continue
                profile = int.from_bytes(p[q : q + 2], "big")
                ext_len = int.from_bytes(p[q + 2 : q + 4], "big") * 4
                ext_off = q + 4
                if ext_off + ext_len > len(p):
                    continue
                if profile == 0xBEDE:
                    j, end = ext_off, ext_off + ext_len
                    while j < end:
                        b0 = p[j]
                        if b0 == 0:
                            j += 1
                            continue
                        eid, elen = b0 >> 4, (b0 & 0x0F) + 1
                        if eid == 15 or j + 1 + elen > end:
                            break
                        if audio_level_ext > 0 and eid == audio_level_ext and elen >= 1:
                            o["voice"] = p[j + 1] >> 7
                            o["audio_level"] = p[j + 1] & 0x7F
                        if dd_ext_id > 0 and eid == dd_ext_id:
                            o["dd_off"] = off + j + 1
                            o["dd_len"] = elen
                        j += 1 + elen
                elif (profile & 0xFFF0) == 0x1000:  # two-byte extensions
                    j, end = ext_off, ext_off + ext_len
                    while j + 1 < end:
                        eid = p[j]
                        if eid == 0:
                            j += 1
                            continue
                        elen = p[j + 1]
                        if j + 2 + elen > end:
                            break
                        if audio_level_ext > 0 and eid == audio_level_ext and elen >= 1:
                            o["voice"] = p[j + 2] >> 7
                            o["audio_level"] = p[j + 2] & 0x7F
                        if dd_ext_id > 0 and eid == dd_ext_id:
                            o["dd_off"] = off + j + 2
                            o["dd_len"] = elen
                        j += 2 + elen
                q = ext_off + ext_len
            pad = p[-1] if has_pad and len(p) > q else 0
            plen = len(p) - q - pad
            if plen < 0:
                continue
            o["payload_off"] = q
            o["payload_len"] = plen
            o["end_frame"] = o["marker"]
            if int(o["pt"]) in vp9_pts and plen >= 1:
                d = p[q : q + plen]
                j = 0
                b0 = d[j]; j += 1
                I, P, L, F = b0 & 0x80, b0 & 0x40, b0 & 0x20, b0 & 0x10
                B, E = b0 & 0x08, b0 & 0x04
                o["begin_pic"] = 1 if B else 0
                o["end_frame"] = 1 if E else 0
                if I:
                    if j >= plen:
                        continue
                    pb = d[j]; j += 1
                    if pb & 0x80:
                        if j >= plen:
                            continue
                        o["picture_id"] = ((pb & 0x7F) << 8) | d[j]; j += 1
                    else:
                        o["picture_id"] = pb & 0x7F
                have_layer = False
                if L:
                    if j >= plen:
                        continue
                    lb = d[j]; j += 1
                    o["tid"] = lb >> 5
                    o["layer_sync"] = (lb >> 4) & 1
                    o["sid"] = (lb >> 1) & 0x07
                    have_layer = True
                    if not F:
                        if j >= plen:
                            continue
                        o["tl0picidx"] = d[j]; j += 1
                if not P and B and (not have_layer or int(o["sid"]) == 0):
                    o["keyframe"] = 1
                if o["keyframe"]:
                    o["layer_sync"] = 1
                continue
            if int(o["pt"]) in h264_pts and plen >= 1:
                d = p[q : q + plen]
                ntype = d[0] & 0x1F
                if 1 <= ntype <= 23:
                    o["begin_pic"] = 1
                    if ntype in (5, 7):
                        o["keyframe"] = 1
                elif ntype == 24:
                    o["begin_pic"] = 1
                    j = 1
                    while j + 2 <= plen:
                        nsz = int.from_bytes(d[j : j + 2], "big")
                        if j + 2 + nsz > plen or nsz < 1:
                            break
                        if d[j + 2] & 0x1F in (5, 7):
                            o["keyframe"] = 1
                        j += 2 + nsz
                elif ntype in (28, 29) and plen >= 2:
                    fu = d[1]
                    start = fu & 0x80
                    o["begin_pic"] = 1 if start else 0
                    if start and (fu & 0x1F) in (5, 7):
                        o["keyframe"] = 1
                if o["keyframe"]:
                    o["layer_sync"] = 1
                continue
            if int(o["pt"]) in vp8_pts and plen >= 1:
                d = p[q : q + plen]
                o["is_vp8"] = 1
                j = 0
                b0 = d[j]; j += 1
                X, S, pid3 = b0 & 0x80, (b0 >> 4) & 1, b0 & 0x07
                o["begin_pic"] = 1 if (S and pid3 == 0) else 0
                bad = False
                if X:
                    if j >= plen:
                        continue
                    xb = d[j]; j += 1
                    if xb & 0x80:  # I
                        if j >= plen:
                            continue
                        pb = d[j]; j += 1
                        if pb & 0x80:
                            if j >= plen:
                                continue
                            o["picture_id"] = ((pb & 0x7F) << 8) | d[j]; j += 1
                        else:
                            o["picture_id"] = pb & 0x7F
                    if xb & 0x40:  # L
                        if j >= plen:
                            continue
                        o["tl0picidx"] = d[j]; j += 1
                    if xb & 0x30:  # T or K
                        if j >= plen:
                            continue
                        tk = d[j]; j += 1
                        o["tid"] = tk >> 6
                        o["layer_sync"] = (tk >> 5) & 1
                        o["keyidx"] = tk & 0x1F
                if o["begin_pic"] and j < plen:
                    o["keyframe"] = 1 if (d[j] & 0x01) == 0 else 0
        return out

    def rewrite_batch(self, buf, offsets, sns, tss, ssrcs):
        for off, sn, ts, ssrc in zip(offsets, sns, tss, ssrcs):
            buf[off + 2 : off + 4] = int(sn).to_bytes(2, "big")
            buf[off + 4 : off + 8] = int(ts).to_bytes(4, "big")
            buf[off + 8 : off + 12] = int(ssrc).to_bytes(4, "big")

    def rewrite_vp8_batch(
        self, buf, offsets, lengths, sns, tss, ssrcs, pids, tl0s, keyidxs, vp8_flags
    ):
        for i, off in enumerate(offsets):
            off, ln = int(off), int(lengths[i])
            if ln < 12:
                continue  # same skip as native: never write past a runt
            buf[off + 2 : off + 4] = int(sns[i]).to_bytes(2, "big")
            buf[off + 4 : off + 8] = int(tss[i]).to_bytes(4, "big")
            buf[off + 8 : off + 12] = int(ssrcs[i]).to_bytes(4, "big")
            if not vp8_flags[i]:
                continue
            p = buf[off : off + ln]
            cc = p[0] & 0x0F
            q = 12 + cc * 4
            if (p[0] >> 4) & 1:  # extension
                if q + 4 > len(p):
                    continue
                q += 4 + int.from_bytes(p[q + 2 : q + 4], "big") * 4
            if q >= len(p):
                continue
            d = off + q  # descriptor start in buf
            b0 = buf[d]
            if not (b0 & 0x80):
                continue
            j = d + 1
            if j >= off + ln:
                continue
            xb = buf[j]
            j += 1
            pid, tl0, kidx = int(pids[i]), int(tl0s[i]), int(keyidxs[i])
            if xb & 0x80:  # I
                if j >= off + ln:
                    continue
                if buf[j] & 0x80:  # 15-bit
                    if j + 1 >= off + ln:
                        continue
                    if pid >= 0:
                        buf[j] = 0x80 | ((pid >> 8) & 0x7F)
                        buf[j + 1] = pid & 0xFF
                    j += 2
                else:
                    if pid >= 0:
                        buf[j] = pid & 0x7F
                    j += 1
            if xb & 0x40:  # L
                if j >= off + ln:
                    continue
                if tl0 >= 0:
                    buf[j] = tl0 & 0xFF
                j += 1
            if xb & 0x30:  # T or K
                if j >= off + ln:
                    continue
                if kidx >= 0:
                    buf[j] = (buf[j] & 0xE0) | (kidx & 0x1F)
                j += 1


def _build_egress() -> Path | None:
    # The EVP_* subset used is ABI-stable across OpenSSL 1.1 and 3; link
    # against whichever libcrypto the image actually ships (images differ).
    for crypto in ("-l:libcrypto.so.3", "-l:libcrypto.so.1.1", "-lcrypto"):
        so = _compile(_EGRESS_SRC, "libegress.so", ("-pthread", crypto))
        if so is not None:
            return so
    return None


def _check_abi(lib: ctypes.CDLL, symbol: str, want: int, what: str) -> None:
    """Raise OSError unless the library reports the expected ABI version.
    A missing symbol means a pre-versioning build — also a mismatch."""
    try:
        fn = getattr(lib, symbol)
    except AttributeError as e:
        raise OSError(f"{what}: no {symbol} symbol (pre-ABI build)") from e
    fn.restype = ctypes.c_int32
    fn.argtypes = []
    got = int(fn())
    if got != want:
        raise OSError(f"{what}: ABI {got} != expected {want}")


class NativeEgress:
    """One-call-per-tick egress: datagram assembly + VP8 descriptor patch +
    AES-128-GCM seal + sendmmsg, fanned over a few threads (the native
    replacement for the per-packet Python send loop — downtrack.go WriteRTP
    + pion/srtp + pacer socket writes)."""

    SEAL_OVERHEAD = 30  # 14-byte frame header + 16-byte GCM tag

    def __init__(self, so: Path):
        self.lib = ctypes.CDLL(str(so))
        _check_abi(self.lib, "egress_abi_version", EGRESS_ABI, "libegress")
        self.lib.egress_batch_send.restype = ctypes.c_int64
        self.lib.egress_batch_send.argtypes = (
            [ctypes.c_int, ctypes.c_int, ctypes.c_void_p, ctypes.c_int32]
            + [ctypes.c_void_p] * 24     # pay_off..out_len
            + [ctypes.c_int]             # pace_window_us
        )
        self.lib.egress_plane_send.restype = ctypes.c_int64
        self.lib.egress_plane_send.argtypes = (
            [ctypes.c_int, ctypes.c_int,              # fd, n_shards
             ctypes.c_void_p, ctypes.c_void_p,        # shard_lo, shard_hi
             ctypes.c_void_p, ctypes.c_int32]         # slab, n
            + [ctypes.c_void_p] * 24                  # pay_off..out_len
            + [ctypes.c_void_p, ctypes.c_void_p,      # rooms, grp
               ctypes.c_int32, ctypes.c_int]          # grp_slots, pace_us
            + [ctypes.c_void_p] * 3                   # shard sent/built/ns
        )
        self.lib.egress_express_send.restype = ctypes.c_int64
        self.lib.egress_express_send.argtypes = (
            [ctypes.c_int, ctypes.c_void_p, ctypes.c_int32]  # fd, slab, n
            + [ctypes.c_void_p] * 24                  # pay_off..out_len
            + [ctypes.c_void_p, ctypes.c_void_p,      # rooms, grp
               ctypes.c_int32, ctypes.c_void_p]       # grp_slots, built_out
        )
        self.lib.egress_pool_ensure.restype = None
        self.lib.egress_pool_ensure.argtypes = [ctypes.c_int]
        self.lib.egress_pool_size.restype = ctypes.c_int32
        self.lib.egress_pool_size.argtypes = []
        self.lib.rx_batch.restype = ctypes.c_int32
        self.lib.rx_batch.argtypes = [
            ctypes.c_int, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int32, ctypes.c_int32,
        ]
        self.lib.send_raw.restype = ctypes.c_int64
        self.lib.send_raw.argtypes = [
            ctypes.c_int, ctypes.c_void_p, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ]
        self.lib.open_batch.restype = None
        self.lib.open_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint8,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ]
        # Exercise the library once so a broken libcrypto link is caught at
        # load time (and the fallback engaged), not on the first media tick.
        self._selftest()

    def _selftest(self) -> None:
        slab = b"\x90\xe0\x80\x01\x02\x20\x00hello"
        out, out_off, out_len, sent = self.send(
            fd=-1, n_threads=1, slab=slab,
            pay_off=np.array([0], np.int64),
            pay_len=np.array([len(slab)], np.int32),
            marker=np.array([1], np.uint8),
            pt=np.array([96], np.uint8),
            vp8=np.array([1], np.uint8),
            sn=np.array([7], np.uint16),
            ts=np.array([9], np.uint32),
            ssrc=np.array([3], np.uint32),
            pid=np.array([5], np.int32),
            tl0=np.array([6], np.int32),
            kidx=np.array([2], np.int32),
            ip=np.array([0x7F000001], np.uint32),
            port=np.array([1], np.uint16),
            seal=np.array([1], np.uint8),
            key_idx=np.array([0], np.int32),
            keys=np.zeros((1, 16), np.uint8),
            key_ids=np.array([42], np.uint32),
            counters=np.array([0], np.uint64),
        )
        frame = bytes(out[: out_len[0]])
        if sent != 1 or frame[0] != 0x01 or len(frame) != 14 + 12 + len(slab) + 16:
            raise OSError("egress self-test failed")
        from livekit_server_tpu.runtime.crypto import HAVE_AEAD, MediaCryptoClient

        if not HAVE_AEAD:
            return  # frame shape validated above; no Python AEAD to open with
        inner = MediaCryptoClient(42, bytes(16)).open(frame)
        # VP8 descriptor patched: 15-bit pid=5, tl0=6, keyidx=2 in T/K byte.
        if inner is None or inner[12:19] != bytes(
            [0x90, 0xE0, 0x80, 0x05, 0x06, 0x22, 0x00]
        ):
            raise OSError("egress seal self-test failed")

    def rx_batch(self, fd: int, scratch, offsets, lengths, ips, ports,
                 max_dgram: int = 2048) -> int:
        """Drain a non-blocking UDP socket with recvmmsg into caller-owned
        arrays; returns datagrams received (the batch ingress twin of
        send — one native call per event-loop wake)."""
        return int(self.lib.rx_batch(
            int(fd), scratch.ctypes.data, scratch.nbytes,
            offsets.ctypes.data, lengths.ctypes.data,
            ips.ctypes.data, ports.ctypes.data,
            len(offsets), int(max_dgram),
        ))

    def open_batch(self, blob, offsets, lengths, key_idx, keys,
                   expect_dir: int):
        """Batch-open sealed frames; returns (out, out_off, out_len) with
        out_len[i] = plaintext length or -1 on auth/direction failure."""
        n = len(offsets)
        out_len = np.full(n, -1, np.int32)
        # Plaintext ≤ frame length − 30; lay out at the frame offsets'
        # scale for simplicity (caller slices by out_off/out_len).
        sizes = np.maximum(lengths.astype(np.int64) - 30, 0)
        out_off = np.zeros(n, np.int64)
        np.cumsum(sizes[:-1], out=out_off[1:])
        out = np.zeros(int(sizes.sum()) if n else 0, np.uint8)
        blob_arr = np.frombuffer(blob, np.uint8) if not isinstance(
            blob, np.ndarray
        ) else blob
        # Bind converted arrays to locals: an inline temporary's buffer
        # could be freed before the C call executes.
        offs_c = np.ascontiguousarray(offsets, np.int32)
        lens_c = np.ascontiguousarray(lengths, np.int32)
        kidx_c = np.ascontiguousarray(key_idx, np.int32)
        keys_c = np.ascontiguousarray(keys, np.uint8)
        self.lib.open_batch(
            blob_arr.ctypes.data,
            offs_c.ctypes.data, lens_c.ctypes.data, n,
            kidx_c.ctypes.data, keys_c.ctypes.data,
            int(expect_dir),
            out.ctypes.data, out_off.ctypes.data, out_len.ctypes.data,
        )
        return out, out_off, out_len

    def send(self, fd, n_threads, slab, pay_off, pay_len, marker, pt, vp8,
             sn, ts, ssrc, pid, tl0, kidx, ip, port, seal, key_idx, keys,
             key_ids, counters, ext_blob=b"", ext_off=None, ext_len=None,
             pace_window_us=0):
        """Returns (out, out_off, out_len, sent). With fd < 0 nothing hits
        the network and `out` holds the built frames (tests / TCP path).
        `ext_blob`/`ext_off`/`ext_len` attach pre-serialized RTP header-
        extension sections (profile+length+elements+padding) per entry
        (playout delay, dependency descriptor, …); ext_len 0 = none."""
        n = len(pay_off)
        if ext_off is None:
            ext_off = np.zeros(n, np.int64)
            ext_len = np.zeros(n, np.int32)
        clear_len = 12 + ext_len.astype(np.int64) + pay_len.astype(np.int64)
        out_len = np.where(
            (seal != 0) & (key_idx >= 0), clear_len + self.SEAL_OVERHEAD, clear_len
        ).astype(np.int32)
        out_off = np.zeros(n, np.int64)
        np.cumsum(out_len[:-1], out=out_off[1:])
        out = np.zeros(int(out_off[-1]) + int(out_len[-1]) if n else 0, np.uint8)
        slab_arr = np.frombuffer(slab, np.uint8) if len(slab) else np.zeros(1, np.uint8)
        ext_arr = (
            np.frombuffer(ext_blob, np.uint8) if len(ext_blob)
            else np.zeros(1, np.uint8)
        )

        def c(a, dt):
            return np.ascontiguousarray(a, dt).ctypes.data

        sent = self.lib.egress_batch_send(
            int(fd), int(n_threads), slab_arr.ctypes.data, n,
            c(pay_off, np.int64), c(pay_len, np.int32), c(marker, np.uint8),
            c(pt, np.uint8), c(vp8, np.uint8),
            ext_arr.ctypes.data, c(ext_off, np.int64), c(ext_len, np.int32),
            c(sn, np.uint16),
            c(ts, np.uint32), c(ssrc, np.uint32), c(pid, np.int32),
            c(tl0, np.int32), c(kidx, np.int32), c(ip, np.uint32),
            c(port, np.uint16), c(seal, np.uint8), c(key_idx, np.int32),
            c(np.ascontiguousarray(keys, np.uint8), np.uint8),
            c(key_ids, np.uint32), c(counters, np.uint64),
            out.ctypes.data, out_off.ctypes.data,
            np.ascontiguousarray(out_len).ctypes.data,
            int(pace_window_us),
        )
        return out, out_off, out_len, int(sent)

    def pool_ensure(self, n: int) -> None:
        """Pre-warm the persistent shard worker pool (idempotent)."""
        self.lib.egress_pool_ensure(int(n))

    def pool_size(self) -> int:
        return int(self.lib.egress_pool_size())

    def send_sharded(self, fd, shard_lo, shard_hi, slab, pay_off, pay_len,
                     marker, pt, vp8, sn, ts, ssrc, pid, tl0, kidx, ip,
                     port, seal, key_idx, keys, key_ids, counters, rooms,
                     grp, grp_slots, ext_blob=b"", ext_off=None,
                     ext_len=None, pace_window_us=0):
        """Plane path: entries pre-sorted by (room, sub, track, k) and cut
        into room-aligned shards [shard_lo[i], shard_hi[i]), each run by a
        persistent pool worker (assemble + group-canonical reuse + seal +
        GSO/sendmmsg on its own disjoint out range). `grp[i]` >= 0 names
        the entry's canonical-cache slot (same (track, packet) group),
        -1 forces a direct build; `rooms` scopes slot validity. Returns
        (out, out_off, out_len, sent, shard_sent, shard_built, shard_ns);
        with fd < 0 nothing hits the network and `sent` counts built
        datagrams (tests / build-only mode)."""
        n = len(pay_off)
        n_shards = len(shard_lo)
        if ext_off is None:
            ext_off = np.zeros(n, np.int64)
            ext_len = np.zeros(n, np.int32)
        pay_len_c = np.ascontiguousarray(pay_len, np.int32)
        ext_len_c = np.ascontiguousarray(ext_len, np.int32)
        seal_c = np.ascontiguousarray(seal, np.uint8)
        kix_c = np.ascontiguousarray(key_idx, np.int32)
        clear_len = 12 + ext_len_c.astype(np.int64) + pay_len_c.astype(np.int64)
        out_len = np.where(
            (seal_c != 0) & (kix_c >= 0),
            clear_len + self.SEAL_OVERHEAD, clear_len,
        ).astype(np.int32)
        out_off = np.zeros(n, np.int64)
        np.cumsum(out_len[:-1], out=out_off[1:])
        out = np.zeros(int(out_off[-1]) + int(out_len[-1]) if n else 0, np.uint8)
        slab_arr = (
            np.frombuffer(slab, np.uint8) if not isinstance(slab, np.ndarray)
            else slab
        )
        if not len(slab_arr):
            slab_arr = np.zeros(1, np.uint8)
        ext_arr = (
            np.frombuffer(ext_blob, np.uint8) if len(ext_blob)
            else np.zeros(1, np.uint8)
        )
        shard_sent = np.zeros(n_shards, np.int64)
        shard_built = np.zeros(n_shards, np.int64)
        shard_ns = np.zeros(n_shards, np.int64)
        # Bind every converted array to a keep-list: a temporary's buffer
        # must outlive the C call (see open_batch's same caveat).
        keep = []

        def c(a, dt):
            arr = np.ascontiguousarray(a, dt)
            keep.append(arr)
            return arr.ctypes.data

        sent = self.lib.egress_plane_send(
            int(fd), n_shards, c(shard_lo, np.int64), c(shard_hi, np.int64),
            slab_arr.ctypes.data, n,
            c(pay_off, np.int64), pay_len_c.ctypes.data,
            c(marker, np.uint8), c(pt, np.uint8), c(vp8, np.uint8),
            ext_arr.ctypes.data, c(ext_off, np.int64), ext_len_c.ctypes.data,
            c(sn, np.uint16), c(ts, np.uint32), c(ssrc, np.uint32),
            c(pid, np.int32), c(tl0, np.int32), c(kidx, np.int32),
            c(ip, np.uint32), c(port, np.uint16),
            seal_c.ctypes.data, kix_c.ctypes.data,
            c(keys, np.uint8), c(key_ids, np.uint32), c(counters, np.uint64),
            out.ctypes.data, out_off.ctypes.data, out_len.ctypes.data,
            c(rooms, np.int32), c(grp, np.int32), int(grp_slots),
            int(pace_window_us),
            shard_sent.ctypes.data, shard_built.ctypes.data,
            shard_ns.ctypes.data,
        )
        del keep
        return out, out_off, out_len, int(sent), shard_sent, shard_built, shard_ns

    def send_express(self, fd, slab, pay_off, pay_len, marker, pt, vp8,
                     sn, ts, ssrc, pid, tl0, kidx, ip, port, seal, key_idx,
                     keys, key_ids, counters, rooms=None, grp=None,
                     grp_slots=0, ext_blob=b"", ext_off=None, ext_len=None):
        """Express-lane path: assemble+seal(+send) a small batch inline on
        the calling thread — no shard planning, no pool handoff, no
        pacing. Canonical-group staging still applies when `grp`/`rooms`
        are given (same semantics as send_sharded); pass None to force
        direct builds. Returns (out, out_off, out_len, sent, built);
        with fd < 0 nothing hits the network and `sent` == built."""
        n = len(pay_off)
        if ext_off is None:
            ext_off = np.zeros(n, np.int64)
            ext_len = np.zeros(n, np.int32)
        pay_len_c = np.ascontiguousarray(pay_len, np.int32)
        ext_len_c = np.ascontiguousarray(ext_len, np.int32)
        seal_c = np.ascontiguousarray(seal, np.uint8)
        kix_c = np.ascontiguousarray(key_idx, np.int32)
        clear_len = 12 + ext_len_c.astype(np.int64) + pay_len_c.astype(np.int64)
        out_len = np.where(
            (seal_c != 0) & (kix_c >= 0),
            clear_len + self.SEAL_OVERHEAD, clear_len,
        ).astype(np.int32)
        out_off = np.zeros(n, np.int64)
        np.cumsum(out_len[:-1], out=out_off[1:])
        out = np.zeros(int(out_off[-1]) + int(out_len[-1]) if n else 0, np.uint8)
        slab_arr = (
            np.frombuffer(slab, np.uint8) if not isinstance(slab, np.ndarray)
            else slab
        )
        if not len(slab_arr):
            slab_arr = np.zeros(1, np.uint8)
        ext_arr = (
            np.frombuffer(ext_blob, np.uint8) if len(ext_blob)
            else np.zeros(1, np.uint8)
        )
        if grp is None or rooms is None:
            grp_ptr = rooms_ptr = None
            grp_slots = 0
        built = np.zeros(1, np.int64)
        # Bind every converted array to a keep-list: a temporary's buffer
        # must outlive the C call (see open_batch's same caveat).
        keep = []

        def c(a, dt):
            arr = np.ascontiguousarray(a, dt)
            keep.append(arr)
            return arr.ctypes.data

        if grp is not None and rooms is not None:
            grp_ptr = c(grp, np.int32)
            rooms_ptr = c(rooms, np.int32)
        sent = self.lib.egress_express_send(
            int(fd), slab_arr.ctypes.data, n,
            c(pay_off, np.int64), pay_len_c.ctypes.data,
            c(marker, np.uint8), c(pt, np.uint8), c(vp8, np.uint8),
            ext_arr.ctypes.data, c(ext_off, np.int64), ext_len_c.ctypes.data,
            c(sn, np.uint16), c(ts, np.uint32), c(ssrc, np.uint32),
            c(pid, np.int32), c(tl0, np.int32), c(kidx, np.int32),
            c(ip, np.uint32), c(port, np.uint16),
            seal_c.ctypes.data, kix_c.ctypes.data,
            c(keys, np.uint8), c(key_ids, np.uint32), c(counters, np.uint64),
            out.ctypes.data, out_off.ctypes.data, out_len.ctypes.data,
            rooms_ptr, grp_ptr, int(grp_slots),
            built.ctypes.data,
        )
        del keep
        return out, out_off, out_len, int(sent), int(built[0])

    def send_raw(self, fd, blob, offs, lens, ips, ports) -> int:
        """GSO/sendmmsg pre-built datagrams (blob + per-entry offset/
        length/destination arrays). Load generators and relays use this to
        put wire-ready bytes on the network in a handful of syscalls."""
        blob_arr = (
            blob if isinstance(blob, np.ndarray)
            else np.frombuffer(blob, np.uint8)
        )
        offs_c = np.ascontiguousarray(offs, np.int64)
        lens_c = np.ascontiguousarray(lens, np.int32)
        ips_c = np.ascontiguousarray(ips, np.uint32)
        ports_c = np.ascontiguousarray(ports, np.uint16)
        return int(self.lib.send_raw(
            int(fd), blob_arr.ctypes.data, len(offs_c),
            offs_c.ctypes.data, lens_c.ctypes.data,
            ips_c.ctypes.data, ports_c.ctypes.data,
        ))


_MUNGE_SRC = Path(__file__).resolve().parents[2] / "native" / "munge.cpp"


def _build_munge() -> Path | None:
    return _compile(_MUNGE_SRC, "libmunge.so")


class NativeMunge:
    """One-call-per-tick munge walk: expand bit-packed send/drop/switch
    masks and apply the SN/TS/VP8 rewrites (rtpmunger.go UpdateAndGetSnTs +
    codecmunger/vp8.go UpdateAndGet) with host-owned state — the rewrite
    half of DownTrack.WriteRTP. Semantics pinned to runtime/munge.py's
    numpy spec by tests/test_host_munge.py."""

    def __init__(self, so: Path):
        self.lib = ctypes.CDLL(str(so))
        _check_abi(self.lib, "munge_abi_version", MUNGE_ABI, "libmunge")
        self.lib.munge_walk.restype = ctypes.c_int64
        self.lib.munge_walk.argtypes = (
            [ctypes.c_int32] * 5 + [ctypes.c_void_p] * 11
            + [ctypes.c_void_p] * 13 + [ctypes.c_void_p] * 9
            + [ctypes.c_int64]
        )
        self.lib.munge_walk_multi.restype = ctypes.c_int64
        self.lib.munge_walk_multi.argtypes = (
            [ctypes.c_int32] + [ctypes.c_void_p] * 4   # n_shards, lo/hi/cnt/ns
            + [ctypes.c_int32] * 5 + [ctypes.c_void_p] * 11
            + [ctypes.c_void_p] * 13 + [ctypes.c_void_p] * 9
            + [ctypes.c_int64]
        )

    def walk(self, sn, ts, ts_jump, pid, tl0, keyidx, begin_pic, valid,
             send_bits, drop_bits, switch_bits, state, cap: int):
        """Returns column arrays (rooms, tracks, ks, subs, sn, ts, pid,
        tl0, keyidx) of the `cap`-bounded walk; None if cap overflowed
        in the counting pre-pass (nothing mutated — caller falls back to
        the dense path). Raises RuntimeError on the -2 invariant code:
        the overflow guard fired mid-walk, AFTER state mutation began, so
        a fallback would re-apply the tick on top of half-advanced
        offsets (double-apply corruption on every walked lane). `state`
        is the HostMunger — its arrays are updated in place."""
        R, T, K = sn.shape
        S = state.sn_offset.shape[-1]
        W = send_bits.shape[-1]
        c32 = lambda x: np.ascontiguousarray(x, np.int32)  # noqa: E731
        cw = lambda x: np.ascontiguousarray(x).view(np.uint32)  # noqa: E731
        cu8 = lambda x: np.ascontiguousarray(x, np.uint8)  # noqa: E731
        sn_c, ts_c, tj_c = c32(sn), c32(ts), c32(ts_jump)
        pid_c, tl0_c, ki_c = c32(pid), c32(tl0), c32(keyidx)
        bp_c, v_c = cu8(begin_pic), cu8(valid)
        sb, db, wb = cw(c32(send_bits)), cw(c32(drop_bits)), cw(c32(switch_bits))
        outs = [np.empty(cap, np.int32) for _ in range(9)]
        st_ptrs = [
            getattr(state, f).ctypes.data for f in (
                "sn_offset", "ts_offset", "last_sn", "last_ts",
                "started", "aligned",
                "pid_offset", "tl0_offset", "ki_offset",
                "last_pid", "last_tl0", "last_ki", "v_started",
            )
        ]
        n = self.lib.munge_walk(
            R, T, K, S, W,
            sb.ctypes.data, db.ctypes.data, wb.ctypes.data,
            sn_c.ctypes.data, ts_c.ctypes.data, tj_c.ctypes.data,
            pid_c.ctypes.data, tl0_c.ctypes.data, ki_c.ctypes.data,
            bp_c.ctypes.data, v_c.ctypes.data,
            *st_ptrs,
            *[o.ctypes.data for o in outs],
            cap,
        )
        if n == -1:
            return None  # pre-pass overflow: state untouched, safe fallback
        if n < -1:
            raise RuntimeError(
                f"munge_walk invariant violation (code {n}): capacity "
                "overflow after state mutation; dense fallback would "
                "double-apply this tick"
            )
        return tuple(o[:n] for o in outs)

    def walk_multi(self, sn, ts, ts_jump, pid, tl0, keyidx, begin_pic,
                   valid, send_bits, drop_bits, switch_bits, state,
                   cap: int, r_lo, r_hi):
        """Sharded walk: each shard owns the contiguous room range
        [r_lo[i], r_hi[i]) — state rows are room-indexed, so whole-room
        ownership keeps every state write disjoint across shards. Output
        is written at exact prefix-sum bases, bit-identical to a single
        walk regardless of shard count. Returns (columns, shard_counts,
        shard_ns) with the same columns as walk(); None on pre-pass
        overflow (nothing mutated); raises on the -2 invariant code."""
        R, T, K = sn.shape
        S = state.sn_offset.shape[-1]
        W = send_bits.shape[-1]
        c32 = lambda x: np.ascontiguousarray(x, np.int32)  # noqa: E731
        cw = lambda x: np.ascontiguousarray(x).view(np.uint32)  # noqa: E731
        cu8 = lambda x: np.ascontiguousarray(x, np.uint8)  # noqa: E731
        lo_c, hi_c = c32(r_lo), c32(r_hi)
        n_shards = len(lo_c)
        shard_counts = np.zeros(n_shards, np.int64)
        shard_ns = np.zeros(n_shards, np.int64)
        sn_c, ts_c, tj_c = c32(sn), c32(ts), c32(ts_jump)
        pid_c, tl0_c, ki_c = c32(pid), c32(tl0), c32(keyidx)
        bp_c, v_c = cu8(begin_pic), cu8(valid)
        sb, db, wb = cw(c32(send_bits)), cw(c32(drop_bits)), cw(c32(switch_bits))
        outs = [np.empty(cap, np.int32) for _ in range(9)]
        st_ptrs = [
            getattr(state, f).ctypes.data for f in (
                "sn_offset", "ts_offset", "last_sn", "last_ts",
                "started", "aligned",
                "pid_offset", "tl0_offset", "ki_offset",
                "last_pid", "last_tl0", "last_ki", "v_started",
            )
        ]
        n = self.lib.munge_walk_multi(
            n_shards, lo_c.ctypes.data, hi_c.ctypes.data,
            shard_counts.ctypes.data, shard_ns.ctypes.data,
            R, T, K, S, W,
            sb.ctypes.data, db.ctypes.data, wb.ctypes.data,
            sn_c.ctypes.data, ts_c.ctypes.data, tj_c.ctypes.data,
            pid_c.ctypes.data, tl0_c.ctypes.data, ki_c.ctypes.data,
            bp_c.ctypes.data, v_c.ctypes.data,
            *st_ptrs,
            *[o.ctypes.data for o in outs],
            cap,
        )
        if n == -1:
            return None  # pre-pass overflow: state untouched, safe fallback
        if n < -1:
            raise RuntimeError(
                f"munge_walk_multi invariant violation (code {n}): "
                "capacity overflow after state mutation; dense fallback "
                "would double-apply this tick"
            )
        return tuple(o[:n] for o in outs), shard_counts, shard_ns


def _load():
    so = _build()
    if so is not None:
        try:
            return _NativeRTP(so)
        except OSError:
            pass
    return _PythonRTP()


def _load_versioned(build, cls):
    """Load an ABI-versioned library; a mismatch (stale cached .so) gets
    exactly one forced rebuild before degrading to the Python path."""
    for attempt in (0, 1):
        so = build()
        if so is None:
            return None
        try:
            return cls(so)
        except OSError:
            if attempt == 0:
                try:
                    so.unlink()
                except OSError:
                    return None
                continue
            return None
    return None


def _load_egress():
    return _load_versioned(_build_egress, NativeEgress)


def _load_munge():
    return _load_versioned(_build_munge, NativeMunge)


def _express_smoke(eg: "NativeEgress") -> str | None:
    """Exercise egress_express_send build-only and require byte parity
    with the batched builder for the same entries (one sealed + one
    clear). Returns a failure string or None."""
    slab = b"\x90\xe0\x80\x01\x02\x20\x00express-smoke"
    kw = dict(
        slab=slab,
        pay_off=np.array([0, 0], np.int64),
        pay_len=np.array([len(slab)] * 2, np.int32),
        marker=np.array([1, 1], np.uint8),
        pt=np.array([96, 96], np.uint8),
        vp8=np.array([1, 1], np.uint8),
        sn=np.array([7, 8], np.uint16),
        ts=np.array([9, 9], np.uint32),
        ssrc=np.array([3, 4], np.uint32),
        pid=np.array([5, 5], np.int32),
        tl0=np.array([6, 6], np.int32),
        kidx=np.array([2, 2], np.int32),
        ip=np.array([0x7F000001] * 2, np.uint32),
        port=np.array([1, 1], np.uint16),
        seal=np.array([1, 0], np.uint8),
        key_idx=np.array([0, -1], np.int32),
        keys=np.zeros((1, 16), np.uint8),
        key_ids=np.array([42], np.uint32),
        counters=np.array([0, 0], np.uint64),
    )
    try:
        out_x, off_x, len_x, sent_x, built_x = eg.send_express(fd=-1, **kw)
    except Exception as e:
        return f"send_express crashed: {e!r}"
    if sent_x != 2 or built_x != 2:
        return f"send_express built {built_x}/2"
    out_b, off_b, len_b, _ = eg.send(fd=-1, n_threads=1, **kw)
    if not (np.array_equal(len_x, len_b) and np.array_equal(out_x, out_b)):
        return "send_express output differs from batched builder"
    return None


def native_smoke() -> list[str]:
    """Strict build/ABI check for tools/check.py: compile every native
    library from source and verify its ABI version and self-test. Returns
    a list of failure strings (empty = healthy). Unlike the import-time
    loaders this does NOT fall back silently — a libegress regression
    must surface in CI before the bench discovers it."""
    failures: list[str] = []
    if _build() is None:
        failures.append("librtp_parser.so: build failed")
    so = _build_egress()
    if so is None:
        failures.append("libegress.so: build failed")
    else:
        try:
            eg = NativeEgress(so)
            err = _express_smoke(eg)
            if err:
                failures.append(f"libegress.so express: {err}")
        except OSError as e:
            failures.append(f"libegress.so: {e}")
    so = _build_munge()
    if so is None:
        failures.append("libmunge.so: build failed")
    else:
        try:
            NativeMunge(so)
        except OSError as e:
            failures.append(f"libmunge.so: {e}")
    return failures


rtp = _load()
egress = _load_egress()
munge = _load_munge()
