"""Egress service: recording/streaming job API.

Reference parity: pkg/service/egress.go:31-262 — the livekit.Egress Twirp
API (StartRoomCompositeEgress, StartWebEgress, StartParticipantEgress,
StartTrackCompositeEgress, StartTrackEgress, UpdateLayout, UpdateStream,
ListEgress, StopEgress) plus pkg/rtc/egress.go's track-egress launcher.
The reference dispatches jobs to external egress workers over psrpc; here
jobs are published on the bus topic `egress_jobs` (a worker subscribes and
reports via `egress_updates`), state lives in the store, and lifecycle
events flow to telemetry/webhooks — the same seams, bus-for-psrpc.
"""

from __future__ import annotations

import enum
import json
import time
from dataclasses import dataclass, field
from typing import TYPE_CHECKING

from aiohttp import web

from livekit_server_tpu.utils import ids

if TYPE_CHECKING:
    from livekit_server_tpu.service.server import LivekitServer


class EgressStatus(enum.IntEnum):
    STARTING = 0
    ACTIVE = 1
    ENDING = 2
    COMPLETE = 3
    FAILED = 4
    ABORTED = 5
    LIMIT_REACHED = 6


@dataclass
class EgressInfo:
    egress_id: str = ""
    room_name: str = ""
    kind: str = ""           # room_composite | web | participant | track_composite | track
    status: EgressStatus = EgressStatus.STARTING
    started_at: int = 0
    ended_at: int = 0
    error: str = ""
    request: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        d = dict(vars(self))
        d["status"] = int(self.status)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "EgressInfo":
        d = dict(d)
        d["status"] = EgressStatus(d.get("status", 0))
        return cls(**d)


class EgressService:
    """Twirp livekit.Egress at /twirp/livekit.Egress/<Method>."""

    PREFIX = "/twirp/livekit.Egress/"
    JOBS_TOPIC = "egress_jobs"
    UPDATES_TOPIC = "egress_updates"

    KINDS = {
        "StartRoomCompositeEgress": "room_composite",
        "StartWebEgress": "web",
        "StartParticipantEgress": "participant",
        "StartTrackCompositeEgress": "track_composite",
        "StartTrackEgress": "track",
    }

    def __init__(self, server: "LivekitServer"):
        self.server = server

    @property
    def egresses(self) -> dict:
        """Shared store owned by the IOInfoService aggregator
        (pkg/service/ioservice.go): the Twirp handlers create/delete
        entries here and the aggregator's bus worker updates them."""
        return self.server.ioinfo.egresses

    async def handle(self, request: web.Request) -> web.Response:
        from livekit_server_tpu.auth import (
            TokenError,
            ensure_record_permission,
            verify_token,
        )

        method = request.path.removeprefix(self.PREFIX)
        token = request.headers.get("Authorization", "").removeprefix("Bearer ").strip()
        try:
            claims = verify_token(token, self.server.config.keys)
        except TokenError as e:
            return web.json_response({"msg": str(e)}, status=401)
        # Reference parity: egress needs the dedicated roomRecord grant
        # (egress.go EnsureRecordPermission) — roomAdmin is NOT a substitute,
        # and in this build roomAdmin is room-scoped anyway.
        if not ensure_record_permission(claims):
            return web.json_response({"msg": "requires roomRecord"}, status=403)
        try:
            body = await request.json()
        except json.JSONDecodeError:
            body = {}

        if method in self.KINDS:
            return await self._start(self.KINDS[method], body)
        if method == "ListEgress":
            items = [
                e.to_dict()
                for e in self.egresses.values()
                if not body.get("room_name") or e.room_name == body["room_name"]
            ]
            return web.json_response({"items": items})
        if method == "StopEgress":
            return await self._stop(body.get("egress_id", ""))
        if method in ("UpdateLayout", "UpdateStream"):
            e = self.egresses.get(body.get("egress_id", ""))
            if e is None:
                return web.json_response({"msg": "egress not found"}, status=404)
            await self._publish_job({"kind": method.lower(), "egress": e.to_dict(), "update": body})
            return web.json_response(e.to_dict())
        return web.json_response({"msg": f"unknown method {method}"}, status=404)

    async def _start(self, kind: str, body: dict) -> web.Response:
        info = EgressInfo(
            egress_id=ids.new_guid(ids.EGRESS_PREFIX),
            room_name=body.get("room_name", ""),
            kind=kind,
            status=EgressStatus.STARTING,
            started_at=int(time.time()),
            request=body,
        )
        self.egresses[info.egress_id] = info
        self.server.ioinfo.stamp(info.egress_id)
        dispatched = await self._publish_job({"kind": "start", "egress": info.to_dict()})
        if not dispatched:
            # No worker listening (egress.go errNoEgressWorkers analog).
            info.status = EgressStatus.ABORTED
            info.error = "no egress workers available"
            info.ended_at = int(time.time())
        return web.json_response(info.to_dict())

    async def _stop(self, egress_id: str) -> web.Response:
        info = self.egresses.get(egress_id)
        if info is None:
            return web.json_response({"msg": "egress not found"}, status=404)
        if info.status in (EgressStatus.COMPLETE, EgressStatus.FAILED, EgressStatus.ABORTED):
            return web.json_response({"msg": "egress already ended"}, status=400)
        info.status = EgressStatus.ENDING
        self.server.ioinfo.stamp(egress_id)
        await self._publish_job({"kind": "stop", "egress": info.to_dict()})
        return web.json_response(info.to_dict())

    async def _publish_job(self, job: dict) -> int:
        bus = getattr(self.server.router, "bus", None)
        if bus is None:
            return 0
        return await bus.publish(self.JOBS_TOPIC, json.dumps(job))
