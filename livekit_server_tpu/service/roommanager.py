"""RoomManager: per-node room registry + participant session workers.

Reference parity: pkg/service/roommanager.go (StartSession :236-496,
getOrCreateRoom :499-577, rtcSessionWorker :580-634, admin ops :655-761)
plus the idle-room reaper (server.go backgroundWorker :367). The node's
single PlaneRuntime is owned here; a tick dispatcher routes TickResults to
each room's handlers (speakers, egress, keyframe requests) — replacing the
reference's per-room worker goroutines (room.go:1278-1396).
"""

from __future__ import annotations

import asyncio
from typing import Callable

import numpy as np

from livekit_server_tpu.config.config import Config
from livekit_server_tpu.models import plane
from livekit_server_tpu.ops import audio as audio_ops, bwe as bwe_ops
from livekit_server_tpu.ops.pacer import WIRE_OVERHEAD_BYTES
from livekit_server_tpu.protocol import models as pm
from livekit_server_tpu.protocol.signal import (
    SignalResponse,
    decode_signal_request,
    encode_signal_response,
)
from livekit_server_tpu.routing.fleet import FencedWriteRejected
from livekit_server_tpu.routing.messagechannel import (
    ChannelClosed,
    ChannelFull,
    MessageChannel,
)
from livekit_server_tpu.routing.router import Router
from livekit_server_tpu.rtc import Participant, Room, handle_participant_signal
from livekit_server_tpu.runtime import CapacityError, PlaneRuntime
from livekit_server_tpu.runtime.plane_runtime import TickResult
from livekit_server_tpu.service.store import ObjectStore
from livekit_server_tpu.utils import ids

# Failover checkpoints outlive a node crash but not a forgotten room:
# long enough for survivors to notice the lapsed lease (~lease_ttl) and
# win the takeover race, short enough that a deliberately deleted room
# cannot be resurrected much later from a stale row image.
CHECKPOINT_TTL_S = 30.0

# Canonical admission-denial causes for telemetry and the traffic twin:
# every human-readable refusal string from _admission_denied rolls up to
# one of overload | draining | no_capacity | fenced, so dashboards and
# twin runs can attribute rejected joins without string-matching prose.
DENIAL_REASON_LABELS = {
    "node fenced (quorum lost)": "fenced",
    "node draining": "draining",
    "no plane capacity for a new room": "no_capacity",
    "node overloaded": "overload",
    "max rooms on node": "no_capacity",
    "max tracks on node": "no_capacity",
    "node ingress packet rate exceeded": "overload",
    "node ingress byte rate exceeded": "overload",
}


class RoomManager:
    def __init__(
        self,
        config: Config,
        router: Router,
        store: ObjectStore,
        mesh=None,
        telemetry=None,
    ):
        self.config = config
        self.router = router
        self.store = store
        self.telemetry = telemetry
        p = config.plane
        if p.pager_enabled:
            from livekit_server_tpu.models import paged
            from livekit_server_tpu.runtime.paged_runtime import PagedPlaneRuntime

            pool = p.pager_pool_pages or (
                p.rooms
                * (p.tracks_per_room // p.pager_tpage)
                * (p.subs_per_room // p.pager_spage)
            )
            runtime_cls = PagedPlaneRuntime
            dims = paged.PagedDims(
                p.rooms, p.tracks_per_room, p.pkts_per_track, p.subs_per_room,
                tpage=p.pager_tpage, spage=p.pager_spage, pool_pages=pool,
            )
        else:
            runtime_cls = PlaneRuntime
            dims = plane.PlaneDims(
                p.rooms, p.tracks_per_room, p.pkts_per_track, p.subs_per_room
            )
        extra = {"paged_kernel": p.paged_kernel} if p.pager_enabled else {}
        self.runtime = runtime_cls(
            dims,
            tick_ms=p.tick_ms,
            mesh=mesh,
            **extra,
            low_latency=p.low_latency,
            red_enabled="audio/red" in config.room.enabled_codecs,
            audio_params=audio_ops.AudioLevelParams(
                active_level=config.audio.active_level,
                min_percentile=config.audio.min_percentile,
                observe_interval_ms=config.audio.update_interval_ms,
                smooth_intervals=config.audio.smooth_intervals,
            ),
            bwe_params=bwe_ops.BWEParams(
                nack_ratio_threshold=config.rtc.congestion_control.nack_ratio_threshold,
                nack_window_min_packets=config.rtc.congestion_control.nack_window_min_packets,
                estimate_required_downgrades=config.rtc.congestion_control.estimate_required_downgrades,
                congested_min_estimate=config.rtc.congestion_control.min_channel_capacity,
            ),
            egress_shards=config.egress.shards,
            egress_multicast=config.egress.multicast_seal,
            express_max_subs=p.express_max_subs,
            express_max_rooms=p.express_max_rooms,
            trace_enabled=config.trace.enabled,
            trace_ring_ticks=config.trace.ring_ticks,
            trace_sample_every=config.trace.sample_every,
            blackbox_events=config.trace.blackbox_events,
        )
        self.rooms: dict[str, Room] = {}
        self._row_to_room: dict[int, Room] = {}
        self._create_locks: dict[str, asyncio.Lock] = {}
        self.udp = None     # UDPMediaTransport, attached by the server at start
        # Media-wire key registry (the DTLS-SRTP key-exchange seat): one
        # AEAD session per participant, minted at join and delivered over
        # the authenticated signal channel.
        from livekit_server_tpu.runtime.crypto import HAVE_AEAD, MediaCryptoRegistry

        # No AEAD backend installed ⇒ run cleartext (room.py join path and
        # the UDP transport both already branch on crypto being None).
        self.crypto = MediaCryptoRegistry() if HAVE_AEAD else None
        from livekit_server_tpu.utils.logger import Logger

        self.log = Logger()  # server start replaces with a node-scoped one
        # Black-box dumps go to the manager's log (re-pointed alongside
        # self.log when the server installs the node-scoped logger).
        self.runtime.blackbox.log = self.log
        self.agents = None  # AgentService; room/publisher job dispatch
        self.runtime.on_tick(self._dispatch_tick)
        self._reaper_task: asyncio.Task | None = None
        self._failover_task: asyncio.Task | None = None
        # Serializes snapshot→publish in checkpoint_rooms: without it, a
        # cadence-driven call that snapshotted, then yielded on the bus
        # write, can land its STALE row over a fresher concurrent publish.
        self._ckpt_lock = asyncio.Lock()
        # Plane supervision: tick watchdog + restart-from-snapshot, with
        # the per-room checkpoint publisher as its cadence callback.
        self.supervisor = None
        sup = config.supervisor
        if sup.enabled:
            from livekit_server_tpu.runtime.supervisor import PlaneSupervisor
            from livekit_server_tpu.utils.backoff import BackoffPolicy

            self.supervisor = PlaneSupervisor(
                self.runtime,
                tick_deadline_s=sup.tick_deadline_ms / 1000.0,
                warmup_deadline_s=sup.warmup_deadline_s,
                check_interval_s=sup.check_interval_ms / 1000.0,
                checkpoint_interval_s=sup.checkpoint_interval_s,
                max_restarts=sup.max_restarts,
                overload_grace=sup.overload_grace,
                ckpt_generations=config.integrity.checkpoint_generations,
                backoff=BackoffPolicy(
                    base=sup.restart_backoff_base_s, max_delay=sup.restart_backoff_max_s
                ),
                telemetry=telemetry,
                log=self.log,
            )
            self.supervisor.room_checkpoint_cb = self.checkpoint_rooms
        # Deterministic fault injection (chaos harness) — default-off; the
        # injector only exists when config.faults.enabled is set.
        self.fault = None
        if config.faults.enabled:
            from livekit_server_tpu.runtime.faultinject import FaultInjector

            self.fault = FaultInjector.from_config(config.faults)
            self.runtime.fault = self.fault
            self.runtime.ingest.fault = self.fault
        # Overload governor (runtime/governor.py): closes the loop from
        # tick telemetry to the degradation ladder. Attached to the
        # runtime (per-tick sensor feed) and consulted by admission; the
        # supervisor reads runtime.governor for its stall grace.
        self.governor = None
        self.admission_rejected: dict[str, int] = {}
        # Same refusals keyed by canonical cause (overload | draining |
        # no_capacity | fenced) — the twin and telemetry attribute
        # rejected joins by WHY, not just by kind.
        self.admission_denied_reasons: dict[str, int] = {}
        if config.limits.governor_enabled:
            from livekit_server_tpu.runtime.governor import OverloadGovernor

            self.governor = OverloadGovernor.from_config(
                self.runtime, config.limits, log=self.log
            )
            self.runtime.governor = self.governor
        # State-integrity plane (runtime/integrity.py): on-device audits
        # on the tick cadence, row quarantine + repair from the
        # supervisor's last verified checkpoint, storm/repair-failure
        # escalation to a supervisor restart (cause `integrity`).
        self.integrity = None
        integ = config.integrity
        if integ.enabled:
            from livekit_server_tpu.runtime.integrity import IntegrityMonitor

            self.integrity = IntegrityMonitor(
                self.runtime,
                audit_every_ticks=integ.audit_every_ticks,
                max_row_repairs=integ.max_row_repairs,
                storm_threshold=integ.storm_threshold,
                log=self.log,
            )
            self.runtime.integrity = self.integrity
            if self.supervisor is not None:
                self.integrity.snapshot_provider = self.supervisor.last_good_snapshot
                self.integrity.escalate_cb = self.supervisor.request_restart
        # Room-checkpoint generations on the KV bus: base key + :g1..:gK-1,
        # rotated from this local history (payload strings, newest first).
        self._ckpt_gens = max(1, integ.checkpoint_generations)
        self._ckpt_history: dict[str, list[str]] = {}
        self.ckpt_fallbacks = 0  # room-restore generations rejected
        # Fired for every checkpoint/snapshot adoption (failover restore
        # and migration alike); subscription masks never travel in a
        # snapshot (restore_room docstring), so this is where re-attach
        # logic — and the drills standing in for it — re-subscribes.
        self.on_adopt: list = []
        # Live migration plane (service/migration.py): two-phase room
        # handoff + node drain. Needs a shared bus to talk to peers —
        # a bus-less single-node router runs without it.
        self.migration = None
        if config.migration.enabled and getattr(router, "bus", None) is not None:
            from livekit_server_tpu.service.migration import MigrationOrchestrator

            self.migration = MigrationOrchestrator(self)
        # Fleet coordination plane (service/fleetplane.py): epoch-fenced
        # room ownership, self-fencing on lease loss, elected failover
        # and the load rebalancer. Needs a shared bus AND a router that
        # runs the lease loop (KVRouter) — single-node runs without it.
        self.fleet = None
        if config.fleet.enabled and hasattr(router, "on_lease"):
            from livekit_server_tpu.service.fleetplane import FleetPlane

            self.fleet = FleetPlane(self)
        router.on_new_session(self.start_session)
        self._update_node_stats()

    # -- room lifecycle ---------------------------------------------------
    async def get_or_create_room(
        self, name: str, info: pm.RoomInfo | None = None,
        *, admission_kind: str = "room",
    ) -> Room:
        # admission_kind: 'room' for client-driven creates; 'restore' when
        # the failover orchestrator re-homes a dead node's room (same hard
        # gates, exempt from the governor's transient overload ladder).
        room = self.rooms.get(name)
        if room is not None:
            return room
        # Serialize creation per name: a second joiner arriving during the
        # awaits below (store load, migration-snapshot restore) must wait
        # for the fully-initialized room — subscribing against a row whose
        # ctrl masks a restore is about to overwrite would silently wipe
        # the subscription.
        lock = self._create_locks.setdefault(name, asyncio.Lock())
        async with lock:
            room = self.rooms.get(name)
            if room is not None:
                return room
            reason = self._admission_denied(admission_kind)
            if reason:
                raise CapacityError(reason)
            stored = await self.store.load_room(name)
            room = Room(name, self.runtime, info=info or stored)
            room.udp = self.udp
            room.crypto = self.crypto
            # Publish-admission gate consulted by Participant.add_track_request.
            room.admission = self._admission_denied
            if info is None and stored is None:
                room.info.empty_timeout = self.config.room.empty_timeout_s
                room.info.departure_timeout = self.config.room.departure_timeout_s
                room.info.max_participants = self.config.room.max_participants
            await self._maybe_restore_room(room)
            self.rooms[name] = room
            self._row_to_room[room.slots.row] = room
            await self.store.store_room(room.info)
            try:
                await self.router.set_node_for_room(
                    name, self.router.local_node.node_id
                )
            except FencedWriteRejected:
                # Lost the ownership election: another node claimed a
                # higher epoch between our admission check and the pin.
                # Tear the half-created replica down and refuse — the
                # epoch holder serves this room.
                self.rooms.pop(name, None)
                self._row_to_room.pop(room.slots.row, None)
                room.close(pm.DisconnectReason.MIGRATION)
                raise CapacityError("room owned by another node")
        self._create_locks.pop(name, None)
        self._update_node_stats()
        from livekit_server_tpu.runtime.trace import EV_ROOM_OPEN

        self.runtime.blackbox.emit(room.slots.row, EV_ROOM_OPEN)
        self.log.info("room started", room=name, row=room.slots.row)
        self._notify("room_started", room=room.info.to_dict())
        if self.agents is not None:
            # room agent job on room start; publisher job on first publish
            # (roommanager.go / rtc/agentclient.go launch points)
            asyncio.ensure_future(self.agents.launch_room_job(name))

            def on_publish(pub, _track, room_name=name):
                if not pub.published:  # first track → becoming a publisher
                    asyncio.ensure_future(
                        self.agents.launch_publisher_job(room_name, pub.identity)
                    )

            room.on_track_published.append(on_publish)
        return room

    async def delete_room(self, name: str) -> None:
        room = self.rooms.pop(name, None)
        if room is not None:
            self._row_to_room.pop(room.slots.row, None)
            from livekit_server_tpu.runtime.trace import EV_ROOM_CLOSE

            self.runtime.blackbox.emit(room.slots.row, EV_ROOM_CLOSE)
            room.close(pm.DisconnectReason.ROOM_DELETED)
            self.log.info("room finished", room=name)
            self._notify("room_finished", room=room.info.to_dict())
        await self.store.delete_room(name)
        bus = getattr(self.router, "bus", None)
        if bus is not None:
            # A deliberate delete must also retire the failover checkpoint
            # — every generation of it — or a same-name room created
            # within CHECKPOINT_TTL_S would adopt the dead room's SN/TS
            # lanes. Runs BEFORE clear_room_state releases the ownership
            # epoch, so the deletes go out under our own fence.
            try:
                for key in self._checkpoint_keys(name):
                    await self._fenced_delete(name, key)
            except FencedWriteRejected:
                pass   # new owner's checkpoints are theirs to retire
            except (ConnectionError, OSError):
                pass
        await self.router.clear_room_state(name)
        self._ckpt_history.pop(name, None)
        self._update_node_stats()

    def _checkpoint_keys(self, name: str) -> list[str]:
        """KV keys for a room's checkpoint generations, newest first."""
        return [f"room_checkpoint:{name}"] + [
            f"room_checkpoint:{name}:g{i}" for i in range(1, self._ckpt_gens)
        ]

    # The ONLY writers for room-checkpoint/snapshot KV keys (graftcheck
    # GC09 fencing discipline): with the fleet plane up every write
    # CAS-asserts this node's ownership epoch first, so a stale owner's
    # checkpoint loses (FencedWriteRejected) instead of clobbering the
    # takeover winner's state. Without a fleet (single node, fleet
    # disabled) they fall through to the raw bus.
    async def _fenced_set(
        self, room_name: str, key: str, value: str, ttl: float | None = None
    ) -> None:
        if self.fleet is not None:
            await self.fleet.fence.guarded_set(room_name, key, value, ttl)
        else:
            await self.router.bus.set(key, value, ttl)

    async def _fenced_delete(self, room_name: str, key: str) -> None:
        if self.fleet is not None and self.fleet.fence.owns(room_name):
            await self.fleet.fence.guarded_delete(room_name, key)
        else:
            await self.router.bus.delete(key)

    # -- session handling (roommanager.go StartSession) -------------------
    async def start_session(
        self,
        room_name: str,
        init: dict,
        request_source: MessageChannel,
        response_sink: MessageChannel,
    ) -> None:
        try:
            room = await self.get_or_create_room(room_name)
        except CapacityError as e:
            # Node room tensor full or admission refused: reject
            # explicitly (the reference sends a limits-reached error; a
            # silent open WebSocket is the failure ADVICE flagged). The
            # sink close lets rtcservice's pump end the connection.
            self._reject_session(
                response_sink, request_source, str(e) or "node at capacity"
            )
            return
        identity = init.get("identity", "")

        existing = room.participants.get(identity)
        if (
            existing is not None
            and existing.client_config is not None
            and existing.client_config.resume_connection == "disabled"
        ):
            # Client-quirk config forbids resume for this device/SDK
            # (clientconfiguration → ResumeConnection DISABLED): force a
            # full rejoin instead of session resumption.
            existing = None
        if existing is not None and init.get("reconnect"):
            # resume: swap the signal sinks onto the live participant
            # (roommanager.go:266-316); bump the epoch so the OLD worker's
            # teardown becomes a no-op when its socket finally closes.
            existing.session_epoch += 1
            existing.response_sink = response_sink
            # Fresh media queue: the old connection's pump may still hold a
            # pending get() on the previous queue — re-attaching reroutes
            # egress to this connection instead of splitting frames.
            self._attach_media_queue(room, existing)
            existing.send("reconnect", {})
            await self._session_worker(room, existing, request_source)
            return

        # Node admission (after resume handling: an existing session may
        # always resume — the governor only refuses NEW load).
        reason = self._admission_denied("join")
        if reason:
            self._reject_session(response_sink, request_source, reason)
            return
        # A same-identity rejoin replaces its old session (room.join kicks
        # the duplicate), so it must not count toward the cap.
        max_p = room.info.max_participants
        if max_p and identity not in room.participants and len(room.participants) >= max_p:
            self._reject_session(response_sink, request_source, "room is full")
            return
        participant = Participant(
            identity,
            room,
            response_sink=response_sink,
            grants=init.get("grants"),
            name=init.get("name", ""),
            auto_subscribe=init.get("auto_subscribe", True),
            client_info=init.get("client_info"),
        )
        self._attach_media_queue(room, participant)
        try:
            join = room.join(participant)
        except CapacityError:
            # subscriber-column tensor full (slots.alloc_sub)
            self._reject_session(response_sink, request_source)
            return
        if participant.client_config is not None:
            join["client_configuration"] = participant.client_config.to_dict()
        participant.send("join", join)
        from livekit_server_tpu.runtime.trace import EV_JOIN

        self.runtime.blackbox.emit(
            room.slots.row, EV_JOIN, float(participant.sub_col)
        )
        self.log.info("participant joined", room=room_name, participant=identity)
        await self.store.store_participant(room_name, participant.to_info())
        self._update_node_stats()
        self._notify(
            "participant_joined",
            room=room.info.to_dict(),
            participant=participant.to_info().to_dict(),
        )
        await self._session_worker(room, participant, request_source)

    async def _session_worker(
        self, room: Room, participant: Participant, request_source: MessageChannel
    ) -> None:
        """Per-participant signal loop (rtcSessionWorker :580)."""
        epoch = participant.session_epoch
        try:
            while not participant.disconnected.is_set():
                raw = await request_source.read_message()
                try:
                    req = decode_signal_request(raw)
                except ValueError:
                    continue  # unknown/garbage frame: skip (reference logs)
                try:
                    handle_participant_signal(room, participant, req)
                except Exception:  # noqa: BLE001 — a malformed payload must
                    # not tear down the session (reference logs and skips)
                    pass
        except ChannelClosed:
            pass
        finally:
            # A stale worker (its session was resumed, or its identity was
            # replaced by a newer connection) must not tear down the live
            # participant or its store record.
            cur = room.participants.get(participant.identity)
            stale = participant.session_epoch != epoch or (
                cur is not None and cur is not participant
            )
            if not stale:
                if not participant.disconnected.is_set():
                    room.remove_participant(participant, pm.DisconnectReason.SIGNAL_CLOSE)
                from livekit_server_tpu.runtime.trace import EV_LEAVE

                self.runtime.blackbox.emit(
                    room.slots.row, EV_LEAVE, float(participant.sub_col)
                )
                await self.store.delete_participant(room.name, participant.identity)
                self.log.info(
                    "participant left", room=room.name,
                    participant=participant.identity,
                    reason=participant.close_reason.name,
                )
                self._update_node_stats()
                self._notify(
                    "participant_left",
                    room=room.info.to_dict(),
                    participant=participant.to_info().to_dict(),
                )

    def _admission_denied(self, kind: str) -> str:
        """Non-empty rejection reason when the node must refuse new work
        of `kind` ('room' / 'join' / 'publish'), or a failover adoption
        ('restore') — the config.go LimitConfig seat plus the governor's
        L4. Every refusal is explicit (signal response) and counted;
        existing sessions are never evicted by any of these gates. A
        'restore' passes the same hard gates as 'room' (fenced, draining,
        plane headroom, max_rooms) but never the transient overload
        ladder — the fleet already admitted that room before its node
        died, and refusing its restore on a busy survivor would orphan
        it permanently (governor.should_admit carries the carve-out)."""
        lim = self.config.limits
        st = self.router.local_node.stats
        reason = ""
        if self.fleet is not None and self.fleet.fenced:
            # Quorum lost: this node may already have been failed over
            # by the majority side — admitting anything here would build
            # state a survivor is about to own.
            reason = "node fenced (quorum lost)"
        elif self.migration is not None and self.migration.draining:
            # Drain works with the governor disabled too: the orchestrator
            # itself refuses every admission kind while rooms move off.
            reason = "node draining"
        elif kind in ("room", "restore") and (
            self.runtime.occupancy().get("admittable_rooms", 1) <= 0
        ):
            # Real plane headroom (paged: free pages / min room footprint;
            # dense: free rows) — checked before the governor so page-pool
            # exhaustion reports its own reason rather than "overloaded".
            reason = "no plane capacity for a new room"
        elif self.governor is not None and not self.governor.should_admit(kind):
            reason = "node overloaded"
        elif kind in ("room", "restore") and (
            lim.max_rooms and len(self.rooms) >= lim.max_rooms
        ):
            reason = "max rooms on node"
        elif kind == "publish" and lim.num_tracks and (
            sum(len(r.tracks) for r in self.rooms.values()) >= lim.num_tracks
        ):
            reason = "max tracks on node"
        elif kind in ("join", "publish") and (
            lim.packets_per_sec and st.packets_in_per_sec > lim.packets_per_sec
        ):
            reason = "node ingress packet rate exceeded"
        elif kind in ("join", "publish") and (
            lim.bytes_per_sec and st.bytes_in_per_sec > lim.bytes_per_sec
        ):
            reason = "node ingress byte rate exceeded"
        if reason:
            self.admission_rejected[kind] = self.admission_rejected.get(kind, 0) + 1
            label = DENIAL_REASON_LABELS.get(reason, "overload")
            self.admission_denied_reasons[label] = (
                self.admission_denied_reasons.get(label, 0) + 1
            )
            if self.governor is not None:
                self.governor.note_rejection(kind)
            self.log.warn("admission refused", kind=kind, reason=reason)
        return reason

    def _reject_session(
        self,
        response_sink: MessageChannel,
        request_source: MessageChannel,
        error: str = "node at capacity",
    ) -> None:
        """Send an explicit JOIN_FAILURE leave and close both channels."""
        try:
            response_sink.write_message(
                encode_signal_response(
                    SignalResponse(
                        "leave",
                        {
                            "reason": int(pm.DisconnectReason.JOIN_FAILURE),
                            "can_reconnect": False,
                            "error": error,
                        },
                    )
                )
            )
        except (ChannelFull, ChannelClosed):
            pass
        response_sink.close()
        request_source.close()

    def _attach_media_queue(self, room: Room, participant: Participant) -> None:
        """Subscriber egress → bounded msgpack queue drained by the WS pump
        (the transport half of DownTrack.WriteRTP → pacer → wire)."""
        import msgpack

        q: asyncio.Queue = asyncio.Queue(maxsize=512)
        participant.media_queue = q

        def media_out(pkt, room=room, q=q):
            data = msgpack.packb(
                {
                    "track_sid": room.col_to_sid.get(pkt.track, ""),
                    "sn": pkt.sn,
                    "ts": pkt.ts,
                    "pid": pkt.pid,
                    "tl0": pkt.tl0,
                    "keyidx": pkt.keyidx,
                    "payload": pkt.payload,
                }
            )
            try:
                q.put_nowait(data)
            except asyncio.QueueFull:
                pass  # slow subscriber: drop (pacer/leaky-bucket analog)

        participant.on_media(media_out)

    # -- cross-node room migration (participant.go:823 analog) ------------
    async def handoff_room(self, name: str, target_node_id: str = "") -> bool:
        """Publish a room's media-plane row to the bus and unpin (or repin)
        it, so another node's get_or_create_room resumes mid-stream with
        intact munger/VP8 offsets — migrated subscribers see contiguous
        SN/TS instead of a stream reset. (The host-side NACK replay ring
        does not travel; post-migration NACKs miss until it repopulates.)"""
        room = self.rooms.get(name)
        bus = getattr(self.router, "bus", None)
        if room is None or bus is None:
            return False
        # Quiesce the row first: packets (or probe padding) forwarded after
        # the snapshot would advance munger SN lanes past what the
        # destination restores, and those SNs would be re-issued there.
        self.runtime.ingest.frozen_rows.add(room.slots.row)
        try:
            async with self.runtime.state_lock:  # vs. the donated device step
                snap = self.runtime.snapshot_room(room.slots.row)
            # Durability gate: the snapshot must be on the bus and the
            # pin moved BEFORE any local teardown. A bus failure here
            # leaves the room fully serving on this node — never pop a
            # room whose state only exists in a packet that didn't land.
            try:
                await self._fenced_set(
                    name,
                    f"room_snapshot:{name}",
                    self.runtime.encode_room_snapshot(snap),
                    self.config.migration.snapshot_ttl_s,
                )
                if target_node_id:
                    await self.router.set_node_for_room(name, target_node_id)
                else:
                    await self.router.clear_room_state(name)
            except FencedWriteRejected:
                # Ownership already moved to a higher epoch — the
                # fence's on_lost callback closed the local replica;
                # there is nothing left here to hand off.
                return False
            except (ConnectionError, OSError) as e:
                self.log.warn(
                    "handoff aborted; room keeps serving here",
                    room=name, error=str(e),
                )
                return False
            # Local teardown only — the pin/store state now belongs to the
            # destination node (clients reconnect there, reason MIGRATION).
            self.rooms.pop(name, None)
            self._row_to_room.pop(room.slots.row, None)
            room.close(pm.DisconnectReason.MIGRATION)
            self.log.info("room handed off", room=name, target=target_node_id or "unpinned")
        finally:
            # On success room.close released the row (its next tenant
            # starts unfrozen); on an aborted handoff this resumes it.
            self.runtime.ingest.frozen_rows.discard(room.slots.row)
        self._update_node_stats()
        return True

    async def migrate_room(self, name: str, target_node_id: str = "") -> bool:
        """Supervised two-phase handoff (service/migration.py): the room
        moves only after the target ACKs a restored replica, freeze-window
        packets are bridged across, and any failure rolls back to serving
        here. Falls back to the fire-and-forget bus handoff when the
        migration plane is disabled."""
        if self.migration is not None:
            return await self.migration.migrate_room(name, target_node_id)
        return await self.handoff_room(name, target_node_id)

    def _on_room_adopted(self, room: Room) -> None:
        """Post-adoption resync (the NACK blind-window satellite): the
        host-side NACK replay ring does not travel in a snapshot, so
        lost-packet recovery is blind until each video track's ring
        repopulates. Shrink that window by soliciting an immediate
        keyframe per migrated video track — a keyframe resets decode
        state without needing history — and re-solicit when a publisher
        reconnects and republishes."""
        row = room.slots.row
        meta = self.runtime.meta
        cols = np.nonzero(meta.published[row] & meta.is_video[row])[0]
        pending: set[int] = set()
        for col in cols:
            room.handle_keyframe_request(int(col))
            pending.add(int(col))

        def _resync(pub, track) -> None:
            col = getattr(track, "track_col", None)
            if col is None or col not in pending:
                return
            pending.discard(col)
            # The adoption-time request above recorded _last_pli for this
            # col even when no publisher was mapped yet; clear it so this
            # republish-time request isn't throttled away.
            room._last_pli.pop(col, None)
            room.handle_keyframe_request(col)

        if pending:
            room.on_track_published.append(_resync)
        for cb in list(self.on_adopt):
            cb(room)

    async def _maybe_restore_room(self, room: Room) -> None:
        """Adopt a migrated room's device state if a snapshot is waiting on
        the bus (the receiving half of handoff_room), falling back to the
        failover checkpoint GENERATIONS (the receiving half of
        checkpoint_rooms) when no deliberate handoff is in flight.

        Every candidate is checksum-verified (decode_room_snapshot) and
        shape/dtype-validated (restore_room) before anything scatters
        into device state; a corrupt or mismatched payload falls back a
        generation (counter + warn) instead of raising out of room
        creation. With no usable candidate the room starts fresh — a
        stream reset, not an outage."""
        bus = getattr(self.router, "bus", None)
        if bus is None:
            return
        candidates = [f"room_snapshot:{room.name}"] + self._checkpoint_keys(room.name)
        for key in candidates:
            raw = await bus.get(key)
            if not raw:
                continue
            try:
                snap = self.runtime.decode_room_snapshot(raw)
                async with self.runtime.state_lock:  # vs. the donated device step
                    self.runtime.restore_room(room.slots.row, snap)
            except Exception as e:  # noqa: BLE001 — corruption, version or
                # dims drift; reject-and-log, then try an older generation.
                self.ckpt_fallbacks += 1
                self.log.warn(
                    "room snapshot rejected; falling back a generation",
                    room=room.name, key=key, error=str(e),
                )
                await bus.delete(key)
                continue
            self.log.info("room restored from snapshot", room=room.name, key=key)
            await bus.delete(key)
            # Same blind window as a two-phase adoption: solicit keyframes
            # so video recovers before the NACK ring repopulates.
            self._on_room_adopted(room)
            return

    # -- supervision & failover (tentpole of the supervised media plane) --
    async def checkpoint_rooms(self, force_fenced: bool = False) -> None:
        """Publish every live room's row snapshot to the KV bus — the seed
        a surviving node restores from if this node dies. Runs on the
        PlaneSupervisor's checkpoint cadence.

        A self-fenced node freezes this entirely (a survivor may hold
        newer state; our write would clobber it) — except the recovery
        reconcile, which calls with ``force_fenced=True`` exactly BECAUSE
        each write CAS-asserts ownership: every room a survivor took
        raises FencedWriteRejected, closing the local replica, and every
        still-owned room gets a fresh checkpoint."""
        bus = getattr(self.router, "bus", None)
        if bus is None:
            return
        if self.fleet is not None and self.fleet.fenced and not force_fenced:
            return
        async with self._ckpt_lock:
            for name, room in list(self.rooms.items()):
                row = room.slots.row
                if row in self.runtime.ingest.frozen_rows:
                    continue  # mid-handoff: handoff_room owns this row's snapshot
                async with self.runtime.state_lock:  # vs. the donated device step
                    snap = self.runtime.snapshot_room(row)
                payload = self.runtime.encode_room_snapshot(snap)
                if self.fault is not None:
                    # corrupt_ckpt seam: damage lands on the encoded frame,
                    # exactly where real bus/storage bit rot would.
                    payload = self.fault.corrupt_ckpt(payload)
                # Rotate the generation ring: newest at the base key, the
                # previous K-1 payloads at :g1..:gK-1 so a corrupt newest
                # frame falls back instead of orphaning the room.
                hist = self._ckpt_history.setdefault(name, [])
                hist.insert(0, payload)
                del hist[self._ckpt_gens:]
                try:
                    for key, gen_payload in zip(self._checkpoint_keys(name), hist):
                        await self._fenced_set(name, key, gen_payload, CHECKPOINT_TTL_S)
                except FencedWriteRejected:
                    continue  # room lost: on_lost closed the replica

    async def _failover_worker(self) -> None:
        """Scan for rooms pinned to dead nodes (lapsed liveness lease,
        routing/router.py dead_room_pins) and adopt the ones we win the
        takeover race for, restoring their media-plane rows from the dead
        node's last checkpoint. Replaces the reference's join-triggered
        takeover with a proactive one: rooms re-home within
        ~lease_ttl + failover_interval even with no client knocking."""
        interval = self.config.kv.failover_interval_s
        while True:
            await asyncio.sleep(interval)
            if self.fleet is not None:
                # Elected restore path (exactly one winner per room via
                # create-lock + epoch CAS); a fenced node sits scans out
                # — it must not restore rooms it may be about to lose.
                if not self.fleet.fenced:
                    try:
                        await self.fleet.orchestrator.run_once()
                    except asyncio.CancelledError:
                        raise
                    except Exception as e:  # noqa: BLE001 — scan must
                        # not kill the loop; next interval retries.
                        self.log.warn("failover scan failed", error=str(e))
                continue
            try:
                dead = await self.router.dead_room_pins()
            except (ConnectionError, OSError):
                continue  # bus outage: retry next interval
            adopted_any = False
            for name, dead_node in dead:
                try:
                    winner = await self.router.try_takeover(name, dead_node)
                    if winner != self.router.local_node.node_id:
                        continue  # another survivor won; it restores the room
                    await self.get_or_create_room(name)
                except (ConnectionError, OSError):
                    continue
                except CapacityError:
                    # No free row here: release the pin so a survivor with
                    # headroom can win the next scan's race.
                    await self.router.clear_room_state(name)
                    continue
                adopted_any = True
                self.log.info("room failed over", room=name, dead_node=dead_node[:12])
                if self.telemetry is not None:
                    self.telemetry.add("livekit_room_failovers_total")
            if dead and hasattr(self.router, "remove_dead_nodes"):
                try:
                    await self.router.remove_dead_nodes()
                except (ConnectionError, OSError):
                    pass
            if adopted_any:
                self._update_node_stats()

    def handle_pli(self, row: int, track_col: int) -> None:
        """RTCP PLI from a UDP subscriber → keyframe request toward the
        publisher over the signal plane (receiver.go SendPLI)."""
        room = self._row_to_room.get(row)
        if room is not None:
            room.handle_keyframe_request(track_col)

    # -- tick fan-out -----------------------------------------------------
    def _dispatch_tick(self, res: TickResult) -> None:
        if self.fleet is not None and self.fleet.fenced:
            # Self-fenced: drop this tick's egress wholesale (UDP batch,
            # WS packets, padding, speaker/keyframe fan-out). The
            # majority side may already be serving these rooms —
            # double-forwarding is exactly the split-brain failure the
            # fleet plane exists to prevent.
            self.fleet.stats["muted_ticks"] += 1
            return
        if self.udp is not None:
            # Batch wire path: one native call assembles/seals/sends every
            # UDP-destined entry; only WS-destined entries materialize as
            # Python packet objects.
            handled = self.udp.send_egress_batch(
                res.egress_batch,
                red_plan=(res.red_sn, res.red_off, res.red_ok),
                layer_caps=(
                    self.runtime.ctrl.max_spatial, self.runtime.ctrl.max_temporal
                ),
                pacer_allowed=res.pacer_allowed,
            )
            if res.padding:
                # BWE probe padding (UDP subscribers only — padding is a
                # channel measurement, meaningless over the WS loopback).
                self.udp.send_egress(res.padding, rtx=True)
            ws_pkts = res.egress_batch.to_packets(~handled) if len(handled) else []
        else:
            ws_pkts = res.egress
        ws_tx = self.runtime.ingest.ws_tx
        for pkt in ws_pkts:
            room = self._row_to_room.get(pkt.room)
            if room is not None:
                room.deliver_egress(pkt)
                # WS-media egress accounting (same wire-byte basis as the
                # UDP counters).
                ws_tx[pkt.room, pkt.sub, 0] += 1
                ws_tx[pkt.room, pkt.sub, 1] += (
                    len(pkt.payload) + WIRE_OVERHEAD_BYTES
                )
        for row, speakers in res.speakers.items():
            room = self._row_to_room.get(row)
            if room is not None:
                room.handle_speakers(speakers)
        seen = set()
        for row, track_col, _sub in res.need_keyframe:
            if (row, track_col) in seen:
                continue  # PLI throttle: one per track per tick
            seen.add((row, track_col))
            room = self._row_to_room.get(row)
            if room is not None:
                room.handle_keyframe_request(track_col)
        if res.quality_window_closed and res.track_quality is not None:
            # ~1/s: connection-quality fan-out + dynacast reconciliation
            # (room.go:1318 connectionQualityWorker; dynacastmanager.go).
            for row, room in self._row_to_room.items():
                room.handle_quality(
                    res.track_quality[row], res.track_mos[row], res.sub_quality[row]
                )
                room.reconcile_dynacast()
                if res.target_layers is not None:
                    room.update_stream_states(res.target_layers[row])
            if self.telemetry is not None:
                # Windowed device reductions → quality histograms + one
                # analytics record per published track (statsworker.go).
                pub = self.runtime.meta.published
                if pub.any():
                    self.telemetry.observe_tracks(
                        res.track_loss_pct[pub],
                        res.track_jitter_ms[pub],
                        res.track_bps[pub],
                    )
                for row, room in self._row_to_room.items():
                    for col, sid in room.col_to_sid.items():
                        if not pub[row, col]:
                            continue
                        self.telemetry.track_stat(
                            room=room.name, track=sid,
                            kind="video" if self.runtime.meta.is_video[row, col] else "audio",
                            loss_pct=round(float(res.track_loss_pct[row, col]), 3),
                            jitter_ms=round(float(res.track_jitter_ms[row, col]), 3),
                            bps=round(float(res.track_bps[row, col]), 1),
                            mos=round(float(res.track_mos[row, col]), 2),
                            quality=int(res.track_quality[row, col]),
                        )
        if self.telemetry is not None:
            self.telemetry.observe_plane(self.runtime.stats)
            self.telemetry.observe_tick_latency(res.tick_s)
            if self.udp is not None:
                self.telemetry.observe_transport(self.udp.stats)
            if self.governor is not None:
                self.telemetry.observe_overload({
                    **self.governor.stats_dict(),
                    "denied_reasons": dict(self.admission_denied_reasons),
                })
            if self.integrity is not None:
                self.telemetry.observe_integrity(self.integrity_stats())
            self.telemetry.observe_egress(self.runtime.egress_plane.observe())
            pager_stats = getattr(self.runtime, "pager_stats", None)
            if pager_stats is not None:
                self.telemetry.observe_pager(pager_stats())
            if self.runtime.wire_stages is not None:
                # Per-stage wire-latency samples since the last tick →
                # stage histograms + livekit_forward_latency_ms.
                self.telemetry.observe_wire_stages(
                    self.runtime.wire_stages.drain()
                )

    def integrity_stats(self) -> dict:
        """IntegrityMonitor stats + the checkpoint-generation fallback
        counters spread across the supervisor (full-plane ring) and this
        manager (KV room checkpoints) — the /debug/integrity payload."""
        snap = self.integrity.stats_dict() if self.integrity is not None else {}
        fallbacks = self.ckpt_fallbacks
        if self.supervisor is not None:
            fallbacks += self.supervisor.ckpt_fallbacks
            snap["restart_causes"] = dict(self.supervisor.restart_causes)
        snap["generation_fallbacks"] = fallbacks
        return snap

    # -- periodic reaping (server.go backgroundWorker) --------------------
    def start(self) -> None:
        self.runtime.start()
        if self.supervisor is not None:
            self.supervisor.start()
        if self._reaper_task is None:
            self._reaper_task = asyncio.ensure_future(self._reaper())
        # Failover scan only makes sense with a shared bus to observe
        # other nodes' leases (and to read their checkpoints from).
        if self._failover_task is None and getattr(self.router, "bus", None) is not None:
            self._failover_task = asyncio.ensure_future(self._failover_worker())
        if self.migration is not None:
            self.migration.start()
        if self.fleet is not None:
            self.fleet.start()

    async def _reaper(self) -> None:
        while True:
            await asyncio.sleep(1.0)
            for name in [n for n, r in self.rooms.items() if r.should_close()]:
                await self.delete_room(name)
            # Publication watchdog (participant_supervisor.go monitor loop):
            # announced tracks whose media never arrived get reaped and the
            # client notified.
            for room in list(self.rooms.values()):
                for p in list(room.participants.values()):
                    p.reap_stale_publications()

    async def stop(self) -> None:
        if self.fleet is not None:
            await self.fleet.stop()
        if self.migration is not None:
            await self.migration.stop()
        if self.supervisor is not None:
            await self.supervisor.stop()
        for attr in ("_reaper_task", "_failover_task"):
            task = getattr(self, attr)
            if task is not None:
                task.cancel()
                setattr(self, attr, None)
        await self.runtime.stop()
        for name in list(self.rooms):
            await self.delete_room(name)

    # -- helpers ----------------------------------------------------------
    def _update_node_stats(self) -> None:
        st = self.router.local_node.stats
        st.num_rooms = len(self.rooms)
        st.num_clients = sum(len(r.participants) for r in self.rooms.values())
        st.num_tracks_in = sum(len(r.tracks) for r in self.rooms.values())
        st.num_tracks_out = sum(
            len(p.subscribed_tracks)
            for r in self.rooms.values()
            for p in r.participants.values()
        )
        st.plane_rooms_used = self.runtime.slots.rooms_used
        st.plane_rooms_capacity = self.runtime.slots.capacity
        occ = self.runtime.occupancy()
        st.plane_pages_used = occ.get("pages_used", 0)
        st.plane_pages_capacity = occ.get("pages_total", 0)

    def sample_traffic(self) -> None:
        """Window deltas of the cumulative rx/tx counters → node packet/
        byte rates (participant_traffic_load.go:38-150 seat: per-
        participant rates feed NodeStats and thereby node selection).
        Called from the server's 2 s stats loop; per-slot rate arrays are
        retained for /debug/rooms' per-participant view."""
        import time as _time

        now = _time.monotonic()
        ing = self.runtime.ingest
        prev = getattr(self, "_traffic_prev", None)
        rx_p = ing.rx_pkts.copy()
        # Wire-byte basis on BOTH directions (payload + fixed per-packet
        # overhead), so bytes_in/bytes_out are comparable.
        rx_b = ing.rx_bytes + ing.rx_pkts * WIRE_OVERHEAD_BYTES
        tx_p = ing.ws_tx[:, :, 0].copy()
        tx_b = ing.ws_tx[:, :, 1].copy()
        if self.udp is not None:
            tx_p += self.udp.tx_pkts
            tx_b += self.udp.tx_bytes
        self._traffic_prev = (now, rx_p, rx_b, tx_p, tx_b)
        if prev is None:
            return
        t0, prx_p, prx_b, ptx_p, ptx_b = prev
        dt = max(now - t0, 1e-3)
        # Clamp: slot release resets counters mid-window.
        self.rx_pps = np.maximum(rx_p - prx_p, 0) / dt      # [R, T]
        self.rx_bps = np.maximum(rx_b - prx_b, 0) * 8 / dt
        self.tx_pps = np.maximum(tx_p - ptx_p, 0) / dt      # [R, S]
        self.tx_bps = np.maximum(tx_b - ptx_b, 0) * 8 / dt
        st = self.router.local_node.stats
        st.packets_in_per_sec = float(self.rx_pps.sum())
        st.bytes_in_per_sec = float(self.rx_bps.sum()) / 8
        st.packets_out_per_sec = float(self.tx_pps.sum())
        st.bytes_out_per_sec = float(self.tx_bps.sum()) / 8

    def participant_traffic(self, room: "Room") -> dict:
        """Per-participant rates from the last sample window: egress from
        the participant's subscriber slot, ingress summed over the tracks
        it publishes."""
        out = {}
        rx_pps = getattr(self, "rx_pps", None)
        row = room.slots.row
        for ident, p in room.participants.items():
            ent = {"tx_pps": 0.0, "tx_bps": 0.0, "rx_pps": 0.0, "rx_bps": 0.0}
            if getattr(self, "tx_pps", None) is not None and p.sub_col >= 0:
                ent["tx_pps"] = round(float(self.tx_pps[row, p.sub_col]), 1)
                ent["tx_bps"] = round(float(self.tx_bps[row, p.sub_col]), 1)
            if rx_pps is not None:
                cols = [
                    t.track_col for pub, t in room.tracks.values()
                    if pub.sid == p.sid
                ]
                if cols:
                    ent["rx_pps"] = round(float(rx_pps[row, cols].sum()), 1)
                    ent["rx_bps"] = round(float(self.rx_bps[row, cols].sum()), 1)
            out[ident] = ent
        return out

    def _notify(self, event: str, **payload) -> None:
        if self.telemetry is not None:
            self.telemetry.notify(event, **payload)
