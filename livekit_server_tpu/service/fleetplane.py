"""Fleet coordination plane: self-fencing, elected failover, rebalancing.

The mechanism half lives in routing/fleet.py (RoomFence epoch CAS,
LeaseGuard transitions); this module wires it to node-level effects:

  FleetPlane            maps LeaseGuard's fence/recover onto the media
                        plane — mute room egress (_dispatch_tick early
                        return), freeze checkpoint writes, quiesce the
                        supervisor's restart-from-KV path, and deny
                        admissions — and closes local replicas the
                        moment their epoch is lost to a survivor.
  FailoverOrchestrator  turns KVRouter.dead_room_pins() into exactly-one
                        -winner recovery: a create-lock (setnx) plus the
                        epoch CAS elect the restorer; everyone else backs
                        off cleanly. Fixes the PR 1 race where two
                        survivors could both restore the same room.
  Rebalancer            drains hot nodes through the PR 6 two-phase
                        migration: when this node's plane load sits
                        above the fleet mean by more than the configured
                        headroom, the busiest rooms move to
                        selector-picked peers (bounded moves per scan).

Recovery order matters: a healed node reconciles BEFORE unmuting. The
forced guarded checkpoint pass CAS-asserts every held room's epoch while
egress is still muted, so each room a survivor took is discovered (and
its replica closed) before this node could double-forward a single
packet for it.
"""

from __future__ import annotations

import asyncio

from livekit_server_tpu.protocol import models as pm
from livekit_server_tpu.routing.fleet import LeaseGuard, RoomFence
from livekit_server_tpu.routing.router import NODE_ROOM_KEY
from livekit_server_tpu.runtime import CapacityError

RESTORE_LOCK_PREFIX = "fleet_restore:"


class FleetPlane:
    """Per-node fencing state machine, fed by the router's lease worker."""

    def __init__(self, manager):
        self.mgr = manager
        self.router = manager.router
        self.cfg = manager.config.fleet
        self.log = manager.log
        self.fence = RoomFence(
            self.router.bus, self.router.local_node.node_id, log=manager.log
        )
        self.guard = LeaseGuard(self.cfg.fence_grace_s)
        # The router runs the lease loop and the fenced pin moves; it
        # observes through us, we fence through it.
        self.router.fence = self.fence
        self.router.on_lease = self._lease_observed
        self.fence.on_lost.append(self._room_lost)
        self.orchestrator = FailoverOrchestrator(manager, self.fence)
        self.rebalancer = Rebalancer(manager, self)
        self._rebalance_task: asyncio.Task | None = None
        self.stats = {
            "fences": 0, "recoveries": 0, "rooms_lost": 0, "muted_ticks": 0,
        }

    @property
    def fenced(self) -> bool:
        return self.guard.fenced

    def start(self) -> None:
        if self.rebalancer.enabled and self._rebalance_task is None:
            self._rebalance_task = asyncio.ensure_future(self.rebalancer.run())

    async def stop(self) -> None:
        if self._rebalance_task is not None:
            self._rebalance_task.cancel()
            self._rebalance_task = None

    # -- lease transitions ------------------------------------------------
    async def _lease_observed(self, ok: bool) -> None:
        action = self.guard.observe(ok)
        if action == "fence":
            self._enter_fence()
        elif action == "recover":
            await self._reconcile_and_unfence()

    def _enter_fence(self) -> None:
        """Quorum lost: go silent BEFORE any survivor's takeover can
        double-forward — egress mute and admission denial key off
        guard.fenced; the supervisor flag stops restart-from-KV."""
        self.stats["fences"] += 1
        if self.mgr.supervisor is not None:
            self.mgr.supervisor.fenced = True
        self.log.warn(
            "node self-fenced: lease unrefreshed past fence_grace",
            lease_age_s=round(self.guard.age(), 2),
            fence_grace_s=self.guard.fence_grace_s,
        )
        if self.mgr.telemetry is not None:
            self.mgr.telemetry.add("livekit_fleet_fences_total")

    async def _reconcile_and_unfence(self) -> None:
        """The lease refreshes again. Reconcile while STILL fenced: the
        forced guarded checkpoint pass CAS-asserts every held room's
        epoch, so each room a survivor took over fires _room_lost (and
        closes here) before a single muted packet could resume."""
        try:
            await self.mgr.checkpoint_rooms(force_fenced=True)
        except (ConnectionError, OSError):
            return   # bus flapped again: stay fenced, retry on next OK
        self.guard.unfence()
        if self.mgr.supervisor is not None:
            self.mgr.supervisor.fenced = False
        self.stats["recoveries"] += 1
        self.log.info(
            "node unfenced: lease restored, ownership reconciled",
            rooms=len(self.mgr.rooms),
        )
        if self.mgr.telemetry is not None:
            self.mgr.telemetry.add("livekit_fleet_recoveries_total")

    # -- ownership loss ---------------------------------------------------
    def _room_lost(self, name: str) -> None:
        """A guarded write lost its epoch CAS: a survivor owns the room
        now. Tear down the local replica only — the KV pin, store row and
        checkpoints belong to the new owner; clients reconnect and route
        there."""
        self.stats["rooms_lost"] += 1
        room = self.mgr.rooms.pop(name, None)
        if room is None:
            return
        self.mgr._row_to_room.pop(room.slots.row, None)
        self.mgr._ckpt_history.pop(name, None)
        from livekit_server_tpu.runtime.trace import EV_ROOM_CLOSE

        self.mgr.runtime.blackbox.emit(room.slots.row, EV_ROOM_CLOSE)
        room.close(pm.DisconnectReason.MIGRATION)
        self.log.warn("room lost to higher epoch; local replica closed",
                      room=name)
        self.mgr._update_node_stats()

    def snapshot(self) -> dict:
        """/debug/fleet payload."""
        return {
            "fenced": self.guard.fenced,
            "lease_age_s": round(self.guard.age(), 3),
            "fence_grace_s": self.guard.fence_grace_s,
            "owned_rooms": self.fence.owned_rooms(),
            "fence": dict(self.fence.stats),
            "plane": dict(self.stats),
            "failover": dict(self.orchestrator.stats),
            "rebalance": dict(self.rebalancer.stats),
        }


class FailoverOrchestrator:
    """Exactly-one-winner restoration of rooms pinned to dead nodes.

    Two independent mechanisms make the election safe even when the
    create-lock's TTL lapses mid-restore: the setnx lock keeps the
    common case cheap (losers never touch the checkpoint), and the
    epoch CAS inside fence.claim is the actual correctness boundary —
    two nodes holding the "lock" across a TTL lapse still resolve to
    one owner, because only one CAS can move the epoch record.
    """

    def __init__(self, manager, fence: RoomFence):
        self.mgr = manager
        self.router = manager.router
        self.fence = fence
        self.cfg = manager.config.fleet
        self.log = manager.log
        self.stats = {
            "restored": 0, "lock_losses": 0, "claim_losses": 0,
            "capacity_released": 0,
        }

    async def run_once(self) -> int:
        """One dead-pin scan; returns the number of rooms restored here."""
        bus = self.router.bus
        me = self.router.local_node.node_id
        try:
            dead = await self.router.dead_room_pins()
        except (ConnectionError, OSError):
            return 0
        restored = 0
        for name, dead_node in dead:
            lock = RESTORE_LOCK_PREFIX + name
            won = False
            try:
                if not await bus.setnx(lock, me, self.cfg.restore_lock_ttl_s):
                    self.stats["lock_losses"] += 1
                    continue   # another survivor is restoring this room
                try:
                    # Re-check under the lock: a scan that started before
                    # another survivor's restore finished still holds the
                    # stale dead-pin — claiming now would steal the room
                    # straight back off the fresh winner.
                    if await bus.hget(NODE_ROOM_KEY, name) != dead_node:
                        continue
                    if not await self.fence.claim(name):
                        self.stats["claim_losses"] += 1
                        continue   # raced a restorer across a lock lapse
                    try:
                        # 'restore' admission: the fleet already admitted
                        # this room — a survivor at L4 must still adopt it
                        # (hard gates only; see governor.should_admit).
                        await self.mgr.get_or_create_room(
                            name, admission_kind="restore"
                        )
                        won = True
                    except CapacityError:
                        # Claimed but cannot host. Keep the bumped epoch
                        # (it fences the dark owner out) and clear only
                        # the pin, so a survivor with headroom can claim
                        # e+1 and restore on its next scan.
                        await bus.hdel(NODE_ROOM_KEY, name)
                        self.fence.forget(name)
                        self.stats["capacity_released"] += 1
                        continue
                finally:
                    # A winner KEEPS the lock until its TTL lapses: it is
                    # the barrier that parks in-flight scans on other
                    # survivors until the new pin is visible to them.
                    # Every losing path frees it for the next scan.
                    if not won:
                        await bus.delete(lock)
            except (ConnectionError, OSError):
                continue   # bus outage mid-restore: retry next scan
            restored += 1
            self.stats["restored"] += 1
            self.log.info("room failed over", room=name,
                          dead_node=dead_node[:12])
            if self.mgr.telemetry is not None:
                self.mgr.telemetry.add("livekit_room_failovers_total")
        if dead and hasattr(self.router, "remove_dead_nodes"):
            try:
                await self.router.remove_dead_nodes()
            except (ConnectionError, OSError):
                pass
        if restored:
            self.mgr._update_node_stats()
        return restored


class Rebalancer:
    """Load-aware drain of hot nodes via live migration (default-off).

    Plane-room occupancy is the load signal — a TPU node saturates its
    room tensor long before its CPUs (same reasoning as the selector's
    capacity gate). Moves are bounded per scan and go through the
    two-phase MigrationOrchestrator, so every move carries the same
    continuity guarantee as an operator-driven drain.
    """

    def __init__(self, manager, plane: FleetPlane):
        self.mgr = manager
        self.plane = plane
        cfg = manager.config.fleet
        self.enabled = cfg.rebalance_enabled
        self.interval_s = cfg.rebalance_interval_s
        self.headroom = cfg.rebalance_headroom
        self.max_moves = cfg.rebalance_max_moves
        self.log = manager.log
        self.stats = {"scans": 0, "moves": 0, "move_failures": 0}

    @staticmethod
    def _load(node) -> float:
        st = node.stats
        if st.plane_rooms_capacity:
            return st.plane_rooms_used / st.plane_rooms_capacity
        return float(st.num_rooms)

    async def run(self) -> None:
        while True:
            await asyncio.sleep(self.interval_s)
            try:
                await self.run_once()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — a scan must not kill
                # the loop; the next interval retries from fresh state.
                self.log.warn("rebalance scan failed", error=str(e))

    async def run_once(self) -> int:
        """One scan; returns the number of rooms moved off this node."""
        mgr = self.mgr
        if (
            mgr.migration is None
            or mgr.migration.draining
            or self.plane.fenced
            or not mgr.rooms
        ):
            return 0
        self.stats["scans"] += 1
        try:
            nodes = await self.router_nodes()
        except (ConnectionError, OSError):
            return 0
        if len(nodes) < 2:
            return 0
        me = mgr.router.local_node.node_id
        mine = next((n for n in nodes if n.node_id == me), None)
        if mine is None:
            return 0
        my_load = self._load(mine)
        mean = sum(self._load(n) for n in nodes) / len(nodes)
        if my_load <= mean * (1.0 + self.headroom):
            return 0
        if any(self._load(n) > my_load for n in nodes if n.node_id != me):
            return 0   # a hotter node exists; let it shed first
        # Shed the emptiest rooms first: each move frees a full plane row
        # while disrupting the fewest participants.
        names = sorted(
            mgr.rooms, key=lambda n: len(mgr.rooms[n].participants)
        )[: self.max_moves]
        moved = 0
        for name in names:
            if await mgr.migration.migrate_room(name):
                moved += 1
                self.stats["moves"] += 1
                self.log.info("rebalanced room off hot node", room=name,
                              load=round(my_load, 3), fleet_mean=round(mean, 3))
            else:
                self.stats["move_failures"] += 1
        return moved

    async def router_nodes(self):
        from livekit_server_tpu.routing.node import NodeState

        nodes = await self.mgr.router.list_nodes()
        return [
            n for n in nodes
            if n.state != NodeState.SHUTTING_DOWN and n.is_available()
        ]
