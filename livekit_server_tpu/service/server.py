"""Server assembly + lifecycle.

Reference parity: pkg/service/server.go (LivekitServer :46-61, Start
:170-293, Stop :295-316, health :351-364) and the Wire DI graph
(wire_gen.go:38-138) — here plain constructor wiring in create_server().
Endpoints: /rtc (WS signal+media), /twirp/livekit.RoomService/* (admin),
/ (health), /metrics (prometheus text format), /debug/rooms.
"""

from __future__ import annotations

import asyncio
import secrets
import time

from aiohttp import web

from livekit_server_tpu.config.config import Config, ConfigError
from livekit_server_tpu.routing import (
    LocalNode,
    MemoryBus,
    NodeState,
    create_router,
    create_selector,
)
from livekit_server_tpu.routing.node import sample_system_stats
from livekit_server_tpu.routing.selector import NoNodesAvailable
from livekit_server_tpu.service.roommanager import RoomManager
from livekit_server_tpu.service.roomservice import RoomServiceAPI
from livekit_server_tpu.service.rtcservice import RTCService
from livekit_server_tpu.service.store import KVStore, LocalStore
from livekit_server_tpu.telemetry import TelemetryService
from livekit_server_tpu.version import __version__


class LivekitServer:
    def __init__(self, config: Config, router, store, room_manager, telemetry):
        self.config = config
        self.router = router
        self.store = store
        self.room_manager: RoomManager = room_manager
        self.telemetry: TelemetryService = telemetry
        from livekit_server_tpu.service.agents import AgentService
        from livekit_server_tpu.service.egress import EgressService
        from livekit_server_tpu.service.ingress import IngressService
        from livekit_server_tpu.service.sip import SIPService

        self.rtc_service = RTCService(self)
        self.room_api = RoomServiceAPI(self)
        self.egress = EgressService(self)
        self.ingress = IngressService(self)
        self.sip = SIPService(self)
        from livekit_server_tpu.service.ioinfo import IOInfoService

        self.ioinfo = IOInfoService(self)
        self.agents = AgentService(self)
        room_manager.agents = self.agents
        from livekit_server_tpu.utils.logger import Logger, configure

        configure(config.log_level)
        self.log = Logger(node=router.local_node.node_id[:12])
        room_manager.log = self.log
        self.app = web.Application(middlewares=[self._request_hooks])
        self.app.router.add_get("/", self.health)
        self.app.router.add_get("/rtc", self.rtc_service.handle)
        self.app.router.add_get("/rtc/validate", self.validate)
        self.app.router.add_get("/agent", self.agents.handle)
        self.app.router.add_post(
            "/twirp/livekit.RoomService/{method}", self.room_api.handle
        )
        self.app.router.add_post("/twirp/livekit.Egress/{method}", self.egress.handle)
        self.app.router.add_post("/twirp/livekit.Ingress/{method}", self.ingress.handle)
        self.app.router.add_post("/twirp/livekit.SIP/{method}", self.sip.handle)
        self.app.router.add_get("/metrics", self.metrics)
        self.app.router.add_get("/debug/rooms", self.debug_rooms)
        self.app.router.add_get("/debug/analytics", self.debug_analytics)
        self.app.router.add_get("/debug/tasks", self.debug_tasks)
        self.app.router.add_get("/debug/ticks", self.debug_ticks)
        self.app.router.add_get("/debug/overload", self.debug_overload)
        self.app.router.add_get("/debug/pager", self.debug_pager)
        self.app.router.add_get("/debug/integrity", self.debug_integrity)
        self.app.router.add_get("/debug/compiles", self.debug_compiles)
        self.app.router.add_get("/debug/egress", self.debug_egress)
        self.app.router.add_get("/debug/migration", self.debug_migration)
        self.app.router.add_get("/debug/fleet", self.debug_fleet)
        self.app.router.add_get("/debug/trace", self.debug_trace)
        self.app.router.add_get("/debug/blackbox/{room}", self.debug_blackbox)
        self._runner: web.AppRunner | None = None
        self._sites: list[web.TCPSite] = []
        self._stats_task: asyncio.Task | None = None
        self.started_at = 0.0

    # -- selector ---------------------------------------------------------
    def select_node(self) -> LocalNode | None:
        """Pick an RTC node for a new room (roomallocator.go)."""
        nodes = getattr(self, "_node_cache", None) or [self.router.local_node]
        try:
            return self._selector.select_node(nodes)
        except NoNodesAvailable:
            return None

    async def _refresh_nodes(self) -> None:
        while True:
            self._node_cache = await self.router.list_nodes()
            sample_system_stats(self.router.local_node.stats)
            # Per-participant traffic rates → NodeStats packet/byte rates
            # (participant_traffic_load.go cadence).
            self.room_manager.sample_traffic()
            await asyncio.sleep(2.0)

    def room_manager_media_queue(self, room_name: str, identity: str):
        room = self.room_manager.rooms.get(room_name)
        if room is None:
            return None
        p = room.participants.get(identity)
        return getattr(p, "media_queue", None) if p else None

    # -- endpoints --------------------------------------------------------
    async def health(self, request: web.Request) -> web.Response:
        # server.go:351 — 406 when node stats are stale
        age = time.time() - self.router.local_node.stats.updated_at
        if age > 4.0 and self.started_at and time.time() - self.started_at > 4.0:
            return web.Response(status=406, text=f"node stats stale ({age:.1f}s)")
        return web.Response(text="OK")

    async def validate(self, request: web.Request) -> web.Response:
        """rtcservice.go validate — join preflight without upgrading."""
        from livekit_server_tpu.auth import TokenError, verify_token

        token = request.query.get("access_token", "")
        try:
            claims = verify_token(token, self.config.keys)
        except TokenError as e:
            return web.Response(status=401, text=str(e))
        if not claims.video.room_join:
            return web.Response(status=401, text="token lacks roomJoin")
        return web.Response(text="success")

    @web.middleware
    async def _request_hooks(self, request: web.Request, handler):
        """Twirp request logging + status metrics (the TwirpLogger /
        request-status hooks of service/server.go's Twirp server options)."""
        t0 = time.perf_counter()
        status = 500
        try:
            resp = await handler(request)
            status = resp.status
            return resp
        except web.HTTPException as e:
            status = e.status
            raise
        except asyncio.CancelledError:
            status = 499  # client went away; not a server error
            raise
        finally:
            if request.path.startswith("/twirp/"):
                svc = request.path.split("/")[2]
                method = request.match_info.get("method", "")
                self.telemetry.add(
                    "livekit_twirp_requests_total",
                    service=svc, method=method, status=str(status),
                )
                self.log.info(
                    "twirp", service=svc, method=method, status=status,
                    dur_ms=round((time.perf_counter() - t0) * 1000.0, 2),
                )

    async def debug_tasks(self, request: web.Request) -> web.Response:
        """Asyncio task dump (the pprof goroutine-profile analog, §5.1)."""
        tasks = []
        for t in asyncio.all_tasks():
            tasks.append({
                "name": t.get_name(),
                "done": t.done(),
                "coro": str(getattr(t.get_coro(), "__qualname__", t.get_coro())),
            })
        return web.json_response({"count": len(tasks), "tasks": tasks})

    async def debug_ticks(self, request: web.Request) -> web.Response:
        """Recent tick timing breakdown (§5.1 profiling surface): totals
        plus the per-tick pipeline-stage split (stage/device/fanout ms,
        depth, late) so an overlap regression is visible per stage rather
        than inferred from host_ms_per_tick."""
        rt = self.room_manager.runtime
        body = {
            "tick_ms": rt.tick_ms,
            "stats": rt.stats,
            "pipeline_depth": 0 if rt.low_latency else 1,
            "recent_tick_s": list(getattr(rt, "recent_tick_s", [])),
            "recent_ticks": list(getattr(rt, "recent_ticks", [])),
        }
        body["sleep_bias_us"] = round(
            max(getattr(rt, "_sleep_bias", 0.0), 0.0) * 1e6, 1
        )
        body["edge_overshoot_us"] = round(
            getattr(rt, "_edge_overshoot_us", 0.0), 1
        )
        if rt.wire_stages is not None:
            # Per-stage wire-latency decomposition (sampled attribution).
            body["wire_stages"] = rt.wire_stages.summary()
        udp = getattr(self.room_manager, "udp", None)
        if udp is not None and getattr(udp, "fwd_latency", None) is not None:
            # Measured wall-clock packet-in→wire-out latency (includes
            # tick-queueing wait) — the probe in runtime/udp.py.
            body["forward_latency"] = udp.fwd_latency.summary()
        if rt.express is not None:
            body["express"] = rt.express.debug()
            if udp is not None:
                # Express twin: arrival-driven, no tick-queue wait.
                body["forward_latency_express"] = (
                    udp.fwd_latency_express.summary()
                )
        return web.json_response(body)

    async def debug_trace(self, request: web.Request) -> web.Response:
        """Chrome/Perfetto trace export of the tick-span ring
        (?ticks=N, newest N ticks) plus the sampled wire-latency stage
        decomposition as a sidecar. Save the body to a file and load it
        in ui.perfetto.dev or chrome://tracing."""
        rt = self.room_manager.runtime
        if rt.trace is None:
            return web.json_response(
                {"error": "tracing disabled (trace.enabled: false)"},
                status=404,
            )
        try:
            n = int(request.query.get("ticks", "120"))
        except ValueError:
            return web.json_response(
                {"error": "ticks must be an integer"}, status=400
            )
        from livekit_server_tpu.telemetry import trace_export

        body: dict = {
            "traceEvents": trace_export.to_chrome(
                rt.trace.snapshot(n), rt.tick_ms
            ),
            "displayTimeUnit": "ms",
        }
        if rt.wire_stages is not None:
            # Perfetto ignores unknown top-level keys; curl consumers get
            # the stage decomposition without a second request.
            body["otherData"] = {"wire_stages": rt.wire_stages.summary()}
        return web.json_response(body)

    async def debug_blackbox(self, request: web.Request) -> web.Response:
        """One room's black-box flight-recorder lane ({room} is a room
        name, a row index, or `node` for the node lane), plus the
        retained automatic dumps."""
        rt = self.room_manager.runtime
        bb = rt.blackbox
        key = request.match_info["room"]
        if key == "node":
            row = bb.NODE
        else:
            room = self.room_manager.rooms.get(key)
            if room is not None:
                row = room.slots.row
            else:
                try:
                    row = int(key)
                except ValueError:
                    return web.json_response(
                        {"error": f"unknown room {key!r}"}, status=404
                    )
                if not 0 <= row < rt.dims.rooms:
                    return web.json_response(
                        {"error": f"row {row} out of range"}, status=404
                    )
        return web.json_response({
            "room": key,
            "row": row,
            "events": bb.dump(row),
            "dumps_total": bb.dumps,
            "last_dumps": list(bb.last_dumps),
        })

    async def metrics(self, request: web.Request) -> web.Response:
        # Recovery-machinery gauges sampled at scrape time: bus transport
        # churn lives on the client object, plane restarts on the
        # supervisor (livekit_plane_restarts_total / _room_failovers_total
        # counters are emitted by their owners via telemetry.add).
        bus = getattr(self.router, "bus", None)
        if bus is not None and hasattr(bus, "retries"):
            self.telemetry.set_gauge("livekit_bus_retries_total", bus.retries)
            self.telemetry.set_gauge("livekit_bus_reconnects_total", bus.reconnects)
        ledger = self.room_manager.runtime.compile_ledger.snapshot()
        self.telemetry.set_gauge(
            "livekit_xla_compiles_total", ledger["xla_compiles_total"]
        )
        self.telemetry.set_gauge(
            "livekit_xla_compiles_post_warmup",
            ledger["xla_compiles_post_warmup"],
        )
        self.telemetry.observe_queue_drops()
        return web.Response(
            text=self.telemetry.prometheus_text(), content_type="text/plain"
        )

    async def debug_overload(self, request: web.Request) -> web.Response:
        """Overload-governor state: ladder level, recent transitions,
        split ingest drop counters, admission rejections, bus/signal
        back-pressure drops, and the active limits."""
        from dataclasses import asdict

        from livekit_server_tpu.routing.kv import Subscription
        from livekit_server_tpu.routing.messagechannel import MessageChannel

        rm = self.room_manager
        gov = rm.governor
        ing = rm.runtime.ingest
        return web.json_response(
            {
                "governor": gov.snapshot() if gov is not None else None,
                "ingest": {
                    "dropped_capacity": ing.dropped_capacity,
                    "dropped_fault": ing.dropped_fault,
                    "dropped_policed": ing.dropped_policed,
                },
                "admission_rejected": dict(rm.admission_rejected),
                "admission_denied_reasons": dict(rm.admission_denied_reasons),
                "queue_drops": {
                    "signal_channel": MessageChannel.total_dropped,
                    "bus_subscription": Subscription.total_dropped,
                },
                "supervisor_restarts": (
                    rm.supervisor.restarts if rm.supervisor is not None else 0
                ),
                "limits": asdict(self.config.limits),
            }
        )

    async def debug_fleet(self, request: web.Request) -> web.Response:
        """Fleet-plane state: fence flag + lease age, owned room epochs,
        and the fencing / failover-election / rebalance counters."""
        fleet = self.room_manager.fleet
        return web.json_response(
            {
                "enabled": fleet is not None,
                "fleet": fleet.snapshot() if fleet is not None else None,
            }
        )

    async def debug_migration(self, request: web.Request) -> web.Response:
        """Migration-plane state: drain flag, in-flight handoffs with
        their epochs, pending adoptions, and the lifetime counters
        (commits, rollbacks, NACKs, bridged packets, stale-epoch drops)."""
        mig = self.room_manager.migration
        return web.json_response(
            {
                "enabled": mig is not None,
                "migration": mig.snapshot() if mig is not None else None,
                "frozen_rows": sorted(self.room_manager.runtime.ingest.frozen_rows),
            }
        )

    async def debug_egress(self, request: web.Request) -> web.Response:
        """Sharded egress plane: host_egress_pps, shard plan, canonical
        grouping rates, per-shard sent/busy totals, and the last tick's
        per-shard send + munge breakdowns."""
        rm = self.room_manager
        snap = rm.runtime.egress_plane.observe()
        if rm.udp is not None:
            snap["tx_total"] = rm.udp.stats.get("tx", 0)
            snap["tx_drop_total"] = rm.udp.stats.get("tx_drop", 0)
        return web.json_response(snap)

    async def debug_pager(self, request: web.Request) -> web.Response:
        """Paged room-state plane: page-pool occupancy/fragmentation,
        allocator churn counters, per-room page extents, and per-resource
        slot occupancy. `paged: false` (with the dense slot occupancy)
        when the plane runs the dense layout."""
        rm = self.room_manager
        rt = rm.runtime
        pager_stats = getattr(rt, "pager_stats", None)
        body: dict = {
            "paged": pager_stats is not None,
            "occupancy": rt.occupancy(),
        }
        if pager_stats is not None:
            body["pool"] = pager_stats()
            pager = rt.pager
            body["rooms"] = {
                room.name: {
                    "row": room.slots.row,
                    "pages": [int(p) for p in pager.pages_of_room(room.slots.row)],
                    "extent": tuple(pager.extent(room.slots.row)),
                }
                for room in rm.rooms.values()
            }
        return web.json_response(body)

    async def debug_integrity(self, request: web.Request) -> web.Response:
        """State-integrity plane: audits run, violations by rule, the
        quarantine/repair ladder's outcomes, checkpoint checksum failures
        + generation fallbacks, and supervisor restart causes."""
        from livekit_server_tpu.utils.checksum import CodecStats

        rm = self.room_manager
        sup = rm.supervisor
        return web.json_response(
            {
                "integrity": rm.integrity_stats() if rm.integrity is not None else None,
                "checksum": {
                    "frames_encoded": CodecStats.frames_encoded,
                    "frames_verified": CodecStats.frames_verified,
                    "verify_failures": CodecStats.verify_failures,
                },
                "restart_causes": (
                    dict(sup.restart_causes) if sup is not None else {}
                ),
                "supervisor_ckpt_fallbacks": (
                    sup.ckpt_fallbacks if sup is not None else 0
                ),
                "room_ckpt_fallbacks": rm.ckpt_fallbacks,
                "config": {
                    "enabled": self.config.integrity.enabled,
                    "audit_every_ticks": self.config.integrity.audit_every_ticks,
                    "max_row_repairs": self.config.integrity.max_row_repairs,
                    "storm_threshold": self.config.integrity.storm_threshold,
                    "checkpoint_generations": (
                        self.config.integrity.checkpoint_generations
                    ),
                },
            }
        )

    async def debug_compiles(self, request: web.Request) -> web.Response:
        """Recompile watchdog: XLA compile counts against the warmup
        watermark, total compile time, and the most recent compile
        events. `xla_compiles_post_warmup` > 0 means the steady-state
        tick path is retracing — a shape escaped the pow2 buckets or a
        static arg lost cache identity (GC11's runtime half)."""
        return web.json_response(
            self.room_manager.runtime.compile_ledger.snapshot()
        )

    async def debug_analytics(self, request: web.Request) -> web.Response:
        """Recent per-track analytics records (statsworker.go stream seat)."""
        try:
            n = max(0, int(request.query.get("n", 100)))
        except ValueError:
            return web.Response(status=400, text="n must be an integer")
        return web.json_response(
            {"track_stats": self.telemetry.track_stats[-n:] if n else []}
        )

    async def debug_rooms(self, request: web.Request) -> web.Response:
        rm = self.room_manager
        return web.json_response(
            {
                "node": self.router.local_node.node_id,
                "version": __version__,
                "rooms": {
                    name: {
                        "row": r.slots.row,
                        "participants": list(r.participants),
                        "tracks": list(r.tracks),
                        "traffic": rm.participant_traffic(r),
                    }
                    for name, r in rm.rooms.items()
                },
                "plane": rm.runtime.stats,
                "ingest_dropped": rm.runtime.ingest.dropped,
            }
        )

    # -- lifecycle --------------------------------------------------------
    async def start(self) -> None:
        # Identify this node's bus connection to the BusServer before any
        # other op: the partition-injection harness severs/heals by node
        # id, and pub/sub sender attribution needs it.
        bus = getattr(self.router, "bus", None)
        if bus is not None and hasattr(bus, "set_ident"):
            bus.set_ident(self.router.local_node.node_id)
        await self.router.register_node()
        if hasattr(self.router, "remove_dead_nodes"):
            await self.router.remove_dead_nodes()
        # Warm-compile the media-plane step before accepting traffic so the
        # first tick doesn't stall the event loop mid-session (XLA compiles
        # once per (shapes, params); later ticks hit the cache).
        await self.room_manager.runtime.step_once()
        # Watermark for the recompile watchdog: anything XLA compiles
        # after this point is a steady-state retrace (surfaced at
        # /debug/compiles and livekit_xla_compiles_total).
        self.room_manager.runtime.mark_warm()
        # Native UDP media transport on the RTC port (rtc/config.go UDPMux).
        if self.config.rtc.udp_port:
            from livekit_server_tpu.runtime.udp import start_udp_transport

            try:
                self.room_manager.udp = await start_udp_transport(
                    self.room_manager.runtime.ingest,
                    self.config.bind_addresses[0],
                    self.config.rtc.udp_port,
                    crypto=self.room_manager.crypto,
                    require_encryption=self.config.rtc.require_encryption,
                    nack_resolver=self.room_manager.runtime.resolve_nacks,
                )
                # Client PLIs over RTCP reach signal-plane publishers too.
                self.room_manager.udp.on_pli = self.room_manager.handle_pli
                # Sharded egress plane: the runtime owns the orchestrator
                # (shard plans, canonical grouping, per-shard stats); the
                # transport routes tick egress through it from here on.
                self.room_manager.udp.attach_egress_plane(
                    self.room_manager.runtime.egress_plane
                )
                # Sampled wire-latency attribution: the transport observes
                # per-stage stamps on each send (runtime/trace.py).
                self.room_manager.udp.wire_stages = (
                    self.room_manager.runtime.wire_stages
                )
                # Express lane (plane.express_max_subs > 0): interactive
                # rooms forward on packet arrival through this transport
                # instead of the batched tick (runtime/express.py).
                if self.room_manager.runtime.express is not None:
                    self.room_manager.udp.attach_express(
                        self.room_manager.runtime.express
                    )
                self.room_manager.udp.send_side_bwe = (
                    self.config.rtc.congestion_control.send_side_bwe
                )
                if self.config.rtc.pacer == "no-queue":
                    self.room_manager.udp.pacer_spread_ms = (
                        self.config.plane.tick_ms / 2.0
                    )
                elif self.config.rtc.pacer == "leaky-bucket":
                    # Per-subscriber byte budgets from the device pacer op
                    # gate egress; over-budget packets defer FIFO.
                    self.room_manager.udp.pacer_mode = "leaky-bucket"
                if self.config.room.playout_delay_max_ms > 0:
                    # Video egress carries the playout-delay extension
                    # (rtpextension/playoutdelay.go; config room section).
                    self.room_manager.udp.playout_delay = (
                        self.config.room.playout_delay_min_ms,
                        self.config.room.playout_delay_max_ms,
                    )
                for room in self.room_manager.rooms.values():
                    room.udp = self.room_manager.udp
                # TCP media fallback (transportmanager.go:73 ladder): same
                # sealed frames, length-prefixed; always encrypted — so it
                # cannot exist on a node running without an AEAD backend.
                if self.config.rtc.tcp_port and self.room_manager.crypto is not None:
                    from livekit_server_tpu.runtime.tcp import start_tcp_transport

                    try:
                        self.tcp_media = await start_tcp_transport(
                            self.room_manager.udp,
                            self.room_manager.crypto,
                            self.config.bind_addresses[0],
                            self.config.rtc.tcp_port,
                        )
                    except OSError:
                        pass  # port busy: UDP path still works
                # Embedded media relay (turn.go:47 seat): a second UDP hop
                # for clients that cannot reach rtc.udp_port directly.
                if self.config.relay.enabled:
                    from livekit_server_tpu.runtime.relay import start_media_relay

                    rcfg = self.config.relay
                    # Relay tokens are minted and verified only by this
                    # process, so the HMAC secret never needs to be derived
                    # from (or leak) API-key material — and a config-derived
                    # secret would be the constant "dev" in keyless dev mode,
                    # making tokens forgeable. A fresh random secret per
                    # process is strictly stronger and costs nothing.
                    secret = secrets.token_bytes(32)
                    # A wildcard bind is not a connectable upstream
                    # destination (0.0.0.0→loopback only works on Linux);
                    # the relay's per-allocation sockets dial loopback.
                    up_host = self.config.bind_addresses[0]
                    if up_host in ("", "0.0.0.0", "::"):
                        up_host = "127.0.0.1"
                    try:
                        self.media_relay = await start_media_relay(
                            self.config.bind_addresses[0],
                            rcfg.udp_port,
                            (up_host, self.config.rtc.udp_port),
                            secret,
                            ttl_s=float(rcfg.allocation_ttl_s),
                            max_allocations=rcfg.max_allocations,
                        )
                        # Signal-layer mint point (request_relay handler).
                        # Never advertise a wildcard bind as the relay host —
                        # clients can't route to 0.0.0.0; without a concrete
                        # external_host the relay runs but is not advertised.
                        advert = rcfg.external_host or self.config.bind_addresses[0]
                        if advert in ("", "0.0.0.0", "::"):
                            self.log.warn(
                                "relay enabled but bind address is a wildcard "
                                "and relay.external_host is unset; not "
                                "advertising relay to clients"
                            )
                        else:
                            self.room_manager.udp.relay_info = (
                                advert,
                                rcfg.udp_port,
                                secret,
                                float(rcfg.allocation_ttl_s),
                            )
                    except OSError:
                        pass  # relay port busy: direct path still works
            except OSError:
                pass  # port busy: WS media path still works
        await self.ioinfo.start()
        await self.room_api.start()
        self.room_manager.start()
        self._stats_task = asyncio.ensure_future(self._refresh_nodes())
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        for addr in self.config.bind_addresses:
            site = web.TCPSite(self._runner, addr, self.config.port)
            await site.start()
            self._sites.append(site)
        self.started_at = time.time()

    async def stop(self, force: bool = False) -> None:
        self.router.local_node.state = NodeState.SHUTTING_DOWN
        await self.router.drain()
        mig = self.room_manager.migration
        if not force and mig is not None:
            # Graceful stop IS a node drain: every local room migrates to
            # a peer through the two-phase handoff (bounded concurrency,
            # admissions refused throughout); rooms with no willing peer
            # stay and are torn down by room_manager.stop() below.
            try:
                await mig.drain_node()
            except Exception as e:  # noqa: BLE001 — stopping anyway
                self.log.warn("graceful drain failed", error=str(e))
        elif not force:
            # Bus-less single node: nobody to migrate to. Wait briefly for
            # participants to leave on their own (server.go:295).
            for _ in range(50):
                if not any(r.participants for r in self.room_manager.rooms.values()):
                    break
                await asyncio.sleep(0.1)
        if self._stats_task:
            self._stats_task.cancel()
        if self.room_manager.udp is not None and self.room_manager.udp.transport:
            self.room_manager.udp.transport.close()
        if getattr(self, "tcp_media", None) is not None:
            self.tcp_media.close()
        if getattr(self, "media_relay", None) is not None:
            self.media_relay.close()
        await self.ioinfo.stop()
        await self.room_api.stop()
        await self.room_manager.stop()
        await self.router.unregister_node()
        if self._runner is not None:
            await self._runner.cleanup()

    @property
    def port(self) -> int:
        return self.config.port


async def connect_bus(config: Config):
    """Resolve the configured multi-node bus (redisrouter's Redis client
    seat): kv.kind == "tcp" dials the in-repo BusServer at kv.address."""
    if config.kv.kind == "tcp":
        if not config.kv.address:
            # Booting a cluster-configured node standalone would silently
            # split-brain it out of the cluster; fail loudly instead.
            raise ConfigError("kv.kind is 'tcp' but kv.address is empty")
        from livekit_server_tpu.routing.tcpbus import TCPBusClient

        return await TCPBusClient.connect_address(
            config.kv.address, token=config.kv.auth_token
        )
    if config.kv.kind in ("", "memory"):
        return None
    # An unknown kind must not fall through to a private in-process bus —
    # the node would boot "clustered" against a registry only it can see.
    raise ConfigError(
        f"unsupported kv.kind {config.kv.kind!r}: no external KV client is "
        "bundled; run `livekit-server-tpu bus` and use kv.kind='tcp'"
    )


def create_server(config: Config, bus=None, mesh=None) -> LivekitServer:
    """The Wire graph (wire_gen.go InitializeServer) as explicit wiring."""
    node = LocalNode(region=config.region)
    sample_system_stats(node.stats)
    if bus is None and config.kv.kind == "memory":
        router = create_router(node, None)
        store = LocalStore()
    else:
        bus = bus if bus is not None else MemoryBus()
        router = create_router(
            node, bus,
            lease_ttl=config.kv.lease_ttl_s,
            stats_interval=config.kv.stats_interval_s,
        )
        store = KVStore(bus)
    telemetry = TelemetryService(config)
    rm = RoomManager(config, router, store, mesh=mesh, telemetry=telemetry)
    server = LivekitServer(config, router, store, rm, telemetry)
    server._selector = create_selector(config.node_selector, config.region)
    if rm.migration is not None:
        # Drain-target ranking reuses the placement selector, so a drain
        # spreads rooms the same way the router places new ones.
        rm.migration.selector = server._selector
    return server
