"""Ingress service: RTMP/WHIP/URL pull ingestion API.

Reference parity: pkg/service/ingress.go:32-350 — the livekit.Ingress
Twirp API (CreateIngress, UpdateIngress, ListIngress, DeleteIngress) with
state in the store and job dispatch to external ingress workers over the
bus (`ingress_jobs` / `ingress_updates`, the psrpc seat). Stream keys are
minted server-side; an ingress worker that accepts an RTMP/WHIP session
joins the room as a publishing participant through the normal signal path.
"""

from __future__ import annotations

import enum
import json
from dataclasses import dataclass, field
from typing import TYPE_CHECKING

from aiohttp import web

from livekit_server_tpu.utils import ids

if TYPE_CHECKING:
    from livekit_server_tpu.service.server import LivekitServer


class IngressInputType(enum.IntEnum):
    RTMP_INPUT = 0
    WHIP_INPUT = 1
    URL_INPUT = 2


class IngressState(enum.IntEnum):
    ENDPOINT_INACTIVE = 0
    ENDPOINT_BUFFERING = 1
    ENDPOINT_PUBLISHING = 2
    ENDPOINT_ERROR = 3
    ENDPOINT_COMPLETE = 4


@dataclass
class IngressInfo:
    ingress_id: str = ""
    name: str = ""
    stream_key: str = ""
    url: str = ""
    input_type: IngressInputType = IngressInputType.RTMP_INPUT
    room_name: str = ""
    participant_identity: str = ""
    participant_name: str = ""
    reusable: bool = False
    state: IngressState = IngressState.ENDPOINT_INACTIVE
    error: str = ""
    audio: dict = field(default_factory=dict)
    video: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        d = dict(vars(self))
        d["input_type"] = int(self.input_type)
        d["state"] = int(self.state)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "IngressInfo":
        d = dict(d)
        d["input_type"] = IngressInputType(d.get("input_type", 0))
        d["state"] = IngressState(d.get("state", 0))
        return cls(**d)


class IngressService:
    PREFIX = "/twirp/livekit.Ingress/"
    JOBS_TOPIC = "ingress_jobs"
    UPDATES_TOPIC = "ingress_updates"

    def __init__(self, server: "LivekitServer"):
        self.server = server

    @property
    def ingresses(self) -> dict:
        """Shared store owned by the IOInfoService aggregator
        (pkg/service/ioservice.go): the Twirp handlers create/delete
        entries here and the aggregator's bus worker updates them."""
        return self.server.ioinfo.ingresses

    async def handle(self, request: web.Request) -> web.Response:
        from livekit_server_tpu.auth import (
            TokenError,
            ensure_ingress_admin_permission,
            verify_token,
        )

        method = request.path.removeprefix(self.PREFIX)
        token = request.headers.get("Authorization", "").removeprefix("Bearer ").strip()
        try:
            claims = verify_token(token, self.server.config.keys)
        except TokenError as e:
            return web.json_response({"msg": str(e)}, status=401)
        # Reference parity: ingress management needs the dedicated
        # ingressAdmin grant (auth.go EnsureIngressAdminPermission) —
        # roomAdmin is room-scoped and is NOT a substitute for a
        # node-global capability.
        if not ensure_ingress_admin_permission(claims):
            return web.json_response({"msg": "requires ingressAdmin"}, status=403)
        try:
            body = await request.json()
        except json.JSONDecodeError:
            body = {}

        if method == "CreateIngress":
            info = IngressInfo(
                ingress_id=ids.new_guid(ids.INGRESS_PREFIX),
                name=body.get("name", ""),
                stream_key=ids.new_guid("SK_"),
                input_type=IngressInputType(body.get("input_type", 0)),
                room_name=body.get("room_name", ""),
                participant_identity=body.get("participant_identity", ""),
                participant_name=body.get("participant_name", ""),
                reusable=bool(body.get("reusable", False)),
                audio=body.get("audio", {}),
                video=body.get("video", {}),
            )
            self.ingresses[info.ingress_id] = info
            self.server.ioinfo.stamp(info.ingress_id)
            await self._publish({"kind": "create", "ingress": info.to_dict()})
            return web.json_response(info.to_dict())
        if method == "UpdateIngress":
            info = self.ingresses.get(body.get("ingress_id", ""))
            if info is None:
                return web.json_response({"msg": "ingress not found"}, status=404)
            for f in ("name", "room_name", "participant_identity", "participant_name"):
                if f in body:
                    setattr(info, f, body[f])
            await self._publish({"kind": "update", "ingress": info.to_dict()})
            return web.json_response(info.to_dict())
        if method == "ListIngress":
            items = [
                i.to_dict()
                for i in self.ingresses.values()
                if not body.get("room_name") or i.room_name == body["room_name"]
            ]
            return web.json_response({"items": items})
        if method == "DeleteIngress":
            info = self.ingresses.pop(body.get("ingress_id", ""), None)
            if info is None:
                return web.json_response({"msg": "ingress not found"}, status=404)
            await self._publish({"kind": "delete", "ingress": info.to_dict()})
            return web.json_response(info.to_dict())
        return web.json_response({"msg": f"unknown method {method}"}, status=404)

    async def _publish(self, job: dict) -> int:
        bus = getattr(self.server.router, "bus", None)
        if bus is None:
            return 0
        return await bus.publish(self.JOBS_TOPIC, json.dumps(job))
