"""Live room migration plane: two-phase handoff with rollback.

The reference moves a room between nodes only implicitly — the old node
dies, its lease lapses, and a survivor adopts the pin (routing-plane
failover, PR 2). That path loses the freeze window's media and cannot be
*asked* to move a room. This module makes migration a first-class,
supervised, abortable operation:

  PREPARE   source freezes the row, snapshots it (LKCK-checksummed,
            plane_runtime.encode_room_snapshot) and publishes the
            snapshot inline on ``node_migrate:{target}`` together with a
            fresh attempt epoch. The target adopts the room into a local
            device row (restore_room under its state_lock), records a
            TTL-bounded *adoption*, and ACKs. A target that is draining,
            governed at L3+, or out of rows NACKs instead — governed
            admission: an inbound migration is deferrable load, so it is
            refused one ladder rung earlier than a client join.
  COMMIT    only after the ACK does the source repin the room to the
            target, flush the freeze-window bridge, publish COMMIT, and
            tear down its replica (close → signals clients MIGRATION so
            they reconnect and land on the new pin).
  ROLLBACK  on NACK, ACK timeout, or a bus failure anywhere in commit,
            the source unfreezes the row, re-asserts its own pin,
            publishes ABORT, and replays the bridged packets into its
            *local* ingest — the room never stopped being served and its
            audio shows no gap. Retries ride utils.backoff.retry_async;
            each attempt carries a new epoch and a timed-out epoch is
            aborted before the next attempt sends, so a late ACK from an
            aborted attempt finds a dead epoch and can never double-commit.

Freeze-window bridging: packets ingested on the source between the
snapshot and COMMIT would otherwise drop on the frozen row. A
FreezeBridge capture sink (ingest.freeze_sinks) buffers them — bounded,
audio evicts video when the budget is hit — and the commit path forwards
them to the target in BRIDGE chunks, so the cutover drops zero audio.

Node drain: ``drain_node`` flips the local node to SHUTTING_DOWN
(selectors exclude it), pins the overload governor at L_MAX, marks the
plane supervisor as draining (a quiescing plane must not be watchdog-
restarted), then migrates every local room off with bounded concurrency.
This is the real implementation behind LivekitServer.stop()'s graceful
path and the ``drain`` CLI verb.

Adoptions that never see a COMMIT (source died mid-handoff, ABORT lost)
are reaped after ``migration.adopt_ttl_s`` — the target releases the row
and forgets the room, so a failed handoff leaks nothing on either side.
"""

from __future__ import annotations

import asyncio
import base64
from collections import deque
from dataclasses import dataclass, field

from livekit_server_tpu.protocol import models as pm
from livekit_server_tpu.routing.fleet import FencedWriteRejected
from livekit_server_tpu.routing.node import NodeState
from livekit_server_tpu.routing.selector import NoNodesAvailable
from livekit_server_tpu.rtc.room import Room
from livekit_server_tpu.runtime.governor import L_PAUSE
from livekit_server_tpu.runtime.ingest import PacketIn
from livekit_server_tpu.runtime import CapacityError
from livekit_server_tpu.utils.backoff import BackoffPolicy, retry_async

# PacketIn fields that ride a BRIDGE message alongside the b64 payload.
_PKT_FIELDS = (
    "track", "sn", "ts", "size", "marker", "layer", "temporal", "keyframe",
    "layer_sync", "begin_pic", "pid", "tl0", "keyidx", "frame_ms",
    "audio_level", "arrival_rtp", "ts_aligned",
)


def _encode_pkt(pkt: PacketIn) -> dict:
    d = {f: getattr(pkt, f) for f in _PKT_FIELDS}
    d["payload"] = base64.b64encode(pkt.payload).decode("ascii")
    return d


def _decode_pkt(d: dict, row: int) -> PacketIn:
    """Rebuild a PacketIn on the ADOPTING node's row (rows are per-node
    slot allocations; only the room identity travels, never the row)."""
    kw = {f: d[f] for f in _PKT_FIELDS if f in d}
    kw["payload"] = base64.b64decode(d.get("payload", ""))
    return PacketIn(room=row, **kw)


class FreezeBridge:
    """Bounded capture buffer for one frozen row's freeze window.

    Audio priority: at budget, an incoming video packet is dropped
    outright and an incoming audio packet evicts the oldest buffered
    video packet first (oldest audio only when the buffer is all audio).
    ``drain()`` returns everything in capture order and resets, so the
    commit path can flush repeatedly until the window runs dry.
    """

    def __init__(self, row: int, is_video_col, max_packets: int):
        self.row = row
        self._is_video = is_video_col       # host mirror view [tracks]
        self.budget = max(1, int(max_packets))
        self._buf: deque = deque()          # (seq, pkt)
        self._seq = 0
        self.captured = 0
        self.dropped = 0

    def capture(self, pkt: PacketIn) -> None:
        video = bool(self._is_video[pkt.track])
        if len(self._buf) >= self.budget:
            if video:
                self.dropped += 1
                return
            evict = None
            for i, (_, old) in enumerate(self._buf):
                if self._is_video[old.track]:
                    evict = i
                    break
            if evict is None:
                evict = 0                   # all-audio: shed the oldest
            del self._buf[evict]
            self.dropped += 1
        self._seq += 1
        self._buf.append((self._seq, pkt))
        self.captured += 1

    def drain(self) -> list[PacketIn]:
        out = [p for _, p in self._buf]
        self._buf.clear()
        return out


@dataclass
class _Attempt:
    """Source side: one in-flight PREPARE awaiting its ACK/NACK."""

    epoch: int
    target: str
    ack: asyncio.Future


@dataclass
class _Adoption:
    """Target side: an adopted room awaiting COMMIT (or reaping).

    The adopted row stays frozen until COMMIT: packets that reach the
    target directly during the handoff window (the pin moves before the
    freeze-window flush finishes) land in ``bridge``, while the source's
    BRIDGE messages accumulate in ``bridged``. COMMIT replays bridged
    first, then the local captures — SN order stays monotonic, so the
    munger never sees the bridged tail as stale."""

    epoch: int
    source: str
    deadline: float                         # loop.time()-based
    row: int = field(default=-1)
    bridge: FreezeBridge | None = None      # direct packets, pre-COMMIT
    bridged: list = field(default_factory=list)  # source freeze window


class MigrationOrchestrator:
    """One per RoomManager (constructed only when the router has a bus).

    All bus traffic rides one channel per node, ``node_migrate:{id}``,
    with dict messages keyed by ``kind``:

      prepare  {room, epoch, source, snapshot, info}   source → target
      ack/nack {room, epoch, target[, reason]}         target → source
      commit   {room, epoch}                           source → target
      abort    {room, epoch}                           source → target
      bridge   {room, packets: [...]}                  source → target
      drain    {}                                      admin  → node
    """

    def __init__(self, manager):
        self.mgr = manager
        self.cfg = manager.config.migration
        self.router = manager.router
        self.bus = manager.router.bus
        self.log = manager.log
        self.selector = None        # wired by create_server (node ranking)
        self.on_adopt: list = []    # test seam: callbacks fired per adoption
        self.draining = False
        self._epoch = 0             # monotonic attempt counter (this node)
        self._attempts: dict[str, _Attempt] = {}
        self._adoptions: dict[str, _Adoption] = {}
        self._migrating: set[str] = set()
        self._sub = None
        self._worker_task: asyncio.Task | None = None
        self._reaper_task: asyncio.Task | None = None
        self._tasks: set[asyncio.Task] = set()
        self.stats = {
            "migrations": 0, "commits": 0, "rollbacks": 0, "timeouts": 0,
            "nacks_sent": 0, "nacks_received": 0, "stale_acks": 0,
            "stale_commits": 0, "adoptions": 0, "commits_in": 0,
            "adoptions_released": 0, "bridged_out": 0, "bridged_in": 0,
            "bridge_dropped": 0, "drains": 0,
            # Handoffs whose ownership epoch was claimed away mid-flight
            # by a failover restorer (routing/fleet.py): the local
            # replica is closed by the fence, not rolled back.
            "fenced_handoffs": 0,
        }

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        me = self.router.local_node.node_id
        self._sub = self.bus.subscribe(f"node_migrate:{me}")
        self._worker_task = asyncio.ensure_future(self._worker())
        self._reaper_task = asyncio.ensure_future(self._adopt_reaper())

    async def stop(self) -> None:
        if self._sub is not None:
            self._sub.close()
            self._sub = None
        tasks = [t for t in (self._worker_task, self._reaper_task)
                 if t is not None]
        tasks += list(self._tasks)
        self._worker_task = self._reaper_task = None
        for t in tasks:
            t.cancel()
        for t in tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass
            except Exception:  # noqa: BLE001 — teardown must not raise
                pass

    # -- bus plumbing -----------------------------------------------------
    async def _send(self, node_id: str, msg: dict) -> int:
        return await self.bus.publish(f"node_migrate:{node_id}", msg)

    def _spawn(self, coro) -> asyncio.Task:
        task = asyncio.ensure_future(coro)
        self._tasks.add(task)
        task.add_done_callback(self._task_done)
        return task

    def _task_done(self, task: asyncio.Task) -> None:
        self._tasks.discard(task)
        if not task.cancelled() and task.exception() is not None:
            self.log.warn(
                "migration handler task failed",
                error=repr(task.exception()),
            )

    @staticmethod
    def _now() -> float:
        return asyncio.get_running_loop().time()

    async def _worker(self) -> None:
        async for raw in self._sub:
            msg = raw if isinstance(raw, dict) else None
            if msg is None:
                continue
            kind = msg.get("kind", "")
            if kind in ("ack", "nack"):
                self._resolve(msg, kind)   # inline: unblocks a waiter
            elif kind in ("prepare", "commit", "abort", "bridge"):
                self._spawn(getattr(self, f"_handle_{kind}")(msg))
            elif kind == "drain":
                self._spawn(self.drain_node())

    def _resolve(self, msg: dict, kind: str) -> None:
        """ACK/NACK dispatch with the epoch guard: a reply whose epoch
        does not match the room's CURRENT attempt is from an aborted
        earlier attempt and is dropped — it must never resolve the new
        attempt's future (the double-commit hazard)."""
        att = self._attempts.get(msg.get("room", ""))
        if att is None or att.epoch != msg.get("epoch"):
            self.stats["stale_acks"] += 1
            self.log.warn(
                "stale migration reply ignored (epoch guard)",
                room=msg.get("room", ""), kind=kind,
                epoch=msg.get("epoch"),
            )
            return
        if not att.ack.done():
            att.ack.set_result((kind, msg.get("reason", "")))

    # -- source side: migrate one room ------------------------------------
    async def migrate_room(self, name: str, target_node_id: str = "") -> bool:
        """Move one locally-served room to another node. Returns True on
        a committed handoff; False leaves the room serving here."""
        mgr = self.mgr
        if name not in mgr.rooms or name in self._migrating:
            return False
        self._migrating.add(name)
        try:
            if target_node_id:
                candidates = [target_node_id]
            else:
                try:
                    candidates = await self._candidates()
                except (ConnectionError, OSError) as e:
                    self.log.warn("migration: node list unavailable",
                                  room=name, error=str(e))
                    return False
            if not candidates:
                self.log.warn("migration: no candidate nodes", room=name)
                return False
            for target in candidates:
                if name not in mgr.rooms:
                    return False           # deleted underneath us
                if await self._attempt_handoff(name, target):
                    self.stats["migrations"] += 1
                    if mgr.telemetry is not None:
                        mgr.telemetry.add("livekit_room_migrations_total")
                    return True
            return False
        finally:
            self._migrating.discard(name)

    async def _candidates(self) -> list[str]:
        """Peer nodes ranked by the placement selector (load/region aware,
        by repeated selection); selector-refused peers still close the
        list as last resorts — they may NACK, which is cheap."""
        me = self.router.local_node.node_id
        nodes = await self.router.list_nodes()
        peers = [
            n for n in nodes
            if n.node_id != me and n.state != NodeState.SHUTTING_DOWN
        ]
        if self.selector is None:
            return [n.node_id for n in peers]
        ordered: list[str] = []
        pool = list(peers)
        while pool:
            try:
                n = self.selector.select_node(list(pool))
            except NoNodesAvailable:
                break
            ordered.append(n.node_id)
            pool = [m for m in pool if m.node_id != n.node_id]
        ordered += [n.node_id for n in peers if n.node_id not in ordered]
        return ordered

    async def _attempt_handoff(self, name: str, target: str) -> bool:
        mgr = self.mgr
        rt = mgr.runtime
        room = mgr.rooms.get(name)
        if room is None:
            return False
        row = room.slots.row
        bridge = FreezeBridge(
            row, rt.meta.is_video[row], self.cfg.bridge_max_packets
        )
        # Freeze + tap: from here the row's packets stop staging and are
        # captured for bridging instead (ingest.push frozen branch).
        # Already-staged packets move into the bridge too — drain() has
        # no frozen filter, so left alone they would enter the device
        # after the snapshot below and race the teardown.
        rt.ingest.frozen_rows.add(row)
        rt.ingest.freeze_sinks[row] = bridge.capture
        for pkt in rt.ingest.extract_row(row):
            bridge.capture(pkt)
        bb = getattr(rt, "blackbox", None)
        if bb is not None:
            from livekit_server_tpu.runtime.trace import EV_MIG_FREEZE

            bb.emit(row, EV_MIG_FREEZE)
        epoch = 0
        try:
            async with rt.state_lock:      # vs. the donated device step
                snap = rt.snapshot_room(row)
            payload = rt.encode_room_snapshot(snap)
            if mgr.fault is not None:
                payload = mgr.fault.corrupt_handoff(payload)
            verdict, reason = "error", ""
            try:
                verdict, reason, epoch = await self._prepare_exchange(
                    name, target, payload, room
                )
            except (
                TimeoutError, asyncio.TimeoutError, ConnectionError, OSError,
            ) as e:
                verdict, reason = "timeout", f"{type(e).__name__}: {e}"
            if verdict == "ack":
                try:
                    if await self._commit(name, target, room, bridge, epoch):
                        return True
                except FencedWriteRejected:
                    # A failover restorer claimed a higher epoch mid-
                    # commit: the fence's on_lost already closed the
                    # local replica, so there is nothing to roll back
                    # INTO. Abort the target's adoption and stand down.
                    self.stats["fenced_handoffs"] += 1
                    try:
                        await self._send(
                            target,
                            {"kind": "abort", "room": name, "epoch": epoch},
                        )
                    except (ConnectionError, OSError):
                        pass   # target's adopt TTL reaps it
                    self.log.warn(
                        "handoff fenced out by a higher ownership epoch",
                        room=name, target=target[:12],
                    )
                    return False
                reason = "commit failed: bus error"
            elif verdict == "nack":
                self.stats["nacks_received"] += 1
            await self._rollback(
                name, target, room, bridge, epoch,
                reason=f"{verdict}: {reason}",
            )
            return False
        finally:
            # Idempotent with _rollback's unfreeze; on commit the row is
            # already released and these are no-ops.
            rt.ingest.freeze_sinks.pop(row, None)
            rt.ingest.frozen_rows.discard(row)
            self.stats["bridge_dropped"] += bridge.dropped

    async def _prepare_exchange(
        self, name: str, target: str, payload: str, room: Room
    ):
        """Send PREPARE and await the ACK/NACK, with retry_async supplying
        the backoff schedule. Each attempt mints a fresh epoch; a timed-out
        epoch is ABORTed before the retry sends, so the target releases a
        silently-adopted row and a late ACK finds a dead epoch."""
        me = self.router.local_node.node_id
        last = {"epoch": 0}

        async def once():
            self._epoch += 1
            epoch = last["epoch"] = self._epoch
            fut = asyncio.get_running_loop().create_future()
            self._attempts[name] = _Attempt(epoch=epoch, target=target, ack=fut)
            n = await self._send(target, {
                "kind": "prepare", "room": name, "epoch": epoch,
                "source": me, "snapshot": payload,
                "info": room.info.to_dict(),
            })
            if n == 0:
                # Dead target detected at publish time — cheaper than
                # burning the full ACK timeout on a node that is gone.
                raise ConnectionError(f"no migration listener on {target[:12]}")
            try:
                return await asyncio.wait_for(fut, self.cfg.ack_timeout_s)
            except (TimeoutError, asyncio.TimeoutError):
                self.stats["timeouts"] += 1
                try:
                    await self._send(
                        target, {"kind": "abort", "room": name, "epoch": epoch}
                    )
                except (ConnectionError, OSError):
                    pass   # severed bus: the target's adopt TTL reaps it
                raise

        policy = BackoffPolicy(
            base=self.cfg.retry_backoff_base_s,
            max_delay=self.cfg.retry_backoff_max_s,
            max_attempts=max(1, self.cfg.retry_attempts),
        )
        try:
            kind, reason = await retry_async(
                once, policy,
                retry_on=(
                    TimeoutError, asyncio.TimeoutError,
                    ConnectionError, OSError,
                ),
            )
            return kind, reason, last["epoch"]
        finally:
            self._attempts.pop(name, None)

    async def _commit(
        self, name: str, target: str, room: Room,
        bridge: FreezeBridge, epoch: int,
    ) -> bool:
        """Phase two. Order matters: repin first (new joins route to the
        target), bridge the freeze window, COMMIT, and only then tear
        down the local replica — a failure before teardown rolls back to
        a fully-serving source."""
        mgr = self.mgr
        row = room.slots.row
        try:
            if mgr.fault is not None and mgr.fault.mig_sever_commit():
                raise ConnectionError("bus severed mid-handoff (fault)")
            await self.router.set_node_for_room(name, target)
            await self._flush_bridge(name, target, bridge)
            # Deregister BEFORE the final flush: nothing new enters the
            # bridge once the manager stops routing here, so the flush
            # below empties it for good — and COMMIT is sent only after
            # the last BRIDGE message, so on the target's FIFO channel
            # the whole freeze window precedes the unfreeze. A failure
            # past this point rolls back; _rollback re-registers.
            mgr.rooms.pop(name, None)
            mgr._row_to_room.pop(row, None)
            await self._flush_bridge(name, target, bridge)
            await self._send(
                target, {"kind": "commit", "room": name, "epoch": epoch}
            )
        except (ConnectionError, OSError) as e:
            self.log.warn(
                "migration commit failed; rolling back",
                room=name, target=target[:12], error=str(e),
            )
            return False
        # Committed: the pin and the row now belong to the target.
        room.close(pm.DisconnectReason.MIGRATION)
        mgr._update_node_stats()
        self.stats["commits"] += 1
        bb = getattr(mgr.runtime, "blackbox", None)
        if bb is not None:
            from livekit_server_tpu.runtime.trace import EV_MIG_COMMIT

            bb.emit(row, EV_MIG_COMMIT, float(epoch))
        self.log.info(
            "room migrated", room=name, target=target[:12], epoch=epoch,
            bridged=bridge.captured,
        )
        return True

    async def _flush_bridge(
        self, name: str, target: str, bridge: FreezeBridge
    ) -> None:
        chunk = max(1, int(self.cfg.bridge_chunk))
        for _ in range(16):   # bounded: the source stops feeding once unpinned
            pkts = bridge.drain()
            if not pkts:
                return
            for i in range(0, len(pkts), chunk):
                await self._send(target, {
                    "kind": "bridge", "room": name,
                    "packets": [_encode_pkt(p) for p in pkts[i:i + chunk]],
                })
            self.stats["bridged_out"] += len(pkts)

    async def _rollback(
        self, name: str, target: str, room: Room,
        bridge: FreezeBridge, epoch: int, reason: str = "",
    ) -> None:
        mgr = self.mgr
        row = room.slots.row
        # Re-register first (idempotent): _commit deregisters before its
        # final flush, so a failure after that point must restore local
        # serving before anything else.
        mgr.rooms[name] = room
        mgr._row_to_room[row] = room
        # The pin may have moved if commit died between repin and COMMIT;
        # the room still serves HERE, so re-assert our pin (idempotent
        # when it never moved). The row stays frozen across these sends —
        # live packets keep landing in the bridge, in order.
        me = self.router.local_node.node_id
        try:
            await self.router.set_node_for_room(name, me)
        except (ConnectionError, OSError):
            pass   # bus down: lease failover will converge the pin
        except FencedWriteRejected:
            # A higher epoch owns the room now (takeover raced the
            # rollback): the fence's on_lost just closed — and popped —
            # the replica re-registered above. Stand down entirely; the
            # epoch holder serves the room.
            self.stats["fenced_handoffs"] += 1
            self.log.warn(
                "rollback fenced out by a higher ownership epoch", room=name
            )
            return
        try:
            await self._send(
                target, {"kind": "abort", "room": name, "epoch": epoch}
            )
        except (ConnectionError, OSError):
            pass   # target reaps the adoption via its TTL
        # Replay the freeze window into the LOCAL ingest: these packets
        # were never rx-counted (the frozen branch precedes accounting),
        # so the default counting path keeps the books exact — and the
        # room's audio shows zero gap across the aborted handoff.
        replayed = await self._replay_unfreeze(row, [], bridge)
        self.stats["rollbacks"] += 1
        self.log.warn(
            "migration rolled back; room keeps serving",
            room=name, target=target[:12], reason=reason, replayed=replayed,
        )
        bb = getattr(mgr.runtime, "blackbox", None)
        if bb is not None:
            from livekit_server_tpu.runtime.trace import EV_MIG_ABORT

            bb.emit(row, EV_MIG_ABORT, float(epoch))
            bb.dump_to(row, f"migration_abort:{reason[:40]}")

    async def _replay_unfreeze(
        self, row: int, head: list, bridge: FreezeBridge | None
    ) -> int:
        """Meter ``head`` plus the row's freeze-bridge captures into the
        local ingest, then unfreeze. One tick's staging set has only
        dims.pkts slots per (room, track); dumping the whole window in
        one burst overflows them and the excess capacity-drops — the
        replay must spread across ticks instead. The row stays frozen
        between rounds so live packets keep queueing in the bridge
        (in arrival order, behind the window being replayed); the final
        drain → unfreeze runs in one sync block, so nothing slips in
        unordered."""
        ing = self.mgr.runtime.ingest
        k_max = int(ing.dims.pkts)
        tick_s = max(0.001, getattr(self.mgr.runtime, "tick_ms", 10) / 1000.0)
        pending = deque(head)
        replayed = 0
        for _ in range(256):          # bound: ~2.5s of ticks, then give up
            if bridge is not None:
                pending.extend(bridge.drain())
            ing.frozen_rows.discard(row)
            while pending and int(ing._count[row, pending[0].track]) < k_max:
                ing.push(pending.popleft(), _fault_ok=True)
                replayed += 1
            # Unfreeze only with headroom left in this tick's slots, so
            # a live packet arriving right behind us isn't shed either.
            if not pending and int(ing._count[row].max()) < k_max:
                break
            ing.frozen_rows.add(row)
            await asyncio.sleep(tick_s)
        else:
            ing.frozen_rows.discard(row)
            while pending:            # bound hit: stop metering, best effort
                ing.push(pending.popleft(), _fault_ok=True)
                replayed += 1
        ing.freeze_sinks.pop(row, None)
        ing.frozen_rows.discard(row)
        if bridge is not None:
            for pkt in bridge.drain():
                ing.push(pkt, _fault_ok=True)
                replayed += 1
        return replayed

    # -- target side ------------------------------------------------------
    async def _handle_prepare(self, msg: dict) -> None:
        mgr = self.mgr
        name = msg.get("room", "")
        epoch = int(msg.get("epoch", 0))
        source = msg.get("source", "")
        if not name or not source:
            return
        me = self.router.local_node.node_id

        async def reply(kind: str, **extra) -> None:
            try:
                await self._send(source, {
                    "kind": kind, "room": name, "epoch": epoch,
                    "target": me, **extra,
                })
            except (ConnectionError, OSError):
                pass   # source times out and rolls back on its own

        async def nack(why: str) -> None:
            self.stats["nacks_sent"] += 1
            self.log.warn("migration PREPARE refused", room=name,
                          source=source[:12], reason=why)
            await reply("nack", reason=why)

        # Already hosting: a retry whose earlier ACK was lost re-ACKs the
        # pending adoption under the NEW epoch; a room we serve outright
        # (committed, or never migrated) NACKs — two nodes must never
        # both serve one room.
        ad = self._adoptions.get(name)
        if name in mgr.rooms:
            if ad is None:
                await nack("already serving this room")
                return
            ad.epoch = epoch
            ad.source = source
            ad.deadline = self._now() + self.cfg.adopt_ttl_s
            if mgr.fault is not None and mgr.fault.mig_swallow_prepare():
                return
            if mgr.fault is not None:
                await mgr.fault.mig_delay_ack()
            await reply("ack")
            return
        # Governed admission, before any decode work. An inbound
        # migration is deferrable load: refuse at L3+ (client joins only
        # stop at L4) and always while draining.
        if self.draining:
            await nack("target draining")
            return
        gov = mgr.governor
        if gov is not None and (gov.drain_hold or gov.level >= L_PAUSE):
            await nack(f"target overloaded (L{gov.level})")
            return
        why = mgr._admission_denied("room")
        if why:
            await nack(why)
            return
        try:
            snap = mgr.runtime.decode_room_snapshot(msg.get("snapshot", ""))
        except Exception as e:  # noqa: BLE001 — checksum/codec damage
            await nack(f"snapshot rejected: {e}")
            return
        info = None
        if isinstance(msg.get("info"), dict):
            try:
                info = pm.RoomInfo.from_dict(msg["info"])
            except (TypeError, ValueError, KeyError):
                info = None
        lock = mgr._create_locks.setdefault(name, asyncio.Lock())
        async with lock:
            if name in mgr.rooms:          # raced a concurrent create
                await nack("already serving this room")
                return
            try:
                room = Room(name, mgr.runtime, info=info)
            except CapacityError as e:
                await nack(str(e) or "no free room row")
                return
            room.udp = mgr.udp
            room.crypto = mgr.crypto
            room.admission = mgr._admission_denied
            try:
                async with mgr.runtime.state_lock:   # vs. the device step
                    mgr.runtime.restore_room(room.slots.row, snap)
            except Exception as e:  # noqa: BLE001 — dims drifted vs source
                room.close(pm.DisconnectReason.MIGRATION)
                await nack(f"snapshot restore failed: {e}")
                return
            mgr.rooms[name] = room
            mgr._row_to_room[room.slots.row] = room
        mgr._create_locks.pop(name, None)
        # Freeze the adopted row until COMMIT: traffic that beats the
        # freeze-window flush here (the pin moves first) is captured and
        # replayed AFTER the bridged packets, preserving SN order.
        arow = room.slots.row
        abridge = FreezeBridge(
            arow, mgr.runtime.meta.is_video[arow], self.cfg.bridge_max_packets
        )
        mgr.runtime.ingest.frozen_rows.add(arow)
        mgr.runtime.ingest.freeze_sinks[arow] = abridge.capture
        self._adoptions[name] = _Adoption(
            epoch=epoch, source=source,
            deadline=self._now() + self.cfg.adopt_ttl_s,
            row=arow, bridge=abridge,
        )
        mgr._on_room_adopted(room)
        for cb in list(self.on_adopt):
            cb(room)
        mgr._update_node_stats()
        self.stats["adoptions"] += 1
        self.log.info("migration PREPARE adopted", room=name,
                      source=source[:12], epoch=epoch, row=room.slots.row)
        if mgr.fault is not None and mgr.fault.mig_swallow_prepare():
            return   # chaos drill: adopted, then went silent — no ACK ever
        if mgr.fault is not None:
            await mgr.fault.mig_delay_ack()
        await reply("ack")

    async def _handle_commit(self, msg: dict) -> None:
        name = msg.get("room", "")
        ad = self._adoptions.get(name)
        if ad is None or ad.epoch != msg.get("epoch"):
            # Aborted/expired adoption, or a stale epoch: never finalize.
            self.stats["stale_commits"] += 1
            return
        del self._adoptions[name]
        self.stats["commits_in"] += 1
        # The source's COMMIT repin transferred the ownership epoch to
        # us; adopt the record now so our own checkpoint writes are
        # fenced under it (guarded writes would auto-assume lazily, but
        # an explicit adopt keeps /debug/fleet truthful immediately).
        fence = getattr(self.router, "fence", None)
        if fence is not None:
            try:
                await fence.assume(name)
            except (ConnectionError, OSError):
                pass   # lazy auto-assume covers it on the next write
        room = self.mgr.rooms.get(name)
        # Replay the source's freeze window first, then whatever arrived
        # here directly while the row was frozen — monotonic SN order, so
        # the munger accepts the bridged tail instead of dropping it.
        await self._replay_unfreeze(ad.row, ad.bridged, ad.bridge)
        if ad.bridge is not None:
            self.stats["bridge_dropped"] += ad.bridge.dropped
        ad.bridged = []
        self.log.info("migration committed (target)", room=name,
                      row=room.slots.row if room else -1)
        try:
            if room is not None:
                await self.mgr.store.store_room(room.info)
        except (ConnectionError, OSError):
            pass   # best-effort; the store heals on the next room update

    async def _handle_abort(self, msg: dict) -> None:
        name = msg.get("room", "")
        ad = self._adoptions.get(name)
        if ad is None or ad.epoch != msg.get("epoch"):
            return   # not our adoption (or already committed): ignore
        await self._release_adoption(name, "aborted by source")

    async def _handle_bridge(self, msg: dict) -> None:
        name = msg.get("room", "")
        room = self.mgr.rooms.get(name)
        if room is None:
            return   # adoption already released: the window died with it
        ad = self._adoptions.get(name)
        ing = self.mgr.runtime.ingest
        n = 0
        for d in msg.get("packets", []):
            try:
                pkt = _decode_pkt(d, room.slots.row)
            except (TypeError, ValueError, KeyError):
                continue
            if ad is not None:
                # Pre-COMMIT: hold the freeze window aside; COMMIT
                # replays it before the row's own captures.
                ad.bridged.append(pkt)
            else:
                ing.push(pkt, _fault_ok=True)
            n += 1
        self.stats["bridged_in"] += n

    async def _adopt_reaper(self) -> None:
        """Release adoptions whose COMMIT never arrived (source died, or
        its ABORT was lost): the row is reclaimed and the pin — which
        still names the source — is left alone for lease failover."""
        interval = max(0.05, self.cfg.adopt_ttl_s / 4.0)
        while True:
            await asyncio.sleep(interval)
            now = self._now()
            expired = [
                n for n, ad in self._adoptions.items() if ad.deadline <= now
            ]
            for name in expired:
                await self._release_adoption(
                    name, "no COMMIT before adopt_ttl_s"
                )

    async def _release_adoption(self, name: str, why: str) -> None:
        ad = self._adoptions.pop(name, None)
        mgr = self.mgr
        room = mgr.rooms.pop(name, None)
        if ad is not None:
            mgr.runtime.ingest.freeze_sinks.pop(ad.row, None)
            mgr.runtime.ingest.frozen_rows.discard(ad.row)
        if room is None:
            return
        mgr._row_to_room.pop(room.slots.row, None)
        # close() releases the UDP row, clears the plane row, and frees
        # the slot — no row leak from an abandoned handoff. The routing
        # pin is NOT ours to clear: it still names the source.
        room.close(pm.DisconnectReason.MIGRATION)
        mgr._update_node_stats()
        self.stats["adoptions_released"] += 1
        self.log.warn("migration adoption released", room=name, reason=why)

    # -- node drain -------------------------------------------------------
    async def drain_node(self) -> dict:
        """Migrate every local room off this node with bounded concurrency
        while the node refuses all new admissions. Used by the graceful
        server stop and the ``drain`` CLI verb."""
        mgr = self.mgr
        if self.draining:
            return {"already_draining": True}
        self.draining = True
        self.stats["drains"] += 1
        self.router.local_node.state = NodeState.SHUTTING_DOWN
        try:
            await self.router.drain()   # republish: selectors exclude us
        except (ConnectionError, OSError):
            pass
        if mgr.governor is not None:
            mgr.governor.hold_max("node draining")
        if mgr.supervisor is not None:
            # A draining plane quiesces on purpose; the watchdog must not
            # read the calm as a stall and restart it mid-drain.
            mgr.supervisor.draining = True
        names = list(mgr.rooms)
        sem = asyncio.Semaphore(max(1, int(self.cfg.drain_concurrency)))
        results: dict[str, bool] = {}

        async def one(name: str) -> None:
            async with sem:
                results[name] = await self.migrate_room(name)

        if names:
            await asyncio.gather(*(one(n) for n in names))
        moved = sum(1 for ok in results.values() if ok)
        failed = sorted(n for n, ok in results.items() if not ok)
        if mgr.telemetry is not None:
            mgr.telemetry.add("livekit_node_drains_total")
        self.log.info("node drain finished", rooms=len(names),
                      migrated=moved, failed=len(failed))
        return {"rooms": len(names), "migrated": moved, "failed": failed}

    # -- visibility -------------------------------------------------------
    def snapshot(self) -> dict:
        """State dump for /debug/migration."""
        return {
            "draining": self.draining,
            "epoch": self._epoch,
            "in_flight": sorted(self._migrating),
            "attempts": {
                n: {"epoch": a.epoch, "target": a.target[:12]}
                for n, a in self._attempts.items()
            },
            "adoptions": {
                n: {"epoch": a.epoch, "source": a.source[:12], "row": a.row}
                for n, a in self._adoptions.items()
            },
            "stats": dict(self.stats),
        }
