"""Agent service: external AI-agent worker dispatch.

Reference parity: pkg/service/agentservice.go:40-508 — the /agent
WebSocket where agent workers register (namespace + job type), report
availability/status/load, and receive job offers; RoomManager asks for a
room agent on room start and a publisher agent on track publish (the
rtc.agentclient.go seat). Protocol here is JSON frames:

  worker → server: {"register": {...}}, {"availability": {job_id, available}},
                   {"status": {...}}, {"job_update": {...}}, {"ping": {}}
  server → worker: {"registered": {...}}, {"job_offer": {job}}, {"pong": {}}

Jobs carry a room join token so the agent connects back through /rtc like
any participant (kind=agent).
"""

from __future__ import annotations

import asyncio
import json
import time
from dataclasses import dataclass, field
from typing import TYPE_CHECKING

from aiohttp import WSMsgType, web

from livekit_server_tpu.auth import AccessToken, VideoGrant
from livekit_server_tpu.utils import ids

if TYPE_CHECKING:
    from livekit_server_tpu.service.server import LivekitServer

JT_ROOM = 0        # JT_ROOM — one agent per room
JT_PUBLISHER = 1   # JT_PUBLISHER — one agent per publishing participant


@dataclass
class AgentWorker:
    worker_id: str
    ws: web.WebSocketResponse
    namespace: str = "default"
    job_type: int | None = None   # None until the register frame arrives —
    # an unregistered worker must never be offered (or counted for) jobs
    load: float = 0.0
    status: int = 0          # 0 available, 1 full
    jobs: set = field(default_factory=set)
    registered_at: float = field(default_factory=time.time)


@dataclass
class AgentJob:
    job_id: str
    job_type: int
    room_name: str
    participant_identity: str = ""
    namespace: str = "default"
    state: str = "pending"    # pending | offered | running | done | failed
    worker_id: str = ""


class AgentService:
    def __init__(self, server: "LivekitServer"):
        self.server = server
        self.workers: dict[str, AgentWorker] = {}
        self.jobs: dict[str, AgentJob] = {}

    # -- worker socket ----------------------------------------------------
    async def handle(self, request: web.Request) -> web.StreamResponse:
        from livekit_server_tpu.auth import TokenError, verify_token

        token = request.query.get("access_token") or request.headers.get(
            "Authorization", ""
        ).removeprefix("Bearer ").strip()
        try:
            claims = verify_token(token, self.server.config.keys)
        except TokenError as e:
            return web.Response(status=401, text=str(e))
        if not claims.video.agent:
            return web.Response(status=401, text="token lacks agent grant")
        ws = web.WebSocketResponse(heartbeat=30)
        await ws.prepare(request)
        worker = AgentWorker(worker_id=ids.new_guid(ids.AGENT_WORKER_PREFIX), ws=ws)
        self.workers[worker.worker_id] = worker
        try:
            async for msg in ws:
                if msg.type != WSMsgType.TEXT:
                    continue
                try:
                    frame = json.loads(msg.data)
                except json.JSONDecodeError:
                    continue
                await self._handle_frame(worker, frame)
        finally:
            self.workers.pop(worker.worker_id, None)
            for job_id in list(worker.jobs):
                job = self.jobs.get(job_id)
                if job is None:
                    continue
                if job.state == "offered":
                    # Never answered: try the remaining workers.
                    job.state = "pending"
                    await self._dispatch(job, exclude={worker.worker_id})
                elif job.state == "running":
                    job.state = "failed"  # worker died mid-job (drain/crash)
        return ws

    async def _handle_frame(self, worker: AgentWorker, frame: dict) -> None:
        if "register" in frame:
            reg = frame["register"] or {}
            worker.namespace = reg.get("namespace", "default")
            worker.job_type = int(reg.get("job_type", JT_ROOM))
            await worker.ws.send_str(
                json.dumps({"registered": {"worker_id": worker.worker_id}})
            )
        elif "availability" in frame:
            av = frame["availability"] or {}
            job = self.jobs.get(av.get("job_id", ""))
            if job is None:
                return
            if av.get("available", False):
                job.state = "running"
                job.worker_id = worker.worker_id
                worker.jobs.add(job.job_id)
            else:
                worker.jobs.discard(job.job_id)
                job.state = "pending"   # re-dispatch to another worker
                await self._dispatch(job, exclude={worker.worker_id})
        elif "status" in frame:
            st = frame["status"] or {}
            worker.load = float(st.get("load", 0.0))
            worker.status = int(st.get("status", 0))
        elif "job_update" in frame:
            upd = frame["job_update"] or {}
            job = self.jobs.get(upd.get("job_id", ""))
            if job is not None and upd.get("state") in ("done", "failed"):
                job.state = upd["state"]
                worker.jobs.discard(job.job_id)
        elif "ping" in frame:
            await worker.ws.send_str(json.dumps({"pong": {}}))

    # -- job dispatch (agentservice.go job assignment + affinity) --------
    async def launch_room_job(self, room_name: str) -> AgentJob | None:
        return await self._launch(JT_ROOM, room_name)

    async def launch_publisher_job(self, room_name: str, identity: str) -> AgentJob | None:
        return await self._launch(JT_PUBLISHER, room_name, identity)

    async def _launch(self, job_type: int, room_name: str, identity: str = "") -> AgentJob | None:
        if not any(w.job_type == job_type for w in self.workers.values()):
            return None
        job = AgentJob(
            job_id=ids.new_guid(ids.AGENT_JOB_PREFIX),
            job_type=job_type,
            room_name=room_name,
            participant_identity=identity,
        )
        self.jobs[job.job_id] = job
        await self._dispatch(job)
        return job

    async def _dispatch(self, job: AgentJob, exclude: set | None = None) -> None:
        exclude = exclude or set()
        candidates = [
            w
            for w in self.workers.values()
            if w.job_type == job.job_type and w.status == 0 and w.worker_id not in exclude
        ]
        if not candidates:
            return
        worker = min(candidates, key=lambda w: w.load)  # least-loaded affinity
        job.state = "offered"
        # Track the offer so a worker that dies before answering triggers
        # re-dispatch from the disconnect cleanup.
        worker.jobs.add(job.job_id)
        key = next(iter(self.server.config.keys), "")
        tok = AccessToken(key, self.server.config.keys.get(key, ""))
        tok.identity = f"agent-{job.job_id}"
        tok.kind = "agent"
        tok.grant = VideoGrant(room_join=True, room=job.room_name, agent=True)
        await worker.ws.send_str(
            json.dumps(
                {
                    "job_offer": {
                        "job": vars(job),
                        "token": tok.to_jwt(),
                        "url": f"ws://127.0.0.1:{self.server.config.port}/rtc",
                    }
                }
            )
        )
