"""/rtc WebSocket endpoint: signaling + media framing.

Reference parity: pkg/service/rtcservice.go (validate :106-194, ServeHTTP
:196-440, startConnection :527) — token validation, room allocation via the
router, then a bidirectional pump between the socket and the participant's
MessageChannels.

Transport re-design: the reference splits signal (WS) from media (WebRTC/
UDP via Pion). This build multiplexes both on the one WebSocket: TEXT
frames carry JSON signal messages (protocol/signal.py), BINARY frames carry
msgpack media packets (header fields + payload) that land in the node's
IngestBuffer — and subscriber egress returns as msgpack BINARY frames. A
native UDP media path can bind the same ingest seam (runtime/ingest.py)
without touching this service.
"""

from __future__ import annotations

import asyncio
import json
from typing import TYPE_CHECKING

import msgpack
from aiohttp import WSMsgType, web

from livekit_server_tpu.auth import TokenError, verify_token
from livekit_server_tpu.protocol import signal as sigproto
from livekit_server_tpu.routing.messagechannel import ChannelClosed, ChannelFull
from livekit_server_tpu.routing.router import ParticipantInit
from livekit_server_tpu.runtime.ingest import PacketIn

if TYPE_CHECKING:
    from livekit_server_tpu.service.server import LivekitServer


class RTCService:
    def __init__(self, server: "LivekitServer"):
        self.server = server
        self.connections = 0

    async def handle(self, request: web.Request) -> web.StreamResponse:
        # -- validate (rtcservice.go:106) --------------------------------
        token = request.query.get("access_token") or request.headers.get(
            "Authorization", ""
        ).removeprefix("Bearer ").strip()
        try:
            claims = verify_token(token, self.server.config.keys)
        except TokenError as e:
            return web.Response(status=401, text=str(e))
        if not claims.video.room_join:
            return web.Response(status=401, text="token lacks roomJoin")
        room_name = request.query.get("room") or claims.video.room
        if not room_name:
            return web.Response(status=400, text="room required")
        if claims.video.room and room_name != claims.video.room:
            return web.Response(status=401, text="token not valid for room")
        if not claims.identity:
            return web.Response(status=400, text="identity required")
        auto_subscribe = request.query.get("auto_subscribe", "1") not in ("0", "false")

        # -- route (rtcservice.go startConnection :527) -------------------
        router = self.server.router
        node_id = await router.get_node_for_room(room_name)
        if node_id and node_id != router.local_node.node_id:
            # Dead-node takeover (redisrouter RemoveDeadNodes + the
            # multinode shutdown-reconnect flow): a room pinned to a
            # REMOTE node that stopped heartbeating is re-homed through a
            # setnx-serialized race so concurrent joins on different live
            # nodes can't split-brain the room. (A local pin needs no
            # registry check — we are obviously alive.)
            if not await router.is_node_alive(node_id):
                node_id = await router.try_takeover(room_name, node_id)
        if not node_id:
            if not self.server.config.room.auto_create:
                # ValidateCreateRoom (roomallocator.go:147): with
                # auto-create off, an admin-created room (store record,
                # no pin yet) must still be joinable; only a room that
                # exists nowhere is a 404.
                if await self.server.store.load_room(room_name) is None:
                    return web.Response(status=404, text="room not found")
            node = self.server.select_node()
            if node is None:
                return web.Response(status=503, text="no nodes available")
            await router.set_node_for_room(room_name, node.node_id)
        # ClientInfo rides the connect query (SDKs send sdk/version/os/...;
        # rtcservice.go ParseClientInfo) → clientconfiguration matching.
        client_info = {
            k: request.query[k]
            for k in ("sdk", "version", "protocol", "os", "os_version",
                      "browser", "browser_version", "device_model")
            if k in request.query
        }
        init = ParticipantInit(
            identity=claims.identity,
            name=claims.name,
            auto_subscribe=auto_subscribe,
            reconnect=request.query.get("reconnect") == "1",
            grants={"video": claims.video.to_claim()},
            client_info=client_info or None,
        )
        try:
            cid, req_sink, resp_source = await router.start_participant_signal(room_name, init)
        except Exception as e:  # noqa: BLE001 — surface as 503 like the reference
            return web.Response(status=503, text=f"signal start failed: {e}")

        # -- websocket pump (rtcservice.go:283-439) -----------------------
        # Signal wire negotiation (wsprotocol.go JSON-vs-protobuf seat):
        # `?signal=binary` or WS subprotocol "signal-binary" selects the
        # compact msgpack signal framing; JSON TEXT remains the default.
        # Either way the session plumbing sees JSON — transcoding happens
        # here at the edge.
        ws = web.WebSocketResponse(
            heartbeat=30, protocols=("signal-json", "signal-binary")
        )
        await ws.prepare(request)
        binary_signal = (
            request.query.get("signal") == "binary"
            or ws.ws_protocol == "signal-binary"
        )
        self.connections += 1
        pump = asyncio.ensure_future(
            self._pump_responses(
                ws, resp_source, room_name, claims.identity, binary_signal
            )
        )
        try:
            async for msg in ws:
                if msg.type == WSMsgType.TEXT:
                    try:
                        req_sink.write_message(msg.data)
                    except (ChannelFull, ChannelClosed):
                        break
                elif msg.type == WSMsgType.BINARY:
                    if sigproto.is_binary_signal_frame(msg.data):
                        try:
                            req = sigproto.decode_signal_request_bin(msg.data)
                            req_sink.write_message(
                                sigproto.encode_signal_request(req)
                            )
                        except (ValueError, TypeError):
                            # malformed frame, or a payload JSON can't carry
                            # (raw bytes in a map value): drop
                            pass
                        except (ChannelFull, ChannelClosed):
                            break
                        continue
                    self._ingest_media(room_name, claims.identity, msg.data)
                elif msg.type in (WSMsgType.CLOSE, WSMsgType.ERROR):
                    break
        finally:
            self.connections -= 1
            req_sink.close()
            pump.cancel()
        return ws

    async def _pump_responses(
        self, ws, resp_source, room_name: str, identity: str,
        binary_signal: bool = False,
    ) -> None:
        """Server→client: signal as TEXT JSON (or tagged BINARY msgpack in
        binary mode); media deliveries as BINARY."""
        sig_t: asyncio.Task | None = None
        med_t: asyncio.Task | None = None
        try:
            while True:
                # Media queue appears once the session handler created the
                # participant (same-node rooms only; cross-node media binds
                # to the hosting node's own /rtc socket).
                media_q = self.server.room_manager_media_queue(room_name, identity)
                if sig_t is None:
                    sig_t = asyncio.ensure_future(resp_source.read_message())
                if media_q is not None and med_t is None:
                    med_t = asyncio.ensure_future(media_q.get())
                tasks = {sig_t} | ({med_t} if med_t is not None else set())
                done, _pending = await asyncio.wait(
                    tasks, return_when=asyncio.FIRST_COMPLETED, timeout=0.25
                )
                if sig_t in done:
                    data = sig_t.result()
                    sig_t = None
                    if binary_signal:
                        await ws.send_bytes(
                            sigproto.encode_signal_response_bin(
                                sigproto.decode_signal_response(data)
                            )
                        )
                    else:
                        await ws.send_str(data)
                if med_t is not None and med_t in done:
                    data = med_t.result()
                    med_t = None
                    await ws.send_bytes(data)
        except (ChannelClosed, ConnectionError, asyncio.CancelledError):
            pass
        finally:
            for t in (sig_t, med_t):
                if t is not None:
                    t.cancel()
            if not ws.closed:
                await ws.close()

    def _ingest_media(self, room_name: str, identity: str, data: bytes) -> None:
        """BINARY media frame → IngestBuffer (the transport→buffer seam)."""
        rm = self.server.room_manager
        room = rm.rooms.get(room_name)
        if room is None:
            return
        participant = room.participants.get(identity)
        if participant is None:
            return
        try:
            frame = msgpack.unpackb(data, raw=False)
        except Exception:  # noqa: BLE001 — malformed frame: drop
            return
        cid = frame.get("cid", "")
        track_sid = frame.get("track_sid", "")
        track = None
        if track_sid:
            track = participant.published.get(track_sid)
        if track is None and cid:
            track = participant.publish_pending(cid)  # first media binds it
            if track is None and cid in participant.pending_tracks:
                return  # no capacity yet
            for t in participant.published.values():
                if t.cid == cid:
                    track = t
                    break
        if track is None:
            return
        rm.runtime.ingest.push(
            PacketIn(
                room=room.slots.row,
                track=track.track_col,
                sn=frame.get("sn", 0),
                ts=frame.get("ts", 0),
                size=len(frame.get("payload", b"")),
                payload=frame.get("payload", b""),
                layer=frame.get("layer", 0),
                temporal=frame.get("temporal", 0),
                keyframe=frame.get("keyframe", False),
                layer_sync=frame.get("layer_sync", frame.get("keyframe", False)),
                begin_pic=frame.get("begin_pic", False),
                pid=frame.get("pid", 0),
                tl0=frame.get("tl0", 0),
                keyidx=frame.get("keyidx", 0),
                frame_ms=frame.get("frame_ms", 20),
                audio_level=frame.get("audio_level", 127),
                arrival_rtp=frame.get("ts", 0),
            )
        )
