"""SIP service: telephony trunk / dispatch-rule / participant API.

Reference parity: pkg/service/sip.go:30-248 — the livekit.SIP Twirp API:
trunk CRUD (CreateSIPTrunk/ListSIPTrunk/DeleteSIPTrunk), dispatch-rule
CRUD, CreateSIPParticipant (outbound call → room participant via an
external SIP worker over the bus) and TransferSIPParticipant. State in
memory + store; job dispatch on `sip_jobs` (the psrpc seat).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import TYPE_CHECKING

from aiohttp import web

from livekit_server_tpu.utils import ids

if TYPE_CHECKING:
    from livekit_server_tpu.service.server import LivekitServer


@dataclass
class SIPTrunk:
    sip_trunk_id: str = ""
    name: str = ""
    kind: str = "inbound"     # inbound | outbound
    numbers: list[str] = field(default_factory=list)
    allowed_addresses: list[str] = field(default_factory=list)
    allowed_numbers: list[str] = field(default_factory=list)
    auth_username: str = ""
    auth_password: str = ""
    outbound_address: str = ""

    def to_dict(self):
        return dict(vars(self))


@dataclass
class SIPDispatchRule:
    sip_dispatch_rule_id: str = ""
    name: str = ""
    trunk_ids: list[str] = field(default_factory=list)
    rule: dict = field(default_factory=dict)   # direct {room} | individual {room_prefix}
    hide_phone_number: bool = False

    def to_dict(self):
        return dict(vars(self))


class SIPService:
    PREFIX = "/twirp/livekit.SIP/"
    JOBS_TOPIC = "sip_jobs"

    def __init__(self, server: "LivekitServer"):
        self.server = server
        self.trunks: dict[str, SIPTrunk] = {}
        self.rules: dict[str, SIPDispatchRule] = {}
        self.calls: dict[str, dict] = {}

    async def handle(self, request: web.Request) -> web.Response:
        from livekit_server_tpu.auth import TokenError, verify_token

        method = request.path.removeprefix(self.PREFIX)
        token = request.headers.get("Authorization", "").removeprefix("Bearer ").strip()
        try:
            claims = verify_token(token, self.server.config.keys)
        except TokenError as e:
            return web.json_response({"msg": str(e)}, status=401)
        if not claims.video.room_admin:
            return web.json_response({"msg": "requires roomAdmin"}, status=403)
        try:
            body = await request.json()
        except json.JSONDecodeError:
            body = {}
        # Scoping policy: SIP trunk/dispatch-rule management is node-global,
        # so it needs an UNscoped admin token (no `room` claim). A token
        # minted as admin of one room may only dial/transfer SIP
        # participants into that room — it must not manage trunks or reach
        # other rooms (room-scoped analog of auth.go EnsureAdminPermission).
        scoped_room = claims.video.room
        if scoped_room:
            room_targeted = method in ("CreateSIPParticipant", "TransferSIPParticipant")
            if not room_targeted or body.get("room_name", "") != scoped_room:
                return web.json_response(
                    {"msg": "token is scoped to one room; requires unscoped roomAdmin"},
                    status=403,
                )

        if method in ("CreateSIPTrunk", "CreateSIPInboundTrunk", "CreateSIPOutboundTrunk"):
            trunk = SIPTrunk(
                sip_trunk_id=ids.new_guid(ids.SIP_TRUNK_PREFIX),
                name=body.get("name", ""),
                kind="outbound" if "Outbound" in method else "inbound",
                numbers=body.get("numbers", []),
                allowed_addresses=body.get("allowed_addresses", []),
                allowed_numbers=body.get("allowed_numbers", []),
                auth_username=body.get("auth_username", ""),
                auth_password=body.get("auth_password", ""),
                outbound_address=body.get("address", ""),
            )
            self.trunks[trunk.sip_trunk_id] = trunk
            return web.json_response(trunk.to_dict())
        if method in ("ListSIPTrunk", "ListSIPInboundTrunk", "ListSIPOutboundTrunk"):
            return web.json_response({"items": [t.to_dict() for t in self.trunks.values()]})
        if method == "DeleteSIPTrunk":
            t = self.trunks.pop(body.get("sip_trunk_id", ""), None)
            if t is None:
                return web.json_response({"msg": "trunk not found"}, status=404)
            return web.json_response(t.to_dict())
        if method == "CreateSIPDispatchRule":
            rule = SIPDispatchRule(
                sip_dispatch_rule_id=ids.new_guid(ids.SIP_DISPATCH_RULE_PREFIX),
                name=body.get("name", ""),
                trunk_ids=body.get("trunk_ids", []),
                rule=body.get("rule", {}),
                hide_phone_number=bool(body.get("hide_phone_number", False)),
            )
            self.rules[rule.sip_dispatch_rule_id] = rule
            return web.json_response(rule.to_dict())
        if method == "ListSIPDispatchRule":
            return web.json_response({"items": [r.to_dict() for r in self.rules.values()]})
        if method == "DeleteSIPDispatchRule":
            r = self.rules.pop(body.get("sip_dispatch_rule_id", ""), None)
            if r is None:
                return web.json_response({"msg": "rule not found"}, status=404)
            return web.json_response(r.to_dict())
        if method == "CreateSIPParticipant":
            trunk = self.trunks.get(body.get("sip_trunk_id", ""))
            if trunk is None:
                return web.json_response({"msg": "trunk not found"}, status=404)
            call = {
                "sip_call_id": ids.new_guid(ids.SIP_CALL_PREFIX),
                "participant_identity": body.get("participant_identity", ""),
                "room_name": body.get("room_name", ""),
                "sip_call_to": body.get("sip_call_to", ""),
                "dtmf": body.get("dtmf", ""),
            }
            self.calls[call["sip_call_id"]] = call
            dispatched = await self._publish({"kind": "dial", "trunk": trunk.to_dict(), "call": call})
            if not dispatched:
                return web.json_response({"msg": "no SIP workers available"}, status=503)
            return web.json_response(call)
        if method == "TransferSIPParticipant":
            await self._publish({"kind": "transfer", "request": body})
            return web.json_response({})
        return web.json_response({"msg": f"unknown method {method}"}, status=404)

    async def _publish(self, job: dict) -> int:
        bus = getattr(self.server.router, "bus", None)
        if bus is None:
            return 0
        return await bus.publish(self.JOBS_TOPIC, json.dumps(job))
