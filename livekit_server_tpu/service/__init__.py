"""Service layer: HTTP/WS APIs, room management, server assembly.

Reference parity: pkg/service (SURVEY.md §2.2) — LivekitServer (HTTP mux +
lifecycle), RTCService (/rtc WebSocket), RoomManager (per-node room
registry + session workers), RoomService (Twirp admin API), object stores,
webhooks. The media-plane difference: RoomManager owns ONE PlaneRuntime
for the node, and a tick dispatcher fans TickResults out to rooms — the
reference instead wires per-room BufferFactories into Pion
(roommanager.go:350).
"""

from livekit_server_tpu.service.roommanager import RoomManager
from livekit_server_tpu.service.server import LivekitServer, create_server
from livekit_server_tpu.service.store import LocalStore, ObjectStore

__all__ = ["LivekitServer", "LocalStore", "ObjectStore", "RoomManager", "create_server"]
