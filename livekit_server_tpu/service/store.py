"""Room/participant object store.

Reference parity: pkg/service/interfaces.go ObjectStore +
localstore.go:28-170 (in-memory, single-node) + redisstore.go:67-944
(KV-backed, multi-node, with distributed room lock). The KV variant rides
the routing MessageBus so multi-node tests run N stores over one
MemoryBus, like the reference's multi-node tests over one Redis.
"""

from __future__ import annotations

import json
import time
from typing import Protocol

from livekit_server_tpu.protocol import models as pm
from livekit_server_tpu.routing.kv import MessageBus


class ObjectStore(Protocol):
    async def store_room(self, room: pm.RoomInfo) -> None: ...
    async def load_room(self, name: str) -> pm.RoomInfo | None: ...
    async def delete_room(self, name: str) -> None: ...
    async def list_rooms(self, names: list[str] | None = None) -> list[pm.RoomInfo]: ...
    async def store_participant(self, room: str, p: pm.ParticipantInfo) -> None: ...
    async def load_participant(self, room: str, identity: str) -> pm.ParticipantInfo | None: ...
    async def delete_participant(self, room: str, identity: str) -> None: ...
    async def list_participants(self, room: str) -> list[pm.ParticipantInfo]: ...
    async def lock_room(self, name: str, ttl: float = 5.0) -> bool: ...
    async def unlock_room(self, name: str) -> None: ...


class LocalStore:
    """localstore.go — maps guarded by the event loop (no locks needed)."""

    def __init__(self):
        self.rooms: dict[str, pm.RoomInfo] = {}
        self.participants: dict[str, dict[str, pm.ParticipantInfo]] = {}
        self._locks: dict[str, float] = {}

    async def store_room(self, room: pm.RoomInfo) -> None:
        self.rooms[room.name] = room

    async def load_room(self, name: str) -> pm.RoomInfo | None:
        return self.rooms.get(name)

    async def delete_room(self, name: str) -> None:
        self.rooms.pop(name, None)
        self.participants.pop(name, None)

    async def list_rooms(self, names: list[str] | None = None) -> list[pm.RoomInfo]:
        if names is None:
            return list(self.rooms.values())
        return [r for n, r in self.rooms.items() if n in names]

    async def store_participant(self, room: str, p: pm.ParticipantInfo) -> None:
        self.participants.setdefault(room, {})[p.identity] = p

    async def load_participant(self, room: str, identity: str) -> pm.ParticipantInfo | None:
        return self.participants.get(room, {}).get(identity)

    async def delete_participant(self, room: str, identity: str) -> None:
        self.participants.get(room, {}).pop(identity, None)

    async def list_participants(self, room: str) -> list[pm.ParticipantInfo]:
        return list(self.participants.get(room, {}).values())

    async def lock_room(self, name: str, ttl: float = 5.0) -> bool:
        now = time.monotonic()
        if self._locks.get(name, 0) > now:
            return False
        self._locks[name] = now + ttl
        return True

    async def unlock_room(self, name: str) -> None:
        self._locks.pop(name, None)


class KVStore:
    """redisstore.go over the MessageBus (hashes + setnx lock)."""

    ROOMS = "rooms"

    def __init__(self, bus: MessageBus):
        self.bus = bus

    async def store_room(self, room: pm.RoomInfo) -> None:
        await self.bus.hset(self.ROOMS, room.name, json.dumps(room.to_dict()))

    async def load_room(self, name: str) -> pm.RoomInfo | None:
        raw = await self.bus.hget(self.ROOMS, name)
        return pm.RoomInfo.from_dict(json.loads(raw)) if raw else None

    async def delete_room(self, name: str) -> None:
        await self.bus.hdel(self.ROOMS, name)
        parts = await self.bus.hgetall(f"room_participants:{name}")
        for identity in parts:
            await self.bus.hdel(f"room_participants:{name}", identity)

    async def list_rooms(self, names: list[str] | None = None) -> list[pm.RoomInfo]:
        raw = await self.bus.hgetall(self.ROOMS)
        rooms = [pm.RoomInfo.from_dict(json.loads(v)) for v in raw.values()]
        if names is not None:
            rooms = [r for r in rooms if r.name in names]
        return rooms

    async def store_participant(self, room: str, p: pm.ParticipantInfo) -> None:
        await self.bus.hset(f"room_participants:{room}", p.identity, json.dumps(p.to_dict()))

    async def load_participant(self, room: str, identity: str) -> pm.ParticipantInfo | None:
        raw = await self.bus.hget(f"room_participants:{room}", identity)
        return pm.ParticipantInfo.from_dict(json.loads(raw)) if raw else None

    async def delete_participant(self, room: str, identity: str) -> None:
        await self.bus.hdel(f"room_participants:{room}", identity)

    async def list_participants(self, room: str) -> list[pm.ParticipantInfo]:
        raw = await self.bus.hgetall(f"room_participants:{room}")
        return [pm.ParticipantInfo.from_dict(json.loads(v)) for v in raw.values()]

    async def lock_room(self, name: str, ttl: float = 5.0) -> bool:
        return await self.bus.setnx(f"room_lock:{name}", "1", ttl)

    async def unlock_room(self, name: str) -> None:
        await self.bus.delete(f"room_lock:{name}")
