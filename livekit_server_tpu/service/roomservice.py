"""RoomService: the Twirp-style admin HTTP API.

Reference parity: pkg/service/roomservice.go:34-331 — the eleven
livekit.RoomService RPCs (CreateRoom, ListRooms, DeleteRoom,
ListParticipants, GetParticipant, RemoveParticipant, MutePublishedTrack,
UpdateParticipant, UpdateSubscriptions, SendData, UpdateRoomMetadata),
served at POST /twirp/livekit.RoomService/<Method> with JSON bodies and
Bearer-token auth, same wire shape as the reference's Twirp JSON mode. In
multi-node mode the reference forwards to the hosting node over psrpc;
here ops on non-hosted rooms return 404 unless this node hosts them (the
KV router's session relay covers joins; admin-op relay lands with the
psrpc-equivalent RPC layer).
"""

from __future__ import annotations

import json
from typing import TYPE_CHECKING

from aiohttp import web

from livekit_server_tpu.auth import (
    TokenError,
    ensure_admin_permission,
    ensure_create_permission,
    ensure_list_permission,
    verify_token,
)
from livekit_server_tpu.protocol import models as pm

if TYPE_CHECKING:
    from livekit_server_tpu.service.server import LivekitServer


def _err(status: int, msg: str) -> web.Response:
    return web.json_response({"code": "error", "msg": msg}, status=status)


class RoomServiceAPI:
    PREFIX = "/twirp/livekit.RoomService/"

    def __init__(self, server: "LivekitServer"):
        self.server = server

    async def handle(self, request: web.Request) -> web.Response:
        method = request.path.removeprefix(self.PREFIX)
        token = request.headers.get("Authorization", "").removeprefix("Bearer ").strip()
        try:
            claims = verify_token(token, self.server.config.keys)
        except TokenError as e:
            return _err(401, str(e))
        try:
            body = await request.json()
        except json.JSONDecodeError:
            body = {}
        handler = getattr(self, f"_rpc_{method}", None)
        if handler is None:
            return _err(404, f"unknown method {method}")
        # Permission guards, matching the reference per-RPC
        # (roomservice.go:79,142,165,174-271): CreateRoom/DeleteRoom need
        # roomCreate, ListRooms needs roomList, and every participant/room
        # mutation needs roomAdmin *scoped to the target room* — a token
        # minted as admin of room A must not administrate room B.
        if method in ("CreateRoom", "DeleteRoom"):
            if not ensure_create_permission(claims):
                return _err(403, "requires roomCreate")
        elif method == "ListRooms":
            if not ensure_list_permission(claims):
                return _err(403, "requires roomList")
        else:
            target = body.get("room", "")
            if not ensure_admin_permission(claims, target):
                return _err(403, "requires roomAdmin for this room")
        return await handler(body)

    # -- RPCs -------------------------------------------------------------
    async def _rpc_CreateRoom(self, body: dict) -> web.Response:
        from livekit_server_tpu.runtime import CapacityError

        name = body.get("name", "")
        if not name:
            return _err(400, "name required")
        info = pm.RoomInfo(
            name=name,
            empty_timeout=body.get("empty_timeout", self.server.config.room.empty_timeout_s),
            departure_timeout=body.get("departure_timeout", self.server.config.room.departure_timeout_s),
            max_participants=body.get("max_participants", 0),
            metadata=body.get("metadata", ""),
        )
        try:
            room = await self.server.room_manager.get_or_create_room(name, info=info)
        except CapacityError as e:
            # node room-tensor full (reference: explicit limits-reached
            # rejection rather than a raw 500 — roomallocator.go)
            return _err(503, f"node at capacity: {e}")
        return web.json_response(room.info.to_dict())

    async def _rpc_ListRooms(self, body: dict) -> web.Response:
        names = body.get("names") or None
        rooms = await self.server.store.list_rooms(names)
        return web.json_response({"rooms": [r.to_dict() for r in rooms]})

    async def _rpc_DeleteRoom(self, body: dict) -> web.Response:
        name = body.get("room", "")
        if not name:
            return _err(400, "room required")
        await self.server.room_manager.delete_room(name)
        return web.json_response({})

    def _room(self, body: dict):
        return self.server.room_manager.rooms.get(body.get("room", ""))

    async def _rpc_ListParticipants(self, body: dict) -> web.Response:
        room = self._room(body)
        if room is None:
            return _err(404, "room not found")
        return web.json_response(
            {"participants": [p.to_info().to_dict() for p in room.participants.values()]}
        )

    async def _rpc_GetParticipant(self, body: dict) -> web.Response:
        room = self._room(body)
        p = room.participants.get(body.get("identity", "")) if room else None
        if p is None:
            return _err(404, "participant not found")
        return web.json_response(p.to_info().to_dict())

    async def _rpc_RemoveParticipant(self, body: dict) -> web.Response:
        room = self._room(body)
        p = room.participants.get(body.get("identity", "")) if room else None
        if p is None:
            return _err(404, "participant not found")
        room.remove_participant(p, pm.DisconnectReason.PARTICIPANT_REMOVED)
        return web.json_response({})

    async def _rpc_MutePublishedTrack(self, body: dict) -> web.Response:
        room = self._room(body)
        p = room.participants.get(body.get("identity", "")) if room else None
        if p is None:
            return _err(404, "participant not found")
        sid = body.get("track_sid", "")
        muted = bool(body.get("muted", False))
        p.set_track_muted(sid, muted)
        track = p.published.get(sid)
        return web.json_response({"track": track.info.to_dict() if track else {}})

    async def _rpc_UpdateParticipant(self, body: dict) -> web.Response:
        room = self._room(body)
        p = room.participants.get(body.get("identity", "")) if room else None
        if p is None:
            return _err(404, "participant not found")
        if "metadata" in body:
            p.metadata = body["metadata"]
        if body.get("attributes"):
            p.attributes.update(body["attributes"])
        if body.get("permission"):
            p.set_permission(pm.ParticipantPermission.from_dict(body["permission"]))
        if "name" in body:
            p.name = body["name"]
        p.version += 1
        room.broadcast_participant_state(p)
        return web.json_response(p.to_info().to_dict())

    async def _rpc_UpdateSubscriptions(self, body: dict) -> web.Response:
        room = self._room(body)
        p = room.participants.get(body.get("identity", "")) if room else None
        if p is None:
            return _err(404, "participant not found")
        subscribe = bool(body.get("subscribe", True))
        for sid in body.get("track_sids", []):
            if subscribe:
                room.subscribe(p, sid)
            else:
                room.unsubscribe(p, sid)
        return web.json_response({})

    async def _rpc_SendData(self, body: dict) -> web.Response:
        room = self._room(body)
        if room is None:
            return _err(404, "room not found")
        room.broadcast_data(
            None,
            payload=body.get("data", ""),
            kind=body.get("kind", 0),
            destination_sids=body.get("destination_sids") or None,
            topic=body.get("topic", ""),
        )
        return web.json_response({})

    async def _rpc_UpdateRoomMetadata(self, body: dict) -> web.Response:
        room = self._room(body)
        if room is None:
            return _err(404, "room not found")
        if "metadata" not in body:
            return _err(400, "metadata required")
        room.info.metadata = body["metadata"]
        await self.server.store.store_room(room.info)
        for p in room.participants.values():
            p.send("room_update", {"room": room.info.to_dict()})
        return web.json_response(room.info.to_dict())
