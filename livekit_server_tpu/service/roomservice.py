"""RoomService: the Twirp-style admin HTTP API.

Reference parity: pkg/service/roomservice.go:34-331 — the eleven
livekit.RoomService RPCs (CreateRoom, ListRooms, DeleteRoom,
ListParticipants, GetParticipant, RemoveParticipant, MutePublishedTrack,
UpdateParticipant, UpdateSubscriptions, SendData, UpdateRoomMetadata),
served at POST /twirp/livekit.RoomService/<Method> with JSON bodies and
Bearer-token auth, same wire shape as the reference's Twirp JSON mode. In
multi-node mode, ops on rooms hosted elsewhere are relayed to the hosting
node over the cluster bus (the reference's psrpc RTC-node RPC;
multinode_roomservice_test.go) and the response mirrored back.
"""

from __future__ import annotations

import json
from typing import TYPE_CHECKING

from aiohttp import web

from livekit_server_tpu.auth import (
    TokenError,
    ensure_admin_permission,
    ensure_create_permission,
    ensure_list_permission,
    verify_token,
)
from livekit_server_tpu.protocol import models as pm

if TYPE_CHECKING:
    from livekit_server_tpu.service.server import LivekitServer


def _err(status: int, msg: str) -> web.Response:
    return web.json_response({"code": "error", "msg": msg}, status=status)


class RoomServiceAPI:
    PREFIX = "/twirp/livekit.RoomService/"
    # RPCs that act on live room/participant state and must execute on the
    # node HOSTING the room (multinode_roomservice_test.go: admin ops hit
    # the non-hosting node and are relayed — the reference's RTC-node RPC).
    ROOM_SCOPED = frozenset({
        "DeleteRoom", "ListParticipants", "GetParticipant",
        "RemoveParticipant", "MutePublishedTrack", "UpdateParticipant",
        "UpdateSubscriptions", "SendData", "UpdateRoomMetadata",
    })

    def __init__(self, server: "LivekitServer"):
        self.server = server
        self._rpc_sub = None
        self._rpc_task = None

    # -- cross-node forwarding -------------------------------------------
    async def start(self) -> None:
        """Subscribe to this node's admin-RPC channel (hosting side)."""
        bus = getattr(self.server.router, "bus", None)
        if bus is None:
            return
        import asyncio

        node_id = self.server.router.local_node.node_id
        self._rpc_sub = bus.subscribe(f"admin_rpc:{node_id}")

        tasks: set = set()

        async def serve_one(req: dict, rid: str) -> None:
            try:
                handler = getattr(self, f"_rpc_{req.get('method', '')}", None)
                if handler is None:
                    resp = {"status": 404, "body": "unknown method"}
                else:
                    r = await handler(req.get("body") or {})
                    resp = {"status": r.status, "body": r.text}
            except Exception as e:  # noqa: BLE001 — a failing handler must
                # not take the relay down; the caller sees the 500.
                resp = {"status": 500, "body": str(e)}
            await bus.publish(f"admin_rpc_resp:{rid}", json.dumps(resp))

        async def worker():
            async for raw in self._rpc_sub:
                try:
                    req = json.loads(raw)
                    rid = req.get("id", "")
                except (ValueError, TypeError):
                    continue  # malformed frame: no id to answer to
                if not rid:
                    continue
                # Concurrent per-request tasks: one slow DeleteRoom must
                # not head-of-line-block other nodes' forwarded RPCs past
                # _forward's timeout.
                t = asyncio.ensure_future(serve_one(req, rid))
                tasks.add(t)
                t.add_done_callback(tasks.discard)

        self._rpc_task = asyncio.ensure_future(worker())

    async def stop(self) -> None:
        if self._rpc_sub is not None:
            self._rpc_sub.close()
        if self._rpc_task is not None:
            self._rpc_task.cancel()

    async def _forward(self, node_id: str, method: str, body: dict) -> web.Response:
        """Relay an admin RPC to the hosting node and mirror its response
        (the Twirp caller never sees which node served it)."""
        import asyncio

        from livekit_server_tpu.utils import ids

        bus = self.server.router.bus
        rpc_id = ids.new_connection_id()
        sub = bus.subscribe(f"admin_rpc_resp:{rpc_id}")
        try:
            await bus.publish(
                f"admin_rpc:{node_id}",
                json.dumps({"id": rpc_id, "method": method, "body": body}),
            )
            try:
                raw = await sub.read(timeout=5.0)
            except asyncio.TimeoutError:
                return _err(504, f"hosting node {node_id[:12]} did not answer")
            resp = json.loads(raw)
            return web.Response(
                status=resp["status"], text=resp["body"],
                content_type="application/json",
            )
        finally:
            sub.close()

    async def handle(self, request: web.Request) -> web.Response:
        method = request.path.removeprefix(self.PREFIX)
        token = request.headers.get("Authorization", "").removeprefix("Bearer ").strip()
        try:
            claims = verify_token(token, self.server.config.keys)
        except TokenError as e:
            return _err(401, str(e))
        try:
            body = await request.json()
        except json.JSONDecodeError:
            body = {}
        handler = getattr(self, f"_rpc_{method}", None)
        if handler is None:
            return _err(404, f"unknown method {method}")
        # Permission guards, matching the reference per-RPC
        # (roomservice.go:79,142,165,174-271): CreateRoom/DeleteRoom need
        # roomCreate, ListRooms needs roomList, and every participant/room
        # mutation needs roomAdmin *scoped to the target room* — a token
        # minted as admin of room A must not administrate room B.
        if method in ("CreateRoom", "DeleteRoom"):
            if not ensure_create_permission(claims):
                return _err(403, "requires roomCreate")
        elif method == "ListRooms":
            if not ensure_list_permission(claims):
                return _err(403, "requires roomList")
        else:
            target = body.get("room", "")
            if not ensure_admin_permission(claims, target):
                return _err(403, "requires roomAdmin for this room")
        if method in self.ROOM_SCOPED:
            router = self.server.router
            name = body.get("room", "")
            node_id = await router.get_node_for_room(name)
            if (
                node_id
                and node_id != router.local_node.node_id
                and getattr(router, "bus", None) is not None
            ):
                if not await router.is_node_alive(node_id):
                    # Running the op LOCALLY against a room living on a
                    # (possibly just slow-heartbeating) other node would
                    # split-brain its state; a join re-homes the room via
                    # takeover, after which admin ops work again.
                    return _err(503, "hosting node unreachable")
                return await self._forward(node_id, method, body)
        return await handler(body)

    # -- RPCs -------------------------------------------------------------
    async def _rpc_CreateRoom(self, body: dict) -> web.Response:
        from livekit_server_tpu.runtime import CapacityError

        name = body.get("name", "")
        if not name:
            return _err(400, "name required")
        info = pm.RoomInfo(
            name=name,
            empty_timeout=body.get("empty_timeout", self.server.config.room.empty_timeout_s),
            departure_timeout=body.get("departure_timeout", self.server.config.room.departure_timeout_s),
            max_participants=body.get("max_participants", 0),
            metadata=body.get("metadata", ""),
        )
        try:
            room = await self.server.room_manager.get_or_create_room(name, info=info)
        except CapacityError as e:
            # node room-tensor full (reference: explicit limits-reached
            # rejection rather than a raw 500 — roomallocator.go)
            return _err(503, f"node at capacity: {e}")
        return web.json_response(room.info.to_dict())

    async def _rpc_ListRooms(self, body: dict) -> web.Response:
        names = body.get("names") or None
        rooms = await self.server.store.list_rooms(names)
        return web.json_response({"rooms": [r.to_dict() for r in rooms]})

    async def _rpc_DeleteRoom(self, body: dict) -> web.Response:
        name = body.get("room", "")
        if not name:
            return _err(400, "room required")
        await self.server.room_manager.delete_room(name)
        return web.json_response({})

    def _room(self, body: dict):
        return self.server.room_manager.rooms.get(body.get("room", ""))

    async def _rpc_ListParticipants(self, body: dict) -> web.Response:
        room = self._room(body)
        if room is None:
            return _err(404, "room not found")
        return web.json_response(
            {"participants": [p.to_info().to_dict() for p in room.participants.values()]}
        )

    async def _rpc_GetParticipant(self, body: dict) -> web.Response:
        room = self._room(body)
        p = room.participants.get(body.get("identity", "")) if room else None
        if p is None:
            return _err(404, "participant not found")
        return web.json_response(p.to_info().to_dict())

    async def _rpc_RemoveParticipant(self, body: dict) -> web.Response:
        room = self._room(body)
        p = room.participants.get(body.get("identity", "")) if room else None
        if p is None:
            return _err(404, "participant not found")
        room.remove_participant(p, pm.DisconnectReason.PARTICIPANT_REMOVED)
        return web.json_response({})

    async def _rpc_MutePublishedTrack(self, body: dict) -> web.Response:
        room = self._room(body)
        p = room.participants.get(body.get("identity", "")) if room else None
        if p is None:
            return _err(404, "participant not found")
        sid = body.get("track_sid", "")
        muted = bool(body.get("muted", False))
        p.set_track_muted(sid, muted)
        track = p.published.get(sid)
        return web.json_response({"track": track.info.to_dict() if track else {}})

    async def _rpc_UpdateParticipant(self, body: dict) -> web.Response:
        room = self._room(body)
        p = room.participants.get(body.get("identity", "")) if room else None
        if p is None:
            return _err(404, "participant not found")
        if "metadata" in body:
            p.metadata = body["metadata"]
        if body.get("attributes"):
            p.attributes.update(body["attributes"])
        if body.get("permission"):
            p.set_permission(pm.ParticipantPermission.from_dict(body["permission"]))
        if "name" in body:
            p.name = body["name"]
        p.version += 1
        room.broadcast_participant_state(p)
        return web.json_response(p.to_info().to_dict())

    async def _rpc_UpdateSubscriptions(self, body: dict) -> web.Response:
        room = self._room(body)
        p = room.participants.get(body.get("identity", "")) if room else None
        if p is None:
            return _err(404, "participant not found")
        subscribe = bool(body.get("subscribe", True))
        for sid in body.get("track_sids", []):
            if subscribe:
                room.subscribe(p, sid)
            else:
                room.unsubscribe(p, sid)
        return web.json_response({})

    async def _rpc_SendData(self, body: dict) -> web.Response:
        room = self._room(body)
        if room is None:
            return _err(404, "room not found")
        room.broadcast_data(
            None,
            payload=body.get("data", ""),
            kind=body.get("kind", 0),
            destination_sids=body.get("destination_sids") or None,
            topic=body.get("topic", ""),
        )
        return web.json_response({})

    async def _rpc_UpdateRoomMetadata(self, body: dict) -> web.Response:
        room = self._room(body)
        if room is None:
            return _err(404, "room not found")
        if "metadata" not in body:
            return _err(400, "metadata required")
        room.info.metadata = body["metadata"]
        await self.server.store.store_room(room.info)
        for p in room.participants.values():
            p.send("room_update", {"room": room.info.to_dict()})
        return web.json_response(room.info.to_dict())
