"""IOInfoService: the central egress/ingress status aggregator.

Reference parity: pkg/service/ioservice.go — workers report status over
RPC to ONE aggregator that owns the authoritative EgressInfo/IngressInfo
stores, fans lifecycle transitions into telemetry/webhooks, and serves
get/list to the Twirp APIs (CreateEgress :81, UpdateEgress :98,
UpdateIngressState :180). Here workers publish JSON updates on the
cluster bus topics; the Twirp services delegate their stores to this
service instead of each keeping a private copy.
"""

from __future__ import annotations

import asyncio
import json


class IOInfoService:

    def __init__(self, server):
        self.server = server
        self.egresses: dict[str, object] = {}    # egress_id → EgressInfo
        self.ingresses: dict[str, object] = {}   # ingress_id → IngressInfo
        self._subs: list = []
        self._workers: list[asyncio.Task] = []

    async def start(self) -> None:
        bus = getattr(self.server.router, "bus", None)
        if bus is None:
            return
        from livekit_server_tpu.service.egress import EgressService
        from livekit_server_tpu.service.ingress import IngressService

        e_sub = bus.subscribe(EgressService.UPDATES_TOPIC)
        i_sub = bus.subscribe(IngressService.UPDATES_TOPIC)
        self._subs = [e_sub, i_sub]
        self._workers = [
            asyncio.ensure_future(self._egress_worker(e_sub)),
            asyncio.ensure_future(self._ingress_worker(i_sub)),
        ]

    async def stop(self) -> None:
        for sub in self._subs:
            sub.close()
        for w in self._workers:
            w.cancel()
        self._subs = []
        self._workers = []

    # -- egress fan-in (ioservice.go UpdateEgress :98) --------------------
    async def _egress_worker(self, sub) -> None:
        from livekit_server_tpu.service.egress import EgressInfo, EgressStatus

        async for raw in sub:
            try:
                info = EgressInfo.from_dict(json.loads(raw))
            except (ValueError, TypeError):
                continue
            prev = self.egresses.get(info.egress_id)
            self.egresses[info.egress_id] = info
            if prev and prev.status != info.status:
                if info.status == EgressStatus.ACTIVE:
                    self.server.telemetry.notify(
                        "egress_started", egress=info.to_dict()
                    )
                elif info.status in (
                    EgressStatus.COMPLETE, EgressStatus.FAILED, EgressStatus.ABORTED
                ):
                    self.server.telemetry.notify(
                        "egress_ended", egress=info.to_dict()
                    )

    # -- ingress fan-in (ioservice.go UpdateIngressState :180) ------------
    async def _ingress_worker(self, sub) -> None:
        from livekit_server_tpu.service.ingress import IngressInfo, IngressState

        async for raw in sub:
            try:
                info = IngressInfo.from_dict(json.loads(raw))
            except (ValueError, TypeError):
                continue
            prev = self.ingresses.get(info.ingress_id)
            self.ingresses[info.ingress_id] = info
            if prev and prev.state != info.state:
                if info.state == IngressState.ENDPOINT_PUBLISHING:
                    self.server.telemetry.notify(
                        "ingress_started", ingress=info.to_dict()
                    )
                elif info.state in (
                    IngressState.ENDPOINT_COMPLETE, IngressState.ENDPOINT_ERROR
                ):
                    self.server.telemetry.notify(
                        "ingress_ended", ingress=info.to_dict()
                    )
