"""IOInfoService: the central egress/ingress status aggregator.

Reference parity: pkg/service/ioservice.go — workers report status over
RPC to ONE aggregator that owns the authoritative EgressInfo/IngressInfo
stores, fans lifecycle transitions into telemetry/webhooks, and serves
get/list to the Twirp APIs (CreateEgress :81, UpdateEgress :98,
UpdateIngressState :180). Here workers publish JSON updates on the
cluster bus topics; the Twirp services delegate their stores to this
service instead of each keeping a private copy.

Lifecycle reaper (pkg/service/redisstore.go:67-944 — the sorted-set
cleanup workers for egress/ingress/SIP state): every record carries a
last-update stamp; ended records expire after ENDED_TTL_S, and a
non-ended record whose worker has gone silent for STALE_ACTIVE_S (its
node crashed mid-job) is marked FAILED/ERROR — so `list_*` on every
node stays clean instead of accumulating orphans forever.
"""

from __future__ import annotations

import asyncio
import json
import time


class IOInfoService:

    REAP_INTERVAL_S = 30.0
    ENDED_TTL_S = 6 * 3600.0    # ended records linger for List, then expire
    # Heartbeat contract (matches the reference's egress workers, which
    # republish status periodically): a live job whose worker has been
    # silent this long is treated as node-lost. Workers must republish
    # on UPDATES_TOPIC at least every STALE_ACTIVE_S / 2.
    STALE_ACTIVE_S = 600.0
    # SIP call entries are dispatch receipts (no worker lifecycle updates
    # exist for them) — expired purely by age, one day like the
    # reference's SIP state cleanup.
    SIP_CALL_TTL_S = 24 * 3600.0

    def __init__(self, server):
        self.server = server
        self.egresses: dict[str, object] = {}    # egress_id → EgressInfo
        self.ingresses: dict[str, object] = {}   # ingress_id → IngressInfo
        self._stamp: dict[str, float] = {}       # record id → monotonic
        self._subs: list = []
        self._workers: list[asyncio.Task] = []

    async def start(self) -> None:
        bus = getattr(self.server.router, "bus", None)
        if bus is None:
            return
        from livekit_server_tpu.service.egress import EgressService
        from livekit_server_tpu.service.ingress import IngressService

        e_sub = bus.subscribe(EgressService.UPDATES_TOPIC)
        i_sub = bus.subscribe(IngressService.UPDATES_TOPIC)
        self._subs = [e_sub, i_sub]
        self._workers = [
            asyncio.ensure_future(self._egress_worker(e_sub)),
            asyncio.ensure_future(self._ingress_worker(i_sub)),
            asyncio.ensure_future(self._reaper()),
        ]

    async def stop(self) -> None:
        for sub in self._subs:
            sub.close()
        for w in self._workers:
            w.cancel()
        self._subs = []
        self._workers = []

    def stamp(self, record_id: str) -> None:
        """Mark a record as just-updated (Twirp create/stop paths and the
        bus workers both call this; the reaper reads it)."""
        self._stamp[record_id] = time.monotonic()

    # -- egress fan-in (ioservice.go UpdateEgress :98) --------------------
    async def _egress_worker(self, sub) -> None:
        from livekit_server_tpu.service.egress import EgressInfo, EgressStatus

        async for raw in sub:
            try:
                info = EgressInfo.from_dict(json.loads(raw))
            except (ValueError, TypeError):
                continue
            prev = self.egresses.get(info.egress_id)
            self.egresses[info.egress_id] = info
            self.stamp(info.egress_id)
            if prev and prev.status != info.status:
                if info.status == EgressStatus.ACTIVE:
                    self.server.telemetry.notify(
                        "egress_started", egress=info.to_dict()
                    )
                elif info.status in (
                    EgressStatus.COMPLETE, EgressStatus.FAILED, EgressStatus.ABORTED
                ):
                    self.server.telemetry.notify(
                        "egress_ended", egress=info.to_dict()
                    )

    # -- ingress fan-in (ioservice.go UpdateIngressState :180) ------------
    async def _ingress_worker(self, sub) -> None:
        from livekit_server_tpu.service.ingress import IngressInfo, IngressState

        async for raw in sub:
            try:
                info = IngressInfo.from_dict(json.loads(raw))
            except (ValueError, TypeError):
                continue
            prev = self.ingresses.get(info.ingress_id)
            self.ingresses[info.ingress_id] = info
            self.stamp(info.ingress_id)
            if prev and prev.state != info.state:
                if info.state == IngressState.ENDPOINT_PUBLISHING:
                    self.server.telemetry.notify(
                        "ingress_started", ingress=info.to_dict()
                    )
                elif info.state in (
                    IngressState.ENDPOINT_COMPLETE, IngressState.ENDPOINT_ERROR
                ):
                    self.server.telemetry.notify(
                        "ingress_ended", ingress=info.to_dict()
                    )

    # -- lifecycle reaper (redisstore.go cleanup workers) -----------------
    async def _reaper(self) -> None:
        while True:
            await asyncio.sleep(self.REAP_INTERVAL_S)
            try:
                self.reap()
            except Exception:  # noqa: BLE001 — one bad webhook/telemetry
                # call must not kill lifecycle cleanup for the process.
                import logging

                logging.getLogger("ioinfo").exception("reap pass failed")

    def reap(self, now: float | None = None) -> None:
        """One cleanup pass (synchronous, directly testable)."""
        from livekit_server_tpu.service.egress import EgressStatus
        from livekit_server_tpu.service.ingress import IngressState

        if now is None:
            now = time.monotonic()
        ended_eg = (
            EgressStatus.COMPLETE, EgressStatus.FAILED, EgressStatus.ABORTED,
            EgressStatus.LIMIT_REACHED,
        )
        for eid, info in list(self.egresses.items()):
            age = now - self._stamp.get(eid, now)
            if info.status in ended_eg:
                if age > self.ENDED_TTL_S:
                    del self.egresses[eid]
                    self._stamp.pop(eid, None)
            elif age > self.STALE_ACTIVE_S:
                # Its worker/node died mid-job: fail it so clients stop
                # seeing a zombie ACTIVE record, then let the ended TTL
                # expire it.
                info.status = EgressStatus.FAILED
                info.error = "egress worker lost"
                info.ended_at = int(time.time())
                self.stamp(eid)
                self.server.telemetry.notify("egress_ended", egress=info.to_dict())
        ended_in = (IngressState.ENDPOINT_COMPLETE, IngressState.ENDPOINT_ERROR)
        for iid, info in list(self.ingresses.items()):
            age = now - self._stamp.get(iid, now)
            if info.state in ended_in:
                if age > self.ENDED_TTL_S:
                    del self.ingresses[iid]
                    self._stamp.pop(iid, None)
            elif info.state == IngressState.ENDPOINT_PUBLISHING and (
                age > self.STALE_ACTIVE_S
            ):
                info.state = IngressState.ENDPOINT_ERROR
                info.error = "ingress worker lost"
                self.stamp(iid)
                self.server.telemetry.notify("ingress_ended", ingress=info.to_dict())
            # ENDPOINT_INACTIVE configs are durable (reference keeps
            # ingress configurations until deleted) — never reaped.
        sip = getattr(self.server, "sip", None)
        if sip is not None and getattr(sip, "calls", None):
            for cid in list(sip.calls):
                if self._stamp.get(cid) is None:
                    self.stamp(cid)  # adopt pre-reaper records
                elif now - self._stamp[cid] > self.SIP_CALL_TTL_S:
                    del sip.calls[cid]
                    self._stamp.pop(cid, None)
