"""Configuration system.

Reference parity: pkg/config/config.go:57-946 — YAML config + env overrides
+ CLI flags *generated from the config schema by reflection*
(config.GenerateCLIFlags, cmd/server/main.go:126-135), strict unknown-key
checking, dev-mode defaults.
"""

from livekit_server_tpu.config.config import (
    AudioConfig,
    BWEConfig,
    Config,
    ConfigError,
    LimitsConfig,
    NodeSelectorConfig,
    PlaneConfig,
    RegionConfig,
    RoomConfig,
    RTCConfig,
    TwinConfig,
    generate_cli_flags,
    load_config,
)

__all__ = [
    "AudioConfig",
    "BWEConfig",
    "Config",
    "ConfigError",
    "LimitsConfig",
    "NodeSelectorConfig",
    "PlaneConfig",
    "RegionConfig",
    "RoomConfig",
    "RTCConfig",
    "TwinConfig",
    "generate_cli_flags",
    "load_config",
]
