"""Typed config schema with YAML/env/CLI merging.

Reference parity: pkg/config/config.go:57-946. The reference's notable
mechanism — CLI flags generated from the YAML schema via reflection so
every key is settable by flag or env (GenerateCLIFlags,
cmd/server/main.go:126-135) — is reproduced here over dataclasses:
`generate_cli_flags` walks the schema and registers `--rtc.tick-ms`-style
flags; env vars use `LIVEKIT_`-prefixed upper-snake paths; strict mode
rejects unknown YAML keys (main.go:197-200).

TPU-specific section: `plane` (tick sizing, tensor capacities, mesh) —
the knobs of the batched media plane that replace the reference's
per-goroutine tuning.
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass, field
from typing import Any, get_args, get_origin

import yaml


class ConfigError(Exception):
    pass


@dataclass
class RegionConfig:
    name: str = ""
    lat: float = 0.0
    lon: float = 0.0


@dataclass
class NodeSelectorConfig:
    """pkg/routing/selector — room placement policy."""

    kind: str = "any"            # any | cpuload | sysload | regionaware
    sort_by: str = "random"      # random | sysload | cpuload | rooms
    cpu_load_limit: float = 0.9  # cpuload.go CPULoadLimit
    sysload_limit: float = 0.9   # sysload.go
    regions: list[RegionConfig] = field(default_factory=list)


@dataclass
class AudioConfig:
    """pkg/config/config.go AudioConfig — active speaker tuning."""

    active_level: int = 35
    min_percentile: int = 40
    update_interval_ms: int = 500
    smooth_intervals: int = 2


@dataclass
class BWEConfig:
    """CongestionControlConfig (config.go) — stream allocator tuning."""

    enabled: bool = True
    allow_pause: bool = False
    nack_ratio_threshold: float = 0.08
    nack_window_min_packets: int = 10
    estimate_required_downgrades: int = 3
    min_channel_capacity: float = 100_000.0
    probe_interval_ms: int = 5000
    # Send-side delay-based estimation over transport-wide feedback (the
    # TWCC seat; transport.go cc.BandwidthEstimator). Off ⇒ allocation
    # budgets come only from client-volunteered estimate samples.
    send_side_bwe: bool = True


@dataclass
class RTCConfig:
    """pkg/config RTCConfig — transport + media-plane edges."""

    udp_port: int = 7882
    # "" = burst each tick; "no-queue" spreads sendmmsg chunks across
    # half the tick (pkg/sfu/pacer seat — shaping without a queue).
    pacer: str = ""
    tcp_port: int = 7881
    require_encryption: bool = True   # drop cleartext media datagrams; the
                                      # sealed AEAD wire (runtime/crypto.py)
                                      # is the DTLS-SRTP seat
    port_range_start: int = 50000
    port_range_end: int = 60000
    use_external_ip: bool = False
    node_ip: str = ""
    stun_servers: list[str] = field(default_factory=list)
    pli_throttle_ms: int = 500         # PLIThrottleConfig
    congestion_control: BWEConfig = field(default_factory=BWEConfig)


@dataclass
class RoomConfig:
    """pkg/config RoomConfig."""

    auto_create: bool = True
    empty_timeout_s: int = 300
    departure_timeout_s: int = 20
    max_participants: int = 0
    enabled_codecs: list[str] = field(
        default_factory=lambda: [
            "audio/opus",
            "audio/red",
            "video/vp8",
            "video/h264",
            "video/vp9",
            "video/av1",
        ]
    )
    max_metadata_size: int = 0
    playout_delay_min_ms: int = 0
    playout_delay_max_ms: int = 0


@dataclass
class LimitsConfig:
    """config.go LimitConfig — node admission limits, plus the overload
    governor (runtime/governor.py) that closes the loop from tick
    telemetry to load shedding. Admission limits default to 0 =
    unlimited; the governor defaults ON (L4 still only engages under
    sustained measured overload)."""

    num_tracks: int = 0          # 0 = unlimited
    bytes_per_sec: float = 0.0
    subscription_limit_video: int = 0
    subscription_limit_audio: int = 0
    max_rooms: int = 0
    # Node-level ingress packet rate: joins/publishes are refused while
    # the measured rate (router stats heartbeat) exceeds this. 0 = off.
    packets_per_sec: float = 0.0
    # Overload governor: degradation ladder L1 clamp spatial layers →
    # L2 police video ingress → L3 pause non-pinned video → L4 reject
    # new work. Escalates after `escalate_ticks` consecutive pressured
    # ticks (late / stalled / capacity-dropping / work ratio ≥ enter);
    # de-escalates one level per `dwell_ticks` consecutive calm ticks
    # (work ratio ≤ exit) — enter/exit split + dwell are the hysteresis.
    governor_enabled: bool = True
    governor_enter_pressure: float = 0.85   # work ratio entering overload
    governor_exit_pressure: float = 0.55    # work ratio counting as calm
    governor_escalate_ticks: int = 20
    governor_dwell_ticks: int = 150
    # L2 token buckets: per-(room, track) video packets/sec + burst.
    governor_ingress_pps: float = 400.0
    governor_ingress_burst: float = 100.0


@dataclass
class PlaneConfig:
    """TPU media-plane sizing (no reference equivalent — replaces
    goroutine tuning like receiver.go lbThreshold with tensor capacities)."""

    tick_ms: int = 10
    rooms: int = 64              # room rows per shard
    tracks_per_room: int = 16
    pkts_per_track: int = 16     # packet slots per track per tick
    subs_per_room: int = 32
    mesh_devices: int = 0        # 0 = all local devices
    donate_state: bool = True
    # Complete each tick's egress before starting the next tick instead of
    # overlapping it with the next device step: ~1 tick lower forward
    # latency, at the cost of the wall budget being the SUM of device +
    # host egress instead of their max. Worth it when both fit the tick.
    low_latency: bool = False
    # Express lane (two-tier latency plane): rooms with at most this many
    # subscribers forward on packet ARRIVAL from the last device selector
    # mirror (≤1-tick-stale, bit-equivalent decisions) instead of waiting
    # for the batched tick — wire latency becomes receive-loop latency.
    # 0 disables the lane; rooms above the bound ride the batched tick.
    # PlaneRuntime.set_express_pin overrides per room in either direction.
    express_max_subs: int = 0
    # Hard cap on rooms simultaneously on the express lane (arrival-path
    # work is per-room; bound it so a flood of small rooms cannot starve
    # the tick loop). Only meaningful when express_max_subs > 0.
    express_max_rooms: int = 16
    # Paged room state (runtime/pager.py): carve device state out of one
    # pooled HBM buffer in (pager_tpage × pager_spage) track×sub pages
    # per room instead of a dense [rooms, tracks, subs] box, so small
    # rooms stop paying the worst-case footprint. Both page dims must be
    # pow2 divisors of tracks_per_room / subs_per_room (spage also ≤ 32
    # and dividing 32 — the selector's sub bitmask lane). pager_pool_pages
    # sizes the pool (pow2; 0 = rooms × max pages per room, i.e. dense-
    # equivalent capacity — useful for parity runs, pointless in prod).
    pager_enabled: bool = False
    pager_tpage: int = 4
    pager_spage: int = 8
    pager_pool_pages: int = 0
    # Ragged-aware pooled-tick kernel (ops/paged_kernel.py): iterate the
    # LIVE pages only — one Pallas grid step per mapped page, dead pages
    # never scheduled — fusing the forward decide + stats routing (+ the
    # audio mix) into one pass. "auto" = on where the kernel exists
    # (TPU); "on" = live-extent path everywhere (gathered fallback off-
    # TPU); "interpret" = Pallas interpret mode (CPU CI parity); "off" =
    # stock full-pool tick. Forced off under a pool mesh (the fused
    # path is single-chip; sharding keeps the stock tick).
    paged_kernel: str = "auto"


@dataclass
class EgressConfig:
    """Sharded native egress plane (runtime/egress_plane.py): per-core
    shards of the munge→assemble→seal→send walk, with multicast-shaped
    canonical staging for high-subscriber fan-out."""

    # Worker shards for the native egress/munge walk. 0 = auto
    # (min(8, cpu cores)); 1 pins everything inline on the caller thread.
    shards: int = 0
    # Stage each (room, track, packet) group's canonical datagram once and
    # patch per-subscriber headers from it, instead of re-gathering payload
    # + extensions per subscriber (P3FA-style constrained multicast).
    # Sealing still runs per datagram — each has a unique counter/nonce.
    multicast_seal: bool = True


@dataclass
class KeyValueConfig:
    """Shared KV for multi-node state (the reference's Redis seat,
    redisrouter.go / redisstore.go). kind=memory keeps single-node mode
    dependency-free (the reference's LocalRouter/LocalStore path)."""

    kind: str = "memory"         # memory | tcp (in-repo BusServer)
    address: str = ""            # host:port for kind=tcp
    auth_token: str = ""         # shared secret for the tcp bus (Redis AUTH seat)
    # Node liveness lease (routing/router.py): refreshed with each stats
    # heartbeat; expiry marks the node dead far faster than the 30 s
    # registry staleness window, triggering room failover.
    lease_ttl_s: float = 6.0
    # Cadence of the surviving nodes' dead-pin scan (room failover).
    failover_interval_s: float = 2.0
    # Heartbeat/lease refresh cadence (the stats worker's sleep). Must
    # divide comfortably into lease_ttl_s: the lease survives a couple
    # of missed refreshes, and the fleet plane's fence_grace timeline is
    # quantized by it.
    stats_interval_s: float = 2.0


@dataclass
class SupervisorConfig:
    """Media-plane supervision (runtime/supervisor.py): tick watchdog +
    bounded restart-from-snapshot. Enabled by default — the failure story
    must hold on the default config path."""

    enabled: bool = True
    # Watchdog stall deadline: no tick progress for this long while the
    # serving loop runs ⇒ restart from the last checkpoint.
    tick_deadline_ms: int = 1000
    # Relaxed deadline until the FIRST tick after a (re)start completes:
    # a cold XLA compile can block that tick for many seconds, and
    # restarting mid-compile both loses the in-flight tick and abandons
    # a worker thread mid-compilation. Tradeoff: a dispatch that hangs at
    # startup takes this long to catch.
    warmup_deadline_s: float = 30.0
    check_interval_ms: int = 100
    # Full-plane + per-room checkpoint cadence (restart/failover rewind
    # is bounded by this).
    checkpoint_interval_s: float = 2.0
    max_restarts: int = 5            # consecutive, without regaining health
    restart_backoff_base_s: float = 0.1
    restart_backoff_max_s: float = 5.0
    # Stall-deadline multiplier while the overload governor is engaged:
    # "overloaded but making progress" must shed load, not restart.
    overload_grace: float = 5.0


@dataclass
class MigrationConfig:
    """Live room migration plane (service/migration.py): two-phase
    PREPARE/ACK/COMMIT handoff over the bus with rollback, freeze-window
    packet bridging, and governed node drain. Needs a shared bus
    (kv.kind=tcp or an injected MemoryBus) — single-node memory mode
    constructs no orchestrator."""

    enabled: bool = True
    # TTL of the `room_snapshot:` key written by the NON-orchestrated
    # handoff path (handoff_room) — how long an unpinned snapshot waits
    # for some node's get_or_create_room to adopt it.
    snapshot_ttl_s: float = 120.0
    # Source-side wait for the target's ACK/NACK per PREPARE attempt.
    # Each timed-out epoch is aborted before the retry re-sends.
    ack_timeout_s: float = 2.0
    # PREPARE retries per target candidate (utils.backoff.retry_async).
    retry_attempts: int = 3
    retry_backoff_base_s: float = 0.1
    retry_backoff_max_s: float = 1.0
    # Rooms migrated concurrently during a node drain.
    drain_concurrency: int = 4
    # Target-side: an adoption whose COMMIT never arrives (source died,
    # bus severed mid-handoff) is released after this long — the device
    # row must not leak.
    adopt_ttl_s: float = 10.0
    # Freeze-window bridge bound (packets). Audio always wins a slot:
    # at budget the oldest buffered VIDEO packet is evicted first.
    bridge_max_packets: int = 512
    # Packets per BRIDGE bus message when flushing to the target.
    bridge_chunk: int = 64


@dataclass
class FaultInjectConfig:
    """Deterministic fault injection (runtime/faultinject.py). OFF by
    default: the default config path constructs no injector — these knobs
    exist so chaos tests and soak runs share one seeded mechanism."""

    enabled: bool = False
    seed: int = 0
    drop_pct: float = 0.0        # P(drop) per ingest packet
    dup_pct: float = 0.0         # P(duplicate) per ingest packet
    delay_pct: float = 0.0       # P(delay) per ingest packet
    delay_ticks: int = 2         # delayed packets re-enter after N ticks
    stall_every: int = 0         # every Nth device step stalls (0 = never)
    stall_s: float = 0.0
    # Flood mode: offered-load multiplier (extra staged copies per
    # arriving packet; <= 1.0 = off) for reproducible overload.
    flood_mult: float = 1.0
    flood_rooms: list[int] = field(default_factory=list)  # [] = all rooms
    # Silent-data-corruption mode: flip bits in one room's slice of a
    # PlaneState leaf right before the device step at a chosen tick
    # (-1 = never). Drives the integrity detect→quarantine→repair ladder.
    bitflip_tick: int = -1
    bitflip_room: int = 0
    bitflip_leaf: str = "temporal_bytes"   # dotted path into PlaneState
    bitflip_bit: int = 30                  # bit index within each element
    bitflip_count: int = 1                 # elements flipped in the row
    # Damage every Nth serialized checkpoint frame (0 = never): exercises
    # checksum verification + generation fallback on restore.
    corrupt_ckpt_every: int = 0
    # Migration chaos drills (service/migration.py). Target-side:
    # adopt the PREPARE'd room, then go silent — never ACK (the
    # "target died mid-PREPARE" drill; source must time out + roll
    # back, target must reap the row).
    mig_drop_prepare: bool = False
    # Target-side: sleep this long before ACKing — past ack_timeout_s
    # the source has already aborted the epoch, so the late ACK must
    # be ignored by the epoch guard (no double-commit).
    mig_ack_delay_s: float = 0.0
    # Source-side: damage the encoded snapshot inside PREPARE; the
    # target's checksum verification must NACK, source rolls back.
    mig_corrupt_handoff: bool = False
    # Source-side: the first N commit phases raise ConnectionError on
    # their bus ops (the "bus severed mid-handoff" drill).
    mig_sever_handoffs: int = 0
    # Bus-partition drills (BusServer.set_partition via the injector's
    # bus_partition_tick seam). Groups are lists of node ids; group 0
    # keeps the bus, later groups are severed (every KV op errors, every
    # pub/sub push is skipped) — the minority side of a split-brain.
    bus_partition_groups: list = field(default_factory=list)
    # Tick to install the partition at / heal it at (-1 = never).
    bus_partition_tick: int = -1
    bus_heal_at_tick: int = -1
    # (src, dst) node-id pairs whose pushes are held during the
    # partition and delivered IN ORDER on heal — the stale-message-
    # after-heal drill (e.g. a migration COMMIT outliving its epoch).
    bus_asym_pairs: list = field(default_factory=list)


@dataclass
class FleetConfig:
    """Partition-tolerant fleet plane (routing/fleet.py +
    service/fleetplane.py): epoch-fenced room ownership, self-fencing on
    lease loss, elected failover and the load rebalancer."""

    enabled: bool = True
    # A node whose liveness lease goes unrefreshed this long self-fences
    # (mutes egress, freezes checkpoints, denies admissions, quiesces
    # supervisor restarts). Validated against the takeover timeline:
    # must stay BELOW kv.lease_ttl_s + kv.failover_interval_s (fence
    # before any survivor can finish a takeover) and at most
    # 2 x kv.lease_ttl_s (a transient blip must not mute a node long).
    fence_grace_s: float = 6.0
    # TTL of the `fleet_restore:{room}` create-lock electing a failover
    # restorer; a crashed winner's lock lapses after this.
    restore_lock_ttl_s: float = 10.0
    # Load rebalancer (default-off): drain the hottest node via live
    # migration when its plane load exceeds the fleet mean by headroom.
    rebalance_enabled: bool = False
    rebalance_interval_s: float = 10.0
    rebalance_headroom: float = 0.25
    rebalance_max_moves: int = 1


@dataclass
class IntegrityConfig:
    """State-integrity plane (runtime/integrity.py): on-device invariant
    audits on a tick cadence, row-level quarantine + repair from the last
    verified checkpoint, bounded escalation to a supervisor restart."""

    enabled: bool = True
    # Audit every Nth tick. The audit is one fused jitted reduction over
    # the plane state; 16 keeps its amortized cost well under 1% of tick
    # time while bounding detection latency to N ticks.
    audit_every_ticks: int = 16
    # Row-repair attempts per room before escalating to a full plane
    # restart (attempts reset once the room audits clean).
    max_row_repairs: int = 3
    # More rooms than this flagged by ONE audit ⇒ the corruption is not
    # row-local (bad upload, poisoned kernel): skip row repair, restart.
    storm_threshold: int = 4
    # Verified checkpoint generations the supervisor retains; corrupt
    # frames fall back a generation at restore.
    checkpoint_generations: int = 3


@dataclass
class RelayConfig:
    """Embedded media relay (pkg/service/turn.go seat): a separately
    addressable UDP hop for clients whose direct path to rtc.udp_port is
    blocked. Blind forwarding — media stays AEAD-sealed end-to-end."""

    enabled: bool = False
    udp_port: int = 7885
    external_host: str = ""      # address advertised to clients; "" = bind addr
    allocation_ttl_s: int = 30
    max_allocations: int = 4096


@dataclass
class WebHookConfig:
    """config.go WebHookConfig."""

    urls: list[str] = field(default_factory=list)
    api_key: str = ""


@dataclass
class TraceConfig:
    """Flight-recorder tracing plane (runtime/trace.py): per-tick span
    ring, sampled wire-latency attribution, and the per-room black-box
    event recorder. Always-on by design — the defaults are sized for a
    bounded (<2%) tick-time overhead."""

    enabled: bool = True
    ring_ticks: int = 512        # tick-span ring capacity (/debug/trace window)
    sample_every: int = 64       # 1-in-K deterministic packet latency sample
    blackbox_events: int = 64    # per-room black-box ring length


@dataclass
class TwinConfig:
    """Traffic-twin scenario knobs (runtime/traffic_twin.py): the
    deterministic fleet-scale load harness behind `bench.py fleet_twin`
    and `tools/check --twin-smoke`. All randomness derives from `seed`;
    two runs with the same knobs produce byte-identical event timelines
    and identical counter-derived SLO numbers."""

    enabled: bool = False        # opt-in: the twin is a harness, not a serving path
    seed: int = 20
    nodes: int = 2               # fleet size replayed against (>=2 for drain)
    ticks: int = 120             # scenario length in virtual ticks
    # Offered-load multipliers for the capacity/SLO curve (>= 4 steps).
    loads: list[float] = field(default_factory=lambda: [0.5, 1.0, 2.0, 4.0])
    video_room_frac: float = 0.4  # codec mix: P(room publishes video)
    probe_every: int = 2          # every Nth admitted room carries SLO probes
    wire_probes: int = 0          # real UDP probe subscribers (wire p99 feed)


@dataclass
class Config:
    """Top-level server config (pkg/config/config.go Config)."""

    bind_addresses: list[str] = field(default_factory=lambda: ["127.0.0.1"])
    port: int = 7880
    prometheus_port: int = 0
    region: str = ""
    keys: dict[str, str] = field(default_factory=dict)
    log_level: str = "info"
    development: bool = False
    rtc: RTCConfig = field(default_factory=RTCConfig)
    room: RoomConfig = field(default_factory=RoomConfig)
    audio: AudioConfig = field(default_factory=AudioConfig)
    limits: LimitsConfig = field(default_factory=LimitsConfig)
    node_selector: NodeSelectorConfig = field(default_factory=NodeSelectorConfig)
    plane: PlaneConfig = field(default_factory=PlaneConfig)
    egress: EgressConfig = field(default_factory=EgressConfig)
    kv: KeyValueConfig = field(default_factory=KeyValueConfig)
    relay: RelayConfig = field(default_factory=RelayConfig)
    webhook: WebHookConfig = field(default_factory=WebHookConfig)
    supervisor: SupervisorConfig = field(default_factory=SupervisorConfig)
    faults: FaultInjectConfig = field(default_factory=FaultInjectConfig)
    integrity: IntegrityConfig = field(default_factory=IntegrityConfig)
    migration: MigrationConfig = field(default_factory=MigrationConfig)
    trace: TraceConfig = field(default_factory=TraceConfig)
    fleet: FleetConfig = field(default_factory=FleetConfig)
    twin: TwinConfig = field(default_factory=TwinConfig)


_SCALARS = (int, float, str, bool)


def _merge_into(obj: Any, data: dict, path: str = "") -> None:
    """Strict recursive merge of a dict into a dataclass tree."""
    names = {f.name: f for f in dataclasses.fields(obj)}
    for k, v in data.items():
        key = k.replace("-", "_")
        if key not in names:
            raise ConfigError(f"unknown config key: {path + k}")
        cur = getattr(obj, key)
        if dataclasses.is_dataclass(cur) and isinstance(v, dict):
            _merge_into(cur, v, path + k + ".")
        elif isinstance(cur, list) and names[key].type == "list[RegionConfig]":
            setattr(obj, key, [RegionConfig(**r) for r in v])
        else:
            setattr(obj, key, _coerce(cur, v, path + k))


def _coerce(cur: Any, v: Any, path: str) -> Any:
    if isinstance(cur, bool):
        if isinstance(v, str):
            return v.lower() in ("1", "true", "yes", "on")
        return bool(v)
    if isinstance(cur, int) and not isinstance(cur, bool):
        return int(v)
    if isinstance(cur, float):
        return float(v)
    if isinstance(cur, str):
        return str(v)
    return v


def _walk_scalars(obj: Any, prefix: str = ""):
    """Yield (dotted_path, field, current_value) for every scalar/list leaf."""
    for f in dataclasses.fields(obj):
        v = getattr(obj, f.name)
        p = f"{prefix}{f.name}"
        if dataclasses.is_dataclass(v):
            yield from _walk_scalars(v, p + ".")
        else:
            yield p, f, v


def generate_cli_flags(parser, config: Config | None = None) -> None:
    """Register every config leaf as a CLI flag (GenerateCLIFlags analog).

    Dotted paths become flags: plane.tick_ms -> --plane.tick-ms.
    """
    config = config or Config()
    for path, _f, v in _walk_scalars(config):
        flag = "--" + path.replace("_", "-")
        if isinstance(v, bool):
            parser.add_argument(flag, type=str, default=None, metavar="BOOL")
        elif isinstance(v, (int, float)):
            parser.add_argument(flag, type=type(v), default=None)
        elif isinstance(v, str):
            parser.add_argument(flag, type=str, default=None)
        elif isinstance(v, list):
            parser.add_argument(flag, type=str, default=None, metavar="CSV")
        elif isinstance(v, dict):
            parser.add_argument(flag, type=str, default=None, metavar="K:V,K:V")


def _apply_path(cfg: Config, path: str, raw: Any) -> None:
    parts = path.split(".")
    obj = cfg
    for p in parts[:-1]:
        obj = getattr(obj, p)
    cur = getattr(obj, parts[-1])
    if isinstance(cur, list):
        raw = [s for s in str(raw).split(",") if s]
    elif isinstance(cur, dict):
        raw = dict(kv.split(":", 1) for kv in str(raw).split(",") if ":" in kv)
    setattr(obj, parts[-1], _coerce(cur, raw, path))


ENV_PREFIX = "LIVEKIT_"


def load_config(
    yaml_text: str | None = None,
    yaml_path: str | None = None,
    cli_args: Any = None,
    env: dict[str, str] | None = None,
) -> Config:
    """YAML < env < CLI precedence (main.go getConfig order)."""
    cfg = Config()
    if yaml_path:
        with open(yaml_path) as f:
            yaml_text = f.read()
    if yaml_text:
        data = yaml.safe_load(yaml_text) or {}
        if not isinstance(data, dict):
            raise ConfigError("config root must be a mapping")
        _merge_into(cfg, data)
    env = os.environ if env is None else env
    paths = {p for p, _f, _v in _walk_scalars(cfg)}
    for path in sorted(paths):
        var = ENV_PREFIX + path.replace(".", "_").upper()
        if var in env:
            _apply_path(cfg, path, env[var])
    if cli_args is not None:
        for path in sorted(paths):
            attr = path.replace(".", "_").replace("-", "_")
            # argparse stores --a.b-c under "a.b_c"; normalize both ways.
            for cand in (path, attr, path.replace("_", "-")):
                v = getattr(cli_args, cand, None) if not isinstance(cli_args, dict) else cli_args.get(cand)
                if v is not None:
                    _apply_path(cfg, path, v)
                    break
    _validate(cfg)
    return cfg


def _validate(cfg: Config) -> None:
    if cfg.rtc.pacer not in ("", "no-queue", "leaky-bucket"):
        raise ConfigError(
            "rtc.pacer must be '', 'no-queue' or 'leaky-bucket', "
            f"got {cfg.rtc.pacer!r}"
        )
    if not cfg.development and not cfg.keys:
        raise ConfigError("one or more API keys are required (or set development: true)")
    if cfg.development and not cfg.keys:
        # dev-mode auto keys (main.go:208-246)
        cfg.keys = {"devkey": "secret"}
    p = cfg.plane
    for name in ("tick_ms", "rooms", "tracks_per_room", "pkts_per_track", "subs_per_room"):
        if getattr(p, name) <= 0:
            raise ConfigError(f"plane.{name} must be positive")
    if p.express_max_subs < 0:
        raise ConfigError(
            f"plane.express_max_subs must be >= 0, got {p.express_max_subs}"
        )
    if p.express_max_subs > p.subs_per_room:
        raise ConfigError(
            "plane.express_max_subs must not exceed plane.subs_per_room "
            f"({p.subs_per_room}), got {p.express_max_subs}"
        )
    if p.express_max_rooms <= 0:
        raise ConfigError(
            f"plane.express_max_rooms must be positive, got {p.express_max_rooms}"
        )
    if p.pager_enabled:
        def _pow2(n: int) -> bool:
            return n > 0 and (n & (n - 1)) == 0

        for name, axis in (("pager_tpage", "tracks_per_room"),
                           ("pager_spage", "subs_per_room")):
            v, cap = getattr(p, name), getattr(p, axis)
            if not _pow2(v):
                raise ConfigError(f"plane.{name} must be a power of two, got {v}")
            if cap % v != 0:
                raise ConfigError(
                    f"plane.{name} must divide plane.{axis} ({cap}), got {v}"
                )
        if p.pager_spage > 32 or 32 % p.pager_spage != 0:
            raise ConfigError(
                "plane.pager_spage must divide 32 (selector sub-bitmask "
                f"lane), got {p.pager_spage}"
            )
        if p.pager_pool_pages and not _pow2(p.pager_pool_pages):
            raise ConfigError(
                "plane.pager_pool_pages must be a power of two (or 0 for "
                f"dense-equivalent), got {p.pager_pool_pages}"
            )
        if p.paged_kernel not in ("auto", "on", "off", "interpret"):
            raise ConfigError(
                "plane.paged_kernel must be one of auto|on|off|interpret, "
                f"got {p.paged_kernel!r}"
            )
    eg = cfg.egress
    if not 0 <= eg.shards <= 64:
        raise ConfigError(f"egress.shards must be in [0, 64], got {eg.shards}")
    f = cfg.faults
    for name in ("drop_pct", "dup_pct", "delay_pct"):
        v = getattr(f, name)
        if not 0.0 <= v <= 1.0:
            raise ConfigError(f"faults.{name} must be in [0, 1], got {v}")
    if f.drop_pct + f.dup_pct + f.delay_pct > 1.0:
        raise ConfigError("faults.drop_pct + dup_pct + delay_pct must be <= 1")
    if f.flood_mult < 0.0:
        raise ConfigError(f"faults.flood_mult must be >= 0, got {f.flood_mult}")
    if not 0 <= f.bitflip_bit <= 31:
        raise ConfigError(f"faults.bitflip_bit must be in [0, 31], got {f.bitflip_bit}")
    if f.bitflip_count <= 0:
        raise ConfigError(f"faults.bitflip_count must be positive, got {f.bitflip_count}")
    if f.bitflip_room < 0:
        raise ConfigError(f"faults.bitflip_room must be >= 0, got {f.bitflip_room}")
    if f.corrupt_ckpt_every < 0:
        raise ConfigError(
            f"faults.corrupt_ckpt_every must be >= 0, got {f.corrupt_ckpt_every}"
        )
    if f.mig_ack_delay_s < 0.0:
        raise ConfigError(f"faults.mig_ack_delay_s must be >= 0, got {f.mig_ack_delay_s}")
    if f.mig_sever_handoffs < 0:
        raise ConfigError(
            f"faults.mig_sever_handoffs must be >= 0, got {f.mig_sever_handoffs}"
        )
    integ = cfg.integrity
    for name in ("audit_every_ticks", "max_row_repairs", "storm_threshold",
                 "checkpoint_generations"):
        if getattr(integ, name) <= 0:
            raise ConfigError(f"integrity.{name} must be positive")
    if cfg.supervisor.tick_deadline_ms <= 0:
        raise ConfigError("supervisor.tick_deadline_ms must be positive")
    if cfg.supervisor.overload_grace < 1.0:
        raise ConfigError("supervisor.overload_grace must be >= 1")
    lim = cfg.limits
    if not lim.governor_enter_pressure > lim.governor_exit_pressure:
        raise ConfigError(
            "limits.governor_enter_pressure must exceed governor_exit_pressure "
            "(the hysteresis band)"
        )
    for name in ("governor_escalate_ticks", "governor_dwell_ticks",
                 "governor_ingress_pps", "governor_ingress_burst"):
        if getattr(lim, name) <= 0:
            raise ConfigError(f"limits.{name} must be positive")
    if cfg.kv.lease_ttl_s <= 0:
        raise ConfigError("kv.lease_ttl_s must be positive")
    if cfg.kv.stats_interval_s <= 0:
        raise ConfigError("kv.stats_interval_s must be positive")
    if f.bus_heal_at_tick < -1 or f.bus_partition_tick < -1:
        raise ConfigError(
            "faults.bus_partition_tick/bus_heal_at_tick must be >= -1"
        )
    fleet = cfg.fleet
    if fleet.enabled:
        if fleet.fence_grace_s <= 0:
            raise ConfigError("fleet.fence_grace_s must be positive")
        if fleet.fence_grace_s > 2 * cfg.kv.lease_ttl_s:
            raise ConfigError(
                "fleet.fence_grace_s must be <= 2 x kv.lease_ttl_s "
                "(a blip must not mute a healthy node for long)"
            )
        if fleet.fence_grace_s >= cfg.kv.lease_ttl_s + cfg.kv.failover_interval_s:
            raise ConfigError(
                "fleet.fence_grace_s must be < kv.lease_ttl_s + "
                "kv.failover_interval_s (the minority must fence before "
                "any survivor can complete a takeover)"
            )
    for name in ("restore_lock_ttl_s", "rebalance_interval_s",
                 "rebalance_max_moves"):
        if getattr(fleet, name) <= 0:
            raise ConfigError(f"fleet.{name} must be positive")
    if fleet.rebalance_headroom < 0:
        raise ConfigError("fleet.rebalance_headroom must be >= 0")
    mig = cfg.migration
    for name in ("snapshot_ttl_s", "ack_timeout_s", "retry_attempts",
                 "retry_backoff_base_s", "retry_backoff_max_s",
                 "drain_concurrency", "adopt_ttl_s", "bridge_max_packets",
                 "bridge_chunk"):
        if getattr(mig, name) <= 0:
            raise ConfigError(f"migration.{name} must be positive")
    tr = cfg.trace
    for name in ("ring_ticks", "sample_every", "blackbox_events"):
        if getattr(tr, name) <= 0:
            raise ConfigError(f"trace.{name} must be positive")
    tw = cfg.twin
    for name in ("nodes", "ticks", "probe_every"):
        if getattr(tw, name) <= 0:
            raise ConfigError(f"twin.{name} must be positive")
    if tw.seed < 0:
        raise ConfigError(f"twin.seed must be >= 0, got {tw.seed}")
    if tw.wire_probes < 0:
        raise ConfigError(f"twin.wire_probes must be >= 0, got {tw.wire_probes}")
    if not 0.0 <= tw.video_room_frac <= 1.0:
        raise ConfigError(
            f"twin.video_room_frac must be in [0, 1], got {tw.video_room_frac}"
        )
    if any(float(x) <= 0 for x in tw.loads):
        raise ConfigError("twin.loads must all be positive multipliers")
    if tw.enabled and len(tw.loads) < 4:
        raise ConfigError(
            "twin.loads needs >= 4 offered-load steps for the capacity/SLO "
            f"curve, got {len(tw.loads)}"
        )
