"""Telemetry: lifecycle events, metrics, webhooks.

Reference parity: pkg/telemetry (SURVEY.md §2.6) — TelemetryService event
queue (events.go:30-552), prometheus counters (prometheus/packets.go,
rooms.go, node.go), webhook notifier. Counters here are plain dicts
rendered in Prometheus text format (prometheus_client is available but a
dependency-free registry keeps the hot path allocation-free); media-plane
counters are pushed in per tick from PlaneRuntime stats.
"""

from livekit_server_tpu.telemetry.service import TelemetryService
from livekit_server_tpu.telemetry.webhook import WebhookNotifier

__all__ = ["TelemetryService", "WebhookNotifier"]
