"""Trace-ring → Chrome/Perfetto trace-event JSON exporter + validator.

The TickTraceRing (runtime/trace.py) stores per-tick span records as raw
perf_counter start/duration pairs. This module renders them in the
Chrome trace-event format (the `traceEvents` array of "X" complete
events with µs timestamps) that chrome://tracing and ui.perfetto.dev
load directly:

  pid 1, one tid per pipeline lane:
    loop    — stage_host (with the express retier nested inside),
              ctrl_upload, and a tick_edge instant marker
    device  — device_step
    fanout  — fan_out (munge+assemble) and egress_send (delivery cbs)
    shard N — per-egress-shard munge/send walls, synthesized inside the
              fan-out/send windows

`validate()` checks the schema the hard way (required fields, dur >= 0,
and strict span nesting per tid — overlap without containment is a
broken trace), and `selftest()` runs a tiny CPU plane for a few ticks
and validates its own export — the `tools/check --trace-schema` gate.
"""

from __future__ import annotations

import json
from typing import Any

# tid lanes (Chrome sorts numerically; names land via metadata events).
TID_LOOP = 1
TID_DEVICE = 2
TID_FANOUT = 3
TID_SHARD0 = 10  # shard i → tid TID_SHARD0 + i

_LANE_NAMES = {TID_LOOP: "loop", TID_DEVICE: "device", TID_FANOUT: "fanout"}


def to_chrome(records: list[dict[str, Any]], tick_ms: int = 0) -> list[dict]:
    """Render trace-ring snapshot records as Chrome trace events."""
    if not records:
        return []
    # Time base: earliest known timestamp in the window → ts 0.
    t0s = []
    for r in records:
        for k in ("edge", "stage_t0", "upload_t0", "device_t0", "fanout_t0"):
            v = r.get(k, 0.0)
            if v > 0.0:
                t0s.append(v)
    base = min(t0s) if t0s else 0.0

    def us(t: float) -> float:
        return round((t - base) * 1e6, 1)

    def dur_us(s: float) -> float:
        return round(max(s, 0.0) * 1e6, 1)

    events: list[dict] = []
    shard_lanes = 0
    for r in records:
        tick = r["tick"]
        args = {"tick": tick, "depth": r.get("depth", 0),
                "late": bool(r.get("late", False))}
        if r.get("edge", 0.0) > 0.0:
            events.append({
                "name": "tick_edge", "ph": "I", "s": "t",
                "ts": us(r["edge"]), "pid": 1, "tid": TID_LOOP,
                "args": {"tick": tick,
                         "wake_over_us": r.get("wake_over_us", 0.0)},
            })
        if r.get("stage_t0", 0.0) > 0.0:
            events.append({
                "name": "stage_host", "ph": "X", "ts": us(r["stage_t0"]),
                "dur": dur_us(r.get("stage_s", 0.0)),
                "pid": 1, "tid": TID_LOOP, "args": args,
            })
            if r.get("retier_s", 0.0) > 0.0:
                # The retier runs first inside stage_host; its span nests
                # at the stage start.
                events.append({
                    "name": "express_retier", "ph": "X",
                    "ts": us(r["stage_t0"]),
                    "dur": min(dur_us(r["retier_s"]),
                               dur_us(r.get("stage_s", 0.0))),
                    "pid": 1, "tid": TID_LOOP, "args": {"tick": tick},
                })
        if r.get("upload_t0", 0.0) > 0.0:
            events.append({
                "name": "ctrl_upload", "ph": "X", "ts": us(r["upload_t0"]),
                "dur": dur_us(r.get("upload_s", 0.0)),
                "pid": 1, "tid": TID_LOOP, "args": {"tick": tick},
            })
        if r.get("device_t0", 0.0) > 0.0:
            events.append({
                "name": "device_step", "ph": "X", "ts": us(r["device_t0"]),
                "dur": dur_us(r.get("device_s", 0.0)),
                "pid": 1, "tid": TID_DEVICE, "args": args,
            })
            # Paged-kernel slice: the phase-0 decide dispatch nested at
            # the head of the device span (0 when the stock tick ran).
            if r.get("kernel_s", 0.0) > 0.0:
                events.append({
                    "name": "paged_kernel", "ph": "X",
                    "ts": us(r["device_t0"]),
                    "dur": dur_us(r["kernel_s"]),
                    "pid": 1, "tid": TID_DEVICE, "args": {"tick": tick},
                })
        f0 = r.get("fanout_t0", 0.0)
        if f0 > 0.0:
            fan_s = r.get("fanout_s", 0.0)
            send_s = r.get("send_s", 0.0)
            events.append({
                "name": "fan_out", "ph": "X", "ts": us(f0),
                "dur": dur_us(fan_s),
                "pid": 1, "tid": TID_FANOUT, "args": args,
            })
            if send_s > 0.0:
                events.append({
                    "name": "egress_send", "ph": "X", "ts": us(f0 + fan_s),
                    "dur": dur_us(send_s),
                    "pid": 1, "tid": TID_FANOUT, "args": {"tick": tick},
                })
            # Per-shard walls: no native start stamps, so each shard's
            # munge rides the fan-out window and its send the send
            # window, on the shard's own lane (clipped to the window).
            munge = r.get("shard_munge_ms", [])
            send = r.get("shard_send_ms", [])
            shard_lanes = max(shard_lanes, len(munge), len(send))
            for i, ms in enumerate(munge):
                if ms > 0.0:
                    events.append({
                        "name": "munge", "ph": "X", "ts": us(f0),
                        "dur": min(round(ms * 1e3, 1), dur_us(fan_s)),
                        "pid": 1, "tid": TID_SHARD0 + i,
                        "args": {"tick": tick},
                    })
            for i, ms in enumerate(send):
                if ms > 0.0:
                    events.append({
                        "name": "send", "ph": "X", "ts": us(f0 + fan_s),
                        "dur": min(round(ms * 1e3, 1), dur_us(send_s))
                        if send_s > 0.0 else round(ms * 1e3, 1),
                        "pid": 1, "tid": TID_SHARD0 + i,
                        "args": {"tick": tick},
                    })
    # Lane-name metadata events (Perfetto thread names).
    for tid, name in _LANE_NAMES.items():
        events.append({
            "name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
            "args": {"name": name},
        })
    for i in range(shard_lanes):
        events.append({
            "name": "thread_name", "ph": "M", "pid": 1,
            "tid": TID_SHARD0 + i, "args": {"name": f"egress-shard-{i}"},
        })
    return events


def validate(events: list[dict]) -> list[str]:
    """Schema + nesting checks; returns a list of problems (empty = ok)."""
    errors: list[str] = []
    spans: dict[tuple, list[tuple[float, float, str]]] = {}
    for i, e in enumerate(events):
        for field in ("name", "ph", "pid", "tid"):
            if field not in e:
                errors.append(f"event {i}: missing {field!r}")
        ph = e.get("ph")
        if ph not in ("X", "I", "M"):
            errors.append(f"event {i}: unknown ph {ph!r}")
            continue
        if ph == "M":
            continue
        if "ts" not in e or not isinstance(e["ts"], (int, float)):
            errors.append(f"event {i}: missing/non-numeric ts")
            continue
        if e["ts"] < 0:
            errors.append(f"event {i} ({e.get('name')}): negative ts")
        if ph == "X":
            dur = e.get("dur")
            if not isinstance(dur, (int, float)):
                errors.append(f"event {i} ({e.get('name')}): missing dur")
                continue
            if dur < 0:
                errors.append(f"event {i} ({e.get('name')}): negative dur")
                continue
            spans.setdefault((e.get("pid"), e.get("tid")), []).append(
                (float(e["ts"]), float(e["ts"]) + float(dur),
                 str(e.get("name")))
            )
    # Nesting: on one tid, any two overlapping spans must be contained
    # (chrome://tracing silently mis-renders partial overlap).
    EPS = 0.11  # µs: ts/dur are rounded to 0.1 µs independently
    for (pid, tid), lst in spans.items():
        # same start → longest first, so a parent precedes the children
        # that open with it (stage_host and its nested retier share ts)
        lst.sort(key=lambda x: (x[0], -x[1]))
        stack: list[tuple[float, float, str]] = []
        for s, t, name in lst:
            while stack and stack[-1][1] <= s + EPS:
                stack.pop()
            if stack and t > stack[-1][1] + EPS:
                errors.append(
                    f"tid {tid}: span {name!r} [{s}, {t}] partially "
                    f"overlaps {stack[-1][2]!r} "
                    f"[{stack[-1][0]}, {stack[-1][1]}]"
                )
            stack.append((s, t, name))
    return errors


def export_json(records: list[dict[str, Any]], tick_ms: int = 0) -> str:
    """Full Chrome trace JSON document for a ring snapshot."""
    return json.dumps(
        {"traceEvents": to_chrome(records, tick_ms),
         "displayTimeUnit": "ms"}
    )


def selftest(ticks: int = 6) -> list[str]:
    """Run a tiny CPU plane with tracing on, export, validate. Returns
    problems (empty = pass). Used by `tools/check --trace-schema`."""
    import asyncio

    import numpy as np

    from livekit_server_tpu.models import plane
    from livekit_server_tpu.runtime.ingest import PacketIn
    from livekit_server_tpu.runtime.plane_runtime import PlaneRuntime
    from livekit_server_tpu.runtime.trace import EV_QUARANTINE

    dims = plane.PlaneDims(rooms=2, tracks=2, pkts=2, subs=2)
    rt = PlaneRuntime(dims, tick_ms=5)

    async def drive() -> None:
        rt.set_track(0, 0, published=True, is_video=False)
        rt.set_subscription(0, 0, 0, subscribed=True)
        for k in range(ticks):
            rt.ingest.push(PacketIn(room=0, track=0, sn=100 + k,
                                    ts=960 * k, size=8, payload=b"p" * 8))
            await rt.step_once()
        await rt.stop()

    asyncio.run(drive())
    problems: list[str] = []
    records = rt.trace.snapshot() if rt.trace is not None else []
    if len(records) < ticks:
        problems.append(
            f"trace ring recorded {len(records)} ticks, expected {ticks}"
        )
    doc = export_json(records, rt.tick_ms)
    parsed = json.loads(doc)
    events = parsed.get("traceEvents", [])
    if not events:
        problems.append("export produced no trace events")
    problems.extend(validate(events))
    names = {e.get("name") for e in events}
    for want in ("stage_host", "device_step", "fan_out"):
        if want not in names:
            problems.append(f"expected span {want!r} missing from export")
    # Black-box round trip: emit + dump on a lane.
    rt.blackbox.emit(0, EV_QUARANTINE, 1.0)
    dumped = rt.blackbox.dump_to(0, "selftest")
    if not dumped or dumped[-1]["event"] != "quarantine":
        problems.append("black-box emit/dump round trip failed")
    # Attribution sampler: synthetic batch through the stage decomposer.
    ws = rt.wire_stages
    if ws is not None:
        now = 100.0
        sn = np.arange(0, 4 * ws.sample_every, ws.sample_every)
        ta = np.full(len(sn), now - 0.010)
        ws.observe_batch(sn, ta, now - 0.006, now - 0.004, now)
        summ = ws.summary()
        for stage in ("staging", "device", "egress", "total"):
            if stage not in summ:
                problems.append(f"attribution stage {stage!r} not fed")
    return problems


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="trace_export",
        description="validate or self-test the trace export schema",
    )
    ap.add_argument("--selftest", action="store_true",
                    help="run a tiny traced plane and validate its export")
    ap.add_argument("--validate", metavar="FILE",
                    help="validate an exported trace JSON file")
    args = ap.parse_args(argv)
    if args.validate:
        with open(args.validate, encoding="utf-8") as fh:
            doc = json.load(fh)
        events = doc.get("traceEvents", doc if isinstance(doc, list) else [])
        problems = validate(events)
        for p in problems:
            print(p)
        print(f"trace: {len(events)} events, {len(problems)} problem(s)")
        return 1 if problems else 0
    if args.selftest:
        problems = selftest()
        for p in problems:
            print(p)
        print("trace selftest:", "FAILED" if problems else "ok")
        return 1 if problems else 0
    ap.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
