"""Event fan-in + metrics registry.

Reference parity: pkg/telemetry/telemetryservice.go:29-200 (single
consumer queue of room/participant/track lifecycle events), events.go
(the ~30 event constructors), prometheus/*.go counters. Events fan out to
the webhook notifier (webhook.go) and increment counters; `prometheus_text`
renders the registry in the exposition format served at /metrics.
"""

from __future__ import annotations

import asyncio
import time
from collections import defaultdict
from typing import Any

from livekit_server_tpu.config.config import Config
from livekit_server_tpu.telemetry.webhook import WebhookNotifier

# Event names follow the reference's webhook event strings
# (webhook.go EventRoomStarted etc.).
EVENTS = {
    "room_started",
    "room_finished",
    "participant_joined",
    "participant_left",
    "track_published",
    "track_unpublished",
    "egress_started",
    "egress_ended",
    "ingress_started",
    "ingress_ended",
}


class TelemetryService:
    def __init__(self, config: Config):
        self.config = config
        self.counters: dict[str, float] = defaultdict(float)
        self.gauges: dict[str, float] = {}
        self.events: list[dict[str, Any]] = []  # ring of recent events
        self.webhook = WebhookNotifier(config)

    # -- events (events.go) ----------------------------------------------
    def notify(self, event: str, **payload: Any) -> None:
        if event not in EVENTS:
            return
        self.counters[f"livekit_events_total{{event=\"{event}\"}}"] += 1
        record = {"event": event, "created_at": int(time.time()), **payload}
        self.events.append(record)
        if len(self.events) > 1000:
            del self.events[: len(self.events) - 1000]
        self.webhook.queue(record)

    # -- counters (prometheus/packets.go naming) -------------------------
    def add(self, name: str, value: float = 1.0, **labels: str) -> None:
        self.counters[_key(name, labels)] += value

    def set_gauge(self, name: str, value: float, **labels: str) -> None:
        self.gauges[_key(name, labels)] = value

    def observe_plane(self, stats: dict[str, Any]) -> None:
        """Per-tick media-plane stats → node counters (statsworker.go)."""
        self.set_gauge("livekit_plane_ticks_total", stats.get("ticks", 0))
        self.set_gauge("livekit_packets_forwarded_total", stats.get("fwd_packets", 0))
        self.set_gauge("livekit_bytes_forwarded_total", stats.get("fwd_bytes", 0))
        self.set_gauge("livekit_plane_late_ticks_total", stats.get("late_ticks", 0))

    def prometheus_text(self) -> str:
        lines = []
        for key, v in sorted(self.counters.items()):
            lines.append(f"{key} {v:g}")
        for key, v in sorted(self.gauges.items()):
            lines.append(f"{key} {v:g}")
        return "\n".join(lines) + "\n"

    async def close(self) -> None:
        await self.webhook.close()


def _key(name: str, labels: dict[str, str]) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"
