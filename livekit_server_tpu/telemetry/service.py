"""Event fan-in + metrics registry.

Reference parity: pkg/telemetry/telemetryservice.go:29-200 (single
consumer queue of room/participant/track lifecycle events), events.go
(the ~30 event constructors), prometheus/*.go counters. Events fan out to
the webhook notifier (webhook.go) and increment counters; `prometheus_text`
renders the registry in the exposition format served at /metrics.
"""

from __future__ import annotations

import asyncio
import time
from collections import defaultdict
from typing import Any

import numpy as np

from livekit_server_tpu.config.config import Config
from livekit_server_tpu.telemetry.webhook import WebhookNotifier

# Event names follow the reference's webhook event strings
# (webhook.go EventRoomStarted etc.).
EVENTS = {
    "room_started",
    "room_finished",
    "participant_joined",
    "participant_left",
    "track_published",
    "track_unpublished",
    "egress_started",
    "egress_ended",
    "ingress_started",
    "ingress_ended",
}


class Histogram:
    """Prometheus histogram fed with numpy batches (the batched analog of
    prometheus/packets.go's per-packet observations)."""

    def __init__(self, buckets: tuple[float, ...]):
        self.buckets = np.asarray(buckets, np.float64)
        # One extra slot for overflow (> last finite bucket → +Inf only).
        self.counts = np.zeros(len(buckets) + 1, np.int64)
        self.sum = 0.0
        self.count = 0

    def observe(self, values) -> None:
        v = np.atleast_1d(np.asarray(values, np.float64))
        if not len(v):
            return
        self.count += len(v)
        self.sum += float(v.sum())
        idx = np.searchsorted(self.buckets, v, side="left")
        self.counts += np.bincount(idx, minlength=len(self.buckets) + 1)

    def render(self, name: str, lines: list[str],
               labels: dict[str, str] | None = None) -> None:
        # Extra labels (e.g. stage="device") precede the cumulative `le`
        # label on every series of the family.
        lbl = (
            "".join(f'{k}="{v}",' for k, v in sorted(labels.items()))
            if labels else ""
        )
        sfx = f"{{{lbl[:-1]}}}" if lbl else ""
        cum = 0
        for b, c in zip(self.buckets, self.counts[:-1]):
            cum += int(c)
            lines.append(f'{name}_bucket{{{lbl}le="{b:g}"}} {cum}')
        lines.append(f'{name}_bucket{{{lbl}le="+Inf"}} {self.count}')
        lines.append(f"{name}_sum{sfx} {self.sum:g}")
        lines.append(f"{name}_count{sfx} {self.count}")


# Bucket ladders (prometheus/packets.go + connectionquality histograms).
_HIST_SPECS = {
    "livekit_track_loss_percent": (0.0, 0.5, 1, 2, 5, 10, 20, 50, 100),
    "livekit_track_jitter_ms": (0.5, 1, 2, 5, 10, 20, 50, 100, 200),
    "livekit_track_bitrate_kbps": (16, 64, 150, 500, 1000, 2000, 4000, 8000),
    "livekit_forward_latency_ms": (1, 2, 5, 10, 20, 50, 100, 250, 1000),
    "livekit_tick_duration_ms": (0.5, 1, 2, 5, 10, 20, 50, 100, 250),
}

# Per-stage wire-latency decomposition (runtime/trace.py
# LatencyAttribution): one histogram per stage label.
_STAGE_BUCKETS = (0.5, 1, 2, 5, 10, 20, 50, 100, 250)

# One-line HELP strings per metric family (exposition-format HELP/TYPE
# headers; families not listed fall back to the family name itself).
_HELP = {
    "livekit_xla_compiles_total": "XLA backend compilations since process start",
    "livekit_xla_compiles_post_warmup": "XLA compilations after the warmup watermark (first-use paths may add a handful; sustained growth is a retrace storm)",
    "livekit_forward_latency_ms": "Sampled packet arrival-to-wire latency (both egress tiers)",
    "livekit_wire_latency_stage_ms": "Sampled wire latency decomposed by pipeline stage",
    "livekit_tick_duration_ms": "Media-plane tick work time (stage+device+fanout)",
    "livekit_host_egress_pps": "Host egress datagrams/s EMA over both tiers",
    "livekit_plane_sleep_bias_us": "Calibrated tick-edge coarse-sleep overshoot margin",
    "livekit_plane_edge_overshoot_us": "Last tick-edge wake overshoot",
    "livekit_events_total": "Lifecycle events by type",
}


class TelemetryService:
    def __init__(self, config: Config):
        self.config = config
        self.counters: dict[str, float] = defaultdict(float)
        self.gauges: dict[str, float] = {}
        self.histograms = {k: Histogram(v) for k, v in _HIST_SPECS.items()}
        # Stage-labelled wire-latency histograms (one per stage key fed
        # by observe_wire_stages); rendered as one labelled family.
        self.stage_hists: dict[str, Histogram] = {}
        self.events: list[dict[str, Any]] = []  # ring of recent events
        # Per-track analytics records (~1/s per published track — the
        # statsworker.go → analytics stream seat; ring-buffered, served at
        # /debug/analytics).
        self.track_stats: list[dict[str, Any]] = []
        self.webhook = WebhookNotifier(config)

    # -- events (events.go) ----------------------------------------------
    def notify(self, event: str, **payload: Any) -> None:
        if event not in EVENTS:
            return
        self.counters[f"livekit_events_total{{event=\"{event}\"}}"] += 1
        record = {"event": event, "created_at": int(time.time()), **payload}
        self.events.append(record)
        if len(self.events) > 1000:
            del self.events[: len(self.events) - 1000]
        self.webhook.queue(record)

    # -- counters (prometheus/packets.go naming) -------------------------
    def add(self, name: str, value: float = 1.0, **labels: str) -> None:
        self.counters[_key(name, labels)] += value

    def set_gauge(self, name: str, value: float, **labels: str) -> None:
        self.gauges[_key(name, labels)] = value

    def observe_plane(self, stats: dict[str, Any]) -> None:
        """Per-tick media-plane stats → node counters (statsworker.go)."""
        self.set_gauge("livekit_plane_ticks_total", stats.get("ticks", 0))
        self.set_gauge("livekit_packets_forwarded_total", stats.get("fwd_packets", 0))
        self.set_gauge("livekit_bytes_forwarded_total", stats.get("fwd_bytes", 0))
        self.set_gauge("livekit_plane_late_ticks_total", stats.get("late_ticks", 0))
        # Pipeline-stage seconds (three-stage tick loop) + control-upload
        # accounting — cumulative, so rates are scrape-window deltas.
        for k in ("stage_s", "device_s", "fanout_s"):
            self.set_gauge(f"livekit_plane_{k}_total", stats.get(k, 0.0))
        for k in ("pipeline_stalls", "ctrl_full_uploads", "ctrl_delta_uploads",
                  "ctrl_delta_rows", "ctrl_upload_bytes"):
            self.set_gauge(f"livekit_plane_{k}_total", stats.get(k, 0))
        # Tick-edge calibration: measured coarse-sleep bias + last wake
        # overshoot (plane_runtime._sleep_until / _calibrate_sleep).
        self.set_gauge(
            "livekit_plane_sleep_bias_us", stats.get("sleep_bias_us", 0.0)
        )
        self.set_gauge(
            "livekit_plane_edge_overshoot_us",
            stats.get("edge_overshoot_us", 0.0),
        )

    def observe_overload(self, snap: dict[str, Any]) -> None:
        """Overload-governor state (runtime/governor.py stats_dict):
        ladder level, transition counts, the split ingest drop counters,
        and admission rejections by kind."""
        self.set_gauge("livekit_governor_level", snap.get("level", 0))
        self.set_gauge(
            "livekit_governor_escalations_total", snap.get("escalations", 0)
        )
        self.set_gauge(
            "livekit_governor_transitions_total", snap.get("transitions_total", 0)
        )
        for k in ("dropped_capacity", "dropped_fault", "dropped_policed"):
            self.set_gauge(f"livekit_ingest_{k}_total", snap.get(k, 0))
        for kind, n in snap.get("rejected", {}).items():
            self.set_gauge(
                "livekit_admission_rejected_total", n, kind=str(kind)
            )
        # The same refusals keyed by canonical cause (roommanager
        # DENIAL_REASON_LABELS: overload | draining | no_capacity |
        # fenced) — twin runs attribute rejected joins by this series.
        for reason, n in snap.get("denied_reasons", {}).items():
            self.set_gauge(
                "livekit_admission_denied_total", n, reason=str(reason)
            )

    def observe_integrity(self, snap: dict[str, Any]) -> None:
        """State-integrity plane (runtime/integrity.py stats_dict +
        checkpoint codec counters): audits run, violations by rule, the
        repair ladder's outcomes, and checksum verification failures."""
        from livekit_server_tpu.utils.checksum import CodecStats

        self.set_gauge("livekit_integrity_audits_total", snap.get("audits", 0))
        self.set_gauge(
            "livekit_integrity_violations_total", snap.get("violations_total", 0)
        )
        for rule, n in snap.get("violations_by_rule", {}).items():
            self.set_gauge(
                "livekit_integrity_rule_violations_total", n, rule=str(rule)
            )
        for k in ("rows_quarantined", "rows_repaired", "repair_failures",
                  "escalations"):
            self.set_gauge(f"livekit_integrity_{k}_total", snap.get(k, 0))
        self.set_gauge(
            "livekit_integrity_quarantined_rows", len(snap.get("quarantined_rows", []))
        )
        self.set_gauge(
            "livekit_ckpt_checksum_failures_total", CodecStats.verify_failures
        )
        self.set_gauge(
            "livekit_ckpt_generation_fallbacks_total",
            snap.get("generation_fallbacks", 0),
        )

    def observe_egress(self, snap: dict[str, Any]) -> None:
        """Sharded egress plane (runtime/egress_plane.py observe()):
        host-side datagram throughput over critical-path send time, total
        volumes, and per-shard sent/busy breakdowns."""
        self.set_gauge("livekit_host_egress_pps", snap.get("host_egress_pps", 0.0))
        self.set_gauge("livekit_egress_shards", snap.get("shards", 0))
        for k in ("entries", "grouped_entries", "datagrams",
                  "express_datagrams"):
            self.set_gauge(f"livekit_egress_{k}_total", snap.get(k, 0))
        self.set_gauge(
            "livekit_egress_send_ms_total", snap.get("send_ms_total", 0.0)
        )
        self.set_gauge(
            "livekit_egress_munge_ms_total", snap.get("munge_ms_total", 0.0)
        )
        for i, sent in enumerate(snap.get("shard_sent", [])):
            self.set_gauge("livekit_egress_shard_sent_total", sent, shard=str(i))
        for i, ms in enumerate(snap.get("shard_send_ms", [])):
            self.set_gauge("livekit_egress_shard_busy_ms_total", ms, shard=str(i))

    def observe_pager(self, snap: dict[str, Any]) -> None:
        """Paged room-state plane (runtime/pager.py stats()): HBM page
        pool occupancy, fragmentation, and churn counters. Only emitted
        when the plane runs paged — a dense plane has no pager."""
        self.set_gauge("livekit_page_pool_used", snap.get("pages_used", 0))
        self.set_gauge("livekit_page_pool_total", snap.get("pages_total", 0))
        self.set_gauge(
            "livekit_page_fragmentation_ratio",
            snap.get("fragmentation_ratio", 0.0),
        )
        self.set_gauge(
            "livekit_page_internal_slack", snap.get("internal_slack", 0)
        )
        # Mapped fraction of the pool == the paged kernel's scheduled-
        # grid fraction (ops/paged_kernel.py: one grid step per live
        # page — dead pages are never scheduled).
        self.set_gauge(
            "livekit_page_live_fraction", snap.get("page_live_fraction", 0.0)
        )
        for k in ("allocs", "frees", "grows", "compactions",
                  "alloc_failures", "table_repairs"):
            self.set_gauge(f"livekit_pager_{k}_total", snap.get(k, 0))

    def observe_queue_drops(self) -> None:
        """Bus/signal back-pressure drops (the QueueFull paths that used
        to lose messages with at most a local count): process-wide
        class counters read at scrape/tick time."""
        from livekit_server_tpu.routing.kv import Subscription
        from livekit_server_tpu.routing.messagechannel import MessageChannel

        self.set_gauge(
            "livekit_signal_channel_dropped_total", MessageChannel.total_dropped
        )
        self.set_gauge(
            "livekit_bus_sub_dropped_total", Subscription.total_dropped
        )

    def observe_transport(self, stats: dict[str, Any]) -> None:
        """UDP/TCP media-wire counters (prometheus/packets.go direction
        labels: rx/tx, plus NACK/PLI/RTX feedback volumes)."""
        for k in ("rx", "tx", "rtx_tx", "nacks_rx", "nacks_tx",
                  "plis_rx", "plis_tx", "bad_frame", "red_tx", "red_rx"):
            if k in stats:
                self.set_gauge(f"livekit_media_{k}_total", stats[k])

    def observe_tick_latency(self, tick_s: float) -> None:
        # Tick work time gets its own family now;
        # livekit_forward_latency_ms is fed by the attribution sampler
        # (observe_wire_stages) with true arrival→wire packet latencies.
        self.histograms["livekit_tick_duration_ms"].observe(tick_s * 1000.0)

    def observe_wire_stages(self, drained: dict[str, Any]) -> None:
        """Sampled per-stage wire-latency arrays (runtime/trace.py
        LatencyAttribution.drain()) → stage histograms, with the end-to-
        end samples also feeding livekit_forward_latency_ms ('total'
        already covers BOTH tiers — the express observer pushes each
        sample into 'express' and 'total')."""
        for stage, vals in drained.items():
            if not len(vals):
                continue
            h = self.stage_hists.get(stage)
            if h is None:
                h = self.stage_hists[stage] = Histogram(_STAGE_BUCKETS)
            h.observe(vals)
            if stage == "total":
                self.histograms["livekit_forward_latency_ms"].observe(vals)

    def observe_tracks(self, loss_pct, jitter_ms, bps) -> None:
        """Windowed per-track receive stats (device reductions) → quality
        histograms; called when the ~1 s stats window rolls."""
        self.histograms["livekit_track_loss_percent"].observe(loss_pct)
        self.histograms["livekit_track_jitter_ms"].observe(jitter_ms)
        self.histograms["livekit_track_bitrate_kbps"].observe(
            np.asarray(bps, np.float64) / 1000.0
        )

    def track_stat(self, **record: Any) -> None:
        """One per-track analytics record (statsworker.go AnalyticsStat)."""
        record["ts"] = int(time.time())
        self.track_stats.append(record)
        if len(self.track_stats) > 2000:
            del self.track_stats[: len(self.track_stats) - 2000]

    def prometheus_text(self) -> str:
        lines: list[str] = []
        seen: set[str] = set()

        def header(key: str, mtype: str) -> None:
            fam = key.split("{", 1)[0]
            if fam in seen:
                return
            seen.add(fam)
            lines.append(f"# HELP {fam} {_HELP.get(fam, fam)}")
            lines.append(f"# TYPE {fam} {mtype}")

        for key, v in sorted(self.counters.items()):
            header(key, "counter")
            lines.append(f"{key} {v:g}")
        for key, v in sorted(self.gauges.items()):
            header(key, "gauge")
            lines.append(f"{key} {v:g}")
        for name, h in sorted(self.histograms.items()):
            header(name, "histogram")
            h.render(name, lines)
        if self.stage_hists:
            header("livekit_wire_latency_stage_ms", "histogram")
            for stage, h in sorted(self.stage_hists.items()):
                h.render(
                    "livekit_wire_latency_stage_ms", lines, {"stage": stage}
                )
        return "\n".join(lines) + "\n"

    async def close(self) -> None:
        await self.webhook.close()


def _key(name: str, labels: dict[str, str]) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"
