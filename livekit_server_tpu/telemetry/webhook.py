"""Webhook notifier: signed POSTs of lifecycle events.

Reference parity: livekit/protocol webhook notifier as configured by
config.go WebHookConfig and fed from telemetry events — each event is
POSTed to every configured URL with an Authorization JWT whose sha256
claim covers the body (the reference's webhook verification scheme).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
from typing import Any

from livekit_server_tpu.auth.token import AccessToken
from livekit_server_tpu.config.config import Config


class WebhookNotifier:
    def __init__(self, config: Config, client=None):
        self.urls = list(config.webhook.urls)
        self.api_key = config.webhook.api_key or (
            next(iter(config.keys)) if config.keys else ""
        )
        self.api_secret = config.keys.get(self.api_key, "")
        self._client = client  # injectable for tests; lazy aiohttp otherwise
        self._tasks: set[asyncio.Task] = set()
        self.sent = 0
        self.failed = 0

    def queue(self, event: dict[str, Any]) -> None:
        if not self.urls:
            return
        try:
            task = asyncio.ensure_future(self._send(event))
        except RuntimeError:
            return  # no running loop (sync tests): drop
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    def _sign(self, body: bytes) -> str:
        import base64

        tok = AccessToken(self.api_key, self.api_secret)
        tok.identity = self.api_key
        tok.ttl = 300
        # sha256 claim covers the body (livekit webhook verification)
        tok.sha256 = base64.b64encode(hashlib.sha256(body).digest()).decode()
        return tok.to_jwt()

    async def _send(self, event: dict[str, Any]) -> None:
        body = json.dumps(event).encode()
        headers = {
            "Authorization": self._sign(body),
            "Content-Type": "application/webhook+json",
        }
        for url in self.urls:
            try:
                if self._client is not None:
                    await self._client(url, body, headers)
                else:
                    import aiohttp

                    async with aiohttp.ClientSession() as s:
                        async with s.post(
                            url, data=body, headers=headers,
                            timeout=aiohttp.ClientTimeout(total=5)
                        ) as resp:
                            await resp.read()
                self.sent += 1
            except Exception:  # noqa: BLE001 — webhook failures never break the room
                self.failed += 1

    async def close(self) -> None:
        for t in list(self._tasks):
            t.cancel()
