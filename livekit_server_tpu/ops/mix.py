"""Batched server-side audio mixing (the MCU seat, BASELINE config 2).

Reference parity: the reference SFU does NOT decode or mix audio —
pkg/sfu/audio/audiolevel.go is level detection only, and audio packets
forward opaque (an SFU stance; PARITY.md argues the same). This module
ships the capability anyway, TPU-first, for deployments that want a
mix bus (telephony bridges, recording, large rooms where N×M audio
fan-out exceeds the client budget):

  * decode: G.711 µ-law/A-law → linear PCM as a 256-entry table gather
    (fully vectorized — one lookup per sample across [R, T, N] at once),
    L16 passthrough. Opus decode needs libopus (not in this image and
    not reimplementable as tensor ops); the codec seam is explicit so an
    XLA custom-call wrapping libopus drops in without touching the mix.
  * mix: one einsum over [R, S, T] include-weights × [R, T, N] PCM —
    a batched matmul the MXU executes directly. Weights fold together
    active-speaker gating (top-K by level), per-subscriber self-
    exclusion (you never hear yourself), and per-track gain.
  * encode: linear → µ-law/A-law vectorized (searchsorted-free bit math).

Shapes: R rooms × T publisher tracks × S subscribers × N samples/tick
(48 kHz × tick_ms; 240 @ 5 ms). All static; vmap/shard over rooms like
the media plane.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from livekit_server_tpu.analysis.registry import device_entry

MIX_TOP_K = 3  # speakers mixed per subscriber (reference fan-out policy
               # for active speakers — room.go speaker updates top-3)


def _ulaw_table() -> np.ndarray:
    """G.711 µ-law byte → linear sample (float32 in [-1, 1))."""
    u = np.arange(256, dtype=np.uint8) ^ 0xFF
    sign = np.where(u & 0x80, -1.0, 1.0)
    exp = (u >> 4) & 0x07
    mant = u & 0x0F
    mag = ((mant.astype(np.int32) << 3) + 0x84) << exp
    return (sign * (mag - 0x84) / 32768.0).astype(np.float32)


def _alaw_table() -> np.ndarray:
    a = np.arange(256, dtype=np.uint8) ^ 0x55
    sign = np.where(a & 0x80, -1.0, 1.0)
    exp = (a >> 4) & 0x07
    mant = (a & 0x0F).astype(np.int32)
    mag = np.where(exp == 0, (mant << 4) + 8, ((mant << 4) + 0x108) << (exp - 1))
    return (sign * mag / 32768.0).astype(np.float32)


ULAW_TABLE = _ulaw_table()
ALAW_TABLE = _alaw_table()

CODEC_PCM16 = 0
CODEC_PCMU = 1
CODEC_PCMA = 2


@device_entry("mix.decode_tick")
def decode_tick(payload_u8: jax.Array, codec: jax.Array) -> jax.Array:
    """[R, T, N] raw bytes (+[R, T] codec ids) → [R, T, N] float PCM.

    PCMU/PCMA: one table gather per sample (the whole room batch decodes
    in one op). PCM16: bytes are little-endian sample pairs packed as
    [R, T, N] uint8 pairs → callers pass N = 2×samples and get N/2 out;
    for uniformity this path expects pre-unpacked int16 via decode_pcm16.
    """
    ul = jnp.asarray(ULAW_TABLE)[payload_u8.astype(jnp.int32)]
    al = jnp.asarray(ALAW_TABLE)[payload_u8.astype(jnp.int32)]
    c = codec[:, :, None]
    return jnp.where(c == CODEC_PCMA, al, ul)


def decode_pcm16(samples_i16: jax.Array) -> jax.Array:
    return samples_i16.astype(jnp.float32) / 32768.0


def encode_ulaw(pcm: jax.Array) -> jax.Array:
    """float PCM [-1, 1) → µ-law bytes, vectorized bit math (RFC G.711)."""
    x = jnp.clip(pcm, -1.0, 1.0 - 1.0 / 32768.0)
    sign = jnp.where(x < 0, 0x80, 0).astype(jnp.int32)
    mag = jnp.minimum((jnp.abs(x) * 32768.0).astype(jnp.int32) + 0x84, 0x7FFF)
    # Exponent = MSB position − 7 (mag ≥ 0x84 ⇒ MSB ∈ [7, 14]); bit math,
    # not float log, so segment boundaries are exact.
    exp = jnp.zeros_like(mag)
    for b in range(8, 15):
        exp = jnp.where(mag >= (1 << b), b - 7, exp)
    mant = (mag >> (exp + 3)) & 0x0F
    return ((sign | (exp << 4) | mant) ^ 0xFF).astype(jnp.uint8)


@device_entry("mix.mix_tick")
@functools.partial(jax.jit, static_argnames=("top_k",))
def mix_tick(
    pcm: jax.Array,        # [R, T, N] float PCM (decoded)
    level: jax.Array,      # [R, T] linear levels (ops/audio observe_tick)
    active: jax.Array,     # [R, T] bool — audio present this tick
    sub_track: jax.Array,  # [R, S] — each subscriber's own track (-1 none)
    gain: jax.Array,       # [R, T] per-track gain
    top_k: int = MIX_TOP_K,
):
    """Per-subscriber active-speaker mix: [R, S, N] output PCM.

    The include weight folds speaker selection, self-exclusion, and gain
    into one [R, S, T] matrix; the mix itself is a single einsum
    "rst,rtn->rsn" — a batched matmul that lands on the MXU with N on
    the lane axis. No per-subscriber loop anywhere.
    """
    R, T, N = pcm.shape
    S = sub_track.shape[1]
    k = min(top_k, T)
    # Top-K speaker gate per room (shared across subscribers, like the
    # reference's room-level active-speaker list).
    lv = jnp.where(active, level, -1.0)
    kth = jnp.sort(lv, axis=-1)[:, T - k][:, None]               # [R, 1]
    speak = active & (lv >= jnp.maximum(kth, 0.0))               # [R, T]
    w = speak[:, None, :] & (
        jnp.arange(T, dtype=jnp.int32)[None, None, :] != sub_track[:, :, None]
    )                                                            # [R, S, T]
    weights = w.astype(jnp.float32) * gain[:, None, :]
    mixed = jnp.einsum("rst,rtn->rsn", weights, pcm)
    # Soft clip: a 3-speaker sum can exceed full scale.
    return jnp.tanh(mixed)
