"""Batched bandwidth estimation: channel observation + trend detection.

Reference parity: pkg/sfu/streamallocator — ChannelObserver
(channelobserver.go:77-170), TrendDetector (trenddetector.go:73-200),
NackTracker (nacktracker.go), RateMonitor, and the congestion-state
machine of the StreamAllocator event loop (streamallocator.go:563-720,
100 ms tick :575).

TPU-first re-design: one state row per subscriber peer connection; the
estimate history is a fixed ring [W]; the trend statistic is a dot product
of the (time-ordered) history with a centered linear-regression weight
vector — the whole per-tick update over all subscribers is one fused
elementwise + matvec kernel (the "BWE per-tick batched matmul" of the north
star). Probe *scheduling* stays host-side (probe_controller timing), fed by
the `probe_good` / congestion outputs here.

Congestion states (streamallocator.go State): 0 = clear, 1 = congested.
Trend directions (trenddetector.go): -1 lowering, 0 neutral, +1 upgrading.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

WINDOW = 8  # estimate samples per trend window (trenddetector RequiredSamples)


class BWEParams(NamedTuple):
    """Mirrors config congestion-control tuning (config.go CongestionControlConfig)."""

    nack_ratio_threshold: float = 0.08   # nacktracker.go ratio threshold
    nack_window_min_packets: int = 10
    estimate_required_downgrades: int = 3  # lowering samples to call a downtrend
    congested_min_estimate: float = 100_000.0  # floor on usable estimate
    stale_ticks: int = 50  # a downtrend older than this many sample-less
                           # ticks no longer holds the channel congested
                           # (channelobserver windows age out; without this
                           # a client that stops reporting would freeze the
                           # congested state and starve the probe controller)


class BWEState(NamedTuple):
    """Per-subscriber-PC state; fields are [..., S]."""

    estimate_ring: jax.Array   # [..., S, W] float32 — recent estimate samples
    ring_pos: jax.Array        # [..., S] int32 — next write slot
    last_estimate: jax.Array   # [..., S] float32 — latest committed estimate
    nack_packets: jax.Array    # [..., S] float32 — window packet count
    nack_count: jax.Array      # [..., S] float32 — window nack count
    congested: jax.Array       # [..., S] bool
    committed_channel_capacity: jax.Array  # [..., S] float32 — allocator budget
    ticks_since_sample: jax.Array  # [..., S] int32 — staleness counter


def init_state(num_subscribers: int, initial_estimate: float = 7_000_000.0) -> BWEState:
    s = (num_subscribers,)
    return BWEState(
        estimate_ring=jnp.full(s + (WINDOW,), initial_estimate, jnp.float32),
        ring_pos=jnp.zeros(s, jnp.int32),
        last_estimate=jnp.full(s, initial_estimate, jnp.float32),
        nack_packets=jnp.zeros(s, jnp.float32),
        nack_count=jnp.zeros(s, jnp.float32),
        congested=jnp.zeros(s, jnp.bool_),
        committed_channel_capacity=jnp.full(s, initial_estimate, jnp.float32),
        ticks_since_sample=jnp.zeros(s, jnp.int32),
    )


def _trend_weights() -> jax.Array:
    """Centered linear-regression slope weights over the window."""
    x = jnp.arange(WINDOW, dtype=jnp.float32)
    xc = x - jnp.mean(x)
    return xc / jnp.sum(xc * xc)


def update_tick(
    state: BWEState,
    params: BWEParams,
    estimate: jax.Array,        # [S] float32 — new TWCC/REMB estimate sample
    estimate_valid: jax.Array,  # [S] bool — a sample arrived this tick
    pkts_sent: jax.Array,       # [S] float32 — packets sent this tick
    nacks: jax.Array,           # [S] float32 — NACKs received this tick
):
    """One BWE tick over all subscribers.

    Returns (new_state, congested [S] bool, trend [S] int32,
    available_capacity [S] float32). `available_capacity` is the committed
    channel capacity the allocator should budget against
    (streamallocator.go handleSignalEstimate → allocateAllTracks).
    """
    # --- estimate ring update (only where a sample arrived) ---
    pos = state.ring_pos % WINDOW
    ring = jnp.where(
        estimate_valid[..., None],
        _scatter_ring(state.estimate_ring, pos, estimate),
        state.estimate_ring,
    )
    ring_pos = jnp.where(estimate_valid, state.ring_pos + 1, state.ring_pos)
    last_estimate = jnp.where(estimate_valid, estimate, state.last_estimate)

    # --- trend: slope of time-ordered ring ---
    # Rotation moved onto the WEIGHTS instead of the data: gathering the
    # ring per subscriber (take_along_axis) lowered to a TPU gather that
    # measured ~0.8 ms/tick at cfg4; rotating the constant 8-tap weight
    # vector via one-hot keeps everything elementwise and fused. The mean
    # is rotation-invariant.
    ranks = (
        jnp.arange(WINDOW, dtype=jnp.int32) - pos[..., None] - 1
    ) % WINDOW                                                   # [S, W]
    w_rot = jnp.sum(
        jax.nn.one_hot(ranks, WINDOW, dtype=jnp.float32)
        * _trend_weights()[None, :],
        axis=-1,
    )                                                            # [S, W]
    slope = jnp.sum(ring * w_rot, axis=-1)  # [S]
    mean = jnp.mean(ring, axis=-1)
    rel_slope = slope / jnp.maximum(mean, 1.0)
    trend = jnp.where(rel_slope < -0.02, -1, jnp.where(rel_slope > 0.02, 1, 0)).astype(jnp.int32)

    # --- nack ratio window ---
    nack_packets = state.nack_packets + pkts_sent
    nack_count = state.nack_count + nacks
    ratio = nack_count / jnp.maximum(nack_packets, 1.0)
    nack_bad = (nack_packets >= params.nack_window_min_packets) & (
        ratio > params.nack_ratio_threshold
    )

    # --- congestion state machine (channelobserver GetTrend semantics:
    # lowering estimate or high nack ratio ⇒ congested). A downtrend only
    # counts while samples are fresh: with no reports the window is stale
    # and must not pin the channel congested forever.
    ticks_since = jnp.where(estimate_valid, 0, state.ticks_since_sample + 1)
    congested = ((trend < 0) & (ticks_since < params.stale_ticks)) | nack_bad
    # Commit capacity on congestion onset; recover to estimate when clear.
    committed = jnp.where(
        congested,
        jnp.maximum(
            jnp.minimum(state.committed_channel_capacity, last_estimate),
            params.congested_min_estimate,
        ),
        last_estimate,
    )

    # Decay the nack window each tick (rolling window approximation).
    new_state = BWEState(
        estimate_ring=ring,
        ring_pos=ring_pos,
        last_estimate=last_estimate,
        nack_packets=nack_packets * 0.5,
        nack_count=nack_count * 0.5,
        congested=congested,
        committed_channel_capacity=committed,
        ticks_since_sample=ticks_since,
    )
    return new_state, congested, trend, committed


def _scatter_ring(ring: jax.Array, pos: jax.Array, value: jax.Array) -> jax.Array:
    """ring[..., pos] = value without dynamic slicing (one-hot mask)."""
    oh = jax.nn.one_hot(pos, ring.shape[-1], dtype=ring.dtype)
    return ring * (1.0 - oh) + oh * value[..., None]


# ---------------------------------------------------------------------------
# Send-side delay-based estimation (TWCC seat).
#
# Reference parity: the reference wires pion's cc.BandwidthEstimator (GCC)
# fed by transport-wide-cc feedback (pkg/rtc/transport.go:253-374) into the
# StreamAllocator (streamallocator.go:304-391 OnREMB/onTargetBitrateChange).
# Here the transport-wide sequence number is the sealed-frame counter the
# egress already stamps on every datagram (runtime/crypto.py layout); the
# host matches client feedback (runtime/udp.py TWCC frames) against its
# send-time ring and reduces each tick's feedback to THREE per-subscriber
# samples: mean delay-variation, acked receive rate, and validity. The
# estimator itself — an EMA'd queuing-delay gradient driving an AIMD rate,
# GCC's shape without the Kalman filter — then updates every subscriber in
# one elementwise pass per tick.
#
# Trust model (the reason this exists): allocation must not depend on
# client-volunteered REMB estimates. A client that sends no feedback at all
# while sealed sends are outstanding decays toward the floor (safe), and a
# client that acks honestly converges the budget to the real channel rate
# with no estimate samples ever sent.
# ---------------------------------------------------------------------------


class DelayBWEParams(NamedTuple):
    overuse_ms: float = 1.5        # EMA'd delay-variation above ⇒ overuse
    underuse_ms: float = -1.5      # below ⇒ draining; hold rate
    ema_alpha: float = 0.3
    beta: float = 0.85             # overuse: rate = beta × acked receive rate
    increase_per_s: float = 0.08   # multiplicative increase while clear
    min_rate_bps: float = 64_000.0
    max_rate_bps: float = 50e6
    fb_timeout_ticks: int = 50     # outstanding sends, no feedback ⇒ decay
    starve_decay: float = 0.97     # per-tick rate factor once starved


class DelayBWEState(NamedTuple):
    """Per-subscriber delay-estimator state; fields [..., S]."""

    slope_ema: jax.Array     # float32 — EMA of mean delay-variation (ms)
    rate_bps: jax.Array      # float32 — delay-based target rate
    ticks_no_fb: jax.Array   # int32 — ticks with sends but no feedback
    ever_fb: jax.Array       # bool — any feedback seen (activates the cap)


def delay_init_state(num_subscribers: int, initial_rate: float = 7_000_000.0) -> DelayBWEState:
    s = (num_subscribers,)
    return DelayBWEState(
        slope_ema=jnp.zeros(s, jnp.float32),
        rate_bps=jnp.full(s, initial_rate, jnp.float32),
        ticks_no_fb=jnp.zeros(s, jnp.int32),
        ever_fb=jnp.zeros(s, jnp.bool_),
    )


def delay_update_tick(
    state: DelayBWEState,
    params: DelayBWEParams,
    fb_delay_ms: jax.Array,   # [S] float32 — mean delay-variation this tick
    fb_recv_bps: jax.Array,   # [S] float32 — acked receive rate sample
    fb_valid: jax.Array,      # [S] bool — feedback arrived this tick
    fb_enabled: jax.Array,    # [S] bool — sub rides the sealed UDP path
    pkts_sent: jax.Array,     # [S] float32 — sends this tick
    tick_ms: jax.Array,       # scalar int32
):
    """Returns (new_state, rate_bps [S], overuse [S] bool, active [S] bool).

    `active` marks subscribers whose budget the delay rate should cap
    (sealed-path subscribers that have ever acked). WS-only subscribers
    never activate and keep the estimate-driven budget path.
    """
    ema = jnp.where(
        fb_valid,
        (1.0 - params.ema_alpha) * state.slope_ema + params.ema_alpha * fb_delay_ms,
        state.slope_ema,
    )
    overuse = ema > params.overuse_ms
    underuse = ema < params.underuse_ms
    tick_s = jnp.maximum(tick_ms.astype(jnp.float32), 1.0) / 1000.0
    rate_up = state.rate_bps * (1.0 + params.increase_per_s * tick_s)
    rate_down = params.beta * jnp.maximum(fb_recv_bps, params.min_rate_bps)
    rate = jnp.where(
        fb_valid,
        jnp.where(
            overuse,
            jnp.minimum(state.rate_bps, rate_down),
            jnp.where(underuse, state.rate_bps, rate_up),
        ),
        state.rate_bps,
    )
    # Silent-client guard: sealed sends outstanding but nothing acked.
    ticks_no_fb = jnp.where(
        fb_valid | ~fb_enabled,
        0,
        state.ticks_no_fb + (pkts_sent > 0).astype(jnp.int32),
    )
    starved = ticks_no_fb > params.fb_timeout_ticks
    rate = jnp.where(starved, rate * params.starve_decay, rate)
    rate = jnp.clip(rate, params.min_rate_bps, params.max_rate_bps)
    ever_fb = state.ever_fb | (fb_valid & fb_enabled)
    active = fb_enabled & (ever_fb | starved)
    new_state = DelayBWEState(
        slope_ema=ema,
        rate_bps=rate,
        ticks_no_fb=ticks_no_fb,
        ever_fb=ever_fb,
    )
    return new_state, rate, overuse & fb_enabled, active
