"""Bit-packed (track, packet, subscriber) mask helpers.

The egress masks travel as ⌈S/32⌉ int32 words per (track, packet) — one
bit per subscriber (see models/plane.py's decide-on-device/rewrite-on-host
design note). Shared by the device tick, the room-batched decision kernel's
CPU fallback, and host-side consumers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def mask_words(num_subscribers: int) -> int:
    """Words on the bit-packed mask minor axis: ⌈S/32⌉."""
    return (num_subscribers + 31) // 32


def pack_bits(mask: jax.Array) -> jax.Array:
    """[..., S] bool → [..., W] int32 bit words (bit s%32 of word s//32)."""
    S = mask.shape[-1]
    W = mask_words(S)
    pad = W * 32 - S
    if pad:
        mask = jnp.pad(mask, [(0, 0)] * (mask.ndim - 1) + [(0, pad)])
    w = mask.reshape(*mask.shape[:-1], W, 32).astype(jnp.uint32)
    weights = jnp.left_shift(
        jnp.uint32(1), jnp.arange(32, dtype=jnp.uint32)
    )
    packed = jnp.sum(w * weights, axis=-1, dtype=jnp.uint32)
    return jax.lax.bitcast_convert_type(packed, jnp.int32)


def unpack_bits(words, num_subscribers: int):
    """Host-side inverse of `pack_bits`: [..., W] int32 → [..., S] bool."""
    import numpy as np

    w = np.asarray(words).astype(np.uint32)
    bits = (w[..., None] >> np.arange(32, dtype=np.uint32)) & 1
    return bits.reshape(*w.shape[:-1], -1)[..., :num_subscribers].astype(bool)
