"""Pure-JAX kernel math for the media plane.

These are the batched, unit-testable equivalents of the reference's
pure-logic hot-path components (SURVEY.md §7 step 2):

  - seqnum      — wrap-aware RTP SN/TS arithmetic (pkg/sfu/utils/wraparound.go)
  - rtpmunger   — SN/TS rewrite with gap compaction (pkg/sfu/rtpmunger.go)
  - vp8         — VP8 payload-descriptor rewriting (pkg/sfu/codecmunger/vp8.go)
  - audio       — RFC6464 active-speaker levels (pkg/sfu/audio/audiolevel.go)
  - selector    — simulcast/temporal layer selection (pkg/sfu/videolayerselector)
  - svc         — VP9 SVC onion + dependency-descriptor decode targets
                  (videolayerselector/vp9.go, dependencydescriptor.go)
  - allocation  — forwarder bandwidth-allocation algebra (pkg/sfu/forwarder.go)
  - bwe         — trend detection / channel observation (pkg/sfu/streamallocator)
  - quality     — E-model connection-quality scoring (pkg/sfu/connectionquality)
  - streamtracker — per-layer liveness/bitrate windows (pkg/sfu/streamtracker)
  - red         — RFC 2198 Opus redundancy planning (pkg/sfu/redreceiver.go)
  - pacer       — per-subscriber leaky-bucket egress pacing (pkg/sfu/pacer)

Everything here is functional: `update(state, inputs) -> (state, outputs)`,
jit/vmap/shard_map-friendly, static shapes, int32 modular arithmetic (no x64).
"""
