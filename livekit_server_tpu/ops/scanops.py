"""Small-axis prefix ops that avoid TPU's pathological scan lowerings.

`jnp.cumsum` lowers to `reduce-window` on TPU, and shift-add prefix sums
(via jnp.pad or concatenate) lower to pad/dynamic-update-slice chains —
at the media plane's tiny static axes (4 spatial layers, K ≤ 16 packet
slots) each measured milliseconds per tick for microseconds of work.
A contraction against an n×n triangular matrix fuses cleanly instead.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cumsum_small(x: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Inclusive prefix sum along a SMALL static axis as a triangular-
    matrix contraction: out_i = Σ_{j≤i} x_j.

    Precision: integer inputs contract in their own dtype — exact, and
    that covers the byte-count/packet-count sums this serves. Float
    inputs use Precision.HIGHEST (TPU's default matmul precision
    truncates float32 operands to bfloat16, which would visibly corrupt
    these sums) — but a matmul accumulates each prefix in one reduction
    order while `jnp.cumsum` folds sequentially, so general float
    results only match a sequential cumsum to within a few ulps, not
    bit-exactly. Float values exactly representable with headroom (e.g.
    byte counts cast to f32 below 2^24) still come out exact.
    """
    n = x.shape[axis]
    axis = axis % x.ndim
    if n == 1:
        return x
    xm = jnp.moveaxis(x, axis, -1)
    tri = jnp.tril(jnp.ones((n, n), x.dtype))          # [i, j≤i]
    if jnp.issubdtype(x.dtype, jnp.integer):
        ym = jnp.einsum("...j,ij->...i", xm, tri)
    else:
        ym = jnp.einsum(
            "...j,ij->...i", xm, tri,
            precision=jax.lax.Precision.HIGHEST,
        )
    return jnp.moveaxis(ym, -1, axis)
