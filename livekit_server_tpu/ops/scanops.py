"""Small-axis prefix ops that stay elementwise.

`jnp.cumsum` lowers to `reduce-window` on TPU; at the media plane's tiny
static axes (4 spatial layers, K ≤ 16 packet slots) that lowering measured
~2.7 ms of an 8 ms cfg4 tick — three orders slower than the work it does.
These helpers express the same prefix sums as log₂(n) shift-adds, which
XLA fuses into the surrounding elementwise graph for free.
"""

from __future__ import annotations

import jax.numpy as jnp


def cumsum_small(x: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Inclusive prefix sum along a SMALL static axis via log-shift adds.

    Bit-exact with jnp.cumsum for ints; for floats the summation order
    differs (pairwise vs serial) — fine for the EMA/bitrate uses here.
    """
    n = x.shape[axis]
    axis = axis % x.ndim
    shift = 1
    while shift < n:
        sl = [slice(None)] * x.ndim
        sl[axis] = slice(0, n - shift)
        zshape = list(x.shape)
        zshape[axis] = shift
        # concatenate, not jnp.pad: pad lowers to a dynamic-update-slice
        # that measured ~0.3 ms/tick at cfg4; concat fuses.
        x = x + jnp.concatenate(
            [jnp.zeros(zshape, x.dtype), x[tuple(sl)]], axis=axis
        )
        shift *= 2
    return x
