"""Batched RTP sequence-number / timestamp munging.

Reference parity: pkg/sfu/rtpmunger.go (UpdateAndGetSnTs :183-271, SN-gap
compaction via RangeMap offsets, PacketDropped, padding synthesis) and the
source-switch re-anchoring in pkg/sfu/forwarder.go (processSourceSwitch
:1456-1650). State snapshot/seed mirrors RTPMungerState (rtpmunger.go:53-69).

TPU-first re-design
-------------------
The reference runs one stateful munger per (downtrack) with an ordered
RangeMap of SN exclusion ranges — inherently serial per stream. Here the same
semantics are expressed as a *tick-batched scan*: each tick delivers up to P
ordered packets per track; per-subscriber offsets are carried in state
tensors and updated by a `lax.scan` over the (small, static) packet axis,
vectorized over the subscriber axis. Gap compaction becomes an increment of
the per-subscriber SN offset for each dropped current-stream packet — the
bounded-history reformulation of RangeMap called out in SURVEY.md §7.

All arithmetic is modular int32 (see ops.seqnum): out_sn is 16-bit, out_ts is
32-bit two's-complement.

Shapes (per track):
  P = max packets per tick (static), S = max subscribers (static).
  Packet fields are [P]; masks are [P, S]; state fields are [S].

Masks per (packet, subscriber):
  forward — packet is sent to the subscriber (selected layer, passes filters)
  drop    — packet belongs to the subscriber's *current* stream but is
            dropped (temporal filter / padding-only) ⇒ compact the gap
            (reference: PacketDropped → RangeMap exclusion)
  switch  — subscriber switches source stream at this packet ⇒ re-anchor
            offsets so out SN continues at last_sn+1 and out TS jumps by
            `switch_ts_jump` (reference: processSourceSwitch)
Packets that are neither forwarded nor dropped for a subscriber (other
simulcast layers' packets) do not touch that subscriber's state.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from livekit_server_tpu.ops import seqnum


class MungerState(NamedTuple):
    """Per-(track, subscriber) munger state; fields are [...,S] int32/bool.

    Serializable checkpoint — the analog of RTPMungerState
    (pkg/sfu/rtpmunger.go:53-69) used for migration seeding.
    """

    sn_offset: jax.Array  # mod 2^16: out_sn = in_sn - sn_offset
    ts_offset: jax.Array  # mod 2^32: out_ts = in_ts - ts_offset
    last_sn: jax.Array    # last outgoing 16-bit SN
    last_ts: jax.Array    # last outgoing 32-bit TS
    started: jax.Array    # bool: offsets are valid
    ts_anchor_aligned: jax.Array  # bool: ts_offset was anchored on a
                                  # common-timeline (SR-normalized) packet —
                                  # only then may an aligned switch carry
                                  # the offset through unchanged


# A forwarded (non-switch) packet whose output TS would jump by more than
# this re-anchors instead: the input timeline shifted under us (e.g. SR
# alignment kicked in mid-stream and renumbered the layer's TS space).
REANCHOR_TS_THRESH = 900_000  # 10 s @ 90 kHz
FALLBACK_TS_JUMP = 3000       # one frame @ 90 kHz / 30 fps


def init_state(num_subscribers: int) -> MungerState:
    z = jnp.zeros((num_subscribers,), jnp.int32)
    f = jnp.zeros((num_subscribers,), jnp.bool_)
    return MungerState(
        sn_offset=z,
        ts_offset=z,
        last_sn=z,
        last_ts=z,
        started=f,
        ts_anchor_aligned=f,
    )


def munge_tick(
    state: MungerState,
    pkt_sn: jax.Array,         # [P] int32 (16-bit values)
    pkt_ts: jax.Array,         # [P] int32 (32-bit values)
    pkt_valid: jax.Array,      # [P] bool
    forward: jax.Array,        # [P, S] bool
    drop: jax.Array,           # [P, S] bool
    switch: jax.Array,         # [P, S] bool
    switch_ts_jump: jax.Array, # [P] int32 — TS advance applied at a switch;
                               # -1 = the host already normalized this
                               # packet's TS onto the track's common
                               # timeline (SR-based cross-layer alignment,
                               # forwarder.go:1456 processSourceSwitch), so
                               # the existing ts_offset stays valid and no
                               # re-anchor happens.
):
    """One tick of SN/TS munging for one track.

    Returns (new_state, out_sn [P,S], out_ts [P,S], send [P,S]).
    Equivalent of running rtpmunger.go UpdateAndGetSnTs over each forwarded
    packet and PacketDropped over each dropped one, per subscriber.
    """

    def step(carry: MungerState, xs):
        sn, ts, valid, fwd, drp, sw, jump = xs
        fwd = fwd & valid
        drp = drp & valid & ~fwd
        sw = sw & fwd
        pkt_aligned = jump < 0
        jump_eff = jnp.where(pkt_aligned, FALLBACK_TS_JUMP, jump)

        # Source switch: continue output SN at last_sn + 1, TS at
        # last_ts + jump — unless BOTH this packet and the current anchor
        # sit on the SR-normalized common timeline, in which case the
        # existing ts_offset already maps it exactly (no guess needed).
        sw_sn_off = seqnum.sub16(sn, seqnum.add16(carry.last_sn, 1))
        sw_ts_off = seqnum.sub32(ts, seqnum.add32(carry.last_ts, jump_eff))
        carry_through = pkt_aligned & carry.ts_anchor_aligned
        sw_ts_off = jnp.where(carry_through, carry.ts_offset, sw_ts_off)
        # First packet ever: identity mapping (reference SetLastSnTs seeds
        # outgoing = incoming on the first packet).
        fresh = fwd & ~carry.started
        resync = sw & carry.started
        # Timeline shear guard: a continuing (non-switch) forward whose
        # output TS would leap implausibly far means the INPUT timeline
        # moved under this subscriber (SR alignment starting mid-stream
        # renumbers a layer's TS space) — re-anchor with the fallback jump
        # instead of emitting a 2^31-size discontinuity.
        cur_out_ts = seqnum.sub32(ts, carry.ts_offset)
        shear = seqnum.sub32(cur_out_ts, carry.last_ts)
        sheared = fwd & ~sw & carry.started & (jnp.abs(shear) > REANCHOR_TS_THRESH)
        shear_ts_off = seqnum.sub32(ts, seqnum.add32(carry.last_ts, FALLBACK_TS_JUMP))

        anchor = fresh | resync | sheared
        sn_offset = jnp.where(resync, sw_sn_off, jnp.where(fresh, 0, carry.sn_offset))
        ts_offset = jnp.where(
            sheared, shear_ts_off,
            jnp.where(resync, sw_ts_off, jnp.where(fresh, 0, carry.ts_offset)),
        )
        ts_anchor_aligned = jnp.where(
            anchor, pkt_aligned, carry.ts_anchor_aligned
        )

        out_sn = seqnum.sub16(sn, sn_offset)
        out_ts = seqnum.sub32(ts, ts_offset)

        last_sn = jnp.where(fwd, out_sn, carry.last_sn)
        last_ts = jnp.where(fwd, out_ts, carry.last_ts)
        # Gap compaction: dropped current-stream packet ⇒ future out SNs shift
        # down by one (reference RangeMap exclusion range).
        sn_offset = jnp.where(drp & carry.started, seqnum.add16(sn_offset, 1), sn_offset)
        started = carry.started | fwd

        new_carry = MungerState(
            sn_offset, ts_offset, last_sn, last_ts, started, ts_anchor_aligned
        )
        return new_carry, (out_sn, out_ts, fwd)

    xs = (pkt_sn, pkt_ts, pkt_valid, forward, drop, switch, switch_ts_jump)
    new_state, (out_sn, out_ts, send) = jax.lax.scan(step, state, xs, unroll=True)
    return new_state, out_sn, out_ts, send


def padding_tick(
    state: MungerState,
    num: jax.Array,        # [S] int32 — padding packets to synthesize per sub
    max_num: int,          # static upper bound on num
    ts_advance: jax.Array, # [S] int32 — TS advance for the first padding pkt
):
    """Synthesize `num` padding packets per subscriber after the last sent one.

    Reference parity: rtpmunger.go UpdateAndGetPaddingSnTs (padding for probing
    via DownTrack.WritePaddingRTP downtrack.go:764-859). Padding advances the
    outgoing SN space without a source packet, so the SN offset moves backward
    (future source packets keep compact numbering).

    Returns (new_state, pad_sn [max_num,S], pad_ts [max_num,S], valid [max_num,S]).
    """
    ks = jnp.arange(max_num, dtype=jnp.int32)[:, None]  # [max_num, 1]
    valid = (ks < num[None, :]) & state.started[None, :]
    pad_sn = seqnum.add16(state.last_sn[None, :], ks + 1)
    # All padding packets in one burst share the advanced TS (they carry no
    # media; UpdateAndGetPaddingSnTs gives the whole run one timestamp).
    pad_ts = jnp.broadcast_to(
        seqnum.add32(state.last_ts[None, :], ts_advance[None, :]),
        (max_num, num.shape[-1]),
    )
    n = jnp.where(state.started, num, 0)
    new_state = MungerState(
        # Outgoing SN space advanced by n with no incoming packets ⇒ offset -= n.
        sn_offset=seqnum.sub16(state.sn_offset, n),
        ts_offset=state.ts_offset,
        last_sn=jnp.where(n > 0, seqnum.add16(state.last_sn, n), state.last_sn),
        last_ts=jnp.where(n > 0, seqnum.add32(state.last_ts, ts_advance), state.last_ts),
        started=state.started,
        ts_anchor_aligned=state.ts_anchor_aligned,
    )
    return new_state, pad_sn, pad_ts, valid
