"""Batched per-layer stream liveness + bitrate + frame-rate tracking.

Reference parity: pkg/sfu/streamtracker — ALL three variants behind
`StreamTrackerImpl`: packet-count cycles (streamtracker_packet.go),
frame-boundary cycles (streamtracker_frame.go — low-fps screenshare
layers must not flap LIVE/STOPPED just because they send few packets),
and DD-driven per-layer liveness (streamtracker_dd.go — an SVC stream's
layer is live when frames targeting that spatial layer keep arriving);
plus fps estimation (buffer/fps.go) and StreamTrackerManager's
available-layer + Bitrates reporting (streamtrackermanager.go:60-732).

The reference runs one tracker goroutine per (track, layer) with sample
windows and picks ONE variant per source kind; here one row per
(track, layer) stream updates every tick with pure elementwise ops and
the packet and frame rules are both evaluated — a stream is LIVE if
either holds, which subsumes the per-kind variant selection (a camera
layer satisfies the packet rule, a 2 fps screenshare the frame rule).
The DD variant falls out of the feed: the plane routes tracker counts by
each packet's TRUE spatial layer (the DD/VP9-refined one for SVC), so an
SVC track's per-layer rows go LIVE/STOPPED exactly as decode targets
appear/vanish.

Semantics kept:
  - a layer goes LIVE after >= `min_pkts` packets OR >= `min_frames`
    frame starts within a cycle window
  - a layer goes STOPPED after `stop_ms` without any packet
  - per-layer bitrate is an EMA over per-cycle byte counts, reported as
    bps (feeds the allocator's [4][4] Bitrates matrix — receiver.go:49)
  - per-layer fps is an EMA over per-cycle frame starts (fps.go)
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

STOPPED = 0
LIVE = 1


class TrackerParams(NamedTuple):
    """config StreamTrackersConfig (config.go) equivalents."""

    cycle_ms: int = 500        # samplesRequired window (streamtracker.go)
    min_pkts: int = 5          # packets per cycle to declare live
    min_frames: int = 1        # frame starts per cycle to declare live
                               # (streamtracker_frame.go: a 2 fps layer
                               # sends ~1 frame / 500 ms window)
    stop_ms: int = 1000        # silence to declare stopped
    bitrate_alpha: float = 0.3  # per-cycle EMA weight
    fps_alpha: float = 0.3      # per-cycle fps EMA weight (fps.go)


class TrackerState(NamedTuple):
    """Per-stream rows [..., N] (N = tracks × layers)."""

    status: jax.Array        # int32 — STOPPED / LIVE
    cycle_pkts: jax.Array    # int32 — packets in current cycle
    cycle_ms: jax.Array      # int32 — elapsed ms in cycle
    silent_ms: jax.Array     # int32 — ms since last packet
    cycle_bytes: jax.Array   # float32 — bytes in current cycle
    bitrate_bps: jax.Array   # float32 — smoothed bitrate
    cycle_frames: jax.Array  # int32 — frame starts in current cycle
    fps: jax.Array           # float32 — smoothed frame rate


def init_state(num_streams: int) -> TrackerState:
    z = lambda dt: jnp.zeros((num_streams,), dt)
    return TrackerState(
        status=z(jnp.int32),
        cycle_pkts=z(jnp.int32),
        cycle_ms=z(jnp.int32),
        silent_ms=z(jnp.int32),
        cycle_bytes=z(jnp.float32),
        bitrate_bps=z(jnp.float32),
        cycle_frames=z(jnp.int32),
        fps=z(jnp.float32),
    )


def update_tick(
    state: TrackerState,
    params: TrackerParams,
    pkts: jax.Array,      # [..., N] int32 — packets observed this tick
    byts: jax.Array,      # [..., N] int32 — bytes observed this tick
    tick_ms: jax.Array,   # scalar int32
    frames: jax.Array | None = None,  # [..., N] int32 — frame starts
):
    """Returns (state, status [N], changed [N] bool, bitrate_bps [N],
    fps [N])."""
    tick_ms = jnp.asarray(tick_ms, jnp.int32)
    if frames is None:
        frames = jnp.zeros_like(pkts)
    got = pkts > 0
    silent_ms = jnp.where(got, 0, state.silent_ms + tick_ms)
    cycle_pkts = state.cycle_pkts + pkts
    cycle_frames = state.cycle_frames + frames
    cycle_bytes = state.cycle_bytes + byts.astype(jnp.float32)
    cycle_ms = state.cycle_ms + tick_ms

    cycle_done = cycle_ms >= params.cycle_ms
    # Packet rule OR frame rule: the frame rule keeps a low-fps
    # screenshare layer LIVE when its packet count never reaches
    # min_pkts in a cycle (streamtracker_frame.go).
    went_live = cycle_done & (
        (cycle_pkts >= params.min_pkts) | (cycle_frames >= params.min_frames)
    )
    went_dead = silent_ms >= params.stop_ms

    status = state.status
    status = jnp.where(went_live, LIVE, status)
    status = jnp.where(went_dead, STOPPED, status)
    changed = status != state.status

    # Bitrate + fps: commit the cycle's counts into EMAs at cycle end.
    cycle_s = jnp.maximum(cycle_ms.astype(jnp.float32), 1.0) / 1000.0
    inst_bps = cycle_bytes * 8.0 / cycle_s
    a = jnp.float32(params.bitrate_alpha)
    bitrate = jnp.where(
        cycle_done,
        jnp.where(
            state.bitrate_bps > 0, state.bitrate_bps * (1 - a) + inst_bps * a, inst_bps
        ),
        state.bitrate_bps,
    )
    bitrate = jnp.where(status == STOPPED, 0.0, bitrate)
    inst_fps = cycle_frames.astype(jnp.float32) / cycle_s
    fa = jnp.float32(params.fps_alpha)
    fps = jnp.where(
        cycle_done,
        jnp.where(state.fps > 0, state.fps * (1 - fa) + inst_fps * fa, inst_fps),
        state.fps,
    )
    fps = jnp.where(status == STOPPED, 0.0, fps)

    new_state = TrackerState(
        status=status,
        cycle_pkts=jnp.where(cycle_done, 0, cycle_pkts),
        cycle_ms=jnp.where(cycle_done, 0, cycle_ms),
        silent_ms=silent_ms,
        cycle_bytes=jnp.where(cycle_done, 0.0, cycle_bytes),
        bitrate_bps=bitrate,
        cycle_frames=jnp.where(cycle_done, 0, cycle_frames),
        fps=fps,
    )
    return new_state, status, changed, bitrate, fps
