"""Batched connection-quality scoring (simplified E-model).

Reference parity: pkg/sfu/connectionquality/scorer.go:45-120 (R-factor from
loss / RTT / jitter, MOS mapping) and connectionstats.go windows; consumed
by ParticipantImpl.GetConnectionQuality (participant.go:927) and the room
connection-quality worker (room.go:1318-1396).

TPU-first re-design: scoring is pure elementwise float math over the track
(or participant) axis — one fused kernel per tick, then a segment-min
reduction to participant level.

Quality enum (livekit.ConnectionQuality): 0 POOR, 1 GOOD, 2 EXCELLENT,
3 LOST — numeric values chosen so min() aggregates to the worst.
"""

from __future__ import annotations

import jax.numpy as jnp

QUALITY_POOR = 0
QUALITY_GOOD = 1
QUALITY_EXCELLENT = 2
QUALITY_LOST = 3


def r_factor(loss_pct, rtt_ms, jitter_ms, is_deficient=None):
    """Transmission rating factor R (simplified E-model, scorer.go).

    loss_pct  [..] float32 — packet loss percentage over the window (0-100)
    rtt_ms    [..] float32
    jitter_ms [..] float32
    is_deficient [..] bool — layer allocation below optimal (distance
        penalty, forwarder DistanceToDesired feeding the scorer)
    """
    loss = jnp.asarray(loss_pct, jnp.float32)
    rtt = jnp.asarray(rtt_ms, jnp.float32)
    jitter = jnp.asarray(jitter_ms, jnp.float32)

    # Delay impairment: one-way delay estimate incl. jitter buffer.
    d = rtt / 2.0 + jitter * 2.0 + 20.0
    id_ = 0.024 * d + 0.11 * (d - 177.3) * (d > 177.3)
    # Equipment/loss impairment (Opus-ish: Ie=0, Bpl=25).
    ie_eff = 0.0 + (95.0 - 0.0) * loss / (loss + 25.0)
    r = 94.2 - id_ - ie_eff
    if is_deficient is not None:
        r = r - jnp.where(jnp.asarray(is_deficient), 10.0, 0.0)
    return jnp.clip(r, 0.0, 100.0)


def mos(r):
    """R → mean-opinion-score (ITU G.107 mapping used by scorer.go)."""
    r = jnp.asarray(r, jnp.float32)
    m = 1.0 + 0.035 * r + 7.1e-6 * r * (r - 60.0) * (100.0 - r)
    return jnp.clip(m, 1.0, 5.0)


def score_to_quality(score, has_packets):
    """MOS → ConnectionQuality enum; no packets in window ⇒ LOST
    (connectionstats.go LOST detection)."""
    q = jnp.where(
        score >= 4.1,
        QUALITY_EXCELLENT,
        jnp.where(score >= 3.5, QUALITY_GOOD, QUALITY_POOR),
    ).astype(jnp.int32)
    return jnp.where(jnp.asarray(has_packets), q, QUALITY_LOST)


def connection_quality(loss_pct, rtt_ms, jitter_ms, has_packets, is_deficient=None):
    """Full pipeline: impairments → R → MOS → quality enum. Elementwise."""
    r = r_factor(loss_pct, rtt_ms, jitter_ms, is_deficient)
    m = mos(r)
    return m, score_to_quality(m, has_packets)


def aggregate_min(quality, mask, axis=-1):
    """Worst-of aggregation (participant = min over its tracks), masked.

    LOST entries only dominate if everything is LOST, mirroring
    ParticipantImpl.GetConnectionQuality aggregation.
    """
    q = jnp.asarray(quality)
    masked = jnp.where(mask, jnp.where(q == QUALITY_LOST, QUALITY_POOR, q), QUALITY_EXCELLENT)
    worst = jnp.min(masked, axis=axis)
    all_lost = jnp.all(jnp.where(mask, q == QUALITY_LOST, True), axis=axis) & jnp.any(
        mask, axis=axis
    )
    return jnp.where(all_lost, QUALITY_LOST, worst)
