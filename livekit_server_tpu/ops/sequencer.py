"""Batched retransmission (NACK/RTX) metadata ring.

Reference parity: pkg/sfu/sequencer.go:82-370 — per-DownTrack ring mapping
munged SN → (original packet reference, layer, codec state) for NACK
replay (`getExtPacketMetas` :263), with RTT gating so a packet isn't
re-sent twice within one round trip.

TPU-first re-design: one ring per subscriber, all subscribers updated in a
single scatter per tick. The ring stores the *slab key* of the original
payload ((track<<16 | pkt_slot) of the tick it was sent in is not stable
across ticks, so the host passes a monotonically increasing slab id) —
lookup returns that key for the host/C++ egress to replay bytes from its
payload history.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

RING_BITS = 9               # 512 entries ≈ reference's default window
RING = 1 << RING_BITS


class SequencerState(NamedTuple):
    """Per-subscriber rings, fields [..., S, RING]."""

    slab_key: jax.Array      # int32 — host payload-history key (-1 empty)
    sent_sn: jax.Array       # int32 — munged SN stored at this slot
    sent_at_ms: jax.Array    # int32 — send time (for RTT gating)
    last_nack_ms: jax.Array  # int32 — last replay time


def init_state(num_subscribers: int) -> SequencerState:
    shape = (num_subscribers, RING)
    return SequencerState(
        slab_key=jnp.full(shape, -1, jnp.int32),
        sent_sn=jnp.full(shape, -1, jnp.int32),
        sent_at_ms=jnp.zeros(shape, jnp.int32),
        last_nack_ms=jnp.full(shape, -(1 << 30), jnp.int32),
    )


def push_tick(
    state: SequencerState,
    out_sn: jax.Array,     # [P, S] int32 — munged SNs sent this tick
    sent: jax.Array,       # [P, S] bool — send mask
    slab_key: jax.Array,   # [P] int32 — host payload-history keys
    now_ms: jax.Array,     # scalar int32
) -> SequencerState:
    """Record this tick's sends into each subscriber's ring (sequencer.push)."""
    P, S = out_sn.shape
    slot = out_sn & (RING - 1)                        # [P, S]
    sub = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (P, S))
    keys = jnp.broadcast_to(slab_key[:, None], (P, S))

    # Masked scatter: unsent entries write to a scratch slot we discard.
    flat_idx = jnp.where(sent, sub * RING + slot, S * RING)  # [P,S]

    def scatter(buf, vals):
        padded = jnp.concatenate([buf.reshape(-1), jnp.zeros((1,), buf.dtype)])
        padded = padded.at[flat_idx.reshape(-1)].set(vals.reshape(-1))
        return padded[:-1].reshape(buf.shape)

    return SequencerState(
        slab_key=scatter(state.slab_key, keys),
        sent_sn=scatter(state.sent_sn, jnp.where(sent, out_sn, -1)),
        sent_at_ms=scatter(state.sent_at_ms, jnp.full((P, S), now_ms, jnp.int32)),
        last_nack_ms=state.last_nack_ms,
    )


def lookup_nacks(
    state: SequencerState,
    nacked_sn: jax.Array,   # [S, M] int32 — munged SNs the subs NACKed (-1 pad)
    now_ms: jax.Array,      # scalar int32
    rtt_ms: jax.Array,      # [S] int32 — per-sub RTT (replay throttle)
):
    """Resolve NACKs → slab keys (getExtPacketMetas + RTT gate).

    Returns (state, slab_key [S, M], ok [S, M]); `ok` is False for unknown/
    evicted SNs and for SNs replayed within the last RTT.
    """
    S, M = nacked_sn.shape
    slot = nacked_sn & (RING - 1)
    sub = jnp.arange(S, dtype=jnp.int32)[:, None]
    hit = (jnp.take_along_axis(state.sent_sn, slot, axis=-1) == nacked_sn) & (
        nacked_sn >= 0
    )
    key = jnp.take_along_axis(state.slab_key, slot, axis=-1)
    last = jnp.take_along_axis(state.last_nack_ms, slot, axis=-1)
    throttled = (now_ms - last) < jnp.maximum(rtt_ms[:, None], 1)
    ok = hit & ~throttled & (key >= 0)

    # Stamp replay time on the slots we are re-sending.
    flat = jnp.where(ok, sub * RING + slot, S * RING)
    padded = jnp.concatenate([state.last_nack_ms.reshape(-1), jnp.zeros((1,), jnp.int32)])
    padded = padded.at[flat.reshape(-1)].set(jnp.full((S * M,), now_ms, jnp.int32))
    new_last = padded[:-1].reshape(state.last_nack_ms.shape)

    return state._replace(last_nack_ms=new_last), jnp.where(ok, key, -1), ok
