"""Batched retransmission (NACK/RTX) metadata ring.

Reference parity: pkg/sfu/sequencer.go:82-370 — per-DownTrack ring mapping
munged SN → (original packet reference, layer, codec state) for NACK
replay (`getExtPacketMetas` :263), with RTT gating so a packet isn't
re-sent twice within one round trip.

TPU-first re-design: ONE ring per subscriber (not per DownTrack), all
subscribers updated in a single scatter per tick. Each slot stores the
originating track alongside the munged SN, so tracks share the ring and
the hit check is (sent_sn, sent_track) == (nacked_sn, nacked_track);
cross-track slot collisions just evict (a miss makes the client re-NACK,
exactly like an evicted reference ring entry).

The slot payload is everything a replay needs:
  - slab_key: host payload-history key — encodes (tick mod window, track,
    pkt slot) so the host can gather the original bytes from its rolling
    PayloadSlab ring (runtime/plane_runtime.py history)
  - sent_ts / sent_meta: the munged TS and packed VP8 descriptor
    (pid<<13 | tl0<<5 | keyidx) of the ORIGINAL transmission — a replay
    must carry identical bytes, not re-munged ones
  - sent_at_ms / last_nack_ms: age + RTT replay throttle
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

RING_BITS = 9               # 512 entries ≈ reference's default window
RING = 1 << RING_BITS
NEVER_MS = -(1 << 30)       # last_nack_ms sentinel: slot never replayed.
                            # Kept OUT of the (now - last) subtraction —
                            # now_ms grows to 2^31 over ~24 days and
                            # now - NEVER_MS would overflow int32, reading
                            # as "throttled" and silently disabling RTX.


def pack_meta(pid: jax.Array, tl0: jax.Array, keyidx: jax.Array) -> jax.Array:
    """VP8 descriptor fields → one int32 (pid 15 bits, tl0 8, keyidx 5)."""
    return (
        (jnp.clip(pid, 0, 0x7FFF) << 13)
        | (jnp.clip(tl0, 0, 0xFF) << 5)
        | jnp.clip(keyidx, 0, 0x1F)
    ).astype(jnp.int32)


def unpack_meta(meta):
    """int32 → (pid, tl0, keyidx); works on jax or numpy arrays/scalars."""
    return (meta >> 13) & 0x7FFF, (meta >> 5) & 0xFF, meta & 0x1F


class SequencerState(NamedTuple):
    """Per-subscriber rings, fields [..., S, RING]."""

    slab_key: jax.Array      # int32 — host payload-history key (-1 empty)
    sent_sn: jax.Array       # int32 — munged SN stored at this slot
    sent_track: jax.Array    # int32 — track the SN belongs to (-1 empty)
    sent_ts: jax.Array       # int32 — munged TS of the original send
    sent_meta: jax.Array     # int32 — packed VP8 descriptor (pack_meta)
    sent_at_ms: jax.Array    # int32 — send time (age + RTT gating)
    last_nack_ms: jax.Array  # int32 — last replay time


def init_state(num_subscribers: int) -> SequencerState:
    shape = (num_subscribers, RING)
    return SequencerState(
        slab_key=jnp.full(shape, -1, jnp.int32),
        sent_sn=jnp.full(shape, -1, jnp.int32),
        sent_track=jnp.full(shape, -1, jnp.int32),
        sent_ts=jnp.zeros(shape, jnp.int32),
        sent_meta=jnp.zeros(shape, jnp.int32),
        sent_at_ms=jnp.zeros(shape, jnp.int32),
        last_nack_ms=jnp.full(shape, NEVER_MS, jnp.int32),
    )


def push_tick(
    state: SequencerState,
    out_sn: jax.Array,     # [P, S] int32 — munged SNs sent this tick
    out_ts: jax.Array,     # [P, S] int32 — munged TSs sent this tick
    out_meta: jax.Array,   # [P, S] int32 — packed VP8 descriptors
    track: jax.Array,      # [P] int32 — source track of each packet row
    sent: jax.Array,       # [P, S] bool — send mask
    slab_key: jax.Array,   # [P] int32 — host payload-history keys
    now_ms: jax.Array,     # scalar int32
) -> SequencerState:
    """Record this tick's sends into each subscriber's ring (sequencer.push)."""
    P, S = out_sn.shape
    slot = out_sn & (RING - 1)                        # [P, S]
    sub = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (P, S))
    keys = jnp.broadcast_to(slab_key[:, None], (P, S))
    trks = jnp.broadcast_to(track[:, None], (P, S))

    # Masked scatter: unsent entries write to a scratch slot we discard.
    flat_idx = jnp.where(sent, sub * RING + slot, S * RING)  # [P,S]

    def scatter(buf, vals):
        padded = jnp.concatenate([buf.reshape(-1), jnp.zeros((1,), buf.dtype)])
        padded = padded.at[flat_idx.reshape(-1)].set(vals.reshape(-1))
        return padded[:-1].reshape(buf.shape)

    return SequencerState(
        slab_key=scatter(state.slab_key, keys),
        sent_sn=scatter(state.sent_sn, jnp.where(sent, out_sn, -1)),
        sent_track=scatter(state.sent_track, jnp.where(sent, trks, -1)),
        sent_ts=scatter(state.sent_ts, out_ts),
        sent_meta=scatter(state.sent_meta, out_meta),
        sent_at_ms=scatter(state.sent_at_ms, jnp.full((P, S), now_ms, jnp.int32)),
        last_nack_ms=state.last_nack_ms,
    )


def lookup_nacks(
    state: SequencerState,
    nacked_sn: jax.Array,     # [S, M] int32 — munged SNs the subs NACKed (-1 pad)
    nacked_track: jax.Array,  # [S, M] int32 — track each NACK targets
    now_ms: jax.Array,        # scalar int32
    rtt_ms: jax.Array,        # [S] int32 — per-sub RTT (replay throttle)
    max_age_ms: jax.Array | int = 1 << 30,
):
    """Resolve NACKs → replay records (getExtPacketMetas + RTT gate).

    Returns (state, slab_key [S, M], ts [S, M], meta [S, M], ok [S, M]);
    `ok` is False for unknown/evicted SNs, for SNs replayed within the last
    RTT, and for entries older than `max_age_ms` (whose payload slab slot
    the host has already recycled).
    """
    S, M = nacked_sn.shape
    slot = nacked_sn & (RING - 1)
    sub = jnp.arange(S, dtype=jnp.int32)[:, None]
    hit = (
        (jnp.take_along_axis(state.sent_sn, slot, axis=-1) == nacked_sn)
        & (jnp.take_along_axis(state.sent_track, slot, axis=-1) == nacked_track)
        & (nacked_sn >= 0)
    )
    key = jnp.take_along_axis(state.slab_key, slot, axis=-1)
    ts = jnp.take_along_axis(state.sent_ts, slot, axis=-1)
    meta = jnp.take_along_axis(state.sent_meta, slot, axis=-1)
    sent_at = jnp.take_along_axis(state.sent_at_ms, slot, axis=-1)
    last = jnp.take_along_axis(state.last_nack_ms, slot, axis=-1)
    # Sentinel excluded from the subtraction (int32 overflow — see NEVER_MS).
    throttled = (last != NEVER_MS) & (
        (now_ms - last) < jnp.maximum(rtt_ms[:, None], 1)
    )
    fresh = (now_ms - sent_at) < max_age_ms
    ok = hit & ~throttled & fresh & (key >= 0)

    # Stamp replay time on the slots we are re-sending.
    flat = jnp.where(ok, sub * RING + slot, S * RING)
    padded = jnp.concatenate([state.last_nack_ms.reshape(-1), jnp.zeros((1,), jnp.int32)])
    padded = padded.at[flat.reshape(-1)].set(jnp.full((S * M,), now_ms, jnp.int32))
    new_last = padded[:-1].reshape(state.last_nack_ms.shape)

    return (
        state._replace(last_nack_ms=new_last),
        jnp.where(ok, key, -1),
        ts,
        meta,
        ok,
    )
