"""Batched egress pacing: per-subscriber leaky bucket.

Reference parity: pkg/sfu/pacer — PassThrough (direct), NoQueue
(sequential worker), LeakyBucket (leaky_bucket.go:47-200: per-interval
byte budget from target bitrate, queue drains at the paced rate). The
reference runs one pacer goroutine per participant; here every
subscriber's bucket updates in one elementwise op per tick, and the host
egress sends `allowed` bytes worth of queued packets per subscriber this
tick (ordering within a subscriber stays FIFO on the host).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


# Fixed per-packet wire overhead beyond the RTP payload slab bytes: sealed
# frame header (crypto.HEADER_LEN = 14) + AES-GCM tag (16) + RTP header (12).
# Codec descriptors / header extensions vary per packet and are approximated
# by this constant too — budgets model wire bytes, not payload bytes, so the
# device bucket and the host gate (runtime/udp.py _pacer_gate) must both
# charge it or egress admits a few percent more than the bucket granted.
WIRE_OVERHEAD_BYTES = 42


class PacerParams(NamedTuple):
    burst_ms: int = 100       # bucket depth in ms of target rate
    min_rate_bps: float = 64_000.0


class PacerState(NamedTuple):
    """Per-subscriber buckets, fields [..., S] float32."""

    tokens: jax.Array      # byte allowance accumulated
    rate_bps: jax.Array    # paced rate (committed channel capacity)
    queued: jax.Array      # bytes waiting host-side


def init_state(num_subscribers: int, initial_rate: float = 7_000_000.0) -> PacerState:
    s = (num_subscribers,)
    return PacerState(
        tokens=jnp.zeros(s, jnp.float32),
        rate_bps=jnp.full(s, initial_rate, jnp.float32),
        queued=jnp.zeros(s, jnp.float32),
    )


def update_tick(
    state: PacerState,
    params: PacerParams,
    enqueued_bytes: jax.Array,   # [..., S] float32 — new egress this tick
    rate_bps: jax.Array,         # [..., S] float32 — allocator's committed rate
    tick_ms: jax.Array,          # scalar int32
):
    """Returns (state, allowed_bytes [S], backlog_bytes [S]).

    `allowed_bytes` is how much each subscriber's transport may write this
    tick; the remainder stays queued (leaky_bucket.go's interval drain).
    """
    rate = jnp.maximum(rate_bps, params.min_rate_bps)
    dt_s = jnp.maximum(jnp.asarray(tick_ms, jnp.float32), 1.0) / 1000.0
    cap = rate * (params.burst_ms / 1000.0) / 8.0      # bucket depth, bytes
    tokens = jnp.minimum(state.tokens + rate * dt_s / 8.0, cap)
    queued = state.queued + enqueued_bytes
    allowed = jnp.minimum(queued, tokens)
    new_state = PacerState(
        tokens=tokens - allowed,
        rate_bps=rate,
        queued=queued - allowed,
    )
    return new_state, allowed, queued - allowed
