"""Batched VP8 payload-descriptor munging.

Reference parity: pkg/sfu/codecmunger/vp8.go (UpdateAndGet :161 — picture-id
7/15-bit wrap, TL0PICIDX, KEYIDX offset rewriting; UpdateOffsets on source
switch; state snapshot VP8State :35-50). Temporal-layer *decisions* live in
ops.selector (the reference's temporallayerselector); this module only
rewrites the descriptor fields for the chosen packets.

TPU-first re-design: offsets per (track, subscriber) carried as int32 state
tensors; a `lax.scan` over the per-tick packet axis applies modular-offset
rewrites vectorized over subscribers. Dropped *pictures* (whole frames
filtered by the temporal selector) compact the picture-id space by one, the
analog of vp8.go's droppedPictureIds accounting.

Field widths: picture-id 15-bit, TL0PICIDX 8-bit, KEYIDX 5-bit.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

MASK15 = 0x7FFF  # plain ints: module import must not init a jax backend
MASK8 = 0xFF
MASK5 = 0x1F


def sub15(a, d):
    return (jnp.asarray(a, jnp.int32) - jnp.asarray(d, jnp.int32)) & MASK15


def add15(a, d):
    return (jnp.asarray(a, jnp.int32) + jnp.asarray(d, jnp.int32)) & MASK15


def diff15(a, b):
    return ((jnp.asarray(a, jnp.int32) - jnp.asarray(b, jnp.int32) + 0x4000) & MASK15) - 0x4000


class VP8State(NamedTuple):
    """Per-(track, subscriber) VP8 munger state, fields [...,S] int32/bool.

    Serializable checkpoint — analog of VP8State (codecmunger/vp8.go:35-50)
    used for migration seeding.
    """

    pid_offset: jax.Array   # mod 2^15
    tl0_offset: jax.Array   # mod 2^8
    keyidx_offset: jax.Array  # mod 2^5
    last_pid: jax.Array
    last_tl0: jax.Array
    last_keyidx: jax.Array
    started: jax.Array      # bool


def init_state(num_subscribers: int) -> VP8State:
    z = jnp.zeros((num_subscribers,), jnp.int32)
    return VP8State(z, z, z, z, z, z, jnp.zeros((num_subscribers,), jnp.bool_))


def munge_tick(
    state: VP8State,
    pid: jax.Array,        # [P] int32 — 15-bit picture id
    tl0: jax.Array,        # [P] int32 — 8-bit TL0PICIDX
    keyidx: jax.Array,     # [P] int32 — 5-bit KEYIDX
    begin_pic: jax.Array,  # [P] bool — first packet of a picture (S bit start)
    pkt_valid: jax.Array,  # [P] bool
    forward: jax.Array,    # [P, S] bool — packet sent to subscriber
    drop_pic: jax.Array,   # [P, S] bool — picture dropped for subscriber
                           #   (set on the picture's first packet only)
    switch: jax.Array,     # [P, S] bool — source-stream switch at this packet
):
    """One tick of VP8 descriptor munging for one track.

    Returns (new_state, out_pid [P,S], out_tl0 [P,S], out_keyidx [P,S]).
    Equivalent of vp8.go UpdateAndGet per forwarded packet plus
    dropped-picture offset accounting, per subscriber.
    """

    def step(carry: VP8State, xs):
        p, t0, ki, bp, valid, fwd, drp, sw = xs
        fwd = fwd & valid
        drp = drp & valid & ~fwd & bp
        sw = sw & fwd

        # Source switch: continue picture-id space at last+1 (vp8.go
        # UpdateOffsets: offsets recomputed so out = last + 1 at switch).
        sw_pid_off = sub15(p, add15(carry.last_pid, 1))
        sw_tl0_off = (t0 - carry.last_tl0 - 1) & MASK8
        sw_ki_off = (ki - carry.last_keyidx - 1) & MASK5

        fresh = fwd & ~carry.started
        resync = sw & carry.started
        pid_off = jnp.where(resync, sw_pid_off, jnp.where(fresh, 0, carry.pid_offset))
        tl0_off = jnp.where(resync, sw_tl0_off, jnp.where(fresh, 0, carry.tl0_offset))
        ki_off = jnp.where(resync, sw_ki_off, jnp.where(fresh, 0, carry.keyidx_offset))

        out_pid = sub15(p, pid_off)
        out_tl0 = (t0 - tl0_off) & MASK8
        out_ki = (ki - ki_off) & MASK5

        last_pid = jnp.where(fwd & bp, out_pid, carry.last_pid)
        last_tl0 = jnp.where(fwd & bp, out_tl0, carry.last_tl0)
        last_ki = jnp.where(fwd & bp, out_ki, carry.last_keyidx)
        # Dropped picture ⇒ future out picture-ids shift down by one.
        pid_off = jnp.where(drp & carry.started, add15(pid_off, 1), pid_off)
        started = carry.started | fwd

        new_carry = VP8State(pid_off, tl0_off, ki_off, last_pid, last_tl0, last_ki, started)
        return new_carry, (out_pid, out_tl0, out_ki)

    xs = (pid, tl0, keyidx, begin_pic, pkt_valid, forward, drop_pic, switch)
    new_state, (out_pid, out_tl0, out_ki) = jax.lax.scan(step, state, xs, unroll=True)
    return new_state, out_pid, out_tl0, out_ki
