"""Batched simulcast / temporal video-layer selection.

Reference parity: pkg/sfu/videolayerselector/simulcast.go:42 (key-frame-gated
spatial switching), temporallayerselector/ (VP8 layer-sync-gated temporal
upgrades), and the selector interface videolayerselector.go:31. SVC/
dependency-descriptor selection (vp9.go, dependencydescriptor.go) builds on
the same mask algebra and lands in ops.svc.

TPU-first re-design: per-(track, subscriber) selector state lives in [S]
int32 tensors; each tick a `lax.scan` over the (small, static) packet axis
produces forward/drop/switch masks consumed by ops.rtpmunger / ops.vp8 —
the decision half of the reference's DownTrack.WriteRTP hot path
(downtrack.go:680 → forwarder.go GetTranslationParams :1436).

Layer encoding: spatial/temporal are small ints; INVALID_LAYER (-1) means
"not forwarding" (reference buffer.InvalidLayer{-1,-1}).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

INVALID_LAYER = -1  # plain int: module import must not init a jax backend


class SelectorState(NamedTuple):
    """Per-(track, subscriber) selection state; fields are [..., S] int32.

    current_*: layer currently forwarded (reference `currentLayer`)
    target_*:  layer the allocator wants (reference `targetLayer`, set by
               the stream allocator / forwarder allocation algebra)
    """

    current_spatial: jax.Array
    current_temporal: jax.Array
    target_spatial: jax.Array
    target_temporal: jax.Array


def init_state(num_subscribers: int, target_spatial: int = 2, target_temporal: int = 3) -> SelectorState:
    s = jnp.full((num_subscribers,), INVALID_LAYER, jnp.int32)
    return SelectorState(
        current_spatial=s,
        current_temporal=s,
        target_spatial=jnp.full((num_subscribers,), target_spatial, jnp.int32),
        target_temporal=jnp.full((num_subscribers,), target_temporal, jnp.int32),
    )


def select_tick(
    state: SelectorState,
    pkt_spatial: jax.Array,    # [P] int32 — simulcast layer of the packet
    pkt_temporal: jax.Array,   # [P] int32 — temporal id (0 if none)
    pkt_keyframe: jax.Array,   # [P] bool
    pkt_layer_sync: jax.Array, # [P] bool — VP8 Y bit / temporal upswitch point
    pkt_valid: jax.Array,      # [P] bool
):
    """One tick of layer selection for one video track.

    Returns (new_state, forward [P,S], drop [P,S], switch [P,S],
    need_keyframe [S]). `drop` marks current-stream packets filtered by the
    temporal selector (they compact the SN space); `switch` marks the packet
    where a subscriber changes spatial source; `need_keyframe` asks the host
    to send a PLI upstream when a subscriber waits on a spatial switch
    (reference Simulcast.Select key-frame gating + downtrack key-frame
    requester downtrack.go:608).
    """

    def step(carry: SelectorState, xs):
        sp, tp, kf, sync, valid = xs

        # Spatial switch: only at a key frame of the target layer; also the
        # initial lock-on when nothing is forwarding yet. A downgrade request
        # (target < current) also waits for a target-layer key frame.
        want_switch = (carry.target_spatial != carry.current_spatial) & (
            carry.target_spatial >= 0
        )
        sw = valid & kf & want_switch & (sp == carry.target_spatial)
        cur_sp = jnp.where(sw, carry.target_spatial, carry.current_spatial)
        # Reset temporal on spatial switch: start from target temporal.
        cur_tp = jnp.where(sw, carry.target_temporal, carry.current_temporal)

        on_current = valid & (sp == cur_sp) & (cur_sp >= 0)

        # Temporal selection (temporallayerselector/simple.go semantics):
        # upgrade only at a layer-sync point, downgrade immediately.
        can_up = on_current & sync & (tp <= carry.target_temporal)
        cur_tp = jnp.where(can_up & (tp > cur_tp), tp, cur_tp)
        cur_tp = jnp.where(
            on_current & (carry.target_temporal < cur_tp), carry.target_temporal, cur_tp
        )

        fwd = on_current & (tp <= cur_tp)
        drp = on_current & ~fwd
        # Pause: target invalid ⇒ stop forwarding entirely.
        paused = carry.target_spatial < 0
        fwd = fwd & ~paused
        drp = (drp | (on_current & paused))

        new_carry = SelectorState(
            current_spatial=jnp.where(paused, INVALID_LAYER, cur_sp),
            current_temporal=cur_tp,
            target_spatial=carry.target_spatial,
            target_temporal=carry.target_temporal,
        )
        return new_carry, (fwd, drp, sw)

    xs = (pkt_spatial, pkt_temporal, pkt_keyframe, pkt_layer_sync, pkt_valid)
    new_state, (fwd, drp, sw) = jax.lax.scan(step, state, xs, unroll=True)
    need_keyframe = (new_state.target_spatial >= 0) & (
        new_state.target_spatial != new_state.current_spatial
    )
    return new_state, fwd, drp, sw, need_keyframe


def select_both_tick(state: SelectorState, is_svc, pkt_spatial, pkt_temporal,
                     pkt_keyframe, pkt_layer_sync, pkt_end_frame, pkt_valid):
    """Merged simulcast + SVC selection for one room's [T] tracks — the
    SCAN formulation (the spec): both selector variants over shared state,
    picked per track by `is_svc` [T]. The production TPU path is the fused
    room-batched `decide_rooms` kernel, pinned bit-identical to this
    composition by tests/test_selector.py.

    Returns (state', fwd [T,K,S] bool, drop, switch, need_kf [T,S] bool).
    """
    from livekit_server_tpu.ops import svc as svc_mod

    sel_state, v_fwd, v_drop, v_switch, nk_sim = jax.vmap(select_tick)(
        state, pkt_spatial, pkt_temporal, pkt_keyframe, pkt_layer_sync,
        pkt_valid,
    )
    svc_state, s_fwd, s_drop, _s_up, nk_svc = jax.vmap(svc_mod.select_tick)(
        svc_mod.SVCSelectorState(*state), pkt_spatial, pkt_temporal,
        pkt_keyframe, pkt_layer_sync, pkt_end_frame, pkt_valid,
    )
    merged = jax.tree.map(
        lambda sim, sv: jnp.where(is_svc[:, None], sv, sim),
        sel_state, SelectorState(*svc_state),
    )
    m = is_svc[:, None, None]
    fwd = jnp.where(m, s_fwd, v_fwd)
    drop = jnp.where(m, s_drop, v_drop)
    switch = jnp.where(m, False, v_switch)
    need_kf = jnp.where(is_svc[:, None], nk_svc, nk_sim)
    return merged, fwd, drop, switch, need_kf


def set_target(state: SelectorState, target_spatial: jax.Array, target_temporal: jax.Array) -> SelectorState:
    """Apply allocator-decided target layers (reference Forwarder.SetTargetLayer)."""
    return state._replace(
        target_spatial=jnp.asarray(target_spatial, jnp.int32),
        target_temporal=jnp.asarray(target_temporal, jnp.int32),
    )


# ---------------------------------------------------------------------------
# Room-batched kernels: rooms on the vector lanes.
#
# A per-room kernel under vmap runs as a grid with ONE room per step;
# per-step fixed costs (DMA setup, tiny [T,S] vregs at ~8% lane occupancy)
# measured ~0.8 ms/tick at cfg4 and ~8 ms at the 10k-room north-star
# shape. These kernels block a room batch onto the 128-wide lane axis
# ([T, K|S, RB] layout), so every vector op is fully packed and the grid
# shrinks by RB.
# ---------------------------------------------------------------------------


def pick_room_block(R: int, per_room_bytes: int) -> int:
    """Room-block size for the lane axis: a multiple of 128 (Mosaic
    requires lane-dim blocks divisible by 128) whose single-buffered VMEM
    working set stays under ~4 MB (Mosaic double-buffers blocks and keeps
    unrolled-loop live ranges in scoped VMEM, so actual use runs a small
    multiple of this against the raised per-kernel limit), or the whole
    array when R has no suitable 128-multiple divisor."""
    from livekit_server_tpu.utils.logger import log

    cap = max(1, (4 << 20) // max(per_room_bytes, 1))
    for cand in (512, 256, 128):
        if cand <= cap and R % cand == 0:
            return cand
    if R % 128 == 0:
        # Over-budget but lane-valid: 128 is the SMALLEST legal block, so
        # it is the best effort when even that exceeds the cap (returning
        # R here would request the largest block exactly when the budget
        # is tightest). The per-kernel vmem_limit gives real headroom.
        # Trace-time only: block sizing runs while jit traces, never in
        # the compiled graph — warning once per compile is the intent.
        log.warn(  # graftcheck: disable=GC02
            "pick_room_block over VMEM budget: smallest legal block "
            "exceeds the ~4MB working-set cap; relying on the raised "
            "per-kernel vmem_limit",
            R=R, per_room_bytes=per_room_bytes, block=128, cap_rooms=cap,
        )
        return 128
    # No 128-multiple divisor (small or odd R): whole array. Legal only
    # because Mosaic pads a sub-128 lane dim; a LARGE R landing here means
    # a dims misconfiguration (e.g. R=384+1) and a likely OOM, not a
    # deliberate small-plane shape.
    if R > 128:
        # Trace-time only, as above: fires once per compile, not per tick.
        log.warn(  # graftcheck: disable=GC02
            "pick_room_block whole-array fallback for large R: no "
            "128-multiple divisor; check plane dims",
            R=R, per_room_bytes=per_room_bytes,
        )
    assert R % 128 != 0, "divisible R must take a 128-multiple block above"
    return R


def _decide_rooms_kernel(sp_ref, tp_ref, kf_ref, sync_ref, eof_ref, valid_ref,
                         size_ref, cur_sp_ref, cur_tp_ref, tgt_sp_ref,
                         tgt_tp_ref, svc_ref, vid_ref, base_ref,
                         send_ref, drop_ref, sw_ref, out_sp_ref, out_tp_ref,
                         nkf_ref, pkts_ref, bytes_ref, fp_ref, fb_ref,
                         *, wire_overhead: int):
    """Pallas TPU kernel: the ENTIRE per-packet forward decision for a
    room block — simulcast+SVC selection, subscription/mute base merge,
    audio path, egress-mask BIT PACKING, and the per-subscriber send
    sums — with nothing dense ever leaving VMEM.

    Packet refs [T, K, RB]; state/base refs [T, S, RB]; svc/vid
    [T, 1, RB]; outputs: masks [T, K, W, RB] int32 bit words,
    selector state + need_kf [T, S, RB], pkts/bytes [1, S, RB],
    fwd totals [1, 1, RB].
    """
    T, K, RB = sp_ref.shape
    S = cur_sp_ref.shape[1]
    W = (S + 31) // 32
    is_svc = svc_ref[:, :, :] != 0                                  # [T,1,RB]
    is_vid = vid_ref[:, :, :] != 0                                  # [T,1,RB]
    base = base_ref[:, :, :] != 0                                   # [T,S,RB]
    tgt_sp = tgt_sp_ref[:, :, :]
    tgt_tp = tgt_tp_ref[:, :, :]
    sim_sp, sim_tp = cur_sp_ref[:, :, :], cur_tp_ref[:, :, :]
    svc_sp, svc_tp = cur_sp_ref[:, :, :], cur_tp_ref[:, :, :]
    paused = tgt_sp < 0

    pkts_acc = jnp.zeros((S, RB), jnp.int32)
    bytes_acc = jnp.zeros((S, RB), jnp.int32)
    fp_acc = jnp.zeros((1, RB), jnp.int32)
    fb_acc = jnp.zeros((1, RB), jnp.int32)

    for k in range(K):
        sp_k = sp_ref[:, k, :][:, None, :]                          # [T,1,RB]
        tp_k = tp_ref[:, k, :][:, None, :]
        kf_k = kf_ref[:, k, :][:, None, :] != 0
        sync_k = sync_ref[:, k, :][:, None, :] != 0
        eof_k = eof_ref[:, k, :][:, None, :] != 0
        val_k = valid_ref[:, k, :][:, None, :] != 0
        size_k = size_ref[:, k, :][:, None, :]                      # [T,1,RB]

        # -- simulcast path ----------------------------------------------
        want = (tgt_sp != sim_sp) & (tgt_sp >= 0)
        sw = val_k & kf_k & want & (sp_k == tgt_sp)
        c_sp = jnp.where(sw, tgt_sp, sim_sp)
        c_tp = jnp.where(sw, tgt_tp, sim_tp)
        on_cur = val_k & (sp_k == c_sp) & (c_sp >= 0)
        can_up = on_cur & sync_k & (tp_k <= tgt_tp)
        c_tp = jnp.where(can_up & (tp_k > c_tp), tp_k, c_tp)
        c_tp = jnp.where(on_cur & (tgt_tp < c_tp), tgt_tp, c_tp)
        fwd_sim = on_cur & (tp_k <= c_tp) & ~paused
        drp_sim = (on_cur & ~(on_cur & (tp_k <= c_tp))) | (on_cur & paused)
        sim_sp = jnp.where(paused, -1, c_sp)
        sim_tp = c_tp

        # -- SVC onion path ----------------------------------------------
        up = val_k & kf_k & (tgt_sp > svc_sp) & (sp_k <= tgt_sp)
        s_sp = jnp.where(up, tgt_sp, svc_sp)
        down = val_k & eof_k & (tgt_sp >= 0) & (tgt_sp < s_sp)
        s_sp_next = jnp.where(down, tgt_sp, s_sp)
        on_stream = val_k & (s_sp >= 0)
        s_tp = jnp.where(up, tgt_tp, svc_tp)
        can_up2 = on_stream & sync_k & (tp_k <= tgt_tp) & (tp_k > s_tp)
        s_tp = jnp.where(can_up2, tp_k, s_tp)
        s_tp = jnp.where(on_stream & (tgt_tp < s_tp), tgt_tp, s_tp)
        fwd_svc = on_stream & (sp_k <= s_sp) & (tp_k <= s_tp) & ~paused
        drp_svc = on_stream & ~fwd_svc
        svc_sp = jnp.where(paused, -1, s_sp_next)
        svc_tp = s_tp

        # -- merge: video selection × base; audio = valid × base ---------
        # (int domain for the select chain — Mosaic cannot lower i1
        # vector truncations.)
        fwd_sel = jnp.where(is_svc, jnp.where(fwd_svc, 1, 0),
                            jnp.where(fwd_sim, 1, 0))
        drp_sel = jnp.where(is_svc, jnp.where(drp_svc, 1, 0),
                            jnp.where(drp_sim, 1, 0))
        sw_sel = jnp.where(sw & ~is_svc, 1, 0)
        base_i = jnp.where(base, 1, 0)
        a_fwd = jnp.where(val_k, base_i, 0)
        fwd_i = jnp.where(is_vid, fwd_sel * base_i, a_fwd)          # [T,S,RB]
        drp_i = jnp.where(is_vid, drp_sel * base_i, 0)
        sw_i = jnp.where(is_vid, sw_sel * base_i, 0)

        # -- send sums ---------------------------------------------------
        pkts_acc = pkts_acc + jnp.sum(fwd_i, axis=0)                # [S,RB]
        bytes_acc = bytes_acc + jnp.sum(
            fwd_i * (size_k + wire_overhead), axis=0
        )
        fp_acc = fp_acc + jnp.sum(fwd_i, axis=(0, 1))[None, :]
        fb_acc = fb_acc + jnp.sum(fwd_i * size_k, axis=(0, 1))[None, :]

        # -- bit packing over the subscriber axis ------------------------
        for w in range(W):
            hi = min(S, (w + 1) * 32)
            send_w = jnp.zeros((T, RB), jnp.int32)
            drop_w = jnp.zeros((T, RB), jnp.int32)
            sw_w = jnp.zeros((T, RB), jnp.int32)
            for s in range(w * 32, hi):
                sh = s - w * 32
                send_w = send_w | jnp.left_shift(fwd_i[:, s, :], sh)
                drop_w = drop_w | jnp.left_shift(drp_i[:, s, :], sh)
                sw_w = sw_w | jnp.left_shift(sw_i[:, s, :], sh)
            send_ref[:, k, w, :] = send_w
            drop_ref[:, k, w, :] = drop_w
            sw_ref[:, k, w, :] = sw_w

    out_sp = jnp.where(is_svc, svc_sp, sim_sp)
    out_tp = jnp.where(is_svc, svc_tp, sim_tp)
    out_sp_ref[:, :, :] = out_sp
    out_tp_ref[:, :, :] = out_tp
    nkf_sim = (tgt_sp >= 0) & (tgt_sp != out_sp)
    nkf_svc = (tgt_sp >= 0) & (tgt_sp > out_sp)
    nkf = jnp.where(is_svc, jnp.where(nkf_svc, 1, 0),
                    jnp.where(nkf_sim, 1, 0))
    nkf_ref[:, :, :] = nkf * jnp.where(base & is_vid, 1, 0)
    pkts_ref[0, :, :] = pkts_acc
    bytes_ref[0, :, :] = bytes_acc
    fp_ref[0, 0, :] = fp_acc[0]
    fb_ref[0, 0, :] = fb_acc[0]


def decide_rooms(state: SelectorState, is_svc, is_video, base, pkt_spatial,
                 pkt_temporal, pkt_keyframe, pkt_layer_sync, pkt_end_frame,
                 pkt_valid, pkt_size, wire_overhead: int,
                 use_pallas: bool | None = None, interpret: bool = False):
    """The full forward decision for ALL rooms: selection + base merge +
    audio path + bit packing + send sums, as ONE kernel.

    Args: state fields [R,T,S]; is_svc/is_video [R,T]; base [R,T,S] bool
    (subscribed & ~sub_muted & publisher live); packets [R,T,K].

    Returns (state', send_bits [R,T,K,W] i32, drop_bits, switch_bits,
    need_kf [R,T,S] bool (base-merged), pkts_sent [R,S] i32,
    sent_bytes [R,S] i32 (wire_overhead included), fwd_packets [R] i32,
    fwd_bytes [R] i32).

    The dense [R,T,K,S] masks NEVER materialize in HBM on this path —
    they measured as both the XLA-fusion VMEM blow-up and several
    hundred MB of traffic per tick at the 10k-room shape. CPU
    (tests/dryrun) composes the same result from the per-room pieces.
    """
    from livekit_server_tpu.ops import bits

    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    S = state.current_spatial.shape[-1]
    if not (use_pallas or interpret):
        sel_state, v_fwd, v_drop, v_switch, nkf_sel = select_both_rooms(
            state, is_svc, pkt_spatial, pkt_temporal, pkt_keyframe,
            pkt_layer_sync, pkt_end_frame, pkt_valid,
        )
        is_vid = jnp.asarray(is_video, bool)[:, :, None, None]
        base_b = jnp.asarray(base, bool)[:, :, None, :]
        a_fwd = jnp.asarray(pkt_valid, bool)[:, :, :, None] & base_b
        fwd = jnp.where(is_vid, v_fwd & base_b, a_fwd)
        drop = jnp.where(is_vid, v_drop & base_b, False)
        switch = jnp.where(is_vid, v_switch & base_b, False)
        need_kf = (
            nkf_sel & jnp.asarray(base, bool)
            & jnp.asarray(is_video, bool)[:, :, None]
        )
        pkts_sent = jnp.sum(fwd, axis=(1, 2)).astype(jnp.int32)
        size_b = jnp.asarray(pkt_size, jnp.int32)[:, :, :, None]
        sent_bytes = jnp.sum(
            jnp.where(fwd, size_b + wire_overhead, 0), axis=(1, 2)
        ).astype(jnp.int32)
        fwd_packets = jnp.sum(fwd, axis=(1, 2, 3)).astype(jnp.int32)
        fwd_bytes = jnp.sum(
            jnp.where(fwd, size_b, 0), axis=(1, 2, 3)
        ).astype(jnp.int32)
        return (sel_state, bits.pack_bits(fwd), bits.pack_bits(drop),
                bits.pack_bits(switch), need_kf, pkts_sent, sent_bytes,
                fwd_packets, fwd_bytes)

    import functools as _functools

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # Renamed upstream: TPUCompilerParams (<=0.4.x) -> CompilerParams.
    _CompilerParams = getattr(pltpu, "CompilerParams", None) or (
        pltpu.TPUCompilerParams
    )

    R, T, K = pkt_spatial.shape
    W = bits.mask_words(S)
    # Word-sized outputs keep this kernel's block footprint ~32× smaller
    # than select_both_rooms', so blocks scale by the input/state set.
    RB = pick_room_block(
        R, 4 * (T * (7 * K + 9 * S + 3 * K * W) + 2 * S + 2)
    )
    i32 = lambda x: jnp.asarray(x, jnp.int32)  # noqa: E731
    tkr = lambda x: i32(x).transpose(1, 2, 0)   # noqa: E731
    tsr = lambda x: i32(x).transpose(1, 2, 0)   # noqa: E731
    t1r = lambda x: i32(x).transpose(1, 0)[:, None, :]  # noqa: E731

    pkt_spec = pl.BlockSpec((T, K, RB), lambda i: (0, 0, i),
                            memory_space=pltpu.VMEM)
    st_spec = pl.BlockSpec((T, S, RB), lambda i: (0, 0, i),
                           memory_space=pltpu.VMEM)
    t1_spec = pl.BlockSpec((T, 1, RB), lambda i: (0, 0, i),
                           memory_space=pltpu.VMEM)
    word_spec = pl.BlockSpec((T, K, W, RB), lambda i: (0, 0, 0, i),
                             memory_space=pltpu.VMEM)
    sub_spec = pl.BlockSpec((1, S, RB), lambda i: (0, 0, i),
                            memory_space=pltpu.VMEM)
    tot_spec = pl.BlockSpec((1, 1, RB), lambda i: (0, 0, i),
                            memory_space=pltpu.VMEM)
    (send_w, drop_w, sw_w, out_sp, out_tp, nkf, pkts, byts, fp, fb) = (
        pl.pallas_call(
            _functools.partial(
                _decide_rooms_kernel, wire_overhead=wire_overhead
            ),
            grid=(R // RB,),
            out_shape=(
                jax.ShapeDtypeStruct((T, K, W, R), jnp.int32),
                jax.ShapeDtypeStruct((T, K, W, R), jnp.int32),
                jax.ShapeDtypeStruct((T, K, W, R), jnp.int32),
                jax.ShapeDtypeStruct((T, S, R), jnp.int32),
                jax.ShapeDtypeStruct((T, S, R), jnp.int32),
                jax.ShapeDtypeStruct((T, S, R), jnp.int32),
                jax.ShapeDtypeStruct((1, S, R), jnp.int32),
                jax.ShapeDtypeStruct((1, S, R), jnp.int32),
                jax.ShapeDtypeStruct((1, 1, R), jnp.int32),
                jax.ShapeDtypeStruct((1, 1, R), jnp.int32),
            ),
            in_specs=[pkt_spec] * 7 + [st_spec] * 4 + [t1_spec] * 2
            + [st_spec],
            out_specs=(word_spec,) * 3 + (st_spec,) * 3
            + (sub_spec,) * 2 + (tot_spec,) * 2,
            # v5e has 128 MB of VMEM; Mosaic's default 16 MB scoped limit
            # under-counts this kernel's unrolled-loop live ranges.
            compiler_params=_CompilerParams(
                vmem_limit_bytes=64 * 1024 * 1024
            ),
            interpret=interpret,
        )(
            tkr(pkt_spatial), tkr(pkt_temporal), tkr(pkt_keyframe),
            tkr(pkt_layer_sync), tkr(pkt_end_frame), tkr(pkt_valid),
            tkr(pkt_size),
            tsr(state.current_spatial), tsr(state.current_temporal),
            tsr(state.target_spatial), tsr(state.target_temporal),
            t1r(is_svc), t1r(is_video), tsr(base),
        )
    )
    new_state = SelectorState(
        current_spatial=out_sp.transpose(2, 0, 1),
        current_temporal=out_tp.transpose(2, 0, 1),
        target_spatial=state.target_spatial,
        target_temporal=state.target_temporal,
    )
    wb = lambda m: m.transpose(3, 0, 1, 2)  # noqa: E731 — [T,K,W,R]→[R,T,K,W]
    return (
        new_state, wb(send_w), wb(drop_w), wb(sw_w),
        nkf.transpose(2, 0, 1).astype(bool),
        pkts[0].transpose(1, 0), byts[0].transpose(1, 0),
        fp[0, 0], fb[0, 0],
    )


def select_both_rooms(state: SelectorState, is_svc, pkt_spatial, pkt_temporal,
                      pkt_keyframe, pkt_layer_sync, pkt_end_frame, pkt_valid):
    """Plane-level merged selection, composed from the per-room scan spec
    (state fields [R, T, S], packets [R, T, K], is_svc [R, T]). Used by
    `decide_rooms`'s CPU fallback and tests; the production TPU path is
    the fused `decide_rooms` kernel.

    Returns (state', fwd [R,T,K,S] bool, drop, switch, need_kf [R,T,S]).
    """
    return jax.vmap(select_both_tick)(
        state, is_svc, pkt_spatial, pkt_temporal, pkt_keyframe,
        pkt_layer_sync, pkt_end_frame, pkt_valid,
    )

