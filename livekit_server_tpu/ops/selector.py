"""Batched simulcast / temporal video-layer selection.

Reference parity: pkg/sfu/videolayerselector/simulcast.go:42 (key-frame-gated
spatial switching), temporallayerselector/ (VP8 layer-sync-gated temporal
upgrades), and the selector interface videolayerselector.go:31. SVC/
dependency-descriptor selection (vp9.go, dependencydescriptor.go) builds on
the same mask algebra and lands in ops.svc.

TPU-first re-design: per-(track, subscriber) selector state lives in [S]
int32 tensors; each tick a `lax.scan` over the (small, static) packet axis
produces forward/drop/switch masks consumed by ops.rtpmunger / ops.vp8 —
the decision half of the reference's DownTrack.WriteRTP hot path
(downtrack.go:680 → forwarder.go GetTranslationParams :1436).

Layer encoding: spatial/temporal are small ints; INVALID_LAYER (-1) means
"not forwarding" (reference buffer.InvalidLayer{-1,-1}).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

INVALID_LAYER = -1  # plain int: module import must not init a jax backend


class SelectorState(NamedTuple):
    """Per-(track, subscriber) selection state; fields are [..., S] int32.

    current_*: layer currently forwarded (reference `currentLayer`)
    target_*:  layer the allocator wants (reference `targetLayer`, set by
               the stream allocator / forwarder allocation algebra)
    """

    current_spatial: jax.Array
    current_temporal: jax.Array
    target_spatial: jax.Array
    target_temporal: jax.Array


def init_state(num_subscribers: int, target_spatial: int = 2, target_temporal: int = 3) -> SelectorState:
    s = jnp.full((num_subscribers,), INVALID_LAYER, jnp.int32)
    return SelectorState(
        current_spatial=s,
        current_temporal=s,
        target_spatial=jnp.full((num_subscribers,), target_spatial, jnp.int32),
        target_temporal=jnp.full((num_subscribers,), target_temporal, jnp.int32),
    )


def select_tick(
    state: SelectorState,
    pkt_spatial: jax.Array,    # [P] int32 — simulcast layer of the packet
    pkt_temporal: jax.Array,   # [P] int32 — temporal id (0 if none)
    pkt_keyframe: jax.Array,   # [P] bool
    pkt_layer_sync: jax.Array, # [P] bool — VP8 Y bit / temporal upswitch point
    pkt_valid: jax.Array,      # [P] bool
):
    """One tick of layer selection for one video track.

    Returns (new_state, forward [P,S], drop [P,S], switch [P,S],
    need_keyframe [S]). `drop` marks current-stream packets filtered by the
    temporal selector (they compact the SN space); `switch` marks the packet
    where a subscriber changes spatial source; `need_keyframe` asks the host
    to send a PLI upstream when a subscriber waits on a spatial switch
    (reference Simulcast.Select key-frame gating + downtrack key-frame
    requester downtrack.go:608).
    """

    def step(carry: SelectorState, xs):
        sp, tp, kf, sync, valid = xs

        # Spatial switch: only at a key frame of the target layer; also the
        # initial lock-on when nothing is forwarding yet. A downgrade request
        # (target < current) also waits for a target-layer key frame.
        want_switch = (carry.target_spatial != carry.current_spatial) & (
            carry.target_spatial >= 0
        )
        sw = valid & kf & want_switch & (sp == carry.target_spatial)
        cur_sp = jnp.where(sw, carry.target_spatial, carry.current_spatial)
        # Reset temporal on spatial switch: start from target temporal.
        cur_tp = jnp.where(sw, carry.target_temporal, carry.current_temporal)

        on_current = valid & (sp == cur_sp) & (cur_sp >= 0)

        # Temporal selection (temporallayerselector/simple.go semantics):
        # upgrade only at a layer-sync point, downgrade immediately.
        can_up = on_current & sync & (tp <= carry.target_temporal)
        cur_tp = jnp.where(can_up & (tp > cur_tp), tp, cur_tp)
        cur_tp = jnp.where(
            on_current & (carry.target_temporal < cur_tp), carry.target_temporal, cur_tp
        )

        fwd = on_current & (tp <= cur_tp)
        drp = on_current & ~fwd
        # Pause: target invalid ⇒ stop forwarding entirely.
        paused = carry.target_spatial < 0
        fwd = fwd & ~paused
        drp = (drp | (on_current & paused))

        new_carry = SelectorState(
            current_spatial=jnp.where(paused, INVALID_LAYER, cur_sp),
            current_temporal=cur_tp,
            target_spatial=carry.target_spatial,
            target_temporal=carry.target_temporal,
        )
        return new_carry, (fwd, drp, sw)

    xs = (pkt_spatial, pkt_temporal, pkt_keyframe, pkt_layer_sync, pkt_valid)
    new_state, (fwd, drp, sw) = jax.lax.scan(step, state, xs, unroll=True)
    need_keyframe = (new_state.target_spatial >= 0) & (
        new_state.target_spatial != new_state.current_spatial
    )
    return new_state, fwd, drp, sw, need_keyframe


def _both_kernel(sp_ref, tp_ref, kf_ref, sync_ref, eof_ref, valid_ref,
                 cur_sp_ref, cur_tp_ref, tgt_sp_ref, tgt_tp_ref, svc_ref,
                 fwd_ref, drp_ref, sw_ref, out_sp_ref, out_tp_ref, nkf_ref):
    """Pallas TPU kernel: simulcast AND SVC-onion selection for one room,
    packet loop unrolled in VMEM, subscribers on lanes.

    The scan formulations (select_tick here + svc.select_tick) are 2·K
    dependent micro-steps per tick — the tick's longest serial chains
    after allocation. This runs both paths per track (exactly like the
    plane's where-merge) with the whole carry chain in registers. Packet
    inputs are [T, K]; state and outputs are [T, S] / [T, K, S];
    `svc_ref` [T, S] picks the path.
    """
    T, K = sp_ref.shape
    is_svc = svc_ref[:, :] != 0                                    # [T, S]
    tgt_sp = tgt_sp_ref[:, :]
    tgt_tp = tgt_tp_ref[:, :]
    sim_sp, sim_tp = cur_sp_ref[:, :], cur_tp_ref[:, :]
    svc_sp, svc_tp = cur_sp_ref[:, :], cur_tp_ref[:, :]
    paused = tgt_sp < 0

    for k in range(K):
        sp_k = sp_ref[:, k][:, None]
        tp_k = tp_ref[:, k][:, None]
        kf_k = kf_ref[:, k][:, None] != 0
        sync_k = sync_ref[:, k][:, None] != 0
        eof_k = eof_ref[:, k][:, None] != 0
        val_k = valid_ref[:, k][:, None] != 0

        # -- simulcast path (select_tick step) ---------------------------
        want = (tgt_sp != sim_sp) & (tgt_sp >= 0)
        sw = val_k & kf_k & want & (sp_k == tgt_sp)
        c_sp = jnp.where(sw, tgt_sp, sim_sp)
        c_tp = jnp.where(sw, tgt_tp, sim_tp)
        on_cur = val_k & (sp_k == c_sp) & (c_sp >= 0)
        can_up = on_cur & sync_k & (tp_k <= tgt_tp)
        c_tp = jnp.where(can_up & (tp_k > c_tp), tp_k, c_tp)
        c_tp = jnp.where(on_cur & (tgt_tp < c_tp), tgt_tp, c_tp)
        fwd_sim = on_cur & (tp_k <= c_tp) & ~paused
        drp_sim = (on_cur & ~(on_cur & (tp_k <= c_tp))) | (on_cur & paused)
        sim_sp = jnp.where(paused, -1, c_sp)
        sim_tp = c_tp

        # -- SVC onion path (svc.select_tick step) -----------------------
        up = val_k & kf_k & (tgt_sp > svc_sp) & (sp_k <= tgt_sp)
        s_sp = jnp.where(up, tgt_sp, svc_sp)
        down = val_k & eof_k & (tgt_sp >= 0) & (tgt_sp < s_sp)
        s_sp_next = jnp.where(down, tgt_sp, s_sp)
        on_stream = val_k & (s_sp >= 0)
        s_tp = jnp.where(up, tgt_tp, svc_tp)
        can_up2 = on_stream & sync_k & (tp_k <= tgt_tp) & (tp_k > s_tp)
        s_tp = jnp.where(can_up2, tp_k, s_tp)
        s_tp = jnp.where(on_stream & (tgt_tp < s_tp), tgt_tp, s_tp)
        fwd_svc = on_stream & (sp_k <= s_sp) & (tp_k <= s_tp) & ~paused
        drp_svc = on_stream & ~fwd_svc
        svc_sp = jnp.where(paused, -1, s_sp_next)
        svc_tp = s_tp

        # Stay in the int domain for mask merges: Mosaic cannot lower
        # bool-valued selects (i8 vector -> i1 truncation).
        fwd_ref[:, k, :] = jnp.where(is_svc, jnp.where(fwd_svc, 1, 0),
                                     jnp.where(fwd_sim, 1, 0))
        drp_ref[:, k, :] = jnp.where(is_svc, jnp.where(drp_svc, 1, 0),
                                     jnp.where(drp_sim, 1, 0))
        sw_ref[:, k, :] = jnp.where(sw & ~is_svc, 1, 0)

    out_sp = jnp.where(is_svc, svc_sp, sim_sp)
    out_tp = jnp.where(is_svc, svc_tp, sim_tp)
    out_sp_ref[:, :] = out_sp
    out_tp_ref[:, :] = out_tp
    nkf_sim = (tgt_sp >= 0) & (tgt_sp != out_sp)
    nkf_svc = (tgt_sp >= 0) & (tgt_sp > out_sp)
    nkf_ref[:, :] = jnp.where(is_svc, jnp.where(nkf_svc, 1, 0),
                              jnp.where(nkf_sim, 1, 0))


def select_both_tick(state: SelectorState, is_svc, pkt_spatial, pkt_temporal,
                     pkt_keyframe, pkt_layer_sync, pkt_end_frame, pkt_valid,
                     use_pallas: bool | None = None, interpret: bool = False):
    """Merged simulcast + SVC selection for one room's [T] tracks.

    Runs both selector variants over shared state and picks per track by
    `is_svc` [T] — the plane's selection block as ONE op. TPU takes the
    fused kernel; CPU (tests/dryrun) the scan formulations.

    Returns (state', fwd [T,K,S] bool, drop, switch, need_kf [T,S] bool).
    """
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if not (use_pallas or interpret):
        from livekit_server_tpu.ops import svc as svc_mod

        sel_state, v_fwd, v_drop, v_switch, nk_sim = jax.vmap(select_tick)(
            state, pkt_spatial, pkt_temporal, pkt_keyframe, pkt_layer_sync,
            pkt_valid,
        )
        svc_state, s_fwd, s_drop, _s_up, nk_svc = jax.vmap(svc_mod.select_tick)(
            svc_mod.SVCSelectorState(*state), pkt_spatial, pkt_temporal,
            pkt_keyframe, pkt_layer_sync, pkt_end_frame, pkt_valid,
        )
        merged = jax.tree.map(
            lambda sim, sv: jnp.where(is_svc[:, None], sv, sim),
            sel_state, SelectorState(*svc_state),
        )
        m = is_svc[:, None, None]
        fwd = jnp.where(m, s_fwd, v_fwd)
        drop = jnp.where(m, s_drop, v_drop)
        switch = jnp.where(m, False, v_switch)
        need_kf = jnp.where(is_svc[:, None], nk_svc, nk_sim)
        return merged, fwd, drop, switch, need_kf

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T, K = pkt_spatial.shape
    S = state.current_spatial.shape[-1]
    spec = pl.BlockSpec(memory_space=pltpu.VMEM)
    i32 = lambda x: jnp.asarray(x, jnp.int32)  # noqa: E731
    fwd, drp, sw, out_sp, out_tp, nkf = pl.pallas_call(
        _both_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((T, K, S), jnp.int32),
            jax.ShapeDtypeStruct((T, K, S), jnp.int32),
            jax.ShapeDtypeStruct((T, K, S), jnp.int32),
            jax.ShapeDtypeStruct((T, S), jnp.int32),
            jax.ShapeDtypeStruct((T, S), jnp.int32),
            jax.ShapeDtypeStruct((T, S), jnp.int32),
        ),
        in_specs=[spec] * 11,
        out_specs=(spec,) * 6,
        interpret=interpret,
    )(
        i32(pkt_spatial), i32(pkt_temporal), i32(pkt_keyframe),
        i32(pkt_layer_sync), i32(pkt_end_frame), i32(pkt_valid),
        state.current_spatial, state.current_temporal,
        state.target_spatial, state.target_temporal,
        jnp.broadcast_to(i32(is_svc)[:, None], (T, S)),
    )
    new_state = SelectorState(
        current_spatial=out_sp, current_temporal=out_tp,
        target_spatial=state.target_spatial,
        target_temporal=state.target_temporal,
    )
    return (new_state, fwd.astype(bool), drp.astype(bool), sw.astype(bool),
            nkf.astype(bool))


def set_target(state: SelectorState, target_spatial: jax.Array, target_temporal: jax.Array) -> SelectorState:
    """Apply allocator-decided target layers (reference Forwarder.SetTargetLayer)."""
    return state._replace(
        target_spatial=jnp.asarray(target_spatial, jnp.int32),
        target_temporal=jnp.asarray(target_temporal, jnp.int32),
    )
