"""Batched simulcast / temporal video-layer selection.

Reference parity: pkg/sfu/videolayerselector/simulcast.go:42 (key-frame-gated
spatial switching), temporallayerselector/ (VP8 layer-sync-gated temporal
upgrades), and the selector interface videolayerselector.go:31. SVC/
dependency-descriptor selection (vp9.go, dependencydescriptor.go) builds on
the same mask algebra and lands in ops.svc.

TPU-first re-design: per-(track, subscriber) selector state lives in [S]
int32 tensors; each tick a `lax.scan` over the (small, static) packet axis
produces forward/drop/switch masks consumed by ops.rtpmunger / ops.vp8 —
the decision half of the reference's DownTrack.WriteRTP hot path
(downtrack.go:680 → forwarder.go GetTranslationParams :1436).

Layer encoding: spatial/temporal are small ints; INVALID_LAYER (-1) means
"not forwarding" (reference buffer.InvalidLayer{-1,-1}).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

INVALID_LAYER = jnp.int32(-1)


class SelectorState(NamedTuple):
    """Per-(track, subscriber) selection state; fields are [..., S] int32.

    current_*: layer currently forwarded (reference `currentLayer`)
    target_*:  layer the allocator wants (reference `targetLayer`, set by
               the stream allocator / forwarder allocation algebra)
    """

    current_spatial: jax.Array
    current_temporal: jax.Array
    target_spatial: jax.Array
    target_temporal: jax.Array


def init_state(num_subscribers: int, target_spatial: int = 2, target_temporal: int = 3) -> SelectorState:
    s = jnp.full((num_subscribers,), INVALID_LAYER, jnp.int32)
    return SelectorState(
        current_spatial=s,
        current_temporal=s,
        target_spatial=jnp.full((num_subscribers,), target_spatial, jnp.int32),
        target_temporal=jnp.full((num_subscribers,), target_temporal, jnp.int32),
    )


def select_tick(
    state: SelectorState,
    pkt_spatial: jax.Array,    # [P] int32 — simulcast layer of the packet
    pkt_temporal: jax.Array,   # [P] int32 — temporal id (0 if none)
    pkt_keyframe: jax.Array,   # [P] bool
    pkt_layer_sync: jax.Array, # [P] bool — VP8 Y bit / temporal upswitch point
    pkt_valid: jax.Array,      # [P] bool
):
    """One tick of layer selection for one video track.

    Returns (new_state, forward [P,S], drop [P,S], switch [P,S],
    need_keyframe [S]). `drop` marks current-stream packets filtered by the
    temporal selector (they compact the SN space); `switch` marks the packet
    where a subscriber changes spatial source; `need_keyframe` asks the host
    to send a PLI upstream when a subscriber waits on a spatial switch
    (reference Simulcast.Select key-frame gating + downtrack key-frame
    requester downtrack.go:608).
    """

    def step(carry: SelectorState, xs):
        sp, tp, kf, sync, valid = xs

        # Spatial switch: only at a key frame of the target layer; also the
        # initial lock-on when nothing is forwarding yet. A downgrade request
        # (target < current) also waits for a target-layer key frame.
        want_switch = (carry.target_spatial != carry.current_spatial) & (
            carry.target_spatial >= 0
        )
        sw = valid & kf & want_switch & (sp == carry.target_spatial)
        cur_sp = jnp.where(sw, carry.target_spatial, carry.current_spatial)
        # Reset temporal on spatial switch: start from target temporal.
        cur_tp = jnp.where(sw, carry.target_temporal, carry.current_temporal)

        on_current = valid & (sp == cur_sp) & (cur_sp >= 0)

        # Temporal selection (temporallayerselector/simple.go semantics):
        # upgrade only at a layer-sync point, downgrade immediately.
        can_up = on_current & sync & (tp <= carry.target_temporal)
        cur_tp = jnp.where(can_up & (tp > cur_tp), tp, cur_tp)
        cur_tp = jnp.where(
            on_current & (carry.target_temporal < cur_tp), carry.target_temporal, cur_tp
        )

        fwd = on_current & (tp <= cur_tp)
        drp = on_current & ~fwd
        # Pause: target invalid ⇒ stop forwarding entirely.
        paused = carry.target_spatial < 0
        fwd = fwd & ~paused
        drp = (drp | (on_current & paused))

        new_carry = SelectorState(
            current_spatial=jnp.where(paused, INVALID_LAYER, cur_sp),
            current_temporal=cur_tp,
            target_spatial=carry.target_spatial,
            target_temporal=carry.target_temporal,
        )
        return new_carry, (fwd, drp, sw)

    xs = (pkt_spatial, pkt_temporal, pkt_keyframe, pkt_layer_sync, pkt_valid)
    new_state, (fwd, drp, sw) = jax.lax.scan(step, state, xs)
    need_keyframe = (new_state.target_spatial >= 0) & (
        new_state.target_spatial != new_state.current_spatial
    )
    return new_state, fwd, drp, sw, need_keyframe


def set_target(state: SelectorState, target_spatial: jax.Array, target_temporal: jax.Array) -> SelectorState:
    """Apply allocator-decided target layers (reference Forwarder.SetTargetLayer)."""
    return state._replace(
        target_spatial=jnp.asarray(target_spatial, jnp.int32),
        target_temporal=jnp.asarray(target_temporal, jnp.int32),
    )
