"""Batched RFC 6464 audio-level / active-speaker detection.

Reference parity: pkg/sfu/audio/audiolevel.go:36-134 (windowed loudest-level
observation with activity weighting and EMA smoothing) and the room
active-speaker loop Room.audioUpdateWorker / GetActiveSpeakers
(pkg/rtc/room.go:1278-1316, :254-279).

TPU-first re-design: one state tensor row per track; packet observations
arrive as per-tick batches and reduce along the packet axis; window
finalization and EMA smoothing are elementwise over the track axis; room
top-K speakers are a `lax.top_k` over the room-local track axis. This is the
"active speaker" batch named in the north star (BASELINE.json).

Levels are RFC 6464 dBov attenuation values in [0, 127]; *smaller is louder*.
127 ⇒ digital silence.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

SILENT_LEVEL = 127.0  # plain float: module import must not init a jax backend


class AudioLevelParams(NamedTuple):
    """Mirrors config audio params (pkg/config/config.go AudioConfig)."""

    active_level: int = 35        # dBov threshold: <= is active (config.go ActiveLevel)
    min_percentile: int = 40      # % of window that must be active
    observe_interval_ms: int = 500  # window length (UpdateInterval)
    smooth_intervals: int = 2     # EMA horizon (SmoothIntervals)


class AudioLevelState(NamedTuple):
    """Per-track accumulators + smoothed level; fields are [..., T]."""

    smoothed_level: jax.Array   # float32 dBov (127 = silent)
    window_min: jax.Array       # float32 — loudest (min dBov) level this window
    active_ms: jax.Array        # int32 — active milliseconds this window
    window_ms: jax.Array        # int32 — elapsed milliseconds this window


def init_state(num_tracks: int) -> AudioLevelState:
    return AudioLevelState(
        smoothed_level=jnp.full((num_tracks,), SILENT_LEVEL, jnp.float32),
        window_min=jnp.full((num_tracks,), SILENT_LEVEL, jnp.float32),
        active_ms=jnp.zeros((num_tracks,), jnp.int32),
        window_ms=jnp.zeros((num_tracks,), jnp.int32),
    )


def observe_tick(
    state: AudioLevelState,
    params: AudioLevelParams,
    levels: jax.Array,     # [T, P] int32 dBov per packet (127 if absent)
    frame_ms: jax.Array,   # [T, P] int32 frame duration per packet
    valid: jax.Array,      # [T, P] bool
    tick_ms: jax.Array,    # scalar int32 — wall time advanced this tick
):
    """Accumulate one tick of observations and finalize windows that elapsed.

    Equivalent of audiolevel.go Observe() per packet followed by the
    window-end smoothing, batched over tracks. Returns (new_state,
    linear_level [T] float32, is_active [T] bool).
    """
    lv = jnp.asarray(levels, jnp.float32)
    dur = jnp.where(valid, jnp.asarray(frame_ms, jnp.int32), 0)
    active = valid & (lv <= jnp.float32(params.active_level))

    window_min = jnp.minimum(
        state.window_min, jnp.min(jnp.where(active, lv, SILENT_LEVEL), axis=-1)
    )
    active_ms = state.active_ms + jnp.sum(jnp.where(active, dur, 0), axis=-1)
    window_ms = state.window_ms + jnp.asarray(tick_ms, jnp.int32)

    done = window_ms >= jnp.int32(params.observe_interval_ms)
    min_active = jnp.int32(params.observe_interval_ms * params.min_percentile // 100)
    was_active = done & (active_ms >= min_active)
    # Window level = loudest observed while active (audiolevel.go tracks the
    # min dBov over the window); inactive windows read as silence.
    obs = jnp.where(was_active, window_min, SILENT_LEVEL)

    # jnp.maximum (not Python max): params may be traced leaves under jit.
    alpha = 1.0 / jnp.maximum(jnp.asarray(params.smooth_intervals, jnp.float32), 1.0)
    ema = state.smoothed_level + (obs - state.smoothed_level) * alpha
    # Seed directly on the first active window after silence (the reference
    # seeds smoothedLevel rather than EMA-ing up from digital silence, so a
    # new speaker is detected within one observe window).
    was_silent = state.smoothed_level >= 126.5
    smoothed = jnp.where(
        done, jnp.where(was_silent & was_active, obs, ema), state.smoothed_level
    )
    new_state = AudioLevelState(
        smoothed_level=smoothed,
        window_min=jnp.where(done, SILENT_LEVEL, window_min),
        active_ms=jnp.where(done, 0, active_ms),
        window_ms=jnp.where(done, 0, window_ms),
    )
    linear = level_to_linear(smoothed)
    is_active = smoothed < jnp.float32(params.active_level)
    return new_state, linear, is_active


def level_to_linear(dbov: jax.Array) -> jax.Array:
    """10^(-dBov/20), with digital silence mapped to 0 (audiolevel.go ConvertAudioLevel)."""
    lin = jnp.power(10.0, -jnp.asarray(dbov, jnp.float32) / 20.0)
    return jnp.where(dbov >= 126.5, 0.0, lin)


def top_speakers(linear_levels: jax.Array, k: int):
    """Top-K speakers along the last (track) axis.

    Equivalent of Room.GetActiveSpeakers (room.go:254-279) sort, batched over
    rooms. Returns (levels [.., k], indices [.., k]); silent tracks have
    level 0 and should be masked by the caller.
    """
    return jax.lax.top_k(linear_levels, k)
