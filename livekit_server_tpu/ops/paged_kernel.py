"""Ragged-aware pooled-tick Pallas kernel: one grid step per LIVE page.

The stock pooled tick computes every pool row at `[P, TP, K, SP]` and
masks the dead ones — after PR 9 the paged plane wins on memory but
still pays full-pool compute. This kernel consumes the device page
table's live extents as a SCALAR-PREFETCH operand (`live_rows`, the
mapped pool ids): the grid is `(NL,)`, each step's input index maps
select pool block `live_rows[i]`, and outputs land compact at block `i`.
Dead and unmapped pages are never *scheduled* — there is no grid step
that could touch them — rather than computed-and-masked, so kernel work
is proportional to occupancy, not pool size.

Each grid step fuses, for one live page:

  * the ENTIRE forward decision (`ops/selector.py` `_decide_rooms_kernel`
    algebra at page shapes): simulcast + SVC selection, base merge,
    audio path, egress bit packing, per-sub send sums;
  * the stats/tracker ROUTING selects from the phase-1 core (the
    stacked `[5, T, K, L]` one-hot routing; models/plane.py `_room_tick`
    accepts them precomputed via `routed_stats`);
  * optionally the `ops/mix.py` active-speaker mix for the page's
    subscribers — the first time decide and mix ride one kernel. The
    page-local top-K speaker gate equals the room-level gate exactly
    when the room's tracks fit one track page (MT == 1 — the MCU
    1000-room shape); multi-track-page rooms would need a cross-page
    level reduction and keep the XLA mix.

Accumulator/output layout keeps the pool dimension leading on every
array, so `parallel/mesh.py page_sharding` still shards the pool axis of
the scattered results. Layout note: page blocks put SP (≤ 32 by config)
or K on the lane axis — fine in interpret mode (CPU CI) and correct on
TPU, but sub-128 lanes under-occupy the VPU; lane-packing multiple
pages per step is recorded future work (ARCHITECTURE.md).

CPU fallback (`use_pallas=False`, `interpret=False`): the same compact
live-row computation composed from `selector.decide_rooms`'s fallback —
still live-only compute, no Pallas — with the routing left to
`_room_tick` (`st`/`tr` returned as None).
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from livekit_server_tpu.analysis.registry import device_entry
from livekit_server_tpu.ops import selector

NUM_LAYERS = 3   # spatial routing lanes (models/plane.py MAX_LAYERS)


class LiveDecide(NamedTuple):
    """Phase-0 products for the live pages only (leading axis [NL]).

    `st`/`tr` are the precomputed stats/tracker routings
    (`[NL, 5, TP*L, K]` / `[NL, 3, TP*L]`) on the kernel path, None on
    the CPU fallback (the phase-1 core then computes them in place).
    """

    sel: Any                 # selector.SelectorState, leaves [NL, TP, SP]
    send_bits: jax.Array     # [NL, TP, K, W] int32
    drop_bits: jax.Array     # [NL, TP, K, W] int32
    switch_bits: jax.Array   # [NL, TP, K, W] int32
    need_kf: jax.Array       # [NL, TP, SP] bool
    pkts_sent: jax.Array     # [NL, SP] int32
    sent_bytes: jax.Array    # [NL, SP] int32
    fwd_packets: jax.Array   # [NL] int32
    fwd_bytes: jax.Array     # [NL] int32
    st: Any                  # [NL, 5, TP*L, K] int32 | None
    tr: Any                  # [NL, 3, TP*L] int32 | None


def _resolve_pallas(use_pallas: bool | None) -> bool:
    if use_pallas is None:
        return jax.default_backend() == "tpu"
    return use_pallas


def _page_kernel(*refs, TP: int, K: int, SP: int, L: int,
                 wire_overhead: int, top_k: int,
                 with_decide: bool, with_mix: bool):
    """One live page per grid step. Ref order (after the prefetched
    live_rows ref): decide inputs, mix inputs, decide outputs, mix
    output — each present only when its flag is set."""
    it = iter(refs)
    _ = next(it)  # live_rows scalar-prefetch ref: consumed by index maps
    if with_decide:
        (cur_sp_ref, cur_tp_ref, tgt_sp_ref, tgt_tp_ref, svc_ref, vid_ref,
         base_ref, layer_ref, temporal_ref, kf_ref, sync_ref, eof_ref,
         valid_ref, size_ref, sn_ref, ts_ref, arr_ref, bpic_ref) = (
            next(it) for _ in range(18)
        )
    if with_mix:
        pcm_ref, level_ref, active_ref, gain_ref, subtrack_ref = (
            next(it) for _ in range(5)
        )
    if with_decide:
        (send_ref, drop_ref, sw_ref, out_sp_ref, out_tp_ref, nkf_ref,
         pkts_ref, bytes_ref, fp_ref, fb_ref, st_ref, tr_ref) = (
            next(it) for _ in range(12)
        )
    if with_mix:
        mixed_ref = next(it)

    if with_decide:
        # ---- forward decision: ops/selector.py `_decide_rooms_kernel`
        # algebra with the room-block lane axis replaced by this page's
        # [TP, SP] plane (int domain throughout — Mosaic cannot lower i1
        # vector truncations).
        is_svc = svc_ref[0][:, None] != 0                       # [TP, 1]
        is_vid = vid_ref[0][:, None] != 0                       # [TP, 1]
        base = base_ref[0] != 0                                 # [TP, SP]
        tgt_sp = tgt_sp_ref[0]                                  # [TP, SP]
        tgt_tp = tgt_tp_ref[0]
        sim_sp, sim_tp = cur_sp_ref[0], cur_tp_ref[0]
        svc_sp, svc_tp = cur_sp_ref[0], cur_tp_ref[0]
        paused = tgt_sp < 0

        sh = jnp.arange(SP, dtype=jnp.int32)[None, :]           # [1, SP]
        pkts_acc = jnp.zeros((SP,), jnp.int32)
        bytes_acc = jnp.zeros((SP,), jnp.int32)
        fp_acc = jnp.zeros((), jnp.int32)
        fb_acc = jnp.zeros((), jnp.int32)

        for k in range(K):
            sp_k = layer_ref[0][:, k][:, None]                  # [TP, 1]
            tp_k = temporal_ref[0][:, k][:, None]
            kf_k = kf_ref[0][:, k][:, None] != 0
            sync_k = sync_ref[0][:, k][:, None] != 0
            eof_k = eof_ref[0][:, k][:, None] != 0
            val_k = valid_ref[0][:, k][:, None] != 0
            size_k = size_ref[0][:, k][:, None]                 # [TP, 1]

            # -- simulcast path ------------------------------------------
            want = (tgt_sp != sim_sp) & (tgt_sp >= 0)
            sw = val_k & kf_k & want & (sp_k == tgt_sp)
            c_sp = jnp.where(sw, tgt_sp, sim_sp)
            c_tp = jnp.where(sw, tgt_tp, sim_tp)
            on_cur = val_k & (sp_k == c_sp) & (c_sp >= 0)
            can_up = on_cur & sync_k & (tp_k <= tgt_tp)
            c_tp = jnp.where(can_up & (tp_k > c_tp), tp_k, c_tp)
            c_tp = jnp.where(on_cur & (tgt_tp < c_tp), tgt_tp, c_tp)
            fwd_sim = on_cur & (tp_k <= c_tp) & ~paused
            drp_sim = (on_cur & ~(on_cur & (tp_k <= c_tp))) | (on_cur & paused)
            sim_sp = jnp.where(paused, -1, c_sp)
            sim_tp = c_tp

            # -- SVC onion path ------------------------------------------
            up = val_k & kf_k & (tgt_sp > svc_sp) & (sp_k <= tgt_sp)
            s_sp = jnp.where(up, tgt_sp, svc_sp)
            down = val_k & eof_k & (tgt_sp >= 0) & (tgt_sp < s_sp)
            s_sp_next = jnp.where(down, tgt_sp, s_sp)
            on_stream = val_k & (s_sp >= 0)
            s_tp = jnp.where(up, tgt_tp, svc_tp)
            can_up2 = on_stream & sync_k & (tp_k <= tgt_tp) & (tp_k > s_tp)
            s_tp = jnp.where(can_up2, tp_k, s_tp)
            s_tp = jnp.where(on_stream & (tgt_tp < s_tp), tgt_tp, s_tp)
            fwd_svc = on_stream & (sp_k <= s_sp) & (tp_k <= s_tp) & ~paused
            drp_svc = on_stream & ~fwd_svc
            svc_sp = jnp.where(paused, -1, s_sp_next)
            svc_tp = s_tp

            # -- merge: video selection × base; audio = valid × base -----
            fwd_sel = jnp.where(is_svc, jnp.where(fwd_svc, 1, 0),
                                jnp.where(fwd_sim, 1, 0))
            drp_sel = jnp.where(is_svc, jnp.where(drp_svc, 1, 0),
                                jnp.where(drp_sim, 1, 0))
            sw_sel = jnp.where(sw & ~is_svc, 1, 0)
            base_i = jnp.where(base, 1, 0)
            a_fwd = jnp.where(val_k, base_i, 0)
            fwd_i = jnp.where(is_vid, fwd_sel * base_i, a_fwd)  # [TP, SP]
            drp_i = jnp.where(is_vid, drp_sel * base_i, 0)
            sw_i = jnp.where(is_vid, sw_sel * base_i, 0)

            # -- send sums -----------------------------------------------
            pkts_acc = pkts_acc + jnp.sum(fwd_i, axis=0)        # [SP]
            bytes_acc = bytes_acc + jnp.sum(
                fwd_i * (size_k + wire_overhead), axis=0
            )
            fp_acc = fp_acc + jnp.sum(fwd_i)
            fb_acc = fb_acc + jnp.sum(fwd_i * size_k)

            # -- bit packing over the sub axis (SP ≤ 32 ⇒ one word):
            # disjoint-bit shift-SUM over lanes == OR, exact incl. the
            # two's-complement bit 31.
            send_ref[0, :, k] = jnp.sum(jnp.left_shift(fwd_i, sh), axis=1)
            drop_ref[0, :, k] = jnp.sum(jnp.left_shift(drp_i, sh), axis=1)
            sw_ref[0, :, k] = jnp.sum(jnp.left_shift(sw_i, sh), axis=1)

        out_sp = jnp.where(is_svc, svc_sp, sim_sp)
        out_tp = jnp.where(is_svc, svc_tp, sim_tp)
        out_sp_ref[0] = out_sp
        out_tp_ref[0] = out_tp
        nkf_sim = (tgt_sp >= 0) & (tgt_sp != out_sp)
        nkf_svc = (tgt_sp >= 0) & (tgt_sp > out_sp)
        nkf = jnp.where(is_svc, jnp.where(nkf_svc, 1, 0),
                        jnp.where(nkf_sim, 1, 0))
        nkf_ref[0] = nkf * jnp.where(base & is_vid, 1, 0)
        pkts_ref[0] = pkts_acc
        bytes_ref[0] = bytes_acc
        fp_ref[0, 0] = fp_acc
        fb_ref[0, 0] = fb_acc

        # ---- stats/tracker routing (models/plane.py `_room_tick`
        # sections 1–2, verbatim int algebra at page shapes) -------------
        lanes = jnp.arange(L, dtype=jnp.int32)[None, None, :]   # [1,1,L]
        layer = layer_ref[0]                                    # [TP, K]
        size = size_ref[0]
        valid_i = valid_ref[0]
        eff_layer = jnp.where(
            is_svc, 0, jnp.clip(layer, 0, L - 1)
        )
        st_vals = jnp.stack(
            [sn_ref[0], ts_ref[0], size, arr_ref[0], valid_i]
        )                                                       # [5,TP,K]
        st_routed = jnp.where(
            (eff_layer[:, :, None] == lanes)[None], st_vals[:, :, :, None], 0
        )                                                       # [5,TP,K,L]
        st_ref[0] = st_routed.transpose(0, 1, 3, 2).reshape(5, TP * L, K)
        true_layer = jnp.clip(layer, 0, L - 1)
        t_lane = true_layer[:, :, None] == lanes                # [TP,K,L]
        ones_k = jnp.ones((TP, K), jnp.int32)
        tr_vals = jnp.stack([ones_k, size, ones_k])             # [3,TP,K]
        tr_pred = jnp.stack(
            [valid_i, valid_i, valid_i * bpic_ref[0]]
        )                                                       # [3,TP,K]
        routed = jnp.where(
            t_lane[None] & (tr_pred[:, :, :, None] != 0),
            tr_vals[:, :, :, None], 0,
        )                                                       # [3,TP,K,L]
        tr_ref[0] = jnp.sum(routed, axis=2).reshape(3, TP * L)

    if with_mix:
        # ---- page-local active-speaker mix (ops/mix.py mix_tick math;
        # exact vs the room-level gate when MT == 1 — module doc). The
        # top-K threshold is the multiset k-th largest via pairwise
        # compares (no sort in-kernel): min{v : #{v' > v} < k}, which
        # equals sort(lv)[TP - k] including tie semantics.
        level = level_ref[0]                                    # [TP] f32
        act = active_ref[0] != 0                                # [TP]
        lv = jnp.where(act, level, -1.0)
        k_eff = min(top_k, TP)
        cnt_gt = jnp.sum(
            (lv[None, :] > lv[:, None]).astype(jnp.int32), axis=1
        )                                                       # [TP]
        thr = jnp.min(jnp.where(cnt_gt < k_eff, lv, jnp.inf))
        speak = act & (lv >= jnp.maximum(thr, 0.0))             # [TP]
        sub_tr = subtrack_ref[0]                                # [SP]
        w = speak[None, :] & (
            jnp.arange(TP, dtype=jnp.int32)[None, :] != sub_tr[:, None]
        )                                                       # [SP, TP]
        weights = w.astype(jnp.float32) * gain_ref[0][None, :]
        mixed_ref[0] = jnp.dot(weights, pcm_ref[0])             # [SP, N]


def _pallas_live_call(live_rows, decide_ops, mix_ops, *, TP, K, SP, N, L,
                      wire_overhead, top_k, interpret):
    """Assemble and run the live-page pallas_call. `decide_ops` /
    `mix_ops` are the input tuples (or None to skip that half)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # Renamed upstream: TPUCompilerParams (<=0.4.x) -> CompilerParams.
    _CompilerParams = getattr(pltpu, "CompilerParams", None) or (
        pltpu.TPUCompilerParams
    )
    NL = live_rows.shape[0]
    with_decide = decide_ops is not None
    with_mix = mix_ops is not None

    def live(i, lr):
        return lr[i]

    vm = pltpu.VMEM
    st3 = pl.BlockSpec((1, TP, SP), lambda i, lr: (live(i, lr), 0, 0),
                       memory_space=vm)
    t2 = pl.BlockSpec((1, TP), lambda i, lr: (live(i, lr), 0),
                      memory_space=vm)
    pk = pl.BlockSpec((1, TP, K), lambda i, lr: (live(i, lr), 0, 0),
                      memory_space=vm)
    in_specs: list = []
    inputs: list = []
    if with_decide:
        in_specs += [st3] * 4 + [t2] * 2 + [st3] + [pk] * 11
        inputs += list(decide_ops)
    if with_mix:
        pcm_spec = pl.BlockSpec((1, TP, N), lambda i, lr: (live(i, lr), 0, 0),
                                memory_space=vm)
        s2 = pl.BlockSpec((1, SP), lambda i, lr: (live(i, lr), 0),
                          memory_space=vm)
        in_specs += [pcm_spec, t2, t2, t2, s2]
        inputs += list(mix_ops)

    # Compact outputs: block i of the [NL]-leading result arrays.
    c3 = pl.BlockSpec((1, TP, SP), lambda i, lr: (i, 0, 0), memory_space=vm)
    cw = pl.BlockSpec((1, TP, K), lambda i, lr: (i, 0, 0), memory_space=vm)
    cs = pl.BlockSpec((1, SP), lambda i, lr: (i, 0), memory_space=vm)
    ct = pl.BlockSpec((1, 1), lambda i, lr: (i, 0), memory_space=vm)
    cst = pl.BlockSpec((1, 5, TP * L, K), lambda i, lr: (i, 0, 0, 0),
                       memory_space=vm)
    ctr = pl.BlockSpec((1, 3, TP * L), lambda i, lr: (i, 0, 0),
                       memory_space=vm)
    out_specs: list = []
    out_shape: list = []
    if with_decide:
        i32 = jnp.int32
        out_specs += [cw] * 3 + [c3] * 3 + [cs] * 2 + [ct] * 2 + [cst, ctr]
        out_shape += [
            jax.ShapeDtypeStruct((NL, TP, K), i32),      # send words
            jax.ShapeDtypeStruct((NL, TP, K), i32),      # drop words
            jax.ShapeDtypeStruct((NL, TP, K), i32),      # switch words
            jax.ShapeDtypeStruct((NL, TP, SP), i32),     # out_sp
            jax.ShapeDtypeStruct((NL, TP, SP), i32),     # out_tp
            jax.ShapeDtypeStruct((NL, TP, SP), i32),     # need_kf
            jax.ShapeDtypeStruct((NL, SP), i32),         # pkts_sent
            jax.ShapeDtypeStruct((NL, SP), i32),         # sent_bytes
            jax.ShapeDtypeStruct((NL, 1), i32),          # fwd_packets
            jax.ShapeDtypeStruct((NL, 1), i32),          # fwd_bytes
            jax.ShapeDtypeStruct((NL, 5, TP * L, K), i32),
            jax.ShapeDtypeStruct((NL, 3, TP * L), i32),
        ]
    if with_mix:
        cm = pl.BlockSpec((1, SP, N), lambda i, lr: (i, 0, 0),
                          memory_space=vm)
        out_specs += [cm]
        out_shape += [jax.ShapeDtypeStruct((NL, SP, N), jnp.float32)]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(NL,),
        in_specs=in_specs,
        out_specs=tuple(out_specs),
    )
    return pl.pallas_call(
        functools.partial(
            _page_kernel, TP=TP, K=K, SP=SP, L=L,
            wire_overhead=wire_overhead, top_k=top_k,
            with_decide=with_decide, with_mix=with_mix,
        ),
        out_shape=tuple(out_shape),
        grid_spec=grid_spec,
        # v5e has 128 MB of VMEM; page blocks are small but the unrolled
        # K loop keeps many live ranges (cf. ops/selector.py).
        compiler_params=_CompilerParams(vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(jnp.asarray(live_rows, jnp.int32), *inputs)


def _decide_inputs(sel_state, is_svc, is_video, base, inp):
    i32 = lambda x: jnp.asarray(x, jnp.int32)  # noqa: E731
    return (
        i32(sel_state.current_spatial), i32(sel_state.current_temporal),
        i32(sel_state.target_spatial), i32(sel_state.target_temporal),
        i32(is_svc), i32(is_video), i32(base),
        i32(inp.layer), i32(inp.temporal), i32(inp.keyframe),
        i32(inp.layer_sync), i32(inp.end_frame), i32(inp.valid),
        i32(inp.size), i32(inp.sn), i32(inp.ts), i32(inp.arrival_rtp),
        i32(inp.begin_pic),
    )


def _decide_from_call(res, sel_state, live_rows):
    (send_w, drop_w, sw_w, out_sp, out_tp, nkf, pkts, byts, fp, fb,
     st, tr) = res[:12]
    sel_new = selector.SelectorState(
        current_spatial=out_sp,
        current_temporal=out_tp,
        target_spatial=sel_state.target_spatial[live_rows],
        target_temporal=sel_state.target_temporal[live_rows],
    )
    return LiveDecide(
        sel=sel_new,
        send_bits=send_w[:, :, :, None],
        drop_bits=drop_w[:, :, :, None],
        switch_bits=sw_w[:, :, :, None],
        need_kf=nkf.astype(bool),
        pkts_sent=pkts, sent_bytes=byts,
        fwd_packets=fp[:, 0], fwd_bytes=fb[:, 0],
        st=st, tr=tr,
    )


def _decide_fallback(sel_state, is_svc, is_video, base, inp, live_rows,
                     wire_overhead):
    """Compact live-row decide without Pallas: the stock fallback algebra
    over gathered rows (bit-identical per row). Routing is left to the
    phase-1 core (st/tr None)."""
    def g(a):
        return a[live_rows]

    sel_c = jax.tree.map(g, sel_state)
    (sel_new, send, drop, sw, nkf, pkts, byts, fp, fb) = selector.decide_rooms(
        sel_c, g(is_svc), g(is_video), g(base),
        g(inp.layer), g(inp.temporal), g(inp.keyframe),
        g(inp.layer_sync), g(inp.end_frame), g(inp.valid), g(inp.size),
        wire_overhead=wire_overhead, use_pallas=False,
    )
    return LiveDecide(sel_new, send, drop, sw, nkf, pkts, byts, fp, fb,
                      None, None)


@device_entry("paged_kernel.decide_pages")
def decide_pages(sel_state, is_svc, is_video, base, inp, live_rows, *,
                 wire_overhead: int, num_layers: int = NUM_LAYERS,
                 use_pallas: bool | None = None, interpret: bool = False):
    """Phase 0 of the live-extent tick: the fused forward decision +
    routing for the live pages named by `live_rows` (pow2-padded pool
    ids). Operands stay at POOLED shapes — the kernel's index maps read
    only the live blocks; the fallback gathers them. Returns LiveDecide
    (leading axis NL = live_rows.shape[0])."""
    if not (_resolve_pallas(use_pallas) or interpret):
        return _decide_fallback(sel_state, is_svc, is_video, base, inp,
                                live_rows, wire_overhead)
    P, TP, SP = base.shape
    K = inp.layer.shape[2]
    if SP > 32:
        raise ValueError(f"sub page must fit one mask word, got SP={SP}")
    res = _pallas_live_call(
        live_rows, _decide_inputs(sel_state, is_svc, is_video, base, inp),
        None, TP=TP, K=K, SP=SP, N=0, L=num_layers,
        wire_overhead=wire_overhead, top_k=0, interpret=interpret,
    )
    return _decide_from_call(res, sel_state, live_rows)


def mix_pages(pcm, level, active, sub_track, gain, live_rows, *,
              top_k: int = 3, use_pallas: bool | None = None,
              interpret: bool = False):
    """Active-speaker mix for the live pages only: [NL, SP, N] PCM.
    Page-local speaker gate — exact vs ops/mix.mix_tick when a room's
    tracks fit one track page (module doc)."""
    if not (_resolve_pallas(use_pallas) or interpret):
        from livekit_server_tpu.ops import mix

        def g(a):
            return a[live_rows]

        return mix.mix_tick(g(pcm), g(level), g(active), g(sub_track),
                            g(gain), top_k=top_k)
    P, TP, N = pcm.shape
    SP = sub_track.shape[1]
    (mixed,) = _pallas_live_call(
        live_rows, None,
        (jnp.asarray(pcm, jnp.float32), jnp.asarray(level, jnp.float32),
         jnp.asarray(active, jnp.int32), jnp.asarray(gain, jnp.float32),
         jnp.asarray(sub_track, jnp.int32)),
        TP=TP, K=0, SP=SP, N=N, L=NUM_LAYERS,
        wire_overhead=0, top_k=top_k, interpret=interpret,
    )
    # Soft clip outside the kernel: same jnp.tanh op as mix_tick's.
    return jnp.tanh(mixed)


def decide_mix_pages(sel_state, is_svc, is_video, base, inp,
                     pcm, level, active, sub_track, gain, live_rows, *,
                     wire_overhead: int, top_k: int = 3,
                     num_layers: int = NUM_LAYERS,
                     use_pallas: bool | None = None,
                     interpret: bool = False):
    """Decide AND mix in a single pass per live page — one pallas_call,
    one grid, both output sets. Returns (LiveDecide, mixed [NL, SP, N])."""
    if not (_resolve_pallas(use_pallas) or interpret):
        dec = _decide_fallback(sel_state, is_svc, is_video, base, inp,
                               live_rows, wire_overhead)
        mixed = mix_pages(pcm, level, active, sub_track, gain, live_rows,
                          top_k=top_k, use_pallas=False, interpret=False)
        return dec, mixed
    P, TP, SP = base.shape
    K = inp.layer.shape[2]
    N = pcm.shape[2]
    if SP > 32:
        raise ValueError(f"sub page must fit one mask word, got SP={SP}")
    res = _pallas_live_call(
        live_rows, _decide_inputs(sel_state, is_svc, is_video, base, inp),
        (jnp.asarray(pcm, jnp.float32), jnp.asarray(level, jnp.float32),
         jnp.asarray(active, jnp.int32), jnp.asarray(gain, jnp.float32),
         jnp.asarray(sub_track, jnp.int32)),
        TP=TP, K=K, SP=SP, N=N, L=num_layers,
        wire_overhead=wire_overhead, top_k=top_k, interpret=interpret,
    )
    return _decide_from_call(res, sel_state, live_rows), jnp.tanh(res[12])
