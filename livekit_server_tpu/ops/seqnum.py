"""Wrap-aware RTP sequence-number / timestamp arithmetic.

Reference parity: pkg/sfu/utils/wraparound.go (16/32-bit SN/TS extension to
monotonic counters). TPU-first design difference: rather than extending to
64-bit integers (x64 is off in JAX and slow on TPU), all per-packet math is
done modulo 2^16 / 2^32 in int32 lanes with *signed wrap-aware distances*
(the classic RTP trick), and a separate int32 cycle counter is carried in
stream state for statistics that need absolute totals.

All functions are elementwise and batch over any leading axes.
"""

from __future__ import annotations

import jax.numpy as jnp

MASK16 = 0xFFFF  # plain ints: module import must not init a jax backend
HALF16 = 0x8000


def diff16(a, b):
    """Signed wrap-aware distance a-b for 16-bit sequence numbers.

    Returns values in [-32768, 32767]; positive means `a` is newer.
    Equivalent to the reference's signed delta logic in wraparound.go
    (updateHighest / isHigher semantics).
    """
    a = jnp.asarray(a, jnp.int32)
    b = jnp.asarray(b, jnp.int32)
    return ((a - b + HALF16) & MASK16) - HALF16


def diff32(a, b):
    """Signed wrap-aware distance a-b for 32-bit values (RTP timestamps).

    Operands are uint32 values stored in int32 lanes; int32 two's-complement
    subtraction gives the signed wrapped distance directly.
    """
    a = jnp.asarray(a, jnp.int32)
    b = jnp.asarray(b, jnp.int32)
    return a - b


def add16(a, d):
    """(a + d) mod 2^16 for sequence numbers stored in int32 lanes."""
    return (jnp.asarray(a, jnp.int32) + jnp.asarray(d, jnp.int32)) & MASK16


def sub16(a, d):
    """(a - d) mod 2^16."""
    return (jnp.asarray(a, jnp.int32) - jnp.asarray(d, jnp.int32)) & MASK16


def add32(a, d):
    """(a + d) mod 2^32 in int32 lanes (two's complement wrap)."""
    return jnp.asarray(a, jnp.int32) + jnp.asarray(d, jnp.int32)


def sub32(a, d):
    """(a - d) mod 2^32 in int32 lanes."""
    return jnp.asarray(a, jnp.int32) - jnp.asarray(d, jnp.int32)


def is_newer16(a, b):
    """True where 16-bit SN `a` is strictly newer than `b` (wrap-aware)."""
    return diff16(a, b) > 0


def is_newer32(a, b):
    """True where 32-bit TS `a` is strictly newer than `b` (wrap-aware)."""
    return diff32(a, b) > 0


def update_highest16(highest, cycles, new):
    """Track the highest 16-bit SN seen and count wraps.

    Mirrors wraparound.go Update() highest-tracking: `highest`/`new` are
    16-bit values in int32 lanes; `cycles` counts wraps so that
    ext = cycles * 2^16 + highest is monotonic for stats.

    Returns (new_highest, new_cycles, is_new_highest).
    """
    d = diff16(new, highest)
    newer = d > 0
    wrapped = newer & (jnp.asarray(new, jnp.int32) < jnp.asarray(highest, jnp.int32))
    new_highest = jnp.where(newer, jnp.asarray(new, jnp.int32), highest)
    new_cycles = jnp.where(wrapped, cycles + 1, cycles)
    return new_highest, new_cycles, newer


def update_highest32(highest, cycles, new):
    """Track the highest 32-bit TS seen and count wraps (see update_highest16)."""
    d = diff32(new, highest)
    newer = d > 0
    # Wrap happened iff moving forward while the raw unsigned value decreased.
    a_u = jnp.asarray(new, jnp.uint32)
    b_u = jnp.asarray(highest, jnp.uint32)
    wrapped = newer & (a_u < b_u)
    new_highest = jnp.where(newer, jnp.asarray(new, jnp.int32), highest)
    new_cycles = jnp.where(wrapped, cycles + 1, cycles)
    return new_highest, new_cycles, newer
