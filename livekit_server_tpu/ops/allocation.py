"""Batched forwarder bandwidth-allocation algebra.

Reference parity: pkg/sfu/forwarder.go allocation family — AllocateOptimal
(:591), ProvisionalAllocate/ProvisionalAllocateMute/ProvisionalAllocateGetCooperativeTransition
(:727-1105), AllocateNextHigher (:1107), Pause (:1308), DistanceToDesired
(:569) — and the cooperative cross-track allocation loop in
pkg/sfu/streamallocator/streamallocator.go (allocateAllTracks).

TPU-first re-design: per track a `[4, 4]` (spatial × temporal) bitrate
matrix (the reference's `Bitrates` [4][4] — receiver.go:49); allocation is
mask algebra + argmax/scan over layer matrices, vmapped over subscribers.
The cross-track greedy loop is a `lax.scan` over the (static) track axis
carrying the remaining-budget register — the per-tick "allocation matmul"
named in the north star.

Layer encoding: flat index l = spatial*MAX_T + temporal, -1 = paused.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

MAX_SPATIAL = 4
MAX_TEMPORAL = 4
NUM_LAYERS = MAX_SPATIAL * MAX_TEMPORAL  # 16 flat layers


def flat_layer(spatial, temporal):
    return jnp.asarray(spatial, jnp.int32) * MAX_TEMPORAL + jnp.asarray(temporal, jnp.int32)


def spatial_of(flat):
    return jnp.where(flat < 0, -1, flat // MAX_TEMPORAL)


def temporal_of(flat):
    return jnp.where(flat < 0, -1, flat % MAX_TEMPORAL)


def allowed_mask(bitrates, max_spatial, max_temporal):
    """[..., 4, 4] bool — layers that exist (bitrate > 0) and satisfy the
    subscriber's max-layer settings (reference maxLayer in forwarder.go).

    bitrates: [..., 4, 4] float32/int32 bps; max_spatial/max_temporal: [...]
    """
    s_idx = jnp.arange(MAX_SPATIAL, dtype=jnp.int32)[:, None]
    t_idx = jnp.arange(MAX_TEMPORAL, dtype=jnp.int32)[None, :]
    avail = jnp.asarray(bitrates) > 0
    cap = (s_idx <= jnp.asarray(max_spatial, jnp.int32)[..., None, None]) & (
        t_idx <= jnp.asarray(max_temporal, jnp.int32)[..., None, None]
    )
    return avail & cap


def optimal_layer(bitrates, max_spatial, max_temporal):
    """Highest allowed layer per element — reference AllocateOptimal (:591).

    Returns flat layer index [...], -1 where nothing is allowed.
    """
    mask = allowed_mask(bitrates, max_spatial, max_temporal)
    flat = mask.reshape(*mask.shape[:-2], NUM_LAYERS)
    idx = jnp.arange(NUM_LAYERS, dtype=jnp.int32)
    best = jnp.max(jnp.where(flat, idx, -1), axis=-1)
    return best


def lowest_layer(bitrates, max_spatial, max_temporal):
    """Lowest allowed layer per element (minimal allocation seed)."""
    mask = allowed_mask(bitrates, max_spatial, max_temporal)
    flat = mask.reshape(*mask.shape[:-2], NUM_LAYERS)
    idx = jnp.arange(NUM_LAYERS, dtype=jnp.int32)
    best = jnp.min(jnp.where(flat, idx, NUM_LAYERS), axis=-1)
    return jnp.where(best >= NUM_LAYERS, -1, best)


def layer_bitrate(bitrates, flat):
    """Bitrate of a flat layer index; 0 for -1. bitrates [..., 4, 4]."""
    b = bitrates.reshape(*bitrates.shape[:-2], NUM_LAYERS)
    safe = jnp.clip(flat, 0, NUM_LAYERS - 1)
    val = jnp.take_along_axis(b, safe[..., None], axis=-1)[..., 0]
    return jnp.where(flat < 0, 0, val)


def allocate_budget(bitrates, max_spatial, max_temporal, muted, budget):
    """Cooperative constrained allocation across one subscriber's tracks.

    Reference parity: streamallocator.go allocateAllTracks — two passes over
    tracks sorted by priority: (1) give every audible/visible track its
    minimal layer, (2) upgrade tracks in order to the best layer that fits
    the remaining budget. Tracks the reference marks "deficient" are those
    whose target < optimal.

    Args (leading axes vmap over subscribers):
      bitrates      [T, 4, 4] float32 bps
      max_spatial   [T] int32, max_temporal [T] int32 — subscriber caps
      muted         [T] bool — pub/sub muted (ProvisionalAllocateMute)
      budget        scalar float32 — available channel capacity (bps)

    Returns (target_flat [T] int32, used_bps scalar, deficient [T] bool).
    """
    lo = lowest_layer(bitrates, max_spatial, max_temporal)
    hi = optimal_layer(bitrates, max_spatial, max_temporal)
    lo = jnp.where(muted, -1, lo)
    hi = jnp.where(muted, -1, hi)
    lo_cost = layer_bitrate(bitrates, lo)

    # Pass 1: minimal layers, in track order, while budget lasts.
    def p1(budget_left, xs):
        cost, valid = xs
        take = valid & (cost <= budget_left)
        budget_left = jnp.where(take, budget_left - cost, budget_left)
        return budget_left, take

    budget_left, got_min = jax.lax.scan(p1, jnp.asarray(budget, jnp.float32), (lo_cost, lo >= 0))

    # Pass 2: upgrade each track (in order) to the best layer that fits
    # budget_left + its own minimal cost.
    b_flat = bitrates.reshape(-1, NUM_LAYERS).astype(jnp.float32)
    mask_flat = allowed_mask(bitrates, max_spatial, max_temporal).reshape(-1, NUM_LAYERS)
    idx = jnp.arange(NUM_LAYERS, dtype=jnp.int32)

    def p2(budget_left, xs):
        costs, mask, min_l, min_cost, valid = xs
        avail = jnp.where(valid, budget_left + min_cost, 0.0)
        fits = mask & (costs <= avail)
        best = jnp.max(jnp.where(fits, idx, -1))
        best = jnp.where(valid, jnp.maximum(best, min_l), -1)
        cost = jnp.where(best >= 0, costs[jnp.clip(best, 0, NUM_LAYERS - 1)], 0.0)
        budget_left = jnp.where(valid, avail - cost, budget_left)
        return budget_left, best

    budget_left, target = jax.lax.scan(
        p2, budget_left, (b_flat, mask_flat, lo, jnp.where(got_min, lo_cost, 0.0), got_min)
    )
    used = jnp.asarray(budget, jnp.float32) - budget_left
    deficient = (hi >= 0) & (target < hi)
    return target, used, deficient


def next_higher(bitrates, max_spatial, max_temporal, current_flat):
    """Next layer above current and its incremental cost — reference
    AllocateNextHigher (:1107), used when probing succeeds.

    Returns (next_flat [...], delta_bps [...]); next == current where no
    higher layer exists.
    """
    mask = allowed_mask(bitrates, max_spatial, max_temporal)
    flat_mask = mask.reshape(*mask.shape[:-2], NUM_LAYERS)
    idx = jnp.arange(NUM_LAYERS, dtype=jnp.int32)
    above = flat_mask & (idx > current_flat[..., None])
    nxt = jnp.min(jnp.where(above, idx, NUM_LAYERS), axis=-1)
    has = nxt < NUM_LAYERS
    nxt = jnp.where(has, nxt, current_flat)
    delta = jnp.where(
        has, layer_bitrate(bitrates, nxt) - layer_bitrate(bitrates, current_flat), 0
    )
    return nxt, delta


def distance_to_desired(target_flat, optimal_flat):
    """Layer distance between allocation and optimum — reference
    DistanceToDesired (:569); >0 means deficient, drives probing and
    connection-quality penalties.
    """
    t = jnp.where(target_flat < 0, -1, target_flat)
    o = jnp.where(optimal_flat < 0, -1, optimal_flat)
    return (o - t).astype(jnp.float32) / MAX_TEMPORAL
