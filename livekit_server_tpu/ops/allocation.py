"""Batched forwarder bandwidth-allocation algebra.

Reference parity: pkg/sfu/forwarder.go allocation family — AllocateOptimal
(:591), ProvisionalAllocate/ProvisionalAllocateMute/ProvisionalAllocateGetCooperativeTransition
(:727-1105), AllocateNextHigher (:1107), Pause (:1308), DistanceToDesired
(:569) — and the cooperative cross-track allocation loop in
pkg/sfu/streamallocator/streamallocator.go (allocateAllTracks).

TPU-first re-design: per track a `[4, 4]` (spatial × temporal) bitrate
matrix (the reference's `Bitrates` [4][4] — receiver.go:49); allocation is
mask algebra + argmax/scan over layer matrices, vmapped over subscribers.
The cross-track greedy loop is a `lax.scan` over the (static) track axis
carrying the remaining-budget register — the per-tick "allocation matmul"
named in the north star.

Layer encoding: flat index l = spatial*MAX_T + temporal, -1 = paused.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

MAX_SPATIAL = 4
MAX_TEMPORAL = 4
NUM_LAYERS = MAX_SPATIAL * MAX_TEMPORAL  # 16 flat layers


def flat_layer(spatial, temporal):
    return jnp.asarray(spatial, jnp.int32) * MAX_TEMPORAL + jnp.asarray(temporal, jnp.int32)


def spatial_of(flat):
    return jnp.where(flat < 0, -1, flat // MAX_TEMPORAL)


def temporal_of(flat):
    return jnp.where(flat < 0, -1, flat % MAX_TEMPORAL)


def allowed_mask(bitrates, max_spatial, max_temporal):
    """[..., 4, 4] bool — layers that exist (bitrate > 0) and satisfy the
    subscriber's max-layer settings (reference maxLayer in forwarder.go).

    bitrates: [..., 4, 4] float32/int32 bps; max_spatial/max_temporal: [...]
    """
    s_idx = jnp.arange(MAX_SPATIAL, dtype=jnp.int32)[:, None]
    t_idx = jnp.arange(MAX_TEMPORAL, dtype=jnp.int32)[None, :]
    avail = jnp.asarray(bitrates) > 0
    cap = (s_idx <= jnp.asarray(max_spatial, jnp.int32)[..., None, None]) & (
        t_idx <= jnp.asarray(max_temporal, jnp.int32)[..., None, None]
    )
    return avail & cap


def optimal_layer(bitrates, max_spatial, max_temporal):
    """Highest allowed layer per element — reference AllocateOptimal (:591).

    Returns flat layer index [...], -1 where nothing is allowed.
    """
    mask = allowed_mask(bitrates, max_spatial, max_temporal)
    flat = mask.reshape(*mask.shape[:-2], NUM_LAYERS)
    idx = jnp.arange(NUM_LAYERS, dtype=jnp.int32)
    best = jnp.max(jnp.where(flat, idx, -1), axis=-1)
    return best


def lowest_layer(bitrates, max_spatial, max_temporal):
    """Lowest allowed layer per element (minimal allocation seed)."""
    mask = allowed_mask(bitrates, max_spatial, max_temporal)
    flat = mask.reshape(*mask.shape[:-2], NUM_LAYERS)
    idx = jnp.arange(NUM_LAYERS, dtype=jnp.int32)
    best = jnp.min(jnp.where(flat, idx, NUM_LAYERS), axis=-1)
    return jnp.where(best >= NUM_LAYERS, -1, best)


def layer_bitrate(bitrates, flat):
    """Bitrate of a flat layer index; 0 for -1. bitrates [..., 4, 4]."""
    b = bitrates.reshape(*bitrates.shape[:-2], NUM_LAYERS)
    safe = jnp.clip(flat, 0, NUM_LAYERS - 1)
    val = jnp.take_along_axis(b, safe[..., None], axis=-1)[..., 0]
    return jnp.where(flat < 0, 0, val)


def allocate_budget(bitrates, max_spatial, max_temporal, muted, budget):
    """Cooperative constrained allocation across one subscriber's tracks.

    Reference parity: streamallocator.go allocateAllTracks — two passes over
    tracks sorted by priority: (1) give every audible/visible track its
    minimal layer, (2) upgrade tracks in order to the best layer that fits
    the remaining budget. Tracks the reference marks "deficient" are those
    whose target < optimal.

    Args (leading axes vmap over subscribers):
      bitrates      [T, 4, 4] float32 bps
      max_spatial   [T] int32, max_temporal [T] int32 — subscriber caps
      muted         [T] bool — pub/sub muted (ProvisionalAllocateMute)
      budget        scalar float32 — available channel capacity (bps)

    Returns (target_flat [T] int32, used_bps scalar, deficient [T] bool).
    """
    lo = lowest_layer(bitrates, max_spatial, max_temporal)
    hi = optimal_layer(bitrates, max_spatial, max_temporal)
    lo = jnp.where(muted, -1, lo)
    hi = jnp.where(muted, -1, hi)
    lo_cost = layer_bitrate(bitrates, lo)

    # Pass 1: minimal layers, in track order, while budget lasts.
    def p1(budget_left, xs):
        cost, valid = xs
        take = valid & (cost <= budget_left)
        budget_left = jnp.where(take, budget_left - cost, budget_left)
        return budget_left, take

    # Full unroll: T is small and static; an unrolled scan fuses into one
    # kernel instead of a 16-iteration while loop (TPU loop overhead
    # dominates the tiny per-step vector work).
    budget_left, got_min = jax.lax.scan(
        p1, jnp.asarray(budget, jnp.float32), (lo_cost, lo >= 0),
        unroll=True,
    )

    # Pass 2: upgrade each track (in order) to the best layer that fits
    # budget_left + its own minimal cost.
    b_flat = bitrates.reshape(-1, NUM_LAYERS).astype(jnp.float32)
    mask_flat = allowed_mask(bitrates, max_spatial, max_temporal).reshape(-1, NUM_LAYERS)
    idx = jnp.arange(NUM_LAYERS, dtype=jnp.int32)

    def p2(budget_left, xs):
        costs, mask, min_l, min_cost, valid = xs
        avail = jnp.where(valid, budget_left + min_cost, 0.0)
        fits = mask & (costs <= avail)
        best = jnp.max(jnp.where(fits, idx, -1))
        best = jnp.where(valid, jnp.maximum(best, min_l), -1)
        cost = jnp.where(best >= 0, costs[jnp.clip(best, 0, NUM_LAYERS - 1)], 0.0)
        budget_left = jnp.where(valid, avail - cost, budget_left)
        return budget_left, best

    budget_left, target = jax.lax.scan(
        p2, budget_left,
        (b_flat, mask_flat, lo, jnp.where(got_min, lo_cost, 0.0), got_min),
        unroll=True,
    )
    used = jnp.asarray(budget, jnp.float32) - budget_left
    deficient = (hi >= 0) & (target < hi)
    return target, used, deficient


def allocate_budget_batch(bitrates, max_spatial, max_temporal, muted, budget):
    """One room's allocation for ALL subscribers at once — the scan
    formulation (the spec). The production TPU path is the room-batched
    `allocate_budget_rooms` kernel, pinned bit-identical to this by
    tests/test_allocation.py.

    Args:
      bitrates      [T, 4, 4] float32
      max_spatial   [S, T] int32, max_temporal [S, T] int32
      muted         [S, T] bool
      budget        [S] float32
    Returns (target [S, T] int32, used [S] float32, deficient [S, T] bool).
    """
    return jax.vmap(
        lambda m1, m2, m3, b: allocate_budget(bitrates, m1, m2, m3, b)
    )(max_spatial, max_temporal, muted, budget)


# ---------------------------------------------------------------------------
# Room-batched kernel: rooms on the vector lanes (see ops/selector.py's
# room-batched twin for the rationale — the vmapped per-room grid pays
# per-step fixed costs at ~8% lane occupancy).
# ---------------------------------------------------------------------------


def _budget_rooms_kernel(bit_ref, ms_ref, mt_ref, muted_ref, budget_ref,
                         target_ref, used_ref, defc_ref):
    """Two-pass cooperative allocation for a ROOM BLOCK: bit_ref
    [T, L, RB]; ms/mt/muted [T, S, RB]; budget [1, S, RB]; outputs
    target/defc [T, S, RB], used [1, S, RB]."""
    T, L, RB = bit_ref.shape
    S = ms_ref.shape[1]
    l_sp = jax.lax.broadcasted_iota(jnp.int32, (L, S, RB), 0) // MAX_TEMPORAL
    l_tp = jax.lax.broadcasted_iota(jnp.int32, (L, S, RB), 0) % MAX_TEMPORAL
    l_ix = jax.lax.broadcasted_iota(jnp.int32, (L, S, RB), 0)

    allowed, lo, hi, locost = [], [], [], []
    for t in range(T):
        bt = bit_ref[t, :, :][:, None, :]                           # [L,1,RB]
        a = (
            (bt > 0.0)
            & (l_sp <= ms_ref[t, :, :][None, :, :])
            & (l_tp <= mt_ref[t, :, :][None, :, :])
            & (muted_ref[t, :, :][None, :, :] == 0)
        )                                                           # [L,S,RB]
        lo_t = jnp.min(jnp.where(a, l_ix, L), axis=0)               # [S,RB]
        lo_t = jnp.where(lo_t >= L, -1, lo_t)
        hi_t = jnp.max(jnp.where(a, l_ix, -1), axis=0)
        lc = jnp.sum(jnp.where(l_ix == lo_t[None, :, :], bt, 0.0), axis=0)
        allowed.append(a); lo.append(lo_t); hi.append(hi_t); locost.append(lc)

    bl = budget_ref[0, :, :]                                        # [S,RB]
    got = []
    for t in range(T):                                              # pass 1
        take = (lo[t] >= 0) & (locost[t] <= bl)
        bl = jnp.where(take, bl - locost[t], bl)
        got.append(take)
    for t in range(T):                                              # pass 2
        bt = bit_ref[t, :, :][:, None, :]
        avail = jnp.where(got[t], bl + locost[t], 0.0)
        fits = allowed[t] & (bt <= avail[None, :, :])
        best = jnp.max(jnp.where(fits, l_ix, -1), axis=0)
        best = jnp.where(got[t], jnp.maximum(best, lo[t]), -1)
        cost = jnp.sum(jnp.where(l_ix == best[None, :, :], bt, 0.0), axis=0)
        cost = jnp.where(best >= 0, cost, 0.0)
        bl = jnp.where(got[t], avail - cost, bl)
        target_ref[t, :, :] = best
        defc_ref[t, :, :] = ((hi[t] >= 0) & (best < hi[t])).astype(jnp.int32)
    used_ref[0, :, :] = budget_ref[0, :, :] - bl


def allocate_budget_rooms(bitrates, max_spatial, max_temporal, muted, budget,
                          use_pallas: bool | None = None,
                          interpret: bool = False):
    """All rooms' allocation at once.

    Args:
      bitrates      [R, T, 4, 4] float32
      max_spatial   [R, S, T] int32, max_temporal [R, S, T] int32
      muted         [R, S, T] bool
      budget        [R, S] float32
    Returns (target [R, S, T] int32, used [R, S] float32,
    deficient [R, S, T] bool).
    """
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if not (use_pallas or interpret):
        return jax.vmap(allocate_budget_batch)(
            bitrates, max_spatial, max_temporal, muted, budget
        )

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # Renamed upstream: TPUCompilerParams (<=0.4.x) -> CompilerParams.
    _CompilerParams = getattr(pltpu, "CompilerParams", None) or (
        pltpu.TPUCompilerParams
    )

    R, T = bitrates.shape[:2]
    S = budget.shape[-1]
    from livekit_server_tpu.ops.selector import pick_room_block

    # Working set: bitrates [T,L,RB] + five [T,S,RB] blocks + two [1,S,RB].
    RB = pick_room_block(R, 4 * (T * NUM_LAYERS + 5 * T * S + 2 * S))
    f32 = lambda x: jnp.asarray(x, jnp.float32)  # noqa: E731
    i32 = lambda x: jnp.asarray(x, jnp.int32)    # noqa: E731

    bit_spec = pl.BlockSpec((T, NUM_LAYERS, RB), lambda i: (0, 0, i),
                            memory_space=pltpu.VMEM)
    st_spec = pl.BlockSpec((T, S, RB), lambda i: (0, 0, i),
                           memory_space=pltpu.VMEM)
    bud_spec = pl.BlockSpec((1, S, RB), lambda i: (0, 0, i),
                            memory_space=pltpu.VMEM)
    target, used, defc = pl.pallas_call(
        _budget_rooms_kernel,
        grid=(R // RB,),
        out_shape=(
            jax.ShapeDtypeStruct((T, S, R), jnp.int32),
            jax.ShapeDtypeStruct((1, S, R), jnp.float32),
            jax.ShapeDtypeStruct((T, S, R), jnp.int32),
        ),
        in_specs=[bit_spec, st_spec, st_spec, st_spec, bud_spec],
        out_specs=(st_spec, bud_spec, st_spec),
        compiler_params=_CompilerParams(
            vmem_limit_bytes=48 * 1024 * 1024
        ),
        interpret=interpret,
    )(
        f32(bitrates).reshape(R, T, NUM_LAYERS).transpose(1, 2, 0),
        i32(max_spatial).transpose(2, 1, 0),
        i32(max_temporal).transpose(2, 1, 0),
        i32(muted).transpose(2, 1, 0),
        f32(budget).transpose(1, 0)[None],
    )
    return (
        target.transpose(2, 1, 0),
        used[0].transpose(1, 0),
        defc.transpose(2, 1, 0).astype(bool),
    )


def next_higher(bitrates, max_spatial, max_temporal, current_flat):
    """Next layer above current and its incremental cost — reference
    AllocateNextHigher (:1107), used when probing succeeds.

    Returns (next_flat [...], delta_bps [...]); next == current where no
    higher layer exists.
    """
    mask = allowed_mask(bitrates, max_spatial, max_temporal)
    flat_mask = mask.reshape(*mask.shape[:-2], NUM_LAYERS)
    idx = jnp.arange(NUM_LAYERS, dtype=jnp.int32)
    above = flat_mask & (idx > current_flat[..., None])
    nxt = jnp.min(jnp.where(above, idx, NUM_LAYERS), axis=-1)
    has = nxt < NUM_LAYERS
    nxt = jnp.where(has, nxt, current_flat)
    delta = jnp.where(
        has, layer_bitrate(bitrates, nxt) - layer_bitrate(bitrates, current_flat), 0
    )
    return nxt, delta


def distance_to_desired(target_flat, optimal_flat):
    """Layer distance between allocation and optimum — reference
    DistanceToDesired (:569); >0 means deficient, drives probing and
    connection-quality penalties.
    """
    t = jnp.where(target_flat < 0, -1, target_flat)
    o = jnp.where(optimal_flat < 0, -1, optimal_flat)
    return (o - t).astype(jnp.float32) / MAX_TEMPORAL
