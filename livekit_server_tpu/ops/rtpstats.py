"""Batched per-stream RTP statistics.

Reference parity: pkg/sfu/buffer rtpstats_base.go / rtpstats_receiver.go /
rtpstats_sender.go (extended SN/TS tracking, loss accounting, RFC 3550
interarrival jitter, receiver-report snapshots) plus the per-tick packet/
byte rate reporting feeding NodeStats (pkg/rtc/participant_traffic_load.go)
and Prometheus counters (pkg/telemetry/prometheus/packets.go).

TPU-first re-design: one state row per stream ([N] = tracks × layers);
per-tick packet batches reduce along the packet axis; the only serial part
(jitter's consecutive-packet transit delta) is a short `lax.scan` over the
static per-tick packet axis.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from livekit_server_tpu.ops import seqnum


class StreamStats(NamedTuple):
    """Per-stream receiver stats; fields are [..., N]."""

    started: jax.Array       # bool
    first_sn: jax.Array      # int32 — 16-bit SN of first packet
    highest_sn: jax.Array    # int32 — 16-bit highest SN seen
    sn_cycles: jax.Array     # int32 — SN wrap count
    highest_ts: jax.Array    # int32 — 32-bit highest TS seen
    received: jax.Array      # int32 — packets received
    bytes: jax.Array         # int32 — payload bytes received
    dups: jax.Array          # int32 — duplicate/old packets
    jitter_q4: jax.Array     # int32 — RFC3550 jitter in RTP units << 4
    last_transit: jax.Array  # int32 — last (arrival_rtp - pkt_ts)
    # Snapshot registers for delta reports (reference RTPDeltaInfo):
    snap_received: jax.Array
    snap_expected: jax.Array


def init_state(num_streams: int) -> StreamStats:
    z = jnp.zeros((num_streams,), jnp.int32)
    return StreamStats(
        started=jnp.zeros((num_streams,), jnp.bool_),
        first_sn=z, highest_sn=z, sn_cycles=z, highest_ts=z,
        received=z, bytes=z, dups=z, jitter_q4=z, last_transit=z,
        snap_received=z, snap_expected=z,
    )


def expected_packets(s: StreamStats) -> jax.Array:
    """Cumulative expected packet count = ext_highest - first + 1."""
    ext_hi = s.sn_cycles * 65536 + s.highest_sn
    return jnp.where(s.started, ext_hi - s.first_sn + 1, 0)


def cumulative_lost(s: StreamStats) -> jax.Array:
    return jnp.maximum(expected_packets(s) - s.received, 0)


def update_tick(
    state: StreamStats,
    pkt_sn: jax.Array,        # [N, K] int32 — 16-bit SNs, arrival order
    pkt_ts: jax.Array,        # [N, K] int32 — 32-bit RTP timestamps
    pkt_size: jax.Array,      # [N, K] int32 — payload bytes
    arrival_rtp: jax.Array,   # [N, K] int32 — arrival time in RTP clock units
    valid: jax.Array,         # [N, K] bool
) -> StreamStats:
    """Fold one tick of received packets into per-stream stats."""

    def step(carry: StreamStats, xs):
        sn, ts, size, arr, v = xs  # each [N]
        fresh = v & ~carry.started
        first_sn = jnp.where(fresh, sn, carry.first_sn)
        hi0 = jnp.where(fresh, sn, carry.highest_sn)
        started = carry.started | v

        d = seqnum.diff16(sn, hi0)
        newer = v & (d > 0)
        dup = v & ~fresh & (d <= 0)
        wrapped = newer & (sn < hi0)
        highest_sn = jnp.where(newer | fresh, sn, hi0)
        cycles = jnp.where(wrapped, carry.sn_cycles + 1, carry.sn_cycles)
        highest_ts = jnp.where(
            v & (seqnum.diff32(ts, carry.highest_ts) > 0) | fresh, ts, carry.highest_ts
        )

        # RFC 3550 jitter: J += (|D| - J) / 16 in RTP units (stored <<4).
        transit = seqnum.sub32(arr, ts)
        dtr = jnp.abs(seqnum.diff32(transit, carry.last_transit))
        upd = v & ~fresh
        jitter_q4 = jnp.where(
            upd, carry.jitter_q4 + ((dtr << 4) - carry.jitter_q4) // 16, carry.jitter_q4
        )
        last_transit = jnp.where(v, transit, carry.last_transit)

        return StreamStats(
            started=started,
            first_sn=first_sn,
            highest_sn=highest_sn,
            sn_cycles=cycles,
            highest_ts=highest_ts,
            received=carry.received + v.astype(jnp.int32),
            bytes=carry.bytes + jnp.where(v, size, 0),
            dups=carry.dups + dup.astype(jnp.int32),
            jitter_q4=jitter_q4,
            last_transit=last_transit,
            snap_received=carry.snap_received,
            snap_expected=carry.snap_expected,
        ), None

    xs = tuple(jnp.moveaxis(a, -1, 0) for a in (pkt_sn, pkt_ts, pkt_size, arrival_rtp, valid))
    new_state, _ = jax.lax.scan(step, state, xs, unroll=True)
    return new_state


def receiver_report(state: StreamStats):
    """Receiver-report fields since the last snapshot, and roll the snapshot.

    Reference: rtpstats_receiver.go SnapshotRcvrReport → (fraction_lost_q8,
    cumulative_lost, ext_highest_sn, jitter_rtp). Returns (new_state, dict).
    """
    expected = expected_packets(state)
    exp_delta = jnp.maximum(expected - state.snap_expected, 0)
    rcv_delta = jnp.maximum(state.received - state.snap_received, 0)
    lost_delta = jnp.maximum(exp_delta - rcv_delta, 0)
    fraction_q8 = jnp.where(exp_delta > 0, (lost_delta << 8) // jnp.maximum(exp_delta, 1), 0)
    report = {
        "fraction_lost_q8": fraction_q8,
        "cumulative_lost": cumulative_lost(state),
        "ext_highest_sn": state.sn_cycles * 65536 + state.highest_sn,
        "jitter_rtp": state.jitter_q4 >> 4,
    }
    new_state = state._replace(snap_received=state.received, snap_expected=expected)
    return new_state, report
