"""Batched SVC layer selection: VP9 onion layering + dependency descriptor.

Reference parity:
  - pkg/sfu/videolayerselector/vp9.go:43 — VP9 SVC: one stream carries all
    spatial layers; a subscriber at spatial s needs every spatial layer
    <= s of each picture; spatial upswitch gated on a non-inter-predicted
    frame of the new layer, temporal upswitch on switching-up points.
  - pkg/sfu/videolayerselector/dependencydescriptor.go:65-430 — AV1 (and
    any-codec) dependency descriptor: packets carry per-decode-target
    indications (DTIs); the selector pins an active decode target and
    forwards packets whose DTI != not-present, switching at packets whose
    template marks a switch indication.

TPU-first re-design: the host RTP parser (or the C++ shim) reduces each
packet's DD/VP9 header to small ints — spatial sid, temporal tid, flags,
and for DD a 32-bit `dti_mask` (bit d = packet required for decode target
d) and `switch_mask` (bit d = safe switch point for d). Selection is then
pure mask algebra over [P] packets × [S] subscribers, scanned over the
packet axis like ops.selector.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

INVALID = -1  # plain int: module import must not init a jax backend


class SVCSelectorState(NamedTuple):
    """Per-subscriber SVC selection state, fields [..., S] int32."""

    current_spatial: jax.Array   # top spatial layer being forwarded
    current_temporal: jax.Array
    target_spatial: jax.Array
    target_temporal: jax.Array


def init_state(num_subscribers: int, target_spatial: int = 2, target_temporal: int = 3) -> SVCSelectorState:
    s = jnp.full((num_subscribers,), INVALID, jnp.int32)
    return SVCSelectorState(
        current_spatial=s,
        current_temporal=s,
        target_spatial=jnp.full((num_subscribers,), target_spatial, jnp.int32),
        target_temporal=jnp.full((num_subscribers,), target_temporal, jnp.int32),
    )


def select_tick(
    state: SVCSelectorState,
    pkt_spatial: jax.Array,      # [P] int32 — sid of this packet
    pkt_temporal: jax.Array,     # [P] int32 — tid
    pkt_keyframe: jax.Array,     # [P] bool — non-inter-predicted picture
    pkt_switch_up: jax.Array,    # [P] bool — temporal switching-up point
    pkt_end_of_frame: jax.Array, # [P] bool — last packet of the frame
    pkt_valid: jax.Array,        # [P] bool
):
    """VP9-style onion SVC selection for one track.

    Unlike simulcast (ops.selector), a subscriber needs ALL spatial layers
    <= current_spatial, so `forward = sid <= cur_sp & tid <= cur_tp`.
    Downswitch applies at end-of-frame (vp9.go: wait for frame completion);
    upswitch at a keyframe carrying the target layer.
    """

    def step(carry: SVCSelectorState, xs):
        sid, tid, kf, sw_up, eof, valid = xs

        want_up = (carry.target_spatial > carry.current_spatial)
        up = valid & kf & want_up & (sid <= carry.target_spatial)
        cur_sp = jnp.where(up, carry.target_spatial, carry.current_spatial)

        # Downswitch once the current frame finishes (no mid-frame cuts).
        want_down = (carry.target_spatial >= 0) & (carry.target_spatial < cur_sp)
        down = valid & eof & want_down
        cur_sp_next = jnp.where(down, carry.target_spatial, cur_sp)

        on_stream = valid & (cur_sp >= 0)
        # Temporal: upgrade at switching-up points, downgrade immediately.
        cur_tp = carry.current_temporal
        cur_tp = jnp.where(up, carry.target_temporal, cur_tp)
        can_up = on_stream & sw_up & (tid <= carry.target_temporal) & (tid > cur_tp)
        cur_tp = jnp.where(can_up, tid, cur_tp)
        cur_tp = jnp.where(
            on_stream & (carry.target_temporal < cur_tp), carry.target_temporal, cur_tp
        )

        fwd = on_stream & (sid <= cur_sp) & (tid <= cur_tp)
        paused = carry.target_spatial < 0
        fwd = fwd & ~paused
        drp = on_stream & ~fwd

        new_carry = SVCSelectorState(
            current_spatial=jnp.where(paused, INVALID, cur_sp_next),
            current_temporal=cur_tp,
            target_spatial=carry.target_spatial,
            target_temporal=carry.target_temporal,
        )
        return new_carry, (fwd, drp, up)

    xs = (pkt_spatial, pkt_temporal, pkt_keyframe, pkt_switch_up,
          pkt_end_of_frame, pkt_valid)
    new_state, (fwd, drp, up) = jax.lax.scan(step, state, xs, unroll=True)
    need_keyframe = (new_state.target_spatial >= 0) & (
        new_state.target_spatial > new_state.current_spatial
    )
    return new_state, fwd, drp, up, need_keyframe


class DDSelectorState(NamedTuple):
    """Dependency-descriptor selection state, fields [..., S] int32."""

    active_dt: jax.Array      # current decode target index (-1 = none)
    target_dt: jax.Array      # allocator-desired decode target
    last_frame: jax.Array     # last forwarded frame number (chain check)


def init_dd_state(num_subscribers: int, target_dt: int = 0) -> DDSelectorState:
    s = jnp.full((num_subscribers,), INVALID, jnp.int32)
    return DDSelectorState(
        active_dt=s,
        target_dt=jnp.full((num_subscribers,), target_dt, jnp.int32),
        last_frame=s,
    )


def dd_select_tick(
    state: DDSelectorState,
    pkt_dti_mask: jax.Array,    # [P] int32 — bit d: packet present for dt d
    pkt_switch_mask: jax.Array, # [P] int32 — bit d: switch indication for d
    pkt_frame: jax.Array,       # [P] int32 — frame number (monotonic)
    pkt_keyframe: jax.Array,    # [P] bool — chain reset point
    pkt_valid: jax.Array,       # [P] bool
):
    """Decode-target selection (dependencydescriptor.go Select).

    Returns (state, forward [P,S], drop [P,S], broken [S]). `broken` means
    a frame the active decode target depends on was never forwarded (a
    frame-number gap on the chain) — the host responds with a PLI, standing
    in for the reference's chain-tracking frame diffs.
    """

    def bit(mask, d):
        return ((mask >> jnp.maximum(d, 0)) & 1).astype(jnp.bool_) & (d >= 0)

    def step(carry: DDSelectorState, xs):
        dti, sw_mask, frame, kf, valid = xs

        # Switch to the target at a switch-indication packet (or keyframe).
        want = (carry.target_dt != carry.active_dt) & (carry.target_dt >= 0)
        can_switch = valid & want & (bit(sw_mask, carry.target_dt) | kf)
        active = jnp.where(can_switch, carry.target_dt, carry.active_dt)

        fwd = valid & bit(dti, active)
        paused = carry.target_dt < 0
        fwd = fwd & ~paused
        drp = valid & ~fwd & (active >= 0)

        # Chain integrity: forwarded frames must be contiguous-or-forward;
        # a gap of > 1 frame since the last forwarded frame breaks decode.
        gap = fwd & (carry.last_frame >= 0) & (frame - carry.last_frame > 1) & ~kf
        last = jnp.where(fwd, frame, carry.last_frame)
        last = jnp.where(kf & valid, frame, last)

        new_carry = DDSelectorState(
            active_dt=jnp.where(paused, INVALID, active),
            target_dt=carry.target_dt,
            last_frame=last,
        )
        return new_carry, (fwd, drp, gap)

    xs = (pkt_dti_mask, pkt_switch_mask, pkt_frame, pkt_keyframe, pkt_valid)
    new_state, (fwd, drp, gap) = jax.lax.scan(step, state, xs, unroll=True)
    broken = jnp.any(gap, axis=0)
    return new_state, fwd, drp, broken


def set_target(state, target):
    """Apply allocator decision (decode target / spatial-temporal pair)."""
    if isinstance(state, DDSelectorState):
        return state._replace(target_dt=jnp.asarray(target, jnp.int32))
    raise TypeError("use svc.SVCSelectorState._replace for spatial/temporal targets")
