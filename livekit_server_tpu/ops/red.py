"""Batched RED (RFC 2198) encode planning for Opus redundancy.

Reference parity: pkg/sfu/redreceiver.go (~230 LoC, encapsulate primary →
RED with up to 2 redundant blocks) and redprimaryreceiver.go (~260 LoC,
decapsulate RED → primary for non-RED subscribers). The reference builds
RED payloads inline per packet; byte assembly stays host/C++ here, and the
device computes the per-packet *plan*: which previous packets to attach,
their 14-bit timestamp offsets, and whether they fit the offset field.

A RED block header carries (block PT, 14-bit TS offset, 10-bit length);
a primary can carry redundancy only for packets ≤ 16383 TS units back
(redreceiver.go's distance checks).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

MAX_TS_OFFSET = (1 << 14) - 1
MAX_BLOCK_LEN = (1 << 10) - 1
RED_DISTANCE = 2   # redundancy depth (redreceiver.go maxRedCount)


class REDState(NamedTuple):
    """Per-track history of the last RED_DISTANCE packets, [..., T, D]."""

    hist_sn: jax.Array    # int32 — SN of historical packet (-1 empty)
    hist_ts: jax.Array    # int32
    hist_len: jax.Array   # int32 — payload length


def init_state(num_tracks: int) -> REDState:
    shape = (num_tracks, RED_DISTANCE)
    return REDState(
        hist_sn=jnp.full(shape, -1, jnp.int32),
        hist_ts=jnp.zeros(shape, jnp.int32),
        hist_len=jnp.zeros(shape, jnp.int32),
    )


def encode_plan_tick(
    state: REDState,
    sn: jax.Array,      # [T, K] int32
    ts: jax.Array,      # [T, K] int32
    length: jax.Array,  # [T, K] int32 — payload bytes
    valid: jax.Array,   # [T, K] bool
):
    """Per-packet RED plan for one tick.

    Returns (state, red_sn [T,K,D], red_offset [T,K,D], red_len [T,K,D],
    red_ok [T,K,D]): for packet (t,k), the D candidate redundancy blocks
    (most recent first), their TS offsets, lengths, and whether each fits
    RFC 2198 field limits. The host/C++ egress assembles bytes for
    subscribers that negotiated RED and strips for those that didn't
    (RedPrimaryReceiver path is the identity here — primaries are staged
    unmodified).
    """
    T, K = sn.shape
    D = RED_DISTANCE

    # Candidate j for packet k is simply the (j+1)-th most recent VALID
    # packet before k — from this tick if the packet's exclusive valid-
    # rank r covers it (r-1-j ≥ 0), else history slot j-r. Formulated as
    # gathers over the K axis instead of the per-packet scan the original
    # used: the scan's per-step shift chain dominated the cfg4 tick.
    valid_i = valid.astype(jnp.int32)
    from livekit_server_tpu.ops import scanops

    rank = scanops.cumsum_small(valid_i, axis=-1) - valid_i  # [T, K] excl.
    js = jnp.arange(D, dtype=jnp.int32)                     # [D]
    cand_rank = rank[:, :, None] - 1 - js[None, None, :]    # [T, K, D]
    from_tick = cand_rank >= 0
    # Rank-match masked sums instead of sort + gather (both lower poorly
    # on TPU at these shapes; K and D are tiny, so the [T,K,D,K] compare
    # stays elementwise and fuses). Exact for int32 — a float32 one-hot
    # contraction would corrupt 32-bit timestamps. A valid packet's
    # exclusive rank is unique within the tick, so each candidate rank
    # matches at most one source packet.
    tick_oh = (
        valid[:, None, None, :]
        & (rank[:, None, None, :] == cand_rank[..., None])
    )                                                        # [T,K,D,K']
    hist_slot = -cand_rank - 1                               # = j - r
    hist_oh = hist_slot[..., None] == js                     # [T,K,D,D']

    def pick(tick_arr, hist_arr):
        # When from_tick is false, hist_slot = j - r ∈ [0, j] ⊂ [0, D) is
        # always a real slot; empty slots carry sn = -1, which r_ok
        # rejects — no separate fill branch needed.
        tick_v = jnp.sum(
            jnp.where(tick_oh, tick_arr[:, None, None, :], 0), axis=-1
        )
        hist_v = jnp.sum(
            jnp.where(hist_oh, hist_arr[:, None, None, :], 0), axis=-1
        )
        return jnp.where(from_tick, tick_v, hist_v)

    c_sn = pick(sn, state.hist_sn)
    c_ts = pick(ts, state.hist_ts)
    c_len = pick(length, state.hist_len)
    off = ts[:, :, None] - c_ts
    r_ok = (
        (c_sn >= 0)
        & valid[:, :, None]
        & (off > 0)
        & (off <= MAX_TS_OFFSET)
        & (c_len <= MAX_BLOCK_LEN)
        # redundancy must be the immediately preceding SNs
        & (((sn[:, :, None] - c_sn) & 0xFFFF) <= D)
    )

    # New history: the last D valid packets overall (tick + old history),
    # most recent first — same rank-match selection with r = the tick's
    # total valids.
    total = jnp.sum(valid_i, axis=-1, keepdims=True)        # [T, 1]
    h_rank = total - 1 - js[None, :]                        # [T, D]
    h_from_tick = h_rank >= 0
    h_tick_oh = (
        valid[:, None, :] & (rank[:, None, :] == h_rank[..., None])
    )                                                       # [T,D,K']
    h_slot = -h_rank - 1
    h_hist_oh = h_slot[..., None] == js                     # [T,D,D']

    def pick_hist(tick_arr, hist_arr):
        # Same slot-range argument as pick(): the fill branch cannot fire.
        tick_v = jnp.sum(
            jnp.where(h_tick_oh, tick_arr[:, None, :], 0), axis=-1
        )
        hist_v = jnp.sum(
            jnp.where(h_hist_oh, hist_arr[:, None, :], 0), axis=-1
        )
        return jnp.where(h_from_tick, tick_v, hist_v)

    new_state = REDState(
        hist_sn=pick_hist(sn, state.hist_sn),
        hist_ts=pick_hist(ts, state.hist_ts),
        hist_len=pick_hist(length, state.hist_len),
    )
    return new_state, c_sn, off, c_len, r_ok
