"""Batched RED (RFC 2198) encode planning for Opus redundancy.

Reference parity: pkg/sfu/redreceiver.go (~230 LoC, encapsulate primary →
RED with up to 2 redundant blocks) and redprimaryreceiver.go (~260 LoC,
decapsulate RED → primary for non-RED subscribers). The reference builds
RED payloads inline per packet; byte assembly stays host/C++ here, and the
device computes the per-packet *plan*: which previous packets to attach,
their 14-bit timestamp offsets, and whether they fit the offset field.

A RED block header carries (block PT, 14-bit TS offset, 10-bit length);
a primary can carry redundancy only for packets ≤ 16383 TS units back
(redreceiver.go's distance checks).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

MAX_TS_OFFSET = (1 << 14) - 1
MAX_BLOCK_LEN = (1 << 10) - 1
RED_DISTANCE = 2   # redundancy depth (redreceiver.go maxRedCount)


class REDState(NamedTuple):
    """Per-track history of the last RED_DISTANCE packets, [..., T, D]."""

    hist_sn: jax.Array    # int32 — SN of historical packet (-1 empty)
    hist_ts: jax.Array    # int32
    hist_len: jax.Array   # int32 — payload length


def init_state(num_tracks: int) -> REDState:
    shape = (num_tracks, RED_DISTANCE)
    return REDState(
        hist_sn=jnp.full(shape, -1, jnp.int32),
        hist_ts=jnp.zeros(shape, jnp.int32),
        hist_len=jnp.zeros(shape, jnp.int32),
    )


def encode_plan_tick(
    state: REDState,
    sn: jax.Array,      # [T, K] int32
    ts: jax.Array,      # [T, K] int32
    length: jax.Array,  # [T, K] int32 — payload bytes
    valid: jax.Array,   # [T, K] bool
):
    """Per-packet RED plan for one tick.

    Returns (state, red_sn [T,K,D], red_offset [T,K,D], red_len [T,K,D],
    red_ok [T,K,D]): for packet (t,k), the D candidate redundancy blocks
    (most recent first), their TS offsets, lengths, and whether each fits
    RFC 2198 field limits. The host/C++ egress assembles bytes for
    subscribers that negotiated RED and strips for those that didn't
    (RedPrimaryReceiver path is the identity here — primaries are staged
    unmodified).
    """
    T, K = sn.shape
    D = RED_DISTANCE

    def per_track(hist, xs):
        h_sn, h_ts, h_len = hist

        def step(carry, x):
            c_sn, c_ts, c_len = carry
            p_sn, p_ts, p_len, p_valid = x
            # Candidates: current history, most recent first.
            off = p_ts - c_ts
            ok = (
                (c_sn >= 0)
                & p_valid
                & (off > 0)
                & (off <= MAX_TS_OFFSET)
                & (c_len <= MAX_BLOCK_LEN)
                # redundancy must be the immediately preceding SNs
                & ((p_sn - c_sn) & 0xFFFF <= D)
            )
            out = (c_sn, off, c_len, ok)
            # Shift history: new packet enters slot 0.
            n_sn = jnp.where(p_valid, jnp.concatenate([p_sn[None], c_sn[:-1]]), c_sn)
            n_ts = jnp.where(p_valid, jnp.concatenate([p_ts[None], c_ts[:-1]]), c_ts)
            n_len = jnp.where(p_valid, jnp.concatenate([p_len[None], c_len[:-1]]), c_len)
            return (n_sn, n_ts, n_len), out

        (h_sn, h_ts, h_len), outs = jax.lax.scan(step, (h_sn, h_ts, h_len), xs, unroll=True)
        return (h_sn, h_ts, h_len), outs

    def run_one(h_sn, h_ts, h_len, t_sn, t_ts, t_len, t_valid):
        (n_sn, n_ts, n_len), (r_sn, r_off, r_len, r_ok) = per_track(
            (h_sn, h_ts, h_len), (t_sn, t_ts, t_len, t_valid)
        )
        return n_sn, n_ts, n_len, r_sn, r_off, r_len, r_ok

    n_sn, n_ts, n_len, r_sn, r_off, r_len, r_ok = jax.vmap(run_one)(
        state.hist_sn, state.hist_ts, state.hist_len, sn, ts, length, valid
    )
    new_state = REDState(hist_sn=n_sn, hist_ts=n_ts, hist_len=n_len)
    return new_state, r_sn, r_off, r_len, r_ok
