"""Server-side audio mixing (the MCU seat) — BASELINE config 2.

Reference parity: the reference is SFU-only (pkg/sfu/audio/audiolevel.go
reads levels; it never decodes). This build's BASELINE commits to a
batched active-speaker mix, so the seat is real here: per-track Opus
decode (host, stateful — interop/opus.py over libopus), an [S, T] mix
(numpy at per-room scale; ops/mix.py's einsum kernel is the same math
batched on-device for the 1000-room shape, benchmarked in bench.py),
and per-subscriber Opus re-encode with self-exclusion (you never hear
yourself).

Egress rides the transport's `_sendto` chokepoint, so a mixed stream
reaches sealed, TCP-fallback, and SRTP-gateway subscribers through
their own lanes unchanged.

Opt-in: signal `subscription {"audio_mix": true}` (signalhandler) or
`AudioMixer.enable_sub` directly. Subscribers typically unsubscribe the
individual audio tracks at the same time — the mix replaces them.
"""

from __future__ import annotations

import time
from collections import deque

import numpy as np

from livekit_server_tpu.interop import opus

__all__ = ["AudioMixer"]

OPUS_PT = 111
# A track with no packet for this long stops contributing (and stops
# burning PLC) until media resumes.
ACTIVE_TTL_S = 0.4
# Brief gaps inside an active stream are concealed by the decoder.
PLC_MAX_FRAMES = 10


class _TrackLane:
    def __init__(self):
        self.dec = opus.OpusDecoder()
        self.pending: deque = deque(maxlen=3)   # tiny jitter absorber
        self.last_seen = 0.0
        self.plc_run = 0


class _SubLane:
    def __init__(self, ssrc: int, bitrate: int, exclude_track: int):
        self.enc = opus.OpusEncoder(bitrate=bitrate)
        self.ssrc = ssrc
        self.sn = 0
        self.ts = 0
        self.exclude_track = exclude_track


class _RoomMix:
    def __init__(self):
        self.tracks: dict[int, _TrackLane] = {}
        self.subs: dict[int, _SubLane] = {}


class AudioMixer:
    """Per-node mixing state; owned by UDPMediaTransport
    (enable_audio_mixer)."""

    def __init__(self, transport, frame_ms: int = 20):
        if not opus.available():
            raise opus.OpusError("libopus not available on this host")
        self.transport = transport
        self.frame_s = frame_ms / 1000.0
        self.rooms: dict[int, _RoomMix] = {}
        self._room_arr = np.zeros(0, np.int64)
        self._next_at = 0.0
        self.stats = {"frames_mixed": 0, "packets_out": 0, "decode_errors": 0}

    # -- control ----------------------------------------------------------

    def enable_sub(
        self, room: int, sub: int, enabled: bool = True,
        exclude_track: int = -1, bitrate: int = 32000,
    ) -> None:
        """Opt one subscriber into (or out of) the room's mixed stream.
        `exclude_track` is their own audio track column (self-exclusion)."""
        if enabled:
            rm = self.rooms.setdefault(room, _RoomMix())
            lane = rm.subs.get(sub)
            if lane is None:
                rm.subs[sub] = _SubLane(
                    self.transport._new_ssrc(), bitrate, exclude_track
                )
            else:
                lane.exclude_track = exclude_track
        else:
            rm = self.rooms.get(room)
            if rm is not None:
                rm.subs.pop(sub, None)
                if not rm.subs:
                    self.rooms.pop(room, None)
        self._room_arr = np.fromiter(self.rooms, np.int64, len(self.rooms))

    def set_publisher_track(self, room: int, sub_col: int, track: int) -> None:
        """An audio track was published by the participant holding
        `sub_col`: keep that subscriber's self-exclusion current even when
        the opt-in arrived before the publish (or across republishes)."""
        rm = self.rooms.get(room)
        if rm is not None and sub_col in rm.subs:
            rm.subs[sub_col].exclude_track = track

    def release_track(self, room: int, track: int) -> None:
        """Track column freed: its decoder state and queued payloads must
        not leak to the column's next tenant, and stale self-exclusions
        must not mute the next publisher for unrelated subscribers."""
        rm = self.rooms.get(room)
        if rm is None:
            return
        lane = rm.tracks.pop(track, None)
        if lane is not None:
            lane.dec.close()
        for sub_lane in rm.subs.values():
            if sub_lane.exclude_track == track:
                sub_lane.exclude_track = -1

    def release_room(self, room: int) -> None:
        rm = self.rooms.pop(room, None)
        if rm is not None:
            for lane in rm.tracks.values():
                lane.dec.close()
            for lane in rm.subs.values():
                lane.enc.close()
        self._room_arr = np.fromiter(self.rooms, np.int64, len(self.rooms))

    def room_mask(self, rooms: np.ndarray) -> np.ndarray:
        """Vector mask: which entries belong to mix-enabled rooms."""
        return np.isin(rooms, self._room_arr)

    # -- ingest tap (udp._process_media_arrays, audio in enabled rooms) ---

    def push(self, room: int, track: int, ts: int, payload: bytes) -> None:
        rm = self.rooms.get(room)
        if rm is None or not payload:
            return
        lane = rm.tracks.get(track)
        if lane is None:
            try:
                lane = rm.tracks[track] = _TrackLane()
            except opus.OpusError:
                return
        lane.pending.append(payload)
        lane.last_seen = time.monotonic()

    # -- frame clock ------------------------------------------------------

    def maybe_tick(self, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        if now < self._next_at:
            return
        # Accumulate from the PREVIOUS deadline (with a one-frame catch-up
        # clamp): rescheduling from `now` would add the caller's lateness
        # to every period, running the frame clock slower than real time
        # and overflowing the per-track jitter queues.
        self._next_at = max(self._next_at + self.frame_s, now - self.frame_s)
        self.tick(now)

    def tick(self, now: float | None = None) -> None:
        """Mix + emit one 20 ms frame for every enabled room."""
        now = time.monotonic() if now is None else now
        for room, rm in list(self.rooms.items()):
            pcm_by_track: dict[int, np.ndarray] = {}
            for track, lane in list(rm.tracks.items()):
                if lane.pending:
                    lane.plc_run = 0
                    try:
                        pcm = lane.dec.decode(lane.pending.popleft())
                    except opus.OpusError:
                        self.stats["decode_errors"] += 1
                        continue
                elif (
                    now - lane.last_seen < ACTIVE_TTL_S
                    and lane.plc_run < PLC_MAX_FRAMES
                ):
                    lane.plc_run += 1
                    try:
                        pcm = lane.dec.decode(None)  # loss concealment
                    except opus.OpusError:
                        continue
                else:
                    if now - lane.last_seen > 5.0:
                        lane.dec.close()
                        del rm.tracks[track]
                    continue
                if len(pcm) == opus.FRAME_SAMPLES:
                    pcm_by_track[track] = pcm.astype(np.int32)
            if not pcm_by_track:
                continue
            tracks = list(pcm_by_track)
            stack = np.stack([pcm_by_track[t] for t in tracks])  # [T, N]
            total = stack.sum(axis=0)
            self.stats["frames_mixed"] += 1
            for sub, lane in rm.subs.items():
                mix = total
                if lane.exclude_track in pcm_by_track:
                    mix = total - pcm_by_track[lane.exclude_track]
                out = np.clip(mix, -32768, 32767).astype(np.int16)
                if not out.any() and lane.exclude_track in pcm_by_track \
                        and len(tracks) == 1:
                    continue  # only their own voice was active
                try:
                    pkt = lane.enc.encode(out)
                except opus.OpusError:
                    continue
                self._emit(room, sub, lane, pkt)

    def _emit(self, room: int, sub: int, lane: _SubLane, payload: bytes) -> None:
        t = self.transport
        addr = t.sub_addrs.get((room, sub))
        if addr is None:
            return
        hdr = bytearray(12)
        hdr[0] = 0x80
        hdr[1] = OPUS_PT
        hdr[2:4] = (lane.sn & 0xFFFF).to_bytes(2, "big")
        hdr[4:8] = (lane.ts & 0xFFFFFFFF).to_bytes(4, "big")
        hdr[8:12] = lane.ssrc.to_bytes(4, "big")
        lane.sn += 1
        lane.ts += opus.FRAME_SAMPLES
        t._sendto(bytes(hdr) + payload, addr, t.sub_sessions.get((room, sub)))
        t.stats["tx"] += 1
        self.stats["packets_out"] += 1

    def debug_summary(self) -> dict:
        return {
            "rooms": len(self.rooms),
            "subs": sum(len(r.subs) for r in self.rooms.values()),
            "tracks": sum(len(r.tracks) for r in self.rooms.values()),
            **self.stats,
        }

    def close(self) -> None:
        for rm in self.rooms.values():
            for lane in rm.tracks.values():
                lane.dec.close()
            for lane in rm.subs.values():
                lane.enc.close()
        self.rooms.clear()
        self._room_arr = np.zeros(0, np.int64)
