"""Server-side audio mixing (the MCU seat) — BASELINE config 2.

Reference parity: the reference is SFU-only (pkg/sfu/audio/audiolevel.go
reads levels; it never decodes). This build's BASELINE commits to a
batched active-speaker mix, so the seat is real here: per-track Opus
decode (host, stateful — interop/opus.py over libopus), an [S, T] mix
(numpy at per-room scale; ops/mix.py's einsum kernel is the same math
batched on-device for the 1000-room shape, benchmarked in bench.py),
and per-subscriber Opus re-encode with self-exclusion (you never hear
yourself).

Egress rides the transport's `_sendto` chokepoint, so a mixed stream
reaches sealed, TCP-fallback, and SRTP-gateway subscribers through
their own lanes unchanged.

Opt-in: signal `subscription {"audio_mix": true}` (signalhandler) or
`AudioMixer.enable_sub` directly. Subscribers typically unsubscribe the
individual audio tracks at the same time — the mix replaces them.
"""

from __future__ import annotations

import functools
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from livekit_server_tpu.analysis.registry import device_entry
from livekit_server_tpu.interop import opus

__all__ = ["AudioMixer"]

OPUS_PT = 111
# A track with no packet for this long stops contributing (and stops
# burning PLC) until media resumes.
ACTIVE_TTL_S = 0.4
# Brief gaps inside an active stream are concealed by the decoder.
PLC_MAX_FRAMES = 10
# Rooms mixing this frame before the batched einsum path takes over from
# the per-room numpy sum. Below it, one device dispatch costs more than
# the host loop; at the 1000-room shape (bench audio_mix_1kroom) the
# einsum is the only tractable form.
DEVICE_MIX_MIN_ROOMS = 64


@device_entry("mixer.device_mix", builder=True)
@functools.lru_cache(maxsize=None)
def _device_mix(T: int, S: int, N: int):
    """Batched room mix, one einsum for every enabled room at once —
    the same "rst,rtn->rsn" contraction as ops/mix.mix_tick with the
    include weight reduced to presence & self-exclusion (the host path's
    sum-all-tracks policy, NOT the top-K speaker gate). int16 samples
    summed in float32 are exact below 2^24, so the result is bit-equal
    to the numpy int32 sum after rounding."""

    @jax.jit
    def mixf(pcm, present, exclude):
        # pcm [R,T,N] f32; present [R,T] bool; exclude [R,S] int32
        # (column index of the subscriber's own track, T = none).
        inc = present[:, None, :] & (
            jnp.arange(T, dtype=jnp.int32)[None, None, :]
            != exclude[:, :, None])
        return jnp.einsum("rst,rtn->rsn", inc.astype(jnp.float32), pcm)

    return mixf


def _p2(n: int) -> int:
    return 1 << max(n - 1, 1).bit_length()


class _TrackLane:
    def __init__(self):
        self.dec = opus.OpusDecoder()
        self.pending: deque = deque(maxlen=3)   # tiny jitter absorber
        self.last_seen = 0.0
        self.plc_run = 0


class _SubLane:
    def __init__(self, ssrc: int, bitrate: int, exclude_track: int):
        self.enc = opus.OpusEncoder(bitrate=bitrate)
        self.ssrc = ssrc
        self.sn = 0
        self.ts = 0
        self.exclude_track = exclude_track


class _RoomMix:
    def __init__(self):
        self.tracks: dict[int, _TrackLane] = {}
        self.subs: dict[int, _SubLane] = {}


class AudioMixer:
    """Per-node mixing state; owned by UDPMediaTransport
    (enable_audio_mixer)."""

    def __init__(self, transport, frame_ms: int = 20):
        if not opus.available():
            raise opus.OpusError("libopus not available on this host")
        self.transport = transport
        self.frame_s = frame_ms / 1000.0
        self.rooms: dict[int, _RoomMix] = {}
        self._room_arr = np.zeros(0, np.int64)
        self._next_at = 0.0
        self.device_mix_min_rooms = DEVICE_MIX_MIN_ROOMS
        self.stats = {"frames_mixed": 0, "packets_out": 0,
                      "decode_errors": 0, "device_mix_frames": 0}

    # -- control ----------------------------------------------------------

    def enable_sub(
        self, room: int, sub: int, enabled: bool = True,
        exclude_track: int = -1, bitrate: int = 32000,
    ) -> None:
        """Opt one subscriber into (or out of) the room's mixed stream.
        `exclude_track` is their own audio track column (self-exclusion)."""
        if enabled:
            rm = self.rooms.setdefault(room, _RoomMix())
            lane = rm.subs.get(sub)
            if lane is None:
                rm.subs[sub] = _SubLane(
                    self.transport._new_ssrc(), bitrate, exclude_track
                )
            else:
                lane.exclude_track = exclude_track
        else:
            rm = self.rooms.get(room)
            if rm is not None:
                rm.subs.pop(sub, None)
                if not rm.subs:
                    self.rooms.pop(room, None)
        self._room_arr = np.fromiter(self.rooms, np.int64, len(self.rooms))

    def set_publisher_track(self, room: int, sub_col: int, track: int) -> None:
        """An audio track was published by the participant holding
        `sub_col`: keep that subscriber's self-exclusion current even when
        the opt-in arrived before the publish (or across republishes)."""
        rm = self.rooms.get(room)
        if rm is not None and sub_col in rm.subs:
            rm.subs[sub_col].exclude_track = track

    def release_track(self, room: int, track: int) -> None:
        """Track column freed: its decoder state and queued payloads must
        not leak to the column's next tenant, and stale self-exclusions
        must not mute the next publisher for unrelated subscribers."""
        rm = self.rooms.get(room)
        if rm is None:
            return
        lane = rm.tracks.pop(track, None)
        if lane is not None:
            lane.dec.close()
        for sub_lane in rm.subs.values():
            if sub_lane.exclude_track == track:
                sub_lane.exclude_track = -1

    def release_room(self, room: int) -> None:
        rm = self.rooms.pop(room, None)
        if rm is not None:
            for lane in rm.tracks.values():
                lane.dec.close()
            for lane in rm.subs.values():
                lane.enc.close()
        self._room_arr = np.fromiter(self.rooms, np.int64, len(self.rooms))

    def room_mask(self, rooms: np.ndarray) -> np.ndarray:
        """Vector mask: which entries belong to mix-enabled rooms."""
        return np.isin(rooms, self._room_arr)

    # -- ingest tap (udp._process_media_arrays, audio in enabled rooms) ---

    def push(self, room: int, track: int, ts: int, payload: bytes) -> None:
        rm = self.rooms.get(room)
        if rm is None or not payload:
            return
        lane = rm.tracks.get(track)
        if lane is None:
            try:
                lane = rm.tracks[track] = _TrackLane()
            except opus.OpusError:
                return
        lane.pending.append(payload)
        lane.last_seen = time.monotonic()

    # -- frame clock ------------------------------------------------------

    def maybe_tick(self, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        if now < self._next_at:
            return
        # Accumulate from the PREVIOUS deadline (with a one-frame catch-up
        # clamp): rescheduling from `now` would add the caller's lateness
        # to every period, running the frame clock slower than real time
        # and overflowing the per-track jitter queues.
        self._next_at = max(self._next_at + self.frame_s, now - self.frame_s)
        self.tick(now)

    def tick(self, now: float | None = None) -> None:
        """Mix + emit one 20 ms frame for every enabled room.

        Decode is always host-side (Opus is stateful C); the mix itself
        runs per room in numpy until DEVICE_MIX_MIN_ROOMS rooms are
        active in the same frame, then switches to one batched einsum
        over every room at once (_device_mix) — the only form that holds
        the 20 ms deadline at the 1000-room shape. Both paths produce
        identical int16 frames."""
        now = time.monotonic() if now is None else now
        staged: list[tuple[int, _RoomMix, dict[int, np.ndarray]]] = []
        for room, rm in list(self.rooms.items()):
            pcm_by_track: dict[int, np.ndarray] = {}
            for track, lane in list(rm.tracks.items()):
                if lane.pending:
                    lane.plc_run = 0
                    try:
                        pcm = lane.dec.decode(lane.pending.popleft())
                    except opus.OpusError:
                        self.stats["decode_errors"] += 1
                        continue
                elif (
                    now - lane.last_seen < ACTIVE_TTL_S
                    and lane.plc_run < PLC_MAX_FRAMES
                ):
                    lane.plc_run += 1
                    try:
                        pcm = lane.dec.decode(None)  # loss concealment
                    except opus.OpusError:
                        continue
                else:
                    if now - lane.last_seen > 5.0:
                        lane.dec.close()
                        del rm.tracks[track]
                    continue
                if len(pcm) == opus.FRAME_SAMPLES:
                    pcm_by_track[track] = pcm.astype(np.int32)
            if not pcm_by_track:
                continue
            self.stats["frames_mixed"] += 1
            staged.append((room, rm, pcm_by_track))
        if len(staged) >= self.device_mix_min_rooms:
            self._mix_device(staged)
        else:
            for room, rm, pcm_by_track in staged:
                self._mix_host(room, rm, pcm_by_track)

    def _mix_host(
        self, room: int, rm: _RoomMix, pcm_by_track: dict[int, np.ndarray]
    ) -> None:
        tracks = list(pcm_by_track)
        stack = np.stack([pcm_by_track[t] for t in tracks])  # [T, N]
        total = stack.sum(axis=0)
        for sub, lane in rm.subs.items():
            mix = total
            if lane.exclude_track in pcm_by_track:
                mix = total - pcm_by_track[lane.exclude_track]
            out = np.clip(mix, -32768, 32767).astype(np.int16)
            self._encode_emit(room, sub, lane, out, pcm_by_track)

    def _mix_device(
        self, staged: list[tuple[int, _RoomMix, dict[int, np.ndarray]]]
    ) -> None:
        # Pad the frame's rooms into one [R, T, N] slab (pow2 track/sub
        # buckets keep the jit cache small across churn) and contract
        # once; emit walks the real subscribers only.
        N = opus.FRAME_SAMPLES
        Tm = _p2(max(len(p) for _, _, p in staged))
        Sm = _p2(max(1, max(len(rm.subs) for _, rm, _ in staged)))
        R = len(staged)
        pcm = np.zeros((R, Tm, N), np.float32)
        present = np.zeros((R, Tm), bool)
        exclude = np.full((R, Sm), Tm, np.int32)
        cols: list[dict[int, int]] = []
        for i, (_room, rm, ptk) in enumerate(staged):
            col = {t: j for j, t in enumerate(ptk)}
            cols.append(col)
            for t, j in col.items():
                pcm[i, j] = ptk[t]
                present[i, j] = True
            for s, lane in enumerate(rm.subs.values()):
                exclude[i, s] = col.get(lane.exclude_track, Tm)
        out = np.asarray(_device_mix(Tm, Sm, N)(
            jnp.asarray(pcm), jnp.asarray(present), jnp.asarray(exclude)))
        self.stats["device_mix_frames"] += 1
        for i, (room, rm, ptk) in enumerate(staged):
            for s, (sub, lane) in enumerate(rm.subs.items()):
                mixed = np.clip(
                    np.rint(out[i, s]), -32768, 32767).astype(np.int16)
                self._encode_emit(room, sub, lane, mixed, ptk)

    def _encode_emit(
        self, room: int, sub: int, lane: _SubLane,
        out: np.ndarray, pcm_by_track: dict[int, np.ndarray],
    ) -> None:
        if not out.any() and lane.exclude_track in pcm_by_track \
                and len(pcm_by_track) == 1:
            return  # only their own voice was active
        try:
            pkt = lane.enc.encode(out)
        except opus.OpusError:
            return
        self._emit(room, sub, lane, pkt)

    def _emit(self, room: int, sub: int, lane: _SubLane, payload: bytes) -> None:
        t = self.transport
        addr = t.sub_addrs.get((room, sub))
        if addr is None:
            return
        hdr = bytearray(12)
        hdr[0] = 0x80
        hdr[1] = OPUS_PT
        hdr[2:4] = (lane.sn & 0xFFFF).to_bytes(2, "big")
        hdr[4:8] = (lane.ts & 0xFFFFFFFF).to_bytes(4, "big")
        hdr[8:12] = lane.ssrc.to_bytes(4, "big")
        lane.sn += 1
        lane.ts += opus.FRAME_SAMPLES
        t._sendto(bytes(hdr) + payload, addr, t.sub_sessions.get((room, sub)))
        t.stats["tx"] += 1
        self.stats["packets_out"] += 1

    def debug_summary(self) -> dict:
        return {
            "rooms": len(self.rooms),
            "subs": sum(len(r.subs) for r in self.rooms.values()),
            "tracks": sum(len(r.tracks) for r in self.rooms.values()),
            **self.stats,
        }

    def close(self) -> None:
        for rm in self.rooms.values():
            for lane in rm.tracks.values():
                lane.dec.close()
            for lane in rm.subs.values():
                lane.enc.close()
        self.rooms.clear()
        self._room_arr = np.zeros(0, np.int64)
