"""PlaneSupervisor: tick watchdog + restart-from-snapshot for the media plane.

The reference SFU survives a wedged loop because every goroutine is
independently restartable; this runtime concentrates the whole node in
one jitted call per tick, so a single hung device dispatch takes every
room down. The supervisor restores the reference's failure story at the
plane level:

  - tick watchdog — samples the runtime's tick counter; no progress for
    `tick_deadline_s` while the loop is supposed to be running means the
    plane is stalled (hung XLA dispatch, wedged worker thread, runaway
    callback)
  - bounded restart-from-snapshot — on stall (or a crashed serving loop)
    the task is cancelled, the possibly-wedged executor thread is
    ABANDONED (a fresh single-worker executor takes over; the run-epoch
    guard in PlaneRuntime._device_step keeps a late-completing stale
    step from overwriting restored state), device+munger state is
    restored from the last periodic snapshot, and the loop starts again
    — with exponential backoff between attempts and a hard cap, after
    which the supervisor gives up loudly rather than flap forever
  - periodic checkpoints — a full-plane snapshot on a cadence (the
    restart seed), plus an optional per-room checkpoint callback the
    RoomManager uses to publish room rows to the KV bus (the failover
    seed surviving nodes restore from; see service/roommanager.py)

Restart rewinds at most one checkpoint interval of munger advance:
packets forwarded after the snapshot are re-issued with the same SNs
(duplicates, which receivers tolerate), never skipped.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Awaitable, Callable

from livekit_server_tpu.utils.backoff import BackoffPolicy
from livekit_server_tpu.utils.logger import Logger


class PlaneSupervisor:
    def __init__(
        self,
        runtime,
        *,
        tick_deadline_s: float = 1.0,
        warmup_deadline_s: float = 30.0,
        check_interval_s: float = 0.1,
        checkpoint_interval_s: float = 2.0,
        max_restarts: int = 5,
        overload_grace: float = 5.0,
        backoff: BackoffPolicy | None = None,
        telemetry=None,
        log: Logger | None = None,
    ):
        self.runtime = runtime
        self.tick_deadline_s = tick_deadline_s
        self.warmup_deadline_s = max(warmup_deadline_s, tick_deadline_s)
        self.check_interval_s = check_interval_s
        self.checkpoint_interval_s = checkpoint_interval_s
        self.max_restarts = max_restarts
        # Stall-deadline multiplier while the overload governor is
        # engaged: a governed plane is slow BECAUSE it is shedding load,
        # and a restart both loses the shed state and re-offers the full
        # load to a cold plane — the restart-storm failure mode. Genuine
        # no-progress still restarts once the widened deadline passes.
        self.overload_grace = max(1.0, overload_grace)
        self.backoff = backoff or BackoffPolicy(base=0.1, max_delay=5.0)
        self.telemetry = telemetry
        self.log = log or Logger()
        # Awaited on the checkpoint cadence; RoomManager points this at
        # its per-room bus publisher.
        self.room_checkpoint_cb: Callable[[], Awaitable[None]] | None = None
        self.last_snapshot: dict[str, Any] | None = None
        self.restarts = 0            # lifetime restart count (telemetry)
        self.gave_up = False
        self._attempts = 0           # consecutive restarts without health
        self._watch_task: asyncio.Task | None = None
        self._ckpt_task: asyncio.Task | None = None
        self._ticks_seen = -1
        self._progress_at = 0.0
        self._baseline_ticks = -1

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        if self._watch_task is None:
            self._progress_at = time.monotonic()
            self._baseline_ticks = self.runtime.stats.get("ticks", 0)
            self._watch_task = asyncio.ensure_future(self._watchdog())
        if self._ckpt_task is None:
            self._ckpt_task = asyncio.ensure_future(self._checkpointer())

    async def stop(self) -> None:
        for attr in ("_watch_task", "_ckpt_task"):
            task = getattr(self, attr)
            if task is not None:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
                setattr(self, attr, None)

    # -- checkpoint cadence ----------------------------------------------
    async def checkpoint_now(self) -> None:
        """One full-plane snapshot (the restart seed), then the per-room
        callback. Taken under state_lock so the donated device step never
        has the arrays mid-flight."""
        async with self.runtime.state_lock:
            self.last_snapshot = self.runtime.snapshot()
        if self.room_checkpoint_cb is not None:
            await self.room_checkpoint_cb()

    async def _checkpointer(self) -> None:
        while True:
            await asyncio.sleep(self.checkpoint_interval_s)
            try:
                await self.checkpoint_now()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — a failed checkpoint
                # (bus outage mid-publish) must not kill the cadence; the
                # next interval retries with fresher state anyway.
                self.log.warn("plane checkpoint failed", error=str(e))

    # -- watchdog ---------------------------------------------------------
    def _stalled(self, now: float) -> str:
        """Non-empty reason string when the plane needs a restart."""
        task = self.runtime._task
        if task is None:
            return ""  # not started (or stopped on purpose): nothing to guard
        if task.done():
            if task.cancelled():
                return ""  # deliberate stop between our samples
            exc = task.exception()
            return f"serving loop died: {exc!r}" if exc else "serving loop exited"
        ticks = self.runtime.stats.get("ticks", 0)
        if ticks != self._ticks_seen:
            self._ticks_seen = ticks
            self._progress_at = now
            if self._attempts:
                self.log.info("plane healthy after restart", restarts=self.restarts)
            self._attempts = 0  # healthy: future failures start a fresh budget
            return ""
        # The first tick after a (re)start may legitimately block for many
        # seconds in a cold XLA compile; restarting mid-compile loses the
        # in-flight tick's packets AND abandons a worker thread that can
        # die mid-cache-write at process exit (truncated persistent-cache
        # entries load as silently-miscompiled executables later). Hold
        # the relaxed warmup deadline until the first tick completes.
        deadline = (
            self.tick_deadline_s
            if ticks > self._baseline_ticks
            else self.warmup_deadline_s
        )
        # "Overloaded but making progress" is the governor's job, not
        # ours: while it is engaged (level > 0) widen the stall deadline
        # so load-induced lateness cannot trigger a restart storm. A
        # truly wedged plane still trips the widened deadline.
        gov = getattr(self.runtime, "governor", None)
        if gov is not None and gov.level > 0 and ticks > self._baseline_ticks:
            deadline = max(deadline, self.tick_deadline_s * self.overload_grace)
        if now - self._progress_at > deadline:
            return f"tick watchdog: no progress in {now - self._progress_at:.2f}s"
        return ""

    async def _watchdog(self) -> None:
        while True:
            await asyncio.sleep(self.check_interval_s)
            reason = self._stalled(time.monotonic())
            if not reason:
                continue
            if self._attempts >= self.max_restarts:
                self.gave_up = True
                self.log.error(
                    "plane restart budget exhausted; supervisor giving up",
                    attempts=self._attempts, reason=reason,
                )
                return
            await self._restart(reason)

    async def _restart(self, reason: str) -> None:
        from concurrent.futures import ThreadPoolExecutor

        rt = self.runtime
        attempt = self._attempts
        self._attempts += 1
        self.log.warn("restarting media plane", reason=reason,
                      attempt=self._attempts, cap=self.max_restarts)
        # Invalidate any in-flight device step FIRST: a stale step
        # completing on the abandoned thread must not commit its state
        # over the restore below.
        rt.run_epoch += 1
        await rt.stop()
        # The old worker thread may be wedged inside the device call
        # forever; hand the runtime a fresh executor and let the stale
        # thread die with its daemon flag.
        old = rt._executor
        rt._executor = ThreadPoolExecutor(max_workers=1, thread_name_prefix="plane")
        old.shutdown(wait=False)
        if self.last_snapshot is not None:
            async with rt.state_lock:
                rt.restore(self.last_snapshot)
        await asyncio.sleep(self.backoff.delay(attempt))
        self._ticks_seen = rt.stats.get("ticks", 0)
        self._baseline_ticks = self._ticks_seen
        self._progress_at = time.monotonic()
        rt.start()
        self.restarts += 1
        if self.telemetry is not None:
            self.telemetry.add("livekit_plane_restarts_total")
