"""PlaneSupervisor: tick watchdog + restart-from-snapshot for the media plane.

The reference SFU survives a wedged loop because every goroutine is
independently restartable; this runtime concentrates the whole node in
one jitted call per tick, so a single hung device dispatch takes every
room down. The supervisor restores the reference's failure story at the
plane level:

  - tick watchdog — samples the runtime's tick counter; no progress for
    `tick_deadline_s` while the loop is supposed to be running means the
    plane is stalled (hung XLA dispatch, wedged worker thread, runaway
    callback)
  - bounded restart-from-snapshot — on stall (or a crashed serving loop)
    the task is cancelled, the possibly-wedged executor thread is
    ABANDONED (a fresh single-worker executor takes over; the run-epoch
    guard in PlaneRuntime._device_step keeps a late-completing stale
    step from overwriting restored state), device+munger state is
    restored from the last periodic snapshot, and the loop starts again
    — with exponential backoff between attempts and a hard cap, after
    which the supervisor gives up loudly rather than flap forever
  - periodic checkpoints — a full-plane snapshot on a cadence (the
    restart seed), plus an optional per-room checkpoint callback the
    RoomManager uses to publish room rows to the KV bus (the failover
    seed surviving nodes restore from; see service/roommanager.py).
    Checkpoints are kept as K encoded GENERATIONS, each wrapped in the
    utils/checksum frame; restore walks newest→oldest and falls back a
    generation (counter + warn) on a corrupt or shape-mismatched frame
    instead of committing garbage into donated device state.
  - restart-cause taxonomy — `stall` (watchdog) vs `integrity`
    (requested by the IntegrityMonitor's escalation ladder via
    request_restart), with separate counters.

Restart rewinds at most one checkpoint interval of munger advance:
packets forwarded after the snapshot are re-issued with the same SNs
(duplicates, which receivers tolerate), never skipped.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Any, Awaitable, Callable

from livekit_server_tpu.utils.backoff import BackoffPolicy
from livekit_server_tpu.utils.logger import Logger


class PlaneSupervisor:
    def __init__(
        self,
        runtime,
        *,
        tick_deadline_s: float = 1.0,
        warmup_deadline_s: float = 30.0,
        check_interval_s: float = 0.1,
        checkpoint_interval_s: float = 2.0,
        max_restarts: int = 5,
        overload_grace: float = 5.0,
        ckpt_generations: int = 3,
        backoff: BackoffPolicy | None = None,
        telemetry=None,
        log: Logger | None = None,
    ):
        self.runtime = runtime
        self.tick_deadline_s = tick_deadline_s
        self.warmup_deadline_s = max(warmup_deadline_s, tick_deadline_s)
        self.check_interval_s = check_interval_s
        self.checkpoint_interval_s = checkpoint_interval_s
        self.max_restarts = max_restarts
        # Stall-deadline multiplier while the overload governor is
        # engaged: a governed plane is slow BECAUSE it is shedding load,
        # and a restart both loses the shed state and re-offers the full
        # load to a cold plane — the restart-storm failure mode. Genuine
        # no-progress still restarts once the widened deadline passes.
        self.overload_grace = max(1.0, overload_grace)
        self.backoff = backoff or BackoffPolicy(base=0.1, max_delay=5.0)
        self.telemetry = telemetry
        self.log = log or Logger()
        # Awaited on the checkpoint cadence; RoomManager points this at
        # its per-room bus publisher.
        self.room_checkpoint_cb: Callable[[], Awaitable[None]] | None = None
        self.last_snapshot: dict[str, Any] | None = None
        # Encoded (checksummed) checkpoint generations, newest first.
        # Restore verifies each frame and falls back a generation on
        # corruption; the corrupt_ckpt fault writes damage HERE, so the
        # in-memory last_snapshot above is kept only as a same-process
        # compatibility convenience and is NOT the restart seed.
        self._gens: deque = deque(maxlen=max(1, int(ckpt_generations)))
        self.ckpt_fallbacks = 0      # generations skipped as corrupt/invalid
        self.restarts = 0            # lifetime restart count (telemetry)
        self.restart_causes: dict[str, int] = {"stall": 0, "integrity": 0}
        self.gave_up = False
        # Node drain (service/migration.py): a draining plane quiesces on
        # purpose — rooms migrate away and tick progress may legitimately
        # stop. The watchdog must not read that as a stall and "restore"
        # rooms the drain just handed off.
        self.draining = False
        # Self-fenced (service/fleetplane.py quorum loss): restarts are
        # quiesced the same way — a restart would restore rooms from KV
        # checkpoints that may already belong to the takeover winner.
        self.fenced = False
        self._attempts = 0           # consecutive restarts without health
        self._requested_restart = "" # set by request_restart(), watchdog-consumed
        self._watch_task: asyncio.Task | None = None
        self._ckpt_task: asyncio.Task | None = None
        self._ticks_seen = -1
        self._progress_at = 0.0
        self._baseline_ticks = -1

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        if self._watch_task is None:
            self._progress_at = time.monotonic()
            self._baseline_ticks = self.runtime.stats.get("ticks", 0)
            self._watch_task = asyncio.ensure_future(self._watchdog())
        if self._ckpt_task is None:
            self._ckpt_task = asyncio.ensure_future(self._checkpointer())

    async def stop(self) -> None:
        for attr in ("_watch_task", "_ckpt_task"):
            task = getattr(self, attr)
            if task is not None:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
                setattr(self, attr, None)

    # -- checkpoint cadence ----------------------------------------------
    async def checkpoint_now(self) -> None:
        """One full-plane snapshot (the restart seed), then the per-room
        callback. Taken under state_lock so the donated device step never
        has the arrays mid-flight. The snapshot is encoded + checksummed
        into the generation ring; the corrupt_ckpt fault seam damages the
        encoded bytes here, exactly where real bit rot would land."""
        async with self.runtime.state_lock:
            self.last_snapshot = self.runtime.snapshot()
        blob = self.runtime.encode_snapshot(self.last_snapshot)
        fault = getattr(self.runtime, "fault", None)
        if fault is not None:
            blob = fault.corrupt_ckpt(blob)
        self._gens.appendleft(blob)
        if self.room_checkpoint_cb is not None:
            await self.room_checkpoint_cb()

    def last_good_snapshot(self) -> dict[str, Any] | None:
        """Newest checkpoint generation that verifies, decoded — the
        IntegrityMonitor's row-repair source. Corrupt generations are
        skipped with a counter + warn."""
        for i, blob in enumerate(self._gens):
            try:
                return self.runtime.decode_snapshot(blob)
            except (ValueError, KeyError, OSError) as e:  # ChecksumError ⊂ ValueError
                self.ckpt_fallbacks += 1
                self.log.warn(
                    "checkpoint generation corrupt; falling back",
                    generation=i, error=str(e),
                )
        return None

    async def _checkpointer(self) -> None:
        while True:
            await asyncio.sleep(self.checkpoint_interval_s)
            try:
                await self.checkpoint_now()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — a failed checkpoint
                # (bus outage mid-publish) must not kill the cadence; the
                # next interval retries with fresher state anyway.
                self.log.warn("plane checkpoint failed", error=str(e))

    # -- requested restarts (integrity escalation) -------------------------
    def request_restart(self, reason: str) -> None:
        """Ask for a full restart-from-snapshot (cause `integrity`).
        Thread-safe: the IntegrityMonitor calls this from the device-step
        worker; the watchdog poll consumes the flag on the event loop, so
        requested restarts serialize with stall restarts."""
        if not self._requested_restart:
            self._requested_restart = reason

    # -- watchdog ---------------------------------------------------------
    def _stalled(self, now: float) -> str:
        """Non-empty reason string when the plane needs a restart."""
        task = self.runtime._task
        if task is None:
            return ""  # not started (or stopped on purpose): nothing to guard
        if task.done():
            if task.cancelled():
                return ""  # deliberate stop between our samples
            exc = task.exception()
            return f"serving loop died: {exc!r}" if exc else "serving loop exited"
        ticks = self.runtime.stats.get("ticks", 0)
        if ticks != self._ticks_seen:
            self._ticks_seen = ticks
            self._progress_at = now
            if self._attempts:
                self.log.info("plane healthy after restart", restarts=self.restarts)
            self._attempts = 0  # healthy: future failures start a fresh budget
            return ""
        # The first tick after a (re)start may legitimately block for many
        # seconds in a cold XLA compile; restarting mid-compile loses the
        # in-flight tick's packets AND abandons a worker thread that can
        # die mid-cache-write at process exit (truncated persistent-cache
        # entries load as silently-miscompiled executables later). Hold
        # the relaxed warmup deadline until the first tick completes.
        deadline = (
            self.tick_deadline_s
            if ticks > self._baseline_ticks
            else self.warmup_deadline_s
        )
        # "Overloaded but making progress" is the governor's job, not
        # ours: while it is engaged (level > 0) widen the stall deadline
        # so load-induced lateness cannot trigger a restart storm. A
        # truly wedged plane still trips the widened deadline.
        gov = getattr(self.runtime, "governor", None)
        if gov is not None and gov.level > 0 and ticks > self._baseline_ticks:
            deadline = max(deadline, self.tick_deadline_s * self.overload_grace)
        if now - self._progress_at > deadline:
            return f"tick watchdog: no progress in {now - self._progress_at:.2f}s"
        return ""

    async def _watchdog(self) -> None:
        while True:
            await asyncio.sleep(self.check_interval_s)
            if self.draining or self.fenced:
                # Quiescing on purpose: never restart a drain or a
                # fenced minority that must stay silent.
                continue
            cause = "stall"
            reason = self._requested_restart
            if reason:
                self._requested_restart = ""
                cause = "integrity"
            else:
                reason = self._stalled(time.monotonic())
            if not reason:
                continue
            if self._attempts >= self.max_restarts:
                self.gave_up = True
                self.log.error(
                    "plane restart budget exhausted; supervisor giving up",
                    attempts=self._attempts, reason=reason,
                )
                return
            await self._restart(reason, cause=cause)

    async def _restart(self, reason: str, cause: str = "stall") -> None:
        from concurrent.futures import ThreadPoolExecutor

        rt = self.runtime
        attempt = self._attempts
        self._attempts += 1
        self.log.warn("restarting media plane", reason=reason, cause=cause,
                      attempt=self._attempts, cap=self.max_restarts)
        # Invalidate any in-flight device step FIRST: a stale step
        # completing on the abandoned thread must not commit its state
        # over the restore below.
        rt.run_epoch += 1
        await rt.stop()
        # The old worker thread may be wedged inside the device call
        # forever; hand the runtime a fresh executor and let the stale
        # thread die with its daemon flag.
        old = rt._executor
        rt._executor = ThreadPoolExecutor(max_workers=1, thread_name_prefix="plane")
        old.shutdown(wait=False)
        await self._restore_from_checkpoint()
        await asyncio.sleep(self.backoff.delay(attempt))
        self._ticks_seen = rt.stats.get("ticks", 0)
        self._baseline_ticks = self._ticks_seen
        self._progress_at = time.monotonic()
        rt.start()
        self.restarts += 1
        self.restart_causes[cause] = self.restart_causes.get(cause, 0) + 1
        bb = getattr(rt, "blackbox", None)
        if bb is not None:
            from livekit_server_tpu.runtime.trace import EV_RESTART

            bb.emit(bb.NODE, EV_RESTART, float(self._attempts))
            bb.dump_to(bb.NODE, f"plane_restart:{cause}")
        if self.telemetry is not None:
            self.telemetry.add("livekit_plane_restarts_total")
            self.telemetry.add(
                "livekit_plane_restarts_by_cause_total", cause=cause
            )

    async def _restore_from_checkpoint(self) -> bool:
        """Restore the plane from the newest checkpoint generation that
        both VERIFIES (checksum) and VALIDATES (leaf shapes/dtypes vs the
        live plane). Each rejected generation counts a fallback. With no
        usable generation (fresh supervisor, or all corrupt) the plane
        restarts on its current state — the pre-checkpoint behavior."""
        rt = self.runtime
        for i, blob in enumerate(list(self._gens)):
            try:
                snap = rt.decode_snapshot(blob)
                async with rt.state_lock:
                    rt.restore(snap)
                return True
            except (ValueError, KeyError, OSError) as e:
                self.ckpt_fallbacks += 1
                self.log.warn(
                    "checkpoint generation rejected at restore; falling back",
                    generation=i, error=str(e),
                )
        if self.last_snapshot is not None:
            # Same-process fallback: the raw dict snapshot (cannot have
            # bit-rotted — it never left memory unencoded).
            async with rt.state_lock:
                rt.restore(self.last_snapshot)
            return True
        return False
