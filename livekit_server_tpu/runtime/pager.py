"""RoomPager: pooled-HBM page allocation for ragged room state.

The dense plane charges every room the configured worst case — a
2-person room pays the same [T, K, S] HBM slab and kernel work as the
50-sub north star, which is exactly ROADMAP open item 4. This module is
the host half of the paged layout that fixes it (the device half is
models/paged.py): one pooled buffer of P fixed-shape PAGES, each
covering a (tpage × spage) block of one room's (track, subscriber)
plane, and a device-resident page table the tick kernels indirect
through. The layout borrows the pooled-page discipline of ragged paged
attention (PAPERS.md): fixed-size pages in one big buffer + an indirection
table beats per-room allocations because the kernels stay static-shaped
and the allocator is O(1) per event.

A room's footprint is a PAGE GRID: ceil(tracks / tpage) × ceil(subs /
spage) pages, so a 2-person room holds one page while the 50-sub room
holds its full grid — rooms/chip scales with the *actual* size
distribution instead of the padded worst case. Page (room, tp, sp)
covers logical tracks [tp·TP, (tp+1)·TP) × subs [sp·SP, (sp+1)·SP), in
order — the logical→page translation is pure index arithmetic, which
keeps checkpoints layout-independent (they serialize LOGICAL rows).

Allocation is a buddy allocator over page indices: free lists per pow2
size class, each grid request rounded up to a pow2 run (the slack is
reported as internal fragmentation), splits on alloc, buddy-coalesce on
free. `compact()` relocates every live run to the bottom of the pool —
the host side of defragmentation; the runtime turns the returned moves
into device row copies plus a page-table delta.

Concurrency/staleness contract: every structural change bumps `epoch`.
A page index is only valid under the epoch it was read at — any code
that holds one across an await or lock release must re-validate with
`check_epoch` (or re-fetch through `pages_of_room`) before using it to
index device state; graftcheck GC08 enforces exactly this discipline.

This module is deliberately jax-free: pure host bookkeeping (numpy
tables only), so allocator tests run anywhere and the device-facing
arrays are plain buffers for the runtime's delta uploads.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from livekit_server_tpu.runtime.slots import CapacityError


class StalePageError(RuntimeError):
    """A page index minted under an older pager epoch was used after the
    table changed (GC08: re-validate across awaits/lock releases)."""


class RoomExtent(NamedTuple):
    """A room's currently-allocated logical coverage (page-granular)."""

    tracks: int
    subs: int


class PageDelta(NamedTuple):
    """One drain of pending page-table events for the device upload lane
    (the page analog of the dirty-row ctrl delta)."""

    rooms: np.ndarray        # [n] int32 — rooms whose table row changed
    fresh_pages: np.ndarray  # [m] int32 — newly mapped pages (state init)
    freed_pages: np.ndarray  # [f] int32 — unmapped pages (state re-init)
    moves: np.ndarray        # [k, 2] int32 — compaction (src, dst) rows

    @property
    def empty(self) -> bool:
        return (
            len(self.rooms) == 0
            and len(self.fresh_pages) == 0
            and len(self.freed_pages) == 0
            and len(self.moves) == 0
        )


class _Room:
    __slots__ = ("grid", "mt", "ms", "runs")

    def __init__(self, max_tp: int, max_sp: int):
        self.grid = np.full((max_tp, max_sp), -1, np.int32)
        self.mt = 0
        self.ms = 0
        self.runs: list[tuple[int, int]] = []  # (start, order)


def _is_pow2(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


class RoomPager:
    """Host-side page-pool allocator + the canonical page-table mirrors.

    The numpy tables here (`pg_room`/`pg_tp`/`pg_sp`, `tmembers`,
    `rooms_pages`) are the authoritative page table; the runtime uploads
    dirty slices to their device copies at tick edges via drain_delta —
    the same mirror-then-delta protocol as the ctrl tensors.
    """

    def __init__(
        self,
        rooms: int,
        tracks: int,
        subs: int,
        *,
        tpage: int,
        spage: int,
        pool_pages: int,
    ):
        if not _is_pow2(tpage) or tracks % tpage:
            raise ValueError(
                f"tpage must be a pow2 divisor of tracks ({tpage} vs {tracks})"
            )
        if not _is_pow2(spage) or subs % spage:
            raise ValueError(
                f"spage must be a pow2 divisor of subs ({spage} vs {subs})"
            )
        if spage > 32 or 32 % spage:
            raise ValueError(
                f"spage must divide the 32-bit mask word (got {spage})"
            )
        if not _is_pow2(pool_pages):
            raise ValueError(f"pool_pages must be pow2 (got {pool_pages})")
        self.num_rooms = rooms
        self.tracks = tracks
        self.subs = subs
        self.tpage = tpage
        self.spage = spage
        self.pool_pages = pool_pages
        self.max_tpages = tracks // tpage
        self.max_spages = subs // spage
        self.min_room_pages = 1  # a minimal room is one (tpage × spage) page

        # Device-table host mirrors. tmembers[p] lists the page ids of
        # p's room sharing p's sub column across track pages — the only
        # cross-page coupling the device tick gathers through (per-sub
        # send sums + the cross-track allocation).
        self.pg_room = np.full(pool_pages, -1, np.int32)
        self.pg_tp = np.full(pool_pages, -1, np.int32)
        self.pg_sp = np.full(pool_pages, -1, np.int32)
        self.tmembers = np.full((pool_pages, self.max_tpages), -1, np.int32)
        self.rooms_pages = np.full(
            (rooms, self.max_tpages * self.max_spages), -1, np.int32
        )

        # Buddy free lists: order → set of aligned run starts.
        self._max_order = pool_pages.bit_length() - 1
        self._free: dict[int, set[int]] = {self._max_order: {0}}
        self._rooms: dict[int, _Room] = {}

        self.epoch = 0
        self._dirty_rooms: set[int] = set()
        self._fresh: set[int] = set()
        self._freed: set[int] = set()
        self._moves: list[tuple[int, int]] = []

        self.allocs = 0
        self.frees = 0
        self.grows = 0
        self.compactions = 0
        self.alloc_failures = 0
        self.peak_reserved = 0

    # -- buddy core -------------------------------------------------------

    def _alloc_run(self, order: int) -> int:
        for o in range(order, self._max_order + 1):
            runs = self._free.get(o)
            if runs:
                start = min(runs)  # lowest address: deterministic + compact
                runs.remove(start)
                while o > order:
                    o -= 1
                    self._free.setdefault(o, set()).add(start + (1 << o))
                return start
        self.alloc_failures += 1
        raise CapacityError(
            f"page pool exhausted: no free run of {1 << order} pages "
            f"({self.pages_free} pages free but fragmented)"
            if self.pages_free >= (1 << order)
            else f"page pool exhausted: need {1 << order} pages, "
            f"{self.pages_free} free"
        )

    def _free_run(self, start: int, order: int) -> None:
        while order < self._max_order:
            buddy = start ^ (1 << order)
            peers = self._free.get(order)
            if peers and buddy in peers:
                peers.remove(buddy)
                start = min(start, buddy)
                order += 1
            else:
                break
        self._free.setdefault(order, set()).add(start)

    @staticmethod
    def _order_for(n_pages: int) -> int:
        return max(0, (n_pages - 1).bit_length())

    # -- room lifecycle ---------------------------------------------------

    def _map_cells(self, row: int, room: _Room, cells: list[tuple[int, int]]) -> None:
        """Allocate one pow2 run covering `cells` grid slots and map them."""
        order = self._order_for(len(cells))
        start = self._alloc_run(order)
        room.runs.append((start, order))
        for i, (ti, si) in enumerate(cells):
            p = start + i
            room.grid[ti, si] = p
            self.pg_room[p] = row
            self.pg_tp[p] = ti
            self.pg_sp[p] = si
            self._fresh.add(p)
            self._freed.discard(p)

    def _refresh_tables(self, row: int) -> None:
        """Recompute the room's page-table mirrors after a grid change.
        tmembers of EVERY page in the room can change when mt grows (a
        new track page joins each sub column), so the whole room's pages
        refresh — still O(room pages), never O(pool)."""
        room = self._rooms[row]
        self.rooms_pages[row] = room.grid.reshape(-1)
        pages = room.grid[room.grid >= 0]
        col = np.full(self.max_tpages, -1, np.int32)
        for p in pages:
            col[: room.mt] = room.grid[: room.mt, self.pg_sp[p]]
            col[room.mt:] = -1
            self.tmembers[p] = col
        self._dirty_rooms.add(row)
        self.epoch += 1

    def alloc_room(self, row: int, tracks: int = 1, subs: int = 1) -> RoomExtent:
        """Claim a page grid covering at least (tracks, subs); a minimal
        room is one page. Raises CapacityError on pool exhaustion (the
        admission-denial surface) and leaves no partial allocation."""
        if row in self._rooms:
            return self.extent(row)
        if not (0 <= row < self.num_rooms):
            raise ValueError(f"room row {row} out of range")
        mt = max(1, -(-tracks // self.tpage))
        ms = max(1, -(-subs // self.spage))
        if mt > self.max_tpages or ms > self.max_spages:
            raise CapacityError(
                f"room exceeds max extent: {tracks}t/{subs}s vs "
                f"{self.tracks}t/{self.subs}s"
            )
        room = _Room(self.max_tpages, self.max_spages)
        cells = [(ti, si) for ti in range(mt) for si in range(ms)]
        try:
            self._map_cells(row, room, cells)
        except CapacityError:
            self._rollback(room)
            raise
        room.mt, room.ms = mt, ms
        self._rooms[row] = room
        self.allocs += 1
        self.peak_reserved = max(self.peak_reserved, self.pages_reserved)
        self._refresh_tables(row)
        return self.extent(row)

    def grow_room(
        self, row: int, tracks: int | None = None, subs: int | None = None
    ) -> RoomExtent:
        """Widen a room's grid to cover (tracks, subs) — the grow-on-join
        path when a publish/join crosses a page boundary. Existing pages
        keep their indices (no device state moves); only the NEW grid
        cells allocate. CapacityError leaves the room at its old extent."""
        room = self._rooms[row]
        mt = room.mt if tracks is None else max(room.mt, -(-tracks // self.tpage))
        ms = room.ms if subs is None else max(room.ms, -(-subs // self.spage))
        if mt > self.max_tpages or ms > self.max_spages:
            raise CapacityError(
                f"room {row} grow past max extent "
                f"({mt}x{ms} vs {self.max_tpages}x{self.max_spages} pages)"
            )
        cells = [
            (ti, si)
            for ti in range(mt)
            for si in range(ms)
            if room.grid[ti, si] < 0
        ]
        if not cells:
            room.mt, room.ms = mt, ms
            return self.extent(row)
        added_runs = len(room.runs)
        try:
            self._map_cells(row, room, cells)
        except CapacityError:
            # undo nothing: _map_cells is one run — it either fully
            # mapped or raised before mutating (alloc_run is atomic).
            del room.runs[added_runs:]
            raise
        room.mt, room.ms = mt, ms
        self.grows += 1
        self.peak_reserved = max(self.peak_reserved, self.pages_reserved)
        self._refresh_tables(row)
        return self.extent(row)

    def _rollback(self, room: _Room) -> None:
        for start, order in room.runs:
            for p in range(start, start + (1 << order)):
                if self.pg_room[p] >= 0 or p in self._fresh:
                    self.pg_room[p] = -1
                    self.pg_tp[p] = -1
                    self.pg_sp[p] = -1
                    self._fresh.discard(p)
            self._free_run(start, order)
        room.runs.clear()

    def release_room(self, row: int) -> None:
        room = self._rooms.pop(row, None)
        if room is None:
            return
        pages = room.grid[room.grid >= 0]
        for p in pages:
            self.pg_room[p] = -1
            self.pg_tp[p] = -1
            self.pg_sp[p] = -1
            self.tmembers[p] = -1
            if p in self._fresh:
                self._fresh.discard(p)
            else:
                self._freed.add(p)
        for start, order in room.runs:
            self._free_run(start, order)
        self.rooms_pages[row] = -1
        self._dirty_rooms.add(row)
        self.epoch += 1
        self.frees += 1

    def compact(self) -> list[tuple[int, int]]:
        """Defragment: relocate every live run to the bottom of a fresh
        pool (rooms in row order). Returns the mapped-page moves [(src,
        dst)] the runtime must replay as device row copies; the page
        table deltas queue alongside. O(live pages)."""
        old_rooms = dict(self._rooms)
        self._free = {self._max_order: {0}}
        moves: list[tuple[int, int]] = []
        self.pg_room[:] = -1
        self.pg_tp[:] = -1
        self.pg_sp[:] = -1
        self.tmembers[:] = -1
        for row in sorted(old_rooms):
            room = old_rooms[row]
            old_grid = room.grid.copy()
            room.runs = []
            room.grid[:] = -1
            cells = [
                (ti, si)
                for ti in range(room.mt)
                for si in range(room.ms)
                if old_grid[ti, si] >= 0
            ]
            order = self._order_for(len(cells))
            start = self._alloc_run(order)  # cannot fail: strictly packing
            room.runs.append((start, order))
            for i, (ti, si) in enumerate(cells):
                src = int(old_grid[ti, si])
                dst = start + i
                room.grid[ti, si] = dst
                self.pg_room[dst] = row
                self.pg_tp[dst] = ti
                self.pg_sp[dst] = si
                if src != dst:
                    if src in self._fresh:
                        self._fresh.discard(src)
                        self._fresh.add(dst)
                    else:
                        moves.append((src, dst))
            self._refresh_tables(row)
        # Pages that were mapped pre-compaction and are no longer mapped
        # anywhere must re-init (their stale state must not forward).
        live = {dst for _, dst in moves} | {
            int(p) for r in self._rooms.values() for p in r.grid[r.grid >= 0]
        }
        for src, _dst in moves:
            if src not in live:
                self._freed.add(src)
        self._moves.extend(moves)
        self.compactions += 1
        self.epoch += 1
        return moves

    # -- queries ----------------------------------------------------------

    def extent(self, row: int) -> RoomExtent:
        room = self._rooms[row]
        return RoomExtent(tracks=room.mt * self.tpage, subs=room.ms * self.spage)

    def pages_of_room(self, row: int) -> np.ndarray:
        """The room's mapped page ids (epoch-scoped — see module doc)."""
        room = self._rooms.get(row)
        if room is None:
            return np.empty(0, np.int32)
        return room.grid[room.grid >= 0].astype(np.int32)

    def room_of_page(self, page: int) -> int:
        return int(self.pg_room[page])

    def check_epoch(self, epoch: int) -> None:
        """Re-validate a page handle minted at `epoch` (GC08): raises
        StalePageError if the table changed since."""
        if epoch != self.epoch:
            raise StalePageError(
                f"page table epoch moved {epoch} -> {self.epoch}; "
                "re-fetch page indices before touching device state"
            )

    # -- delta lane -------------------------------------------------------

    def drain_delta(self) -> PageDelta:
        """Pending page events since the last drain, for the device
        upload (page-table rows + fresh/freed page state init + move
        copies). Clears the queues."""
        # Never reinit a currently-mapped page: a page released to _freed
        # can be re-mapped before the drain (compaction picking it as a
        # move destination) — the reinit runs AFTER the move replay and
        # would wipe the relocated state. alloc_room already migrates
        # such pages _freed -> _fresh; this filter closes the compaction
        # path. An unmapped stale page still reinits as usual.
        freed = [p for p in sorted(self._freed) if self.pg_room[p] < 0]
        delta = PageDelta(
            rooms=np.asarray(sorted(self._dirty_rooms), np.int32),
            fresh_pages=np.asarray(sorted(self._fresh), np.int32),
            freed_pages=np.asarray(freed, np.int32),
            moves=np.asarray(self._moves, np.int32).reshape(-1, 2),
        )
        self._dirty_rooms = set()
        self._fresh = set()
        self._freed = set()
        self._moves = []
        return delta

    # -- stats ------------------------------------------------------------

    @property
    def pages_reserved(self) -> int:
        return self.pool_pages - self.pages_free

    @property
    def pages_free(self) -> int:
        return sum(len(v) << o for o, v in self._free.items())

    @property
    def pages_mapped(self) -> int:
        return int((self.pg_room >= 0).sum())

    def stats(self) -> dict:
        free = self.pages_free
        largest = max(
            ((1 << o) for o, v in self._free.items() if v), default=0
        )
        return {
            "pages_total": self.pool_pages,
            "pages_used": self.pages_reserved,
            "pages_free": free,
            "pages_mapped": self.pages_mapped,
            # reserved-but-unmapped slack inside pow2 runs:
            "internal_slack": self.pages_reserved - self.pages_mapped,
            # external fragmentation: how much of the free space is
            # unreachable by the largest-class request (0 = one run).
            "fragmentation_ratio": (
                0.0 if free == 0 else round(1.0 - largest / free, 4)
            ),
            "free_runs_by_order": {
                o: len(v) for o, v in sorted(self._free.items()) if v
            },
            "rooms": len(self._rooms),
            "epoch": self.epoch,
            "allocs": self.allocs,
            "frees": self.frees,
            "grows": self.grows,
            "compactions": self.compactions,
            "alloc_failures": self.alloc_failures,
            "peak_pages_used": self.peak_reserved,
            "tpage": self.tpage,
            "spage": self.spage,
        }
