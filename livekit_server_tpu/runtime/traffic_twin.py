"""Deterministic fleet-scale traffic twin (ROADMAP item 2).

Every robustness plane in this repo — governor ladder, integrity repair,
live migration, epoch-fenced fleet ownership — was grown against a
handful of rooms with seeded point faults. The twin closes the gap to
production-shaped load: a **scenario DSL** (a dataclass timeline of
churn segments and incident events, all derived from ONE seed) is
expanded into an explicit event timeline, then replayed against real
servers — room manager → governor → pager → plane runtime → egress,
with the migration/fleet planes across a multi-node TCP bus — while the
SLO envelope is measured per offered-load step.

Determinism contract
--------------------
`build_timeline(scenario, offered_load)` is a pure function of
(scenario, offered_load): two runs at the same seed produce
byte-identical timelines (`timeline_bytes`). The replay drives VIRTUAL
time — each node's serving loop is paused and the twin calls
`step_once()` per scenario tick — and the governor is configured so only
deterministic sensors (capacity-drop deltas) classify ticks, so the
counter-derived SLOs (`SLOReport.deterministic_dict()`) are identical
across same-seed runs. Wall-clock SLOs (wire p99 via the flight
recorder) are reported alongside but excluded from that subset; they
depend on the host, not the seed.

Traffic shape
-------------
* diurnal join/leave churn: Poisson arrivals whose rate is modulated by
  a sinusoid per `ChurnSegment`;
* power-law room sizes: weighted size classes, default 80/15/5
  (tiny/medium/large) with a heavier tail available via `SizeClass`;
* regional skew: rooms land on a region sampled from `Scenario.regions`
  weights; each region maps onto one fleet node;
* codec mix: a fraction of rooms publish video (vp8 / vp9-svc mix), the
  rest are audio-only opus.

Incident catalog
----------------
* ``flash_crowd``  — regional cut followed by a reinvite/reconnect
  storm: every live session in the region resumes (reconnect=True swaps
  signal sinks without re-admission) while an arrival burst of NEW joins
  at `magnitude`× the base rate hits the same nodes and a seeded ingest
  flood (FaultInjector flood_mult) drives the governor up its ladder.
* ``regional_cut`` — all sessions in the region drop at `at`; at
  `at+ticks` the survivors' clients come back as a reconnect storm of
  fresh joins.
* ``rolling_drain`` — one node enters drain (migration orchestrator
  `drain_node()`): every room migrates off exactly once under active
  churn; joins routed at it are refused with reason ``draining``.

SLO envelope (per offered-load step)
------------------------------------
admission rate (+ denial reasons), audio continuity for probe
subscribers (unique contiguous munged SNs, exactly-once on the wire),
governor rung residency (fraction of node-ticks per ladder level),
time-to-recover per incident (ticks from incident end until every
governor is back at L0), and wire p99 from the flight recorder when
wire probes are enabled. `capacity_curve()` sweeps ≥4 offered-load
multipliers and reports the curve for the bench summary line.
"""

from __future__ import annotations

import asyncio
import json
import math
import time
from dataclasses import dataclass, field, asdict

import numpy as np

INCIDENT_KINDS = ("flash_crowd", "regional_cut", "rolling_drain")


class ScenarioError(ValueError):
    """A scenario that cannot be expanded into a timeline."""


# ---------------------------------------------------------------------------
# scenario DSL
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class SizeClass:
    """One rung of the room-size power law."""

    weight: float          # relative probability mass
    lo: int                # participants, inclusive
    hi: int                # participants, inclusive


#: 80/15/5: most rooms are 1:1-ish, a few are medium, a handful are big.
DEFAULT_SIZES = (
    SizeClass(0.80, 1, 2),
    SizeClass(0.15, 3, 8),
    SizeClass(0.05, 9, 30),
)

#: Heavier tail for stress sweeps: the big rooms get bigger and likelier.
HEAVY_TAIL_SIZES = (
    SizeClass(0.70, 1, 2),
    SizeClass(0.20, 3, 10),
    SizeClass(0.10, 12, 50),
)


@dataclass(frozen=True)
class ChurnSegment:
    """A span of ticks with one arrival/departure regime."""

    ticks: int
    join_rate: float               # expected room arrivals per tick @ load 1.0
    leave_rate: float = 0.0        # per-live-room leave probability per tick
    diurnal_amplitude: float = 0.0  # 0..1 sinusoidal modulation of join_rate
    diurnal_period: int = 0         # ticks per diurnal cycle; 0 = flat


@dataclass(frozen=True)
class Incident:
    """A scripted incident anchored to the scenario clock."""

    kind: str                      # one of INCIDENT_KINDS
    at: int                        # start tick
    ticks: int                     # duration
    region: str = ""               # "" = first region
    magnitude: float = 4.0         # flood multiplier / storm burst scale


@dataclass(frozen=True)
class Scenario:
    """The whole run, reproducible from `seed` alone."""

    seed: int = 20
    segments: tuple[ChurnSegment, ...] = (
        ChurnSegment(ticks=120, join_rate=0.5, leave_rate=0.01,
                     diurnal_amplitude=0.5, diurnal_period=60),
    )
    incidents: tuple[Incident, ...] = ()
    regions: tuple[tuple[str, float], ...] = (
        ("us-east", 0.5), ("eu", 0.3), ("ap", 0.2),
    )
    sizes: tuple[SizeClass, ...] = DEFAULT_SIZES
    video_room_frac: float = 0.4   # codec mix: P(room publishes video)
    video_codecs: tuple[tuple[str, float], ...] = (
        ("vp8", 0.7), ("vp9-svc", 0.3),
    )

    @property
    def total_ticks(self) -> int:
        return sum(s.ticks for s in self.segments)

    @classmethod
    def micro(cls, seed: int = 20) -> "Scenario":
        """~2-second end-to-end smoke shape: one segment, one incident."""
        return cls(
            seed=seed,
            segments=(ChurnSegment(ticks=30, join_rate=0.6, leave_rate=0.02,
                                   diurnal_amplitude=0.3, diurnal_period=20),),
            incidents=(Incident("flash_crowd", at=10, ticks=8,
                                region="us-east", magnitude=4.0),),
            regions=(("us-east", 0.7), ("eu", 0.3)),
        )

    @classmethod
    def standard(cls, seed: int = 20, ticks: int = 120) -> "Scenario":
        """The bench shape: diurnal churn + flash crowd + rolling drain."""
        third = max(ticks // 3, 10)
        return cls(
            seed=seed,
            segments=(
                ChurnSegment(ticks=ticks, join_rate=0.8, leave_rate=0.015,
                             diurnal_amplitude=0.6, diurnal_period=ticks // 2),
            ),
            incidents=(
                Incident("flash_crowd", at=third, ticks=third // 2,
                         region="us-east", magnitude=4.0),
                Incident("rolling_drain", at=2 * third,
                         ticks=max(third // 2, 8), region="eu"),
            ),
        )


def validate_scenario(sc: Scenario) -> None:
    """Raise ScenarioError on a shape the expander cannot honor."""
    if not sc.segments:
        raise ScenarioError("scenario needs at least one churn segment")
    for seg in sc.segments:
        if seg.ticks <= 0:
            raise ScenarioError(f"segment ticks must be positive, got {seg.ticks}")
        if seg.join_rate < 0 or not 0.0 <= seg.leave_rate <= 1.0:
            raise ScenarioError("join_rate must be >= 0 and leave_rate in [0, 1]")
        if not 0.0 <= seg.diurnal_amplitude <= 1.0:
            raise ScenarioError("diurnal_amplitude must be in [0, 1]")
        if seg.diurnal_amplitude > 0 and seg.diurnal_period <= 0:
            raise ScenarioError("diurnal_period must be positive when modulated")
    if not sc.regions or abs(sum(w for _, w in sc.regions) - 1.0) > 1e-6:
        raise ScenarioError("region weights must sum to 1")
    if not sc.sizes or any(s.weight <= 0 or s.lo <= 0 or s.hi < s.lo
                           for s in sc.sizes):
        raise ScenarioError("size classes need positive weights and lo <= hi")
    if not 0.0 <= sc.video_room_frac <= 1.0:
        raise ScenarioError("video_room_frac must be in [0, 1]")
    names = {n for n, _ in sc.regions}
    total = sc.total_ticks
    for inc in sc.incidents:
        if inc.kind not in INCIDENT_KINDS:
            raise ScenarioError(
                f"unknown incident kind {inc.kind!r} "
                f"(known: {', '.join(INCIDENT_KINDS)})"
            )
        if not 0 <= inc.at < total or inc.ticks <= 0:
            raise ScenarioError(
                f"incident {inc.kind} at tick {inc.at} x{inc.ticks} falls "
                f"outside the {total}-tick scenario"
            )
        if inc.region and inc.region not in names:
            raise ScenarioError(f"incident region {inc.region!r} not in scenario")
        if inc.magnitude <= 0:
            raise ScenarioError("incident magnitude must be positive")


# ---------------------------------------------------------------------------
# timeline expansion (pure, seeded)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class TwinEvent:
    """One expanded timeline entry. The canonical serialization of the
    event tuple IS the determinism contract (`timeline_bytes`)."""

    tick: int
    kind: str                  # join | leave | reconnect | incident_begin | incident_end
    room: str = ""
    region: str = ""
    participants: int = 0
    video: bool = False
    codec: str = ""
    incident: str = ""
    magnitude: float = 0.0


def _weighted(rng: np.random.Generator, pairs) -> str:
    names = [n for n, _ in pairs]
    weights = np.asarray([w for _, w in pairs], np.float64)
    return names[int(rng.choice(len(names), p=weights / weights.sum()))]


def build_timeline(
    sc: Scenario, offered_load: float = 1.0
) -> tuple[TwinEvent, ...]:
    """Expand a scenario into the explicit seeded event timeline.

    Pure in (scenario, offered_load): one `np.random.Generator` seeded
    from both drives every draw, events are emitted in a single
    deterministic pass, and nothing here reads a clock.
    """
    validate_scenario(sc)
    if offered_load <= 0:
        raise ScenarioError(f"offered_load must be positive, got {offered_load}")
    rng = np.random.default_rng([sc.seed, int(round(offered_load * 1000))])
    size_w = np.asarray([s.weight for s in sc.sizes], np.float64)
    size_w /= size_w.sum()

    events: list[TwinEvent] = []
    live: dict[str, TwinEvent] = {}    # room -> its join event (insertion order)
    room_no = 0

    def sample_room(tick: int, kind: str = "join") -> TwinEvent:
        nonlocal room_no
        cls = sc.sizes[int(rng.choice(len(sc.sizes), p=size_w))]
        video = bool(rng.random() < sc.video_room_frac)
        ev = TwinEvent(
            tick=tick, kind=kind, room=f"r{room_no:05d}",
            region=_weighted(rng, sc.regions),
            participants=int(rng.integers(cls.lo, cls.hi + 1)),
            video=video,
            codec=_weighted(rng, sc.video_codecs) if video else "opus",
        )
        room_no += 1
        return ev

    def burst_join(tick: int, region: str) -> TwinEvent:
        nonlocal room_no
        cls = sc.sizes[int(rng.choice(len(sc.sizes), p=size_w))]
        video = bool(rng.random() < sc.video_room_frac)
        ev = TwinEvent(
            tick=tick, kind="join", room=f"r{room_no:05d}", region=region,
            participants=int(rng.integers(cls.lo, cls.hi + 1)),
            video=video,
            codec=_weighted(rng, sc.video_codecs) if video else "opus",
        )
        room_no += 1
        return ev

    incidents = sorted(sc.incidents, key=lambda i: (i.at, i.kind))
    inc_region = {
        inc: (inc.region or sc.regions[0][0]) for inc in incidents
    }
    cut_rooms: dict[Incident, list[TwinEvent]] = {}

    tick = 0
    for seg in sc.segments:
        for _ in range(seg.ticks):
            # -- incident begins/ends anchored to this tick ---------------
            for inc in incidents:
                region = inc_region[inc]
                if inc.at == tick:
                    events.append(TwinEvent(
                        tick=tick, kind="incident_begin", incident=inc.kind,
                        region=region, magnitude=inc.magnitude,
                    ))
                    if inc.kind == "flash_crowd":
                        # The reinvite storm: every live session in the
                        # region resumes, spread across the window with
                        # seeded jitter (utils/backoff full-jitter analog).
                        for ev in [e for e in live.values()
                                   if e.region == region]:
                            events.append(TwinEvent(
                                tick=tick + int(rng.integers(0, max(inc.ticks // 2, 1))),
                                kind="reconnect", room=ev.room, region=region,
                                participants=ev.participants, video=ev.video,
                                codec=ev.codec,
                            ))
                    elif inc.kind == "regional_cut":
                        # Cut: the region's rooms drop now; their users
                        # come back as a storm of fresh joins at heal.
                        cut = [e for e in live.values() if e.region == region]
                        cut_rooms[inc] = cut
                        for ev in cut:
                            events.append(TwinEvent(
                                tick=tick, kind="leave", room=ev.room,
                                region=region,
                            ))
                            live.pop(ev.room, None)
                if inc.at + inc.ticks == tick:
                    events.append(TwinEvent(
                        tick=tick, kind="incident_end", incident=inc.kind,
                        region=region, magnitude=inc.magnitude,
                    ))
                    if inc.kind == "regional_cut":
                        for old in cut_rooms.get(inc, []):
                            ev = burst_join(
                                tick + int(rng.integers(0, 3)), region
                            )
                            events.append(ev)
                            live[ev.room] = ev
                # Flash-crowd window: arrival burst of NEW joins on top of
                # the base churn, magnitude x the segment rate.
                if (inc.kind == "flash_crowd"
                        and inc.at <= tick < inc.at + inc.ticks):
                    extra = rng.poisson(
                        inc.magnitude * seg.join_rate * offered_load
                    )
                    for _ in range(int(extra)):
                        ev = burst_join(tick, region)
                        events.append(ev)
                        live[ev.room] = ev

            # -- base churn ----------------------------------------------
            rate = seg.join_rate * offered_load
            if seg.diurnal_amplitude > 0:
                rate *= 1.0 + seg.diurnal_amplitude * math.sin(
                    2.0 * math.pi * tick / seg.diurnal_period
                )
            for _ in range(int(rng.poisson(max(rate, 0.0)))):
                ev = sample_room(tick)
                events.append(ev)
                live[ev.room] = ev
            if seg.leave_rate > 0 and live:
                # One vectorized draw over the (insertion-ordered) live
                # set keeps the pass O(rooms) and the order deterministic.
                names = list(live.keys())
                gone = np.nonzero(rng.random(len(names)) < seg.leave_rate)[0]
                for i in gone:
                    ev = live.pop(names[int(i)])
                    events.append(TwinEvent(
                        tick=tick, kind="leave", room=ev.room, region=ev.region,
                    ))
            tick += 1

    events.sort(key=lambda e: e.tick)   # stable: same-tick order preserved
    return tuple(events)


def timeline_bytes(events: tuple[TwinEvent, ...]) -> bytes:
    """Canonical serialization — the byte-identity determinism target."""
    return "\n".join(
        json.dumps(asdict(e), sort_keys=True, separators=(",", ":"))
        for e in events
    ).encode()


# ---------------------------------------------------------------------------
# SLO report
# ---------------------------------------------------------------------------

@dataclass
class SLOReport:
    """The measured SLO envelope of one twin run at one offered load."""

    offered_load: float = 1.0
    ticks: int = 0
    joins_offered: int = 0
    joins_admitted: int = 0
    denial_reasons: dict = field(default_factory=dict)
    rooms_peak: int = 0
    audio_expected: int = 0
    audio_received: int = 0
    audio_gaps: int = 0
    dup_wire_packets: int = 0
    rung_residency: dict = field(default_factory=dict)   # "L0".."L4" -> frac
    recovery_ticks: dict = field(default_factory=dict)   # incident -> ticks
    migrations: int = 0
    wire_p99_ms: float | None = None    # wall-clock; excluded from the
    wall_s: float = 0.0                 # deterministic subset below

    @property
    def admission_rate(self) -> float:
        return (self.joins_admitted / self.joins_offered
                if self.joins_offered else 1.0)

    @property
    def audio_continuity(self) -> float:
        return (self.audio_received / self.audio_expected
                if self.audio_expected else 1.0)

    def deterministic_dict(self) -> dict:
        """The counter-derived SLOs that must be identical across
        same-seed runs (no wall-clock terms)."""
        return {
            "offered_load": self.offered_load,
            "ticks": self.ticks,
            "joins_offered": self.joins_offered,
            "joins_admitted": self.joins_admitted,
            "admission_rate": round(self.admission_rate, 6),
            "denial_reasons": dict(sorted(self.denial_reasons.items())),
            "rooms_peak": self.rooms_peak,
            "audio_expected": self.audio_expected,
            "audio_received": self.audio_received,
            "audio_continuity": round(self.audio_continuity, 6),
            "audio_gaps": self.audio_gaps,
            "dup_wire_packets": self.dup_wire_packets,
            "rung_residency": {k: round(v, 6) for k, v in
                               sorted(self.rung_residency.items())},
            "recovery_ticks": dict(sorted(self.recovery_ticks.items())),
            "migrations": self.migrations,
        }

    def to_dict(self) -> dict:
        d = self.deterministic_dict()
        d["wire_p99_ms"] = self.wire_p99_ms
        d["wall_s"] = round(self.wall_s, 2)
        return d


# ---------------------------------------------------------------------------
# the runner
# ---------------------------------------------------------------------------

class _Probe:
    """Plane-level instrumentation of one admitted room: real tracks +
    one subscriber column, SN-contiguity bookkeeping across nodes (a
    migrated room keeps its probe — continuity must hold through the
    handoff)."""

    __slots__ = ("room", "video", "participants", "next_sn", "pushed",
                 "got", "base_sn")

    def __init__(self, room: str, video: bool, participants: int):
        self.room = room
        self.video = video
        self.participants = max(participants, 1)
        self.base_sn = 1000
        self.next_sn = self.base_sn
        self.pushed = 0
        self.got: list[int] = []


class TrafficTwin:
    """Replays a scenario timeline against a live single- or multi-node
    stack in virtual time and measures the SLO envelope."""

    def __init__(
        self,
        scenario: Scenario,
        *,
        nodes: int = 1,
        plane: dict | None = None,
        probe_every: int = 2,
        wire_probes: int = 0,
        flood_all_nodes: bool = True,
        settle_spins: int = 12,
        log=None,
    ):
        validate_scenario(scenario)
        if nodes < 1:
            raise ScenarioError("twin needs at least one node")
        self.scenario = scenario
        self.nodes = nodes
        self.plane = {"rooms": 16, "tracks_per_room": 4, "pkts_per_track": 8,
                      "subs_per_room": 4, "tick_ms": 10} | (plane or {})
        self.probe_every = max(probe_every, 1)
        self.wire_probes = wire_probes
        self.flood_all_nodes = flood_all_nodes
        self.settle_spins = settle_spins
        self.log = log or (lambda *_: None)
        self.debug: dict = {}   # filled by run(): drill-assertable state

    # -- cluster plumbing --------------------------------------------------

    def _make_config(self, port: int):
        from livekit_server_tpu.config import load_config

        doc = {
            "keys": {"twinkey": "twinsecret"},
            "port": port,
            "bind_addresses": ["127.0.0.1"],
            "plane": dict(self.plane),
            "rtc": {"udp_port": port + 1, "tcp_port": port + 2},
            "room": {"empty_timeout_s": 600},
            # Virtual time: only the deterministic sensors (capacity-drop
            # deltas) classify ticks; wall-clock pressure pushed out of
            # reach, policer transparent (test_overload's flood recipe).
            "limits": {
                "governor_enabled": True,
                "governor_enter_pressure": 1e9,
                "governor_exit_pressure": 1e8,
                "governor_escalate_ticks": 3,
                "governor_dwell_ticks": 8,
                "governor_ingress_pps": 1e6,
                "governor_ingress_burst": 1e6,
            },
            # The watchdog reads wall-clock tick cadence; the twin steps
            # virtual time, so supervision must sit out.
            "supervisor": {"enabled": False},
        }
        if self.nodes > 1:
            doc["kv"] = {"lease_ttl_s": 0.8, "failover_interval_s": 0.4,
                         "stats_interval_s": 0.2}
            # fence_grace must stay under lease_ttl + failover_interval
            # and at most 2 x lease_ttl (config invariant).
            doc["fleet"] = {"fence_grace_s": 1.1}
        return load_config(yaml_text=json.dumps(doc))

    async def _start_cluster(self):
        import socket

        from livekit_server_tpu.runtime.faultinject import (
            FaultInjector,
            FaultSpec,
        )
        from livekit_server_tpu.service.server import create_server

        def free_port() -> int:
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
            return port

        bus_srv = None
        servers = []
        if self.nodes > 1:
            from livekit_server_tpu.routing.tcpbus import BusServer

            bus_srv = BusServer()
            await bus_srv.start("127.0.0.1", 0)
        for i in range(self.nodes):
            bus = None
            if bus_srv is not None:
                from livekit_server_tpu.routing.tcpbus import TCPBusClient

                bus = await TCPBusClient.connect("127.0.0.1", bus_srv.port)
            srv = create_server(self._make_config(free_port()), bus=bus)
            await srv.start()
            rt = srv.room_manager.runtime
            # Pause the serving loop: the twin owns virtual time and the
            # step_once() contract forbids interleaving with it.
            await rt.stop()
            inj = FaultInjector(FaultSpec(
                seed=self.scenario.seed + i, flood_mult=1.0,
            ))
            rt.fault = inj
            rt.ingest.fault = inj
            servers.append((srv, inj))
        return bus_srv, servers

    @staticmethod
    async def _settle(spins: int) -> None:
        """Let ready callbacks (session tasks, bus IO) run between
        virtual ticks without advancing wall-clock timers."""
        for _ in range(spins):
            await asyncio.sleep(0)

    # -- the replay --------------------------------------------------------

    async def run(self, offered_load: float = 1.0) -> SLOReport:
        from livekit_server_tpu.routing.messagechannel import MessageChannel
        from livekit_server_tpu.runtime.governor import L_HEALTHY
        from livekit_server_tpu.runtime.ingest import PacketIn

        events = build_timeline(self.scenario, offered_load)
        by_tick: dict[int, list[TwinEvent]] = {}
        for ev in events:
            by_tick.setdefault(ev.tick, []).append(ev)

        t0 = time.perf_counter()
        bus_srv, servers = await self._start_cluster()
        rep = SLOReport(offered_load=offered_load,
                        ticks=self.scenario.total_ticks)
        region_node = {
            name: i % self.nodes
            for i, (name, _) in enumerate(self.scenario.regions)
        }
        sessions: dict[str, tuple] = {}     # room -> (node, req, resp, task)
        probes: dict[str, _Probe] = {}
        # (node, row) -> probe room, rebound on every (re)admission so a
        # recycled row or a migrated room keeps attributing correctly.
        row_probe: dict[tuple[int, int], str] = {}
        wire_seen: dict[tuple, int] = {}    # (room, track, sub, sn) -> count
        drain_task: asyncio.Task | None = None
        level_ticks: dict[int, int] = {}
        pending_recovery: dict[str, int] = {}   # incident -> end tick
        probe_count = 0

        def collector(node_idx: int):
            def on_tick(res):
                for p in res.egress:
                    room = row_probe.get((node_idx, p.room))
                    if room is None:
                        continue
                    key = (room, p.track, p.sub, p.sn)
                    wire_seen[key] = wire_seen.get(key, 0) + 1
                    if p.track == 0 and p.sub == 1:
                        pr = probes.get(room)
                        if pr is not None:
                            pr.got.append(p.sn)
            return on_tick

        wire_socks = []
        try:
            for i, (srv, _) in enumerate(servers):
                srv.room_manager.runtime.on_tick(collector(i))

            async def attempt_join(ev: TwinEvent, reconnect: bool) -> None:
                nonlocal probe_count
                node_idx = region_node.get(ev.region, 0)
                srv, _ = servers[node_idx]
                rm = srv.room_manager
                req, resp = MessageChannel(), MessageChannel()
                init = {"identity": f"{ev.room}-p0"}
                if reconnect:
                    init["reconnect"] = True
                old = sessions.pop(ev.room, None)
                task = asyncio.ensure_future(
                    rm.start_session(ev.room, init, req, resp)
                )
                sessions[ev.room] = (node_idx, req, resp, task)
                rep.joins_offered += 1
                await self._settle(self.settle_spins)
                if old is not None:
                    # The storm resumed the session (sink swap + epoch
                    # bump); the dead connection's channel closing later
                    # must be a stale-teardown no-op, which the settle
                    # above guarantees ordering for.
                    old[1].close()
                # Probe selection is eager but arming is lazy: over a real
                # TCP bus the room may not be visible yet when the settle
                # window closes (store round-trips), so the per-tick
                # ownership scan arms the probe the moment the room
                # appears — and re-arms it if a migration moves it.
                room = rm.rooms.get(ev.room)
                if ev.room not in probes and probe_count % self.probe_every == 0:
                    probes[ev.room] = _Probe(ev.room, ev.video,
                                             ev.participants)
                if room is not None and ev.room in probes:
                    self._arm_probe(srv, room, probes[ev.room], node_idx,
                                    row_probe, wire_socks)
                probe_count += 1

            async def do_leave(ev: TwinEvent) -> None:
                ses = sessions.pop(ev.room, None)
                if ses is not None:
                    _node_idx, req, _resp, _task = ses
                    req.close()
                    await self._settle(4)
                    # Delete wherever the room lives NOW — a migration
                    # may have moved it off the node that admitted it.
                    for srv, _ in servers:
                        if ev.room in srv.room_manager.rooms:
                            await srv.room_manager.delete_room(ev.room)
                probes.pop(ev.room, None)

            for tick in range(self.scenario.total_ticks):
                for ev in by_tick.get(tick, ()):  # timeline order
                    if ev.kind == "join":
                        await attempt_join(ev, reconnect=False)
                    elif ev.kind == "reconnect":
                        if ev.room in sessions:
                            await attempt_join(ev, reconnect=True)
                    elif ev.kind == "leave":
                        await do_leave(ev)
                    elif ev.kind == "incident_begin":
                        self.log(f"twin: incident {ev.incident} begins @ {tick}")
                        if ev.incident == "flash_crowd":
                            targets = (servers if self.flood_all_nodes else
                                       [servers[region_node.get(ev.region, 0)]])
                            for _, inj in targets:
                                inj.spec.flood_mult = ev.magnitude
                        elif ev.incident == "rolling_drain":
                            node_idx = region_node.get(ev.region, 0)
                            mig = servers[node_idx][0].room_manager.migration
                            if mig is not None and self.nodes > 1:
                                drain_task = asyncio.ensure_future(
                                    mig.drain_node()
                                )
                    elif ev.kind == "incident_end":
                        if ev.incident == "flash_crowd":
                            for _, inj in servers:
                                inj.spec.flood_mult = 1.0
                        pending_recovery[ev.incident] = tick

                # Probe media for this virtual tick: one audio packet per
                # probe room (+ participant-scaled video for video rooms).
                now = time.perf_counter()
                for room, pr in probes.items():
                    if room not in sessions:
                        continue
                    # Ownership scan, not the session's original node: a
                    # drain can migrate the room mid-run, and the probe
                    # (media push + wire accounting) must follow it to
                    # the survivor or the exactly-once check goes blind
                    # at the handoff.
                    owner = next(
                        ((i, srv, srv.room_manager.rooms[room])
                         for i, (srv, _) in enumerate(servers)
                         if room in srv.room_manager.rooms),
                        None,
                    )
                    if owner is None:
                        continue
                    node_idx, srv, r = owner
                    rm = srv.room_manager
                    if row_probe.get((node_idx, r.slots.row)) != room:
                        self._arm_probe(srv, r, pr, node_idx, row_probe,
                                        wire_socks)
                    rm.runtime.ingest.push(PacketIn(
                        room=r.slots.row, track=0, sn=pr.next_sn,
                        ts=960 * (pr.next_sn - pr.base_sn), size=40,
                        payload=b"a",
                    ), t_rx=now)
                    pr.next_sn += 1
                    pr.pushed += 1
                    if pr.video:
                        for j in range(min(pr.participants, 3)):
                            rm.runtime.ingest.push(PacketIn(
                                room=r.slots.row, track=1,
                                sn=50_000 + pr.pushed * 4 + j,
                                ts=3000 * pr.pushed, size=400, payload=b"v",
                                keyframe=True, layer_sync=True,
                                begin_pic=True, marker=True,
                            ), t_rx=now)

                for srv, _ in servers:
                    rt = srv.room_manager.runtime
                    await rt.step_once()
                    gov = srv.room_manager.governor
                    lvl = gov.level if gov is not None else 0
                    level_ticks[lvl] = level_ticks.get(lvl, 0) + 1
                await self._settle(4)

                # Recovery clock: ticks from incident end until every
                # governor is back at L0.
                done = []
                for inc, end_tick in pending_recovery.items():
                    # A drain-held governor is pinned at L4 by design for
                    # the node's remaining life — it can't "recover" and
                    # must not mask the fleet's recovery clock.
                    if all((srv.room_manager.governor is None
                            or srv.room_manager.governor.drain_hold
                            or srv.room_manager.governor.level == L_HEALTHY)
                           for srv, _ in servers):
                        rep.recovery_ticks[inc] = tick - end_tick
                        done.append(inc)
                for inc in done:
                    pending_recovery.pop(inc)

                rep.rooms_peak = max(
                    rep.rooms_peak,
                    sum(len(srv.room_manager.rooms) for srv, _ in servers),
                )

            if drain_task is not None:
                # Keep virtual time flowing while the drain finishes: the
                # migration protocol may need plane ticks on both ends to
                # flush before it commits.
                for _ in range(200):
                    if drain_task.done():
                        break
                    for srv, _ in servers:
                        await srv.room_manager.runtime.step_once()
                    await self._settle(8)
                await asyncio.wait_for(drain_task, timeout=30)
            # A few settle ticks so in-flight egress (bridged packets,
            # final fan-out) lands before the books close.
            for _ in range(3):
                for srv, _ in servers:
                    await srv.room_manager.runtime.step_once()
                await self._settle(4)
            for inc, _end in pending_recovery.items():
                rep.recovery_ticks.setdefault(inc, -1)   # never recovered

            # -- close the books ------------------------------------------
            for srv, _ in servers:
                rm = srv.room_manager
                for reason, n in getattr(
                    rm, "admission_denied_reasons", {}
                ).items():
                    rep.denial_reasons[reason] = (
                        rep.denial_reasons.get(reason, 0) + n
                    )
                if rm.migration is not None:
                    rep.migrations += rm.migration.stats.get("commits", 0)
            denied = sum(rep.denial_reasons.values())
            rep.joins_admitted = max(rep.joins_offered - denied, 0)

            for pr in probes.values():
                rep.audio_expected += pr.pushed
                uniq = sorted(set(pr.got))
                rep.audio_received += len(uniq)
                rep.audio_gaps += sum(
                    1 for a, b in zip(uniq, uniq[1:]) if b - a != 1
                )
            rep.dup_wire_packets = sum(
                n - 1 for n in wire_seen.values() if n > 1
            )
            total_lvl = sum(level_ticks.values()) or 1
            rep.rung_residency = {
                f"L{lvl}": n / total_lvl for lvl, n in level_ticks.items()
            }
            if self.wire_probes:
                probes_p99 = [
                    srv.room_manager.udp.fwd_latency.summary()
                    for srv, _ in servers
                    if srv.room_manager.udp is not None
                ]
                samples = [(s["p99_ms"], s["n"]) for s in probes_p99 if s["n"]]
                if samples:
                    rep.wire_p99_ms = max(p for p, _ in samples)
            # Cross-plane drill snapshot, captured before teardown: the
            # tier-1 drills assert on ladder order, migration accounting,
            # and where load landed — state the servers take with them.
            self.debug = {
                "governor_transitions": [
                    [dict(t) for t in srv.room_manager.governor.transitions]
                    if srv.room_manager.governor is not None else []
                    for srv, _ in servers
                ],
                "migration_stats": [
                    dict(srv.room_manager.migration.stats)
                    if srv.room_manager.migration is not None else {}
                    for srv, _ in servers
                ],
                "rooms_final": [
                    sorted(srv.room_manager.rooms) for srv, _ in servers
                ],
                "denied_by_node": [
                    dict(getattr(srv.room_manager,
                                 "admission_denied_reasons", {}))
                    for srv, _ in servers
                ],
            }
            rep.wall_s = time.perf_counter() - t0
            return rep
        finally:
            # Drain sessions while the bus is still alive: a worker whose
            # teardown does store ops against a closed bus spends the
            # retry policy's full budget timing out.
            for _n, req, _resp, _task in list(sessions.values()):
                try:
                    req.close()
                except Exception:  # noqa: BLE001
                    pass
            live = [t for *_x, t in sessions.values() if not t.done()]
            if live:
                await asyncio.wait(live, timeout=5)
                for t in live:
                    if not t.done():
                        t.cancel()
            for s in wire_socks:
                try:
                    s.close()
                except OSError:
                    pass
            for srv, _ in servers:
                try:
                    await srv.stop(force=True)
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
            if bus_srv is not None:
                bus_srv.close()

    def _arm_probe(self, srv, room, pr: _Probe, node_idx: int,
                   row_probe: dict, wire_socks: list) -> None:
        """Attach plane tracks/subscription + optional wire sink for one
        probe room on whichever node currently owns it."""
        rt = srv.room_manager.runtime
        row = room.slots.row
        rt.set_track(row, 0, published=True, is_video=False)
        rt.set_subscription(row, 0, 1, subscribed=True)
        if pr.video:
            rt.set_track(row, 1, published=True, is_video=True)
            rt.set_subscription(row, 1, 1, subscribed=True)
        row_probe[(node_idx, row)] = pr.room
        udp = srv.room_manager.udp
        if (self.wire_probes and udp is not None
                and len(wire_socks) < self.wire_probes):
            import socket

            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            s.bind(("127.0.0.1", 0))
            s.setblocking(False)
            udp.register_subscriber(row, 1, s.getsockname())
            wire_socks.append(s)


# ---------------------------------------------------------------------------
# capacity curve (the bench entrypoint)
# ---------------------------------------------------------------------------

async def capacity_curve(
    scenario: Scenario,
    loads: tuple[float, ...] = (0.5, 1.0, 2.0, 4.0),
    *,
    nodes: int = 2,
    plane: dict | None = None,
    wire_probes: int = 0,
    log=None,
    on_step=None,
) -> dict:
    """Run the scenario at each offered-load multiplier (fresh cluster per
    step — no state bleed between points) and report the capacity/SLO
    curve for the bench summary. `on_step(partial_steps)` fires after
    each load so a caller under a deadline can emit incrementally."""
    if len(loads) < 4:
        raise ScenarioError("capacity curve needs >= 4 offered-load steps")
    steps = []
    for load in loads:
        twin = TrafficTwin(scenario, nodes=nodes, plane=plane,
                           wire_probes=wire_probes, log=log)
        rep = await twin.run(load)
        steps.append(rep.to_dict())
        if log:
            log(f"twin: load x{load}: admission "
                f"{rep.admission_rate:.3f}, continuity "
                f"{rep.audio_continuity:.3f}, residency {rep.rung_residency}")
        if on_step:
            on_step(list(steps))
    knee = next(
        (s["offered_load"] for s in steps if s["admission_rate"] < 0.999),
        None,
    )
    return {
        "seed": scenario.seed,
        "loads": list(loads),
        "steps": steps,
        "capacity_knee_load": knee,
    }


def run_micro_smoke(seed: int = 20) -> dict:
    """The ~2-second end-to-end micro-scenario behind
    `tools/check --twin-smoke`: single node, tiny pool, one churn
    segment, one flash-crowd incident."""
    sc = Scenario.micro(seed)
    twin = TrafficTwin(
        sc, nodes=1,
        plane={"rooms": 8, "tracks_per_room": 4, "pkts_per_track": 8,
               "subs_per_room": 4, "tick_ms": 10},
        probe_every=2,
    )
    rep = asyncio.run(twin.run(1.0))
    out = rep.to_dict()
    out["ok"] = (
        rep.audio_gaps == 0
        and rep.dup_wire_packets == 0
        and rep.joins_admitted > 0
    )
    return out


def scenario_from_config(twin_cfg) -> Scenario:
    """Build the bench scenario from the `twin.*` config block (so the
    knobs in config-sample.yaml are load-bearing, not decorative)."""
    sc = Scenario.standard(seed=twin_cfg.seed, ticks=twin_cfg.ticks)
    sc = Scenario(
        seed=sc.seed, segments=sc.segments, incidents=sc.incidents,
        regions=sc.regions, sizes=sc.sizes,
        video_room_frac=twin_cfg.video_room_frac,
        video_codecs=sc.video_codecs,
    )
    validate_scenario(sc)
    return sc


def main(argv=None) -> int:
    """CLI used by `bench.py fleet_twin` and `tools/check --twin-smoke`.

    Prints progress to stderr and exactly one JSON object line to stdout
    LAST — the contract `bench.absorb_twin_json` pins (the driver keeps
    the final `{`-prefixed stdout line).
    """
    import argparse
    import sys

    ap = argparse.ArgumentParser(prog="traffic_twin")
    ap.add_argument("--smoke", action="store_true",
                    help="run the ~2s micro-scenario once and exit")
    ap.add_argument("--seed", type=int, default=20)
    ap.add_argument("--ticks", type=int, default=120)
    ap.add_argument("--nodes", type=int, default=2)
    ap.add_argument("--loads", type=str, default="0.5,1.0,2.0,4.0")
    ap.add_argument("--wire-probes", type=int, default=0)
    args = ap.parse_args(argv)

    def log(msg: str) -> None:
        print(msg, file=sys.stderr, flush=True)

    if args.smoke:
        out = run_micro_smoke(seed=args.seed)
        print(json.dumps(out), flush=True)
        return 0 if out["ok"] else 1

    loads = tuple(float(x) for x in args.loads.split(",") if x.strip())
    sc = Scenario.standard(seed=args.seed, ticks=args.ticks)
    validate_scenario(sc)

    def on_step(partial):
        # Incremental emission: a deadline kill loses at most the load
        # step in flight (the bench keeps the last complete JSON line).
        print(json.dumps({"seed": sc.seed, "loads": list(loads),
                          "steps": partial, "partial": True}), flush=True)

    curve = asyncio.run(capacity_curve(
        sc, loads, nodes=args.nodes,
        plane={"rooms": 16, "tracks_per_room": 4, "pkts_per_track": 8,
               "subs_per_room": 4, "tick_ms": 10},
        wire_probes=args.wire_probes, log=log, on_step=on_step,
    ))
    print(json.dumps(curve), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
