"""Per-tick packet ingest: wire fields → TickInputs tensors.

Reference parity: the ingest half of buffer.Buffer (pkg/sfu/buffer/
buffer.go:268 Write → :417 calc — each arriving RTP packet is parsed and
queued for the hot loop). Here arriving packets are staged into
preallocated numpy arrays with per-(room, track) write cursors; at each
tick boundary `drain()` hands the filled tensors (plus the valid mask) to
the device step and resets the cursors. Overflow (more packets than K
slots in one tick) drops-and-counts, mirroring the reference's bounded
buffers; payload bytes are staged separately in a slab so the device only
ever sees fixed-size header fields.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from livekit_server_tpu.models import plane

# Max NACKed SNs per (room, sub) per TICK counted into the BWE loss channel
# (the bound the old device-staging slots enforced; reference drops the same).
NACK_COUNT_CAP = 8


def _gather_ranges(blob: np.ndarray, starts: np.ndarray, lens: np.ndarray) -> bytes:
    """Concatenate blob[starts[i] : starts[i] + lens[i]] for all i in ONE
    call — the per-packet `bytes` slicing this replaces was the slab's
    per-tick Python hot spot. Native memcpy loop when available."""
    from livekit_server_tpu.native import rtp

    if getattr(rtp, "native", False):
        return rtp.gather_ranges(blob, starts, lens)
    total = int(lens.sum())
    if total == 0:
        return b""
    # Index trick: repeat each range's start minus the running output
    # offset, add arange → absolute source index per output byte.
    out_base = np.repeat(
        starts - np.concatenate([[np.int64(0)], np.cumsum(lens[:-1])]), lens
    )
    return (blob[out_base + np.arange(total, dtype=np.int64)]).tobytes()


def _wrap_i32(x: int) -> int:
    """uint32 bit pattern → int32 two's complement (numpy 2.x raises on
    out-of-range np.int32(...) casts)."""
    x &= 0xFFFFFFFF
    return x - (1 << 32) if x >= (1 << 31) else x


@dataclass
class PayloadSlab:
    """One tick's payload bytes + [R, T, K] index arrays (drain output)."""

    data: bytes
    off: np.ndarray      # int64; -1 = no payload staged
    length: np.ndarray   # int32
    marker: np.ndarray   # bool — RTP M bit
    # Dependency-descriptor extension bytes (SVC tracks): staged alongside
    # payloads so egress re-attaches them (-1 = none).
    dd_off: np.ndarray | None = None   # int64
    dd_len: np.ndarray | None = None   # int32
    dd_ver: np.ndarray | None = None   # int32 — structure version stamp
    # Arrival stamps (time.perf_counter seconds at batch-receive return;
    # 0 = not stamped): the rx half of the wall-clock packet-in→wire-out
    # forward-latency probe (udp.py observes at sendmmsg-return).
    t_arr: np.ndarray | None = None    # float64

    def get(self, r: int, t: int, k: int) -> tuple[bytes, bool]:
        o = int(self.off[r, t, k])
        if o < 0:
            return b"", False
        return (
            bytes(self.data[o : o + int(self.length[r, t, k])]),
            bool(self.marker[r, t, k]),
        )

    def get_dd(self, r: int, t: int, k: int) -> bytes:
        if self.dd_off is None:
            return b""
        o = int(self.dd_off[r, t, k])
        if o < 0:
            return b""
        return bytes(self.data[o : o + int(self.dd_len[r, t, k])])


@dataclass
class PacketIn:
    """Parsed header fields of one media packet (ExtPacket analog)."""

    room: int               # room row
    track: int              # track col
    sn: int
    ts: int
    size: int
    payload: bytes = b""
    marker: bool = False    # RTP M bit (frame delimiter; egress restores it)
    layer: int = 0
    temporal: int = 0
    keyframe: bool = False
    layer_sync: bool = False
    begin_pic: bool = False
    pid: int = 0
    tl0: int = 0
    keyidx: int = 0
    frame_ms: int = 20
    audio_level: int = 127
    arrival_rtp: int = 0
    ts_aligned: bool = False  # ts already on the track's common timeline
                              # (SR-normalized by the transport)


class _StagingSet:
    """One of the two ping-ponged per-tick staging halves: the [R, T, K]
    packet field arrays, the payload slab, and the per-(room, track)
    write cursor. IngestBuffer binds the ACTIVE set's arrays as its own
    attributes, so push()/push_batch()/drain() — and the tests that poke
    `buf.sn` directly — address whichever set currently receives pushes."""

    # Attributes rebound onto IngestBuffer at each flip.
    ARRAYS = (
        "_count", "sn", "ts", "layer", "temporal", "keyframe", "layer_sync",
        "begin_pic", "end_frame", "pid", "tl0", "keyidx", "size", "frame_ms",
        "audio_level", "arrival_rtp", "ts_jump", "valid",
        "_slab", "pay_off", "pay_len", "marker", "t_arr",
        "dd_off", "dd_len", "dd_ver",
    )

    def __init__(self, dims: plane.PlaneDims):
        R, T, K, _ = dims
        i32 = lambda: np.zeros((R, T, K), np.int32)
        boo = lambda: np.zeros((R, T, K), bool)
        self._count = np.zeros((R, T), np.int32)
        self.sn = i32()
        self.ts = i32()
        self.layer = i32()
        self.temporal = i32()
        self.keyframe = boo()
        self.layer_sync = boo()
        self.begin_pic = boo()
        self.end_frame = boo()
        self.pid = i32()
        self.tl0 = i32()
        self.keyidx = i32()
        self.size = i32()
        self.frame_ms = i32()
        self.audio_level = np.full((R, T, K), 127, np.int32)
        self.arrival_rtp = i32()
        # -1 = SR-normalized (exact cross-layer continuity); else one-frame
        # fallback advance at a source switch (forwarder.go:1456).
        self.ts_jump = np.full((R, T, K), 3000, np.int32)
        self.valid = boo()
        # Payload slab — host-side only (PacketFactory analog; payload
        # bytes never cross to the device). One contiguous bytearray per
        # tick plus [R, T, K] offset/length arrays, so egress gathers
        # payloads by index math instead of dict lookups per packet.
        self._slab = bytearray()
        self.pay_off = np.full((R, T, K), -1, np.int64)
        self.pay_len = np.zeros((R, T, K), np.int32)
        self.marker = np.zeros((R, T, K), bool)
        self.t_arr = np.zeros((R, T, K), np.float64)
        self.dd_off = np.full((R, T, K), -1, np.int64)
        self.dd_len = np.zeros((R, T, K), np.int32)
        self.dd_ver = np.full((R, T, K), -1, np.int32)
        self.needs_scrub = False

    def scrub(self) -> None:
        """Reset for reuse as the push target. Only the masks/cursors and
        payload index arrays need clearing — stale packet field values
        are dead under valid=False (drain snapshots honor the mask)."""
        self._slab.clear()
        self.pay_off[:] = -1
        self.pay_len[:] = 0
        self.marker[:] = False
        self.t_arr[:] = 0.0
        self.dd_off[:] = -1
        self.dd_len[:] = 0
        self.dd_ver[:] = -1
        self._count[:] = 0
        self.valid[:] = False
        self.audio_level[:] = 127
        self.needs_scrub = False


class IngestBuffer:
    """Double-buffered staging area for one node's tick inputs: two
    ping-ponged _StagingSets, flipped at each drain(), so staging tick
    N+1 can fill one set while tick N's device step / slab-history
    retention still reference data snapshotted from the other. The
    retired set's reset is deferrable (scrub_retired) so its memsets run
    in the serving loop's post-dispatch slack, off the staging path."""

    def __init__(self, dims: plane.PlaneDims, tick_ms: int):
        self.dims = dims
        self.tick_ms = tick_ms
        R, T, K, S = dims
        # Drop accounting, split by cause so shedding metrics are
        # trustworthy: capacity = tick slab overflow (real overload
        # pressure), fault = chaos-injected loss (faultinject.py),
        # policed = governor token-bucket shedding (intentional — must
        # NOT read back as pressure). `dropped` below sums them for the
        # pre-split readers (/debug/rooms, bench).
        self.dropped_capacity = 0
        self.dropped_fault = 0
        self.dropped_policed = 0
        # Rows quiesced for migration: once a room's state snapshot is
        # taken, admitting more packets would advance munger offsets past
        # what the destination node restores (duplicate SNs on re-issue).
        self.frozen_rows: set[int] = set()
        # Freeze-window bridge taps (service/migration.py): per-row
        # capture callbacks for packets arriving while their row is
        # frozen. With a sink attached the packet is buffered and
        # forwarded to the migration target instead of silently lost —
        # the zero-audio-gap half of the freeze contract. No sink (the
        # legacy handoff path) keeps the old drop behavior.
        self.freeze_sinks: dict = {}
        # Optional FaultInjector (runtime/faultinject.py) consulted by
        # push()/push_batch(); None on the default config path. Delayed
        # packets re-enter at the top of drain() for their release tick.
        self.fault = None
        self._fault_tick = 0
        # Ingress policer (governor L2+): per-(room, track) token
        # buckets, refilled at drain() so admission cost stays O(1) per
        # packet. rate == 0 disables. `_police_video` holds a LIVE view
        # of the runtime's is_video mirror when set — audio is exempt by
        # construction (prioritized degradation: video sheds first).
        self._police_rate = 0.0
        self._police_burst = 0.0
        self._police_tokens = np.zeros((R, T), np.float64)
        self._police_video = None
        # Staging coordinates of the last push_batch (diagnostics/tests;
        # None after any path that staged nothing vectorized — chaos,
        # frozen-only, policed/capacity-empty).
        self.last_put: tuple | None = None
        # Arrival hook: called with (rooms, tracks, ks) staging coordinates
        # after EVERY successful staging — vectorized (push_batch) and
        # per-packet (push) alike, so the express lane sees TCP/gateway/
        # bridge-replayed packets too, not just the UDP fast path. The
        # fan-out masks express rooms' rows wholesale; an ingest path that
        # bypassed this hook would silently drop their media.
        self.on_put = None
        self._sets = (_StagingSet(dims), _StagingSet(dims))
        self._active = 0
        self._bind(self._sets[0])
        # Per-subscriber feedback staging (single-set: the [R, S]
        # accumulators are small enough to reset inline at drain).
        self._estimate = np.zeros((R, S), np.float32)
        self._estimate_valid = np.zeros((R, S), bool)
        self._nacks = np.zeros((R, S), np.float32)
        # Per-sub RTT (host replay throttle) — NACK resolution itself is
        # host-side (plane_runtime.HostSequencer).
        self.rtt_ms = np.full((R, S), 100, np.int32)  # persistent (RR-updated)
        # Track → publishing participant's subscriber slot (-1 unknown):
        # lets the tick score each track's MOS with its publisher-path RTT
        # (scorer.go includes RTT in the E-model delay term).
        self.track_pub_sub = np.full((R, T), -1, np.int32)
        # TWCC feedback accumulators (runtime/udp.py push_twcc_feedback →
        # ops/bwe delay estimator): per-(room, sub) sums reduced to one
        # sample per tick at drain.
        self._fb_delay_sum = np.zeros((R, S), np.float64)
        self._fb_count = np.zeros((R, S), np.int64)
        self._fb_bytes = np.zeros((R, S), np.int64)
        self._fb_span_ms = np.zeros((R, S), np.float64)
        self.fb_enabled = np.zeros((R, S), bool)  # sealed-UDP-path subs
        # One-tick reset mask: a released subscriber slot's device-side
        # per-sub state (BWE/delay/pacer) must not leak to the next
        # occupant (e.g. a decayed floor rate + sticky ever_fb latch
        # would cap a fresh subscriber for up to a minute).
        self.sub_reset = np.zeros((R, S), bool)
        # Cumulative per-(room, track) receive counters
        # (participant_traffic_load.go seat: per-participant rates are
        # window deltas over these, summed across a publisher's tracks).
        self.rx_pkts = np.zeros((R, T), np.int64)
        self.rx_bytes = np.zeros((R, T), np.int64)
        # WS-media egress counters ([..., 0]=pkts, [..., 1]=bytes): the
        # UDP transport keeps its own; subscribers on the WS media path
        # must count too or a WS-heavy node reports zero egress.
        self.ws_tx = np.zeros((R, S, 2), np.int64)
        self.nack_overflow = 0   # NACK counts clipped by NACK_COUNT_CAP
        self._nack_seen: set = set()           # per-tick (r, s, sn, track)
        self._nack_tick_cnt = np.zeros((R, S), np.int32)
        self.dupes = 0

    def _bind(self, s: _StagingSet) -> None:
        """Point the buffer's staging attributes at `s`'s arrays (the
        ping-pong flip). bytearray += and reshape-view writes mutate the
        bound objects in place, so push paths need no indirection."""
        for name in _StagingSet.ARRAYS:
            setattr(self, name, getattr(s, name))

    def scrub_retired(self) -> None:
        """Deferred reset of the set retired by the last drain(). The
        serving loop calls this in the post-dispatch slack; if it never
        runs (step_once, direct drain() callers), the next drain() scrubs
        inline before flipping to the set."""
        s = self._sets[1 - self._active]
        if s.needs_scrub:
            s.scrub()

    @property
    def dropped(self) -> int:
        """Total drops across causes (back-compat reader; the split
        counters are the trustworthy signal)."""
        return self.dropped_capacity + self.dropped_fault + self.dropped_policed

    def set_policer(
        self, rate_pps: float, burst: float, is_video: np.ndarray | None = None
    ) -> None:
        """Arm the per-(room, track) ingress token buckets (governor L2).
        `is_video` is held by reference — tracks whose flag is False
        (audio) bypass the policer entirely."""
        self._police_rate = float(rate_pps)
        self._police_burst = float(burst)
        self._police_tokens[:] = burst
        self._police_video = is_video

    def clear_policer(self) -> None:
        self._police_rate = 0.0
        self._police_video = None

    @staticmethod
    def _group_ranks(flat_rt: np.ndarray, n: int):
        """Arrival-order rank of each packet within its (room, track)
        group. Returns (order, sorted_rt, grp_start, sizes, ranks)."""
        order = np.argsort(flat_rt, kind="stable")
        sorted_rt = flat_rt[order]
        grp_start = np.r_[0, np.nonzero(np.diff(sorted_rt))[0] + 1]
        sizes = np.diff(np.r_[grp_start, n])
        ranks = np.empty(n, np.int64)
        ranks[order] = np.arange(n) - np.repeat(grp_start, sizes)
        return order, sorted_rt, grp_start, sizes, ranks

    def push(
        self,
        pkt: PacketIn,
        t_rx: float = 0.0,
        _fault_ok: bool = False,
        _count_rx: bool = True,
    ) -> bool:
        """Stage one packet; False (and counted by cause) if shed."""
        if pkt.room in self.frozen_rows:
            # Mid-migration: the row's state is already shipped. A bridge
            # sink captures the packet for forwarding; otherwise it drops.
            sink = self.freeze_sinks.get(pkt.room)
            if sink is not None:
                sink(pkt)
            return False
        r, t = pkt.room, pkt.track
        # Receive accounting first: the packet arrived on the wire no
        # matter what verdict follows (the old fault path returned before
        # counting, skewing rates vs. capacity drops which counted after).
        # drain()'s delayed-release re-entry passes _count_rx=False — its
        # arrival was counted at the original push.
        if _count_rx:
            self.rx_pkts[r, t] += 1
            self.rx_bytes[r, t] += pkt.size
        if self.fault is not None and not _fault_ok:
            verdict = self.fault.on_packet(pkt, self._fault_tick)
            if verdict == "drop":
                self.dropped_fault += 1
                return False
            if verdict == "delay":
                return False  # not a drop: re-enters via drain() take_due
            if verdict == "dup":
                self.push(pkt, t_rx, _fault_ok=True)
            # Flood mode: stage seeded extra copies of this packet —
            # reproducible offered-load multiplication for overload tests.
            extra = self.fault.flood_copies(pkt.room)
            for _ in range(extra):
                self.push(pkt, t_rx, _fault_ok=True)
        if self._police_rate > 0.0 and (
            self._police_video is None or self._police_video[r, t]
        ):
            if self._police_tokens[r, t] < 1.0:
                self.dropped_policed += 1
                return False
            self._police_tokens[r, t] -= 1.0
        k = self._count[r, t]
        if k >= self.dims.pkts:
            self.dropped_capacity += 1
            return False
        self._count[r, t] = k + 1
        self.sn[r, t, k] = pkt.sn & 0xFFFF
        self.ts[r, t, k] = _wrap_i32(pkt.ts)
        self.layer[r, t, k] = pkt.layer
        self.temporal[r, t, k] = pkt.temporal
        self.keyframe[r, t, k] = pkt.keyframe
        self.layer_sync[r, t, k] = pkt.layer_sync
        self.begin_pic[r, t, k] = pkt.begin_pic
        self.end_frame[r, t, k] = pkt.marker
        self.pid[r, t, k] = pkt.pid
        self.tl0[r, t, k] = pkt.tl0
        self.keyidx[r, t, k] = pkt.keyidx
        self.size[r, t, k] = pkt.size
        self.frame_ms[r, t, k] = pkt.frame_ms
        self.audio_level[r, t, k] = pkt.audio_level
        self.arrival_rtp[r, t, k] = _wrap_i32(pkt.arrival_rtp)
        self.ts_jump[r, t, k] = -1 if pkt.ts_aligned else 3000
        self.valid[r, t, k] = True
        if pkt.payload:
            self.pay_off[r, t, k] = len(self._slab)
            self.pay_len[r, t, k] = len(pkt.payload)
            self.marker[r, t, k] = pkt.marker
            self._slab += pkt.payload
        self.t_arr[r, t, k] = t_rx
        if self.on_put is not None:
            self.on_put(np.array([r], np.int64), np.array([t], np.int64),
                        np.array([k], np.int64))
        return True

    def extract_row(self, room: int) -> list:
        """Remove and return one row's staged-but-undrained packets, in
        arrival order per track. Migration freeze calls this right after
        freezing the row: drain() has no frozen filter (push-time only),
        so packets already staged would otherwise enter the device AFTER
        the snapshot and race the source teardown. Extracted packets ride
        the freeze bridge instead; their rx accounting is reversed here
        because the replay path re-counts them on whichever node wins."""
        out: list = []
        counts = self._count[room]
        if not counts.any():
            return out
        for t in np.nonzero(counts)[0]:
            for k in range(int(counts[t])):
                if not self.valid[room, t, k]:
                    continue
                ps = int(self.pay_off[room, t, k])
                pl = int(self.pay_len[room, t, k])
                out.append(PacketIn(
                    room=int(room), track=int(t),
                    sn=int(self.sn[room, t, k]),
                    ts=int(self.ts[room, t, k]),
                    size=int(self.size[room, t, k]),
                    payload=bytes(self._slab[ps:ps + pl]) if ps >= 0 else b"",
                    marker=bool(self.end_frame[room, t, k]),
                    layer=int(self.layer[room, t, k]),
                    temporal=int(self.temporal[room, t, k]),
                    keyframe=bool(self.keyframe[room, t, k]),
                    layer_sync=bool(self.layer_sync[room, t, k]),
                    begin_pic=bool(self.begin_pic[room, t, k]),
                    pid=int(self.pid[room, t, k]),
                    tl0=int(self.tl0[room, t, k]),
                    keyidx=int(self.keyidx[room, t, k]),
                    frame_ms=int(self.frame_ms[room, t, k]),
                    audio_level=int(self.audio_level[room, t, k]),
                    arrival_rtp=int(self.arrival_rtp[room, t, k]),
                    ts_aligned=bool(self.ts_jump[room, t, k] == -1),
                ))
                self.rx_pkts[room, t] -= 1
                self.rx_bytes[room, t] -= int(self.size[room, t, k])
        self._count[room] = 0
        self.valid[room] = False
        self.pay_off[room] = -1
        self.pay_len[room] = 0
        return out

    def push_batch(
        self, room, track, layer, sn, ts, ts_aligned, temporal, keyframe,
        layer_sync, begin_pic, marker, pid, tl0, keyidx, size, frame_ms,
        audio_level, arrival_rtp, pay_start, pay_length, blob,
        dd_start=None, dd_length=None, dd_version=None, end_frame=None,
        t_rx: float = 0.0,
    ) -> int:
        """Vectorized push: stage a whole receive batch with numpy group
        math instead of one Python call per packet (the batch half of the
        native-parse → tensor-staging path this module documents). All
        args are equal-length arrays; payload bytes are sliced out of
        `blob` by (pay_start, pay_length). Returns packets staged."""
        self.last_put = None
        n = len(room)
        if n == 0:
            return 0
        if self.fault is not None:
            # Chaos path: route the batch through the per-packet seam so
            # the seeded rng sees every packet in arrival order (the
            # reproducibility contract). Slow is fine — fault runs are
            # tests/soaks, never the default config. DD extension bytes
            # are not re-staged on this path (chaos runs don't assert SVC
            # descriptor passthrough).
            staged = 0
            for i in range(n):
                ps, pl = int(pay_start[i]), int(pay_length[i])
                staged += self.push(
                    PacketIn(
                        room=int(room[i]), track=int(track[i]),
                        sn=int(sn[i]), ts=int(ts[i]), size=int(size[i]),
                        payload=bytes(blob[ps:ps + pl]) if ps >= 0 else b"",
                        marker=bool(marker[i]), layer=int(layer[i]),
                        temporal=int(temporal[i]), keyframe=bool(keyframe[i]),
                        layer_sync=bool(layer_sync[i]),
                        begin_pic=bool(begin_pic[i]), pid=int(pid[i]),
                        tl0=int(tl0[i]), keyidx=int(keyidx[i]),
                        frame_ms=int(frame_ms[i]),
                        audio_level=int(audio_level[i]),
                        arrival_rtp=int(arrival_rtp[i]),
                        ts_aligned=bool(ts_aligned[i]),
                    ),
                    t_rx,
                )
            return staged
        if dd_start is None:
            dd_start = np.full(n, -1, np.int64)
            dd_length = np.zeros(n, np.int32)
        if dd_version is None:
            dd_version = np.full(n, -1, np.int32)
        if end_frame is None:
            end_frame = marker
        if self.frozen_rows:
            keep0 = ~np.isin(room, list(self.frozen_rows))
            if not keep0.all():
                if self.freeze_sinks:
                    # Feed frozen-row packets to their bridge sink (same
                    # capture the scalar path does) before filtering.
                    for i in np.nonzero(~keep0)[0]:
                        sink = self.freeze_sinks.get(int(room[i]))
                        if sink is None:
                            continue
                        ps, pl = int(pay_start[i]), int(pay_length[i])
                        sink(PacketIn(
                            room=int(room[i]), track=int(track[i]),
                            sn=int(sn[i]), ts=int(ts[i]), size=int(size[i]),
                            payload=bytes(blob[ps:ps + pl]) if ps >= 0 else b"",
                            marker=bool(marker[i]), layer=int(layer[i]),
                            temporal=int(temporal[i]),
                            keyframe=bool(keyframe[i]),
                            layer_sync=bool(layer_sync[i]),
                            begin_pic=bool(begin_pic[i]), pid=int(pid[i]),
                            tl0=int(tl0[i]), keyidx=int(keyidx[i]),
                            frame_ms=int(frame_ms[i]),
                            audio_level=int(audio_level[i]),
                            arrival_rtp=int(arrival_rtp[i]),
                            ts_aligned=bool(ts_aligned[i]),
                        ))
                (room, track, layer, sn, ts, ts_aligned, temporal, keyframe,
                 layer_sync, begin_pic, marker, pid, tl0, keyidx, size,
                 frame_ms, audio_level, arrival_rtp, pay_start, pay_length,
                 dd_start, dd_length, dd_version, end_frame) = (
                    a[keep0] for a in (
                        room, track, layer, sn, ts, ts_aligned, temporal,
                        keyframe, layer_sync, begin_pic, marker, pid, tl0,
                        keyidx, size, frame_ms, audio_level, arrival_rtp,
                        pay_start, pay_length, dd_start, dd_length,
                        dd_version, end_frame)
                )
                n = len(room)
                if n == 0:
                    return 0
        T, K = self.dims.tracks, self.dims.pkts
        flat_rt = room.astype(np.int64) * T + track
        # Receive accounting (includes packets a full tick then drops —
        # they arrived on the wire either way).
        np.add.at(self.rx_pkts.reshape(-1), flat_rt, 1)
        np.add.at(self.rx_bytes.reshape(-1), flat_rt, size.astype(np.int64))
        # Arrival-order rank within each (room, track) group.
        order, sorted_rt, grp_start, sizes, ranks = self._group_ranks(flat_rt, n)
        if self._police_rate > 0.0:
            # Vectorized token buckets (same semantics as the scalar
            # path): each group's first floor(tokens) non-exempt packets
            # are admitted this batch; the rest are policed. Audio
            # (is_video False) bypasses entirely.
            tok = self._police_tokens.reshape(-1)
            exempt = (
                np.zeros(n, bool) if self._police_video is None
                else ~self._police_video.reshape(-1)[flat_rt]
            )
            quota = np.floor(tok[flat_rt]).astype(np.int64)
            pol = ~exempt & (ranks >= quota)
            adm = ~exempt & ~pol
            if adm.any():
                np.subtract.at(tok, flat_rt[adm], 1.0)
            n_pol = int(pol.sum())
            if n_pol:
                self.dropped_policed += n_pol
                keep1 = ~pol
                (room, track, layer, sn, ts, ts_aligned, temporal, keyframe,
                 layer_sync, begin_pic, marker, pid, tl0, keyidx, size,
                 frame_ms, audio_level, arrival_rtp, pay_start, pay_length,
                 dd_start, dd_length, dd_version, end_frame) = (
                    a[keep1] for a in (
                        room, track, layer, sn, ts, ts_aligned, temporal,
                        keyframe, layer_sync, begin_pic, marker, pid, tl0,
                        keyidx, size, frame_ms, audio_level, arrival_rtp,
                        pay_start, pay_length, dd_start, dd_length,
                        dd_version, end_frame)
                )
                n = len(room)
                if n == 0:
                    return 0
                flat_rt = room.astype(np.int64) * T + track
                order, sorted_rt, grp_start, sizes, ranks = self._group_ranks(
                    flat_rt, n
                )
        base = self._count.reshape(-1)[flat_rt]
        k = base + ranks
        keep = k < K
        dropped = n - int(keep.sum())
        if dropped:
            self.dropped_capacity += dropped
            (room, track, k, layer, sn, ts, ts_aligned, temporal, keyframe,
             layer_sync, begin_pic, end_frame, marker, pid, tl0, keyidx,
             size, frame_ms, audio_level, arrival_rtp, pay_start,
             pay_length, dd_start, dd_length, dd_version) = (
                a[keep] for a in (
                    room, track, k, layer, sn, ts, ts_aligned, temporal,
                    keyframe, layer_sync, begin_pic, end_frame, marker, pid,
                    tl0, keyidx, size, frame_ms, audio_level, arrival_rtp,
                    pay_start, pay_length, dd_start, dd_length, dd_version)
            )
        # else: the common no-overflow tick — no masked copies at all.
        r_, t_, k_ = room, track, k
        # One flat index shared by all the field scatters below — the
        # repeated 3-D index math would otherwise dominate the writes.
        fi = (r_.astype(np.int64) * T + t_) * K + k_

        def put(arr, vals):
            arr.reshape(-1)[fi] = vals

        put(self.sn, sn & 0xFFFF)
        put(self.ts, ts.astype(np.int64).astype(np.int32))
        put(self.layer, layer)
        put(self.temporal, temporal)
        put(self.keyframe, keyframe)
        put(self.layer_sync, layer_sync)
        put(self.begin_pic, begin_pic)
        put(self.end_frame, end_frame)
        put(self.pid, pid)
        put(self.tl0, tl0)
        put(self.keyidx, keyidx)
        put(self.size, size)
        put(self.frame_ms, frame_ms)
        put(self.audio_level, audio_level)
        put(self.arrival_rtp, arrival_rtp.astype(np.int64).astype(np.int32))
        put(self.ts_jump, np.where(ts_aligned, -1, 3000))
        put(self.valid, True)
        # Payload slab: one join in kept order (arrays already masked
        # above when the tick overflowed).
        lens = pay_length.astype(np.int64)
        starts = pay_start.astype(np.int64)
        offs = len(self._slab) + np.r_[np.int64(0), np.cumsum(lens[:-1])]
        # Header-only packets keep pay_off = -1 (push() semantics): they
        # feed stats but must not emit empty datagrams on egress.
        put(self.pay_off, np.where(lens > 0, offs, -1))
        put(self.pay_len, lens)
        put(self.marker, marker)
        put(self.t_arr, t_rx)
        blob_arr = (
            blob if isinstance(blob, np.ndarray)
            else np.frombuffer(blob, np.uint8)
        )
        self._slab += _gather_ranges(blob_arr, starts, lens)
        # DD extension bytes (SVC): appended after the payload bytes.
        dmask = dd_start >= 0
        if dmask.any():
            dstarts = dd_start[dmask].astype(np.int64)
            dlens = dd_length[dmask].astype(np.int64)
            doffs = len(self._slab) + np.r_[np.int64(0), np.cumsum(dlens[:-1])]
            didx = (r_[dmask], t_[dmask], k_[dmask])
            self.dd_off[didx] = doffs
            self.dd_len[didx] = dlens
            self.dd_ver[didx] = dd_version[dmask]
            self._slab += _gather_ranges(blob_arr, dstarts, dlens)
        # New per-group counts (capped at K).
        uniq_rt = sorted_rt[grp_start]
        self._count.reshape(-1)[uniq_rt] = np.minimum(
            K, base[order][grp_start] + sizes
        )
        self.last_put = (r_, t_, k_)
        if self.on_put is not None:
            self.on_put(r_, t_, k_)
        return len(r_)

    def push_twcc_feedback(
        self, room: int, sub: int, delay_sum_ms: float, n_deltas: int,
        acked_bytes: int, span_ms: float,
    ) -> None:
        """Accumulate one TWCC feedback frame's reductions (udp.py parses
        the frame and matches its acks against the send-time ring)."""
        self._fb_delay_sum[room, sub] += delay_sum_ms
        self._fb_count[room, sub] += max(n_deltas, 0)
        self._fb_bytes[room, sub] += acked_bytes
        self._fb_span_ms[room, sub] += span_ms

    def push_feedback(
        self, room: int, sub: int, estimate: float | None = None, nacks: int = 0
    ) -> None:
        """Stage subscriber feedback (TWCC/REMB estimate sample, NACK count)."""
        if estimate is not None:
            self._estimate[room, sub] = estimate
            self._estimate_valid[room, sub] = True
        if nacks:
            self._nacks[room, sub] += nacks

    def push_nack(self, room: int, sub: int, track: int, sns) -> int:
        """Count NACKed SNs into the BWE loss channel (nacktracker.go ratio
        semantics). Resolution/replay is host-side at RTCP time
        (plane_runtime.HostSequencer.resolve) — not staged for the device.

        Deduped per (sn, track) ACROSS the tick and hard-capped at
        NACK_COUNT_CAP per (room, sub) per tick, so repeated/overlapping
        feedback packets cannot inflate the loss signal without bound."""
        staged = 0
        for sn in sns:
            key = (room, sub, sn & 0xFFFF, track)
            if key in self._nack_seen:
                continue
            # Dedup BEFORE the cap check so re-sent duplicates above the
            # cap don't inflate the overflow stat.
            self._nack_seen.add(key)
            if self._nack_tick_cnt[room, sub] >= NACK_COUNT_CAP:
                self.nack_overflow += 1
                continue
            self._nack_tick_cnt[room, sub] += 1
            staged += 1
        if staged:
            self._nacks[room, sub] += staged
        return staged

    def set_rtt(self, room: int, sub: int, rtt_ms: int) -> None:
        """RR-derived round-trip time (replay throttle input)."""
        self.rtt_ms[room, sub] = max(1, min(int(rtt_ms), 10_000))

    def _reorder_dedup(self) -> None:
        """Sort each (room, track)'s staged packets by (layer, SN) and drop
        same-SN duplicates — the jitter-ordering half of buffer.Buffer
        (buffer.go Write reorder + duplicate detection). Within-tick only:
        packets are in flight for one tick, so this IS the jitter window."""
        if not (self._count > 1).any():
            return
        R, T, K = self.sn.shape
        # Per-(r, t, layer) SN unwrap: rel SN relative to the first staged
        # packet of the same layer (simulcast layers are separate SN spaces).
        rel = np.zeros((R, T, K), np.int32)
        for l in range(int(self.layer.max()) + 1 if self.valid.any() else 0):
            m = self.valid & (self.layer == l)
            if not m.any():
                continue
            first = np.argmax(m, axis=-1)                       # [R, T]
            base = np.take_along_axis(self.sn, first[:, :, None], axis=-1)
            d = (self.sn - base) & 0xFFFF
            rel = np.where(m, np.where(d >= 0x8000, d - 0x10000, d), rel)
        key = np.where(
            self.valid, self.layer.astype(np.int64) * (1 << 20) + rel, 1 << 40
        )
        order = np.argsort(key, axis=-1, kind="stable")
        if (order == np.arange(K)).all():
            pass  # already ordered; still run dedup below
        else:
            for arr in (
                self.sn, self.ts, self.layer, self.temporal, self.keyframe,
                self.layer_sync, self.begin_pic, self.end_frame, self.pid,
                self.tl0, self.keyidx, self.size, self.frame_ms,
                self.audio_level, self.arrival_rtp, self.ts_jump, self.valid,
                self.pay_off, self.pay_len, self.marker,
            ):
                arr[...] = np.take_along_axis(arr, order, axis=-1)
        dup = np.zeros_like(self.valid)
        dup[:, :, 1:] = (
            self.valid[:, :, 1:]
            & self.valid[:, :, :-1]
            & (self.sn[:, :, 1:] == self.sn[:, :, :-1])
            & (self.layer[:, :, 1:] == self.layer[:, :, :-1])
        )
        n = int(dup.sum())
        if n:
            self.valid[dup] = False
            self.dupes += n

    def drain(
        self,
        roll_quality: bool = False,
        tick_index: int = 0,
        pad_num=None,
        pad_track=None,
        reuse_fields: bool = False,
    ) -> tuple[plane.TickInputs, PayloadSlab]:
        """Snapshot this tick's tensors, then flip to the other staging
        set so the next tick's pushes land in a fresh buffer.

        Fields with post-drain lifetimes are ALWAYS copied: the munger
        columns (sn/ts/ts_jump/pid/tl0/keyidx/begin_pic/valid) are read
        at fan-out time — up to a full pipeline window later — and the
        PayloadSlab is retained for the SLAB_WINDOW RTX history. With
        `reuse_fields=True` (the pipelined runtime's staging path, which
        packs the device arrays synchronously right after this returns),
        the remaining pack-only fields are handed out as zero-copy views
        of the retiring set; they are dead once packed, and the set is
        recycled at the next flip. Direct callers (tests, mesh staging)
        keep the default full-copy semantics."""
        if self._police_rate > 0.0:
            # Token refill: once per tick, clipped at the burst ceiling.
            np.minimum(
                self._police_tokens
                + self._police_rate * (self.tick_ms / 1000.0),
                self._police_burst,
                out=self._police_tokens,
            )
        if self.fault is not None:
            # Release held-back (delayed) packets whose tick has arrived:
            # they stage now, so they ride THIS tick's tensors. Their
            # arrival was rx-counted at the original push.
            for pkt in self.fault.take_due(tick_index):
                self.push(pkt, _fault_ok=True, _count_rx=False)
            self._fault_tick = tick_index + 1
        self._reorder_dedup()
        R, T, K, S = self.dims
        if pad_num is None:
            pad_num = np.zeros((R, S), np.int32)
        if pad_track is None:
            pad_track = np.full((R, S), -1, np.int32)
        cp = (lambda a: a) if reuse_fields else (lambda a: a.copy())
        inp = plane.TickInputs(
            sn=self.sn.copy(), ts=self.ts.copy(), layer=cp(self.layer),
            temporal=cp(self.temporal), keyframe=cp(self.keyframe),
            layer_sync=cp(self.layer_sync), begin_pic=self.begin_pic.copy(),
            end_frame=cp(self.end_frame),
            pid=self.pid.copy(), tl0=self.tl0.copy(), keyidx=self.keyidx.copy(),
            size=cp(self.size), frame_ms=cp(self.frame_ms),
            audio_level=cp(self.audio_level),
            arrival_rtp=cp(self.arrival_rtp), ts_jump=self.ts_jump.copy(),
            valid=self.valid.copy(),
            estimate=self._estimate.copy(),
            estimate_valid=self._estimate_valid.copy(),
            nacks=self._nacks.copy(),
            pub_rtt_ms=np.where(
                self.track_pub_sub >= 0,
                np.take_along_axis(
                    self.rtt_ms, np.clip(self.track_pub_sub, 0, S - 1), axis=1
                ),
                0,
            ).astype(np.float32),
            fb_delay_ms=np.where(
                self._fb_count > 0,
                self._fb_delay_sum / np.maximum(self._fb_count, 1),
                0.0,
            ).astype(np.float32),
            fb_recv_bps=np.where(
                self._fb_span_ms > 0,
                self._fb_bytes * 8000.0 / np.maximum(self._fb_span_ms, 1e-3),
                0.0,
            ).astype(np.float32),
            fb_valid=self._fb_count > 0,
            fb_enabled=self.fb_enabled.copy(),
            sub_reset=self.sub_reset.copy(),
            pad_num=np.asarray(pad_num, np.int32),
            pad_track=np.asarray(pad_track, np.int32),
            tick_ms=np.int32(self.tick_ms),
            roll_quality=np.int32(1 if roll_quality else 0),
        )
        payloads = PayloadSlab(
            data=bytes(self._slab),
            off=self.pay_off.copy(),
            length=self.pay_len.copy(),
            marker=self.marker.copy(),
            dd_off=self.dd_off.copy(),
            dd_len=self.dd_len.copy(),
            dd_ver=self.dd_ver.copy(),
            t_arr=self.t_arr.copy(),
        )
        # Retire the drained set (its reset is deferred to scrub_retired)
        # and flip pushes onto the other one — scrubbing it inline only if
        # the deferred scrub never ran.
        self._sets[self._active].needs_scrub = True
        nxt = self._sets[1 - self._active]
        if nxt.needs_scrub:
            nxt.scrub()
        self._active = 1 - self._active
        self._bind(nxt)
        self._estimate_valid[:] = False
        self._nacks[:] = 0.0
        self._fb_delay_sum[:] = 0.0
        self._fb_count[:] = 0
        self._fb_bytes[:] = 0
        self._fb_span_ms[:] = 0.0
        self.sub_reset[:] = False
        self._nack_seen.clear()
        self._nack_tick_cnt[:] = 0
        return inp, payloads
