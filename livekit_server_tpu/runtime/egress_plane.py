"""Sharded native egress plane: per-core fan-out of the host packet walk.

The device side of a tick resolves ~R*T*K*S forwarding decisions in one
fused step; the host side then has to *realize* them as datagrams — munge
application, assembly, AES-GCM seal, socket writes. Done as one native
call on one thread, that walk is the number that caps users per node
(BASELINE.md round 5: the chip emits ~1000x more decisions/s than the
host path drains). This module is the orchestrator that cuts the walk
into per-core shards and keeps every byte of output bit-identical to the
single-threaded path:

- **Room-aligned shards.** Egress entries arrive destination-major
  (room, sub, track, k). Shards are contiguous entry ranges cut only on
  room boundaries: munger state rows are indexed [room, track, sub], so
  whole-room ownership makes every state write (munge) and every
  canonical-cache slot (send) private to one worker — no locks on the
  per-tick path, and migration room freezes/snapshots keep working
  unchanged because a room's lanes never straddle workers.
- **Exact prefix-sum output bases.** The native walkers count before they
  write (native/munge.cpp count_range, udp.py's cumsum of out_len), so
  shard outputs land at exact offsets and the concatenated result is
  byte-identical regardless of shard count (pinned by
  tests/test_egress_plane.py).
- **Multicast-shaped assembly** (P3FA, PAPERS.md: treat N-subscriber
  delivery as constrained multicast rather than N unicasts). Entries of
  one (room, track, packet) group share everything except a 12-byte
  header and the VP8 picture-id chain: the canonical datagram — header
  template + extensions + payload — is gathered ONCE per group into a
  per-worker hot scratch slab, and each subscriber's copy is a single
  memcpy + header patch from it (native/egress.cpp CanonSlot). The AEAD
  seal itself still runs per datagram: every sealed frame carries a
  unique per-session counter, and that counter IS the GCM nonce — "seal
  once, retag per subscriber" would reuse nonces across distinct
  ciphertexts, which breaks GCM catastrophically. What the multicast
  shape removes is the per-subscriber gather/extension-build work; the
  per-byte AES cost stays and is paid from L1-hot canonical bytes.

The plane object itself is thin: it plans shard cuts (numpy searchsorted
on the sorted room column), derives canonical-group slots, and scrapes
per-shard timing/throughput out of the native calls for telemetry
(/debug/egress, livekit_host_egress_pps). One instance is shared by
PlaneRuntime (munge sharding) and UDPMediaTransport (send sharding).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any

import numpy as np

# Above this many (track, packet) slots the per-worker canonical scratch
# (slots * 2048 B) stops fitting hot cache and grouping is disabled.
MAX_GROUP_SLOTS = 512

# Decay for the published packets-per-second EMA (per observe interval).
_PPS_ALPHA = 0.3


def resolve_shards(configured: int) -> int:
    """0 = auto: one shard per core, capped at 8 (the native pool caps at
    16; past 8 the seal walk is memory-bound and extra shards only add
    barrier latency)."""
    if configured > 0:
        return min(configured, 16)
    return max(1, min(8, os.cpu_count() or 1))


class EgressPlane:
    """Shard planner + stats collector for the native egress/munge path.

    Thread-safety: plan_* methods are pure; record_* methods take the
    stats lock (the paced send path calls record_send from a worker
    thread while observe() reads from the event loop).
    """

    def __init__(self, shards: int = 0, multicast_seal: bool = True):
        self.shards = resolve_shards(shards)
        self.multicast_seal = multicast_seal
        self._lock = threading.Lock()
        # Cumulative counters (monotonic; telemetry derives rates).
        self.stats: dict[str, float] = {
            "ticks": 0, "entries": 0, "datagrams": 0, "grouped_entries": 0,
            "send_ns": 0, "munge_ns": 0, "munge_entries": 0,
            "express_datagrams": 0, "express_ns": 0,
        }
        self.shard_sent_total = np.zeros(self.shards, np.int64)
        self.shard_ns_total = np.zeros(self.shards, np.int64)
        self.munge_shard_ns_total = np.zeros(self.shards, np.int64)
        # Last-tick snapshots (recent_ticks / debug).
        self.last_send: dict[str, Any] = {}
        self.last_munge: dict[str, Any] = {}
        self._pps_ema = 0.0
        self._ema_entries = 0.0
        self._ema_ns = 0.0
        # Express-lane sends land between ticks; record_express accumulates
        # them here and record_send folds them into the next tick's EMA
        # sample so host_egress_pps covers BOTH tiers.
        self._express_pending_dgrams = 0
        self._express_pending_ns = 0
        self._warmed = False

    # -- shard planning ---------------------------------------------------

    def room_plan(self, n_rooms: int) -> tuple[np.ndarray, np.ndarray]:
        """Cut [0, n_rooms) into up to `shards` contiguous room ranges for
        the munge walk. Rooms are the unit of state ownership, so this is
        the only legal cut axis."""
        w = min(self.shards, n_rooms) or 1
        edges = (np.arange(w + 1, dtype=np.int64) * n_rooms) // w
        return edges[:-1].astype(np.int32), edges[1:].astype(np.int32)

    def entry_plan(self, rooms_sorted: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Cut a room-ascending entry column into up to `shards`
        room-aligned ranges balanced by entry count. Returns (lo, hi)
        int64 arrays; every cut lands on the first entry of a room so
        canonical groups never straddle workers."""
        n = len(rooms_sorted)
        w = min(self.shards, n) or 1
        if w == 1:
            return (np.zeros(1, np.int64), np.array([n], np.int64))
        targets = (np.arange(1, w, dtype=np.int64) * n) // w
        # Snap each target cut back to its room's first entry.
        cuts = np.searchsorted(rooms_sorted, rooms_sorted[targets], side="left")
        bounds = np.unique(np.concatenate(([0], cuts, [n])))
        return bounds[:-1].astype(np.int64), bounds[1:].astype(np.int64)

    def group_slots(
        self, flat_rtk_sorted: np.ndarray, tracks: np.ndarray,
        ks: np.ndarray, n_tracks: int, n_k: int,
    ) -> tuple[np.ndarray | None, int]:
        """Canonical-cache slot per entry: slot = track * K + k for
        entries whose (room, track, packet) group has >= 2 members (the
        canonical is worth staging only when reused), -1 otherwise.
        `flat_rtk_sorted` is the entries' room*T*K + slot composite —
        already computed by the udp staging path. Returns (None, 0) when
        grouping is off or the slot space is too large to scratch."""
        slots = n_tracks * n_k
        if not self.multicast_seal or slots > MAX_GROUP_SLOTS:
            return None, 0
        n = len(flat_rtk_sorted)
        if n == 0:
            return None, 0
        # Group sizes via bincount on the composite key, bounded: offset
        # to the min key so the count array spans only the rooms present.
        lo = int(flat_rtk_sorted.min())
        span = int(flat_rtk_sorted.max()) - lo + 1
        if span > max(4 * n, 1 << 20):
            return None, 0
        counts = np.bincount(flat_rtk_sorted - lo, minlength=span)
        grouped = counts[flat_rtk_sorted - lo] > 1
        grp = np.where(
            grouped, tracks.astype(np.int32) * n_k + ks.astype(np.int32), -1
        ).astype(np.int32)
        return grp, slots

    # -- stats ------------------------------------------------------------

    def warm(self) -> None:
        """Pre-spawn the native worker pool so the first real tick does
        not pay thread creation."""
        if self._warmed:
            return
        self._warmed = True
        if self.shards > 1:
            from livekit_server_tpu import native

            if native.egress is not None:
                native.egress.pool_ensure(self.shards)

    def record_send(self, n_entries: int, n_grouped: int, sent: int,
                    shard_lo, shard_hi, shard_sent, shard_built,
                    shard_ns) -> None:
        ns = int(np.max(shard_ns)) if len(shard_ns) else 0  # critical path
        with self._lock:
            st = self.stats
            st["ticks"] += 1
            st["entries"] += n_entries
            st["grouped_entries"] += n_grouped
            st["datagrams"] += sent
            st["send_ns"] += ns
            w = len(shard_sent)
            self.shard_sent_total[:w] += shard_sent
            self.shard_ns_total[:w] += shard_ns
            # Fold the express sends of the window that just closed into
            # this tick's EMA sample (both tiers' work over both tiers'
            # wall), then reset the accumulators.
            ema_n = n_entries + self._express_pending_dgrams
            ema_ns = ns + self._express_pending_ns
            self._express_pending_dgrams = 0
            self._express_pending_ns = 0
            self._ema_entries = (
                _PPS_ALPHA * ema_n + (1 - _PPS_ALPHA) * self._ema_entries
            )
            self._ema_ns = _PPS_ALPHA * max(ema_ns, 1) + (1 - _PPS_ALPHA) * self._ema_ns
            if self._ema_ns > 0:
                self._pps_ema = self._ema_entries / (self._ema_ns * 1e-9)
            self.last_send = {
                "entries": int(n_entries),
                "grouped": int(n_grouped),
                "sent": int(sent),
                "shards": [
                    {
                        "range": [int(a), int(b)],
                        "sent": int(s),
                        "built": int(bu),
                        "ms": round(int(nn) / 1e6, 3),
                    }
                    for a, b, s, bu, nn in zip(
                        shard_lo, shard_hi, shard_sent, shard_built, shard_ns
                    )
                ],
            }

    def record_express(self, sent: int, ns: int) -> None:
        """Express-lane send accounting (udp._send_express): datagrams +
        send wall, folded into the pps EMA at the next tick's record_send
        so the gauge reflects both tiers."""
        with self._lock:
            self.stats["express_datagrams"] += sent
            self.stats["express_ns"] += ns
            self._express_pending_dgrams += sent
            self._express_pending_ns += ns

    def record_munge(self, shard_counts, shard_ns) -> None:
        with self._lock:
            self.stats["munge_ns"] += int(np.max(shard_ns)) if len(shard_ns) else 0
            self.stats["munge_entries"] += int(np.sum(shard_counts))
            w = len(shard_ns)
            self.munge_shard_ns_total[:w] += shard_ns
            self.last_munge = {
                "counts": [int(c) for c in shard_counts],
                "ms": [round(int(n) / 1e6, 3) for n in shard_ns],
            }

    @property
    def host_egress_pps(self) -> float:
        """Datagrams/s through the native send walk, EMA over recent
        ticks; the walk wall time is the max shard (critical path)."""
        return self._pps_ema

    def observe(self) -> dict[str, Any]:
        """Snapshot for /debug/egress and the telemetry exporter."""
        with self._lock:
            send_s = self.stats["send_ns"] * 1e-9
            munge_s = self.stats["munge_ns"] * 1e-9
            return {
                "shards": self.shards,
                "multicast_seal": self.multicast_seal,
                "host_egress_pps": round(self._pps_ema, 1),
                "ticks": int(self.stats["ticks"]),
                "entries": int(self.stats["entries"]),
                "grouped_entries": int(self.stats["grouped_entries"]),
                "datagrams": int(self.stats["datagrams"]),
                "send_ms_total": round(send_s * 1000.0, 3),
                "munge_ms_total": round(munge_s * 1000.0, 3),
                "munge_entries": int(self.stats["munge_entries"]),
                "express_datagrams": int(self.stats["express_datagrams"]),
                "express_ms_total": round(
                    self.stats["express_ns"] / 1e6, 3
                ),
                "shard_sent": [int(x) for x in self.shard_sent_total],
                "shard_send_ms": [
                    round(int(x) / 1e6, 3) for x in self.shard_ns_total
                ],
                "shard_munge_ms": [
                    round(int(x) / 1e6, 3) for x in self.munge_shard_ns_total
                ],
                "last_send": self.last_send,
                "last_munge": self.last_munge,
            }


def bench_plane(
    plane: EgressPlane,
    n_rooms: int = 64,
    subs_per_room: int = 16,
    tracks: int = 2,
    pkts: int = 4,
    payload_len: int = 1100,
    sealed: bool = True,
    seconds: float = 2.0,
    fd: int = -1,
) -> dict[str, Any]:
    """Pure egress-plane microbench: drive the native sharded walk on a
    synthetic wire-shaped batch (no device step, no ingest) and measure
    datagrams/s through assemble+seal(+send when fd >= 0). This isolates
    the number the plane exists to move — the host packet walk — from
    tick scheduling; bench.py's wire sections measure the end-to-end
    version of the same number."""
    from livekit_server_tpu import native

    if native.egress is None:
        return {"error": "native egress unavailable"}
    rng = np.random.default_rng(7)
    n = n_rooms * subs_per_room * tracks * pkts
    slab = rng.integers(0, 256, pkts * payload_len, np.uint8)
    # Destination-major (room, sub, track, k) — the udp staging order.
    rr = np.repeat(np.arange(n_rooms, dtype=np.int32), subs_per_room * tracks * pkts)
    ss = np.tile(
        np.repeat(np.arange(subs_per_room, dtype=np.int32), tracks * pkts), n_rooms
    )
    tt = np.tile(np.repeat(np.arange(tracks, dtype=np.int32), pkts),
                 n_rooms * subs_per_room)
    kk = np.tile(np.arange(pkts, dtype=np.int32), n_rooms * subs_per_room * tracks)
    slot = tt * pkts + kk
    flat_rtk = rr.astype(np.int64) * (tracks * pkts) + slot
    grp, grp_slots = plane.group_slots(flat_rtk, tt, kk, tracks, pkts)
    if grp is None:
        grp = np.full(n, -1, np.int32)
        grp_slots = 0
    lo, hi = plane.entry_plan(rr)
    n_sess = n_rooms * subs_per_room
    args = dict(
        shard_lo=lo, shard_hi=hi, slab=slab,
        pay_off=(kk.astype(np.int64) * payload_len),
        pay_len=np.full(n, payload_len, np.int32),
        marker=(kk == pkts - 1).astype(np.uint8),
        pt=np.full(n, 96, np.uint8), vp8=np.ones(n, np.uint8),
        sn=(np.arange(n) & 0xFFFF).astype(np.uint16),
        ts=(kk.astype(np.uint32) * 3000),
        ssrc=(rr.astype(np.uint32) << 16) | ss.astype(np.uint32),
        pid=np.full(n, 77, np.int32), tl0=np.full(n, 3, np.int32),
        kidx=np.full(n, 1, np.int32),
        ip=np.full(n, 0x7F000001, np.uint32),
        port=np.full(n, 50555, np.uint16),
        seal=np.full(n, 1 if sealed else 0, np.uint8),
        key_idx=(rr * subs_per_room + ss).astype(np.int32),
        keys=rng.integers(0, 256, (n_sess, 16), np.uint8),
        key_ids=np.arange(1, n_sess + 1, dtype=np.uint32),
        rooms=rr, grp=grp, grp_slots=grp_slots,
    )
    plane.warm()
    counters = np.zeros(n, np.uint64)
    ctr_base = 0
    iters = 0
    datagrams = 0
    t0 = time.perf_counter()
    deadline = t0 + seconds
    while time.perf_counter() < deadline:
        # Fresh counters every pass: nonces must never repeat per session.
        counters[:] = np.uint64(ctr_base) + kk.astype(np.uint64)
        ctr_base += pkts
        out, out_off, out_len, sent, s_sent, s_built, s_ns = (
            native.egress.send_sharded(fd=fd, counters=counters, **args)
        )
        plane.record_send(n, int((grp >= 0).sum()), sent, lo, hi,
                          s_sent, s_built, s_ns)
        datagrams += sent
        iters += 1
    wall = time.perf_counter() - t0
    return {
        "entries_per_call": n,
        "iters": iters,
        "datagrams": datagrams,
        "wall_s": round(wall, 3),
        "pps": round(datagrams / wall, 1) if wall > 0 else 0.0,
        "shards": plane.shards,
        "grouped_pct": round(100.0 * float((grp >= 0).mean()), 1),
        "sealed": sealed,
        "bytes_per_dgram": payload_len + 12 + (30 if sealed else 0),
    }


def bench_plane_scaling(
    payload_len: int = 1100,
    sealed: bool = True,
    seconds_per_point: float = 1.5,
    max_shards: int = 0,
    **shape: Any,
) -> dict[str, Any]:
    """pps vs shard count on THIS host: one bench_plane point per shard
    count (1, 2, 4, ... up to the core budget). Room-aligned shards share
    no state, so an N-core host should scale the sealed walk near
    linearly until the memory bus saturates — the curve makes the actual
    knee visible instead of leaving "multiply by cores" as an untested
    claim. On a 1-CPU rig this degenerates to the single-shard point
    (flagged in the result; see BASELINE.md)."""
    cores = os.cpu_count() or 1
    budget = max_shards or min(cores, 8)
    ks: list[int] = []
    k = 1
    while k <= budget:
        ks.append(k)
        k *= 2
    if budget not in ks:
        ks.append(budget)
    points = []
    for k in ks:
        ep = EgressPlane(k)
        r = bench_plane(
            ep, payload_len=payload_len, sealed=sealed,
            seconds=seconds_per_point, **shape,
        )
        if "error" in r:
            return {"error": r["error"], "cores": cores}
        points.append({"shards": k, "pps": r["pps"]})
    base = points[0]["pps"] or 1.0
    return {
        "cores": cores,
        "single_core_rig": cores <= 1,
        "sealed": sealed,
        "points": points,
        "speedup": [round(p["pps"] / base, 2) for p in points],
    }
