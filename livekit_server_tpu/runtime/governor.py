"""OverloadGovernor: closed-loop load shedding for the media plane.

The reference SFU degrades under pressure instead of missing pacing
deadlines — its stream allocator pauses and downgrades simulcast layers
(streamallocator.go), and LimitConfig gates node admission. This runtime
concentrates the node in one jitted call per tick, so overload shows up
as tick-deadline lateness, pipeline stalls, and ingest slab overflow —
the sensor suite the pipelined serving loop already exports. The
governor closes the loop from those sensors to a monotonic ladder of
degradation levels, each mapped to an existing actuator:

  L0  healthy — no intervention
  L1  clamp spatial layer caps, highest layers first (the dirty-row
      ctrl-upload path applies an *effective* cap at upload time; the
      host mirrors keep every subscriber's desired caps, so snapshots,
      failover, and recovery are exact)
  L2  police per-(room, track) ingress with token buckets — video only,
      so greedy publishers shed before polite ones and audio rides
      through untouched (IngestBuffer.set_policer)
  L3  pause non-pinned video subscriptions; audio and signaling stay
      live (effective sub_muted mask, same upload-time seam as L1)
  L4  reject new room creates, joins, and track publishes with explicit
      signal responses (RoomManager admission consults should_admit)

Sensors are evaluated once per completed tick (PlaneRuntime._complete →
on_tick): deadline lateness, work ratio (tick work time / tick period),
new pipeline stalls, and new ingest *capacity* drops. Policed drops are
deliberately excluded — intentional shedding must not read as pressure,
which is the point of the dropped_capacity / dropped_policed split.

Recovery walks the ladder DOWN one level at a time with hysteresis:
distinct enter/exit work-ratio thresholds plus a dwell time (consecutive
calm ticks) per step, so an oscillating load cannot flap the governor.
The PlaneSupervisor watchdog treats a governed plane (level > 0) as
"overloaded but making progress" and extends its stall deadline — load
must shed, not trigger a restart storm that makes the overload worse.
"""

from __future__ import annotations

from collections import deque

from livekit_server_tpu.models import plane
from livekit_server_tpu.utils.logger import Logger

# Ladder levels (monotonic; each includes every actuator below it).
L_HEALTHY = 0
L_CLAMP = 1      # drop the top spatial layer(s)
L_POLICE = 2     # + token-bucket video ingress policing, base layer only
L_PAUSE = 3      # + pause non-pinned video subscriptions
L_REJECT = 4     # + reject new rooms / joins / publishes
L_MAX = L_REJECT


class OverloadGovernor:
    """One governor per runtime; attach via `runtime.governor` (RoomManager
    does this when config.limits.governor_enabled, the default)."""

    def __init__(
        self,
        runtime,
        *,
        enter_pressure: float = 0.85,
        exit_pressure: float = 0.55,
        escalate_ticks: int = 20,
        dwell_ticks: int = 150,
        ingress_pps: float = 400.0,
        ingress_burst: float = 100.0,
        log: Logger | None = None,
    ):
        self.runtime = runtime
        self.enter_pressure = enter_pressure
        self.exit_pressure = exit_pressure
        self.escalate_ticks = max(1, int(escalate_ticks))
        self.dwell_ticks = max(1, int(dwell_ticks))
        self.ingress_pps = ingress_pps
        self.ingress_burst = ingress_burst
        self.log = log or Logger()
        self.level = L_HEALTHY
        self.ticks = 0
        self.escalations = 0         # lifetime up-transitions (telemetry)
        self.transition_count = 0
        # Recent transition records for /debug/overload.
        self.transitions: deque = deque(maxlen=64)
        # Admission rejections by kind ("room" / "join" / "publish");
        # RoomManager increments via note_rejection at each refusal.
        self.rejected: dict[str, int] = {}
        # Node drain (service/migration.py): while held, the node sits at
        # L_MAX and the sensor loop neither escalates nor recovers — a
        # draining node must keep rejecting admissions no matter how calm
        # its (emptying) plane looks.
        self.drain_hold = False
        self._hot = 0                # consecutive pressured ticks
        self._calm = 0               # consecutive relaxed ticks
        self._stalls_seen = runtime.stats.get("pipeline_stalls", 0)
        self._cap_drops_seen = runtime.ingest.dropped_capacity

    @classmethod
    def from_config(cls, runtime, limits, log: Logger | None = None):
        """Construct from config.LimitsConfig (the governor_* keys)."""
        return cls(
            runtime,
            enter_pressure=limits.governor_enter_pressure,
            exit_pressure=limits.governor_exit_pressure,
            escalate_ticks=limits.governor_escalate_ticks,
            dwell_ticks=limits.governor_dwell_ticks,
            ingress_pps=limits.governor_ingress_pps,
            ingress_burst=limits.governor_ingress_burst,
            log=log,
        )

    # -- sensors ----------------------------------------------------------
    def on_tick(self, rec: dict) -> None:
        """One completed tick's verdict (PlaneRuntime._complete passes the
        recent_ticks record it just appended). Three-way classification:
        pressured (any overload sensor fires), relaxed (everything under
        the exit threshold — the hysteresis band), or the middle band,
        which resets BOTH streaks: not bad enough to escalate, not calm
        enough to count toward dwell."""
        if self.drain_hold:
            self.ticks += 1
            return
        rt = self.runtime
        stalls = rt.stats.get("pipeline_stalls", 0)
        cap_drops = rt.ingest.dropped_capacity
        d_stalls = stalls - self._stalls_seen
        d_caps = cap_drops - self._cap_drops_seen
        self._stalls_seen = stalls
        self._cap_drops_seen = cap_drops
        work = rec.get("total_ms", 0.0) / max(float(rt.tick_ms), 1e-3)
        late = bool(rec.get("late"))
        self.ticks += 1
        pressured = (
            late or d_stalls > 0 or d_caps > 0 or work >= self.enter_pressure
        )
        relaxed = (
            not late and d_stalls == 0 and d_caps == 0
            and work <= self.exit_pressure
        )
        if pressured:
            self._calm = 0
            self._hot += 1
            if self._hot >= self.escalate_ticks and self.level < L_MAX:
                why = []
                if late:
                    why.append("late")
                if d_stalls > 0:
                    why.append(f"stalls+{d_stalls}")
                if d_caps > 0:
                    why.append(f"cap_drops+{d_caps}")
                if work >= self.enter_pressure:
                    why.append(f"work={work:.2f}")
                self._set_level(self.level + 1, " ".join(why))
                # One step per full streak: the next rung needs another
                # escalate_ticks of sustained pressure, so a single bad
                # burst cannot ride the ladder straight to L_MAX.
                self._hot = 0
        elif relaxed:
            self._hot = 0
            self._calm += 1
            if self._calm >= self.dwell_ticks and self.level > L_HEALTHY:
                self._set_level(self.level - 1, "recovered (dwell elapsed)")
                # Symmetric: each downward step earns its own full dwell.
                self._calm = 0
        else:
            self._hot = 0
            self._calm = 0

    # -- actuators --------------------------------------------------------
    def _set_level(self, new: int, reason: str = "") -> None:
        """Move one ladder step and apply the new level's actuator set.
        Levels are cumulative, so the actuators are recomputed absolutely
        from `new` rather than toggled incrementally — a restart-restored
        governor lands in a consistent state either way."""
        old = self.level
        if new == old:
            return
        self.level = new
        rt = self.runtime
        if new >= L_POLICE:
            spatial_cap = 0                        # base layer only
        elif new >= L_CLAMP:
            spatial_cap = max(0, plane.MAX_LAYERS - 2)  # shed top layer
        else:
            spatial_cap = plane.MAX_LAYERS - 1     # no clamp
        rt.set_shed(spatial_cap=spatial_cap, pause_video=new >= L_PAUSE)
        if new >= L_POLICE:
            rt.ingest.set_policer(
                self.ingress_pps, self.ingress_burst,
                is_video=rt.meta.is_video,
            )
        else:
            rt.ingest.clear_policer()
        self.transition_count += 1
        if new > old:
            self.escalations += 1
        self.transitions.append(
            {"tick": self.ticks, "from": old, "to": new, "reason": reason}
        )
        bb = getattr(rt, "blackbox", None)
        if bb is not None:
            # Node-lane black-box event (cold path: level transitions).
            from livekit_server_tpu.runtime.trace import EV_GOV_LEVEL

            bb.emit(bb.NODE, EV_GOV_LEVEL, float(old), float(new))
        log = self.log.warn if new > old else self.log.info
        log("overload governor level change", level=new, was=old, reason=reason)

    # -- admission (L4) ---------------------------------------------------
    def should_admit(self, kind: str) -> bool:
        """Node admission gate for new work ('room' / 'join' / 'publish')
        and failover adoption ('restore'). Existing sessions — including
        resumes — are never evicted by the governor; only NEW load is
        refused, and only at L4. A 'restore' is NOT new load: the fleet
        already admitted that room and its participants before their node
        died, so the transient ladder never refuses it — on a busy fleet
        an L4 gate here would orphan rooms permanently, exactly when a
        flash crowd makes the survivors late. Restores still stop on
        drain_hold (this node is leaving) and on hard plane headroom.

        Room admission is additionally keyed on REAL plane headroom, not
        row count: `occupancy()["admittable_rooms"]` folds in the page
        pool on a paged runtime (free pages / min room footprint), so a
        fragmented or page-exhausted pool refuses rooms even while room
        rows remain — and a dense runtime degrades to the row check."""
        if self.drain_hold:
            return False
        if kind != "restore" and self.level >= L_REJECT:
            return False
        if kind in ("room", "restore"):
            occ = self.runtime.occupancy()
            if occ.get("admittable_rooms", 1) <= 0:
                return False
        return True

    def note_rejection(self, kind: str) -> None:
        self.rejected[kind] = self.rejected.get(kind, 0) + 1

    # -- drain hold (node drain, service/migration.py) --------------------
    def hold_max(self, reason: str = "node draining") -> None:
        """Pin the ladder at L_MAX and freeze the sensor loop: every
        admission is refused until release_hold(). In practice a drain
        ends in process shutdown and the hold is never released."""
        self.drain_hold = True
        if self.level < L_MAX:
            self._set_level(L_MAX, reason)

    def release_hold(self) -> None:
        self.drain_hold = False

    # -- visibility -------------------------------------------------------
    def snapshot(self) -> dict:
        """Full governor state for /debug/overload."""
        ing = self.runtime.ingest
        return {
            "level": self.level,
            "drain_hold": self.drain_hold,
            "ticks": self.ticks,
            "hot_streak": self._hot,
            "calm_streak": self._calm,
            "escalations": self.escalations,
            "transition_count": self.transition_count,
            "transitions": list(self.transitions),
            "rejected": dict(self.rejected),
            "dropped_capacity": ing.dropped_capacity,
            "dropped_fault": ing.dropped_fault,
            "dropped_policed": ing.dropped_policed,
            "thresholds": {
                "enter_pressure": self.enter_pressure,
                "exit_pressure": self.exit_pressure,
                "escalate_ticks": self.escalate_ticks,
                "dwell_ticks": self.dwell_ticks,
                "ingress_pps": self.ingress_pps,
                "ingress_burst": self.ingress_burst,
            },
        }

    def stats_dict(self) -> dict:
        """Light per-tick stats for the telemetry gauges (the full
        snapshot builds lists; this stays allocation-cheap)."""
        ing = self.runtime.ingest
        return {
            "level": self.level,
            "drain_hold": self.drain_hold,
            "escalations": self.escalations,
            "transitions_total": self.transition_count,
            "dropped_capacity": ing.dropped_capacity,
            "dropped_fault": ing.dropped_fault,
            "dropped_policed": ing.dropped_policed,
            "rejected": dict(self.rejected),
        }
