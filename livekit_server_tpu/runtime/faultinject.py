"""Deterministic, seedable fault injection for chaos tests and soak runs.

Faults thread in at well-defined seams so the SAME mechanism drives unit
chaos tests (tests/test_faultinject.py, tests/test_failover.py) and
future on-TPU soak runs:

  - packet faults (drop / delay / duplicate) at the ingest boundary —
    IngestBuffer.push consults an attached injector before staging, so
    faulted traffic exercises the identical tick path real loss would
  - tick stalls — PlaneRuntime._device_step calls maybe_stall() on the
    worker thread, wedging the tick exactly where a pathological XLA
    dispatch or driver hang would (what the PlaneSupervisor watchdog
    exists to catch)
  - bus severing — abort a TCPBusClient's transport mid-conversation
    (exercises the retry/backoff/reconnect path in routing/tcpbus.py)
  - node kill — abrupt, non-graceful teardown of a server's cluster
    presence: heartbeats stop, the lease expires, the pin is left behind
    (exactly what a crashed host looks like to the survivors)

Determinism: every probabilistic decision draws from one seeded
numpy Generator in arrival order, so a given (seed, packet sequence)
replays the identical fault pattern — the property the reproducibility
tests pin. All faults default OFF; config (config.faults.*) gates them
and the default config path never constructs an injector.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import numpy as np


@dataclass
class FaultSpec:
    """Injection plan (mirrors config.FaultInjectConfig)."""

    seed: int = 0
    drop_pct: float = 0.0     # P(drop) per ingest packet
    dup_pct: float = 0.0      # P(duplicate) per ingest packet
    delay_pct: float = 0.0    # P(delay) per ingest packet
    delay_ticks: int = 2      # held-back packets re-enter after this many ticks
    stall_every: int = 0      # every Nth device step stalls (0 = never)
    stall_s: float = 0.0      # stall duration
    # Flood mode: multiply offered load by staging extra copies of each
    # arriving packet (<= 1.0 disables). Non-integer multipliers add the
    # fractional copy with a seeded draw; integer multipliers draw
    # nothing, keeping the drop/delay/dup sequence alignment identical
    # to a non-flood run with the same seed.
    flood_mult: float = 1.0
    flood_rooms: tuple = ()   # room rows to flood (empty = every room)
    # Silent-data-corruption mode: flip bits in one room's slice of a
    # chosen PlaneState leaf right before the device step at bitflip_tick
    # (-1 = never). Element choice draws from a SEPARATE seeded rng so
    # the packet-fault draw sequence stays alignment-identical to a
    # no-bitflip run with the same seed.
    bitflip_tick: int = -1
    bitflip_room: int = 0
    bitflip_leaf: str = "temporal_bytes"  # dotted path into PlaneState
    bitflip_bit: int = 30     # bit index within each element's word
    bitflip_count: int = 1    # elements flipped in the chosen row
    # Checkpoint corruption: damage every Nth serialized checkpoint frame
    # past its header (0 = never), so restore paths must catch it via
    # checksum verification, not a deserialize crash.
    corrupt_ckpt_every: int = 0
    # Migration chaos drills (service/migration.py seams):
    # target adopts the PREPARE'd room then goes silent — never ACKs.
    mig_drop_prepare: bool = False
    # target sleeps this long before ACKing (late-ACK epoch-guard drill).
    mig_ack_delay_s: float = 0.0
    # source damages the encoded snapshot inside PREPARE (target NACKs).
    mig_corrupt_handoff: bool = False
    # source's first N commit phases fail their bus ops (sever drill).
    mig_sever_handoffs: int = 0
    # Bus-partition drills (BusServer.set_partition seam): node-id groups
    # to sever from each other at bus_partition_tick — group 0 keeps the
    # bus, later groups lose every KV op and pub/sub push (the minority
    # side of a split-brain). Healed at bus_heal_at_tick (-1 = never).
    # bus_asym_pairs lists (src, dst) node-id pairs whose pushes are
    # HELD and delivered in order on heal — the stale-message-after-heal
    # drill (e.g. a migration COMMIT landing after its epoch died).
    bus_partition_groups: tuple = ()
    bus_partition_tick: int = -1
    bus_heal_at_tick: int = -1
    bus_asym_pairs: tuple = ()


@dataclass
class FaultStats:
    dropped: int = 0
    duplicated: int = 0
    delayed: int = 0
    stalls: int = 0
    severed: int = 0
    killed: int = 0
    flooded: int = 0          # extra packet copies staged by flood mode
    bitflips: int = 0         # state elements corrupted by bitflip mode
    ckpt_corrupted: int = 0   # checkpoint frames damaged after encoding
    mig_prepares_swallowed: int = 0  # adoptions that then went silent
    mig_acks_delayed: int = 0        # ACKs slept past the source timeout
    mig_handoffs_corrupted: int = 0  # PREPARE snapshots damaged in flight
    mig_commits_severed: int = 0     # commit phases failed at the bus seam
    partitions: int = 0              # bus partitions installed by the tick seam
    heals: int = 0                   # partitions healed by the tick seam


class FaultInjector:
    """One injector per runtime; attach via `runtime.fault` and
    `runtime.ingest.fault` (RoomManager does both when config enables it)."""

    def __init__(self, spec: FaultSpec | None = None, **overrides: Any):
        spec = spec or FaultSpec()
        if overrides:
            spec = FaultSpec(**{**vars(spec), **overrides})
        self.spec = spec
        self.rng = np.random.default_rng(spec.seed)
        # Separate stream for bitflip element choice: corruption faults
        # must not perturb the packet-fault draw alignment.
        self._sdc_rng = np.random.default_rng(spec.seed ^ 0x5DC5DC)
        self.stats = FaultStats()
        # release_tick → [PacketIn]; drained by take_due() at tick edges.
        self._held: dict[int, list] = {}
        self._step_count = 0
        self._ckpt_count = 0
        self._mig_severed = 0
        self._mig_handoff_count = 0

    @classmethod
    def from_config(cls, cfg) -> "FaultInjector":
        return cls(FaultSpec(
            seed=cfg.seed, drop_pct=cfg.drop_pct, dup_pct=cfg.dup_pct,
            delay_pct=cfg.delay_pct, delay_ticks=cfg.delay_ticks,
            stall_every=cfg.stall_every, stall_s=cfg.stall_s,
            flood_mult=cfg.flood_mult, flood_rooms=tuple(cfg.flood_rooms),
            bitflip_tick=cfg.bitflip_tick, bitflip_room=cfg.bitflip_room,
            bitflip_leaf=cfg.bitflip_leaf, bitflip_bit=cfg.bitflip_bit,
            bitflip_count=cfg.bitflip_count,
            corrupt_ckpt_every=cfg.corrupt_ckpt_every,
            mig_drop_prepare=cfg.mig_drop_prepare,
            mig_ack_delay_s=cfg.mig_ack_delay_s,
            mig_corrupt_handoff=cfg.mig_corrupt_handoff,
            mig_sever_handoffs=cfg.mig_sever_handoffs,
            bus_partition_groups=tuple(
                tuple(g) for g in cfg.bus_partition_groups
            ),
            bus_partition_tick=cfg.bus_partition_tick,
            bus_heal_at_tick=cfg.bus_heal_at_tick,
            bus_asym_pairs=tuple(tuple(p) for p in cfg.bus_asym_pairs),
        ))

    # -- ingest-boundary packet faults -----------------------------------
    def on_packet(self, pkt, tick_index: int) -> str:
        """Verdict for one arriving packet, drawn in arrival order:
        'drop' (discard), 'delay' (held; re-enters at tick_index +
        delay_ticks), 'dup' (stage twice), or 'pass'. One uniform draw
        per packet keeps the sequence alignment-stable across verdicts."""
        s = self.spec
        u = float(self.rng.random())
        if u < s.drop_pct:
            self.stats.dropped += 1
            return "drop"
        if u < s.drop_pct + s.delay_pct:
            self.stats.delayed += 1
            self._held.setdefault(tick_index + max(1, s.delay_ticks), []).append(pkt)
            return "delay"
        if u < s.drop_pct + s.delay_pct + s.dup_pct:
            self.stats.duplicated += 1
            return "dup"
        return "pass"

    def flood_copies(self, room: int) -> int:
        """Extra copies to stage for one arriving packet in flood mode
        (0 when disabled or the room is excluded). IngestBuffer.push
        calls this once per ORIGINAL packet; a 4.0 multiplier returns 3
        so original + copies = 4x offered load."""
        s = self.spec
        if s.flood_mult <= 1.0:
            return 0
        if s.flood_rooms and room not in s.flood_rooms:
            return 0
        extra = int(s.flood_mult) - 1
        frac = s.flood_mult - int(s.flood_mult)
        if frac > 0.0 and float(self.rng.random()) < frac:
            extra += 1
        self.stats.flooded += extra
        return extra

    def take_due(self, tick_index: int) -> list:
        """Delayed packets whose release tick has arrived (drained by
        IngestBuffer right before each tick's drain)."""
        due: list = []
        for t in sorted(k for k in self._held if k <= tick_index):
            due.extend(self._held.pop(t))
        return due

    # -- tick stalls ------------------------------------------------------
    def maybe_stall(self) -> None:
        """Called from the device-step worker thread: sleeping here wedges
        the tick without blocking the event loop — the watchdog's view is
        identical to a hung dispatch."""
        self._step_count += 1
        s = self.spec
        if s.stall_every and s.stall_s > 0 and self._step_count % s.stall_every == 0:
            import time

            self.stats.stalls += 1
            time.sleep(s.stall_s)

    # -- silent data corruption -------------------------------------------
    def maybe_bitflip(self, runtime, tick_index: int) -> None:
        """Flip bits in one room's slice of the configured state leaf at
        the configured tick — the SDC event the integrity audit exists to
        catch. Called from PlaneRuntime._device_step on the worker thread
        right before the step; the caller holds state_lock (GC01)."""
        s = self.spec
        if s.bitflip_tick < 0 or tick_index != s.bitflip_tick:
            return
        import jax.numpy as jnp

        leaf = runtime.state
        for part in s.bitflip_leaf.split("."):
            leaf = getattr(leaf, part)
        row = np.array(leaf[s.bitflip_room])
        flat = row.reshape(-1)
        itemsize = flat.dtype.itemsize
        if itemsize == 4:
            words = flat.view(np.uint32)
            bit = np.uint32(1 << (s.bitflip_bit % 32))
        else:  # bool / int8 leaves: flip within the byte
            words = flat.view(np.uint8)
            bit = np.uint8(1 << (s.bitflip_bit % 8))
        n = min(max(1, s.bitflip_count), words.size)
        idx = self._sdc_rng.choice(words.size, size=n, replace=False)
        words[idx] ^= bit
        new_leaf = leaf.at[s.bitflip_room].set(jnp.asarray(row, leaf.dtype))
        runtime.state = _replace_leaf(runtime.state, s.bitflip_leaf, new_leaf)
        self.stats.bitflips += n

    def corrupt_ckpt(self, blob):
        """Damage every Nth encoded checkpoint (bytes or b64 str) at a
        deterministic offset PAST the frame header: the magic/version
        survive, so only CRC verification can catch the damage."""
        s = self.spec
        if s.corrupt_ckpt_every <= 0:
            return blob
        self._ckpt_count += 1
        if self._ckpt_count % s.corrupt_ckpt_every:
            return blob
        self.stats.ckpt_corrupted += 1
        if isinstance(blob, str):
            # b64 text (KV-bus room checkpoints): the 20-byte header spans
            # the first 28 chars; swap one payload char for a different
            # valid b64 char so decode succeeds but the CRC does not.
            pos = 28 + (self._ckpt_count * 7919) % max(1, len(blob) - 30)
            repl = "A" if blob[pos] != "A" else "B"
            return blob[:pos] + repl + blob[pos + 1:]
        pos = 20 + (self._ckpt_count * 7919) % max(1, len(blob) - 21)
        out = bytearray(blob)
        out[pos] ^= 0xFF
        return bytes(out)

    # -- migration chaos seams (service/migration.py) ---------------------
    def mig_swallow_prepare(self) -> bool:
        """Target seam: True = the PREPARE handler adopted the room but
        must now go silent (no ACK, ever) — to the source this target
        died mid-PREPARE. Exercises the source's timeout rollback and
        the target's adoption reaper (no row leak)."""
        if not self.spec.mig_drop_prepare:
            return False
        self.stats.mig_prepares_swallowed += 1
        return True

    async def mig_delay_ack(self) -> None:
        """Target seam: hold the ACK past the source's timeout so the
        epoch guard gets a genuinely late ACK to ignore."""
        s = self.spec
        if s.mig_ack_delay_s > 0:
            self.stats.mig_acks_delayed += 1
            import asyncio

            await asyncio.sleep(s.mig_ack_delay_s)

    def corrupt_handoff(self, payload: str) -> str:
        """Source seam: damage the encoded snapshot riding in PREPARE the
        same way corrupt_ckpt damages b64 checkpoint frames — header
        intact, one payload char swapped, so only the target's CRC
        verification catches it (⇒ NACK)."""
        if not self.spec.mig_corrupt_handoff:
            return payload
        self.stats.mig_handoffs_corrupted += 1
        self._mig_handoff_count += 1
        pos = 28 + (self._mig_handoff_count * 7919) % max(1, len(payload) - 30)
        repl = "A" if payload[pos] != "A" else "B"
        return payload[:pos] + repl + payload[pos + 1:]

    def mig_sever_commit(self) -> bool:
        """Source seam: True = this commit phase's bus ops must fail
        (the orchestrator raises ConnectionError and rolls back).
        Consumes one of mig_sever_handoffs per handoff attempt."""
        if self._mig_severed >= self.spec.mig_sever_handoffs:
            return False
        self._mig_severed += 1
        self.stats.mig_commits_severed += 1
        return True

    # -- bus-partition drills (routing/tcpbus.py BusServer seam) ----------
    def bus_partition_tick(self, bus_server, tick_index: int) -> None:
        """Deterministic sever/heal on the tick clock: install the
        configured partition at bus_partition_tick, heal it at
        bus_heal_at_tick. Driven by whichever test/bench owns both the
        BusServer and a tick counter; idempotent across repeat calls for
        the same tick."""
        s = self.spec
        if not s.bus_partition_groups:
            return
        if tick_index == s.bus_partition_tick and not bus_server._severed:
            bus_server.set_partition(
                [list(g) for g in s.bus_partition_groups],
                asym_pairs=s.bus_asym_pairs,
            )
            self.stats.partitions += 1
        if (
            s.bus_heal_at_tick >= 0
            and tick_index == s.bus_heal_at_tick
            and (bus_server._severed or bus_server._asym)
        ):
            bus_server.heal_partition()
            self.stats.heals += 1

    # -- infrastructure faults (chaos-test helpers) ----------------------
    def sever_bus(self, client) -> None:
        """Hard-drop a TCPBusClient's socket (no FIN handshake): in-flight
        calls fail, the retry/backoff path re-dials."""
        self.stats.severed += 1
        transport = getattr(client._writer, "transport", None)
        if transport is not None:
            transport.abort()
        else:  # non-asyncio writer (tests with fakes)
            client._writer.close()

    async def kill_node(self, server) -> None:
        """Crash a server the way a dead host looks to the cluster:
        heartbeats and the session worker stop, the runtime halts, the
        bus socket drops — but NOTHING is cleaned up (no hdel, no lease
        delete, no room unpin). Survivors must detect the expired lease
        and take the rooms over."""
        self.stats.killed += 1
        router = server.router
        for attr in ("_stats_task", "_session_task"):
            task = getattr(router, attr, None)
            if task is not None:
                task.cancel()
        if getattr(server, "_stats_task", None) is not None:
            server._stats_task.cancel()
        sup = getattr(server.room_manager, "supervisor", None)
        if sup is not None:
            await sup.stop()
        await server.room_manager.runtime.stop()
        failover = getattr(server.room_manager, "_failover_task", None)
        if failover is not None:
            failover.cancel()
        fleet = getattr(server.room_manager, "fleet", None)
        if fleet is not None:
            await fleet.stop()
        bus = getattr(router, "bus", None)
        if bus is not None and hasattr(bus, "_writer"):
            bus.closed = True  # suppress the reconnect loop: the node is dead
            self.sever_bus(bus)


def _replace_leaf(tree, path: str, value):
    """Rebuild a NamedTuple pytree with the leaf at dotted `path` swapped."""
    parts = path.split(".")

    def rec(node, i: int):
        if i == len(parts) - 1:
            return node._replace(**{parts[i]: value})
        child = getattr(node, parts[i])
        return node._replace(**{parts[i]: rec(child, i + 1)})

    return rec(tree, 0)
