"""Authenticated media-wire encryption: AEAD frames + replay protection.

Reference parity: the reference's media plane rides DTLS-SRTP — keys are
negotiated per peer connection and every RTP/RTCP packet is encrypted and
authenticated (pkg/rtc/transport.go:167 PCTransport's DTLS role,
pion/srtp underneath). This build replaces the DTLS handshake with keys
minted server-side and delivered over the ALREADY-authenticated signal
channel (the JWT-gated WebSocket — the trust anchor the reference's
token validation provides), and SRTP with an explicit-nonce AEAD frame:

    frame = 0x01 | key_id(4) | dir(1) | counter(8) | AESGCM(ct+tag)
      nonce = dir(1) | counter(8) | zeros(3)        (12 bytes)
      aad   = frame[:14]                            (header is bound)

The leading 0x01 byte cannot collide with RTP/RTCP (version bits force
byte0 >= 0x80) or the punch magic ('L'), so plaintext and sealed frames
demux on one socket. Counters are per-direction and strictly increasing;
the receiver keeps a sliding bitmap window (RFC 4303-style) so replayed
or duplicated frames authenticate but are rejected. One session per
participant: direction separation lives in the nonce, so a captured
server→client frame can never be replayed back as client→server.
"""

from __future__ import annotations

import secrets

try:
    from cryptography.exceptions import InvalidTag
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ImportError:  # optional dependency: fall back to libcrypto below
    AESGCM = None

    class InvalidTag(Exception):
        pass


if AESGCM is None:
    # Without the `cryptography` package, drive OpenSSL's EVP interface
    # directly via ctypes (the same libcrypto native/egress.cpp links
    # against, and the EVP_* subset used is stable across 1.1/3). Only if
    # libcrypto itself is missing does the node degrade to cleartext
    # media (RoomManager skips registry creation, join responses omit
    # media_crypto; constructing any session/endpoint raises).
    import ctypes
    import ctypes.util

    def _find_libcrypto():
        for name in (
            ctypes.util.find_library("crypto"),
            "libcrypto.so.3", "libcrypto.so.1.1", "libcrypto.so",
        ):
            if not name:
                continue
            try:
                lib = ctypes.CDLL(name)
                lib.EVP_aes_128_gcm.restype = ctypes.c_void_p
                return lib
            except (OSError, AttributeError):
                continue
        return None

    _libcrypto = _find_libcrypto()

    if _libcrypto is not None:
        _libcrypto.EVP_CIPHER_CTX_new.restype = ctypes.c_void_p
        _libcrypto.EVP_CIPHER_CTX_free.argtypes = [ctypes.c_void_p]
        for _f in ("EVP_EncryptInit_ex", "EVP_DecryptInit_ex"):
            getattr(_libcrypto, _f).argtypes = [ctypes.c_void_p] * 5
            getattr(_libcrypto, _f).restype = ctypes.c_int
        for _f in ("EVP_EncryptUpdate", "EVP_DecryptUpdate"):
            getattr(_libcrypto, _f).argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_int), ctypes.c_char_p, ctypes.c_int,
            ]
            getattr(_libcrypto, _f).restype = ctypes.c_int
        for _f in ("EVP_EncryptFinal_ex", "EVP_DecryptFinal_ex"):
            getattr(_libcrypto, _f).argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
            ]
            getattr(_libcrypto, _f).restype = ctypes.c_int
        _libcrypto.EVP_CIPHER_CTX_ctrl.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_void_p,
        ]
        _libcrypto.EVP_CIPHER_CTX_ctrl.restype = ctypes.c_int
        _EVP_CTRL_GCM_SET_TAG = 0x11
        _EVP_CTRL_GCM_GET_TAG = 0x10

        class AESGCM:  # type: ignore[no-redef]
            """API-compatible stand-in for cryptography's AESGCM
            (16-byte keys / 12-byte nonces, the only shapes used here)."""

            def __init__(self, key: bytes):
                if len(key) != 16:
                    raise ValueError("AES-128-GCM needs a 16-byte key")
                self._key = bytes(key)

            def encrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
                lc = _libcrypto
                ctx = lc.EVP_CIPHER_CTX_new()
                try:
                    lc.EVP_EncryptInit_ex(
                        ctx, lc.EVP_aes_128_gcm(), None, self._key, nonce
                    )
                    outl = ctypes.c_int(0)
                    if aad:
                        lc.EVP_EncryptUpdate(
                            ctx, None, ctypes.byref(outl), aad, len(aad)
                        )
                    ct = ctypes.create_string_buffer(len(data) or 1)
                    lc.EVP_EncryptUpdate(
                        ctx, ct, ctypes.byref(outl), data, len(data)
                    )
                    fin = ctypes.create_string_buffer(16)
                    lc.EVP_EncryptFinal_ex(ctx, fin, ctypes.byref(outl))
                    tag = ctypes.create_string_buffer(16)
                    lc.EVP_CIPHER_CTX_ctrl(ctx, _EVP_CTRL_GCM_GET_TAG, 16, tag)
                    return ct.raw[: len(data)] + tag.raw
                finally:
                    lc.EVP_CIPHER_CTX_free(ctx)

            def decrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
                if len(data) < 16:
                    raise InvalidTag("short frame")
                lc = _libcrypto
                ct, tag = data[:-16], data[-16:]
                ctx = lc.EVP_CIPHER_CTX_new()
                try:
                    lc.EVP_DecryptInit_ex(
                        ctx, lc.EVP_aes_128_gcm(), None, self._key, nonce
                    )
                    outl = ctypes.c_int(0)
                    if aad:
                        lc.EVP_DecryptUpdate(
                            ctx, None, ctypes.byref(outl), aad, len(aad)
                        )
                    pt = ctypes.create_string_buffer(len(ct) or 1)
                    lc.EVP_DecryptUpdate(
                        ctx, pt, ctypes.byref(outl), ct, len(ct)
                    )
                    lc.EVP_CIPHER_CTX_ctrl(
                        ctx, _EVP_CTRL_GCM_SET_TAG, 16,
                        ctypes.create_string_buffer(tag, 16),
                    )
                    fin = ctypes.create_string_buffer(16)
                    ok = lc.EVP_DecryptFinal_ex(ctx, fin, ctypes.byref(outl))
                    if ok != 1:
                        raise InvalidTag("GCM tag mismatch")
                    return pt.raw[: len(ct)]
                finally:
                    lc.EVP_CIPHER_CTX_free(ctx)


HAVE_AEAD = AESGCM is not None

MAGIC = 0x01
DIR_C2S = 0
DIR_S2C = 1
HEADER_LEN = 14          # magic + key_id(4) + dir(1) + counter(8)
REPLAY_WINDOW = 1024
ALGO = "aes-128-gcm"


def _seal(aead: AESGCM, key_id: int, direction: int, counter: int, pt: bytes) -> bytes:
    header = (
        bytes([MAGIC])
        + key_id.to_bytes(4, "big")
        + bytes([direction])
        + counter.to_bytes(8, "big")
    )
    nonce = bytes([direction]) + counter.to_bytes(8, "big") + b"\x00\x00\x00"
    return header + aead.encrypt(nonce, pt, header)


def parse_key_id(frame: bytes) -> int | None:
    if len(frame) < HEADER_LEN + 16 or frame[0] != MAGIC:
        return None
    return int.from_bytes(frame[1:5], "big")


def parse_counter(frame: bytes) -> int | None:
    """Sealed frame → its 64-bit counter (the plaintext header field).
    Clients use it as the transport-wide sequence number when building
    TWCC feedback (runtime/udp.py build_twcc_feedback)."""
    if len(frame) < HEADER_LEN + 16 or frame[0] != MAGIC:
        return None
    return int.from_bytes(frame[6:14], "big")


class _Replay:
    """Sliding-window anti-replay (RFC 4303 §3.4.3 bitmap)."""

    def __init__(self) -> None:
        self.hi = -1
        self.mask = 0

    def check(self, ctr: int) -> bool:
        if ctr > self.hi:
            shift = ctr - self.hi
            # Bound the shift BEFORE computing it: counters are attacker-
            # chosen (only authenticated), and `mask << 2**60` would try to
            # allocate an exabyte-scale int from one 30-byte datagram.
            if shift >= REPLAY_WINDOW:
                self.mask = 1
            else:
                self.mask = ((self.mask << shift) | 1) & ((1 << REPLAY_WINDOW) - 1)
            self.hi = ctr
            return True
        off = self.hi - ctr
        if off >= REPLAY_WINDOW:
            return False
        bit = 1 << off
        if self.mask & bit:
            return False
        self.mask |= bit
        return True


class _Endpoint:
    """One side of a session: seals in `tx_dir`, opens frames in the
    opposite direction with authentication + replay rejection."""

    def __init__(self, key_id: int, key: bytes, tx_dir: int) -> None:
        if AESGCM is None:
            raise RuntimeError("media crypto requires the 'cryptography' package")
        self.key_id = key_id
        self.key = key
        self.aead = AESGCM(key)
        self.tx_dir = tx_dir
        self.rx_dir = 1 - tx_dir
        self.tx_counter = 0
        self._ctr_bind: tuple | None = None  # (array, index) when bound
        self.replay = _Replay()

    def next_counter(self) -> int:
        """Allocate one tx counter. A GCM nonce must NEVER repeat under a
        key, so every sealing path (per-frame control traffic here, the
        native bulk egress via its counter-array binding) allocates from
        ONE source."""
        if self._ctr_bind is not None:
            arr, i = self._ctr_bind
            v = int(arr[i])
            arr[i] = v + 1
            return v
        ctr = self.tx_counter
        self.tx_counter += 1
        return ctr

    def cur_counter(self) -> int:
        if self._ctr_bind is not None:
            arr, i = self._ctr_bind
            return int(arr[i])
        return self.tx_counter

    def bind_counter(self, arr, idx: int) -> None:
        """Move the tx counter into a shared numpy array slot (the batch
        egress allocates counter blocks vectorized from it)."""
        arr[idx] = self.cur_counter()
        self._ctr_bind = (arr, idx)

    def seal(self, plaintext: bytes) -> bytes:
        ctr = self.next_counter()
        return _seal(self.aead, self.key_id, self.tx_dir, ctr, plaintext)

    def open(self, frame: bytes) -> bytes | None:
        """frame → inner datagram; None on any tamper/replay/direction
        failure (callers count, never raise — the socket is hostile)."""
        if len(frame) < HEADER_LEN + 16 or frame[0] != MAGIC:
            return None
        if frame[5] != self.rx_dir:
            return None  # reflected frame (our own direction)
        ctr = int.from_bytes(frame[6:14], "big")
        nonce = frame[5:14] + b"\x00\x00\x00"
        try:
            pt = self.aead.decrypt(nonce, frame[HEADER_LEN:], frame[:HEADER_LEN])
        except InvalidTag:
            return None
        if not self.replay.check(ctr):
            return None
        return pt


class MediaCryptoSession(_Endpoint):
    """Server side: seals server→client, opens client→server. Carries the
    participant's media coordinates so transports can route by key alone."""

    def __init__(self, key_id: int, key: bytes) -> None:
        super().__init__(key_id, key, tx_dir=DIR_S2C)
        self.room = -1
        self.sub = -1
        # Opportunistic-mode latch: set once the client sends any frame
        # that opens under this key — from then on egress to it is sealed
        # even when the node allows cleartext (require_encryption=False).
        self.client_active = False


class MediaCryptoClient(_Endpoint):
    """Client side (SDKs / tests): the mirror image of the session."""

    def __init__(self, key_id: int, key: bytes) -> None:
        super().__init__(key_id, key, tx_dir=DIR_C2S)


class MediaCryptoRegistry:
    """key_id → session for every connected participant on this node."""

    def __init__(self) -> None:
        self.sessions: dict[int, MediaCryptoSession] = {}

    def mint(self) -> MediaCryptoSession:
        while True:
            key_id = secrets.randbits(32)
            if key_id and key_id not in self.sessions:
                break
        s = MediaCryptoSession(key_id, secrets.token_bytes(16))
        self.sessions[key_id] = s
        return s

    def get(self, key_id: int) -> MediaCryptoSession | None:
        return self.sessions.get(key_id)

    def remove(self, key_id: int) -> None:
        self.sessions.pop(key_id, None)
