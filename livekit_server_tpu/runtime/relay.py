"""Media relay: the embedded-TURN seat for UDP-hostile network paths.

Reference parity: the reference embeds a TURN server (pkg/service/turn.go:47)
so clients whose direct UDP path to the SFU is blocked — symmetric NATs,
egress firewalls that whitelist a single relay address — can still move
media over UDP. This build's wire is not ICE, so RFC 5766 itself would buy
nothing; what this module keeps is TURN's *capability*: a separately
addressable UDP hop that forwards media between a client and the SFU's
media port, admitted by credentials minted over the authenticated signal
channel (TURN's long-term credential seat).

The relay is deliberately BLIND. Media frames are AEAD-sealed end-to-end
between client and SFU (runtime/crypto.py) — the relay never holds media
keys, so it forwards opaque datagrams verbatim in both directions. The
punch handshake (udp.py address-consent) rides through unchanged: the SFU
latches the relay's per-allocation source port as the subscriber address,
which is exactly the address media must flow to. One UDP socket is opened
per allocation so each relayed client keeps a distinct source address at
the SFU (SSRC latching and punch consent stay per-client).

Admission: a BIND datagram carrying a token minted by the SFU —

    token   = expiry_ms(8) | key_id(4) | nonce(4) | hmac16
    hmac16  = HMAC-SHA256(secret, "lk-relay" | payload)[:16]
    BIND    = "LKRL" | 0x01 | token(32)
    ACK     = "LKRL" | 0x02 | key_id(4)

key_id is the participant's media-crypto session id: one allocation per
session, so a leaked token cannot multiply allocations, and a re-BIND from
a new source address *moves* the allocation (the NAT-rebind recovery path).

Move continuity. A bare v1 BIND is replayable for its TTL: an on-path
observer who captures one can replay it from another address and re-aim
(hijack) the allocation — media stays AEAD-sealed, so the impact is a
targeted DoS of the victim's relay path, not disclosure. Clients that want
moves to be token-holder-only append a hash-chain continuity extension:

    BIND v2 = "LKRL" | 0x01 | token(32) | reveal(16) | commit(16)

The first BIND pins `commit` (reveal is ignored; send zeros). Every later
BIND from a *different* address must carry `reveal` with
SHA-256(reveal)[:16] == pinned commit, and supplies the next commit. An
observer sees only the hash (one-way) before a move and an already-spent
preimage after it, so captured (replayed) datagrams cannot re-aim the
allocation. v1 (37-byte) BINDs remain accepted for clients that opt out.

Token freshness is the recovery escape hatch. The relay remembers which
token nonces each allocation has already seen; a move whose token nonce is
*fresh* is accepted even without a chain proof (and re-pins to the BIND's
commit, or unpins for v1). Fresh tokens are mintable only over the
authenticated signal channel, so this stays token-holder-only, and it
covers two corners the chain alone cannot: (a) a client that lost its
chain state (crash) re-requests a token and recovers; (b) an on-path
attacker who wins the race against a legitimate move in flight — spending
the victim's reveal with an attacker commit — cannot lock the victim out,
because the victim mints a fresh token and takes the allocation back.
Replays still fail: an accepted BIND's nonce is spent on arrival.

Pin updates (set or rotate) happen only on origin-authorized frames —
creation, a valid reveal, or a fresh nonce — never on a replay, so a
source-spoofed replay of an old v2 BIND cannot reset the pin to a
commitment whose preimage has since been publicly spent.

Residual risk, accepted: media is AEAD-sealed end-to-end, so every attack
above is at worst a *recoverable* DoS of the victim's relay path; the
relay never learns or affects media confidentiality/integrity.
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac
import secrets
import time

from livekit_server_tpu.utils.backoff import BackoffPolicy, retry_async

RELAY_MAGIC = b"LKRL"
BIND_REQ = 0x01
BIND_ACK = 0x02
BIND_ERR = 0x03
TOKEN_LEN = 32
CONT_LEN = 16  # reveal(16) + commit(16) in the v2 continuity extension
_HMAC_CTX = b"lk-relay"


def continuity_commit(reveal: bytes) -> bytes:
    """The pin a BIND's 16-byte reveal must hash to (see module docstring)."""
    return hashlib.sha256(reveal).digest()[:CONT_LEN]


def mint_relay_token(secret: bytes, key_id: int, ttl_s: float) -> bytes:
    """Allocation credential for one media session (TURN credential seat)."""
    payload = (
        int((time.time() + ttl_s) * 1000).to_bytes(8, "big")
        + key_id.to_bytes(4, "big")
        + secrets.token_bytes(4)
    )
    mac = hmac.new(secret, _HMAC_CTX + payload, hashlib.sha256).digest()[:16]
    return payload + mac


def verify_relay_token(secret: bytes, token: bytes) -> int | None:
    """token → key_id, or None if forged/expired."""
    if len(token) != TOKEN_LEN:
        return None
    payload, mac = token[:16], token[16:]
    want = hmac.new(secret, _HMAC_CTX + payload, hashlib.sha256).digest()[:16]
    if not hmac.compare_digest(mac, want):
        return None
    if int.from_bytes(payload[:8], "big") < time.time() * 1000:
        return None
    return int.from_bytes(payload[8:12], "big")


class _Upstream(asyncio.DatagramProtocol):
    """Per-allocation socket facing the SFU media port: whatever the SFU
    sends to this allocation's source address goes back to the client."""

    def __init__(self, relay: "MediaRelay", key_id: int) -> None:
        self.relay = relay
        self.key_id = key_id
        self.transport: asyncio.DatagramTransport | None = None

    def connection_made(self, transport) -> None:
        self.transport = transport

    def datagram_received(self, data: bytes, addr) -> None:
        alloc = self.relay.allocs.get(self.key_id)
        if alloc is None or self.relay.transport is None:
            return
        alloc.last_active = time.monotonic()
        self.relay.stats["down_fwd"] += 1
        self.relay.transport.sendto(data, alloc.client_addr)


class _Allocation:
    __slots__ = (
        "key_id", "client_addr", "upstream", "last_active", "commit",
        "seen_nonces",
    )

    MAX_SEEN_NONCES = 256

    def __init__(self, key_id: int, client_addr, upstream: _Upstream) -> None:
        self.key_id = key_id
        self.client_addr = client_addr
        self.upstream = upstream
        self.last_active = time.monotonic()
        # Continuity pin (v2 BINDs): sha256(next reveal)[:16], or None for
        # v1 clients whose moves are token-gated only.
        self.commit: bytes | None = None
        # Token nonces already accepted on this allocation → token expiry
        # (ms). A BIND reusing a seen nonce is a replay and can never move
        # the allocation or touch the pin. Eviction is expiry-aware, not
        # FIFO: an entry leaves the set only once its token has expired
        # (at which point verify_relay_token rejects the replay anyway),
        # so a spent nonce can never be replayed within its token's
        # lifetime. Over-cap with >MAX_SEEN unexpired tokens (requires the
        # server to mint >256 live tokens for one session) evicts the
        # soonest-to-expire entry — the tightest remaining replay window.
        self.seen_nonces: dict[bytes, int] = {}

    def spend_nonce(self, nonce: bytes, expiry_ms: int) -> None:
        self.seen_nonces[nonce] = expiry_ms
        if len(self.seen_nonces) > self.MAX_SEEN_NONCES:
            now_ms = time.time() * 1000
            for n, exp in list(self.seen_nonces.items()):
                if exp < now_ms:
                    del self.seen_nonces[n]
            while len(self.seen_nonces) > self.MAX_SEEN_NONCES:
                del self.seen_nonces[min(self.seen_nonces,
                                         key=self.seen_nonces.get)]


class MediaRelay(asyncio.DatagramProtocol):
    """One UDP socket facing clients; one socket per allocation facing the
    SFU. Forwards datagrams verbatim — admission only, no inspection."""

    # Upstream-bind retry budget: short, because the client is blocked on
    # the BIND ACK and will retransmit anyway.
    BIND_RETRY = BackoffPolicy(base=0.02, max_delay=0.2, max_attempts=3)

    def __init__(
        self,
        upstream_addr: tuple[str, int],
        secret: bytes,
        ttl_s: float = 30.0,
        max_allocations: int = 4096,
    ) -> None:
        self.upstream_addr = upstream_addr
        self.secret = secret
        self.ttl_s = ttl_s
        self.max_allocations = max_allocations
        self.transport: asyncio.DatagramTransport | None = None
        self.allocs: dict[int, _Allocation] = {}
        self.by_client: dict[tuple, _Allocation] = {}
        # key_ids whose upstream socket is being created: a BIND burst for
        # one session must not open one socket per datagram (the creation
        # await yields; duplicates would leak unreachable FDs).
        self._pending: set[int] = set()
        self.stats = {
            "binds": 0, "bad_bind": 0, "up_fwd": 0, "down_fwd": 0,
            "dropped": 0, "expired": 0,
        }
        self._sweeper: asyncio.Task | None = None

    # -- protocol ---------------------------------------------------------
    def connection_made(self, transport) -> None:
        self.transport = transport
        self._sweeper = asyncio.ensure_future(self._sweep())

    def datagram_received(self, data: bytes, addr) -> None:
        is_bind = (
            len(data) in (5 + TOKEN_LEN, 5 + TOKEN_LEN + 2 * CONT_LEN)
            and data[:4] == RELAY_MAGIC
        )
        alloc = self.by_client.get(addr)
        if alloc is not None and not is_bind:
            alloc.last_active = time.monotonic()
            self.stats["up_fwd"] += 1
            if alloc.upstream.transport is not None:
                alloc.upstream.transport.sendto(data)
            return
        if is_bind and data[4] == BIND_REQ:
            asyncio.ensure_future(self._bind(data[5:], addr))
            return
        self.stats["dropped"] += 1

    # -- allocation lifecycle --------------------------------------------
    def _reject(self, addr) -> None:
        self.stats["bad_bind"] += 1
        if self.transport is not None:
            self.transport.sendto(RELAY_MAGIC + bytes([BIND_ERR]), addr)

    async def _bind(self, token: bytes, addr) -> None:
        reveal = commit = None
        if len(token) == TOKEN_LEN + 2 * CONT_LEN:  # v2: continuity extension
            token, reveal, commit = (
                token[:TOKEN_LEN],
                token[TOKEN_LEN:TOKEN_LEN + CONT_LEN],
                token[TOKEN_LEN + CONT_LEN:],
            )
        key_id = verify_relay_token(self.secret, token)
        if key_id is None:
            self._reject(addr)
            return
        nonce = token[12:16]  # payload = expiry(8) | key_id(4) | nonce(4)
        expiry_ms = int.from_bytes(token[:8], "big")
        alloc = self.allocs.get(key_id)
        if alloc is None:
            if key_id in self._pending:
                return  # creation in flight; the retransmit will re-ACK
            # Count pending creations against the cap too, or a burst of
            # distinct-token BINDs in one event-loop batch overshoots it.
            if len(self.allocs) + len(self._pending) >= self.max_allocations:
                self._reject(addr)
                return
            proto = _Upstream(self, key_id)
            loop = asyncio.get_running_loop()
            self._pending.add(key_id)
            try:
                # Bounded retry (uniform BackoffPolicy): transient FD
                # pressure or a momentarily exhausted ephemeral-port range
                # clears within a few dozen ms, and one extra dial beats
                # bouncing the client to its TCP fallback.
                await retry_async(
                    lambda: loop.create_datagram_endpoint(
                        lambda: proto, remote_addr=self.upstream_addr
                    ),
                    self.BIND_RETRY,
                    retry_on=(OSError,),
                )
            except OSError:
                # Still failing after the retry budget: tell the client now
                # so it falls back to TCP instead of timing out.
                self._reject(addr)
                return
            finally:
                self._pending.discard(key_id)
            alloc = _Allocation(key_id, addr, proto)
            alloc.commit = commit  # None for v1 clients
            alloc.spend_nonce(nonce, expiry_ms)
            self.allocs[key_id] = alloc
        else:
            # Origin authorization (see module docstring): a valid chain
            # reveal proves continuity; a fresh token nonce proves access
            # to the authenticated signal channel (recovery path). A
            # replayed datagram has neither.
            proof_ok = (
                alloc.commit is not None
                and reveal is not None
                and hmac.compare_digest(continuity_commit(reveal), alloc.commit)
            )
            fresh = nonce not in alloc.seen_nonces
            if alloc.client_addr != addr:
                # NAT rebind: moves the allocation; the old client address
                # stops receiving (re-aim is revocation). Pinned
                # allocations move only for origin-authorized frames.
                if alloc.commit is not None and not (proof_ok or fresh):
                    self._reject(addr)
                    return
                # The mover chooses the next pin (None for v1: an explicit,
                # token-holder-authorized unpin) — but ONLY when origin-
                # authorized. A replayed frame may still move an UNPINNED
                # allocation (that is v1's documented risk model), yet it
                # must never plant a pin: an attacker pinning a v1 client's
                # allocation would block the victim's own re-BIND reclaim.
                if proof_ok or fresh:
                    alloc.commit = commit
                self.by_client.pop(alloc.client_addr, None)
                alloc.client_addr = addr
            elif commit is not None and (proof_ok or fresh):
                # Same-address refresh may set/rotate the pin — including
                # first-pinning an allocation a v1 BIND created — but only
                # when origin-authorized, so a source-spoofed replay of an
                # old v2 BIND cannot reset the pin to a spent commitment.
                alloc.commit = commit
            alloc.spend_nonce(nonce, expiry_ms)
        alloc.last_active = time.monotonic()
        self.by_client[addr] = alloc
        self.stats["binds"] += 1
        if self.transport is not None:
            self.transport.sendto(
                RELAY_MAGIC + bytes([BIND_ACK]) + key_id.to_bytes(4, "big"), addr
            )

    def _close_alloc(self, alloc: _Allocation) -> None:
        self.allocs.pop(alloc.key_id, None)
        if self.by_client.get(alloc.client_addr) is alloc:
            del self.by_client[alloc.client_addr]
        if alloc.upstream.transport is not None:
            alloc.upstream.transport.close()

    async def _sweep(self) -> None:
        # Idle allocations expire after ttl (TURN allocation lifetime seat);
        # any datagram in either direction refreshes, as does a re-BIND.
        try:
            while True:
                await asyncio.sleep(max(1.0, self.ttl_s / 4))
                cutoff = time.monotonic() - self.ttl_s
                for alloc in [a for a in self.allocs.values() if a.last_active < cutoff]:
                    self.stats["expired"] += 1
                    self._close_alloc(alloc)
        except asyncio.CancelledError:
            pass

    def close(self) -> None:
        if self._sweeper is not None:
            self._sweeper.cancel()
        for alloc in list(self.allocs.values()):
            self._close_alloc(alloc)
        if self.transport is not None:
            self.transport.close()


async def start_media_relay(
    host: str,
    port: int,
    upstream_addr: tuple[str, int],
    secret: bytes,
    ttl_s: float = 30.0,
    max_allocations: int = 4096,
) -> MediaRelay:
    loop = asyncio.get_running_loop()
    # Listen-side bind, not a dial: a taken port is a config error that
    # should fail loudly at startup, not be retried into.
    _, proto = await loop.create_datagram_endpoint(  # graftcheck: disable=GC04
        lambda: MediaRelay(upstream_addr, secret, ttl_s, max_allocations),
        local_addr=(host, port),
    )
    return proto
