"""TCP media fallback: the same sealed frames, length-prefixed on a stream.

Reference parity: the transport fallback ladder — when UDP is blocked the
reference falls back to ICE-TCP and then TURN (pkg/rtc/transportmanager.go:73
onFailed → fallback candidate types; pkg/service/turn.go:47 embedded TURN
server). Here the ladder has one rung: a TCP listener speaking

    frame := len(2, big-endian) | <AEAD frame — runtime/crypto.py>

Each connection authenticates implicitly: the first frame that opens under
a registered session key binds the connection as that participant's media
sink (no punch needed — the connection itself is the validated return
path, the consent property ICE-TCP provides). Inner datagrams then flow
through the exact same dispatch as UDP (`UDPMediaTransport._dispatch_inner`),
and egress to that participant is routed by the ("tcp", key_id) pseudo
address the UDP transport's send chokepoint understands.

Encryption is mandatory on TCP: a cleartext mode on an internet-facing
fallback port has no reason to exist.
"""

from __future__ import annotations

import asyncio

from livekit_server_tpu.runtime.crypto import MediaCryptoRegistry, parse_key_id
from livekit_server_tpu.runtime.udp import UDPMediaTransport

MAX_FRAME = 64 * 1024
MAX_BUFFERED = 256 * 1024  # per-connection write backlog before media drops


class TCPMediaTransport:
    """Accepts framed media connections; delegates to the UDP transport's
    dispatch + send maps so both wires share one routing brain."""

    def __init__(self, udp: UDPMediaTransport, crypto: MediaCryptoRegistry):
        self.udp = udp
        self.crypto = crypto
        self.server: asyncio.AbstractServer | None = None
        self.stats = {"conns": 0, "bad_frame": 0, "frames_rx": 0}

    async def start(self, host: str, port: int) -> None:
        self.server = await asyncio.start_server(self._handle, host, port)

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        self.stats["conns"] += 1
        bound_key: int | None = None
        bound_sink = None
        try:
            while True:
                hdr = await reader.readexactly(2)
                n = int.from_bytes(hdr, "big")
                if n == 0 or n > MAX_FRAME:
                    break
                frame = await reader.readexactly(n)
                key_id = parse_key_id(frame)
                session = self.crypto.get(key_id) if key_id is not None else None
                inner = session.open(frame) if session is not None else None
                if inner is None:
                    self.stats["bad_frame"] += 1
                    continue
                self.stats["frames_rx"] += 1
                session.client_active = True
                if bound_key is None:
                    # First authenticated frame binds the connection as the
                    # participant's media sink (the ICE-TCP consent analog).
                    bound_key = session.key_id

                    def sink(data: bytes) -> None:
                        if writer.is_closing():
                            return
                        # Media is loss-tolerant: a stalled receiver must
                        # not buffer unbounded frames in server memory —
                        # drop instead (the pacer/leaky-bucket stance).
                        if writer.transport.get_write_buffer_size() > MAX_BUFFERED:
                            self.stats["frames_dropped"] = (
                                self.stats.get("frames_dropped", 0) + 1
                            )
                            return
                        writer.write(len(data).to_bytes(2, "big") + data)

                    self.udp.tcp_sinks[bound_key] = sink
                    bound_sink = sink
                    if session.room >= 0 and session.sub >= 0:
                        self.udp.sub_addrs[(session.room, session.sub)] = (
                            "tcp", bound_key,
                        )
                        self.udp._touch_subs()
                        # TCP egress carries no TWCC counters; without this
                        # refresh a sub that had a UDP address would keep
                        # fb_enabled=True, never ack, and starve its BWE
                        # budget to the floor.
                        self.udp._refresh_fb_enabled(session.room, session.sub)
                self.udp._dispatch_inner(inner, ("tcp", session.key_id), session)
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            # Tear down ONLY if this connection still owns the sink — a
            # reconnect may have rebound the key to a newer connection,
            # whose routing a stale close must not destroy.
            if bound_key is not None and self.udp.tcp_sinks.get(bound_key) is bound_sink:
                del self.udp.tcp_sinks[bound_key]
                for k, v in list(self.udp.sub_addrs.items()):
                    if v == ("tcp", bound_key):
                        del self.udp.sub_addrs[k]
                        self.udp._refresh_fb_enabled(*k)
                self.udp._touch_subs()
            writer.close()

    def close(self) -> None:
        if self.server is not None:
            self.server.close()


async def start_tcp_transport(
    udp: UDPMediaTransport,
    crypto: MediaCryptoRegistry,
    host: str = "0.0.0.0",
    port: int = 7881,
) -> TCPMediaTransport:
    t = TCPMediaTransport(udp, crypto)
    await t.start(host, port)
    return t
