"""PagedPlaneRuntime: the tick loop re-based onto pooled HBM pages.

PlaneRuntime's host side — ctrl mirrors, munger, sequencer, ingest,
express lane, fan-out, governor — speaks LOGICAL dense [R, T, S] shapes
end to end. This subclass swaps only the device layout underneath it via
the five seam hooks (plane_runtime.py): the device state becomes ONE
pool of P `[tpage, K, spage]` pages (models/paged.py) indirected through
a device-resident page table whose host canonical copy lives in the
RoomPager (runtime/pager.py). Rooms claim page grids through
PagedSlotAllocator instead of pre-paying the dense worst case, so
rooms/chip follows the actual room-size distribution.

Upload protocol (the PR 3 dirty-row delta, extended with the page lane):
at every tick edge `_upload_ctrl` first drains the pager's PageDelta —
table-row scatter, compaction row moves, fresh/freed page re-init — and
then ships the dirtied rooms' ctrl at PAGE granularity (each dirty
room's pages gather [TP]/[TP, SP] blocks out of the logical mirrors).
Device-state invariant: a FREE page always holds pristine init state
(pages are re-initialized when freed, and a never-mapped page was
init at allocation of the pool), so free pages compute no sends and
carry no stale tenant state.

Checkpoints, row repair, and migration all serialize the LOGICAL form
(LayoutXlate translates at the boundary), which keeps snapshot bytes
identical across pool layouts and lets rooms migrate dense↔paged.

Tick variants (`paged_kernel` ctor knob / `plane.paged_kernel`): "off"
runs the stock full-pool jit tick; "auto" (TPU) / "on" / "interpret"
run the live-extent path — a timed decide dispatch through the fused
`ops/paged_kernel.py` grid-over-live-pages kernel (recorded per tick as
`paged_kernel_ms` + grid steps) and a donated-state rest phase, with
`live_rows` refreshed in `_sync_pages` under the same epoch pinning as
`_step_xlate`. Zero live pages short-circuits to a broadcast dead-page
tick. Forced "off" under a pool mesh (the sharded tick stays stock).

Staleness discipline (graftcheck GC08): page indices are only valid
under the pager epoch they were read at. Everything here that crosses a
thread or an await uses an epoch-pinned `LayoutXlate` snapshot —
`_step_xlate` is pinned at upload time (when the device table last
matched the pager) and used by the worker thread to translate that
step's outputs/mirror; fresh page indices are re-fetched under the
state lock. Inputs staged between an epoch bump and the next upload are
bounded one tick stale: packets for pages that moved or freed land on
re-initialized (unsubscribed) pages and drop, never misroute.
"""

from __future__ import annotations

import functools
import time
from typing import Any

import jax
import numpy as np

from livekit_server_tpu.models import paged, plane
from livekit_server_tpu.ops import pacer
from livekit_server_tpu.runtime.pager import RoomPager
from livekit_server_tpu.runtime.plane_runtime import (
    PlaneRuntime,
    _build_ctrl_delta,
)
from livekit_server_tpu.runtime.slots import PagedSlotAllocator


@functools.lru_cache(maxsize=None)
def _build_paged_step(audio_params, bwe_params, red_enabled=True):
    """Packed-wire paged step (the pooled analog of _build_step): one
    input upload, one output fetch; state donated, table read-only."""

    def tick(state, table, pkt, fb, tf, tick_ms, roll_quality):
        inp = plane.unpack_tick_inputs(pkt, fb, tf, tick_ms, roll_quality)
        state, out = paged.paged_plane_tick(
            state, inp, table, audio_params, bwe_params,
            red_enabled=red_enabled,
        )
        return state, plane.pack_tick_outputs(out)

    return jax.jit(tick, donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def _build_live_decide(interpret: bool):
    """Phase 0 of the live-extent tick (ops/paged_kernel.decide_pages) as
    its OWN dispatch, so the worker thread can time the kernel span
    (`paged_kernel_ms`) separately from the rest of the device step. The
    fb/tf operands ride along only to reuse unpack_tick_inputs — the
    decide algebra reads packet fields, XLA drops the rest."""
    from livekit_server_tpu.ops import paged_kernel

    def decide(sel, is_svc, is_video, subscribed, sub_muted,
               published, pub_muted, pkt, fb, tf, tick_ms, roll, live_rows):
        inp = plane.unpack_tick_inputs(pkt, fb, tf, tick_ms, roll)
        base = subscribed & ~sub_muted & (published & ~pub_muted)[:, :, None]
        return paged_kernel.decide_pages(
            sel, is_svc, is_video, base, inp, live_rows,
            wire_overhead=pacer.WIRE_OVERHEAD_BYTES,
            use_pallas=None, interpret=interpret,
        )

    return jax.jit(decide)


@functools.lru_cache(maxsize=None)
def _build_live_rest(audio_params, bwe_params, red_enabled=True):
    """Phases 1–2 + scatter of the live-extent tick, consuming the
    LiveDecide produced by _build_live_decide. State donated, table and
    live-row indices read-only."""

    def rest(state, table, live_rows, live_inv, dec, pkt, fb, tf,
             tick_ms, roll_quality):
        inp = plane.unpack_tick_inputs(pkt, fb, tf, tick_ms, roll_quality)
        state, out = paged.paged_plane_tick_live(
            state, inp, table, live_rows, live_inv, dec,
            audio_params, bwe_params, red_enabled,
        )
        return state, plane.pack_tick_outputs(out)

    return jax.jit(rest, donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def _build_dead_step(audio_params, bwe_params, red_enabled, max_tpages):
    """Zero-live-pages tick: no grid to schedule. State is untouched (the
    freeze-the-dead invariant — every free page already holds pristine
    init state) and the outputs are the representative dead page's,
    broadcast across the pool."""

    def tick(state, pkt, fb, tf, tick_ms, roll_quality):
        inp = plane.unpack_tick_inputs(pkt, fb, tf, tick_ms, roll_quality)
        P, TP, K = inp.sn.shape
        SP = inp.estimate.shape[1]
        rep = paged.dead_page_outputs(
            max_tpages, TP, K, SP, inp,
            audio_params, bwe_params, red_enabled,
        )
        out = paged.broadcast_dead_outputs(rep, P)
        return state, plane.pack_tick_outputs(out)

    # state passes through untouched, so donation is a pure alias (no
    # copy either way on CPU, but on TPU the undonated form re-
    # materializes the whole pool in fresh HBM every dead tick).
    return jax.jit(tick, donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def _build_table_delta():
    return jax.jit(paged.apply_table_delta, donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def _build_reinit():
    return jax.jit(paged.reinit_pages, donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def _build_moves():
    return jax.jit(paged.move_state_rows, donate_argnums=(0,))


def _p2(n: int) -> int:
    """Pow2 padding bucket so the row scatters compile once per bucket."""
    return 1 << (n - 1).bit_length() if n > 1 else 1


def _pad_rows(to: int, *arrays):
    """Pad each array's leading axis to `to` by repeating row 0
    (duplicate scatter indices carry identical values)."""
    out = []
    for a in arrays:
        if 0 < len(a) < to:
            a = np.concatenate([a, np.repeat(a[:1], to - len(a), axis=0)])
        out.append(a)
    return out


class PagedPlaneRuntime(PlaneRuntime):
    """PlaneRuntime over the pooled paged device layout."""

    def __init__(self, dims: paged.PagedDims, *, mesh=None,
                 paged_kernel: str = "auto", **kwargs):
        if not isinstance(dims, paged.PagedDims):
            raise TypeError("PagedPlaneRuntime requires paged.PagedDims")
        self.pdims = dims
        # Live-extent tick variant (ops/paged_kernel.py): "auto" runs it
        # where the Pallas kernel actually exists (TPU), "on" forces the
        # live path everywhere (kernel on TPU, gathered-decide fallback
        # on CPU), "interpret" runs the kernel in Pallas interpret mode
        # (CPU CI parity), "off" keeps the stock full-pool tick.
        if isinstance(paged_kernel, bool):
            paged_kernel = "on" if paged_kernel else "off"
        if paged_kernel not in ("auto", "on", "off", "interpret"):
            raise ValueError(
                f"paged_kernel must be auto|on|off|interpret, "
                f"got {paged_kernel!r}"
            )
        if mesh is not None and paged_kernel != "off":
            # The fused path is single-chip: its cross-page member
            # gathers defeat GSPMD pool sharding. The sharded pooled
            # tick stays the stock one (parallel/mesh.py page_sharding).
            from livekit_server_tpu.utils.logger import Logger

            if paged_kernel != "auto":
                Logger(plane="paged").warn(
                    "paged_kernel forced off: pool-mesh sharding uses "
                    "the stock pooled tick", requested=paged_kernel,
                )
            paged_kernel = "off"
        self._pk_mode = paged_kernel
        self._pk_interpret = paged_kernel == "interpret"
        self._pk_enabled = paged_kernel in ("on", "interpret") or (
            paged_kernel == "auto" and jax.default_backend() == "tpu"
        )
        self._kernel_s_scratch = 0.0
        self._kernel_steps_scratch = 0
        self.pager = RoomPager(
            dims.rooms, dims.tracks, dims.subs,
            tpage=dims.tpage, spage=dims.spage, pool_pages=dims.pool_pages,
        )
        # Pool-axis mesh kept separate: the base class's mesh path is the
        # shard_map'd DENSE tick; the paged tick has cross-page gathers,
        # so its mesh story is plain GSPMD jit over page-sharded leaves.
        self._pmesh = mesh
        self._xlate: paged.LayoutXlate | None = None
        self._xlate_epoch = -1
        self._lfill = None
        self._pfill = None
        P, MT = dims.pool_pages, dims.max_tpages
        # What the DEVICE table should currently hold (pager mirrors as
        # of the last page sync) — the SDC audit's comparison baseline;
        # the live pager may legitimately be ahead (queued delta).
        self._dev_tables = (
            np.full(P, -1, np.int32), np.full(P, -1, np.int32),
            np.full(P, -1, np.int32), np.full((P, MT), -1, np.int32),
        )
        self.table_repairs = 0
        # Live-row cache for the kernel grid and the live-fraction gauge:
        # derived from `_dev_tables` (the device table as of the last
        # page sync), refreshed by `_sync_pages` — same epoch pinning as
        # `_step_xlate` (GC08). `_live_rows` is the pow2-padded mapped
        # pool ids (padding repeats a LIVE row — models/paged.py needs a
        # live representative, never a dead one); `_live_inv` maps pool
        # id → compact index (dead rows 0, read only clipped+masked).
        self._live_rows = np.empty(0, np.int32)
        self._live_inv = np.zeros(P, np.int32)
        self._live_n = 0
        super().__init__(dims.logical, mesh=None, **kwargs)
        # The base ctor wired a dense SlotAllocator; rooms actually claim
        # page grids, so admission/occupancy route through the pager.
        self.slots = PagedSlotAllocator(self.pager)
        self._step_xlate = self._xlate_cached()
        self.stats.update({
            "page_delta_uploads": 0, "page_rows_uploaded": 0,
            "pages_reinit": 0, "page_moves": 0,
            # Kernel grid accounting: steps == the padded live-page
            # bucket per tick — the "work ∝ live pages" probe the bench
            # and tier-1 assert against.
            "paged_kernel_ticks": 0, "paged_kernel_steps": 0,
        })

    # -- seam hooks -------------------------------------------------------

    def _init_device_state(self):
        import jax.numpy as jnp

        del jnp  # (import kept symmetrical with the base hook style)
        self.table = paged.init_table(self.pdims)
        self._page_template = paged.page_init_template(self.pdims)
        return plane.init_state(self.pdims.pooled())

    def _init_step(self) -> None:
        self._paged_step = _build_paged_step(self._ap, self._bp, self.red_enabled)
        self._apply_delta = _build_ctrl_delta()
        self._table_delta = _build_table_delta()
        self._reinit = _build_reinit()
        self._move = _build_moves()
        if self._pmesh is not None:
            from livekit_server_tpu.parallel.mesh import shard_pool

            self.state = shard_pool(self.state, self._pmesh)
            self.table = shard_pool(self.table, self._pmesh)

        def step(state, *packed):
            # Reads self.table at call time: the upload that precedes
            # each dispatch leaves the device table at the pinned epoch.
            return self._paged_step(state, self.table, *packed)

        self._step = step
        if self._pk_enabled:
            self._live_decide = _build_live_decide(self._pk_interpret)
            self._live_rest = _build_live_rest(
                self._ap, self._bp, self.red_enabled
            )
            self._dead_step = _build_dead_step(
                self._ap, self._bp, self.red_enabled, self.pdims.max_tpages
            )
            self._step = self._live_step

    def _live_step(self, state, *packed):
        """Live-extent device step: phase-0 kernel dispatch timed into
        `_kernel_s_scratch` (the worker thread copies it onto the
        StagedTick in `_device_step` — same thread, no race), then the
        rest of the tick. Live rows read at call time: `_sync_pages` at
        the preceding upload edge pinned them with the device table."""
        pkt, fb, tf, tick_ms, roll = packed
        lr, li = self._live_rows, self._live_inv
        if lr.shape[0] == 0:
            self._kernel_s_scratch = 0.0
            self._kernel_steps_scratch = 0
            return self._dead_step(state, pkt, fb, tf, tick_ms, roll)
        t0 = time.perf_counter()
        dec = self._live_decide(
            state.sel, state.meta.is_svc, state.meta.is_video,
            state.ctrl.subscribed, state.ctrl.sub_muted,
            state.meta.published, state.meta.pub_muted,
            pkt, fb, tf, tick_ms, roll, lr,
        )
        rest = self._live_rest(
            state, self.table, lr, li, dec, pkt, fb, tf, tick_ms, roll
        )
        # The span probe blocks AFTER phase 1 is dispatched: the device
        # queue already holds the rest of the tick, so the wait overlaps
        # useful work instead of opening a dispatch bubble. The block
        # itself is the declared kernel-span measurement seam.
        jax.block_until_ready(dec)  # graftcheck: disable=GC12
        self._kernel_s_scratch = time.perf_counter() - t0
        self._kernel_steps_scratch = int(lr.shape[0])
        return rest

    def _pack_inputs(self, inp: plane.TickInputs) -> tuple:
        pkt, fb, tf, tick_ms, roll = plane.pack_tick_inputs(inp)
        pkt_p, fb_p, tf_p = self._xlate_cached().stage_inputs(
            np.asarray(pkt), np.asarray(fb), np.asarray(tf)
        )
        return (pkt_p, fb_p, tf_p, tick_ms, roll)

    def _unpack_outputs(self, buf) -> plane.TickOutputs:
        out = plane.unpack_tick_outputs(
            np.asarray(buf), self.pdims.pooled(), self.red_enabled
        )
        # _step_xlate, not _xlate_cached(): the event loop may have
        # alloc'd/freed pages while this step ran on the worker thread —
        # the outputs belong to the table the step actually saw (GC08).
        return self._step_xlate.outputs_to_logical(out)

    def _sel_mirror(self, state) -> tuple:
        sel_np = jax.tree.map(np.asarray, state.sel)
        sel_lg = self._step_xlate.sel_to_logical(sel_np, self._logical_fill().sel)
        return (
            sel_lg.current_spatial, sel_lg.current_temporal,
            sel_lg.target_spatial, sel_lg.target_temporal,
        )

    # -- layout translation caches ---------------------------------------

    def _xlate_cached(self) -> paged.LayoutXlate:
        """The translation snapshot for the CURRENT pager epoch. The
        index arrays are copied, so a cached instance stays valid as a
        point-in-time snapshot after further pager churn."""
        if self._xlate is None or self._xlate_epoch != self.pager.epoch:
            self._xlate = paged.LayoutXlate(
                self.pdims,
                self.pager.pg_room.copy(),
                self.pager.pg_tp.copy(),
                self.pager.pg_sp.copy(),
            )
            self._xlate_epoch = self.pager.epoch
        return self._xlate

    def _logical_fill(self):
        """Logical-dense init-state template (numpy, broadcast views):
        the fill for unmapped regions in pooled→logical translation and
        the shape/dtype spec for snapshot validation."""
        if self._lfill is None:
            d = self.dims
            tpl = plane.init_state(plane.PlaneDims(1, d.tracks, d.pkts, d.subs))
            self._lfill = jax.tree.map(
                lambda a: np.broadcast_to(
                    np.asarray(a), (d.rooms,) + a.shape[1:]
                ),
                tpl,
            )
        return self._lfill

    def _pooled_fill(self):
        if self._pfill is None:
            P = self.pdims.pool_pages
            tpl = jax.tree.map(np.asarray, self._page_template)
            self._pfill = jax.tree.map(
                lambda a: np.broadcast_to(a, (P,) + a.shape[1:]), tpl
            )
        return self._pfill

    # -- page-table delta lane --------------------------------------------

    def _sync_pages(self) -> None:
        """Drain the pager's pending page events into the device: table
        rows, compaction row moves, then fresh/freed page re-init (moves
        must land before the re-init wipes their sources). Re-pins
        `_step_xlate` — after this, device table == pager mirrors."""
        import jax.numpy as jnp

        delta = self.pager.drain_delta()
        if not delta.empty:
            (page_rows, tm, pgr, pgt, pgs, room_rows, rps) = (
                paged.pack_table_delta(self.pager, delta)
            )
            page_rows, tm, pgr, pgt, pgs = _pad_rows(
                _p2(len(page_rows)), page_rows, tm, pgr, pgt, pgs
            )
            room_rows, rps = _pad_rows(_p2(len(room_rows)), room_rows, rps)
            self.table = self._table_delta(
                self.table, page_rows, tm, pgr, pgt, pgs, room_rows, rps
            )
            if len(delta.moves):
                src, dst = delta.moves[:, 0], delta.moves[:, 1]
                src, dst = _pad_rows(_p2(len(src)), src, dst)
                self.state = self._move(
                    self.state, jnp.asarray(src), jnp.asarray(dst)
                )
                self.stats["page_moves"] += len(delta.moves)
            reinit = np.concatenate([delta.fresh_pages, delta.freed_pages])
            if len(reinit):
                (reinit,) = _pad_rows(_p2(len(reinit)), reinit.astype(np.int32))
                self.state = self._reinit(
                    self.state, jnp.asarray(reinit), self._page_template
                )
                self.stats["pages_reinit"] += len(reinit)
            # Rooms whose grid changed must re-assert ctrl onto their
            # (possibly fresh/relocated) pages at this same edge.
            self._dirty_rows.update(int(r) for r in delta.rooms)
            self._dev_tables = (
                self.pager.pg_room.copy(), self.pager.pg_tp.copy(),
                self.pager.pg_sp.copy(), self.pager.tmembers.copy(),
            )
            if self.integrity is not None:
                # Page identity changed under the audit mirror's feet;
                # re-baseline instead of flagging relocated cursors.
                self.integrity.on_layout_change()
            self.stats["page_delta_uploads"] += 1
            self.stats["page_rows_uploaded"] += len(page_rows)
            self._refresh_live_rows()
        self._step_xlate = self._xlate_cached()

    def _refresh_live_rows(self) -> None:
        """Rebuild the live-row cache from the device-table mirror (see
        __init__). Called whenever `_dev_tables` changes; the pow2 bucket
        keeps the kernel grid compiling once per size class."""
        pg_room = self._dev_tables[0]
        rows = np.nonzero(pg_room >= 0)[0].astype(np.int32)
        inv = np.zeros(len(pg_room), np.int32)
        inv[rows] = np.arange(len(rows), dtype=np.int32)
        self._live_n = len(rows)
        if len(rows):
            (rows,) = _pad_rows(_p2(len(rows)), rows)
        self._live_rows = rows
        self._live_inv = inv

    def _upload_ctrl(self) -> None:
        """Page lane first (table delta / moves / re-init), then the
        dirty rooms' ctrl shipped at PAGE granularity: each page row is a
        [TP] / [TP, SP] block gathered from the logical host mirrors, so
        the pooled apply_ctrl_delta scatter is unchanged — page ids are
        just its row indices."""
        self._sync_pages()
        rows = self._dirty_rows
        if not self._ctrl_dirty and not rows:
            return
        if self._ctrl_dirty or len(rows) > self.ctrl_delta_max_rows:
            page_rows = np.nonzero(self.pager.pg_room >= 0)[0].astype(np.int32)
            self.stats["ctrl_full_uploads"] += 1
        else:
            parts = [self.pager.pages_of_room(int(r)) for r in sorted(rows)]
            page_rows = (
                np.concatenate(parts).astype(np.int32)
                if parts else np.empty(0, np.int32)
            )
            self.stats["ctrl_delta_uploads"] += 1
            self.stats["ctrl_delta_rows"] += len(rows)
        if len(page_rows):
            pr, meta_rows, ctrl_rows = self._pack_ctrl_pages(
                self.meta, self._effective_ctrl(), page_rows,
                pad_to=_p2(len(page_rows)),
            )
            self.state = self._apply_delta(self.state, pr, meta_rows, ctrl_rows)
            self.stats["ctrl_upload_bytes"] += meta_rows.nbytes + ctrl_rows.nbytes
        self._dirty_rows = set()
        self._ctrl_dirty = False

    def _pack_ctrl_pages(self, meta, ctrl, page_rows, pad_to=None):
        """pack_ctrl_rows at page granularity: gather each mapped page's
        [TP] meta / [TP, SP] ctrl block out of the logical mirrors."""
        d = self.pdims
        pr = np.sort(np.asarray(page_rows, np.int32))
        if pad_to is not None and len(pr) < pad_to:
            pr = np.concatenate([pr, np.repeat(pr[:1], pad_to - len(pr))])
        rooms = self.pager.pg_room[pr]
        tps = self.pager.pg_tp[pr]
        sps = self.pager.pg_sp[pr]
        meta_rows = np.stack([
            np.asarray(m)
            .reshape(d.rooms, d.max_tpages, d.tpage)[rooms, tps]
            .astype(np.int32)
            for m in meta
        ])
        ctrl_rows = np.stack([
            np.asarray(c)
            .reshape(d.rooms, d.max_tpages, d.tpage, d.max_spages, d.spage)
            [rooms, tps, :, sps]
            .astype(np.int32)
            for c in ctrl
        ])
        return pr, meta_rows, ctrl_rows

    # -- kernel span accounting --------------------------------------------

    def _device_step(self, st):
        """Stamp the kernel span/grid-steps scratches (written by
        `_live_step` on this same worker thread) onto the StagedTick
        before it crosses back to the event loop."""
        out = super()._device_step(st)
        if out is not None and self._pk_enabled:
            st.kernel_s = self._kernel_s_scratch
            st.kernel_steps = self._kernel_steps_scratch
        return out

    def _tick_rec_extras(self, st) -> dict:
        """recent_ticks extras + the per-tick stats fold (runs exactly
        once per completed tick, on the event loop)."""
        if not self._pk_enabled:
            return {}
        self.stats["paged_kernel_ticks"] += 1
        self.stats["paged_kernel_steps"] += st.kernel_steps
        return {
            "paged_kernel_ms": round(st.kernel_s * 1000.0, 3),
            "page_live_fraction": round(
                self._live_n / self.pdims.pool_pages, 4
            ),
        }

    # -- integrity plane ---------------------------------------------------

    def map_audit_mask(self, mask: np.ndarray) -> np.ndarray:
        """[P] per-page audit mask → [R] per-room mask, plus the page-
        table SDC check: the device table is delta-maintained from the
        pager's canonical mirrors, so any divergence from the last-sync
        snapshot is corruption — repair the table rows from the host
        canonical immediately and flag the touched rooms (their state
        computed through a corrupt indirection, so it is suspect too).
        Runs on the worker thread with state_lock held (via maybe_audit)."""
        from livekit_server_tpu.runtime import integrity

        room_mask = self._step_xlate.page_mask_to_rooms(mask).astype(np.int32)
        bad_rooms = self._audit_page_table()
        if bad_rooms is not None:
            room_mask[bad_rooms] |= np.int32(integrity.BIT_TABLE)
        return room_mask

    def _audit_page_table(self):
        mr, mt, ms, mtm = self._dev_tables
        dr = np.asarray(self.table.pg_room)
        dt = np.asarray(self.table.pg_tp)
        ds = np.asarray(self.table.pg_sp)
        dtm = np.asarray(self.table.tmembers)
        bad = (dr != mr) | (dt != mt) | (ds != ms) | (dtm != mtm).any(axis=1)
        if not bad.any():
            return None
        rows = np.nonzero(bad)[0].astype(np.int32)
        # Host canonical is authoritative: re-scatter the diverged rows.
        self.table = self._table_delta(
            self.table, rows, mtm[rows], mr[rows], mt[rows], ms[rows],
            np.empty(0, np.int32),
            np.empty((0, self.pager.rooms_pages.shape[1]), np.int32),
        )
        self.table_repairs += len(rows)
        R = self.dims.rooms
        bad_rooms = np.zeros(R, bool)
        for owner in (mr[bad], dr[bad]):  # true owner + phantom pointee
            valid = (owner >= 0) & (owner < R)
            bad_rooms[owner[valid]] = True
        return bad_rooms

    # -- checkpoint / repair / migration (LOGICAL wire form) ---------------

    def _to_logical_state(self):
        """Device pooled state → logical PlaneState (numpy). Flushes the
        page lane first so the translation epoch matches the device
        table. Callers hold state_lock."""
        self._sync_pages()
        pooled_np = jax.tree.map(np.asarray, self.state)
        return self._xlate_cached().state_to_logical(
            pooled_np, self._logical_fill()
        )

    def _write_logical_row(self, row: int, leaves: list) -> None:
        """Scatter one LOGICAL room row into every page of the room's
        grid (re-establishing the duplicate-everywhere invariant). Page
        ids are fetched fresh under the lock after a page-lane flush —
        never held across an await (GC08)."""
        import jax.numpy as jnp

        self._sync_pages()
        pages = self.pager.pages_of_room(row)
        if len(pages) == 0:
            return
        d = self.pdims
        tps = self.pager.pg_tp[pages].astype(np.int64)
        sps = self.pager.pg_sp[pages].astype(np.int64)
        _, sdef = jax.tree.flatten(self.state)
        row_tree = jax.tree.unflatten(sdef, leaves)
        kinds = paged._kind_tree(row_tree)

        def rowfun(kind, lrow, pooled_leaf):
            a = np.ascontiguousarray(np.asarray(lrow))
            if kind == paged._K_TRACK:
                w = a.size // d.tracks
                v = a.reshape(d.max_tpages, d.tpage, w)[tps]
            elif kind == paged._K_SUB:
                w = a.size // d.subs
                v = a.reshape(d.max_spages, d.spage, w)[sps]
            else:
                w = a.size // (d.tracks * d.subs)
                v = a.reshape(
                    d.max_tpages, d.tpage, d.max_spages, d.spage, w
                )[tps, :, sps]
            return v.reshape((len(pages),) + pooled_leaf.shape[1:])

        rows_tree = jax.tree.map(rowfun, kinds, row_tree, self.state)
        pj = jnp.asarray(pages)
        self.state = jax.tree.map(
            lambda leaf, rws: leaf.at[pj].set(jnp.asarray(rws, leaf.dtype)),
            self.state, rows_tree,
        )

    def snapshot(self) -> dict[str, Any]:
        logical = self._to_logical_state()
        flat, _ = jax.tree.flatten(logical)
        return {
            "tick_index": self.tick_index,
            "arrays": [np.asarray(a) for a in flat],
            "munger": self.munger.snapshot(),
        }

    def snapshot_room(self, row: int) -> dict[str, Any]:
        logical = self._to_logical_state()
        flat, treedef = jax.tree.flatten(logical)
        arrays = [np.array(a[row]) for a in flat]
        tree = jax.tree.unflatten(treedef, arrays)
        tree = tree._replace(
            meta=plane.TrackMeta(*[np.array(m[row]) for m in self.meta]),
            ctrl=plane.SubControl(*[np.array(c[row]) for c in self.ctrl]),
        )
        return {
            "arrays": jax.tree.flatten(tree)[0]
            + self.munger.snapshot_room(row)
        }

    def repair_room_row(self, row: int, snap: dict[str, Any]) -> None:
        lflat, _ = jax.tree.flatten(self._logical_fill())
        self._check_row_leaves(lflat, snap["arrays"])
        dev_arrays = snap["arrays"][: len(lflat)]
        self.munger.restore_room(row, snap["arrays"][len(lflat):])
        self._write_logical_row(row, dev_arrays)
        # Same post-repair hygiene as the dense path: the replay ring
        # references pre-repair SN spaces; host mirrors stay
        # authoritative and re-assert at the next edge.
        self.host_seq.clear_room(row)
        self._dirty_rows.add(row)

    def restore_room(self, row: int, snap: dict[str, Any]) -> None:
        self.host_seq.clear_room(row)
        lflat, ldef = jax.tree.flatten(self._logical_fill())
        self._check_row_leaves(lflat, snap["arrays"])
        dev_arrays = snap["arrays"][: len(lflat)]
        snap_tree = jax.tree.unflatten(
            ldef, [np.asarray(a) for a in dev_arrays]
        )
        # The incoming room's live tracks may exceed this row's current
        # page extent (the adopter allocated minimally): grow the grid to
        # cover every published track column BEFORE writing the row, so
        # migrated publisher state lands instead of truncating.
        pub = np.asarray(snap_tree.meta.published)
        live = np.nonzero(pub)[0]
        need_t = int(live[-1]) + 1 if len(live) else 1
        if len(self.pager.pages_of_room(row)) == 0:
            self.pager.alloc_room(row, tracks=need_t)
        else:
            self.pager.grow_room(row, tracks=need_t)
        self.munger.restore_room(row, snap["arrays"][len(lflat):])
        self._write_logical_row(row, dev_arrays)
        for host_arr, snap_arr in zip(self.meta, snap_tree.meta):
            host_arr[row] = snap_arr
        # Subscription masks are not carried (see the dense docstring):
        # destination sub columns are allocated fresh.
        self.ctrl.subscribed[row] = False
        self.ctrl.sub_muted[row] = False
        self.ctrl.max_spatial[row] = plane.MAX_LAYERS - 1
        self.ctrl.max_temporal[row] = 3
        self._dirty_rows.add(row)
        if self.integrity is not None:
            self.integrity.on_row_restore(row)

    def restore(self, snap: dict[str, Any]) -> None:
        import jax.numpy as jnp

        from livekit_server_tpu.runtime.munge import HostMunger

        self._sync_pages()
        lflat, ldef = jax.tree.flatten(self._logical_fill())
        arrays = snap.get("arrays")
        if arrays is None or len(arrays) != len(lflat):
            raise ValueError(
                f"full snapshot has {0 if arrays is None else len(arrays)} "
                f"leaves, plane has {len(lflat)} — snapshot/plane versions "
                "differ"
            )
        for i, (leaf, a) in enumerate(zip(lflat, arrays)):
            a = np.asarray(a)
            if tuple(a.shape) != tuple(leaf.shape):
                raise ValueError(
                    f"full snapshot leaf {i} shape {tuple(a.shape)} != "
                    f"plane shape {tuple(leaf.shape)} — dims mismatch"
                )
            if not np.can_cast(a.dtype, np.dtype(leaf.dtype), casting="same_kind"):
                raise ValueError(
                    f"full snapshot leaf {i} dtype {a.dtype} incompatible "
                    f"with plane dtype {np.dtype(leaf.dtype)}"
                )
        logical = jax.tree.unflatten(ldef, [np.asarray(a) for a in arrays])
        # Rooms live in THIS node's pager keep their state; logical rows
        # without pages (not resident here) drop — the checkpoint stays
        # layout-independent, placement is the restoring node's business.
        pooled = self._xlate_cached().state_to_pooled(
            logical, self._pooled_fill()
        )
        pflat, pdef = jax.tree.flatten(pooled)
        self.state = jax.tree.unflatten(pdef, [jnp.asarray(a) for a in pflat])
        if self._pmesh is not None:
            from livekit_server_tpu.parallel.mesh import shard_pool

            self.state = shard_pool(self.state, self._pmesh)
        if "munger" in snap:
            self.munger.restore(snap["munger"])
        else:
            self.munger = HostMunger(self.dims)
        self.tick_index = snap["tick_index"]
        self._ctrl_dirty = True
        if self.integrity is not None:
            self.integrity.on_full_restore()

    # -- admin -------------------------------------------------------------

    def compact(self) -> int:
        """Defragment the page pool (host side now; the device moves +
        table delta replay at the next tick-edge sync). Returns the
        number of device row moves queued."""
        return len(self.pager.compact())

    def pager_stats(self) -> dict:
        st = self.pager.stats()
        st["table_repairs"] = self.table_repairs
        st["paged_kernel"] = self._pk_mode if self._pk_enabled else "off"
        st["page_live_fraction"] = round(
            self._live_n / self.pdims.pool_pages, 4
        )
        return st
