"""AV1 Dependency Descriptor (DD) header extension: parse + patch.

Reference parity: pkg/sfu/dependencydescriptor/ — bitstreamreader.go
(MSB-first bit reader incl. the ns(n) non-symmetric encoding),
dependencydescriptorreader.go:57 (mandatory fields, extended flags,
template dependency structure, active-decode-targets bitmask) and the
writer's bitmask placement (dependencydescriptorwriter.go:254). This is
the byte half the device-side decode-target selection (ops/svc.py) needs:
structures are parsed once per keyframe on the host, cached per SSRC, and
every packet's (spatial, temporal) comes from a template-table lookup.

Scope: everything the SFU forwards or rewrites — mandatory fields,
extended flags, the full template dependency structure (layers, DTIs,
fdiffs, chains, resolutions), the active-decode-targets bitmask with
its exact bit offset so egress can patch it in place, AND the per-frame
custom dtis / fdiffs / chain fdiffs (frame_dependency_definition): the
reference reads them (dependencydescriptorreader.go readFrameDtis /
readFrameFdiffs / readFrameChains) and its selector prefers a frame's
custom DTIs over the template's when deciding per-decode-target
forwarding — so `effective_dtis`/`refine_layer` below feed the same
override into this build's layer-based selection.
"""

from __future__ import annotations

from dataclasses import dataclass, field

MAX_TEMPLATES = 64
MAX_SPATIAL = 4
MAX_TEMPORAL = 8

# DecodeTargetIndication (2-bit): not present / discardable / switch / required
DTI_NOT_PRESENT = 0


class BitReader:
    """MSB-first bit reader (bitstreamreader.go)."""

    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0  # bit position

    def ok(self) -> bool:
        return self.pos <= len(self.data) * 8

    def remaining(self) -> int:
        return len(self.data) * 8 - self.pos

    def read_bits(self, n: int) -> int:
        if self.pos + n > len(self.data) * 8:
            raise ValueError("DD truncated")
        v = 0
        pos = self.pos
        for _ in range(n):
            byte = self.data[pos >> 3]
            v = (v << 1) | ((byte >> (7 - (pos & 7))) & 1)
            pos += 1
        self.pos = pos
        return v

    def read_bool(self) -> bool:
        return bool(self.read_bits(1))

    def read_ns(self, num_values: int) -> int:
        """ns(n) non-symmetric unsigned (bitstreamreader.go:102)."""
        if num_values <= 1:
            return 0
        width = num_values.bit_length()
        num_min = (1 << width) - num_values
        v = self.read_bits(width - 1)
        if v < num_min:
            return v
        return (v << 1) + self.read_bits(1) - num_min


class BitWriter:
    """MSB-first writer (test/round-trip support; bitstreamwriter.go)."""

    def __init__(self) -> None:
        self.bits: list[int] = []

    def write_bits(self, v: int, n: int) -> None:
        for i in range(n - 1, -1, -1):
            self.bits.append((v >> i) & 1)

    def write_ns(self, v: int, num_values: int) -> None:
        if num_values <= 1:
            return
        width = num_values.bit_length()
        num_min = (1 << width) - num_values
        if v < num_min:
            self.write_bits(v, width - 1)
        else:
            self.write_bits(v + num_min, width)

    def tobytes(self) -> bytes:
        out = bytearray((len(self.bits) + 7) // 8)
        for i, b in enumerate(self.bits):
            if b:
                out[i >> 3] |= 1 << (7 - (i & 7))
        return bytes(out)


@dataclass
class Template:
    spatial: int
    temporal: int
    dtis: list[int] = field(default_factory=list)    # per decode target
    fdiffs: list[int] = field(default_factory=list)
    chain_diffs: list[int] = field(default_factory=list)


@dataclass
class Structure:
    """FrameDependencyStructure (dependencydescriptorextension.go)."""

    structure_id: int
    num_decode_targets: int
    templates: list[Template]
    num_chains: int = 0
    protected_by: list[int] = field(default_factory=list)
    resolutions: list[tuple[int, int]] = field(default_factory=list)

    def decode_target_layers(self) -> list[tuple[int, int]]:
        """Per decode target: (spatial, temporal) = max layer of any
        template where the DT is present (the dt → layer map ops/svc's
        selection consumes). Memoized — structures are parsed once per
        keyframe and never mutated, and this runs in per-packet paths."""
        cached = getattr(self, "_dt_layers", None)
        if cached is not None:
            return cached
        out = []
        for d in range(self.num_decode_targets):
            sp = tp = 0
            for t in self.templates:
                if d < len(t.dtis) and t.dtis[d] != DTI_NOT_PRESENT:
                    sp = max(sp, t.spatial)
                    tp = max(tp, t.temporal)
            out.append((sp, tp))
        object.__setattr__(self, "_dt_layers", out)
        return out


@dataclass
class Descriptor:
    first_packet_in_frame: bool
    last_packet_in_frame: bool
    template_id: int          # raw 6-bit field (index is relative to
                              # structure_id modulo 64)
    frame_number: int
    structure: Structure | None = None          # attached this packet
    active_mask: int | None = None
    active_mask_bit_off: int = -1               # bit offset of the mask
    active_mask_bits: int = 0
    # frame_dependency_definition overrides (None = use the template's)
    custom_dtis: list[int] | None = None
    custom_fdiffs: list[int] | None = None
    custom_chain_fdiffs: list[int] | None = None

    def _template(self, structure: Structure) -> Template | None:
        idx = (self.template_id + MAX_TEMPLATES - structure.structure_id) % MAX_TEMPLATES
        if idx >= len(structure.templates):
            return None
        return structure.templates[idx]

    def layer(self, structure: Structure) -> tuple[int, int]:
        """(spatial, temporal) of this packet via the template table."""
        t = self._template(structure)
        if t is None:
            return 0, 0
        return t.spatial, t.temporal

    def effective_dtis(self, structure: Structure) -> list[int] | None:
        """Per-decode-target indications for THIS frame: the custom
        override when present, else the template's (the precedence the
        reference's DD selector applies)."""
        if self.custom_dtis is not None:
            return self.custom_dtis
        t = self._template(structure)
        return t.dtis if t is not None and t.dtis else None

    def refine_layer(self, structure: Structure) -> tuple[int, int]:
        """(spatial, effective temporal) honoring per-frame DTIs.

        The template gives the frame's nominal (s, t). When DTIs mark the
        frame not-present for every decode target at temporal <= t (a
        per-frame skip — only expressible via custom dtis), the frame's
        effective temporal id is the lowest temporal of any decode target
        that still needs it, so layer-based selection drops it for
        subscribers below that point exactly as per-DT selection would.
        Absent from every decode target at this spatial → (s, MAX_TEMPORAL):
        forwardable to no one.

        Frames WITHOUT custom dtis take the template fast path (one table
        lookup — this runs per packet at ingest; template dtis are
        consistent with the template's own (s, t) by construction)."""
        sp, tp = self.layer(structure)
        dtis = self.custom_dtis
        if dtis is None:
            return sp, tp
        layers = structure.decode_target_layers()
        needed = [
            layers[d][1]
            for d in range(min(len(dtis), len(layers)))
            if dtis[d] != DTI_NOT_PRESENT and layers[d][0] >= sp
        ]
        if not needed:
            return sp, MAX_TEMPORAL
        return sp, max(tp, min(needed))


def parse(data: bytes) -> Descriptor:
    """Parse one DD extension payload (dependencydescriptorreader.go:57).
    Raises ValueError on truncation/overflow."""
    r = BitReader(data)
    first = r.read_bool()
    last = r.read_bool()
    template_id = r.read_bits(6)
    frame_number = r.read_bits(16)
    d = Descriptor(first, last, template_id, frame_number)
    if len(data) <= 3:
        return d

    structure_present = r.read_bool()
    active_present = r.read_bool()
    custom_dtis = r.read_bool()
    custom_fdiffs = r.read_bool()
    custom_chains = r.read_bool()

    if structure_present:
        d.structure = _parse_structure(r)
        # Structure attach implies all targets active unless overridden.
        d.active_mask = (1 << d.structure.num_decode_targets) - 1
        d.active_mask_bits = d.structure.num_decode_targets
    if (active_present or custom_dtis or custom_chains) and d.structure is None:
        # These fields' widths come from the sender's structure
        # (decode-target count / chain count); the caller re-parses via
        # parse_with_structure against its cache.
        raise NeedStructure(d)
    if active_present:
        d.active_mask_bit_off = r.pos
        d.active_mask_bits = d.structure.num_decode_targets
        d.active_mask = r.read_bits(d.structure.num_decode_targets)
    _parse_frame_deps(r, d, d.structure, custom_dtis, custom_fdiffs, custom_chains)
    return d


class NeedStructure(ValueError):
    """Raised when a DD needs the sender's cached structure to finish
    (active bitmask width = that structure's decode-target count)."""

    def __init__(self, partial: Descriptor):
        super().__init__("DD requires cached structure")
        self.partial = partial


def parse_with_structure(data: bytes, structure: Structure) -> Descriptor:
    """Parse using a previously-cached structure for field widths."""
    try:
        return parse(data)
    except NeedStructure:
        pass
    r = BitReader(data)
    first = r.read_bool()
    last = r.read_bool()
    template_id = r.read_bits(6)
    frame_number = r.read_bits(16)
    d = Descriptor(first, last, template_id, frame_number)
    r.read_bool()                      # structure_present (False here)
    active_present = r.read_bool()
    custom_dtis = r.read_bool()
    custom_fdiffs = r.read_bool()
    custom_chains = r.read_bool()
    if active_present:
        d.active_mask_bit_off = r.pos
        d.active_mask_bits = structure.num_decode_targets
        d.active_mask = r.read_bits(structure.num_decode_targets)
    _parse_frame_deps(r, d, structure, custom_dtis, custom_fdiffs, custom_chains)
    return d


def _parse_frame_deps(
    r: BitReader, d: Descriptor, structure: Structure | None,
    custom_dtis: bool, custom_fdiffs: bool, custom_chains: bool,
) -> None:
    """frame_dependency_definition (dependencydescriptorreader.go
    readFrameDtis/readFrameFdiffs/readFrameChains): per-frame overrides of
    the template's dtis / fdiffs / chain diffs."""
    if custom_dtis:
        d.custom_dtis = [r.read_bits(2) for _ in range(structure.num_decode_targets)]
    if custom_fdiffs:
        d.custom_fdiffs = []
        while True:
            size = r.read_bits(2)      # next_fdiff_size: 0 ends the list
            if size == 0:
                break
            if len(d.custom_fdiffs) >= MAX_TEMPLATES:
                raise ValueError("too many frame fdiffs")
            d.custom_fdiffs.append(r.read_bits(4 * size) + 1)
    if custom_chains:
        d.custom_chain_fdiffs = [r.read_bits(8) for _ in range(structure.num_chains)]


def _parse_structure(r: BitReader) -> Structure:
    structure_id = r.read_bits(6)
    num_dt = r.read_bits(5) + 1
    # template layers: 2-bit next_layer_idc walk
    templates: list[Template] = []
    spatial = temporal = 0
    while True:
        if len(templates) >= MAX_TEMPLATES:
            raise ValueError("too many DD templates")
        templates.append(Template(spatial=spatial, temporal=temporal))
        idc = r.read_bits(2)
        if idc == 1:      # next temporal
            temporal += 1
            if temporal >= MAX_TEMPORAL:
                raise ValueError("too many temporal layers")
        elif idc == 2:    # next spatial
            spatial += 1
            temporal = 0
            if spatial >= MAX_SPATIAL:
                raise ValueError("too many spatial layers")
        elif idc == 3:    # no more
            break
    for t in templates:
        t.dtis = [r.read_bits(2) for _ in range(num_dt)]
    for t in templates:
        while r.read_bool():
            t.fdiffs.append(r.read_bits(4) + 1)
    s = Structure(structure_id=structure_id, num_decode_targets=num_dt,
                  templates=templates)
    s.num_chains = r.read_ns(num_dt + 1)
    if s.num_chains:
        s.protected_by = [r.read_ns(s.num_chains) for _ in range(num_dt)]
        for t in templates:
            t.chain_diffs = [r.read_bits(4) for _ in range(s.num_chains)]
    if r.read_bool():  # resolutions
        spatial_layers = templates[-1].spatial + 1
        s.resolutions = [
            (r.read_bits(16) + 1, r.read_bits(16) + 1)
            for _ in range(spatial_layers)
        ]
    return s


def patch_active_mask(buf: bytearray, base_bit: int, d: Descriptor, mask: int) -> bool:
    """In-place rewrite of the active-decode-targets bitmask (the
    writer-side seat of dependencydescriptorwriter.go:254): `base_bit` is
    the DD payload's first bit position within `buf`. Returns False when
    this packet carries no bitmask field (nothing to patch — the
    restriction rides the next keyframe's descriptor instead)."""
    if d.active_mask_bit_off < 0 or d.active_mask_bits <= 0:
        return False
    pos = base_bit + d.active_mask_bit_off
    for i in range(d.active_mask_bits):
        bit = (mask >> (d.active_mask_bits - 1 - i)) & 1
        p = pos + i
        if bit:
            buf[p >> 3] |= 1 << (7 - (p & 7))
        else:
            buf[p >> 3] &= ~(1 << (7 - (p & 7)))
    return True


# -- writer (tests + synthetic SVC publishers) ------------------------------

def build(
    first: bool, last: bool, template_id: int, frame_number: int,
    structure: Structure | None = None, active_mask: int | None = None,
    mask_bits: int = 0,
    custom_dtis: list[int] | None = None,
    custom_fdiffs: list[int] | None = None,
    custom_chain_fdiffs: list[int] | None = None,
) -> bytes:
    """Serialize a DD mirroring the reader's field order — used by tests
    and the traffic synthesizer."""
    w = BitWriter()
    w.write_bits(1 if first else 0, 1)
    w.write_bits(1 if last else 0, 1)
    w.write_bits(template_id & 0x3F, 6)
    w.write_bits(frame_number & 0xFFFF, 16)
    any_custom = (
        custom_dtis is not None or custom_fdiffs is not None
        or custom_chain_fdiffs is not None
    )
    if structure is None and active_mask is None and not any_custom:
        return w.tobytes()
    w.write_bits(1 if structure is not None else 0, 1)   # structure present
    w.write_bits(1 if active_mask is not None else 0, 1)  # active present
    w.write_bits(1 if custom_dtis is not None else 0, 1)
    w.write_bits(1 if custom_fdiffs is not None else 0, 1)
    w.write_bits(1 if custom_chain_fdiffs is not None else 0, 1)
    if structure is not None:
        w.write_bits(structure.structure_id & 0x3F, 6)
        w.write_bits(structure.num_decode_targets - 1, 5)
        for i, t in enumerate(structure.templates):
            if i + 1 < len(structure.templates):
                nxt = structure.templates[i + 1]
                if nxt.spatial == t.spatial and nxt.temporal == t.temporal:
                    idc = 0
                elif nxt.spatial == t.spatial:
                    idc = 1
                else:
                    idc = 2
            else:
                idc = 3
            w.write_bits(idc, 2)
        for t in structure.templates:
            for dti in t.dtis:
                w.write_bits(dti, 2)
        for t in structure.templates:
            for f in t.fdiffs:
                w.write_bits(1, 1)
                w.write_bits(f - 1, 4)
            w.write_bits(0, 1)
        w.write_ns(structure.num_chains, structure.num_decode_targets + 1)
        if structure.num_chains:
            for p in structure.protected_by:
                w.write_ns(p, structure.num_chains)
            for t in structure.templates:
                cds = t.chain_diffs or [0] * structure.num_chains
                for cd in cds[: structure.num_chains]:
                    w.write_bits(cd, 4)
        w.write_bits(1 if structure.resolutions else 0, 1)
        for wd, ht in structure.resolutions:
            w.write_bits(wd - 1, 16)
            w.write_bits(ht - 1, 16)
    if active_mask is not None:
        bits = mask_bits or (structure.num_decode_targets if structure else 0)
        w.write_bits(active_mask, bits)
    if custom_dtis is not None:
        for dti in custom_dtis:
            w.write_bits(dti, 2)
    if custom_fdiffs is not None:
        for f in custom_fdiffs:
            if not 1 <= f <= 4096:
                # next_fdiff_size is 2 bits (1..3 nibbles): silently
                # truncating would misalign every later field.
                raise ValueError(f"custom fdiff {f} outside 1..4096")
            size = max(1, ((f - 1).bit_length() + 3) // 4)
            w.write_bits(size, 2)
            w.write_bits(f - 1, 4 * size)
        w.write_bits(0, 2)
    if custom_chain_fdiffs is not None:
        for cd in custom_chain_fdiffs:
            w.write_bits(cd, 8)
    return w.tobytes()
