"""Host-side RTP/VP8 munging: the rewrite half of the forward path.

Reference parity: pkg/sfu/rtpmunger.go (UpdateAndGetSnTs :183-271, SN-gap
compaction, PacketDropped, UpdateAndGetPaddingSnTs) and
pkg/sfu/codecmunger/vp8.go (UpdateAndGet :161, UpdateOffsets, dropped-
picture accounting) — run, like the reference runs them, on the CPU in
the per-packet write path.

Why host-side (the round-5 device→host split)
---------------------------------------------
Rounds 1-4 ran SN/TS/VP8 munging on the device and compacted the per-
(packet, subscriber) results with `jnp.nonzero` + gathers. Device tracing
showed those gathers ARE the tick at scale: TPUs have no vector gather, so
six [R·cap]-element random fetches cost ~29 ms of a 38 ms cfg4 tick — and
at the north-star shape the dense [R,T,K,S] value tensors (65 M elements
each) make ANY multi-pass compaction unaffordable. The decisions
(selection, BWE, allocation) stay batched on the TPU; the *values* are a
handful of integer ops per forwarded packet, applied here by the host
egress path that already touches every outgoing packet's bytes. The
device→host transfer shrinks from six compacted value tensors to three
bit-packed mask words per (room, track, packet).

Semantics are defined by ops.rtpmunger / ops.vp8 (the golden scan
formulations, kept + tested); `tests/test_host_munge.py` asserts this
implementation is bit-identical on randomized cases. A native C++ walker
(livekit_server_tpu.native) accelerates the same algebra; this numpy
implementation is the fallback and the spec.
"""

from __future__ import annotations

import numpy as np

from livekit_server_tpu.models import plane

M16 = 0xFFFF
M32 = 0xFFFFFFFF
M15 = 0x7FFF
M8 = 0xFF
M5 = 0x1F

REANCHOR_TS_THRESH = 900_000  # ops/rtpmunger.py REANCHOR_TS_THRESH
FALLBACK_TS_JUMP = 3000       # ops/rtpmunger.py FALLBACK_TS_JUMP


def _sdiff(a, b, mask, half):
    """Signed modular difference (a - b) in a `mask`-wide ring."""
    return ((a - b + half) & mask) - half


def _popcount_u32(x: np.ndarray) -> np.ndarray:
    """Per-element popcount of uint32 words (np.bitwise_count needs
    numpy>=2.0 and this package pins no numpy version)."""
    x = x - ((x >> 1) & np.uint32(0x55555555))
    x = (x & np.uint32(0x33333333)) + ((x >> 2) & np.uint32(0x33333333))
    x = (x + (x >> 4)) & np.uint32(0x0F0F0F0F)
    return (x * np.uint32(0x01010101)) >> 24


class HostMunger:
    """Per-(room, track, subscriber) SN/TS + VP8 rewrite state.

    All state arrays are [R, T, S] int64 (value-masked to their field
    widths); bool arrays for started/aligned. The state tuple mirrors
    ops.rtpmunger.MungerState + ops.vp8.VP8State and serializes into room
    snapshots for cross-node migration (rtpmunger.go:53-69 seeding).
    """

    # Field order for snapshot/restore (int arrays then bools).
    FIELDS = (
        "sn_offset", "ts_offset", "last_sn", "last_ts",
        "pid_offset", "tl0_offset", "ki_offset",
        "last_pid", "last_tl0", "last_ki",
        "started", "aligned", "v_started",
    )

    def __init__(self, dims: plane.PlaneDims):
        R, T, _, S = dims
        self.dims = dims
        z = lambda: np.zeros((R, T, S), np.int64)  # noqa: E731
        f = lambda: np.zeros((R, T, S), bool)      # noqa: E731
        self.sn_offset = z()
        self.ts_offset = z()
        self.last_sn = z()
        self.last_ts = z()
        self.started = f()
        self.aligned = f()
        self.pid_offset = z()
        self.tl0_offset = z()
        self.ki_offset = z()
        self.last_pid = z()
        self.last_tl0 = z()
        self.last_ki = z()
        self.v_started = f()
        # Per-shard walk stats of the last sharded apply_columns (scraped
        # by EgressPlane.record_munge for /debug/egress).
        self.last_shard_counts = np.zeros(0, np.int64)
        self.last_shard_ns = np.zeros(0, np.int64)

    # -- tick application -------------------------------------------------
    def apply_dense(
        self,
        sn, ts, ts_jump, pid, tl0, keyidx, begin_pic, valid,  # [R, T, K]
        send, drop, switch,                                   # [R, T, K, S] bool
    ):
        """Run one tick of munging over dense masks.

        Exactly the scan semantics of ops.rtpmunger.munge_tick +
        ops.vp8.munge_tick, vectorized over (room, track, subscriber) with
        a host loop over the K packet slots. Returns dense
        (out_sn, out_ts, out_pid, out_tl0, out_ki) int64 [R, T, K, S]
        (defined where `send`; zero elsewhere).
        """
        R, T, K = np.asarray(sn).shape
        S = send.shape[-1]
        sn = np.asarray(sn, np.int64) & M16
        ts = np.asarray(ts, np.int64) & M32
        pid = np.asarray(pid, np.int64) & M15
        tl0 = np.asarray(tl0, np.int64) & M8
        ki = np.asarray(keyidx, np.int64) & M5
        jump = np.asarray(ts_jump, np.int64)
        bp = np.asarray(begin_pic, bool)
        val = np.asarray(valid, bool)

        # int32 outputs (ts as the uint32 bit pattern viewed signed would
        # lose the & M32 comparisons downstream, so ts stays int64; the
        # rest fit their field widths): halves the dense-fallback
        # allocation, which at big shapes is this path's cost.
        out_sn = np.zeros((R, T, K, S), np.int32)
        out_ts = np.zeros((R, T, K, S), np.int64)
        out_pid = np.zeros((R, T, K, S), np.int32)
        out_tl0 = np.zeros((R, T, K, S), np.int32)
        out_ki = np.zeros((R, T, K, S), np.int32)

        for k in range(K):
            v = val[:, :, k][:, :, None]
            fwd = send[:, :, k, :] & v
            drp = drop[:, :, k, :] & v & ~fwd
            sw = switch[:, :, k, :] & fwd
            sn_k = sn[:, :, k][:, :, None]
            ts_k = ts[:, :, k][:, :, None]
            jump_k = jump[:, :, k][:, :, None]
            pkt_aligned = jump_k < 0
            jump_eff = np.where(pkt_aligned, FALLBACK_TS_JUMP, jump_k)

            # --- rtpmunger step (ops/rtpmunger.py:109-162) ---------------
            sw_sn_off = (sn_k - ((self.last_sn + 1) & M16)) & M16
            sw_ts_off = (ts_k - ((self.last_ts + jump_eff) & M32)) & M32
            carry_through = pkt_aligned & self.aligned
            sw_ts_off = np.where(carry_through, self.ts_offset, sw_ts_off)
            fresh = fwd & ~self.started
            resync = sw & self.started
            cur_out_ts = (ts_k - self.ts_offset) & M32
            shear = _sdiff(cur_out_ts, self.last_ts, M32, 1 << 31)
            sheared = (
                fwd & ~sw & self.started & (np.abs(shear) > REANCHOR_TS_THRESH)
            )
            shear_ts_off = (ts_k - ((self.last_ts + FALLBACK_TS_JUMP) & M32)) & M32
            anchor = fresh | resync | sheared
            self.sn_offset = np.where(
                resync, sw_sn_off, np.where(fresh, 0, self.sn_offset)
            )
            self.ts_offset = np.where(
                sheared, shear_ts_off,
                np.where(resync, sw_ts_off, np.where(fresh, 0, self.ts_offset)),
            )
            self.aligned = np.where(anchor, pkt_aligned, self.aligned)
            o_sn = (sn_k - self.sn_offset) & M16
            o_ts = (ts_k - self.ts_offset) & M32
            self.last_sn = np.where(fwd, o_sn, self.last_sn)
            self.last_ts = np.where(fwd, o_ts, self.last_ts)
            self.sn_offset = np.where(
                drp & self.started, (self.sn_offset + 1) & M16, self.sn_offset
            )
            self.started = self.started | fwd

            # --- vp8 step (ops/vp8.py:82-112) ----------------------------
            drp_pic = drp & bp[:, :, k][:, :, None]
            pid_k = pid[:, :, k][:, :, None]
            tl0_k = tl0[:, :, k][:, :, None]
            ki_k = ki[:, :, k][:, :, None]
            sw_pid_off = (pid_k - ((self.last_pid + 1) & M15)) & M15
            sw_tl0_off = (tl0_k - self.last_tl0 - 1) & M8
            sw_ki_off = (ki_k - self.last_ki - 1) & M5
            v_fresh = fwd & ~self.v_started
            v_resync = sw & self.v_started
            self.pid_offset = np.where(
                v_resync, sw_pid_off, np.where(v_fresh, 0, self.pid_offset)
            )
            self.tl0_offset = np.where(
                v_resync, sw_tl0_off, np.where(v_fresh, 0, self.tl0_offset)
            )
            self.ki_offset = np.where(
                v_resync, sw_ki_off, np.where(v_fresh, 0, self.ki_offset)
            )
            o_pid = (pid_k - self.pid_offset) & M15
            o_tl0 = (tl0_k - self.tl0_offset) & M8
            o_ki = (ki_k - self.ki_offset) & M5
            fwd_bp = fwd & bp[:, :, k][:, :, None]
            self.last_pid = np.where(fwd_bp, o_pid, self.last_pid)
            self.last_tl0 = np.where(fwd_bp, o_tl0, self.last_tl0)
            self.last_ki = np.where(fwd_bp, o_ki, self.last_ki)
            self.pid_offset = np.where(
                drp_pic & self.v_started, (self.pid_offset + 1) & M15,
                self.pid_offset,
            )
            self.v_started = self.v_started | fwd

            out_sn[:, :, k, :] = np.where(fwd, o_sn, 0)
            out_ts[:, :, k, :] = np.where(fwd, o_ts, 0)
            out_pid[:, :, k, :] = np.where(fwd, o_pid, 0)
            out_tl0[:, :, k, :] = np.where(fwd, o_tl0, 0)
            out_ki[:, :, k, :] = np.where(fwd, o_ki, 0)
        return out_sn, out_ts, out_pid, out_tl0, out_ki

    def apply_arrivals(
        self,
        gr, gt,                                               # [G] lane coords
        sn, ts, ts_jump, pid, tl0, keyidx, begin_pic, valid,  # [G, Kb]
        send, drop, switch,                                   # [G, Kb, S] bool
    ):
        """Express-lane munging: the apply_dense scan applied to G
        gathered (room, track) lanes over one receive batch, in arrival
        order. apply_dense REBINDS the state arrays via np.where, so the
        lanes are pulled into [G, S] locals, advanced packet by packet,
        and scattered back — the SAME per-(room, track, sub) state the
        batched fan-out walks, which is what keeps a subscriber's SN/TS
        space continuous across tier promotion/demotion. (gr, gt) must
        name distinct lanes. Returns (out_sn, out_ts, out_pid, out_tl0,
        out_ki) [G, Kb, S] (defined where `send & valid`; zero
        elsewhere)."""
        G, Kb = np.asarray(sn).shape
        S = send.shape[-1]
        sn = np.asarray(sn, np.int64) & M16
        ts = np.asarray(ts, np.int64) & M32
        pid = np.asarray(pid, np.int64) & M15
        tl0 = np.asarray(tl0, np.int64) & M8
        ki = np.asarray(keyidx, np.int64) & M5
        jump = np.asarray(ts_jump, np.int64)
        bp = np.asarray(begin_pic, bool)
        val = np.asarray(valid, bool)

        st = {name: getattr(self, name)[gr, gt] for name in self.FIELDS}
        out_sn = np.zeros((G, Kb, S), np.int32)
        out_ts = np.zeros((G, Kb, S), np.int64)
        out_pid = np.zeros((G, Kb, S), np.int32)
        out_tl0 = np.zeros((G, Kb, S), np.int32)
        out_ki = np.zeros((G, Kb, S), np.int32)

        for k in range(Kb):
            v = val[:, k][:, None]
            fwd = send[:, k, :] & v
            drp = drop[:, k, :] & v & ~fwd
            sw = switch[:, k, :] & fwd
            sn_k = sn[:, k][:, None]
            ts_k = ts[:, k][:, None]
            jump_k = jump[:, k][:, None]
            pkt_aligned = jump_k < 0
            jump_eff = np.where(pkt_aligned, FALLBACK_TS_JUMP, jump_k)

            # --- rtpmunger step (mirrors apply_dense) --------------------
            sw_sn_off = (sn_k - ((st["last_sn"] + 1) & M16)) & M16
            sw_ts_off = (ts_k - ((st["last_ts"] + jump_eff) & M32)) & M32
            carry_through = pkt_aligned & st["aligned"]
            sw_ts_off = np.where(carry_through, st["ts_offset"], sw_ts_off)
            fresh = fwd & ~st["started"]
            resync = sw & st["started"]
            cur_out_ts = (ts_k - st["ts_offset"]) & M32
            shear = _sdiff(cur_out_ts, st["last_ts"], M32, 1 << 31)
            sheared = (
                fwd & ~sw & st["started"] & (np.abs(shear) > REANCHOR_TS_THRESH)
            )
            shear_ts_off = (
                ts_k - ((st["last_ts"] + FALLBACK_TS_JUMP) & M32)
            ) & M32
            anchor = fresh | resync | sheared
            st["sn_offset"] = np.where(
                resync, sw_sn_off, np.where(fresh, 0, st["sn_offset"])
            )
            st["ts_offset"] = np.where(
                sheared, shear_ts_off,
                np.where(resync, sw_ts_off, np.where(fresh, 0, st["ts_offset"])),
            )
            st["aligned"] = np.where(anchor, pkt_aligned, st["aligned"])
            o_sn = (sn_k - st["sn_offset"]) & M16
            o_ts = (ts_k - st["ts_offset"]) & M32
            st["last_sn"] = np.where(fwd, o_sn, st["last_sn"])
            st["last_ts"] = np.where(fwd, o_ts, st["last_ts"])
            st["sn_offset"] = np.where(
                drp & st["started"], (st["sn_offset"] + 1) & M16,
                st["sn_offset"],
            )
            st["started"] = st["started"] | fwd

            # --- vp8 step ------------------------------------------------
            drp_pic = drp & bp[:, k][:, None]
            pid_k = pid[:, k][:, None]
            tl0_k = tl0[:, k][:, None]
            ki_k = ki[:, k][:, None]
            sw_pid_off = (pid_k - ((st["last_pid"] + 1) & M15)) & M15
            sw_tl0_off = (tl0_k - st["last_tl0"] - 1) & M8
            sw_ki_off = (ki_k - st["last_ki"] - 1) & M5
            v_fresh = fwd & ~st["v_started"]
            v_resync = sw & st["v_started"]
            st["pid_offset"] = np.where(
                v_resync, sw_pid_off, np.where(v_fresh, 0, st["pid_offset"])
            )
            st["tl0_offset"] = np.where(
                v_resync, sw_tl0_off, np.where(v_fresh, 0, st["tl0_offset"])
            )
            st["ki_offset"] = np.where(
                v_resync, sw_ki_off, np.where(v_fresh, 0, st["ki_offset"])
            )
            o_pid = (pid_k - st["pid_offset"]) & M15
            o_tl0 = (tl0_k - st["tl0_offset"]) & M8
            o_ki = (ki_k - st["ki_offset"]) & M5
            fwd_bp = fwd & bp[:, k][:, None]
            st["last_pid"] = np.where(fwd_bp, o_pid, st["last_pid"])
            st["last_tl0"] = np.where(fwd_bp, o_tl0, st["last_tl0"])
            st["last_ki"] = np.where(fwd_bp, o_ki, st["last_ki"])
            st["pid_offset"] = np.where(
                drp_pic & st["v_started"], (st["pid_offset"] + 1) & M15,
                st["pid_offset"],
            )
            st["v_started"] = st["v_started"] | fwd

            out_sn[:, k, :] = np.where(fwd, o_sn, 0)
            out_ts[:, k, :] = np.where(fwd, o_ts, 0)
            out_pid[:, k, :] = np.where(fwd, o_pid, 0)
            out_tl0[:, k, :] = np.where(fwd, o_tl0, 0)
            out_ki[:, k, :] = np.where(fwd, o_ki, 0)

        for name in self.FIELDS:
            dst = getattr(self, name)
            dst[gr, gt] = st[name].astype(dst.dtype, copy=False)
        return out_sn, out_ts, out_pid, out_tl0, out_ki

    def apply_columns(
        self,
        sn, ts, ts_jump, pid, tl0, keyidx, begin_pic, valid,  # [R, T, K]
        send_bits, drop_bits, switch_bits,                    # [R, T, K, W] i32
        shard_plan=None,
    ):
        """One tick's rewrites straight from the device's bit-packed masks
        to egress COLUMN arrays (rooms, tracks, ks, subs, sn, ts, pid,
        tl0, keyidx) — the production fan-out path. Uses the native C++
        walker when available; numpy apply_dense + nonzero otherwise.

        `shard_plan` = (r_lo, r_hi) contiguous room ranges (from
        EgressPlane.room_plan) fans the walk across the native worker
        shards. Rooms are the state-ownership unit — lanes are indexed
        [room, track, sub] — so whole-room shards keep every state write
        thread-private, and migration freezes/snapshots (snapshot_room /
        clear_room) stay valid: a frozen room's lanes live entirely inside
        one shard and are never half-written. Output is bit-identical to
        the unsharded walk (exact per-shard prefix-sum bases)."""
        from livekit_server_tpu import native

        send_bits = np.asarray(send_bits)
        if native.munge is not None:
            cap = int(_popcount_u32(send_bits.astype(np.uint32)).sum(dtype=np.int64))
            if shard_plan is not None and len(shard_plan[0]) > 1:
                res = native.munge.walk_multi(
                    np.asarray(sn), np.asarray(ts), np.asarray(ts_jump),
                    np.asarray(pid), np.asarray(tl0), np.asarray(keyidx),
                    np.asarray(begin_pic), np.asarray(valid),
                    send_bits, np.asarray(drop_bits),
                    np.asarray(switch_bits),
                    self, cap, shard_plan[0], shard_plan[1],
                )
                if res is not None:
                    cols, counts, ns = res
                    self.last_shard_counts = counts
                    self.last_shard_ns = ns
                    return cols
            else:
                res = native.munge.walk(
                    np.asarray(sn), np.asarray(ts), np.asarray(ts_jump),
                    np.asarray(pid), np.asarray(tl0), np.asarray(keyidx),
                    np.asarray(begin_pic), np.asarray(valid),
                    send_bits, np.asarray(drop_bits), np.asarray(switch_bits),
                    self, cap,
                )
                if res is not None:
                    return res
        S = self.dims.subs
        send = plane.unpack_bits(send_bits, S)
        drop = plane.unpack_bits(drop_bits, S)
        switch = plane.unpack_bits(switch_bits, S)
        o_sn, o_ts, o_pid, o_tl0, o_ki = self.apply_dense(
            sn, ts, ts_jump, pid, tl0, keyidx, begin_pic, valid,
            send, drop, switch,
        )
        eff = send & np.asarray(valid, bool)[..., None]
        rr, tt, kk, ss = np.nonzero(eff)
        return (
            rr.astype(np.int32), tt.astype(np.int32),
            kk.astype(np.int32), ss.astype(np.int32),
            o_sn[rr, tt, kk, ss].astype(np.int32),
            (o_ts[rr, tt, kk, ss] & M32).astype(np.uint32).view(np.int32),
            o_pid[rr, tt, kk, ss].astype(np.int32),
            o_tl0[rr, tt, kk, ss].astype(np.int32),
            o_ki[rr, tt, kk, ss].astype(np.int32),
        )

    # -- probe padding (rtpmunger.go UpdateAndGetPaddingSnTs) -------------
    def padding(self, pad_num, pad_track, ts_advance: int):
        """Synthesize padding runs after this tick's sends.

        pad_num [R, S] int, pad_track [R, S] int (-1 = none). Returns a
        list of (room, track, sub, sn, ts) per padding packet, and
        advances the named (room, track, sub) lanes' SN space exactly like
        ops.rtpmunger.padding_tick (offset -= n, last_sn += n).
        """
        pad_num = np.asarray(pad_num)
        pad_track = np.asarray(pad_track)
        rr, ss = np.nonzero((pad_num > 0) & (pad_track >= 0))
        out = []
        for r, s in zip(rr, ss):
            t = int(pad_track[r, s])
            if not self.started[r, t, s]:
                continue
            n = int(pad_num[r, s])
            base_sn = int(self.last_sn[r, t, s])
            pad_ts = (int(self.last_ts[r, t, s]) + ts_advance) & M32
            for j in range(n):
                out.append((int(r), t, int(s), (base_sn + j + 1) & M16, pad_ts))
            self.sn_offset[r, t, s] = (self.sn_offset[r, t, s] - n) & M16
            self.last_sn[r, t, s] = (base_sn + n) & M16
            self.last_ts[r, t, s] = pad_ts
        return out

    # -- lifecycle / migration -------------------------------------------
    def clear_room(self, room: int) -> None:
        for name in self.FIELDS:
            getattr(self, name)[room] = False if name in (
                "started", "aligned", "v_started") else 0

    def snapshot_room(self, room: int) -> list[np.ndarray]:
        return [np.array(getattr(self, name)[room]) for name in self.FIELDS]

    def restore_room(self, room: int, arrays: list[np.ndarray]) -> None:
        if len(arrays) != len(self.FIELDS):
            raise ValueError(
                f"munger snapshot has {len(arrays)} fields, expected "
                f"{len(self.FIELDS)}"
            )
        for name, arr in zip(self.FIELDS, arrays):
            dst = getattr(self, name)
            dst[room] = np.asarray(arr, dst.dtype)

    def snapshot(self) -> list[np.ndarray]:
        return [np.array(getattr(self, name)) for name in self.FIELDS]

    def restore(self, arrays: list[np.ndarray]) -> None:
        if len(arrays) != len(self.FIELDS):
            raise ValueError("munger snapshot field count mismatch")
        for name, arr in zip(self.FIELDS, arrays):
            dst = getattr(self, name)
            dst[...] = np.asarray(arr, dst.dtype)
