"""The tick loop: control mutations in, device step, outputs fanned out.

This is the TPU replacement for the reference's always-on goroutine mesh:
where pkg/sfu runs one forwardRTP loop per (track, layer) plus per-
subscriber allocator/transport loops, this runtime advances the ENTIRE
node in one jitted call per tick (models/plane.media_plane_tick, room axis
sharded over the mesh — parallel/mesh.py).

Per tick:
  1. apply queued control mutations to the host mirrors of TrackMeta /
     SubControl (subscription churn lands at tick boundaries — the
     reference serializes the same churn with locks + shadow slices,
     downtrackspreader.go:110)
  2. drain the IngestBuffer → TickInputs
  3. step the device plane
  4. fan out TickOutputs: egress writes (send mask × munged headers +
     payload slab), speaker updates, keyframe/PLI requests, congestion →
     registered async callbacks

Checkpoint/resume (§5.4): snapshot()/restore() serialize the full device
state tree — the analog of the reference's ForwarderState/RTPMungerState
migration seeding (forwarder.go:340-376).
"""

from __future__ import annotations

import asyncio
import functools
import time
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable

import jax
import numpy as np

from livekit_server_tpu.models import plane
from livekit_server_tpu.runtime.ingest import IngestBuffer
from livekit_server_tpu.runtime.munge import HostMunger
from livekit_server_tpu.runtime.probe import PAD_BYTES, ProbeController
from livekit_server_tpu.runtime.slots import SlotAllocator


@dataclass
class EgressPacket:
    """One packet to deliver to one subscriber (host egress unit)."""

    room: int
    track: int
    sub: int
    sn: int
    ts: int
    pid: int
    tl0: int
    keyidx: int
    size: int
    payload: bytes
    marker: bool = False
    padding: bool = False  # probe padding (RTP P-bit; no media payload)
    dd: bytes = b""       # dependency-descriptor ext bytes (SVC tracks)
    t_arr: float = 0.0    # rx stamp (forward-latency probe; 0 = unstamped)


@dataclass
class EgressBatch:
    """One tick's egress as column arrays — the vectorized host-egress
    unit (no per-packet Python objects on the wire path). All arrays are
    [N] over egress entries; payload bytes stay in the ingest slab and
    are gathered by (room, track, k) index math."""

    rooms: np.ndarray     # int32
    tracks: np.ndarray    # int32
    ks: np.ndarray        # int32 — packet slot within the tick
    subs: np.ndarray      # int32
    sn: np.ndarray        # int32 (16-bit munged)
    ts: np.ndarray        # int32 (32-bit munged, two's complement)
    pid: np.ndarray       # int32
    tl0: np.ndarray       # int32
    keyidx: np.ndarray    # int32
    payloads: Any         # PayloadSlab
    # Attribution stamps (runtime/trace.py LatencyAttribution): when the
    # owning tick was dispatched to the device and when its step
    # committed — the stage boundaries the sampled wire-latency
    # decomposition splits on. 0.0 = unstamped (tracing off / tests).
    t_dispatch: float = 0.0
    t_device_end: float = 0.0

    def __len__(self) -> int:
        return len(self.rooms)

    def to_packets(self, mask: np.ndarray | None = None) -> list[EgressPacket]:
        """Materialize EgressPacket objects (WS delivery / tests); `mask`
        selects a subset of entries."""
        idx = np.nonzero(mask)[0] if mask is not None else range(len(self.rooms))
        out = []
        ta = self.payloads.t_arr
        for i in idx:
            r, t, k = int(self.rooms[i]), int(self.tracks[i]), int(self.ks[i])
            payload, marker = self.payloads.get(r, t, k)
            out.append(
                EgressPacket(
                    room=r, track=t, sub=int(self.subs[i]),
                    sn=int(self.sn[i]) & 0xFFFF,
                    ts=int(self.ts[i]) & 0xFFFFFFFF,
                    pid=int(self.pid[i]),
                    tl0=int(self.tl0[i]),
                    keyidx=int(self.keyidx[i]),
                    size=len(payload),
                    payload=payload,
                    marker=marker,
                    dd=self.payloads.get_dd(r, t, k),
                    t_arr=float(ta[r, t, k]) if ta is not None else 0.0,
                )
            )
        return out


class HostSequencer:
    """Host-side NACK/RTX replay ring (pkg/sfu/sequencer.go:82-370 seat).

    The device's egress batch already hands the host every send's munged
    SN/TS/descriptor, so the replay ring lives in numpy and NACKs resolve
    at RTCP time — one tick-cadence device round trip fewer, and the
    device tick carries no scatter-heavy sequencer state (a TPU scatter
    serializes per element; the device-side ring was measured at ~80% of
    the whole tick).

    One ring per (room, sub); slot = munged SN & (RING-1); cross-track
    collisions evict (a miss makes the client re-NACK, exactly like an
    evicted reference ring entry). Replays are RTT-throttled per slot
    (sequencer.go:263 getExtPacketMetas semantics).
    """

    RING = 512
    # Retransmit-amplification bounds: one compound NACK (BLP masks) can
    # name the whole slab window — tiny RTCP in must not buy full-history
    # media out. Per-resolve burst cap + per-subscriber replay budget that
    # refills each second (sequencer.go bounds the same pressure via its
    # per-tick staging slots).
    BURST_CAP = 16
    BUDGET_PER_S = 256

    def __init__(self, dims: plane.PlaneDims):
        R, S = dims.rooms, dims.subs
        self._tk = dims.tracks * dims.pkts
        self._k = dims.pkts
        self._s = S
        self.budget = np.full((R, S), self.BUDGET_PER_S, np.int32)
        self._budget_refill_ms = np.zeros((R, S), np.int64)
        shape = (R, S, self.RING)
        self.key = np.full(shape, -1, np.int32)       # slab history key
        self.sn = np.full(shape, -1, np.int32)
        self.track = np.full(shape, -1, np.int32)
        self.ts = np.zeros(shape, np.int64)
        self.pid = np.zeros(shape, np.int32)
        self.tl0 = np.zeros(shape, np.int32)
        self.keyidx = np.zeros(shape, np.int32)
        self.at_tick = np.full(shape, -(1 << 30), np.int64)
        self.last_ms = np.full(shape, -(1 << 60), np.int64)

    def record(self, batch: "EgressBatch", tick_idx: int) -> None:
        """Vectorized ring update from one tick's egress batch (the push
        half of sequencer.go; duplicate slots resolve last-write-wins)."""
        if not len(batch):
            return
        slot = batch.sn & (self.RING - 1)
        r, s = batch.rooms, batch.subs
        w = tick_idx % plane.SLAB_WINDOW
        # One flat index shared by all eight scatters (recomputing the
        # 3-D index math per field costs more than the writes themselves).
        flat = (r.astype(np.int64) * self._s + s) * self.RING + slot
        self.key.reshape(-1)[flat] = (
            w * self._tk + batch.tracks * self._k + batch.ks
        )
        self.sn.reshape(-1)[flat] = batch.sn & 0xFFFF
        self.track.reshape(-1)[flat] = batch.tracks
        self.ts.reshape(-1)[flat] = batch.ts.astype(np.int64) & 0xFFFFFFFF
        self.pid.reshape(-1)[flat] = batch.pid
        self.tl0.reshape(-1)[flat] = batch.tl0
        self.keyidx.reshape(-1)[flat] = batch.keyidx
        self.at_tick.reshape(-1)[flat] = tick_idx

    def clear_room(self, room: int) -> None:
        self.sn[room] = -1
        self.key[room] = -1
        self.track[room] = -1
        # A recycled row must not inherit the previous room's drained
        # replay budget OR its per-slot RTT throttle stamps (record()
        # never rewrites last_ms, so stale stamps would gate the new
        # room's first retransmits for up to one RTT).
        self.budget[room] = self.BUDGET_PER_S
        self._budget_refill_ms[room] = 0
        self.last_ms[room] = -(1 << 60)


@dataclass
class TickResult:
    """Host-visible outputs of one tick."""

    tick_index: int
    egress_batch: EgressBatch
    speakers: dict[int, list[tuple[int, float]]]     # room → [(track, level)]
    need_keyframe: list[tuple[int, int, int]]        # (room, track, sub)
    congested: dict[int, list[int]]                  # room → [sub]
    fwd_packets: int
    fwd_bytes: int
    tick_s: float                                    # wall time of the step
    # NACK retransmits are no longer tick-cadence: HostSequencer resolves
    # and transports send them at RTCP time (kept for API compat).
    replays: list[EgressPacket] = field(default_factory=list)
    padding: list[EgressPacket] = field(default_factory=list)  # probe padding
    # Quality / stats tensors (numpy views of TickOutputs; consumers index
    # by room row). None until the first tick completes.
    track_quality: Any = None     # [R, T] int32 ConnectionQuality enum
    track_mos: Any = None         # [R, T] float32
    sub_quality: Any = None       # [R, S] int32
    layer_live: Any = None        # [R, T, L] int32
    layer_fps: Any = None         # [R, T, L] float32 (measured fps)
    track_loss_pct: Any = None    # [R, T] float32
    track_jitter_ms: Any = None   # [R, T] float32
    # RED plan (ops/red): per-packet redundancy candidates for the host
    # egress to assemble (redreceiver.go seat).
    red_sn: Any = None            # [R, T, K, D] int32
    red_off: Any = None           # [R, T, K, D] int32
    red_ok: Any = None            # [R, T, K, D] bool
    pacer_allowed: Any = None     # [R, S] float32 — leaky-bucket byte budgets
    target_layers: Any = None     # [R, S, T] int32 flat layer targets (-1 = paused)
    track_bps: Any = None         # [R, T] float32
    quality_window_closed: bool = False  # this tick rolled the stats window
    _egress_cache: list[EgressPacket] | None = None

    @property
    def egress(self) -> list[EgressPacket]:
        """Lazy object view of egress_batch (WS fan-out, tests). The UDP
        wire path consumes egress_batch directly and never builds this."""
        if self._egress_cache is None:
            self._egress_cache = self.egress_batch.to_packets()
        return self._egress_cache


@functools.lru_cache(maxsize=None)
def _build_step(audio_params, bwe_params, red_enabled=True):
    """Packed-wire step: ONE input upload, ONE output fetch per tick
    (plane.pack_tick_inputs / pack_tick_outputs)."""

    def tick(state, pkt, fb, tf, tick_ms, roll_quality):
        inp = plane.unpack_tick_inputs(pkt, fb, tf, tick_ms, roll_quality)
        state, out = plane.media_plane_tick(
            state, inp, audio_params, bwe_params, red_enabled=red_enabled,
        )
        return state, plane.pack_tick_outputs(out)

    return jax.jit(tick, donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def _build_ctrl_delta(sharding=None):
    """Dirty-row control upload (plane.apply_ctrl_delta), state donated so
    the row scatters run in-place in HBM. One instance per sharding,
    shared across runtimes like _build_step; jax caches per padded row
    count (the caller pads to power-of-two buckets to bound variants)."""
    if sharding is None:
        return jax.jit(plane.apply_ctrl_delta, donate_argnums=(0,))
    return jax.jit(
        plane.apply_ctrl_delta, donate_argnums=(0,), out_shardings=sharding
    )


@dataclass
class StagedTick:
    """One tick's host-staged inputs, carried through the three-stage
    pipeline (stage N+1 ‖ device N ‖ fan-out N-1) with its per-stage
    timings. `packed` holds the pre-packed device arrays (non-mesh path):
    packing happens at STAGE time, so the staging set's field arrays are
    fully consumed before the set is recycled, and the worker thread's
    span shrinks to the device round trip alone."""

    inp: plane.TickInputs
    payloads: Any
    idx: int
    roll: bool
    packed: tuple | None = None
    stage_s: float = 0.0
    device_s: float = 0.0
    # Paged-kernel slice of device_s (phase-0 decide dispatch when the
    # live-extent path ran — runtime/paged_runtime.py) and the grid
    # steps it scheduled (== padded live-page bucket). 0 on the stock tick.
    kernel_s: float = 0.0
    kernel_steps: int = 0
    edge: float = 0.0      # scheduled dispatch edge (perf_counter)
    deadline: float = 0.0  # owning-tick egress deadline; 0 = unaccounted
    depth: int = 0         # pipeline depth this tick ran at
    # Express-lane handoff (runtime/express.py): rooms whose fast-path
    # subscribers were already served on arrival during this tick's
    # window (their bits are masked at fan-out), the packed sub-bit
    # words to clear, and the window's send log for the replay ring.
    express_rows: Any = None
    express_words: Any = None
    express_log: Any = None
    edge_over_us: float = 0.0  # wake overshoot past the dispatch edge
    # Span start stamps + extra durations for the trace ring
    # (runtime/trace.py): staging start, the express retier's slice of
    # it, the ctrl-upload window, and the device dispatch time.
    stage_t0: float = 0.0
    retier_s: float = 0.0
    upload_t0: float = 0.0
    upload_s: float = 0.0
    device_t0: float = 0.0


class PlaneRuntime:
    """Owns the device plane state + the host mirrors and tick loop."""

    def __init__(
        self,
        dims: plane.PlaneDims,
        tick_ms: int = 10,
        mesh=None,
        audio_params=None,
        bwe_params=None,
        red_enabled: bool = True,
        low_latency: bool = False,
        egress_shards: int = 0,
        egress_multicast: bool = True,
        express_max_subs: int = 0,
        express_max_rooms: int = 16,
        trace_enabled: bool = True,
        trace_ring_ticks: int = 512,
        trace_sample_every: int = 64,
        blackbox_events: int = 64,
    ):
        from livekit_server_tpu.ops import audio as audio_ops, bwe as bwe_ops

        self.dims = dims
        self.tick_ms = tick_ms
        self.red_enabled = red_enabled
        # low_latency: complete each tick's egress before the next tick
        # starts (≈1 tick less forward latency) instead of overlapping it
        # with the next device step (higher throughput ceiling).
        self.low_latency = low_latency
        self.slots = SlotAllocator(dims.rooms, dims.tracks, dims.subs)
        self.ingest = IngestBuffer(dims, tick_ms)
        self.tick_index = 0
        self._ap = audio_params or audio_ops.AudioLevelParams()
        self._bp = bwe_params or bwe_ops.BWEParams()

        R, T, S = dims.rooms, dims.tracks, dims.subs
        # Host mirrors of control tensors; mutated by the control plane,
        # uploaded at tick boundaries when dirty.
        self.meta = plane.TrackMeta(
            is_video=np.zeros((R, T), bool),
            published=np.zeros((R, T), bool),
            pub_muted=np.zeros((R, T), bool),
            is_svc=np.zeros((R, T), bool),
        )
        self.ctrl = plane.SubControl(
            subscribed=np.zeros((R, T, S), bool),
            sub_muted=np.zeros((R, T, S), bool),
            max_spatial=np.full((R, T, S), plane.MAX_LAYERS - 1, np.int32),
            max_temporal=np.full((R, T, S), 3, np.int32),
        )
        # Control-upload dirty tracking: mutations record their room row;
        # the upload ships only those rows unless the full flag is set
        # (init/restore) or the count crosses ctrl_delta_max_rows.
        self._ctrl_dirty = True          # full [R, T, S] upload needed
        self._dirty_rows: set[int] = set()
        self.ctrl_delta_max_rows = max(1, dims.rooms // 8)
        # Governor shed overlay (runtime/governor.py): applied to the
        # EFFECTIVE control tensors at upload time, never written into
        # the authoritative `self.ctrl` mirrors — snapshots, failover
        # restores, and recovery all keep every subscriber's true
        # desired caps, and un-shedding is just a re-upload.
        self.shed_spatial_cap = plane.MAX_LAYERS - 1   # no clamp
        self.shed_pause_video = False
        # Subscriptions exempt from the L3 video pause (screen-share /
        # active-speaker pins via update_track_settings).
        self.pinned = np.zeros((R, T, S), bool)
        # Optional OverloadGovernor; None unless RoomManager attaches
        # one. _complete feeds it each finished tick's verdict.
        self.governor = None

        self.state = self._init_device_state()
        # Host-owned SN/TS/VP8 rewrite state (the round-5 decide-on-
        # device / rewrite-on-host split; see runtime/munge.py).
        self.munger = HostMunger(dims)
        # Sharded native egress plane (runtime/egress_plane.py): one
        # shared instance plans the room-aligned shard cuts for BOTH the
        # munge walk (here, _fan_out) and the send walk (udp.py attaches
        # via attach_egress_plane) and aggregates per-shard stats.
        from livekit_server_tpu.runtime.egress_plane import EgressPlane

        self.egress_plane = EgressPlane(egress_shards, egress_multicast)
        self._munge_shard_plan = self.egress_plane.room_plan(dims.rooms)
        # Two-tier latency plane (runtime/express.py): when enabled,
        # small/interactive rooms are forwarded on packet arrival from
        # the last device selector mirror instead of waiting for the
        # batched tick. None when express_max_subs == 0.
        self.express = None
        if express_max_subs > 0:
            from livekit_server_tpu.runtime.express import ExpressLane

            self.express = ExpressLane(self, express_max_subs, express_max_rooms)
        self._mesh = mesh
        self._init_step()

        # Rolling payload history for NACK replay (slab keys reference slot
        # tick % SLAB_WINDOW; resolve_nacks age-gates so a recycled slot is
        # never dereferenced) + the host-side replay ring it feeds.
        self._slab_history: list = [None] * plane.SLAB_WINDOW
        self.host_seq = HostSequencer(dims)
        # BWE probe controller (probe_controller.go) + its inputs mirrored
        # from the previous tick's outputs.
        self.prober = ProbeController(dims, tick_ms)
        self._last_committed = np.zeros((R, S), np.float32)
        self._last_congested = np.zeros((R, S), bool)
        self._last_deficient = np.zeros((R, S), bool)
        self._task: asyncio.Task | None = None
        self._complete_task: asyncio.Task | None = None
        # Bumped by PlaneSupervisor on restart: a device step that started
        # before the bump must not commit its result over restored state
        # (the stale step ran — or is still wedged — on the abandoned
        # executor thread).
        self.run_epoch = 0
        # Optional FaultInjector (runtime/faultinject.py); None on the
        # default config path — chaos tests and soak runs attach one.
        self.fault = None
        # Optional IntegrityMonitor (runtime/integrity.py); None unless
        # RoomManager attaches one. _device_step runs its audit on the
        # cadence; _complete drains its row-repair queue; quarantined
        # rows are masked at fan-out and muted in the effective ctrl.
        self.integrity = None
        # Guards self.state across the donated device step vs. host-side
        # snapshot/restore (room migration): donation deletes the old
        # buffers mid-step, so concurrent readers would see dead arrays.
        self.state_lock = asyncio.Lock()
        self._on_tick: list[Callable[[TickResult], Awaitable[None] | None]] = []
        self.stats = {
            "ticks": 0, "fwd_packets": 0, "fwd_bytes": 0, "late_ticks": 0,
            # Pipeline shape: cumulative per-stage seconds + stall count
            # (a window that found the previous fan-out still running).
            "stage_s": 0.0, "device_s": 0.0, "fanout_s": 0.0,
            "pipeline_stalls": 0,
            # Control-upload accounting (the dirty-row protocol's receipt).
            "ctrl_full_uploads": 0, "ctrl_delta_uploads": 0,
            "ctrl_delta_rows": 0, "ctrl_upload_bytes": 0,
        }
        from collections import deque

        self.recent_tick_s: deque = deque(maxlen=120)  # /debug/ticks window
        # Per-tick stage breakdown dicts (idx/stage_ms/device_ms/fanout_ms/
        # total_ms/depth/late) — the /debug/ticks pipeline view.
        self.recent_ticks: deque = deque(maxlen=120)
        # Tick-edge sleep calibration: measured coarse-sleep overshoot
        # for this host (seconds; <0 = not yet calibrated — falls back
        # to the historical fixed 1.5 ms margin), and the last wake's
        # overshoot past its edge (surfaced per tick in recent_ticks).
        self._sleep_bias = -1.0
        self._edge_overshoot_us = 0.0
        # Single worker: device steps are strictly ordered (donated state).
        from concurrent.futures import ThreadPoolExecutor

        self._executor = ThreadPoolExecutor(max_workers=1, thread_name_prefix="plane")

        # Recompile watchdog: process-wide XLA compile counter. The
        # server marks the warmup watermark after its warm step; the
        # steady-state tick path must not compile past it (GC11's
        # runtime half — see runtime/compile_ledger.py).
        from livekit_server_tpu.runtime.compile_ledger import LEDGER

        self.compile_ledger = LEDGER.install()

        # Flight-recorder tracing plane (runtime/trace.py): fixed ring of
        # per-tick span records, the sampled wire-latency attribution
        # stage decomposer, and the per-room black-box event recorder.
        # trace/wire_stages are None when disabled; the black box is
        # always on (cold-path emits only, bounded per-room rings).
        from livekit_server_tpu.runtime import trace as trace_mod

        self.trace = None
        self.wire_stages = None
        if trace_enabled:
            self.trace = trace_mod.TickTraceRing(trace_ring_ticks)
            self.wire_stages = trace_mod.LatencyAttribution(trace_sample_every)
        self.blackbox = trace_mod.BlackBox(R, blackbox_events)

    # -- device-layout seams (overridden by PagedPlaneRuntime) ------------
    # The host side of the runtime — mirrors, munger, sequencer, express,
    # fan-out, governor — speaks LOGICAL dense [R, T, S] shapes. These
    # four hooks are the only places the device layout leaks in, so a
    # subclass can swap the dense plane for the pooled paged plane
    # (runtime/paged_runtime.py) without touching the tick loop.

    def _init_device_state(self):
        """Allocate the device-resident plane state (dense layout)."""
        return plane.init_state(self.dims)

    def _init_step(self) -> None:
        """Build the jitted device step + ctrl-delta appliers."""
        if self._mesh is not None:
            from livekit_server_tpu.parallel import make_sharded_tick, shard_tree
            from livekit_server_tpu.parallel.mesh import room_sharding

            self.state = shard_tree(self.state, self._mesh)
            self._step = make_sharded_tick(
                self._mesh, self._ap, self._bp, donate=True,
                red_enabled=self.red_enabled,
            )
            self._apply_delta = _build_ctrl_delta(room_sharding(self._mesh))
        else:
            # Shared across PlaneRuntime instances with identical params so
            # repeated construction (tests, restarts) reuses the XLA
            # compilation cache instead of re-tracing a fresh closure.
            self._step = _build_step(self._ap, self._bp, self.red_enabled)
            self._apply_delta = _build_ctrl_delta()

    def _pack_inputs(self, inp: plane.TickInputs) -> tuple:
        """Logical TickInputs → the device-step upload arrays."""
        return plane.pack_tick_inputs(inp)

    def _unpack_outputs(self, buf) -> plane.TickOutputs:
        """Device-step output buffer → LOGICAL-shape TickOutputs."""
        return plane.unpack_tick_outputs(
            np.asarray(buf), self.dims, self.red_enabled
        )

    def _sel_mirror(self, state) -> tuple:
        """The express lane's post-step selector mirror, in LOGICAL
        [R, T, S] shape: (current_spatial, current_temporal,
        target_spatial, target_temporal) numpy arrays."""
        sel = state.sel
        return (
            np.asarray(sel.current_spatial),
            np.asarray(sel.current_temporal),
            np.asarray(sel.target_spatial),
            np.asarray(sel.target_temporal),
        )

    def occupancy(self) -> dict:
        """Per-resource occupancy (rooms/tracks/subs used vs pool) for
        admission gating and /debug — the capacity accounting the slot
        allocator keeps. `admittable_rooms` is how many more MINIMAL
        rooms this plane could accept (the governor's L4 headroom key)."""
        return self.slots.occupancy()

    # -- control-plane mutation API (host mirrors; applied at tick edge) --
    def set_track(self, room: int, track: int, *, published: bool, is_video: bool,
                  pub_muted: bool = False, is_svc: bool = False,
                  pub_sub: int | None = None) -> None:
        self.meta.published[room, track] = published
        self.meta.is_video[room, track] = is_video
        self.meta.pub_muted[room, track] = pub_muted
        self.meta.is_svc[room, track] = is_svc
        # pub_sub: the publishing participant's subscriber slot — lets the
        # tick score this track's MOS with the publisher-path RTT. None
        # leaves the existing mapping (mute toggles re-call set_track).
        if pub_sub is not None:
            self.ingest.track_pub_sub[room, track] = pub_sub
        if not published:
            # Free the columns' subscriber state implicitly: masks go false.
            self.ctrl.subscribed[room, track, :] = False
            self.ingest.track_pub_sub[room, track] = -1
        self._dirty_rows.add(room)

    def set_subscription(self, room: int, track: int, sub: int, *,
                         subscribed: bool, sub_muted: bool = False) -> None:
        self.ctrl.subscribed[room, track, sub] = subscribed
        self.ctrl.sub_muted[room, track, sub] = sub_muted
        self._dirty_rows.add(room)

    def set_layer_caps(self, room: int, track: int, sub: int,
                       max_spatial: int, max_temporal: int = 3) -> None:
        self.ctrl.max_spatial[room, track, sub] = max_spatial
        self.ctrl.max_temporal[room, track, sub] = max_temporal
        self._dirty_rows.add(room)

    def set_pinned(self, room: int, track: int, sub: int, pinned: bool) -> None:
        """Exempt one subscription from the governor's L3 video pause
        (screen shares, active speakers). Dirty-row like any ctrl edit:
        the pin participates in the effective upload."""
        self.pinned[room, track, sub] = pinned
        self._dirty_rows.add(room)

    def set_express_pin(self, room: int, pin: bool | None) -> None:
        """Pin one room's latency tier: True = express lane, False =
        batched tick, None = automatic (subscriber-count eligibility).
        No-op when the express lane is disabled. Takes effect at the
        next tick boundary (re-tier runs with staging)."""
        if self.express is not None:
            self.express.set_pin(room, pin)

    def set_shed(self, *, spatial_cap: int | None = None,
                 pause_video: bool | None = None) -> None:
        """Governor actuator: set the shed overlay. A change forces a
        full ctrl upload at the next tick edge — transitions are rare
        (ladder moves), so the O(R·T·S) copy is fine; the authoritative
        mirrors stay untouched."""
        changed = False
        if spatial_cap is not None and spatial_cap != self.shed_spatial_cap:
            self.shed_spatial_cap = int(spatial_cap)
            changed = True
        if pause_video is not None and pause_video != self.shed_pause_video:
            self.shed_pause_video = bool(pause_video)
            changed = True
        if changed:
            self._ctrl_dirty = True

    def _effective_ctrl(self) -> plane.SubControl:
        """The SubControl actually uploaded: desired caps with the shed
        overlay applied (spatial clamp; L3 mutes non-pinned video subs)
        and integrity-quarantined rooms fully muted. Reads only host
        mirrors — callable without the state lock."""
        cap = self.shed_spatial_cap
        quarantined = (
            self.integrity.quarantined if self.integrity is not None else None
        )
        if (
            cap >= plane.MAX_LAYERS - 1
            and not self.shed_pause_video
            and not quarantined
        ):
            return self.ctrl
        sub_muted = self.ctrl.sub_muted
        if self.shed_pause_video:
            vid = (self.meta.is_video & self.meta.published)[:, :, None]
            sub_muted = sub_muted | (vid & ~self.pinned)
        if quarantined:
            # Quarantine mutes the WHOLE flagged room row (its state is
            # suspect end to end); other rooms keep full audio + video.
            qmask = np.zeros_like(self.ctrl.sub_muted)
            qmask[sorted(quarantined)] = True
            sub_muted = sub_muted | qmask
        return plane.SubControl(
            subscribed=self.ctrl.subscribed,
            sub_muted=sub_muted,
            max_spatial=np.minimum(self.ctrl.max_spatial, cap),
            max_temporal=self.ctrl.max_temporal,
        )

    def clear_room(self, room: int) -> None:
        self.meta.published[room, :] = False
        self.meta.pub_muted[room, :] = False
        self.ctrl.subscribed[room, :, :] = False
        self.ingest.track_pub_sub[room, :] = -1
        self.ingest.fb_enabled[room, :] = False
        self.ingest.sub_reset[room, :] = True  # next tenant: fresh BWE state
        # Stale replay-ring entries must not survive row reuse: a new
        # room's NACK aliasing an old slot would retransmit the PREVIOUS
        # room's media bytes (cross-room leak).
        self.host_seq.clear_room(room)
        # Munger offsets likewise: the next tenant's streams must anchor
        # fresh, not continue a dead room's SN/TS spaces.
        self.munger.clear_room(room)
        if self.express is not None:
            # Tier state (pin, activation, selector mirror) must not leak
            # to the next tenant or past a migration snapshot.
            self.express.clear_room(room)
        self._dirty_rows.add(room)

    def on_tick(self, cb: Callable[[TickResult], Awaitable[None] | None]) -> None:
        self._on_tick.append(cb)

    # (The r4 egress-cap auto-widening machinery is gone: the bit-packed
    # mask egress has no capacity to overflow — every send is one bit.)

    # -- tick ------------------------------------------------------------
    def _upload_ctrl(self) -> None:
        """Ship pending host-mirror control mutations to the device.

        Dirty-row delta by default: the dirtied room rows go up as a
        stacked row-gather + `.at[rows].set(...)` scatter (O(dirty rows)
        bytes), so subscription churn in one room no longer costs an
        [R, T, S] host→HBM copy at north-star dims. Full `_replace`
        upload when the full flag is set (init/restore) or the dirty
        count crosses ctrl_delta_max_rows. No-op when clean."""
        import jax.numpy as jnp

        rows = self._dirty_rows
        if not self._ctrl_dirty and not rows:
            return
        if self._ctrl_dirty or len(rows) > self.ctrl_delta_max_rows:
            if self._mesh is None:
                put = jnp.asarray
            else:
                from livekit_server_tpu.parallel.mesh import room_sharding

                sharding = room_sharding(self._mesh)
                put = lambda x: jax.device_put(jnp.asarray(x), sharding)
            self.state = self.state._replace(
                meta=jax.tree.map(lambda x: put(x.copy()), plane.TrackMeta(*self.meta)),
                ctrl=jax.tree.map(
                    lambda x: put(x.copy()),
                    plane.SubControl(*self._effective_ctrl()),
                ),
            )
            self.stats["ctrl_full_uploads"] += 1
        else:
            # Pad the row count to a power-of-two bucket so the scatter
            # compiles once per bucket, not once per distinct count.
            pad_to = 1 << (len(rows) - 1).bit_length() if len(rows) > 1 else 1
            r, meta_rows, ctrl_rows = plane.pack_ctrl_rows(
                self.meta, self._effective_ctrl(), rows, pad_to=pad_to
            )
            self.state = self._apply_delta(self.state, r, meta_rows, ctrl_rows)
            self.stats["ctrl_delta_uploads"] += 1
            self.stats["ctrl_delta_rows"] += len(rows)
            self.stats["ctrl_upload_bytes"] += meta_rows.nbytes + ctrl_rows.nbytes
        self._dirty_rows = set()
        self._ctrl_dirty = False

    def _tick_rec_extras(self, st: StagedTick) -> dict:
        """Subclass hook: extra fields for this tick's `recent_ticks`
        record (event loop, after the device step committed). The paged
        runtime adds the kernel span and live-page fraction here."""
        return {}

    def _device_step(self, st: StagedTick):
        """The blocking device round trip; runs off the event loop.
        Inputs were pre-packed at stage time (non-mesh), so this thread's
        span is the device call alone — its wall time lands in
        `st.device_s`.

        Returns None (instead of outputs) when a supervisor restart
        abandoned this step mid-flight: the epoch check straddles the
        injected stall so a woken stale thread never consumes — or
        donates — state the restart already restored."""
        epoch = self.run_epoch
        t0 = time.perf_counter()
        st.device_t0 = t0
        if self.fault is not None:
            self.fault.maybe_stall()
        if epoch != self.run_epoch:
            return None
        if self.fault is not None:
            self.fault.maybe_bitflip(self, st.idx)
        if self._mesh is not None:
            state, out = self._step(self.state, st.inp)
            # The mesh path's one per-tick drain: outputs land host-side
            # here (the non-mesh path drains in _unpack_outputs instead).
            out = jax.tree.map(np.asarray, out)  # graftcheck: disable=GC12
        else:
            state, buf = self._step(self.state, *st.packed)
            out = self._unpack_outputs(buf)
        if epoch != self.run_epoch:
            return None  # restarted mid-step: result belongs to a dead run
        self.state = state
        if self.express is not None and self.express.wants_mirror():
            # Post-commit selector mirror for the express lane: fetched
            # here (same device sync as `out`), consumed at the next
            # retier on the event loop — decisions made from it are
            # bounded ≤1 tick stale.
            self.express.post_mirror(*self._sel_mirror(state))
        if self.integrity is not None:
            # Audit the committed state on the cadence; the fetched mask
            # is a few dozen bytes riding the same device sync as `out`.
            self.integrity.maybe_audit(st.idx)
        st.device_s = time.perf_counter() - t0
        return out

    def _stage_host(self) -> StagedTick:
        """Pipelined host staging: claim a tick index, drain the ingest
        buffer, pre-pack the device input arrays. Touches ONLY host-owned
        state (ingest staging sets, slab history) — never self.state — so
        it needs no lock and can overlap an in-flight device step. Probe
        scheduling happens later, at dispatch (_schedule_probe), where the
        freshest device mirrors are available."""
        t0 = time.perf_counter()
        idx = self.tick_index
        self.tick_index += 1
        # Close the quality/stats window about once per second
        # (connectionquality windows; room.go:1318 worker cadence).
        q_ticks = max(1, 1000 // self.tick_ms)
        roll = (idx + 1) % q_ticks == 0
        ex_rows = ex_words = ex_log = None
        retier_s = 0.0
        if self.express is not None:
            # Tier boundary, in the same synchronous event-loop slice as
            # the drain (atomic w.r.t. arrivals and migration freezes):
            # close the ending window, re-tier, and take over the closing
            # window for freshly promoted rooms. Returns the rooms whose
            # fast-path subscriber bits this tick's fan-out must skip.
            r0 = time.perf_counter()
            ex_rows, ex_words, ex_log = self.express.tick_boundary(self.ingest)
            retier_s = time.perf_counter() - r0
        inp, payloads = self.ingest.drain(
            roll_quality=roll, tick_index=idx,
            reuse_fields=(self._mesh is None),
        )
        # Retain the slab for the RTX window: replay keys minted this tick
        # reference slot (tick % SLAB_WINDOW) until it recycles.
        self._slab_history[idx % plane.SLAB_WINDOW] = payloads
        packed = None
        if self._mesh is None:
            # Pack here — NOT in the worker — so the drained staging set's
            # zero-copy field views are consumed before the set recycles,
            # and the packing memcpys overlap the previous device step.
            packed = self._pack_inputs(inp)
        st = StagedTick(inp=inp, payloads=payloads, idx=idx, roll=roll,
                        packed=packed, express_rows=ex_rows,
                        express_words=ex_words, express_log=ex_log)
        st.stage_t0 = t0
        st.retier_s = retier_s
        st.stage_s = time.perf_counter() - t0
        return st

    def _schedule_probe(self, st: StagedTick) -> None:
        """Probe scheduling (probe_controller.go) for `st`, at dispatch
        time: padding rides the first live video track each subscriber is
        actually SUBSCRIBED to (its munger lane must be started for
        padding_tick to emit anything); results return as estimate
        samples. Runs against the latest device mirrors (one tick stale,
        same as the pre-split staging) and the tick's own drained
        estimate snapshot. pad_num/pad_track are host-only fields — the
        device never reads them — so injecting them after pre-pack is
        sound; they feed _assemble_padding at fan-out."""
        vid = self.meta.is_video & self.meta.published & ~self.meta.pub_muted
        cand = vid[:, :, None] & self.ctrl.subscribed          # [R, T, S]
        pad_track = np.where(
            cand.any(axis=1), cand.argmax(axis=1), -1
        ).astype(np.int32)                                     # [R, S]
        pad_num = self.prober.update(
            now_ms=st.idx * self.tick_ms,
            committed=self._last_committed,
            congested=self._last_congested,
            deficient=self._last_deficient,
            estimate=np.asarray(st.inp.estimate),
            estimate_valid=np.asarray(st.inp.estimate_valid),
            pad_track=pad_track,
        )
        if self.ingest.frozen_rows:
            # Probe padding also advances munger SN lanes; a row mid-
            # migration must stay byte-for-byte at its snapshot.
            pad_num[list(self.ingest.frozen_rows)] = 0
        st.inp = st.inp._replace(
            pad_num=np.asarray(pad_num, np.int32),
            pad_track=np.asarray(pad_track, np.int32),
        )

    def _mirror_probe_inputs(self, out) -> None:
        """Probe-controller inputs for the NEXT stage; must land as soon
        as the device step resolves (a congested flag one tick stale
        already delays padding shutdown; two would be worse)."""
        self._last_committed = np.asarray(out.committed_bps)
        self._last_congested = np.asarray(out.congested)
        self._last_deficient = np.asarray(out.deficient)

    async def _complete(self, out, st: StagedTick) -> TickResult:
        """Host post-step: fan out + callbacks. Per-stage work times
        (stage/device/fan-out) sum into tick_s — the deferred fan-out
        never bills the scheduler sleep between windows as work — and
        lateness is judged against the OWNING tick's deadline (dispatch
        edge + (1 + depth) periods), checked after the delivery callbacks
        have actually run."""
        c0 = time.perf_counter()
        result = self._fan_out(
            out, st.payloads, st.inp, 0.0, st.idx,
            express=(st.express_rows, st.express_words, st.express_log),
        )
        fanout_s = time.perf_counter() - c0
        # Attribution stamps for the wire-latency stage decomposer: the
        # egress consumer (udp.send_egress_batch's do_send — possibly on
        # a pacer thread) reads these off the batch, so they must land
        # before the callbacks run.
        result.egress_batch.t_dispatch = st.device_t0
        result.egress_batch.t_device_end = st.device_t0 + st.device_s
        result.tick_s = st.stage_s + st.device_s + fanout_s
        result.quality_window_closed = st.roll
        self.recent_tick_s.append(round(result.tick_s, 5))
        self.stats["ticks"] += 1
        self.stats["fwd_packets"] += result.fwd_packets
        self.stats["fwd_bytes"] += result.fwd_bytes
        self.stats["stage_s"] += st.stage_s
        self.stats["device_s"] += st.device_s
        self.stats["fanout_s"] += fanout_s
        s0 = time.perf_counter()
        for cb in self._on_tick:
            r = cb(result)
            if asyncio.iscoroutine(r):
                await r
        send_s = time.perf_counter() - s0
        # Egress leaves inside the callbacks (wire tx), so the deadline
        # check runs after them: a tick is late when its sends left after
        # the end of the window its pipeline depth entitles it to.
        late = bool(st.deadline) and time.perf_counter() > st.deadline
        if late:
            self.stats["late_ticks"] += 1
        tick_rec = {
            "idx": st.idx, "depth": st.depth,
            "stage_ms": round(st.stage_s * 1000.0, 3),
            "device_ms": round(st.device_s * 1000.0, 3),
            "fanout_ms": round(fanout_s * 1000.0, 3),
            "total_ms": round(result.tick_s * 1000.0, 3),
            "late": late,
            "edge_overshoot_us": round(st.edge_over_us, 1),
        }
        # Per-shard egress timing: the send callbacks above just ran, so
        # the plane's last-send snapshot is THIS tick's (munge likewise).
        ep = self.egress_plane
        if ep.last_munge:
            tick_rec["munge_shard_ms"] = ep.last_munge.get("ms")
        if ep.last_send:
            tick_rec["egress_shard_ms"] = [
                s["ms"] for s in ep.last_send.get("shards", [])
            ]
        tick_rec.update(self._tick_rec_extras(st))
        self.recent_ticks.append(tick_rec)
        if self.trace is not None:
            # Trace ring: scalar stores into preallocated columns only
            # (GC07 — no allocation on the hot path).
            slot = self.trace.record_tick(
                st.idx, st.edge, st.stage_t0, st.stage_s, st.retier_s,
                st.upload_t0, st.upload_s, st.device_t0, st.device_s,
                c0, fanout_s, send_s, st.edge_over_us, st.depth, late,
                kernel_s=st.kernel_s,
            )
            if ep.last_send:
                shards = ep.last_send.get("shards", ())
                munge_ms = ep.last_munge.get("ms", ()) if ep.last_munge else ()
                for i in range(len(shards)):
                    self.trace.set_shard(
                        slot, i,
                        munge_ms[i] if i < len(munge_ms) else 0.0,
                        shards[i]["ms"],
                    )
        # Tick-edge calibration gauges (telemetry scrapes these).
        self.stats["sleep_bias_us"] = round(max(self._sleep_bias, 0.0) * 1e6, 1)
        self.stats["edge_overshoot_us"] = round(self._edge_overshoot_us, 1)
        if self.governor is not None:
            # Close the overload loop on the finished tick's verdict.
            self.governor.on_tick(self.recent_ticks[-1])
        return result

    def mark_warm(self) -> None:
        """Close the warmup window: XLA compiles after this are
        steady-state recompiles the watchdog reports (and the seeded
        drills fail on). Call after the warm step(s) have run."""
        self.compile_ledger.mark_warm()

    async def step_once(self) -> TickResult:
        """One sequential tick (tests, warmup, manual stepping); the device
        round trip runs in a worker thread so the event loop (signal
        sessions) never blocks on HBM/tunnel latency. The serving loop
        (`_run`) instead pipelines: staging of tick N+1 and egress fan-out
        of tick N-1 overlap tick N's device step.

        step_once must NOT interleave with a RUNNING serving loop: the
        device steps serialize safely under state_lock, but this path's
        immediate fan-out can land before the loop's deferred fan-out of
        an EARLIER tick, which then rewrites munger lanes backwards
        (last-writer-wins) and emits egress out of wire order — hence the
        hard RuntimeError below instead of a docstring plea."""
        if self._task is not None and not self._task.done():
            raise RuntimeError(
                "step_once() while the serving loop is running: its "
                "immediate fan-out would land ahead of the loop's deferred "
                "fan-out of an earlier tick and rewrite munger lanes "
                "backwards (out-of-wire-order egress). Stop the loop first "
                "or consume ticks via on_tick()."
            )
        loop = asyncio.get_running_loop()
        # Staging reads only host mirrors — no lock needed. The ctrl
        # upload and the device step touch (and donate) self.state, so
        # they run under the lock: a concurrent snapshot/restore (room
        # migration) must never observe donated-and-deleted buffers.
        st = self._stage_host()
        self._schedule_probe(st)
        async with self.state_lock:
            st.upload_t0 = time.perf_counter()
            self._upload_ctrl()
            st.upload_s = time.perf_counter() - st.upload_t0
            out = await loop.run_in_executor(self._executor, self._device_step, st)
        if out is None:
            raise asyncio.CancelledError("device step abandoned by restart")
        self._mirror_probe_inputs(out)
        self.ingest.scrub_retired()
        result = await self._complete(out, st)
        if self.integrity is not None:
            # Sequential path: repair right after the tick that audited.
            await self.integrity.process()
        return result

    def resolve_nacks(self, room: int, sub: int, track: int, sns) -> list[EgressPacket]:
        """NACKed munged SNs → replay EgressPackets, at RTCP time (the
        resolve half of sequencer.go:263 getExtPacketMetas; cold path —
        loss events only, so per-packet objects are fine here).

        Misses (evicted slot, wrong track, slab recycled) return nothing —
        the client re-NACKs. A hit within one RTT of its last replay is
        throttled."""
        hs = self.host_seq
        now_ms = int(time.monotonic() * 1000)
        if now_ms - int(hs._budget_refill_ms[room, sub]) >= 1000:
            hs.budget[room, sub] = hs.BUDGET_PER_S
            hs._budget_refill_ms[room, sub] = now_ms
        rtt = max(1, int(self.ingest.rtt_ms[room, sub]))
        K = self.dims.pkts
        budget_before = int(hs.budget[room, sub])
        replays: list[EgressPacket] = []
        for sn in sns:
            if len(replays) >= hs.BURST_CAP or hs.budget[room, sub] <= 0:
                break  # amplification bound; the client re-NACKs what's left
            sn &= 0xFFFF
            slot = sn & (hs.RING - 1)
            if int(hs.sn[room, sub, slot]) != sn:
                continue
            if int(hs.track[room, sub, slot]) != track:
                continue
            # Age gate: the slab slot recycles after SLAB_WINDOW ticks.
            if self.tick_index - int(hs.at_tick[room, sub, slot]) > plane.SLAB_WINDOW - 2:
                continue
            if now_ms - int(hs.last_ms[room, sub, slot]) < rtt:
                continue  # RTT replay throttle
            w, tk = divmod(int(hs.key[room, sub, slot]), hs._tk)
            t, k = divmod(tk, K)
            slab = self._slab_history[w]
            if slab is None:
                continue
            payload, marker = slab.get(room, t, k)
            if not payload:
                continue
            hs.last_ms[room, sub, slot] = now_ms
            hs.budget[room, sub] -= 1
            replays.append(
                EgressPacket(
                    room=room, track=t, sub=sub,
                    sn=sn,
                    ts=int(hs.ts[room, sub, slot]) & 0xFFFFFFFF,
                    pid=int(hs.pid[room, sub, slot]),
                    tl0=int(hs.tl0[room, sub, slot]),
                    keyidx=int(hs.keyidx[room, sub, slot]),
                    size=len(payload), payload=payload, marker=marker,
                    dd=slab.get_dd(room, t, k),
                )
            )
        if replays:
            self.stats["rtx_packets"] = self.stats.get("rtx_packets", 0) + len(replays)
        if budget_before > 0 and int(hs.budget[room, sub]) <= 0:
            # Replay budget newly exhausted: a NACK storm on this
            # (room, sub) pair. Cold path (loss events only) — black-box
            # the event and dump the room's recorder for the post-mortem.
            from livekit_server_tpu.runtime.trace import EV_NACK_STORM

            self.blackbox.emit(room, EV_NACK_STORM, float(sub), float(len(sns)))
            self.blackbox.dump_to(room, "nack_storm")
        return replays

    def _assemble_padding(self, inp) -> list[EgressPacket]:
        """Probe padding synthesis (the host half of WritePaddingRTP;
        cold path — probing windows only). Advances the host munger's SN
        lanes after this tick's real sends, exactly like the former
        device-side rtpmunger.padding_tick."""
        pads = self.munger.padding(
            inp.pad_num, inp.pad_track, ts_advance=self.tick_ms * 90
        )
        return [
            EgressPacket(
                room=r, track=t, sub=s, sn=sn, ts=ts,
                pid=0, tl0=0, keyidx=0,
                size=PAD_BYTES, payload=b"", padding=True,
            )
            for (r, t, s, sn, ts) in pads
        ]

    def _fan_out(self, out, payloads, inp, tick_s: float, tick_idx: int | None = None,
                 express: tuple | None = None) -> TickResult:
        # Bit-packed egress masks → host munge (runtime/munge.py) →
        # column arrays. The device ships one bit per (track, pkt, sub)
        # send; the SN/TS/VP8 value rewrites run here with host-owned
        # offset state (the rewrite half of DownTrack.WriteRTP,
        # rtpmunger.go + codecmunger/vp8.go) — via the native C++ walker
        # when built, numpy otherwise.
        send_bits, drop_bits, switch_bits = (
            out.send_bits, out.drop_bits, out.switch_bits,
        )
        if self.integrity is not None and self.integrity.quarantined:
            # Same-tick quarantine: a room flagged by THIS tick's audit
            # must not fan out its (suspect) sends even once — the ctrl
            # mute only lands at the next upload edge. Zeroing the row's
            # egress bits also freezes its munger lanes at their last
            # good values, exactly like a migration freeze.
            rows = [
                r for r in self.integrity.quarantined
                if r < send_bits.shape[0]
            ]
            if rows:
                send_bits = np.array(send_bits)
                drop_bits = np.array(drop_bits)
                switch_bits = np.array(switch_bits)
                send_bits[rows] = 0
                drop_bits[rows] = 0
                switch_bits[rows] = 0
        ex_rows = ex_words = ex_log = None
        if express is not None:
            ex_rows, ex_words, ex_log = express
        if ex_rows is not None and len(ex_rows):
            # Express-handled rooms: their fast-path subscribers were
            # served (and their munger lanes advanced) on arrival during
            # this tick's window — clear exactly those subscriber bits so
            # the batched walk neither re-sends nor re-advances them.
            # WS/TCP/RED subscribers of the same rooms keep their bits.
            send_bits = np.array(send_bits)
            drop_bits = np.array(drop_bits)
            switch_bits = np.array(switch_bits)
            clear = ~ex_words[:, None, None, :]
            send_bits[ex_rows] &= clear
            drop_bits[ex_rows] &= clear
            switch_bits[ex_rows] &= clear
        rr, tt, kk, ss, b_sn, b_ts, b_pid, b_tl0, b_ki = (
            self.munger.apply_columns(
                inp.sn, inp.ts, inp.ts_jump, inp.pid, inp.tl0, inp.keyidx,
                inp.begin_pic, inp.valid,
                send_bits, drop_bits, switch_bits,
                shard_plan=self._munge_shard_plan,
            )
        )
        if len(self.munger.last_shard_ns):
            self.egress_plane.record_munge(
                self.munger.last_shard_counts, self.munger.last_shard_ns
            )
            self.munger.last_shard_ns = self.munger.last_shard_ns[:0]
        batch = EgressBatch(
            rooms=rr, tracks=tt, ks=kk, subs=ss,
            sn=b_sn, ts=b_ts, pid=b_pid, tl0=b_tl0, keyidx=b_ki,
            payloads=payloads,
        )
        speakers: dict[int, list[tuple[int, float]]] = {}
        lv, tr = out.speaker_levels, out.speaker_tracks
        for r in range(lv.shape[0]):
            row = [
                (int(tr[r, i]), float(lv[r, i]))
                for i in range(lv.shape[1])
                if tr[r, i] >= 0 and lv[r, i] > 0
            ]
            if row:
                speakers[r] = row
        nk = [
            (int(r), int(t), int(s))
            for r, t, s in zip(*np.nonzero(out.need_keyframe))
        ]
        congested: dict[int, list[int]] = {}
        for r, s in zip(*np.nonzero(out.congested)):
            congested.setdefault(int(r), []).append(int(s))
        # Feed the host replay ring from this tick's sends (the push half
        # of the sequencer, now host-side — NACKs resolve at RTCP time).
        eff_idx = self.tick_index if tick_idx is None else tick_idx
        self.host_seq.record(batch, eff_idx)
        if ex_log is not None and len(ex_log):
            # Express sends of this window, recorded against the SAME
            # slab now that it is retained in _slab_history. The drain's
            # reorder pass can permute staging slots within a (room,
            # track) after the log was written, so entries whose slot no
            # longer holds their wire SN are dropped — a replay miss the
            # client re-NACKs, never a wrong payload.
            T, K = self.dims.tracks, self.dims.pkts
            lflat = (
                ex_log.rooms.astype(np.int64) * T + ex_log.tracks
            ) * K + ex_log.ks
            ok = (
                np.asarray(inp.sn).reshape(-1)[lflat] & 0xFFFF
            ) == ex_log.orig_sn
            if not ok.all():
                if self.express is not None:
                    self.express.stats["replay_drops"] += int((~ok).sum())
                ex_log = ex_log.take(ok)
            self.host_seq.record(ex_log, eff_idx)
        padding = self._assemble_padding(inp)
        if padding:
            self.stats["pad_packets"] = self.stats.get("pad_packets", 0) + len(padding)
        return TickResult(
            tick_index=self.tick_index if tick_idx is None else tick_idx,
            egress_batch=batch,
            padding=padding,
            speakers=speakers,
            need_keyframe=nk,
            congested=congested,
            # `out` is post-drain host numpy by the time _fan_out runs
            # (materialized in _device_step), so these casts are host
            # no-ops the device-name heuristic cannot see through.
            fwd_packets=int(out.fwd_packets.sum()),  # graftcheck: disable=GC12
            fwd_bytes=int(out.fwd_bytes.sum()),  # graftcheck: disable=GC12
            tick_s=tick_s,
            track_quality=out.track_quality,
            track_mos=out.track_mos,
            sub_quality=out.sub_quality,
            layer_live=out.layer_live,
            layer_fps=out.layer_fps,
            track_loss_pct=out.track_loss_pct,
            track_jitter_ms=out.track_jitter_ms,
            track_bps=out.track_bps,
            red_sn=out.red_sn,
            red_off=out.red_off,
            red_ok=out.red_ok,
            pacer_allowed=out.pacer_allowed,
            target_layers=out.target_layers,
        )

    # -- loop ------------------------------------------------------------
    def start(self) -> None:
        if self._task is None:
            self.egress_plane.warm()  # spawn shard workers off the hot path
            self._task = asyncio.ensure_future(self._run())

    async def _calibrate_sleep(self) -> None:
        """Measure this host's asyncio coarse-sleep overshoot once at
        loop start: epoll timer slop + event-loop lag, typically
        0.3-2 ms, previously approximated by a fixed 1.5 ms margin. The
        median of a short burst (plus a small spin cushion) becomes the
        pre-edge margin _sleep_until subtracts before its yield-spin
        tail — a low-slop host stops burning 1.5 ms of spin per tick,
        and a high-slop host stops self-inflicting lateness at tick 2."""
        if self._sleep_bias >= 0:
            return
        samples = []
        for _ in range(8):
            t0 = time.perf_counter()
            await asyncio.sleep(0.001)
            samples.append(time.perf_counter() - t0 - 0.001)
        self._sleep_bias = min(max(float(np.median(samples)) + 2e-4, 3e-4), 4e-3)

    async def _sleep_until(self, when: float) -> None:
        """Window-edge sleep: coarse asyncio.sleep to just short of the
        edge, then a yield loop for the tail. An epoll-backed sleep
        overshoots by the event-loop lag (hundreds of µs under rx load)
        — at a 5 ms tick that alone costs 5-10% of the cadence. The
        sleep(0) tail keeps rx/feedback callbacks running while landing
        the dispatch within ~50 µs of the edge; the spin is bounded by
        the calibrated margin and only burns the window's idle slack.
        The wake overshoot is recorded (edge_overshoot_us per tick in
        recent_ticks) and a coarse sleep that blows THROUGH the edge
        widens the margin for the next windows (EWMA, capped)."""
        bias = self._sleep_bias if self._sleep_bias >= 0 else 0.0015
        delay = when - time.perf_counter() - bias
        if delay > 0:
            await asyncio.sleep(delay)
        while time.perf_counter() < when:
            await asyncio.sleep(0)
        over = time.perf_counter() - when
        self._edge_overshoot_us = over * 1e6
        if over > 2.5e-4 and self._sleep_bias >= 0:
            self._sleep_bias = min(self._sleep_bias + 0.25 * over, 4e-3)

    async def _run(self) -> None:
        """Three-stage pipelined serving loop (the 'double-buffered DMA'
        this module documents): within one tick window,

            stage N+1  ‖  device N  ‖  fan-out N-1

        Tick N — staged during the PREVIOUS window — is dispatched to the
        worker thread at the window edge; while the device crunches, the
        event loop stages tick N+1 (ingest drain + input pre-pack, into
        the other ingest ping-pong set) and runs tick N-1's fan-out +
        egress. A tick's wall budget is max(device, stage + fan-out) +
        dispatch ε instead of the former stage + max(device, fan-out):
        nothing host-side sits in front of the device dispatch but the
        (delta) ctrl upload.

        The completion queue is bounded at 1: if host egress can't keep
        up, the loop degrades to sequential (counted in pipeline_stalls)
        instead of queueing stale sends, and a stalled device future
        simply holds the loop at `await fut` — no new tick is staged past
        the one already prepared, so depth is bounded by construction.

        self.state stays single-owner: only the ctrl upload + dispatched
        device step touch the donated state, and exactly that span runs
        under state_lock. Staging reads host mirrors only and needs no
        lock (the GC01 split: _upload_ctrl/_device_step keep the
        lock-held contract, _stage_host is lock-free)."""
        period = self.tick_ms / 1000.0
        await self._calibrate_sleep()
        next_at = time.perf_counter() + period
        loop = asyncio.get_running_loop()
        pending: tuple | None = None   # (out, StagedTick) awaiting fan-out
        pending_task: asyncio.Task | None = None
        staged: StagedTick | None = None  # pre-staged next tick
        depth = 0 if self.low_latency else 1
        try:
            while True:
                if staged is not None:
                    # Edge surgery: deadline accounting and probe
                    # scheduling for a pre-staged tick happen BEFORE the
                    # sleep — no device step completes while the loop
                    # sleeps, so the mirrors _schedule_probe reads cannot
                    # change — leaving the post-wake path dispatch-only.
                    staged.depth = depth
                    staged.edge = next_at
                    staged.deadline = next_at + (1 + depth) * period
                    self._schedule_probe(staged)
                await self._sleep_until(next_at)
                if self.integrity is not None and self.integrity._pending_repair:
                    # Drain the row-repair queue filled by the last audit,
                    # at the window edge and OUTSIDE the lock region below:
                    # each repair takes state_lock itself, and the repaired
                    # row's dirtied ctrl re-uploads in this very tick.
                    # (Guarded: the empty-queue case stays off the wake
                    # path.)
                    await self.integrity.process()
                if pending_task is not None:
                    # Backpressure: previous fan-out still running ⇒ wait
                    # (sequential under overload; no unbounded queue).
                    if not pending_task.done():
                        self.stats["pipeline_stalls"] += 1
                    await pending_task
                    pending_task = self._complete_task = None
                if staged is None:
                    # Cold start, post-resync, or low_latency mode: stage
                    # at the window edge (low latency keeps the freshest
                    # possible drain at the cost of serializing it).
                    staged = self._stage_host()
                    staged.depth = depth
                    staged.edge = next_at
                    staged.deadline = next_at + (1 + depth) * period
                    self._schedule_probe(staged)
                cur, staged = staged, None
                cur.edge_over_us = self._edge_overshoot_us
                if self.ingest.frozen_rows:
                    # A migration freeze can land during the sleep, after
                    # the pre-edge probe scheduling: re-zero frozen rows'
                    # probe padding at dispatch (pads advance munger
                    # lanes; a frozen row must stay at its snapshot).
                    np.asarray(cur.inp.pad_num)[list(self.ingest.frozen_rows)] = 0
                await self.state_lock.acquire()
                try:
                    cur.upload_t0 = time.perf_counter()
                    self._upload_ctrl()
                    cur.upload_s = time.perf_counter() - cur.upload_t0
                    fut = loop.run_in_executor(self._executor, self._device_step, cur)
                    if pending is not None:
                        pending_task = self._complete_task = asyncio.ensure_future(
                            self._complete(pending[0], pending[1])
                        )
                        pending = None
                    if not self.low_latency:
                        # Stage N+1 while device N runs in the worker:
                        # the drain flips to the other ingest ping-pong
                        # set and the pre-pack memcpys overlap the device
                        # step — the tentpole overlap. Staging touches
                        # host mirrors only; the lock we hold here guards
                        # the in-flight donated state, not this.
                        staged = self._stage_host()
                    # Fan-out N-1 (the task above) and any arriving-packet
                    # handlers run on the event loop during this await.
                    out = await fut
                finally:
                    self.state_lock.release()
                if out is None:
                    # Abandoned by a supervisor restart racing our cancel:
                    # bail to the drain handler without touching state.
                    raise asyncio.CancelledError("device step abandoned by restart")
                self._mirror_probe_inputs(out)
                self.ingest.scrub_retired()
                pending = (out, cur)
                if self.low_latency:
                    # Fan out THIS tick's egress now rather than
                    # overlapping it with the next device step: the sends
                    # leave within the same tick period. `pending` is
                    # cleared BEFORE the await — a cancellation landing
                    # inside _complete must not let the drain handler
                    # re-run the same tick (double egress + munger state
                    # advanced twice).
                    to_complete, pending = pending, None
                    await self._complete(to_complete[0], to_complete[1])
                next_at += period
                if next_at < time.perf_counter() - 5 * period:
                    next_at = time.perf_counter() + period  # resync after stall
        except asyncio.CancelledError:
            # Drain: the final tick's device step already ran — its egress,
            # callbacks, and stats must not silently vanish at shutdown.
            if pending_task is not None:
                await pending_task
                self._complete_task = None
            if pending is not None:
                await self._complete(pending[0], pending[1])
            raise

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self._complete_task is not None:
            self._complete_task.cancel()
            try:
                await self._complete_task
            except asyncio.CancelledError:
                pass
            self._complete_task = None

    # -- checkpoint / resume (§5.4) --------------------------------------
    def snapshot(self) -> dict[str, Any]:
        """Serializable plane snapshot: device decision state + the
        host-side munger offsets (migration seeding analog)."""
        flat, treedef = jax.tree.flatten(self.state)
        return {
            "tick_index": self.tick_index,
            "arrays": [np.asarray(x) for x in flat],
            "munger": self.munger.snapshot(),
        }

    def snapshot_room(self, row: int) -> dict[str, Any]:
        """One room row's slice of the plane state — the cross-node room
        handoff payload (participant.go:823 MaybeStartMigration seeds the
        same per-forwarder state on the destination node).

        Control tensors come from the HOST mirrors (authoritative: they may
        hold un-uploaded mutations newer than the device copy); everything
        else slices on device first so only one row crosses HBM→host. The
        host munger's row (SN/TS/VP8 offsets — RTPMungerState seeding,
        rtpmunger.go:53-69) rides along after the device leaves."""
        flat, treedef = jax.tree.flatten(self.state)
        arrays = [np.asarray(x[row]) for x in flat]
        tree = jax.tree.unflatten(treedef, arrays)
        tree = tree._replace(
            meta=plane.TrackMeta(*[np.array(m[row]) for m in self.meta]),
            ctrl=plane.SubControl(*[np.array(c[row]) for c in self.ctrl]),
        )
        return {
            "arrays": jax.tree.flatten(tree)[0]
            + self.munger.snapshot_room(row)
        }

    @staticmethod
    def encode_room_snapshot(snap: dict[str, Any]) -> str:
        """Room snapshot → checksummed npz frame, base64 (rides the KV
        bus). The utils/checksum frame (GC06) lets every restore path
        verify the bytes before any `.at[]` scatter."""
        import io

        from livekit_server_tpu.utils import checksum

        buf = io.BytesIO()
        np.savez_compressed(buf, *snap["arrays"])
        return checksum.encode_frame_b64(buf.getvalue())

    @staticmethod
    def decode_room_snapshot(payload: str) -> dict[str, Any]:
        """Verify + decode a room checkpoint; raises ChecksumError on a
        corrupt frame BEFORE np.load touches the bytes."""
        import io

        from livekit_server_tpu.utils import checksum

        z = np.load(io.BytesIO(checksum.decode_frame_b64(payload)))
        # savez names leaves arr_0..arr_N; z.files sorts lexically (arr_10
        # before arr_2), so index numerically.
        return {"arrays": [z[f"arr_{i}"] for i in range(len(z.files))]}

    @staticmethod
    def encode_snapshot(snap: dict[str, Any]) -> bytes:
        """Full-plane snapshot → checksummed npz frame (the supervisor's
        checkpoint-generation format)."""
        import io

        from livekit_server_tpu.utils import checksum

        arrays = list(snap["arrays"]) + list(snap.get("munger", []))
        buf = io.BytesIO()
        np.savez_compressed(
            buf, *arrays,
            tick_index=np.int64(snap["tick_index"]),
            n_state=np.int64(len(snap["arrays"])),
        )
        return checksum.encode_frame(buf.getvalue())

    @staticmethod
    def decode_snapshot(blob: bytes) -> dict[str, Any]:
        """Verify + decode a full-plane checkpoint into the snapshot()
        dict shape; ChecksumError on corruption, ValueError/KeyError on a
        malformed archive."""
        import io

        from livekit_server_tpu.utils import checksum

        z = np.load(io.BytesIO(checksum.decode_frame(blob)))
        n_arrays = sum(1 for f in z.files if f.startswith("arr_"))
        n_state = int(z["n_state"])
        arrays = [z[f"arr_{i}"] for i in range(n_arrays)]
        return {
            "tick_index": int(z["tick_index"]),
            "arrays": arrays[:n_state],
            "munger": arrays[n_state:],
        }

    def _check_row_leaves(self, flat: list, arrays: list) -> None:
        """Validate a row snapshot's leaves against the LIVE plane spec
        (count, per-leaf row shape, dtype compatibility) before anything
        scatters into donated device state."""
        n_munger = len(HostMunger.FIELDS)
        if len(arrays) != len(flat) + n_munger:
            raise ValueError(
                f"snapshot has {len(arrays)} leaves, plane has "
                f"{len(flat)} + {n_munger} munger fields — "
                f"source/destination plane versions differ"
            )
        for i, (leaf, a) in enumerate(zip(flat, arrays)):
            a = np.asarray(a)
            want = tuple(leaf.shape[1:])
            if tuple(a.shape) != want:
                raise ValueError(
                    f"snapshot leaf {i} row shape {tuple(a.shape)} != "
                    f"plane row shape {want} — dims mismatch"
                )
            if not np.can_cast(a.dtype, np.dtype(leaf.dtype), casting="same_kind"):
                raise ValueError(
                    f"snapshot leaf {i} dtype {a.dtype} incompatible with "
                    f"plane dtype {np.dtype(leaf.dtype)}"
                )

    @staticmethod
    def row_snapshot_from_full(snap: dict[str, Any], row: int) -> dict[str, Any]:
        """Slice one room's row out of a FULL snapshot() dict, in the
        snapshot_room() wire shape (state leaves then munger fields) —
        how the integrity monitor turns the supervisor's last verified
        checkpoint into a row-repair payload."""
        return {
            "arrays": [np.asarray(a[row]) for a in snap["arrays"]]
            + [np.asarray(m[row]) for m in snap.get("munger", [])]
        }

    def repair_room_row(self, row: int, snap: dict[str, Any]) -> None:
        """Integrity row repair: overwrite ONE corrupt room row from a
        verified checkpoint, in place, without disturbing any other row.

        Unlike restore_room (cross-node migration), the HOST mirrors stay
        authoritative: this node's meta/ctrl were never suspect — only
        the device row was — so the row's current subscriptions survive
        and the dirty-row upload re-asserts them over the checkpoint's
        older device copy at the next tick edge. Callers hold state_lock
        (GC01)."""
        import jax.numpy as jnp

        flat, treedef = jax.tree.flatten(self.state)
        self._check_row_leaves(flat, snap["arrays"])
        dev_arrays = snap["arrays"][: len(flat)]
        self.munger.restore_room(row, snap["arrays"][len(flat):])
        new_flat = [
            leaf.at[row].set(jnp.asarray(a, leaf.dtype))
            for leaf, a in zip(flat, dev_arrays)
        ]
        self.state = jax.tree.unflatten(treedef, new_flat)
        if self._mesh is not None:
            from livekit_server_tpu.parallel import shard_tree

            self.state = shard_tree(self.state, self._mesh)
        # The replay ring references pre-repair munger SN spaces; replaying
        # across the rewind would emit wrong-SN bytes. Clients re-NACK.
        self.host_seq.clear_room(row)
        self._dirty_rows.add(row)

    def restore_room(self, row: int, snap: dict[str, Any]) -> None:
        """Seed `row` from a snapshot taken on another node: munger/VP8
        offsets continue mid-stream, so migrated subscribers see
        contiguous SN/TS instead of a stream reset. The host-side replay
        ring is NOT carried: NACKs of pre-migration packets miss (the
        payload slab did not travel either) until the destination ring
        repopulates — clients simply re-request via PLI on a sustained
        gap, like the reference's post-migration behavior.

        Subscription masks are NOT carried over: the destination's slot
        allocator hands out sub columns fresh, and a restored subscribed
        bit on a column later given to a different participant would leak
        media to someone who never subscribed. Rejoining subscribers
        re-subscribe; their (track, sub) munger lanes resume intact."""
        import jax.numpy as jnp

        # The destination row's replay ring starts empty (see docstring) —
        # and must not retain entries from whatever used the row before.
        self.host_seq.clear_room(row)
        flat, treedef = jax.tree.flatten(self.state)
        self._check_row_leaves(flat, snap["arrays"])
        dev_arrays = snap["arrays"][: len(flat)]
        self.munger.restore_room(row, snap["arrays"][len(flat):])
        new_flat = [
            leaf.at[row].set(jnp.asarray(a, leaf.dtype))
            for leaf, a in zip(flat, dev_arrays)
        ]
        self.state = jax.tree.unflatten(treedef, new_flat)
        if self._mesh is not None:
            from livekit_server_tpu.parallel import shard_tree

            self.state = shard_tree(self.state, self._mesh)
        # Mirror the migrated row's track metadata back to the host copies
        # (other rows' possibly-dirty host state stays untouched)…
        snap_tree = jax.tree.unflatten(treedef, dev_arrays)
        for host_arr, snap_arr in zip(self.meta, snap_tree.meta):
            host_arr[row] = snap_arr
        # …but clear the subscriber-facing control masks (see docstring);
        # the next ctrl upload clears them on device too.
        self.ctrl.subscribed[row] = False
        self.ctrl.sub_muted[row] = False
        self.ctrl.max_spatial[row] = plane.MAX_LAYERS - 1
        self.ctrl.max_temporal[row] = 3
        self._dirty_rows.add(row)
        if self.integrity is not None:
            # A legitimate row rewrite: drop quarantine history and
            # re-baseline the audit cursors (they rewound on purpose).
            self.integrity.on_row_restore(row)

    def restore(self, snap: dict[str, Any]) -> None:
        flat, treedef = jax.tree.flatten(self.state)
        arrays = snap.get("arrays")
        if arrays is None or len(arrays) != len(flat):
            raise ValueError(
                f"full snapshot has {0 if arrays is None else len(arrays)} "
                f"leaves, plane has {len(flat)} — snapshot/plane versions "
                "differ"
            )
        for i, (leaf, a) in enumerate(zip(flat, arrays)):
            a = np.asarray(a)
            if tuple(a.shape) != tuple(leaf.shape):
                raise ValueError(
                    f"full snapshot leaf {i} shape {tuple(a.shape)} != "
                    f"plane shape {tuple(leaf.shape)} — dims mismatch"
                )
            if not np.can_cast(a.dtype, np.dtype(leaf.dtype), casting="same_kind"):
                raise ValueError(
                    f"full snapshot leaf {i} dtype {a.dtype} incompatible "
                    f"with plane dtype {np.dtype(leaf.dtype)}"
                )
        self.state = jax.tree.unflatten(treedef, [a for a in snap["arrays"]])
        if self._mesh is not None:
            from livekit_server_tpu.parallel import shard_tree

            self.state = shard_tree(self.state, self._mesh)
        if "munger" in snap:
            self.munger.restore(snap["munger"])
        else:
            # A munger-less snapshot (pre-round-5 format, or a producer
            # that stripped host state) must not pair restored device
            # decisions with STALE SN/TS offsets — every lane would keep
            # rewriting against the wrong anchor. Reset so lanes anchor
            # fresh instead (a one-time stream reset, like a new room).
            self.munger = HostMunger(self.dims)
        self.tick_index = snap["tick_index"]
        self._ctrl_dirty = True
        if self.integrity is not None:
            self.integrity.on_full_restore()
